"""Benchmark CLI mirroring the reference's three criterion benches.

Reference harness (no published numbers, SURVEY.md §6):

- ``dcf``             — single ``gen`` + single-point ``eval``, N=16, lam=16
                        (/root/reference/benches/dcf.rs:7-43)
- ``dcf_batch_eval``  — 100 000-point batch eval, N=16, lam=16
                        (/root/reference/benches/dcf_batch_eval.rs:17-39)
- ``dcf_large_lambda``— lam=16384 (2048 AES keys), 10 000 points
                        (/root/reference/benches/dcf_large_lambda.rs:8-43)

plus ``secure_relu`` — the BASELINE.json config-5 many-keys workload.

plus ``full_domain`` — the BASELINE.json config-3 workload (two-party
reconstruction over the whole 2^n domain, on-device point generation for
the staged backends).

plus ``serve_bench`` — the online serving layer (``dcf_tpu.serve``)
under a closed-loop load generator: N client threads each keep one
ragged request in flight against several registered keys while the
service micro-batches, and the emitted ``RESULTS_serve`` JSONL line
records the served closed-loop throughput next to the equivalent
staged-path batch rate (same backend, same ``--max-batch`` shape) with
the full metrics snapshot (queue depth, batch occupancy, latencies).

plus ``edge_bench`` — the network edge (``dcf_tpu.serve.edge``, ISSUE
12): the zero-copy DCFE wire path measured against the in-process
serving rate at the same shape (interleaved closed-loop legs over
``--connections`` TCP connections), plus the 8+-connection soak under
injected ``edge.read`` faults (bit-exact reconstruction, reconnecting
clients), a rate-limited-tenant refusal leg asserting every refusal
carries a typed retry-after hint, and an open-loop (Poisson) latency
leg — exit-code gates on wire_vs_inprocess >= 0.8, the single-feed
ingest probe, soak parity, and hint coverage
(``benchmarks/RESULTS_edge.jsonl``).

plus ``serve_host`` — one pod shard process (ISSUE 13): a
``DcfService`` warm-restored from its durable store behind an
``EdgeServer``, publishing its bound address (``--ready-file``) and
per-host metrics snapshots (``--metrics-file``) until SIGTERM — the
unit ``pod_bench`` spawns N of.

plus ``pod_bench`` — the pod-scale serving tier (``serve.shardmap`` +
``serve.router``, ISSUE 13): ring provisioning with durably
replicated frames, N+1 ``serve_host`` subprocesses (pod + solo legs),
routed two-party parity vs the numpy oracle, interleaved solo/pod
closed-loop legs at the same shape/seeds, open-loop reconciliation
against the pod metrics rollup, and a kill-a-shard failover soak
gated on every request accounted (``benchmarks/RESULTS_pod.jsonl``;
the >= 2.2x scaling gate applies when the host offers the pod
parallelism and is recorded environment-gated otherwise).

plus ``mic_bench`` — the protocol layer (``dcf_tpu.protocols``, ISSUE
5): an m-interval MIC bundle (2m K-packed DCF keys) served closed-loop
with the share combine applied server-side; the ``RESULTS_protocols``
JSONL line records served points/s, the staged ``MicEvaluator``
equivalent, and ``vs_baseline`` against the pinned single-core
numpy-oracle denominator (CPU_BASELINE.md).

plus ``gate_bench`` — the fixed-point gate suite (ISSUE 20,
``protocols.fixedpoint`` + ``workloads.gates``): spline sigmoid,
faithful truncation and signed comparison served through
``GateServer`` in the ``add16`` output group, parity-gated against
the clear-input numpy gate oracles before timing
(``benchmarks/RESULTS_gates.jsonl``; pinned denominators
``gates.sigmoid_m8`` / ``gates.trunc``).

plus ``chaos_bench`` — the serve resilience layer (ISSUE 6): a
mixed-priority closed-loop load under a declarative fail-N-then-recover
fault schedule at the ``serve.eval`` seam, with exit-code assertions on
the metrics snapshot (breaker opened AND closed, zero CRITICAL sheds,
BATCH-first shedding, post-recovery two-party parity vs the C++ core).

plus ``keygen_bench`` — the on-device keygen (ISSUE 10): closed-loop
keys/s through ``gen.gen_on_device`` (the Pallas keygen kernel sharing
the eval kernels' narrow level-walk core, ``ops.pallas_keygen``) at
K in {1, 8, 64, 2m} and lam in {128, 256}, gated on two-party
reconstruction of device-generated keys (exit non-zero on mismatch),
with ``vs_baseline`` against the pinned single-core numpy ``gen_batch``
denominator (CPU_BASELINE.md ``keygen`` entries; one
``RESULTS_keygen`` JSONL line per lam).

Usage::

    python -m dcf_tpu.cli dcf_batch_eval --backend=pallas --points=1048576
    python -m dcf_tpu.cli full_domain --backend=pallas --n-bits=24
    python -m dcf_tpu.cli secure_relu --backend=sharded --mesh=4x2
    python -m dcf_tpu.cli all --backend=cpu --profile=/tmp/trace

The criterion benches are single-key, so their sharded variant shards
points only (mesh 1xN); the multi-key mesh factorizations (8x1 / 4x2 /
2x4) are compared on ``secure_relu --backend=sharded --mesh=KxP``.

Backends: ``cpu`` (C++ core, all threads), ``cpu1`` (C++ single thread —
the stand-in for the reference's serial feature matrix), ``numpy``,
``jax`` (XLA scan/vmap), ``bitsliced`` (XLA bit-planes), ``pallas``
(fused TPU kernel, lam=16 only), ``prefix`` (the prefix-shared walk:
top-k tree frontier cached per key + per-point gather + n-k walked
levels; single-key random-batch shapes — the fastest config-2/flagship
path), ``sharded`` (the XLA bit-plane core
under shard_map over a device mesh; ``--mesh=KxP`` picks the
factorization), ``sharded-pallas`` (the Pallas kernels under shard_map:
the flagship walk kernel for dcf_batch_eval, the keys-in-lanes kernel
for secure_relu; lam=16 only).  Each bench prints one
human line and one JSON line with criterion-grade stats (median +- MAD of
``--reps`` samples after warmup).  ``--profile=DIR`` wraps the timed
region in a ``jax.profiler`` trace.  gen runs on the C++ host core except
where a bench states otherwise (``secure_relu --device-gen`` generates
keys on device).  Two bench-specific backends: ``tree`` (full_domain:
GGM tree expansion) and ``hybrid`` (dcf_large_lambda: Pallas narrow walk
+ GF(2)-affine wide part).
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time

import numpy as np

from dcf_tpu.gen import random_s0s
from dcf_tpu.keys import KeyBundle
from dcf_tpu.spec import Bound

BACKENDS = ("cpu", "cpu1", "numpy", "jax", "bitsliced", "pallas", "prefix",
            "sharded", "sharded-pallas")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _cipher_keys(lam: int, rng) -> list[bytes]:
    """The reference contract count 2*(lam/16), floored at 18 for lam >= 32
    (any such shape touches cipher index 17 — reference-inexecutable lams
    48..128 run here as extensions and need the extra keys)."""
    n_keys = max(2, 2 * (lam // 16))
    if lam >= 32:
        n_keys = max(n_keys, 18)
    return [rng.bytes(32) for _ in range(n_keys)]


def _parse_mesh(spec: str):
    """'4x2' -> (4, 2); '' -> None (auto factorization)."""
    if not spec:
        return None
    try:
        k, p = spec.lower().split("x")
        return (int(k), int(p))
    except ValueError:
        raise SystemExit(f"--mesh wants KxP (e.g. 4x2), got {spec!r}")


def _make_evaluator(backend: str, lam: int, cipher_keys, native, args=None):
    """Returns (eval_fn, backend_obj_or_None) where eval_fn(b, bundle_party,
    xs) -> uint8 [K, M, lam].  The backend object is None for host paths;
    benches use it to reach the staged protocol where one exists."""
    if backend in ("cpu", "cpu1"):
        threads = 1 if backend == "cpu1" else None

        def run(b, bundle, xs):
            return native.eval(b, bundle, xs, num_threads=threads)

        return run, None
    if backend == "numpy":
        from dcf_tpu.backends.numpy_backend import eval_batch_np
        from dcf_tpu.ops.prg import HirosePrgNp

        prg = HirosePrgNp(lam, cipher_keys)
        return (lambda b, bundle, xs: eval_batch_np(prg, b, bundle, xs),
                None)
    if backend == "hybrid":
        # --prefix-levels=k > 0 switches the narrow walk to the
        # prefix-shared path (ops.pallas_hybrid_prefix): top-k frontier
        # expanded once per (key, party) and cached as a gather table,
        # only n-k levels walked per point.
        plev = int(getattr(args, "prefix_levels", 0) or 0) if args else 0
        if args is not None and getattr(args, "mesh", ""):
            import jax

            from dcf_tpu.parallel import (
                ShardedLargeLambdaBackend,
                make_mesh,
            )

            mesh = make_mesh(shape=_parse_mesh(args.mesh))
            log(f"mesh: {dict(mesh.shape)}")
            be = ShardedLargeLambdaBackend(
                lam, cipher_keys, mesh, prefix_levels=plev,
                interpret=jax.devices()[0].platform != "tpu")
        else:
            from dcf_tpu.backends.large_lambda import LargeLambdaBackend

            kw = {}
            if plev:
                import jax

                # The frontier machinery is Pallas-only; same
                # interpreter rule as the facade applies off-TPU.
                kw = dict(prefix_levels=plev,
                          interpret=jax.devices()[0].platform != "tpu")
            be = LargeLambdaBackend(lam, cipher_keys, **kw)
    elif backend == "jax":
        from dcf_tpu.backends.jax_backend import JaxBackend

        be = JaxBackend(lam, cipher_keys)
    elif backend == "bitsliced":
        from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

        be = BitslicedBackend(lam, cipher_keys)
    elif backend == "pallas":
        from dcf_tpu.backends.pallas_backend import PallasBackend

        be = PallasBackend(lam, cipher_keys)
    elif backend == "prefix":
        # Prefix-shared walk: top-k tree expansion cached per (key, party),
        # per-point frontier gather, n-k walked levels (single key; the
        # config-2 / flagship random-batch shape).  k tracks the batch
        # size: the frontier is untimed key material, so one level past
        # log2(M) still wins on the eval clock (the measured optimum; a
        # frontier far deeper would be absurd for smoke runs), capped at
        # the 2^22-total-row gather cliff — the backend further shrinks k
        # by ceil(log2 K) for multi-key bundles.  With --mesh the same
        # evaluator runs under shard_map (single key -> 1xN points mesh).
        import jax

        from dcf_tpu.backends.pallas_prefix import MAX_PREFIX_LEVELS

        pts = (getattr(args, "points", 0) or 100_000) if args else 100_000
        klev = max(6, min(MAX_PREFIX_LEVELS, pts.bit_length()))
        interp = jax.devices()[0].platform != "tpu"
        if args is not None and getattr(args, "mesh", ""):
            from dcf_tpu.parallel import ShardedPrefixBackend, make_mesh

            mesh = make_mesh(shape=_parse_mesh(args.mesh))
            log(f"mesh: {dict(mesh.shape)}")
            be = ShardedPrefixBackend(lam, cipher_keys, mesh,
                                      prefix_levels=klev, interpret=interp)
        else:
            from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

            be = PrefixPallasBackend(lam, cipher_keys, prefix_levels=klev,
                                     interpret=interp)
    elif backend in ("sharded", "sharded-pallas"):
        import jax

        from dcf_tpu.parallel import (
            ShardedBitslicedBackend,
            ShardedPallasBackend,
            make_mesh,
        )

        shape = _parse_mesh(getattr(args, "mesh", ""))
        if shape is None:
            # criterion benches are single-key: put every device on points
            shape = (1, len(jax.devices()))
        mesh = make_mesh(shape=shape)
        log(f"mesh: {dict(mesh.shape)}")
        if backend == "sharded-pallas":
            # Mosaic on TPU meshes; the Pallas interpreter elsewhere
            # (the DCF_CPU_DEVICES virtual-mesh smoke mode).
            be = ShardedPallasBackend(
                lam, cipher_keys, mesh,
                interpret=jax.devices()[0].platform != "tpu")
        else:
            be = ShardedBitslicedBackend(lam, cipher_keys, mesh)
    else:
        # api-edge: CLI backend-name contract
        raise ValueError(f"unknown backend {backend!r}")

    def run(b, bundle, xs):
        return be.eval(b, xs, bundle=bundle)

    return run, be


def _timed_staged(be, xs, reps: int, profile: str):
    """Shared staged-bench timing: stage once (untimed, criterion-setup
    analog), k dispatches per sample with one digest sync, results
    HBM-resident.  k adapts to the measured dispatch time: fast dispatches
    need many per sample to amortize the tunnel-sync RTT; for slow ones
    (>= 0.4s compute) the sync share is already small and the full count
    would take minutes per sample.  The probe dispatch's own sync RTT
    (~85-155ms on the tunneled device, enough to flip the bucket near the
    threshold) is measured separately and subtracted before classifying.
    Returns (per-dispatch median — i.e. per full-batch eval — MAD,
    samples, unit)."""
    from dcf_tpu.utils.benchtime import (
        DISPATCHES_PER_SAMPLE,
        DISPATCHES_PER_SAMPLE_SLOW,
        device_sync,
    )

    from dcf_tpu.utils.benchtime import measure_sync_rtt

    staged = be.stage(xs)
    y = be.eval_staged(0, staged)
    device_sync(y)  # staged-path warmup / compile
    rtt = measure_sync_rtt(y)
    t0 = time.perf_counter()
    y = be.eval_staged(0, staged)
    device_sync(y)  # one post-compile dispatch incl. the sync RTT
    k = (DISPATCHES_PER_SAMPLE if time.perf_counter() - t0 - rtt < 0.4
         else DISPATCHES_PER_SAMPLE_SLOW)

    def timed():
        for _ in range(k):
            y = be.eval_staged(0, staged)
        device_sync(y)

    dt, mad, ss = _timed(timed, reps, profile)
    # Each sample carries exactly one digest-fetch sync; its round-trip is
    # the dev tunnel's latency, not chip work (same correction bench.py
    # applies) — without it a 5 ms dispatch under a ~100 ms RTT reads up
    # to ~15% slow and tracks the tunnel's day-to-day state.
    return (max(dt - rtt, 1e-9) / k, mad / k, ss,
            "evals/s (staged, results HBM-resident, sync RTT subtracted)")


class _Profiler:
    """Optional jax.profiler trace around the timed region (--profile)."""

    def __init__(self, trace_dir: str):
        self.trace_dir = trace_dir

    def __enter__(self):
        if self.trace_dir:
            import jax

            jax.profiler.start_trace(self.trace_dir)
        return self

    def __exit__(self, *exc):
        if self.trace_dir:
            import jax

            jax.profiler.stop_trace()
            log(f"profiler trace written to {self.trace_dir}")
        return False


def _timed(fn, reps: int, profile: str = ""):
    """Criterion-grade sampling: ``reps`` timed samples (caller warmed up),
    median +- MAD (benches/dcf_batch_eval.rs:35-39 methodology analog).
    Returns (median_s, mad_s, samples)."""
    samples = []
    with _Profiler(profile):
        for _ in range(max(reps, 1)):
            t0 = time.perf_counter()
            fn()
            samples.append(time.perf_counter() - t0)
    arr = np.array(samples)
    med = float(np.median(arr))
    mad = float(np.median(np.abs(arr - med)))
    return med, mad, samples


def _load_pinned(baseline_path: str | None = None) -> dict | None:
    """Resolve + load benchmarks/cpu_baseline.json (the ONE loader both
    pinned-ratio helpers share); None when the file is absent or
    corrupt.  ValueError covers json.JSONDecodeError: a corrupt baseline
    file must make the caller omit vs_baseline, not abort the bench
    run."""
    import os

    path = baseline_path or os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "cpu_baseline.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _pinned_ratio(nb: int, k: int, rate: float,
                  interpreted: bool = False,
                  baseline_path: str | None = None,
                  lam: int = 16, keygen: bool = False) -> dict:
    """vs_baseline against the pinned per-shape single-core CPU anchor
    (benchmarks/cpu_baseline.json, CPU_BASELINE.md protocol), when one
    exists for this shape — the flagship N=16 pin, the config-2 literal
    n=32 entry, or (round 6) the lam=128/256/16384 large-lambda
    entries.  Empty otherwise (no silent in-run fallback), and empty for
    ``interpreted`` runs: a Pallas-interpreter smoke run's ratio against
    a real CPU pin is meaningless noise (host backends and compiled
    device runs keep theirs).  ``baseline_path`` overrides the artifact
    location (tests feed corrupt/absent files through it).

    ``keygen=True`` (ISSUE 10): ``rate`` is keys/s and the anchor is
    the pinned single-core numpy ``gen_batch`` denominator
    (``keygen.lam{lam}``, the protocols.mic_m8 numpy-oracle
    discipline); the pin records its key count, and only a matching-K
    leg gets the ratio.  Unlike the eval shapes the ratio is KEPT for
    interpreted runs — keygen_bench's acceptance gate wants the
    disclosure on the line — but annotated as an interpret-mode
    numerator, never a chip claim."""
    if keygen:
        pinned = _load_pinned(baseline_path)
        if pinned is None:
            return {}
        entry = pinned.get("keygen", {}).get(f"lam{lam}")
        if not entry or k != entry.get("keys"):
            return {}
        note = ("; interpret-mode numerator (no TPU this session) — "
                "run the committed repro on a chip for a real ratio"
                if interpreted else "")
        return {"vs_baseline": round(rate / entry["keys_per_sec"], 2),
                "baseline": f"pinned single-core numpy gen_batch "
                            f"keygen.lam{lam} K={k} "
                            f"({entry['keys_per_sec']:,.1f} keys/s, "
                            f"CPU_BASELINE.md protocol{note})"}
    if k != 1 or interpreted:
        return {}
    pinned = _load_pinned(baseline_path)
    if pinned is None:
        return {}
    if lam != 16:
        tag = {128: "lam128", 256: "lam256", 16384: "lam16384"}.get(lam, "")
        entry = pinned.get("shapes", {}).get(tag) if tag else None
    else:
        entry, tag = ((pinned, "flagship") if nb == 16 else
                      (pinned.get("shapes", {}).get("n32"), "n32")
                      if nb == 4 else (None, ""))
    if not entry:
        return {}
    note = "; flagship-ratio transferred pin" if entry.get("anchor") else ""
    return {"vs_baseline": round(rate / entry["evals_per_sec"], 2),
            "baseline": f"pinned single-core {tag} "
                        f"({entry['evals_per_sec']:,.0f} evals/s, "
                        f"CPU_BASELINE.md protocol{note})"}


def _emit(name: str, backend: str, metric: str, value: float, unit: str,
          med_s: float | None = None, mad_s: float | None = None,
          samples: int | None = None, extra_fields: dict | None = None):
    extra = dict(extra_fields or {})
    if med_s is not None:
        extra = {"median_s": round(med_s, 6), "mad_s": round(mad_s or 0, 6),
                 "samples": samples, **extra}
        log(f"{name}[{backend}]: {value:,.1f} {unit} "
            f"(median {med_s * 1e3:.3f} ms +- MAD {(mad_s or 0) * 1e3:.3f} ms, "
            f"{samples} samples)")
    else:
        log(f"{name}[{backend}]: {value:,.1f} {unit}")
    print(
        json.dumps(
            {"bench": name, "backend": backend, "metric": metric,
             "value": round(value, 1), "unit": unit, **extra}
        ),
        flush=True,
    )


def _full_device_parity(args, be, lam, ck, native, bundle, alphas, betas,
                        xs) -> None:
    """Full on-device two-party parity for staged backends: every staged
    point's XOR reconstruction is checked against the comparison function
    on device (VERDICT's replacement for the old spot checks); the C++
    anchor the caller already ran remains the cross-implementation gate.
    No-op for backends without the staged counter."""
    if be is None or not hasattr(be, "points_mismatch_count") \
            or not hasattr(be, "stage"):
        return
    if alphas.shape[0] > 1 and not getattr(
            be, "points_mismatch_multikey", False):
        log(f"full device parity: skipped ({type(be).__name__}'s counter "
            "is single-key); per-key C++ anchor above stands")
        return
    _run1, be1 = _make_evaluator(args.backend, lam, ck, native, args)
    st = be.stage(xs)
    y0 = be.eval_staged(0, st)
    be1.put_bundle(bundle.for_party(1))
    y1 = be1.eval_staged(1, st)
    single = alphas.shape[0] == 1
    mism = int(be.points_mismatch_count(
        y0, y1, alphas[0].tobytes() if single else alphas,
        betas[0].tobytes() if single else betas, st))
    if mism:
        raise SystemExit(
            f"full on-device parity: {mism} mismatching points")
    log(f"parity: full (device, {alphas.shape[0]} keys x all "
        f"{xs.shape[0]} pts, two-party): 0 mismatches")


def bench_dcf(args) -> None:
    """Single gen + single-point eval latency (benches/dcf.rs analog)."""
    from dcf_tpu.native import NativeDcf

    if args.backend in ("sharded", "sharded-pallas"):
        raise SystemExit(
            "dcf is a single-point latency bench; sharding one point over "
            "a mesh is meaningless — use any single-device backend")

    lam, nb = 16, 16
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    alphas = rng.integers(0, 256, (1, nb), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, lam), dtype=np.uint8)
    s0s = random_s0s(1, lam, rng)

    gen_s, gen_mad, gs = _timed(
        lambda: native.gen_batch(alphas, betas, s0s, Bound.LT_BETA), args.reps
    )
    _emit("dcf_gen", "cpu", "gen_latency_us", gen_s * 1e6, "us",
          gen_s, gen_mad, len(gs))

    bundle = native.gen_batch(alphas, betas, s0s, Bound.LT_BETA)
    run, _ = _make_evaluator(args.backend, lam, ck, native, args)
    xs = rng.integers(0, 256, (1, nb), dtype=np.uint8)
    k0 = bundle.for_party(0)
    run(0, k0, xs)  # warmup / compile
    ev_s, ev_mad, es = _timed(lambda: run(0, k0, xs), args.reps, args.profile)
    _emit("dcf_eval_1pt", args.backend, "eval_latency_us", ev_s * 1e6, "us",
          ev_s, ev_mad, len(es))


def bench_batch(args) -> None:
    """Batch eval throughput (benches/dcf_batch_eval.rs analog).

    --domain-bytes picks the input width: 16 (the reference bench's
    N=16-byte domain, 128 scan levels — the default and the flagship
    number) or 4 (BASELINE.json config 2's literal "n=32" wording).
    """
    from dcf_tpu.native import NativeDcf

    lam = 16
    nb = args.domain_bytes or 16
    m = args.points or 100_000
    k = args.keys or 1  # the reference bench is K=1; K>1 records the
    # walk kernel's key-axis grid scaling (shared point batch)
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    alphas = rng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = rng.integers(0, 256, (k, lam), dtype=np.uint8)
    bundle = native.gen_batch(
        alphas, betas, random_s0s(k, lam, rng), Bound.LT_BETA)
    xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
    run, be = _make_evaluator(args.backend, lam, ck, native, args)
    k0 = bundle.for_party(0)
    y = run(0, k0, xs)  # warmup / compile
    if args.check:
        want = native.eval(0, bundle, xs[:2048])
        assert np.array_equal(y[:, :2048], want), \
            "parity mismatch vs C++"  # every key's shares, not just key 0
        log(f"parity vs C++ core: OK ({k} keys x first 2048 pts)")
        _full_device_parity(args, be, lam, ck, native, bundle,
                            alphas, betas, xs)
    if be is not None and hasattr(be, "stage"):
        # Staged methodology (_timed_staged): xs conversion + transfer
        # happen outside the timed region, like criterion's untimed setup
        # (/root/reference/benches/dcf_batch_eval.rs:17-24); results stay in
        # HBM where a secure-computation consumer reads them.
        dt, mad, ss, unit = _timed_staged(be, xs, args.reps, args.profile)
    else:
        dt, mad, ss = _timed(lambda: run(0, k0, xs), args.reps, args.profile)
        unit = "evals/s"
    name = args.backend if k == 1 else f"{args.backend} (K={k})"
    if getattr(args, "mesh", ""):
        name += f" --mesh={args.mesh}"  # a sharded run must say so
    _emit("dcf_batch_eval", name, "evals_per_sec",
          k * m / dt, unit, dt, mad, len(ss),
          extra_fields=_pinned_ratio(
              nb, k, k * m / dt,
              interpreted=bool(getattr(be, "interpret", False))))


def bench_large_lambda(args) -> None:
    """Large-range eval, lam=16384 (benches/dcf_large_lambda.rs analog).

    --backend=hybrid: the narrow-walk + GF(2)-affine split
    (backends.large_lambda) — the device path built for this regime.
    --lam picks the range size: 16384 (the reference bench's literal
    shape, 2048 AES ciphers) or e.g. 256 (BASELINE.json config 4).
    --keys runs K independent keys over the shared point batch (the
    multi-key large-lambda regime the bitsliced path used to lose to the
    CPU on; the hybrid grids its narrow walk over keys and batches the
    GF(2) matmul on the MXU).
    """
    from dcf_tpu.native import NativeDcf

    lam, nb = args.lam or 16384, 16
    if lam < 48 or lam % 16:
        raise SystemExit(
            f"--lam must be a multiple of 16 >= 48 for the large-lambda "
            f"bench, got {lam}")
    m = args.points or 10_000
    k = args.keys or 1
    if args.backend in ("pallas", "sharded-pallas"):
        raise SystemExit(f"{args.backend} backend is lam=16 only; "
                         "use hybrid/cpu")
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    log(f"gen (lam={lam}, {2 * (lam // 16)} ciphers, {k} keys) ...")
    alphas = rng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = rng.integers(0, 256, (k, lam), dtype=np.uint8)
    bundle = native.gen_batch(
        alphas, betas, random_s0s(k, lam, rng), Bound.LT_BETA)
    xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
    run, be = _make_evaluator(args.backend, lam, ck, native, args)
    k0 = bundle.for_party(0)
    if args.check:
        # The C++ byte anchor needs only a small slice (at lam=16384 a
        # full-batch bytes fetch is ~160MB through the dev tunnel); the
        # full batch is then verified on device, both parties.
        y = run(0, k0, xs[:64])
        want = native.eval(0, bundle, xs[:64])
        assert np.array_equal(y[:, :64], want), "parity mismatch vs C++"
        log(f"parity vs C++ core: OK ({k} keys x first 64 pts)")
        _full_device_parity(args, be, lam, ck, native, bundle,
                            alphas, betas, xs)
    if be is not None and hasattr(be, "stage"):
        # Staged methodology: at lam=16384 the per-rep result image is
        # 160MB/key, which the dev tunnel would otherwise dominate.
        if not args.check:  # --check's parity run already shipped the bundle
            be.put_bundle(k0)
        dt, mad, ss, unit = _timed_staged(be, xs, args.reps, args.profile)
    else:
        run(0, k0, xs)  # warmup
        dt, mad, ss = _timed(lambda: run(0, k0, xs), args.reps, args.profile)
        unit = "evals/s"
    name = args.backend if k == 1 else f"{args.backend} (K={k})"
    _emit("dcf_large_lambda", name, "evals_per_sec",
          k * m / dt, unit, dt, mad, len(ss),
          extra_fields=_pinned_ratio(
              nb, k, k * m / dt, lam=lam,
              interpreted=bool(getattr(be, "interpret", False))))


def bench_secure_relu(args) -> None:
    """Many-keys x few-points workload (BASELINE.json config 5, scaled).

    Default path: C++ host keygen + XLA keys-in-lanes eval.  With
    ``--device-gen``: fully device-resident — DeviceKeyGen + the Pallas
    keylanes kernel + on-device verification (the config-5 pipeline that
    runs 10^6 keys x 1024 points, see benchmarks/RESULTS_r02.jsonl).
    ``--backend=pallas``: host keygen + the keys-in-lanes Pallas kernel
    (the 1-chip anchor the sharded overhead is measured against);
    ``--backend=sharded-pallas``: the same kernel under shard_map
    (``--mesh=KxP``).
    """
    lam, nb = 16, 16
    k = args.keys or 65_536
    m = args.points or 1_024
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    alphas = rng.integers(0, 256, (k, nb), dtype=np.uint8)
    betas = rng.integers(0, 256, (k, lam), dtype=np.uint8)
    s0s = random_s0s(k, lam, rng)
    xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)

    if args.device_gen:
        if args.backend != "cpu":
            raise SystemExit(
                "--device-gen is its own pipeline (DeviceKeyGen + Pallas "
                "keylanes); it does not combine with --backend")
        from dcf_tpu.workloads import secure_relu_check_device

        def run():
            mism = secure_relu_check_device(lam, ck, alphas, betas, s0s, xs)
            if mism:
                raise SystemExit(
                    f"secure_relu: {mism} reconstruction mismatches")

        run()  # warmup (compile) + correctness
        log(f"on-device verification: 0 mismatches of {k * m}")
        dt, mad, ss = _timed(run, args.reps, args.profile)
        _emit("secure_relu", "device-gen+pallas-keylanes", "evals_per_sec",
              2 * k * m / dt, "evals/s (incl device keygen + verify)",
              dt, mad, len(ss))
        return

    from dcf_tpu.native import NativeDcf
    from dcf_tpu.workloads import secure_relu_eval

    native = NativeDcf(lam, ck)
    log(f"gen {k} keys ...")
    bundle = native.gen_batch(alphas, betas, s0s, Bound.LT_BETA)
    if args.backend in ("pallas", "sharded-pallas"):
        # The keys-in-lanes Pallas kernel — sharded over the mesh
        # (``sharded-pallas``, the path a TPU pod runs for config 5) or
        # unsharded (``pallas``, the 1-chip anchor the sharded variant's
        # overhead is measured against).  Staged methodology (results stay
        # HBM-resident, like _timed_staged): the packed CW image ships
        # once, both parties walk it per rep.
        import jax

        from dcf_tpu.utils.benchtime import device_sync

        interp = jax.devices()[0].platform != "tpu"
        if args.backend == "sharded-pallas":
            from dcf_tpu.parallel import ShardedKeyLanesBackend, make_mesh

            mesh = make_mesh(shape=_parse_mesh(args.mesh))
            log(f"mesh: {dict(mesh.shape)}")
            be = ShardedKeyLanesBackend(lam, ck, mesh, interpret=interp)
            be.put_bundle(bundle)
            name = "sharded-keylanes-pallas"
        else:
            # Through the facade: Dcf(backend="keylanes") without a mesh is
            # the single-chip config-5 entry point (it was mesh-only before
            # round 5), and its eval ships the shared two-party image once.
            # The facade smoke-eval doubles as the reachability check; the
            # timed loop then reuses the same backend instance (image
            # already shipped) for the staged HBM-resident methodology.
            from dcf_tpu import Dcf

            dcf = Dcf(nb, lam, ck, backend="keylanes")
            y_smoke = dcf.eval(0, bundle, xs[:2])
            assert y_smoke.shape == (k, 2, lam)
            be = dcf.eval_backend()
            # Label stays "keylanes-pallas": rounds join result rows on
            # (workload, backend) and kernel + methodology are unchanged —
            # only construction moved behind the facade.
            log("constructed via the Dcf facade (backend='keylanes', no mesh)")
            name = "keylanes-pallas"
        staged = be.stage(xs)
        y0 = be.eval_staged(0, staged)
        y1 = be.eval_staged(1, staged)
        mism = int(be.relu_mismatch_count(y0, y1, alphas, betas, xs))
        if mism:
            raise SystemExit(f"secure_relu: {mism} reconstruction mismatches")
        log(f"on-device verification: 0 mismatches of {k * m}")

        def run():
            y0 = be.eval_staged(0, staged)
            y1 = be.eval_staged(1, staged)
            device_sync(y0 ^ y1)

        dt, mad, ss = _timed(run, args.reps, args.profile)
        _emit("secure_relu", name, "evals_per_sec",
              2 * k * m / dt, "evals/s (staged, results HBM-resident)",
              dt, mad, len(ss))
        return

    if args.backend == "sharded":
        # The one multi-key CLI workload: this is where mesh factorizations
        # (8x1 / 4x2 / 2x4) are meaningfully compared via --mesh.  Uses the
        # byte-layout sharded backend: at K=65536+ the bit-plane variant's
        # 32x key-image blow-up would dominate host RAM and the links.
        from dcf_tpu.parallel import ShardedJaxBackend, make_mesh

        mesh = make_mesh(shape=_parse_mesh(args.mesh))
        log(f"mesh: {dict(mesh.shape)}")
        be0 = ShardedJaxBackend(lam, ck, mesh)
        be1 = ShardedJaxBackend(lam, ck, mesh)
        name = "sharded"
    else:
        from dcf_tpu.backends.jax_bitsliced import KeyLanesBackend

        be0 = KeyLanesBackend(lam, ck)
        be1 = KeyLanesBackend(lam, ck)
        name = "bitsliced-keylanes"
    secure_relu_eval(be0, be1, bundle, xs)  # warmup / compile
    dt, mad, ss = _timed(
        lambda: secure_relu_eval(be0, be1, bundle, xs), args.reps,
        args.profile)
    # Two parties evaluated -> 2*K*M DCF evals.
    _emit("secure_relu", name, "evals_per_sec",
          2 * k * m / dt, "evals/s", dt, mad, len(ss))


def bench_full_domain(args) -> None:
    """Full-domain two-party reconstruction (BASELINE.json config 3).

    Staged backends (pallas/bitsliced) run fully device-resident: points
    generated from an iota on device, XOR reconstruction verified on
    device, only the mismatch counter fetched.  Other backends use the
    host chunk loop.  The metric counts both parties' evals.
    """
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.workloads import full_domain_check, full_domain_check_device

    lam = 16
    n_bits = args.n_bits or 24
    if n_bits % 8 != 0 or n_bits < 8:
        raise SystemExit(f"--n-bits must be a positive multiple of 8, "
                         f"got {n_bits} (domains are byte-granular, "
                         "SURVEY.md section 0)")
    nb = n_bits // 8
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    alpha = int(rng.integers(0, 1 << n_bits))
    beta = rng.bytes(lam)
    bundle = native.gen_batch(
        np.frombuffer(alpha.to_bytes(nb, "big"), dtype=np.uint8)[None],
        np.frombuffer(beta, dtype=np.uint8)[None],
        random_s0s(1, lam, rng),
        Bound.LT_BETA,
    )
    chunk = min(1 << 20, 1 << n_bits)
    per_run_checks = 1
    sub_rtt = 0.0
    if args.backend == "tree":
        # Device-accumulated counters, fetched once per sample — the same
        # sync-amortization methodology as the staged batch bench, with
        # the one per-sample sync RTT measured and subtracted like
        # _timed_staged does.  With --mesh the frontier shards over the
        # mesh and each device expands+verifies its disjoint subtree.
        from dcf_tpu.utils.benchtime import (
            DISPATCHES_PER_SAMPLE_TREE,
            measure_sync_rtt,
        )

        import jax.numpy as jnp

        if args.mesh:
            import jax

            from dcf_tpu.parallel import ShardedTreeFullDomain, make_mesh

            mesh = make_mesh(shape=_parse_mesh(args.mesh))
            log(f"mesh: {dict(mesh.shape)}")
            fd = ShardedTreeFullDomain(
                lam, ck, mesh,
                interpret=jax.devices()[0].platform != "tpu")
        else:
            from dcf_tpu.backends.fulldomain import TreeFullDomain

            fd = TreeFullDomain(lam, ck)
        per_run_checks = DISPATCHES_PER_SAMPLE_TREE
        from dcf_tpu.utils.benchtime import device_sync

        probe = jnp.zeros(8, jnp.int32)
        device_sync(probe)  # materialize: measure_sync_rtt wants a synced y
        sub_rtt = measure_sync_rtt(probe)

        def run():
            counters = [fd.check_device(bundle, alpha, beta, n_bits)
                        for _ in range(per_run_checks)]
            if int(jnp.sum(jnp.stack(counters))):
                raise SystemExit("full_domain: reconstruction mismatches")
    elif args.backend in ("pallas", "bitsliced"):
        if args.backend == "pallas":
            from dcf_tpu.backends.pallas_backend import PallasBackend as B
        else:
            from dcf_tpu.backends.jax_bitsliced import BitslicedBackend as B
        be0, be1 = B(lam, ck), B(lam, ck)
        be0.put_bundle(bundle.for_party(0))
        be1.put_bundle(bundle.for_party(1))

        def run():
            mism = full_domain_check_device(
                be0, be1, alpha, beta, n_bits, chunk=chunk)
            if mism:
                raise SystemExit(f"full_domain: {mism} mismatches")
    else:
        run0, _ = _make_evaluator(args.backend, lam, ck, native, args)
        k0 = bundle.for_party(0)
        k1 = bundle.for_party(1)

        def run():
            mism = full_domain_check(
                lambda xs: run0(0, k0, xs), lambda xs: run0(1, k1, xs),
                alpha, beta, n_bits, chunk=chunk)
            if mism:
                raise SystemExit(f"full_domain: {mism} mismatches")

    run()  # warmup / compile + correctness
    log(f"full domain 2^{n_bits}: 0 mismatches")
    dt, mad, ss = _timed(run, args.reps, args.profile)
    dt = max(dt - sub_rtt, 1e-9) / per_run_checks
    mad = mad / per_run_checks
    # The unit discloses the RTT correction when one was applied (tree is
    # the only branch that measures sub_rtt), matching _timed_staged's
    # wording — JSON consumers must be able to tell a corrected number
    # from an uncorrected one.
    unit = "evals/s (sync RTT subtracted)" if sub_rtt else "evals/s"
    _emit("full_domain", args.backend, "evals_per_sec",
          2 * (1 << n_bits) / dt, unit, dt, mad, len(ss))


def _gen_serve_bundles(svc, native, rng, n_bundles, nb, lam,
                       durable: bool = False) -> dict:
    """``n_bundles`` fresh single-key two-party bundles, registered
    under ``key-<i>`` (the serve_bench/chaos_bench workload shape).
    ``durable=True`` writes each through the service's key store
    (chaos_bench --crash-restart)."""
    bundles = {}
    for i in range(n_bundles):
        alphas = rng.integers(0, 256, (1, nb), dtype=np.uint8)
        betas = rng.integers(0, 256, (1, lam), dtype=np.uint8)
        b = native.gen_batch(alphas, betas, random_s0s(1, lam, rng),
                             Bound.LT_BETA)
        bundles[f"key-{i}"] = b
        svc.register_key(f"key-{i}", b, durable=durable)
    return bundles


def _serve_parity_gate(svc, native, bundles, rng, nb, *, points: int,
                       bench: str, tag: str = "",
                       priority: str = "normal",
                       timeout: float | None = None) -> None:
    """Every bundle, both parties, through the SERVICE, XOR
    reconstruction vs the C++ anchor (shared by serve_bench and
    chaos_bench — one copy, or the benches silently diverge)."""
    xs = rng.integers(0, 256, (points, nb), dtype=np.uint8)
    for name, bundle in bundles.items():
        f0 = svc.submit(name, xs, b=0, priority=priority)
        f1 = svc.submit(name, xs, b=1, priority=priority)
        svc.pump()
        want = native.eval(0, bundle, xs) ^ native.eval(1, bundle, xs)
        if not np.array_equal(f0.result(timeout) ^ f1.result(timeout),
                              want):
            where = f" ({tag})" if tag else ""
            raise SystemExit(
                f"{bench} parity mismatch vs C++ on {name}{where}")
    where = f" ({tag})" if tag else ""
    log(f"parity vs C++ core{where}: OK ({len(bundles)} bundles x "
        f"{points} pts, two-party)")


def bench_serve(args) -> None:
    """Closed-loop load test of the online serving layer (ISSUE 4).

    Shape: the flagship N=16/lam=16 domain, ``--bundles`` registered
    single-key bundles, ``--concurrency`` closed-loop clients submitting
    ragged requests sized uniformly in [3/8, 1/2] of ``--max-batch`` by
    default (``--min-req-points``/``--max-req-points`` override; the
    default range makes coalesced batches exercise padding AND near-full
    occupancy) for ``--duration`` seconds.  Backend = any facade backend usable at
    lam=16 (``bitsliced`` is the no-TPU default; explicit ``pallas``
    stays strict/compiled, per the facade contract).

    The line also records the STAGED-PATH equivalent: the same backend
    evaluating one staged ``--max-batch`` batch in a bare loop (one
    dispatch per sample, sync RTT subtracted) — the serving layer's
    overhead budget is ``serve_vs_staged`` of that rate.  Parity is
    gated before timing: one sample request per bundle, both parties,
    XOR reconstruction vs the C++ host core.

    ``--skew s`` (ISSUE 7) switches the key choice to Zipf(s) and runs
    the skew-curve experiment: a CACHED leg (the serve-resident
    frontier cache on, the default) and a COLD-frontier leg
    (``frontier_cache=False`` — the pre-cache instance-store behavior)
    at the SAME shape, seeds and device-byte budget (defaulted to 80%
    of the party-0 working set so the LRU actually churns — an uncapped
    registry never rebuilds anything and the two legs coincide),
    interleaved in alternating segments so shared-host throughput
    drift cancels out of the ratio.  The
    emitted line gains ``skew``, ``frontier_hit_rate`` (hits /
    consults), the cold leg's rate and ``cached_vs_cold``; with a
    frontier-capable backend the run FAILS (exit != 0) unless hit-rate
    >= 0.5 and the cached leg strictly beats the cold one — the
    amortization claim is falsifiable with one command
    (``--backend prefix --skew 1.1``; plain ``--skew 1.1`` defaults the
    backend to ``prefix`` for exactly this reason).
    """
    from dcf_tpu import Dcf
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.serve.loadgen import closed_loop
    from dcf_tpu.utils.benchtime import device_sync, measure_sync_rtt

    lam, nb = 16, 16
    skew = _parse_skew(args.skew)  # bad flags fail fast, before the
    # bundle gen / warmup ladder / parity gate spend real time
    backend = args.backend
    if skew > 0 and backend == "cpu":
        # The skew curve is about the serve frontier cache; "cpu" is the
        # global argparse default (rejected below), so route it to the
        # frontier-capable lam=16 backend instead of dying on a flag the
        # user never chose.
        backend = "prefix"
        log("--skew exercises the serve frontier cache; defaulting "
            "--backend to prefix (the frontier-capable lam=16 backend)")
    if backend not in ("numpy", "jax", "bitsliced", "pallas", "prefix"):
        raise SystemExit(
            f"serve_bench serves lam=16 single-device facade backends "
            f"(numpy/jax/bitsliced/pallas/prefix), got {backend!r}")
    max_batch = args.max_batch or ((1 << 10) if skew > 0 else (1 << 17))
    n_bundles = args.bundles or (8 if skew > 0 else 3)
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    opts = None
    if backend == "prefix" and args.prefix_levels:
        opts = {"prefix_levels": args.prefix_levels}
    elif backend == "prefix" and skew > 0:
        import jax

        if jax.devices()[0].platform != "tpu":
            # Interpret-mode frontier expansion at the backend's default
            # depth 21 takes ~2 minutes per (key, party) on XLA-CPU —
            # the skew experiment needs churnable frontiers, not a
            # 30-minute warmup.  k=10 also keeps a frontier (32 KB)
            # byte-cheap next to its key image (133 KB): the merged LRU
            # then sheds images first and cached frontiers survive the
            # churn, which is the amortization under test (at equal
            # byte cost the sweep drops a cold key's image AND frontier
            # together and every re-stage rebuilds).  On-chip the
            # default depth stands.
            opts = {"prefix_levels": 10}
            log("no TPU: clamping the prefix frontier to "
                "prefix_levels=10 for interpret mode (override with "
                "--prefix-levels)")
    dcf = Dcf(nb, lam, ck, backend=backend, backend_opts=opts)
    svc = dcf.serve(max_batch=max_batch,
                    max_delay_ms=args.max_delay_ms,
                    device_bytes_budget=args.device_bytes_budget)
    log(f"gen {n_bundles} bundles ...")
    bundles = _gen_serve_bundles(svc, native, rng, n_bundles, nb, lam)
    parity_pts = 128 if skew > 0 else 512
    _serve_parity_gate(svc, native, bundles, rng, nb, points=parity_pts,
                       bench="serve_bench")

    min_req = args.min_req_points or (max_batch * 3 // 8)
    max_req = args.max_req_points or (max_batch // 2)
    if not 1 <= min_req <= max_req:
        raise SystemExit(f"bad request-size range [{min_req}, {max_req}]")

    # Skew mode: a churn budget — without one the LRU never evicts, no
    # frontier is ever rebuilt, and the cached and cold legs coincide.
    # Default: 80% of the party-0 working set (image + frontier per
    # key, probed from the already-staged key-0 residency) — below the
    # full image demand so residencies churn, with enough slack that
    # the byte-cheap frontier population can persist through the churn
    # (measured: at 50% the steady state pins AT the budget, every
    # frontier insert evicts a frontier, and the cache holds only the
    # hot keys that never needed re-staging — zero amortization in
    # EITHER leg's favor).
    budget = args.device_bytes_budget
    if skew > 0 and not budget:
        from dcf_tpu.serve.registry import device_image_bytes

        per_img = device_image_bytes(svc.registry.resident("key-0", 0))
        fc = svc.frontier_cache
        n_fc = len(fc.lru_entries()) if fc is not None else 0
        per_frontier = fc.total_bytes() // n_fc if n_fc else 0
        budget = max(1, (per_img + per_frontier) * n_bundles * 4 // 5)
        log(f"skew mode: device_bytes_budget defaulted to {budget:,} B "
            f"(80% of the party-0 working set of {n_bundles} keys)")
    if skew > 0:
        svc.registry.device_bytes_budget = budget

    # Warm every padded batch shape the loop can produce (each distinct
    # power of two is one XLA compile; a compile inside the timed loop
    # would be measured as serving time).  Coalescing and splitting can
    # land remainder batches on ANY power of two from next_pow2(min_req)
    # up to max_batch, so warm the whole ladder — log2(max_batch) shapes
    # at most, each one dispatch.
    from dcf_tpu.serve.batcher import next_pow2

    xs_warm = rng.integers(0, 256, (max_batch, nb), dtype=np.uint8)
    m = next_pow2(min_req)
    while m <= max_batch:
        log(f"warming batch shape {m} ...")
        svc.submit("key-0", xs_warm[:m])
        svc.pump()
        m *= 2

    import jax

    # Disclosure: a no-TPU session serves XLA-CPU (or interpret-mode
    # Pallas) graphs — the committed line must say so, same policy as
    # _pinned_ratio's interpreted rule.
    platform = jax.devices()[0].platform
    interp = (platform != "tpu"
              or bool(getattr(dcf.eval_backend(0), "interpret", False)))
    res_cold = cold_snap = wire_res = None
    if args.edge and skew > 0:
        raise SystemExit(
            "serve_bench --edge is the wire-path comparison leg; the "
            "--skew frontier experiment already runs two legs — run "
            "them separately")
    if skew > 0:
        # The COLD-frontier comparison leg: same backend, same bundles,
        # same budget/shape/seeds, frontier_cache=False — every budget
        # eviction costs the next touch a full 2^k frontier expansion
        # on the serving clock.  Parity-gated like the cached leg (the
        # gate also pre-stages both parties, keeping the legs' starting
        # states symmetric before the budget bites).
        log("cold-frontier comparison service (frontier_cache=False) ...")
        svc_cold = dcf.serve(max_batch=max_batch,
                             max_delay_ms=args.max_delay_ms,
                             frontier_cache=False)
        for name, bundle in bundles.items():
            svc_cold.register_key(name, bundle)
        _serve_parity_gate(svc_cold, native, bundles, rng, nb,
                           points=parity_pts, bench="serve_bench",
                           tag="cold leg")
        svc_cold.registry.device_bytes_budget = budget
        m = next_pow2(min_req)
        while m <= max_batch:  # same ladder; the compiles are shared
            svc_cold.submit("key-0", xs_warm[:m])
            svc_cold.pump()
            m *= 2
        # The legs run INTERLEAVED, 3 alternating segments each, not
        # back to back: a shared host's throughput drifts by more than
        # the effect under test over tens of seconds, and alternation
        # makes the drift hit both legs equally — the cached/cold ratio
        # then reflects the cache, not the neighbors.  Each leg still
        # gets --duration seconds of load in total, and segment state
        # (residencies, cache population) carries across segments, so
        # the steady-state churn dynamics are those of one long run.
        segs = 3
        seg_s = float(args.duration) / segs
        runs = {"cached": [], "cold": []}
        with svc, svc_cold:
            for i in range(2 * segs):
                leg, tgt = (("cached", svc) if i % 2 == 0
                            else ("cold", svc_cold))
                # i // 2: the cached and cold halves of each segment
                # pair draw the SAME seeded key/size streams — seed
                # luck must not decide the cached_vs_cold gate.
                runs[leg].append(closed_loop(
                    tgt, sorted(bundles), duration_s=seg_s,
                    concurrency=args.concurrency,
                    min_points=min_req, max_points=max_req,
                    seed=args.seed + i // 2, skew=skew))
        res = _merge_loadgen(runs["cached"])
        res_cold = _merge_loadgen(runs["cold"])
        snap = svc.metrics_snapshot()
        cold_snap = svc_cold.metrics_snapshot()
    else:
        with svc:
            res = closed_loop(
                svc, sorted(bundles), duration_s=float(args.duration),
                concurrency=args.concurrency,
                min_points=min_req, max_points=max_req,
                seed=args.seed, skew=skew)
            if args.edge:
                # The --edge leg (ISSUE 12): the same closed-loop
                # shape over the DCFE wire path — one TCP connection
                # per client — so the serve line carries the wire/
                # in-process ratio next to the staged-path one.
                # edge_bench is the full acceptance harness; this leg
                # is the one-flag comparison.
                from dcf_tpu.serve.edge import EdgeServer

                with EdgeServer(svc) as edge_srv:
                    clients = _edge_clients(*edge_srv.address,
                                            args.concurrency, nb, "")
                    try:
                        wire_res = closed_loop(
                            svc, sorted(bundles),
                            duration_s=float(args.duration),
                            concurrency=args.concurrency,
                            min_points=min_req, max_points=max_req,
                            seed=args.seed, skew=skew,
                            clients=clients)
                    finally:
                        for c in clients:
                            c.close()
                log(f"edge leg: {wire_res.throughput:,.1f} evals/s "
                    f"over the wire vs {res.throughput:,.1f} "
                    "in-process")
        snap = svc.metrics_snapshot()

    # Staged-path equivalent: same backend, one staged max_batch batch,
    # bare dispatch loop (one dispatch per sample — CPU-mode dispatches
    # are seconds long, the 128-dispatch sample would take minutes).
    staged_rate = None
    be = dcf.new_eval_backend()
    if be is not None and hasattr(be, "stage"):
        be.put_bundle(bundles["key-0"].for_party(0))
        staged = be.stage(xs_warm)
        y = be.eval_staged(0, staged)
        device_sync(y)  # warmup/compile
        rtt = measure_sync_rtt(y)

        def one():
            device_sync(be.eval_staged(0, staged))

        dt, mad, ss = _timed(one, args.reps)
        staged_rate = max_batch / max(dt - rtt, 1e-9)
        log(f"staged-path rate at {max_batch} pts: {staged_rate:,.1f} "
            f"evals/s (median {dt * 1e3:.1f} ms +- {mad * 1e3:.1f} ms, "
            f"{len(ss)} samples, sync RTT subtracted)")

    extra = {
        "duration_s": round(res.duration_s, 3),
        "concurrency": args.concurrency,
        "max_batch": max_batch,
        "req_points": [min_req, max_req],
        "bundles": n_bundles,
        "requests_ok": res.requests_ok,
        "requests_shed": res.requests_shed,
        "requests_failed": res.requests_failed,
        **res.latency_quantiles(),
        "platform": platform,
        "interpreted": interp,
        "metrics_snapshot": snap,
    }
    if staged_rate is not None:
        extra["staged_path_evals_per_sec"] = round(staged_rate, 1)
        extra["serve_vs_staged"] = round(res.throughput / staged_rate, 3)
    if wire_res is not None:
        extra["wire_evals_per_sec"] = round(wire_res.throughput, 1)
        extra["wire_requests_ok"] = wire_res.requests_ok
        extra["wire_vs_inprocess"] = round(
            wire_res.throughput / max(res.throughput, 1e-9), 3)
    hit_rate = None
    if skew > 0:
        fr_hits = snap.get("serve_frontier_hits_total", 0)
        fr_misses = snap.get("serve_frontier_misses_total", 0)
        hit_rate = fr_hits / max(fr_hits + fr_misses, 1)
        log(f"frontier cache: {fr_hits} hits / {fr_misses} misses "
            f"(hit rate {hit_rate:.3f}); cached {res.throughput:,.1f} "
            f"vs cold {res_cold.throughput:,.1f} evals/s")
        extra.update({
            "skew": skew,
            "segments_per_leg": segs,
            "prefix_levels": getattr(dcf.eval_backend(0),
                                     "prefix_levels", 0),
            "frontier_hit_rate": round(hit_rate, 4),
            "frontier_hits": fr_hits,
            "frontier_misses": fr_misses,
            "frontier_evictions":
                snap.get("serve_frontier_evictions_total", 0),
            "device_bytes_budget_effective": budget,
            "cached_key_stagings": snap.get("serve_key_stagings_total",
                                            0),
            "cold_frontier_evals_per_sec": round(res_cold.throughput, 1),
            "cold_requests_ok": res_cold.requests_ok,
            "cold_key_stagings": cold_snap.get("serve_key_stagings_total",
                                               0),
            "cached_vs_cold": round(
                res.throughput / max(res_cold.throughput, 1e-9), 3),
        })
    extra.update(_serve_pinned_ratio(res.throughput, platform))
    unit = "evals/s (closed-loop served, party 0)"
    if interp:
        unit += " [no TPU this session: interpret/CPU mode, disclosed]"
    _emit("serve_bench", backend, "evals_per_sec",
          res.throughput, unit, extra_fields=extra)

    # The skew-mode acceptance assertions (ISSUE 7) — emitted-then-
    # asserted like chaos_bench, so the JSONL line survives a failure
    # and the exit code makes the claim falsifiable in CI/on-chip.
    if skew > 0 and getattr(dcf.eval_backend(0), "prefix_levels", 0):
        failures = []
        if hit_rate < 0.5:
            failures.append(
                f"frontier hit-rate {hit_rate:.3f} < 0.5 — the cache is "
                "not amortizing under this skew/budget")
        if res.throughput <= res_cold.throughput:
            failures.append(
                f"cached leg ({res.throughput:,.1f} evals/s) did not "
                f"beat the cold-frontier leg ({res_cold.throughput:,.1f})"
                " at the same shape")
        if failures:
            raise SystemExit("serve_bench --skew: "
                             + "; ".join(failures))


def _merge_loadgen(rs):
    """Fold the per-segment ``LoadgenResult``s of one interleaved leg
    into a single total (rates are then points per SUMMED duration).
    Folds INTO ``rs[0]`` — callers hand over the segment list."""
    tot = rs[0]
    for r in rs[1:]:
        tot.duration_s += r.duration_s
        tot.requests_ok += r.requests_ok
        tot.points_ok += r.points_ok
        tot.requests_failed += r.requests_failed
        tot.requests_shed += r.requests_shed
        tot.latencies_s.extend(r.latencies_s)
        for cls, counts in r.by_class.items():
            for outcome, n in counts.items():
                tot.by_class.setdefault(
                    cls, {"ok": 0, "shed": 0, "failed": 0})[outcome] += n
    return tot


def _serve_pinned_ratio(rate: float, platform: str,
                        baseline_path: str | None = None) -> dict:
    """vs_baseline for serve_bench: the pinned single-core C++ flagship
    eval denominator (``benchmarks/cpu_baseline.json`` top level,
    CPU_BASELINE.md protocol) — what the obviously-correct host core
    evaluates per second at the same N=16/lam=16 shape, single thread.
    Kept for XLA-CPU/interpret serving runs (both sides are CPU; the
    mic_bench precedent) with the platform disclosed on the same JSONL
    line.  Empty when no pin exists (no silent in-run fallback)."""
    pinned = _load_pinned(baseline_path)
    if pinned is None or "evals_per_sec" not in pinned:
        return {}
    return {"vs_baseline": round(rate / pinned["evals_per_sec"], 3),
            "baseline": f"pinned single-core flagship C++ eval "
                        f"({pinned['evals_per_sec']:,.0f} evals/s, "
                        f"CPU_BASELINE.md protocol; serving platform "
                        f"{platform})"}


def _edge_clients(host: str, port: int, n: int, nb: int,
                  tenant: str) -> list:
    """``n`` single-connection reconnecting pools (ISSUE 13: PR 12's
    hand-rolled closed-check/reconnect bench logic now lives in
    ``serve.edge.EdgeClientPool`` — size=1 keeps the closed-loop
    one-connection-per-client shape while dead connections replace
    themselves with backoff instead of killing the leg)."""
    from dcf_tpu.serve.edge import EdgeClientPool

    return [EdgeClientPool(host, port, n_bytes=nb, tenant=tenant,
                           size=1)
            for _ in range(n)]


def _edge_soak(addr, native, bundles, nb, *, conns: int,
               duration_s: float, tenant: str, seed: int,
               fault_every: int) -> dict:
    """The edge acceptance soak (ISSUE 12): ``conns`` concurrent
    connections, each a closed-loop session client evaluating BOTH
    parties of a ragged request and reconstructing, with an
    ``edge.read`` fault killing whichever connection owns every
    ``fault_every``-th server recv (deterministic, so the failure path
    is GUARANTEED to be exercised) — dead connections reconnect, every
    delivered reconstruction is checked bit-exact against the C++
    anchor (the test suite pins the same walk against the numpy
    oracle), and every typed refusal must carry a retry-after hint."""
    import threading

    from dcf_tpu.errors import QueueFullError
    from dcf_tpu.serve.edge import EdgeClient
    from dcf_tpu.testing import faults
    from dcf_tpu.utils.benchtime import monotonic

    host, port = addr
    names = sorted(bundles)
    stats = {"sessions_ok": 0, "points_ok": 0, "mismatches": 0,
             "reconnects": 0, "refusals": 0, "refusals_hinted": 0,
             "other_failures": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + 101 * i)
        conn = None
        while not stop.is_set():
            if conn is None:
                try:
                    conn = EdgeClient(host, port, n_bytes=nb,
                                      tenant=tenant)
                except OSError:
                    continue  # server busy accepting; retry
            name = names[int(rng.integers(0, len(names)))]
            m = int(rng.integers(1, 257))
            xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
            try:
                f0 = conn.submit(name, xs, b=0)
                f1 = conn.submit(name, xs, b=1)
                got = f0.result(120) ^ f1.result(120)
            except QueueFullError as e:
                with lock:
                    stats["refusals"] += 1
                    if e.retry_after_s is not None:
                        stats["refusals_hinted"] += 1
                continue
            except Exception:  # fallback-ok: the injected edge.read
                # fault kills this client's CONNECTION typed; the soak
                # client reconnects — that recovery loop is the thing
                # under test.  Only an actually-DEAD connection counts
                # as a reconnect: a request-level typed failure leaves
                # the connection open and must not inflate the
                # reconnects gate the deterministic fault exists for.
                if not conn.closed:
                    with lock:
                        stats["other_failures"] += 1
                    continue
                with lock:
                    stats["reconnects"] += 1
                try:
                    conn.close()
                except Exception:  # fallback-ok: best-effort teardown
                    pass
                conn = None
                continue
            want = native.eval(0, bundles[name], xs) ^ \
                native.eval(1, bundles[name], xs)
            with lock:
                if np.array_equal(got, want):
                    stats["sessions_ok"] += 1
                    stats["points_ok"] += m
                else:
                    stats["mismatches"] += 1
        if conn is not None:
            try:
                conn.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass

    threads = [threading.Thread(target=client, args=(i,),
                                name=f"edge-soak-{i}", daemon=True)
               for i in range(conns)]
    fires = {"n": 0}

    def every_nth(*_args):
        fires["n"] += 1
        if fires["n"] % fault_every == 0:
            # dcflint: disable=typed-error this IS the fault-injection
            # handler (testing.faults raises InjectedFault by design;
            # the harness modules are exempt, this handler just lives
            # in the bench that arms it)
            raise faults.InjectedFault(
                f"injected edge.read fault (fire #{fires['n']})")

    with faults.inject("edge.read", handler=every_nth):
        t0 = monotonic()
        for t in threads:
            t.start()
        while monotonic() - t0 < duration_s:
            stop.wait(0.05)
        stop.set()
        for t in threads:
            t.join()
    return stats


def bench_edge(args) -> None:
    """The network-edge acceptance bench (ISSUE 12): the zero-copy
    DCFE wire path vs the in-process serving rate, at the same shape.

    Legs, one service end to end (flagship N=16/lam=16 shape):

    1. parity gates — every bundle, both parties, through the
       IN-PROCESS path and through the WIRE path, XOR reconstruction
       vs the C++ anchor;
    2. ingest probe — a counted wrap of ``batcher.ingest_points``
       proves the bytes-ingest entry is the ONLY batcher feed on both
       paths (the zero-per-point-object claim, asserted, on the line);
    3. throughput — in-process vs wire closed-loop legs INTERLEAVED in
       3 alternating segments (shared-host drift cancels out of the
       ratio), ``--connections`` wire clients each on their own TCP
       connection; the emitted ``wire_vs_inprocess`` must be >= 0.8
       (exit != 0 below: the zero-copy claim, falsified by
       measurement);
    4. the 8+-connection soak under a seeded ``edge.read`` fault —
       connections die typed and reconnect, every delivered
       reconstruction bit-exact vs the C++ anchor, zero tolerated
       mismatches;
    5. refusals — a burst through the rate-limited BATCH tenant; every
       refusal must arrive as a typed wire error CARRYING a
       retry-after hint (asserted);
    6. open-loop latency — a Poisson-arrival leg at 60% of the
       measured wire request rate (``serve.loadgen.open_loop``:
       latency from SCHEDULED arrival, no coordinated omission), with
       sent/shed/expired reconciled against the service metrics.

    Emits one ``RESULTS_edge`` JSONL line (interpret/CPU disclosed
    in-line; the same command on a chip is the repro), with
    ``vs_baseline`` against the pinned single-core C++ flagship
    denominator (CPU_BASELINE.md protocol), then applies the exit-code
    gates."""
    from dcf_tpu import Dcf
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.serve import TenantSpec
    from dcf_tpu.serve import batcher as batcher_mod
    from dcf_tpu.serve import service as service_mod
    from dcf_tpu.serve.edge import EdgeServer
    from dcf_tpu.serve.loadgen import closed_loop, open_loop

    lam, nb = 16, 16
    backend = args.backend
    if backend == "cpu":
        backend = "bitsliced"  # the no-TPU serving default, as in
        # serve_bench's skew mode: "cpu" is the global argparse default
    if backend not in ("numpy", "jax", "bitsliced", "pallas", "prefix"):
        raise SystemExit(
            f"edge_bench serves lam=16 single-device facade backends "
            f"(numpy/jax/bitsliced/pallas/prefix), got {backend!r}")
    conns = args.connections
    if conns < 1:
        raise SystemExit(f"--connections must be >= 1, got {conns}")
    max_batch = args.max_batch or (1 << 14)
    min_req = args.min_req_points or (max_batch * 3 // 8)
    max_req = args.max_req_points or (max_batch // 2)
    if not 1 <= min_req <= max_req:
        # fail fast, before the bundle gen / warmup ladder spend time
        raise SystemExit(f"bad request-size range [{min_req}, {max_req}]")
    n_bundles = args.bundles or 3
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    dcf = Dcf(nb, lam, ck, backend=backend)
    # The tenant table (ServeConfig.tenants -> the PR 6 classes):
    # throughput/soak traffic rides "silver" (NORMAL, unlimited); the
    # refusal leg bursts through "bronze" (BATCH, rate-limited so the
    # bucket demonstrably refuses with its exact time-to-refill).
    bronze_rate = float(max_batch)
    svc = dcf.serve(
        max_batch=max_batch, max_delay_ms=args.max_delay_ms,
        tenants=(TenantSpec("gold", "critical"),
                 TenantSpec("silver", "normal"),
                 TenantSpec("bronze", "batch",
                            points_per_sec=bronze_rate,
                            burst_points=max_batch // 2)))
    log(f"gen {n_bundles} bundles ...")
    bundles = _gen_serve_bundles(svc, native, rng, n_bundles, nb, lam)
    _serve_parity_gate(svc, native, bundles, rng, nb, points=256,
                       bench="edge_bench", tag="in-process")

    # Warm every padded batch shape both the ragged legs and the soak
    # (m in [1, 256]) can produce — same ladder rule as serve_bench,
    # but for BOTH parties: the soak reconstructs two-party, and the
    # party-1 eval graphs are their own compiles.
    xs_warm = rng.integers(0, 256, (max_batch, nb), dtype=np.uint8)
    m = 1
    while m <= max_batch:
        log(f"warming batch shape {m} (both parties) ...")
        svc.submit("key-0", xs_warm[:m], b=0)
        svc.submit("key-0", xs_warm[:m], b=1)
        svc.pump()
        m *= 2

    import jax

    platform = jax.devices()[0].platform
    interp = (platform != "tpu"
              or bool(getattr(dcf.eval_backend(0), "interpret", False)))

    svc.start()
    edge = EdgeServer(svc).start()
    addr = edge.address
    log(f"edge listening on {addr[0]}:{addr[1]}")

    # Wire parity gate: same bundles, both parties, over TCP.
    wire_gate = _edge_clients(*addr, 1, nb, "silver")[0]
    xs_gate = rng.integers(0, 256, (256, nb), dtype=np.uint8)
    for name, bundle in bundles.items():
        got = wire_gate.evaluate(name, xs_gate, b=0, timeout=300) ^ \
            wire_gate.evaluate(name, xs_gate, b=1, timeout=300)
        want = native.eval(0, bundle, xs_gate) ^ \
            native.eval(1, bundle, xs_gate)
        if not np.array_equal(got, want):
            raise SystemExit(
                f"edge_bench parity mismatch vs C++ on {name} (wire)")
    log(f"parity vs C++ core (wire): OK ({len(bundles)} bundles x "
        "256 pts, two-party)")

    # Ingest probe: ingest_points is the ONE batcher feed — count its
    # calls across an in-process and a wire submit burst and require
    # exactly one call per request (zero per-point Python objects by
    # construction: the entry wraps the frame buffer, never iterates
    # points).
    real_ingest = batcher_mod.ingest_points
    probe = {"calls": 0}

    def counting_ingest(data, n_bytes, m=None):
        probe["calls"] += 1
        return real_ingest(data, n_bytes, m)

    service_mod.ingest_points = counting_ingest
    try:
        xs_probe = rng.integers(0, 256, (64, nb), dtype=np.uint8)
        for _ in range(4):
            svc.evaluate("key-0", xs_probe, timeout=120)
        for _ in range(4):
            wire_gate.evaluate("key-0", xs_probe, timeout=120)
    finally:
        service_mod.ingest_points = real_ingest
    ingest_single_feed = probe["calls"] == 8
    log(f"ingest probe: {probe['calls']} ingest_points calls for 8 "
        f"requests (single-feed={ingest_single_feed})")
    wire_gate.close()

    # Throughput: interleaved in-process / wire closed-loop segments.
    segs = 3
    seg_s = float(args.duration) / (2 * segs)
    clients = _edge_clients(*addr, conns, nb, "silver")
    runs = {"inproc": [], "wire": []}
    try:
        for i in range(2 * segs):
            leg = "inproc" if i % 2 == 0 else "wire"
            kw = dict(duration_s=seg_s, concurrency=conns,
                      min_points=min_req, max_points=max_req,
                      seed=args.seed + i // 2)
            if leg == "wire":
                kw["clients"] = clients
            runs[leg].append(closed_loop(svc, sorted(bundles), **kw))
    finally:
        pass  # clients stay up for the open-loop leg below
    res_in = _merge_loadgen(runs["inproc"])
    res_wire = _merge_loadgen(runs["wire"])
    wire_vs_inprocess = res_wire.throughput / max(res_in.throughput,
                                                  1e-9)
    log(f"throughput: wire {res_wire.throughput:,.1f} vs in-process "
        f"{res_in.throughput:,.1f} evals/s "
        f"(wire_vs_inprocess={wire_vs_inprocess:.3f})")

    # Open-loop latency leg: 60% of the measured wire request rate.
    snap_before = svc.metrics_snapshot()
    wire_rps = res_wire.requests_ok / max(res_wire.duration_s, 1e-9)
    open_rate = max(0.6 * wire_rps, 1.0)
    res_open = open_loop(
        clients[0], sorted(bundles), rate_rps=open_rate,
        duration_s=min(float(args.duration) / 3, 10.0),
        min_points=min_req, max_points=max_req, seed=args.seed + 17)
    snap_after = svc.metrics_snapshot()
    open_reconciled = (
        res_open.sent == snap_after["serve_requests_total"]
        - snap_before["serve_requests_total"]
        and res_open.expired == snap_after["serve_deadline_expired_total"]
        - snap_before["serve_deadline_expired_total"])
    log(f"open-loop @ {open_rate:,.1f} req/s: ok={res_open.ok} "
        f"shed={res_open.shed} expired={res_open.expired} "
        f"{res_open.latency_quantiles()} (reconciled={open_reconciled})")
    for c in clients:
        c.close()

    # Refusal leg: burst the rate-limited BATCH tenant until the
    # bucket refuses; every refusal must carry a retry-after hint.
    from dcf_tpu.errors import QueueFullError

    bronze = _edge_clients(*addr, 1, nb, "bronze")[0]
    refusals = refusals_hinted = 0
    xs_burst = rng.integers(0, 256, (max_batch // 2, nb),
                            dtype=np.uint8)
    # Submit the whole burst CONCURRENTLY (pipelined on one
    # connection) so the bucket sees it inside one refill window —
    # sequential blocking round trips would let a slow interpret-mode
    # host refill the bucket between attempts and flake the
    # refusals>=1 gate on a healthy edge.
    burst = [bronze.submit("key-0", xs_burst) for _ in range(6)]
    for f in burst:
        try:
            f.result(300)
        except QueueFullError as e:
            refusals += 1
            if e.retry_after_s is not None:
                refusals_hinted += 1
    bronze.close()
    log(f"refusal leg: {refusals} rate-limit refusals, "
        f"{refusals_hinted} carried retry_after_s")

    # The soak: 8+ connections under a deterministic edge.read fault.
    soak_s = max(float(args.duration) / 4, 3.0)
    soak = _edge_soak(addr, native, bundles, nb,
                      conns=max(conns, 8), duration_s=soak_s,
                      tenant="silver", seed=args.seed,
                      fault_every=25)
    log(f"soak: {soak}")

    snap = svc.metrics_snapshot()
    edge.close()
    svc.close()

    extra = {
        "duration_s": round(res_wire.duration_s, 3),
        "connections": conns,
        "max_batch": max_batch,
        "req_points": [min_req, max_req],
        "bundles": n_bundles,
        "segments_per_leg": segs,
        "wire_requests_ok": res_wire.requests_ok,
        "inprocess_evals_per_sec": round(res_in.throughput, 1),
        "wire_vs_inprocess": round(wire_vs_inprocess, 3),
        "ingest_single_feed": ingest_single_feed,
        "ingest_probe_calls": probe["calls"],
        **res_wire.latency_quantiles(),
        "open_loop_rate_rps": round(open_rate, 1),
        "open_loop_ok": res_open.ok,
        "open_loop_shed": res_open.shed,
        "open_loop_expired": res_open.expired,
        "open_loop_reconciled": open_reconciled,
        **{f"open_loop_{k}": v
           for k, v in res_open.latency_quantiles().items()},
        "refusals": refusals,
        "refusals_hinted": refusals_hinted,
        "soak_connections": max(conns, 8),
        "soak_sessions_ok": soak["sessions_ok"],
        "soak_mismatches": soak["mismatches"],
        "soak_reconnects": soak["reconnects"],
        "soak_refusals": soak["refusals"],
        "soak_refusals_hinted": soak["refusals_hinted"],
        "soak_other_failures": soak["other_failures"],
        "edge_frames_total": snap.get("edge_frames_total", 0),
        "edge_connection_errors_total":
            snap.get("edge_connection_errors_total", 0),
        "platform": platform,
        "interpreted": interp,
        "repro": (f"python -m dcf_tpu.cli edge_bench "
                  f"--duration {float(args.duration):g} "
                  f"--max-batch {max_batch} --connections {conns} "
                  f"--seed {args.seed}"),
    }
    extra.update(_serve_pinned_ratio(res_wire.throughput, platform))
    unit = "evals/s (closed-loop served over TCP, party 0)"
    if interp:
        unit += " [no TPU this session: interpret/CPU mode, disclosed]"
    _emit("edge_bench", backend, "evals_per_sec",
          res_wire.throughput, unit, extra_fields=extra)

    # Emitted-then-asserted, chaos_bench style: the JSONL line
    # survives a failure, the exit code makes each claim falsifiable.
    failures = []
    if wire_vs_inprocess < 0.8:
        failures.append(
            f"wire path served {wire_vs_inprocess:.3f}x the in-process "
            "rate at the same shape (< 0.8: the zero-copy wire path is "
            "not holding)")
    if not ingest_single_feed:
        failures.append(
            f"ingest probe saw {probe['calls']} ingest_points calls "
            "for 8 requests — the bytes-ingest entry is not the only "
            "batcher feed")
    if soak["mismatches"]:
        failures.append(
            f"{soak['mismatches']} soak reconstructions mismatched the "
            "C++ anchor")
    if soak["sessions_ok"] < 8:
        failures.append(
            f"soak delivered only {soak['sessions_ok']} sessions")
    if soak["reconnects"] < 1:
        failures.append(
            "the injected edge.read fault never killed a connection — "
            "the soak did not exercise the failure path")
    if refusals < 1:
        failures.append("the refusal leg never saw a refusal")
    hinted_ok = (refusals_hinted == refusals and
                 soak["refusals_hinted"] == soak["refusals"])
    if not hinted_ok:
        failures.append(
            "a refusal reached a client WITHOUT a typed retry-after "
            f"hint (leg {refusals_hinted}/{refusals}, soak "
            f"{soak['refusals_hinted']}/{soak['refusals']})")
    if failures:
        raise SystemExit("edge_bench: " + "; ".join(failures))


def _protocols_pinned_ratio(m_int: int, rate: float,
                            baseline_path: str | None = None) -> dict:
    """vs_baseline for mic_bench: the pinned SINGLE-CORE NUMPY-ORACLE
    denominator (``benchmarks/cpu_baseline.json`` key
    ``protocols.mic_m{m}``, CPU_BASELINE.md protocol) — the honest
    "what would the obviously-correct host implementation serve"
    anchor, in served points/s at the same interval count.  Empty when
    no pin exists for this m (no silent in-run fallback).  The ratio is
    kept for XLA-CPU serving runs (both sides are CPU) with the
    platform disclosed in-line on the same JSONL line."""
    pinned = _load_pinned(baseline_path)
    if pinned is None:
        return {}
    entry = pinned.get("protocols", {}).get(f"mic_m{m_int}")
    if not entry:
        return {}
    return {"vs_baseline": round(rate / entry["points_per_sec"], 2),
            "baseline": f"pinned single-core numpy-oracle mic_m{m_int} "
                        f"({entry['points_per_sec']:,.0f} points/s, "
                        "CPU_BASELINE.md protocol)"}


def bench_mic(args) -> None:
    """Closed-loop MIC serving bench (ISSUE 5): m intervals x M points.

    Registers one m-interval MIC protocol bundle
    (``Dcf.mic`` — 2m interval-bound DCF keys K-packed into one
    bundle) in a ``DcfService`` and drives it with the same closed-loop
    generator as ``serve_bench``; the service applies the per-interval
    share combine server-side.  Parity is gated before timing: both
    parties served for a sample batch, XOR reconstruction vs the numpy
    protocol oracle (``protocols.oracle.mic_oracle``).  The JSONL line
    records served points/s (each served point yields all m interval
    rows), the staged ``MicEvaluator`` equivalent, and ``vs_baseline``
    against the pinned single-core numpy-oracle denominator.
    """
    from dcf_tpu import Dcf
    from dcf_tpu.protocols import MicEvaluator
    from dcf_tpu.protocols.oracle import mic_oracle
    from dcf_tpu.serve.loadgen import closed_loop

    lam, nb = 16, 16
    skew = _parse_skew(args.skew)  # shared --skew plumbing: validated
    # loudly here, before the bundle gen and warmup ladder (mic_bench
    # registers ONE protocol bundle, so a Zipf draw over one key is
    # uniform — the flag is still validated and recorded, keeping the
    # three serve benches' loadgen contracts identical)
    if args.backend not in ("numpy", "jax", "bitsliced", "pallas",
                            "prefix"):
        raise SystemExit(
            f"mic_bench serves lam=16 single-device facade backends "
            f"(numpy/jax/bitsliced/pallas/prefix), got {args.backend!r}")
    m_int = args.intervals or 8
    max_batch = args.max_batch or (1 << 14)
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    dcf = Dcf(nb, lam, ck, backend=args.backend)

    # m disjoint intervals: 2m sorted distinct bounds paired up (the
    # 128-bit domain makes collisions vanishingly unlikely; fail loudly
    # on a duplicate — an empty interval would silently skew the
    # workload, and the guard must survive `python -O`).
    bounds = sorted(
        int.from_bytes(rng.integers(0, 256, nb, dtype=np.uint8).tobytes(),
                       "big")
        for _ in range(2 * m_int))
    if len(set(bounds)) != 2 * m_int:
        raise SystemExit(
            "mic_bench drew duplicate interval bounds; rerun with a "
            "different --seed")
    intervals = [(bounds[2 * i], bounds[2 * i + 1]) for i in range(m_int)]
    betas = rng.integers(0, 256, (m_int, lam), dtype=np.uint8)
    log(f"gen MIC bundle: {m_int} intervals -> {2 * m_int} K-packed keys")
    pb = dcf.mic(intervals, betas, rng=rng)

    svc = dcf.serve(max_batch=max_batch, max_delay_ms=args.max_delay_ms,
                    device_bytes_budget=args.device_bytes_budget)
    svc.register_key("mic-0", pb)

    # Parity gate: both parties through the SERVICE, vs the oracle.
    xs_check = rng.integers(0, 256, (256, nb), dtype=np.uint8)
    f0 = svc.submit("mic-0", xs_check, b=0)
    f1 = svc.submit("mic-0", xs_check, b=1)
    svc.pump()
    want = mic_oracle(xs_check, intervals, betas)
    if not np.array_equal(f0.result() ^ f1.result(), want):
        raise SystemExit("mic_bench parity mismatch vs the numpy oracle")
    log(f"parity vs numpy oracle: OK ({m_int} intervals x 256 pts, "
        "two-party, served)")

    min_req = args.min_req_points or (max_batch * 3 // 8)
    max_req = args.max_req_points or (max_batch // 2)
    if not 1 <= min_req <= max_req:
        raise SystemExit(f"bad request-size range [{min_req}, {max_req}]")

    # Warm the padded-batch compile ladder (same rule as serve_bench).
    from dcf_tpu.serve.batcher import next_pow2

    xs_warm = rng.integers(0, 256, (max_batch, nb), dtype=np.uint8)
    mm = next_pow2(min_req)
    while mm <= max_batch:
        log(f"warming batch shape {mm} ...")
        svc.submit("mic-0", xs_warm[:mm])
        svc.pump()
        mm *= 2

    import jax

    platform = jax.devices()[0].platform
    interp = (platform != "tpu"
              or bool(getattr(dcf.eval_backend(0), "interpret", False)))
    with svc:
        res = closed_loop(
            svc, ["mic-0"], duration_s=float(args.duration),
            concurrency=args.concurrency,
            min_points=min_req, max_points=max_req, seed=args.seed,
            skew=skew)
    snap = svc.metrics_snapshot()

    # Staged equivalent: the MicEvaluator path (stage + eval_staged +
    # on-device pair-combine + conversion) on one max_batch batch.
    ev = MicEvaluator(dcf, pb, 0)
    ev.eval(xs_warm)  # warm the EXACT timed shape (same rule as
    # serve_bench: a first-sample compile would skew the staged rate)
    dt, mad, ss = _timed(lambda: ev.eval(xs_warm), args.reps)
    staged_rate = max_batch / dt
    log(f"staged MicEvaluator rate at {max_batch} pts: "
        f"{staged_rate:,.1f} points/s (median {dt * 1e3:.1f} ms +- "
        f"{mad * 1e3:.1f} ms, {len(ss)} samples)")

    extra = {
        "duration_s": round(res.duration_s, 3),
        "concurrency": args.concurrency,
        "skew": skew,
        "intervals": m_int,
        "max_batch": max_batch,
        "req_points": [min_req, max_req],
        "requests_ok": res.requests_ok,
        "requests_shed": res.requests_shed,
        "requests_failed": res.requests_failed,
        **res.latency_quantiles(),
        "platform": platform,
        "interpreted": interp,
        "staged_mic_points_per_sec": round(staged_rate, 1),
        "serve_vs_staged": round(res.throughput / staged_rate, 3),
        "metrics_snapshot": snap,
        **_protocols_pinned_ratio(m_int, res.throughput),
    }
    unit = (f"points/s (closed-loop served MIC, m={m_int}, party 0; "
            "each point yields all m interval rows)")
    if interp:
        unit += " [no TPU this session: interpret/CPU mode, disclosed]"
    _emit("mic_bench", args.backend, "points_per_sec",
          res.throughput, unit, extra_fields=extra)


def _gates_pinned_ratio(tag: str, rate: float,
                        baseline_path: str | None = None) -> dict:
    """vs_baseline for gate_bench: the pinned SINGLE-CORE NUMPY
    GATE-ORACLE denominator (``benchmarks/cpu_baseline.json`` key
    ``gates.<tag>``, CPU_BASELINE.md protocol) — what the
    obviously-correct host implementation computes for the same gate on
    the clear input.  Empty when no pin exists for this tag (no silent
    in-run fallback); the ratio is kept for XLA-CPU runs with the
    platform disclosed on the same JSONL line (mic_bench precedent)."""
    pinned = _load_pinned(baseline_path)
    if pinned is None:
        return {}
    entry = pinned.get("gates", {}).get(tag)
    if not entry:
        return {}
    # 6 decimals: the clear-input oracle does no crypto at all, so the
    # served-interpret ratio is honestly tiny (~1e-4) — 2 decimals
    # would round the disclosure to a meaningless 0.0.
    return {"vs_baseline": round(rate / entry["points_per_sec"], 6),
            "baseline": f"pinned single-core numpy gate oracle "
                        f"gates.{tag} "
                        f"({entry['points_per_sec']:,.0f} points/s, "
                        "CPU_BASELINE.md protocol)"}


def bench_gates(args) -> None:
    """Served fixed-point gate bench (ISSUE 20): spline sigmoid +
    faithful truncation + signed comparison through ``GateServer``.

    Dealer-side: one gate of each kind on the 16-bit fixed-point
    domain (f=8 fractional bits, ``add16`` output group) with fresh
    input masks; their component interval bundles register in a
    ``DcfService`` pair (full domain + the truncation gate's low-byte
    domain).  Parity is gated BEFORE timing: every gate reconstructs
    bit-exactly against its clear-input numpy oracle
    (``protocols.fixedpoint``) on a served two-party sample.  The
    timed legs measure party 0's SERVED share rate per gate — submit,
    service combine, client-side gate fold — on one fixed batch; the
    sigmoid rate is the headline ``value`` (it is the deepest
    composition: m-piece MIC + group reduce), truncation and sign ride
    as fields on the same line, each with its ``vs_baseline`` against
    the pinned single-core numpy gate oracle when a pin exists.
    """
    from dcf_tpu import Dcf
    from dcf_tpu.protocols import (
        gen_sigmoid_gate, gen_sign_gate, gen_trunc_gate,
        sigmoid_fixed_oracle, sign_oracle, trunc_oracle)
    from dcf_tpu.protocols.fixedpoint import decode_lanes
    from dcf_tpu.workloads import GateServer

    lam, nb, f_bits, group = 16, 2, 8, "add16"
    if args.backend not in ("numpy", "jax", "bitsliced", "pallas",
                            "prefix"):
        raise SystemExit(
            f"gate_bench serves lam=16 single-device facade backends "
            f"(numpy/jax/bitsliced/pallas/prefix), got {args.backend!r}")
    m_pieces = args.intervals or 8
    points = args.points or 4096
    n_total = 1 << (8 * nb)
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    dcf = Dcf(nb, lam, ck, backend=args.backend)
    dcf_low = Dcf(1, lam, ck, backend=args.backend)

    r_sig = int(rng.integers(0, n_total))
    r_tr = int(rng.integers(0, n_total))
    r_sgn = int(rng.integers(0, n_total))
    log(f"gen gates: sigmoid m={m_pieces} f={f_bits}, trunc f={f_bits}, "
        f"sign — {group} group, {8 * nb}-bit domain")
    sig = gen_sigmoid_gate(dcf, r_sig, rng, group, f=f_bits, m=m_pieces)
    tr = gen_trunc_gate(dcf, dcf_low, r_tr, f_bits, rng, group)
    sgn = gen_sign_gate(dcf, r_sgn, rng, group)

    max_batch = args.max_batch or (1 << 14)
    svc = dcf.serve(max_batch=max_batch, max_delay_ms=args.max_delay_ms,
                    device_bytes_budget=args.device_bytes_budget)
    svc_low = dcf_low.serve(max_batch=max_batch,
                            max_delay_ms=args.max_delay_ms)
    gs = GateServer(svc, svc_low)
    gs.register("sigmoid", sig)
    gs.register("trunc", tr)
    gs.register("sign", sgn)

    import jax

    platform = jax.devices()[0].platform
    interp = (platform != "tpu"
              or bool(getattr(dcf.eval_backend(0), "interpret", False)))
    with svc, svc_low:
        # Parity gates: two-party SERVED reconstruction vs the clear
        # oracles, before any timing.
        x_check = rng.integers(0, n_total, size=512, dtype=np.int64)
        got = decode_lanes(gs.reconstruct("sigmoid", x_check), group)
        want = sigmoid_fixed_oracle((x_check - r_sig) % n_total,
                                    sig.cuts, sig.values)
        if not np.array_equal(got, want):
            raise SystemExit(
                "gate_bench sigmoid parity mismatch vs the numpy oracle")
        got = decode_lanes(gs.reconstruct("trunc", x_check), group)
        if not np.array_equal(got, trunc_oracle(x_check, r_tr, f_bits,
                                                8 * nb)):
            raise SystemExit(
                "gate_bench trunc parity mismatch vs the numpy oracle")
        got = decode_lanes(gs.reconstruct("sign", x_check), group)
        if not np.array_equal(got, sign_oracle((x_check - r_sgn)
                                               % n_total, 8 * nb)):
            raise SystemExit(
                "gate_bench sign parity mismatch vs the numpy oracle")
        log("parity vs numpy gate oracles: OK (3 gates x 512 pts, "
            "two-party, served)")

        x_bench = rng.integers(0, n_total, size=points, dtype=np.int64)
        rates = {}
        meds = {}
        for gate_id in ("sigmoid", "trunc", "sign"):
            gs.eval_share(gate_id, 0, x_bench)  # warm the timed shape
            dt, mad, ss = _timed(
                lambda g=gate_id: gs.eval_share(g, 0, x_bench),
                args.reps)
            rates[gate_id] = points / dt
            meds[gate_id] = (dt, mad, len(ss))
            log(f"served {gate_id} gate: {rates[gate_id]:,.1f} points/s "
                f"(median {dt * 1e3:.1f} ms +- {mad * 1e3:.1f} ms, "
                f"{len(ss)} samples)")
    snap = svc.metrics_snapshot()

    extra = {
        "points": points,
        "pieces": m_pieces,
        "frac_bits": f_bits,
        "group": group,
        "domain_bits": 8 * nb,
        "max_batch": max_batch,
        "trunc_points_per_sec": round(rates["trunc"], 1),
        "sign_points_per_sec": round(rates["sign"], 1),
        "platform": platform,
        "interpreted": interp,
        "metrics_snapshot": snap,
        "repro": (f"python -m dcf_tpu.cli gate_bench --backend pallas "
                  f"--points {points} --intervals {m_pieces} "
                  f"--seed {args.seed}"),
        **_gates_pinned_ratio(f"sigmoid_m{m_pieces}", rates["sigmoid"]),
    }
    tr_pin = _gates_pinned_ratio("trunc", rates["trunc"])
    if tr_pin:
        extra["trunc_vs_baseline"] = tr_pin["vs_baseline"]
    unit = (f"points/s (served spline-sigmoid gate, party 0 share, "
            f"m={m_pieces} pieces, f={f_bits}, {group})")
    if interp:
        unit += " [no TPU this session: interpret/CPU mode, disclosed]"
    dt, mad, n_samples = meds["sigmoid"]
    _emit("gate_bench", args.backend, "points_per_sec",
          rates["sigmoid"], unit, med_s=dt, mad_s=mad,
          samples=n_samples, extra_fields=extra)


def bench_keygen(args) -> None:
    """On-device K-packed keygen bench (ISSUE 10): closed-loop keys/s.

    For each lam in {128, 256} (or the single ``--lam``), generates
    fresh key batches back-to-back through ``gen.gen_on_device`` — the
    Pallas narrow keygen kernel + affine wide tail, the same level-walk
    core the eval kernels use — at K in {1, 8, 64, 2m} (the last leg is
    the MIC packing: ``gen_interval_bundle`` with m = ``--intervals``
    intervals through ``Dcf.mic(..., device=True)``).  Before timing,
    the two-party reconstruction GATE must pass: a device-generated
    bundle is evaluated by both parties on the host oracle, including
    the exact boundary x = alpha, and reconstructed against the
    comparison function; any mismatch exits non-zero.  The JSONL line
    records every leg, the host ``gen_batch`` companion rate at the
    pinned K, and ``vs_baseline`` against the pinned single-core numpy
    keygen denominator (CPU_BASELINE.md).  Off TPU the kernel runs in
    interpret mode — disclosed in-line; the committed one-command chip
    repro is the ``repro`` field.
    """
    from dcf_tpu import Dcf
    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.gen import (
        device_fallback_count,
        gen_batch,
        gen_on_device,
        random_s0s,
    )
    from dcf_tpu.ops.prg import HirosePrgNp

    nb = 16  # flagship domain: n = 128 walked levels per key
    lams = [args.lam] if args.lam else [128, 256]
    for lam in lams:
        if lam < 48 or lam % 16:
            raise SystemExit(
                f"keygen_bench drives the hybrid-family device keygen "
                f"(lam >= 48, a multiple of 16), got --lam={lam}")
    m_int = args.intervals or 8
    import jax

    platform = jax.devices()[0].platform
    interp = platform != "tpu"
    pinned_k = 64  # the CPU_BASELINE.md keygen pin shape

    for lam in lams:
        # A dead device path would silently fall back to host gen_batch
        # (the SERVING contract) — but then the gate compares host bytes
        # to host bytes and every timed leg publishes host rates labeled
        # "device keygen".  The bench's claims are about the device
        # path, so any fallback during the run fails it non-zero, with
        # the count on the emitted line.
        fallbacks_before = device_fallback_count()
        rng = np.random.default_rng(args.seed)
        ck = _cipher_keys(lam, rng)
        import warnings as _warnings

        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            prg = HirosePrgNp(lam, ck)

        # -- reconstruction gate (before any timing) --------------------
        k_gate = 4
        alphas = rng.integers(0, 256, (k_gate, nb), dtype=np.uint8)
        betas = rng.integers(0, 256, (k_gate, lam), dtype=np.uint8)
        s0s = random_s0s(k_gate, lam, rng)
        dev_bundle = gen_on_device(lam, ck, alphas, betas, s0s,
                                   Bound.LT_BETA)
        host_bundle = gen_batch(prg, alphas, betas, s0s, Bound.LT_BETA)
        if dev_bundle.to_bytes() != host_bundle.to_bytes():
            raise SystemExit(
                f"keygen_bench gate: device keys at lam={lam} are not "
                "byte-identical to the host gen_batch")
        xs = rng.integers(0, 256, (8, nb), dtype=np.uint8)
        xs[0] = alphas[0]  # exact boundary
        y0 = eval_batch_np(prg, 0, dev_bundle.for_party(0), xs)
        y1 = eval_batch_np(prg, 1, dev_bundle.for_party(1), xs)
        recon = y0 ^ y1
        for i in range(k_gate):
            a = alphas[i].tobytes()
            for j in range(xs.shape[0]):
                want = (betas[i].tobytes() if xs[j].tobytes() < a
                        else bytes(lam))
                if recon[i, j].tobytes() != want:
                    raise SystemExit(
                        f"keygen_bench gate: two-party reconstruction "
                        f"mismatch at lam={lam}, key {i}, point {j}")
        log(f"gate: device keys byte-identical to gen_batch AND "
            f"two-party reconstruction OK (lam={lam}, {k_gate} keys x "
            f"{xs.shape[0]} pts incl. x=alpha)")

        # -- closed-loop legs ------------------------------------------
        # Every timed call generates DIFFERENT keys (fresh alphas/betas/
        # seeds, pre-drawn off the clock): production keygen never
        # repeats inputs, and timing a repeated-input loop would let any
        # input-keyed caching — in the generator, jit, or a future
        # optimization — quietly hollow out the measurement.
        k_sweep = ([args.keys] if args.keys
                   else [1, 8, pinned_k, 2 * m_int])
        legs = []
        for k_num in k_sweep:
            pool = [(rng.integers(0, 256, (k_num, nb), dtype=np.uint8),
                     rng.integers(0, 256, (k_num, lam), dtype=np.uint8),
                     random_s0s(k_num, lam, rng))
                    for _ in range(max(args.reps, 1) + 1)]
            it = iter(pool)

            def one_gen():
                al, be, ss = next(it)
                gen_on_device(lam, ck, al, be, ss, Bound.LT_BETA)

            one_gen()  # warm the compiled shapes
            med, mad, samples = _timed(one_gen, args.reps, args.profile)
            rate = k_num / med
            legs.append({"keys": k_num,
                         "keys_per_sec": round(rate, 1),
                         "median_s": round(med, 6),
                         "mad_s": round(mad, 6),
                         "samples": len(samples)})
            log(f"keygen lam={lam} K={k_num}: {rate:,.1f} keys/s "
                f"(median {med * 1e3:.1f} ms +- {mad * 1e3:.1f} ms)")

        # -- the MIC 2m packing leg through the facade ------------------
        dcf = Dcf(nb, lam, ck, backend="numpy")
        bounds = sorted(
            int.from_bytes(
                rng.integers(0, 256, nb, dtype=np.uint8).tobytes(),
                "big")
            for _ in range(2 * m_int))
        intervals = [(bounds[2 * i], bounds[2 * i + 1])
                     for i in range(m_int)]
        mic_betas = rng.integers(0, 256, (m_int, lam), dtype=np.uint8)
        seeds = iter(range(max(args.reps, 1) + 1))

        def one_mic():  # fresh seeds per bundle — same rule as above
            dcf.mic(intervals, mic_betas,
                    rng=np.random.default_rng(next(seeds)), device=True)

        one_mic()  # warm
        med, mad, samples = _timed(one_mic, args.reps, args.profile)
        mic_rate = 2 * m_int / med
        log(f"keygen lam={lam} MIC m={m_int} (K=2m={2 * m_int}): "
            f"{mic_rate:,.1f} keys/s (median {med * 1e3:.1f} ms)")

        # -- host companion at the pinned K (same-session context) ------
        al = rng.integers(0, 256, (pinned_k, nb), dtype=np.uint8)
        be = rng.integers(0, 256, (pinned_k, lam), dtype=np.uint8)
        ss = random_s0s(pinned_k, lam, rng)
        gen_batch(prg, al, be, ss, Bound.LT_BETA)  # warm
        hmed, _hm, _hs = _timed(
            lambda: gen_batch(prg, al, be, ss, Bound.LT_BETA), args.reps)
        host_rate = pinned_k / hmed

        pin_leg = next((leg for leg in legs
                        if leg["keys"] == pinned_k), None)
        head = pin_leg or legs[-1]  # headline = the pinned K shape
        fallbacks = device_fallback_count() - fallbacks_before
        extra = {
            "lam": lam,
            "n_bytes": nb,
            "device_fallbacks": fallbacks,
            "legs": legs,
            "mic_intervals": m_int,
            "mic_keys_per_sec": round(mic_rate, 1),
            "host_gen_batch_keys_per_sec": round(host_rate, 1),
            "platform": platform,
            "interpreted": interp,
            "repro": (f"python -m dcf_tpu.cli keygen_bench --lam {lam} "
                      f"--seed {args.seed}"),
            **(_pinned_ratio(nb, pinned_k, pin_leg["keys_per_sec"],
                             interpreted=interp, lam=lam, keygen=True)
               if pin_leg else {}),
        }
        unit = (f"keys/s (closed-loop device keygen, K={head['keys']}, "
                f"N={nb}B domain)")
        if interp:
            unit += (" [no TPU this session: Pallas interpret mode, "
                     "disclosed; see repro]")
        _emit("keygen_bench", "device", "keys_per_sec",
              head["keys_per_sec"], unit, extra_fields=extra)
        if fallbacks:
            raise SystemExit(
                f"keygen_bench: {fallbacks} device-keygen call(s) fell "
                "back to the host walk (see warnings) — the emitted "
                "rates are NOT device rates; fix the device path or "
                "bench the host explicitly")


def _dpf_pinned_ratio(n_bits: int, rate: float,
                      interpreted: bool = False,
                      baseline_path: str | None = None) -> dict:
    """vs_baseline for pir_bench: the pinned SINGLE-CORE NUMPY EvalAll
    denominator (``benchmarks/cpu_baseline.json`` key
    ``dpf.evalall_n16``, CPU_BASELINE.md protocol) — one numpy
    full-domain expansion is one query's dominant cost, and the numpy
    walk is the portable floor every deployment has (the keygen-pin
    rationale).  The pin is at n=16 and RESCALED by 2^16 / 2^n for the
    bench's other domains (EvalAll cost is linear in leaf count); the
    rescale and the pin's one-party scope are disclosed in the baseline
    string.  Empty when no pin exists (no silent in-run fallback).
    Like the keygen pins the ratio is KEPT for interpreted runs — the
    acceptance gate wants the number on the line — but annotated as an
    interpret-mode numerator, never a chip claim."""
    pinned = _load_pinned(baseline_path)
    if pinned is None:
        return {}
    entry = pinned.get("dpf", {}).get("evalall_n16")
    if not entry:
        return {}
    denom = entry["queries_per_sec"] * (1 << 16) / (1 << n_bits)
    note = ("; interpret-mode numerator (no TPU this session) — "
            "run the committed repro on a chip for a real ratio"
            if interpreted else "")
    scale = (f" rescaled x 2^16/2^{n_bits} -> {denom:,.3f}"
             if n_bits != 16 else "")
    return {"vs_baseline": round(rate / denom, 2),
            "baseline": f"pinned single-core numpy EvalAll "
                        f"dpf.evalall_n16 "
                        f"({entry['queries_per_sec']:,.3f} queries/s, "
                        f"one party{scale}, "
                        f"CPU_BASELINE.md protocol{note})"}


def bench_pir(args) -> None:
    """2-server PIR serving bench (ISSUE 19): closed-loop queries/s.

    For each domain n in {14, 16, 18} (or the single ``--n-bits``):
    pack a fresh 2^n x 32 B database resident on device
    (``workloads.pir.PirDatabase``), stand up a ``PirServer`` over a
    ``KeyRegistry``, and serve both parties' answers per query batch —
    each answer is a full-domain DPF EvalAll (the Pallas kernel) plus
    the GF(2) selection-vector inner product, which is the whole point:
    every PIR query touches every record.  Before any timing the
    reconstruction GATE must pass: probed records (including the first
    and last) retrieved through the SERVED path must reconstruct
    bit-exactly against the plaintext database — the retrieval oracle;
    any mismatch exits non-zero.  Timed legs are closed-loop with a
    FRESH pre-registered query bundle per call (fresh alphas/seeds,
    registration off the clock): repeating a key would let the
    server's per-key selection cache hollow out the measurement.  The
    JSONL line records every leg and ``vs_baseline`` against the
    pinned single-core numpy EvalAll denominator (``dpf.evalall_n16``,
    CPU_BASELINE.md), rescaled by leaf count for n != 16.  Off TPU the
    kernel runs in interpret mode — disclosed in-line; the committed
    one-command chip repro is the ``repro`` field.  n=14 and n=18
    exercise the non-byte-granular database domains (prefix-depth
    evaluation of a byte-granular key; ``pir_query_bundle``).
    """
    ns = [args.n_bits] if args.n_bits else [14, 16, 18]
    for n in ns:
        if not 5 <= n <= 24:
            raise SystemExit(
                f"pir_bench serves 5 <= n <= 24 bit database domains "
                f"(one lane word to 16M records), got --n-bits={n}")
    if args.keys < 0:
        raise SystemExit(
            f"pir_bench --keys is the queries-per-batch count "
            f"(0 = 4), got {args.keys}")
    from dcf_tpu.backends.evalall import DpfEvalAll
    from dcf_tpu.gen import random_s0s
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.serve.registry import KeyRegistry
    from dcf_tpu.workloads.pir import (
        PirDatabase,
        PirServer,
        pir_query_bundle,
        pir_reconstruct,
    )

    lam = 32  # DPF_DEVICE_LAM: the two-block narrow kernel width
    record_bytes = 32
    k_num = args.keys or 4
    import jax

    platform = jax.devices()[0].platform
    interp = platform != "tpu"
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")
        prg = HirosePrgNp(lam, ck)
        evaluator = DpfEvalAll(lam, ck, interpret=interp)

    legs = []
    for n in ns:
        records = rng.integers(0, 256, (1 << n, record_bytes),
                               dtype=np.uint8)
        db = PirDatabase(records, n)
        registry = KeyRegistry(None)
        server = PirServer(evaluator, db, registry)

        # -- reconstruction gate (before any timing) --------------------
        gate_idx = [0, (1 << n) - 1] + [
            int(x) for x in rng.integers(0, 1 << n, 4)]
        registry.register("gate", pir_query_bundle(
            prg, gate_idx, n, random_s0s(len(gate_idx), lam, rng)))
        got = pir_reconstruct(server.answer("gate", 0),
                              server.answer("gate", 1))
        for j, i in enumerate(gate_idx):
            if got[j].tobytes() != records[i].tobytes():
                raise SystemExit(
                    f"pir_bench gate: record {i} of the 2^{n} database "
                    "did not reconstruct bit-exactly through the "
                    "served path")
        log(f"gate: {len(gate_idx)} records (incl. first/last) "
            f"retrieved bit-exactly through the served path (n={n})")

        # -- closed-loop timed leg --------------------------------------
        kids = []
        for q in range(max(args.reps, 1) + 1):
            kid = f"q{n}-{q}"
            registry.register(kid, pir_query_bundle(
                prg, rng.integers(0, 1 << n, k_num), n,
                random_s0s(k_num, lam, rng)))
            kids.append(kid)
        it = iter(kids)

        def one_batch():
            kid = next(it)
            pir_reconstruct(server.answer(kid, 0), server.answer(kid, 1))

        one_batch()  # warm the compiled shapes
        med, mad, samples = _timed(one_batch, args.reps, args.profile)
        rate = k_num / med
        legs.append({"n_bits": n,
                     "queries_per_sec": round(rate, 3),
                     "median_s": round(med, 6),
                     "mad_s": round(mad, 6),
                     "samples": len(samples),
                     "eval_faults": server.eval_faults,
                     **_dpf_pinned_ratio(n, rate, interpreted=interp)})
        log(f"pir n={n} K={k_num}: {rate:,.3f} queries/s "
            f"(median {med * 1e3:.1f} ms +- {mad * 1e3:.1f} ms, "
            "both parties served)")

    head = next((leg for leg in legs if leg["n_bits"] == 16), legs[-1])
    extra = {
        "lam": lam,
        "record_bytes": record_bytes,
        "keys": k_num,
        # _emit rounds "value" to 1 decimal; interpret-mode queries/s
        # can live below that, so the floor (FLOORS.json) pins this
        # 3-decimal copy of the headline instead.
        "queries_per_sec": head["queries_per_sec"],
        "legs": legs,
        "platform": platform,
        "interpreted": interp,
        "repro": (f"python -m dcf_tpu.cli pir_bench "
                  f"--seed {args.seed}"),
        **{k: v for k, v in head.items()
           if k in ("vs_baseline", "baseline")},
    }
    unit = (f"queries/s (closed-loop 2-server PIR, both parties "
            f"served, 2^{head['n_bits']} x {record_bytes}B records)")
    if interp:
        unit += (" [no TPU this session: Pallas interpret mode, "
                 "disclosed; see repro]")
    _emit("pir_bench", "device", "queries_per_sec",
          head["queries_per_sec"], unit, extra_fields=extra)


def bench_keyfactory(args) -> None:
    """Key-factory provisioning bench (ISSUE 11): does ahead-of-demand
    pooling actually take keygen off the registration clock?

    Shape: the flagship N=16-byte domain at ``--lam`` (default 128 —
    the pinned keygen-baseline shape), a single-key-per-session plain
    pool refilled in ``--keys``-session device batches (default 64, the
    CPU_BASELINE.md keygen pin's K).  The serving backend is the host
    path (default ``numpy``; the bench measures PROVISIONING, not eval
    throughput — ``serve_bench`` owns that).  Four phases:

    1. **Parity gates** — a pool-hit key AND a pool-exhaustion
       fallback key (the miss counter pinned to prove which path ran)
       each serve a bit-exact two-party reconstruction through the
       service, including x = alpha.  Exit != 0 on any mismatch.
    2. **Sustained publish-to-servable** — repeated full refills of a
       durable pool (mint K-packed on device + batched atomic manifest
       flip + pooled), median keys/s across ``--reps`` fills, with
       ``vs_baseline`` against the pinned single-core numpy keygen
       denominator (``keygen.lam*``).  Any device→host keygen fallback
       during the timed fills fails the run non-zero (host rates must
       not publish labeled "device").
    3. **Registration latency** — median ``register_key(pool=...)``
       latency with a warm pool (pool HIT: a pop) vs a deliberately
       empty, never-refilled pool (the synchronous-mint fallback path)
       at the same (lam, K=1) session shape.  The line records both
       and ``pool_hit_speedup``; the run FAILS unless the pool hit is
       >= 10x faster — the acceptance claim, falsifiable in one
       command.
    4. **Session churn** — ``serve.loadgen.session_churn`` drives the
       started service + refill worker with fresh-key-per-session
       traffic (register -> evaluate both parties -> unregister) for
       ``--duration`` seconds; the line records sessions/s, the
       under-churn registration quantiles and the pool hit rate.

    Off TPU the device refills run the Pallas interpreter — disclosed
    in-line; the committed one-command chip repro is the ``repro``
    field.  ``--host-refill`` routes refills through the host pipeline
    instead (an explicit host measurement, not a silent fallback).
    """
    import shutil
    import tempfile

    from dcf_tpu import Dcf
    from dcf_tpu.gen import device_fallback_count
    from dcf_tpu.serve import PoolSpec
    from dcf_tpu.serve.loadgen import session_churn
    from dcf_tpu.utils.benchtime import monotonic

    nb = 16
    lam = args.lam or 128
    if lam < 16:
        raise SystemExit(
            f"keyfactory_bench wants lam >= 16, got --lam={lam}")
    backend = args.backend
    if backend == "cpu":
        # The global argparse default; the bench measures provisioning
        # through the host serve path — route to numpy unless the user
        # chose a backend explicitly.
        backend = "numpy"
        log("keyfactory_bench measures provisioning; defaulting "
            "--backend to numpy (the host serve path)")
    if backend not in ("numpy", "bitsliced", "jax", "hybrid"):
        raise SystemExit(
            "keyfactory_bench serves through numpy/bitsliced/jax/"
            f"hybrid, got {backend!r}")
    if backend == "hybrid" and (lam < 48 or lam % 16):
        raise SystemExit(
            f"--backend=hybrid wants lam >= 48, a multiple of 16 "
            f"(got {lam})")
    refill_batch = args.keys or 64
    use_device = not args.host_refill
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")
        dcf = Dcf(nb, lam, ck, backend=backend)
    import jax

    platform = jax.devices()[0].platform
    interp = platform != "tpu"
    store_dir = args.store_dir or tempfile.mkdtemp(prefix="dcf-kf-")
    cleanup = not args.store_dir
    try:
        svc = dcf.serve(max_batch=256, store_dir=store_dir)
        alphas = rng.integers(0, 256, (1, nb), dtype=np.uint8)
        betas = rng.integers(1, 256, (1, lam), dtype=np.uint8)

        def pool(name, **kw):
            base = dict(name=name, alphas=alphas, betas=betas,
                        device=use_device)
            return svc.add_pool(PoolSpec(**{**base, **kw}))

        # -- phase 1: parity gates (before any timing) ------------------
        pool("gate", target_depth=2, low_water=2, refill_batch=2)
        svc.keyfactory.pump()

        def gate(key_id, tag):
            xs = rng.integers(0, 256, (8, nb), dtype=np.uint8)
            xs[0] = alphas[0]  # exact boundary
            f0 = svc.submit(key_id, xs, b=0)
            f1 = svc.submit(key_id, xs, b=1)
            svc.pump()
            recon = f0.result() ^ f1.result()
            a = alphas[0].tobytes()
            for j in range(xs.shape[0]):
                want = (betas[0].tobytes() if xs[j].tobytes() < a
                        else bytes(lam))
                if recon[0, j].tobytes() != want:
                    raise SystemExit(
                        f"keyfactory_bench gate: two-party "
                        f"reconstruction mismatch on the {tag} path "
                        f"(lam={lam}, point {j})")

        snap0 = svc.metrics_snapshot()
        svc.register_key("gate-hit", pool="gate")
        gate("gate-hit", "pool-hit")
        while svc.keyfactory.depth("gate"):
            svc.register_key("gate-drain", pool="gate")
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            svc.register_key("gate-miss", pool="gate")  # exhausted
        gate("gate-miss", "sync-fallback")
        snap1 = svc.metrics_snapshot()
        miss_delta = (snap1["keyfactory_pool_misses_total"]
                      - snap0.get("keyfactory_pool_misses_total", 0))
        if miss_delta != 1:
            raise SystemExit(
                "keyfactory_bench gate: the fallback leg recorded "
                f"{miss_delta} pool misses (want exactly 1) — the "
                "parity claim must name the path that served it")
        log(f"gate: pool-hit AND sync-fallback keys reconstruct "
            f"bit-exactly (lam={lam}, x=alpha included; fallback "
            f"counted)")

        # -- phase 2: sustained publish-to-servable ---------------------
        pool("supply", target_depth=refill_batch,
             low_water=refill_batch, refill_batch=refill_batch)
        svc.keyfactory.pump()  # warm the compiled keygen shapes
        fallbacks_mid = device_fallback_count()
        fill_rates = []
        for _ in range(max(args.reps, 1)):
            while svc.keyfactory.depth("supply"):  # drain: all hits
                svc.register_key("supply-drain", pool="supply")
            # Flush the drained claims' reclaim flip OUTSIDE the timed
            # region: the line claims the PUBLISH rate (mint + ONE
            # manifest flip), and the spent reclaim is a separate flip
            # that normally amortizes across worker sweeps.
            svc.keyfactory.reclaim_spent()
            t0 = monotonic()
            svc.keyfactory.pump()  # mint + publish (one manifest flip)
            dt = monotonic() - t0
            fill_rates.append(refill_batch / dt)
        keys_per_sec = float(np.median(fill_rates))
        refill_fallbacks = device_fallback_count() - fallbacks_mid
        log(f"publish-to-servable: {keys_per_sec:,.1f} keys/s sustained "
            f"(K={refill_batch} per batch, {len(fill_rates)} fills, "
            f"durable batched manifest flips)")

        # -- phase 3: registration latency, hit vs sync -----------------
        # low_water > 0 matters only once the refill worker runs (the
        # churn phase); the latency legs below pump nothing, so the
        # hit-leg pool depth stays exactly what this fill leaves.
        hit_n = min(100, refill_batch * 2)
        pool("sess", target_depth=max(hit_n, refill_batch),
             low_water=max(refill_batch // 2, 1),
             refill_batch=refill_batch)
        while svc.keyfactory.depth("sess") < hit_n:
            svc.keyfactory.pump()
        hit_lat = []
        for i in range(hit_n):
            t0 = monotonic()
            svc.register_key(f"lat-{i}", pool="sess")
            hit_lat.append(monotonic() - t0)
            svc.unregister_key(f"lat-{i}")
        pool("never-filled", target_depth=1, low_water=0,
             refill_batch=1)
        sync_n = max(args.reps * 4, 12)
        sync_lat = []
        with _warnings.catch_warnings():
            _warnings.simplefilter("ignore")
            for i in range(sync_n):
                t0 = monotonic()
                svc.register_key(f"sync-{i}", pool="never-filled")
                sync_lat.append(monotonic() - t0)
                svc.unregister_key(f"sync-{i}")
        hit_med = float(np.median(hit_lat))
        sync_med = float(np.median(sync_lat))
        speedup = sync_med / max(hit_med, 1e-9)
        log(f"registration latency: pool hit {hit_med * 1e6:,.1f} us "
            f"vs synchronous keygen {sync_med * 1e6:,.1f} us "
            f"({speedup:,.1f}x) at the same (lam={lam}, K=1) shape")

        # -- phase 4: session churn -------------------------------------
        churn = None
        if args.duration > 0:
            with svc:  # worker + refill worker
                churn = session_churn(
                    svc, pool="sess", duration_s=float(args.duration),
                    concurrency=args.concurrency,
                    min_points=args.min_req_points or 8,
                    max_points=args.max_req_points or 64,
                    seed=args.seed)
            log(f"churn: {churn.sessions_ok} sessions in "
                f"{churn.duration_s:.1f}s "
                f"({churn.sessions_per_sec:,.1f} sessions/s, "
                f"{churn.sessions_failed} failed)")
        snap = svc.metrics_snapshot()
        hits = snap.get("keyfactory_pool_hits_total", 0)
        misses = snap.get("keyfactory_pool_misses_total", 0)

        extra = {
            "lam": lam,
            "n_bytes": nb,
            "refill_batch": refill_batch,
            "device_refill": use_device,
            "device_fallbacks": refill_fallbacks,
            "fills": len(fill_rates),
            "pool_hit_register_s": round(hit_med, 9),
            "sync_register_s": round(sync_med, 9),
            "pool_hit_speedup": round(speedup, 1),
            "pool_hits": hits,
            "pool_misses": misses,
            "pool_hit_rate": round(hits / max(hits + misses, 1), 4),
            "store_writes": snap.get("serve_store_writes_total", 0),
            "platform": platform,
            "interpreted": interp and use_device,
            "repro": (f"python -m dcf_tpu.cli keyfactory_bench "
                      f"--lam {lam} --keys {refill_batch} "
                      f"--seed {args.seed}"),
            **_pinned_ratio(nb, refill_batch, keys_per_sec,
                            interpreted=interp and use_device, lam=lam,
                            keygen=True),
        }
        if churn is not None:
            extra.update({
                "churn_duration_s": round(churn.duration_s, 3),
                "churn_concurrency": args.concurrency,
                "churn_sessions_ok": churn.sessions_ok,
                "churn_sessions_failed": churn.sessions_failed,
                "churn_sessions_per_sec":
                    round(churn.sessions_per_sec, 2),
                **churn.register_quantiles(),
                **churn.session_quantiles(),
            })
        unit = (f"keys/s publish-to-servable (K={refill_batch} "
                f"{'device' if use_device else 'host'} batches, "
                f"durable, N={nb}B domain)")
        if interp and use_device:
            unit += (" [no TPU this session: Pallas interpret mode, "
                     "disclosed; see repro]")
        _emit("keyfactory_bench", backend, "keys_per_sec", keys_per_sec,
              unit, extra_fields=extra)

        # Emitted-then-asserted (the serve_bench --skew discipline): the
        # JSONL line survives a failure, the exit code makes the claims
        # falsifiable in CI / on chip.
        failures = []
        if speedup < 10:
            failures.append(
                f"pool-hit registration is only {speedup:.1f}x faster "
                "than the synchronous path (acceptance wants >= 10x)")
        if use_device and refill_fallbacks:
            failures.append(
                f"{refill_fallbacks} device-keygen call(s) in the "
                "timed fills fell back to the host walk — the emitted "
                "keys/s is NOT a device rate; fix the device path or "
                "pass --host-refill")
        if churn is not None and churn.sessions_ok == 0:
            failures.append("session churn completed zero sessions")
        if failures:
            raise SystemExit("keyfactory_bench: " + "; ".join(failures))
    finally:
        if cleanup:
            shutil.rmtree(store_dir, ignore_errors=True)


def _parse_skew(value, flag: str = "--skew") -> float:
    """Zipf-exponent validation shared by serve_bench / mic_bench /
    chaos_bench (the ``_parse_priority_mix`` discipline: reject a bad
    flag loudly, naming it, BEFORE the warmup ladder and parity gate
    spend real time).  0 = uniform key choice; s > 0 weights the r-th
    registered key by 1/r^s (``serve.loadgen``)."""
    try:
        s = float(value)
    except (TypeError, ValueError):
        raise SystemExit(
            f"{flag}: expected a Zipf exponent (a finite number >= 0, "
            f"0 = uniform), got {value!r}")
    if not math.isfinite(s) or s < 0.0:
        # NaN compares false to 0, so `s < 0` alone would let it
        # through to rng.choice inside every client thread.
        raise SystemExit(
            f"{flag}: Zipf exponent must be finite and >= 0 "
            f"(0 = uniform), got {value!r}")
    return s


def _parse_priority_mix(spec: str) -> dict:
    """``critical=0.2,normal=0.5,batch=0.3`` -> weight dict, validated
    loudly (class names, parseable non-negative weights, no duplicates
    — a malformed entry must name the flag and the expected shape, not
    die in ``float('')``)."""
    from dcf_tpu.serve.admission import parse_priority

    mix = {}
    for part in spec.split(","):
        name, _, w = part.partition("=")
        name = name.strip().lower()
        try:
            parse_priority(name)
            weight = float(w)
        except ValueError as e:
            raise SystemExit(
                f"--priority-mix: bad entry {part.strip()!r} ({e}); "
                "expected class=weight pairs, e.g. "
                "critical=0.2,normal=0.5,batch=0.3")
        if name in mix:
            raise SystemExit(
                f"--priority-mix: duplicate class {name!r}")
        if not math.isfinite(weight) or weight < 0.0:
            # NaN compares false to 0, so `weight < 0` alone lets it
            # through to rng.choice inside every client thread.
            raise SystemExit(
                f"--priority-mix: weight for {name!r} must be a finite "
                f"non-negative number, got {w.strip()!r}")
        mix[name] = weight
    if sum(mix.values()) <= 0.0:
        raise SystemExit(
            "--priority-mix: weights sum to zero — at least one class "
            "needs positive weight, e.g. critical=0.2,normal=0.5")
    return mix


def _chaos_flags(args) -> tuple:
    """The fail-fast flag validation shared by chaos_bench's two
    scenarios (flapping-window and --crash-restart) — one copy, or the
    SystemExit wording the tests match on silently diverges.  Returns
    ``(max_batch, min_req, max_req, window)``."""
    if args.backend not in ("numpy", "jax", "bitsliced", "pallas",
                            "prefix"):
        raise SystemExit(
            f"chaos_bench serves lam=16 single-device facade backends "
            f"(numpy/jax/bitsliced/pallas/prefix), got {args.backend!r}")
    max_batch = args.max_batch or 256
    min_req = args.min_req_points or max(max_batch // 8, 1)
    max_req = args.max_req_points or (max_batch // 2)
    if not 1 <= min_req <= max_req:
        raise SystemExit(f"bad request-size range [{min_req}, {max_req}]")
    window = args.fault_window
    if window < 1:
        raise SystemExit(
            f"--fault-window must be >= 1 failing eval, got {window}")
    return max_batch, min_req, max_req, window


def _chaos_keyfactory_kill(svc, rng, nb, lam) -> tuple:
    """chaos_bench --crash-restart --keyfactory, the pre-kill half
    (ISSUE 11): declare a pool, refill it durably (batched atomic
    manifest flips), claim two sessions, then KILL the next refill
    between its frame writes and the manifest flip (armed
    ``store.manifest`` seam — the exact crash window batched publish
    must survive).  Returns ``(spec, pre_pool, claimed_ids)`` for the
    post-restart assertions."""
    import warnings

    from dcf_tpu.serve import PoolSpec
    from dcf_tpu.testing import faults

    alphas = rng.integers(0, 256, (1, nb), dtype=np.uint8)
    betas = rng.integers(1, 256, (1, lam), dtype=np.uint8)
    spec = svc.add_pool(PoolSpec(
        name="chaos-pool", alphas=alphas, betas=betas,
        target_depth=6, low_water=6, refill_batch=3))
    svc.keyfactory.pump()
    pre_pool = svc.keyfactory.pool_manifest("chaos-pool")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        svc.register_key("pool-sess-0", pool="chaos-pool")
        svc.register_key("pool-sess-1", pool="chaos-pool")
    claimed = set(pre_pool) - set(
        svc.keyfactory.pool_manifest("chaos-pool"))
    try:
        with faults.inject("store.manifest"):
            svc.keyfactory.pump()  # the refill batch writes its
            # frames and dies before the flip (contained, counted);
            # the spent-frame reclaim flip dies too and re-queues
        raise SystemExit(
            "chaos_bench --keyfactory: the armed store.manifest fault "
            "never fired — the kill scenario did not run")
    except faults.InjectedFault:
        pass
    # The reclaim of the two claimed frames retries on a healthy store
    # (the scenario's kill is the refill window, not the reclaim).
    svc.keyfactory.close()
    return spec, pre_pool, claimed


def _chaos_crash_restart(args) -> None:
    """``chaos_bench --crash-restart`` (ISSUE 8): the durable-store
    process-lifecycle scenario.  A service with a key store registers
    its bundles ``durable=True``, serves mixed load under a
    ``serve.eval`` fault window, and is then KILLED mid-stage (closed
    without draining while requests are in flight — the in-process
    stand-in for SIGKILL; the deterministic fake-clock replays live in
    tests/test_store.py).  A fresh service on the same store directory
    restores, and the harness asserts:

    * every durable key came back (``regen_count == 0`` — zero
      re-keygen: the offline phase is the expensive one) with its
      GENERATION preserved (no aliasing of pre-crash snapshots);
    * nothing was quarantined (the store's atomic publish discipline
      means a kill can never leave a torn visible frame);
    * the restored registry serves BIT-EXACT two-party reconstructions
      against the C++ host core (the same parity anchor every serve
      bench uses).

    Exit code != 0 on any violated assertion, so the scenario is
    CI-usable like the flapping-window chaos soak.
    """
    import shutil
    import tempfile

    from dcf_tpu import Dcf
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.serve.batcher import next_pow2
    from dcf_tpu.serve.loadgen import closed_loop
    from dcf_tpu.testing import faults

    lam, nb = 16, 16
    max_batch, min_req, max_req, window = _chaos_flags(args)
    n_bundles = args.bundles or 3
    store_dir = args.store_dir or tempfile.mkdtemp(prefix="dcf-chaos-")
    cleanup = not args.store_dir  # keep an operator-chosen dir around
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    dcf = Dcf(nb, lam, ck, backend=args.backend)
    try:
        svc = dcf.serve(max_batch=max_batch,
                        max_delay_ms=args.max_delay_ms, retries=1,
                        breaker_failures=args.breaker_failures,
                        breaker_cooldown_s=args.breaker_cooldown,
                        store_dir=store_dir)
        bundles = _gen_serve_bundles(svc, native, rng, n_bundles, nb,
                                     lam, durable=True)
        gens_pre = {k: svc.registry.snapshot(k)[2] for k in bundles}
        m = next_pow2(min_req)
        while m <= max_batch:  # compile ladder before timing anything
            svc.submit("key-0",
                       rng.integers(0, 256, (m, nb), dtype=np.uint8))
            svc.pump()
            m *= 2
        _serve_parity_gate(svc, native, bundles, rng, nb, points=64,
                           bench="chaos_bench", tag="pre-crash",
                           timeout=30)
        # Mixed load under a fail-then-recover window: durable keys
        # must survive retries/invalidation sweeps like any other.
        with faults.inject_schedule("serve.eval",
                                    window_evals=window) as sched:
            svc.start()
            res = closed_loop(
                svc, sorted(bundles), duration_s=float(args.duration),
                concurrency=args.concurrency,
                min_points=min_req, max_points=max_req, seed=args.seed)
            # The KILL: in-flight submits, then shutdown without drain
            # (queued futures fail typed; nothing is persisted beyond
            # what register_key already acked — exactly a crash's view).
            kill_futs = [svc.submit(
                k, rng.integers(0, 256, (min_req, nb), dtype=np.uint8))
                for k in sorted(bundles)]
            svc.close(drain=False)
        pool_state = None
        if args.keyfactory:
            # ISSUE 11: the key-factory half — batched durable refills
            # + a kill between the frame writes and the manifest flip.
            pool_state = _chaos_keyfactory_kill(svc, rng, nb, lam)
        del svc  # abandoned, as a killed process would be

        # Warm restart: fresh facade state, same store directory.
        svc2 = dcf.serve(max_batch=max_batch, retries=1,
                         store_dir=store_dir)
        if pool_state is not None:
            svc2.add_pool(pool_state[0])  # declared before restore, so
            # restored ~pool/ frames adopt straight into the pool
        report = svc2.restore_keys()
        failures = []
        regen = sorted(set(bundles) - set(report.restored))
        if regen:
            failures.append(
                f"regen_count={len(regen)}: durable keys {regen} did "
                "not restore — keygen would have to re-run")
        if report.quarantined:
            failures.append(
                f"quarantined on restore: {sorted(report.quarantined)} "
                "— a kill must never leave a torn visible frame")
        gens_post = {k: svc2.registry.snapshot(k)[2]
                     for k in report.restored}
        if gens_post != {k: gens_pre[k] for k in gens_post}:
            failures.append(
                f"generations drifted across restart: {gens_pre} -> "
                f"{gens_post}")
        pool_extra = {}
        if pool_state is not None:
            spec, pre_pool, claimed = pool_state
            post_pool = svc2.keyfactory.pool_manifest(spec.name)
            want_pool = {k: g for k, g in pre_pool.items()
                         if k not in claimed}
            if post_pool != want_pool:
                failures.append(
                    f"pool supply drifted across restart: "
                    f"{sorted(want_pool)} -> {sorted(post_pool)} "
                    "(torn entries, lost generations, or resurrected "
                    "claims)")
            minted_post = svc2.metrics_snapshot().get(
                "keyfactory_minted_keys_total", 0)
            if minted_post:
                failures.append(
                    f"restore minted {minted_post} pool keys — "
                    "already-published supply must restore with ZERO "
                    "re-keygen")
            if not failures:
                # A restored pool entry must still serve bit-exactly.
                import warnings as _w

                with _w.catch_warnings():
                    _w.simplefilter("ignore")
                    kb_pool = svc2.register_key("post-pool-sess",
                                                pool=spec.name)
                xs_p = rng.integers(0, 256, (32, nb), dtype=np.uint8)
                f0 = svc2.submit("post-pool-sess", xs_p, b=0)
                f1 = svc2.submit("post-pool-sess", xs_p, b=1)
                svc2.pump()
                want = (native.eval(0, kb_pool, xs_p)
                        ^ native.eval(1, kb_pool, xs_p))
                if not np.array_equal(f0.result(30) ^ f1.result(30),
                                      want):
                    failures.append(
                        "restored pool key served a wrong two-party "
                        "reconstruction vs the C++ core")
            pool_extra = {
                "pool_published": len(pre_pool),
                "pool_claimed_pre_kill": len(claimed),
                "pool_restored": len(post_pool),
            }
        if not failures:
            _serve_parity_gate(svc2, native, bundles, rng, nb,
                               points=64, bench="chaos_bench",
                               tag="post-restart", timeout=30)
        for line in failures:
            log(f"CRASH-RESTART FAIL: {line}")
        snap = svc2.metrics_snapshot()
        extra = {
            "scenario": "crash-restart",
            **pool_extra,
            "duration_s": round(res.duration_s, 3),
            "concurrency": args.concurrency,
            "max_batch": max_batch,
            "bundles": n_bundles,
            "fault_window": window,
            "fault_evals_failed": sched.failed,
            "requests_ok": res.requests_ok,
            "requests_failed": res.requests_failed,
            "killed_inflight": len(kill_futs),
            "regen_count": len(regen),
            "restored": len(report.restored),
            "quarantined": len(report.quarantined),
            "store_restored_total": snap.get(
                "serve_store_restored_total", 0),
            "assertions_failed": failures,
        }
        _emit("chaos_bench", args.backend, "restored_keys",
              float(len(report.restored)),
              "durable keys restored after the mid-stage kill",
              extra_fields=extra)
        if failures:
            raise SystemExit(
                f"chaos_bench --crash-restart: {len(failures)} "
                "durability assertions failed")
    finally:
        if cleanup:
            shutil.rmtree(store_dir, ignore_errors=True)


def bench_chaos(args) -> None:
    """Chaos harness for the serve resilience layer (ISSUE 6).

    Drives the service with a mixed-priority closed-loop load while a
    DECLARATIVE fault schedule is armed at the ``serve.eval`` seam —
    fail the first ``--fault-window`` evals, then recover (the sustained
    failure mode the one-shot fault tests cannot express) — and then
    ASSERTS the resilience contract off the metrics snapshot:

    * the (key, backend-family) circuit breaker OPENED during the window
      (``serve_breaker_transitions_total{to=open}`` >= 1) and CLOSED
      again after it (``{to=closed}`` >= 1, ``any_open()`` false at
      exit) — the open/half-open/closed walk actually happened;
    * shedding was lowest-class-first: zero CRITICAL requests shed,
      BATCH-class brownout refusals observed whenever a breaker opened
      (``serve_brownout_refusals_total`` > 0 when the run sheds at all);
    * the service still serves BIT-EXACTLY after recovery: a post-chaos
      two-party reconstruction per bundle is checked against the C++
      host core, same anchor as serve_bench's parity gate.

    Exit code != 0 on any violated assertion (SystemExit), so the chaos
    run is CI-usable as a soak.  Uses the real clock — the driving loop
    is a load generator; the deterministic fake-clock replays of the
    same scenarios live in tests/test_chaos.py.

    ``--crash-restart`` (ISSUE 8) switches to the durable-store
    process-lifecycle scenario instead: durable keys, a mid-stage kill,
    a warm restart from the store, and bit-exact post-restart parity vs
    the C++ core with zero re-keygen (see ``_chaos_crash_restart``).
    """
    from dcf_tpu import Dcf
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.serve.loadgen import closed_loop
    from dcf_tpu.testing import faults

    if args.crash_restart:
        _chaos_crash_restart(args)
        return
    if args.keyfactory:
        raise SystemExit(
            "--keyfactory extends the durable-store scenario; pass it "
            "with --crash-restart")
    lam, nb = 16, 16
    max_batch, min_req, max_req, window = _chaos_flags(args)
    mix = _parse_priority_mix(args.priority_mix)  # bad flags fail fast,
    # before the warmup ladder and parity gate spend real time
    skew = _parse_skew(args.skew)  # same edge discipline for --skew
    n_bundles = args.bundles or 2
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    dcf = Dcf(nb, lam, ck, backend=args.backend)
    svc = dcf.serve(max_batch=max_batch,
                    max_delay_ms=args.max_delay_ms,
                    retries=1,
                    breaker_failures=args.breaker_failures,
                    breaker_cooldown_s=args.breaker_cooldown,
                    # Queue bound generous on purpose: overload sheds
                    # must come from the BROWNOUT/breaker machinery under
                    # test, not from a queue sized too small for the
                    # client count (which would shed CRITICAL too and
                    # void the lowest-class-first assertion).
                    max_queued_points=1 << 20)
    bundles = _gen_serve_bundles(svc, native, rng, n_bundles, nb, lam)

    # Warm the padded-batch compile ladder BEFORE arming faults (a
    # compile inside the chaos window would eat the whole schedule).
    from dcf_tpu.serve.batcher import next_pow2

    m = next_pow2(min_req)
    while m <= max_batch:
        svc.submit("key-0", rng.integers(0, 256, (m, nb), dtype=np.uint8))
        svc.pump()
        m *= 2
    _serve_parity_gate(svc, native, bundles, rng, nb, points=64,
                       bench="chaos_bench", tag="pre-chaos",
                       priority="critical", timeout=30)

    with faults.inject_schedule("serve.eval",
                                window_evals=window) as sched:
        with svc:
            res = closed_loop(
                svc, sorted(bundles), duration_s=float(args.duration),
                concurrency=args.concurrency,
                min_points=min_req, max_points=max_req,
                seed=args.seed, priority_mix=mix, skew=skew)
        # NOTE: ``with svc`` drains on exit, so the snapshot below is a
        # quiescent end-state, not a mid-flight race.
    snap = svc.metrics_snapshot()

    # --- the resilience assertions (the point of the harness) ---------
    failures = []
    opened = snap.get("serve_breaker_transitions_total{to=open}", 0)
    closed = snap.get("serve_breaker_transitions_total{to=closed}", 0)
    if not sched.recovered:
        failures.append(
            f"fault window not consumed ({sched.failed}/{window} "
            "failing evals): raise --duration or lower --fault-window")
    if opened < 1:
        failures.append("breaker never opened under the fault window")
    if closed < 1:
        failures.append("breaker never closed after recovery")
    stuck = sorted(k for k, v in snap.items()
                   if k.startswith("serve_breaker_state{") and v)
    if stuck:
        # NOT any_open(): its cooldown filter is right for brownout
        # pressure but wrong here — by snapshot time (drain >> cooldown)
        # a breaker wedged OPEN is merely probe-ready and would slip
        # through.  The state gauges are cooldown-independent.
        failures.append(
            f"breaker(s) not closed after recovery: {', '.join(stuck)}")
    crit_shed = snap.get(
        "serve_shed_by_class_total{priority=critical}", 0)
    batch_shed = snap.get("serve_shed_by_class_total{priority=batch}", 0)
    if crit_shed:
        failures.append(f"{crit_shed} CRITICAL requests shed — shedding "
                        "must be lowest-class-first")
    if snap.get("serve_shed_total", 0) and not batch_shed:
        failures.append("the run shed load but no BATCH-class request "
                        "was shed — not lowest-class-first")
    for line in failures:
        log(f"CHAOS FAIL: {line}")

    # Post-recovery proof: the drain above closed admission, so rebuild
    # a fresh service on the same facade — it must serve bit-exactly.
    svc2 = dcf.serve(max_batch=max_batch, retries=1)
    for name, bundle in bundles.items():
        svc2.register_key(name, bundle)
    _serve_parity_gate(svc2, native, bundles, rng, nb, points=64,
                       bench="chaos_bench", tag="post-chaos",
                       priority="critical", timeout=30)

    extra = {
        "duration_s": round(res.duration_s, 3),
        "concurrency": args.concurrency,
        "max_batch": max_batch,
        "fault_window": window,
        "fault_evals_failed": sched.failed,
        "priority_mix": mix,
        "skew": skew,
        "requests_ok": res.requests_ok,
        "requests_shed": res.requests_shed,
        "requests_failed": res.requests_failed,
        "by_class": res.by_class,
        "breaker_opens": opened,
        "breaker_closes": closed,
        "brownout_refusals": snap.get("serve_brownout_refusals_total", 0),
        "metrics_snapshot": snap,
        "assertions_failed": failures,
    }
    _emit("chaos_bench", args.backend, "requests_ok",
          float(res.requests_ok),
          "requests served under the chaos schedule", extra_fields=extra)
    if failures:
        raise SystemExit(
            f"chaos_bench: {len(failures)} resilience assertions failed")


def bench_baseline(args) -> None:
    """All five BASELINE.json configs in one run, one JSON line per
    bench invocation (8 lines total: config 1 emits gen + 1-pt eval, and
    configs 2 and 4 each run both their literal wording and the
    reference-bench shape they cite).

    Per-config backend = the measured winner on this hardware (the
    accelerator everywhere: the hybrid affine split reclaimed large-lambda
    from the CPU, benchmarks/RESULTS_r02.jsonl).

    ``--full`` runs config 5 at its literal 10^6-key scale (the whole
    report then takes ~20 minutes, dominated by three timed 10^6-key
    pipelines); without it secure_relu uses 2^18 keys to keep the report
    minutes-long.  The round-5 headline artifact is regenerated by
    exactly::

        python -m dcf_tpu.cli baseline --full > BASELINE_REPORT_r05.jsonl
    """
    import copy

    # An explicit --keys always wins; --full only raises the default.
    full_keys = args.keys or (1_000_000 if args.full else 1 << 18)
    specs = [
        ("1", "dcf", dict(backend="cpu")),
        # Round 5: the prefix-shared evaluator is the measured winner for
        # both random-batch shapes (1.71x config 2, +11% flagship vs the
        # from-root walk — ROOFLINE.md round 5).
        # keys=1 pinned explicitly: an outer --keys (meant for config 5)
        # must not leak into the single-key prefix shapes.
        ("2 (flagship n=128 scale-up)", "dcf_batch_eval",
         dict(backend="prefix", points=1 << 20, keys=1)),
        # BASELINE.json config 2's literal "n=32" wording (4-byte domain),
        # same 2^20-point batch — the n=128 line above is the scaled-up
        # headline shape.
        ("2 (literal n=32)", "dcf_batch_eval",
         dict(backend="prefix", points=1 << 20, domain_bytes=4, keys=1)),
        ("3", "full_domain", dict(backend="tree", n_bits=24)),
        # Config 4 twice: the lambda=16384 shape of the reference bench it
        # cites (benches/dcf_large_lambda.rs:8-43) and the literal
        # "lambda=256" of the BASELINE.json wording.
        ("4 (reference bench lambda=16384)", "dcf_large_lambda",
         dict(backend="hybrid", points=10_000, keys=1)),
        ("4 (literal lambda=256)", "dcf_large_lambda",
         dict(backend="hybrid", points=10_000, keys=1, lam=256)),
        ("5", "secure_relu", dict(backend="cpu", device_gen=True,
                                  keys=full_keys,
                                  points=args.points or 1_024)),
    ]
    if args.mesh:
        log("baseline is the single-chip report; ignoring --mesh "
            "(bench the sharded backends individually)")
    for cfg, name, over in specs:
        log(f"--- BASELINE config {cfg}: {name} {over} ---")
        a = copy.copy(args)
        a.mesh = ""
        for key, val in over.items():
            setattr(a, key, val)
        BENCHES[name](a)


def _serve_host_facade(args):
    """The shard facade serve_host/pod_bench share: flagship-shaped by
    default, cipher keys DERIVED from ``--seed`` — every process in a
    pod launched with the same seed/lam reconstructs the same cipher
    keys, which is what lets pod_bench provision bundles in the parent
    and have every shard serve them."""
    from dcf_tpu import Dcf

    lam = args.lam or 16
    nb = args.domain_bytes or 16
    backend = args.backend
    if backend == "cpu":
        backend = "bitsliced"  # the no-TPU serving default, as in
        # serve_bench/edge_bench
    if backend not in ("numpy", "jax", "bitsliced", "pallas", "prefix"):
        raise SystemExit(
            f"serve_host/pod_bench serve single-device facade backends "
            f"(numpy/jax/bitsliced/pallas/prefix), got {backend!r}")
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    return Dcf(nb, lam, ck, backend=backend), lam, nb, backend, rng


def bench_serve_host(args) -> None:
    """One pod shard process (ISSUE 13): the existing crash-safe,
    breaker-guarded single-host serving unit — ``DcfService`` warm-
    started from its durable store + an ``EdgeServer`` — run as a
    long-lived process a router forwards DCFE frames to.

    Keys are provisioned through the shard's store (``--store-dir``):
    the operator (or pod_bench) writes DCFK frames there under ring
    placement — owner AND replica stores, generations preserved via
    ``KeyStore.replicate_to`` — and this process restores ALL of them
    at startup (``restore_keys()``), so a replica is warm the moment
    failover routes to it.  ``--ready-file`` receives a JSON line with
    the bound address once serving; ``--metrics-file`` is refreshed
    (atomic rename) every ~0.5s and at shutdown — the per-host
    snapshots ``pod_bench`` rolls up into the pod view.  Runs until
    SIGTERM/SIGINT (or until its parent exits — a shard orphaned by a
    dead launcher must not linger).  ``--tls-cert``/``--tls-key``
    (+ ``--tls-client-ca`` to pin the router) arm TLS on the edge
    socket.

    Graceful shutdown (ISSUE 15 satellite): SIGTERM/SIGINT stop the
    edge (no new frames), DRAIN the service (``close(drain=True)`` —
    queued requests are served, never failed), write one final
    metrics snapshot, and remove the ready file before exiting 0 — a
    PLANNED restart (a drain, a rolling deploy) loses no accepted
    work and un-advertises itself, so a launcher polling the ready
    file sees the shard gone rather than stale.  SIGKILL remains the
    crash test: the failover/restore machinery owns that path."""
    import json as _json
    import os
    import signal
    import threading

    from dcf_tpu.serve import EdgeServer

    if not args.store_dir:
        raise SystemExit(
            "serve_host needs --store-dir (the shard's durable key "
            "store; pod provisioning writes frames there)")
    dcf, lam, nb, backend, _rng = _serve_host_facade(args)
    knobs = {}
    if args.max_queued_points:
        # The surge scenario pins a small admission bound so sustained
        # overload becomes visible demand (sheds/brownout) within the
        # bench window instead of an invisible mile-deep queue.
        knobs["max_queued_points"] = args.max_queued_points
    svc = dcf.serve(max_batch=args.max_batch or (1 << 10),
                    max_delay_ms=args.max_delay_ms,
                    store_dir=args.store_dir,
                    tls_cert=args.tls_cert, tls_key=args.tls_key,
                    tls_client_ca=args.tls_client_ca, **knobs)
    if args.standby:
        # A standby host (ISSUE 16) is provisioned-but-idle: it serves
        # and probes, but restores nothing at startup — the graceful
        # join's warm-before-admit pass ships it exactly the keys its
        # ring placement owes it WHEN the capacity controller admits
        # it, so a stale store left from a previous tour never races
        # the migration.
        from dcf_tpu.serve import RestoreReport

        report = RestoreReport()
        log(f"serve_host[{backend} lam={lam} nb={nb}]: STANDBY "
            "(restore skipped; the join warms this host)")
    else:
        report = svc.restore_keys()
        log(f"serve_host[{backend} lam={lam} nb={nb}]: restored "
            f"{len(report.restored)} keys "
            f"({len(report.quarantined)} quarantined)")
    svc.start()
    edge = EdgeServer(svc, host=args.bind, port=args.port).start()
    host, port = edge.address

    def _flush(path: str, doc: dict) -> None:
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            _json.dump(doc, fh)
        os.replace(tmp, path)  # readers only ever see a whole file

    if args.ready_file:
        _flush(args.ready_file, {
            "host": host, "port": port, "pid": os.getpid(),
            "restored": len(report.restored),
            "quarantined": len(report.quarantined),
            "standby": bool(args.standby)})
    log(f"serve_host listening on {host}:{port}")
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_a: stop.set())
    ppid = os.getppid()
    try:
        while not stop.is_set():
            stop.wait(0.5)
            if args.metrics_file:
                _flush(args.metrics_file, svc.metrics_snapshot())
            if os.getppid() != ppid:
                log("serve_host: parent exited; shutting down")
                break
    finally:
        # Ordered graceful teardown: listener first (no NEW
        # connections — live ones stay open so drained responses can
        # still reach their clients), then drain the service (queued
        # requests complete; frames arriving mid-drain are refused
        # typed over the still-open links), then the edge flushes each
        # writer's backlog before the hard close, and only THEN the
        # final metrics snapshot — it must include the drained work's
        # counters.
        edge.stop_accepting()
        try:
            svc.close(drain=True)
        except Exception:  # fallback-ok: a failing drain (dying
            # store reclaim at shutdown) must not skip the snapshot
            # or the ready-file removal below
            log("serve_host: drain raised; exiting anyway")
        edge.close(drain_s=5.0)
        if args.metrics_file:
            try:
                _flush(args.metrics_file, svc.metrics_snapshot())
            except OSError:
                pass  # fallback-ok: dying disk at shutdown — the
                # periodic flush above already published a snapshot
        if args.ready_file:
            try:
                os.unlink(args.ready_file)
            except OSError:
                pass  # fallback-ok: never written, or already gone
    log("serve_host: stopped")


def _pod_rollup(metric_files: list) -> dict:
    """The pod view: per-host metrics snapshots (the serve_host
    ``--metrics-file`` JSON dumps) summed via
    ``serve.metrics.rollup_snapshots``.  Hosts that never wrote one
    (killed before the first flush) contribute nothing."""
    import json as _json

    from dcf_tpu.serve.metrics import rollup_snapshots

    snaps = []
    for path in metric_files:
        try:
            with open(path, encoding="utf-8") as fh:
                snaps.append(_json.load(fh))
        except (OSError, ValueError):
            continue  # fallback-ok: a killed shard's file may be
            # absent; the rollup is over the hosts that reported
    return rollup_snapshots(snaps)


def _pod_provision(dcf, lam, nb, rng, root, shard_ids,
                   n_bundles: int, *, solo: bool = False) -> tuple:
    """The ONE provisioning block every pod scenario starts with
    (ISSUE 16 small fix: ``--churn``/``--partition``/``--flap``/the
    kill leg each carried a near-copy): build the rendezvous ring over
    ``shard_ids``, open one ``KeyStore`` per shard under ``root``,
    mint ``n_bundles`` two-party bundles, and write each durably to
    its owner's store with ``replicate_to`` copies to its replicas —
    same bytes, same generation.  ``solo`` adds the single-shard
    comparison store holding everything (the ``bench_pod`` leg).
    Returns ``(ring, stores, bundles, gens)``."""
    import os

    from dcf_tpu.serve import KeyStore, ShardMap, ShardSpec

    ring = ShardMap([ShardSpec(s) for s in shard_ids])
    stores = {s: KeyStore(os.path.join(root, s)) for s in shard_ids}
    if solo:
        stores["solo"] = KeyStore(os.path.join(root, "solo"))
    bundles, gens = {}, {}
    for i in range(n_bundles):
        name = f"key-{i}"
        alphas = rng.integers(0, 256, (1, nb), dtype=np.uint8)
        betas = rng.integers(0, 256, (1, lam), dtype=np.uint8)
        kb = dcf.gen(alphas, betas, rng=rng)
        bundles[name], gens[name] = kb, i + 1
        placed = ring.placement(name, replicas=1)
        stores[placed[0].host_id].put(name, kb, generation=gens[name])
        for rep in placed[1:]:
            stores[placed[0].host_id].replicate_to(
                stores[rep.host_id], name)
        if solo:
            stores["solo"].put(name, kb, generation=gens[name])
    return ring, stores, bundles, gens


def _pod_warmup(rng, nb: int, max_batch: int, plan) -> None:
    """The ONE warmup ladder every pod scenario runs (the other half
    of the ISSUE 16 dedupe): warm every padded pow-2 batch shape on
    every process, both parties — ``plan`` is ``[(target,
    [key, ...]), ...]`` with one key per shard the ladder must reach.
    Without this the soaks pay the XLA compile storm mid-scenario and
    the ledger measures compilation, not the product."""
    xs_warm = rng.integers(0, 256, (max_batch, nb), dtype=np.uint8)
    m = 1
    while m <= max_batch:
        for target, keys in plan:
            for name in keys:
                target.evaluate(name, xs_warm[:m], b=0, timeout=300)
                target.evaluate(name, xs_warm[:m], b=1, timeout=300)
        m *= 2


def _pod_spawn(tag: str, store_dir: str, run_dir: str, args,
               standby: bool = False, extra=()) -> tuple:
    """Spawn one serve_host subprocess; returns (Popen, ready_path,
    metrics_path).  ``standby``: launch it as a provisioned-but-idle
    standby host (``serve_host --standby``, ISSUE 16); ``extra``:
    additional serve_host flags (the surge scenario's queue bound)."""
    import os
    import subprocess

    ready = os.path.join(run_dir, f"ready-{tag}.json")
    metrics = os.path.join(run_dir, f"metrics-{tag}.json")
    cmd = [sys.executable, "-m", "dcf_tpu.cli", "serve_host",
           "--store-dir", store_dir, "--ready-file", ready,
           "--metrics-file", metrics, "--seed", str(args.seed),
           "--backend", args.backend,
           "--max-batch", str(args.max_batch or (1 << 10)),
           "--max-delay-ms", str(args.max_delay_ms)]
    if args.lam:
        cmd += ["--lam", str(args.lam)]
    if args.domain_bytes:
        cmd += ["--domain-bytes", str(args.domain_bytes)]
    if standby:
        cmd += ["--standby"]
    cmd += list(extra)
    proc = subprocess.Popen(cmd)
    return proc, ready, metrics


def _pod_wait_ready(procs: dict, timeout_s: float = 300.0) -> dict:
    """Block until every spawned shard wrote its ready file; returns
    ``{tag: ready_doc}``.  A shard that exits early (or the deadline)
    is a SystemExit — a half-up pod must not silently bench."""
    import json as _json
    import os

    t0 = time.monotonic()
    ready: dict = {}
    while len(ready) < len(procs):
        for tag, (proc, rpath, _m) in procs.items():
            if tag in ready:
                continue
            if proc.poll() is not None:
                raise SystemExit(
                    f"pod_bench: shard {tag} exited rc={proc.returncode} "
                    "before becoming ready")
            if os.path.exists(rpath):
                with open(rpath, encoding="utf-8") as fh:
                    ready[tag] = _json.load(fh)
        if len(ready) < len(procs):
            if time.monotonic() - t0 > timeout_s:
                raise SystemExit(
                    f"pod_bench: shards not ready after {timeout_s:.0f}s "
                    f"({sorted(ready)} of {sorted(procs)})")
            time.sleep(0.2)
    return ready


def _pod_soak(router, bundles, prg, nb, *, duration_s: float,
              conns: int, seed: int, kill_after_s: float,
              kill_fn) -> dict:
    """The kill-a-shard failover soak (ISSUE 13 acceptance): ``conns``
    closed-loop clients drive mixed CRITICAL/NORMAL two-party sessions
    through the pod router while ``kill_fn`` SIGKILLs one shard
    mid-run.  EVERY request must be accounted: completed bit-exact vs
    the numpy oracle, or refused typed WITH a ``retry_after_s`` hint
    (the router converts bare transport deaths into hinted
    ``CircuitOpenError`` refusals precisely so this ledger closes).
    Anything else — an unhinted refusal, a mismatch, an untyped error
    — fails the gate."""
    import threading

    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.errors import DcfError

    names = sorted(bundles)
    stats = {"sessions_ok": 0, "critical_ok": 0, "mismatches": 0,
             "refused_hinted": 0, "refused_unhinted": 0,
             "unaccounted": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def client(i: int) -> None:
        rng = np.random.default_rng(seed + 211 * i)
        while not stop.is_set():
            name = names[int(rng.integers(0, len(names)))]
            pr = "critical" if rng.random() < 0.5 else "normal"
            m = int(rng.integers(1, 65))
            xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
            try:
                f0 = router.submit(name, xs, b=0, priority=pr)
                f1 = router.submit(name, xs, b=1, priority=pr)
                got = f0.result(120) ^ f1.result(120)
            except DcfError as e:
                hinted = getattr(e, "retry_after_s", None) is not None
                with lock:
                    if hinted:
                        stats["refused_hinted"] += 1
                    else:
                        stats["refused_unhinted"] += 1
                continue
            except Exception:  # fallback-ok: the gate's failure arm —
                # anything untyped escaping the router is exactly what
                # the soak exists to catch, counted and asserted on
                with lock:
                    stats["unaccounted"] += 1
                continue
            kb = bundles[name]
            want = eval_batch_np(prg, 0, kb.for_party(0), xs) ^ \
                eval_batch_np(prg, 1, kb.for_party(1), xs)
            with lock:
                if np.array_equal(got, want):
                    stats["sessions_ok"] += 1
                    if pr == "critical":
                        stats["critical_ok"] += 1
                else:
                    stats["mismatches"] += 1

    threads = [threading.Thread(target=client, args=(i,),
                                name=f"pod-soak-{i}", daemon=True)
               for i in range(conns)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    killed = False
    while time.monotonic() - t0 < duration_s:
        if not killed and time.monotonic() - t0 >= kill_after_s:
            kill_fn()
            killed = True
        stop.wait(0.05)
    stop.set()
    for t in threads:
        t.join()
    return stats


def _pod_live_register(router, dcf, rng, lam, nb, count: int,
                       prefix: str = "live-key") -> tuple:
    """Register ``count`` LIVE (non-durable) keys through the router's
    REGISTER fan-out (ISSUE 14): the owner mints each generation, the
    replicas apply it preserved — the path whose survival the kill and
    partition soaks gate on.  Returns ``(bundles, generations)``."""
    live, live_gens = {}, {}
    for i in range(count):
        name = f"{prefix}-{i}"
        alphas = rng.integers(0, 256, (1, nb), dtype=np.uint8)
        betas = rng.integers(0, 256, (1, lam), dtype=np.uint8)
        kb = dcf.gen(alphas, betas, rng=rng)
        live_gens[name] = router.register_key(name, kb)
        live[name] = kb
    return live, live_gens


def _pod_wire_digest(addr: tuple, nb: int) -> dict:
    """A shard's live ``{key_id: generation}`` digest over the wire
    (the DIGEST verb — generations only, no key material moves)."""
    from dcf_tpu.serve import EdgeClient

    with EdgeClient(addr[0], addr[1], n_bytes=nb) as c:
        return c.pull_digest(timeout=60)


def bench_pod_selfheal(args) -> None:
    """``pod_bench --partition`` / ``--flap`` (ISSUE 14): the
    partition-tolerance acceptance scenario.  N shard processes behind
    the self-healing router; durable keys provisioned through the
    stores (owner + replica, ``replicate_to``), live keys through the
    REGISTER fan-out; then a ``net.partition`` window (``--flap``:
    three windows) cuts the router<->victim link under 3-thread mixed
    CRITICAL/NORMAL load while the health prober runs.

    Emitted-then-asserted gates:

    * LEDGER: every request reconstructs bit-exact vs the numpy
      oracle or is refused typed WITH ``retry_after_s`` — zero
      mismatches, zero untyped, zero unhinted;
    * PROMOTION: the prober walks the victim to DOWN inside every cut
      window, and a NORMAL request for a victim-owned key then serves
      bit-exact from the promoted replica within about one probe
      interval of the DOWN transition;
    * HEALING: after every window the victim is re-admitted UP
      through the anti-entropy gate, its wire digest converges to the
      owners' generations (including a re-registration minted MID-cut
      on the reachable side), and generations never regress across
      cycles;
    * THE FENCE: a doctored old-generation REGISTER frame sent
      straight to the victim dies typed ``E_STALE`` and the key keeps
      serving the newer bits."""
    import os
    import shutil
    import tempfile

    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.errors import StaleStateError
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.serve import DcfRouter, EdgeClient, ShardSpec
    from dcf_tpu.serve.health import DOWN, UP
    from dcf_tpu.testing import faults

    n_shards = args.shards
    if n_shards < 2:
        raise SystemExit(
            f"--shards must be >= 2 for the partition scenario, "
            f"got {n_shards}")
    if args.probe_interval <= 0:
        raise SystemExit(
            f"--probe-interval must be > 0, got {args.probe_interval}")
    if args.live_bundles < 0:
        raise SystemExit(
            f"--live-bundles must be >= 0, got {args.live_bundles}")
    dcf, lam, nb, backend, rng = _serve_host_facade(args)
    prg = HirosePrgNp(lam, dcf.cipher_keys)
    n_bundles = args.bundles or 4
    cycles = 3 if args.flap else 1
    mode = "flap" if args.flap else "partition"

    keep_dirs = bool(args.store_dir)
    root = args.store_dir or tempfile.mkdtemp(prefix="dcf-pod-")
    os.makedirs(root, exist_ok=True)
    shard_ids = [f"shard-{i}" for i in range(n_shards)]
    ring, stores, bundles, gens = _pod_provision(
        dcf, lam, nb, rng, root, shard_ids, n_bundles)
    procs: dict = {}
    router = None
    try:
        for tag in shard_ids:
            procs[tag] = _pod_spawn(tag, os.path.join(root, tag),
                                    root, args)
        ready = _pod_wait_ready(procs)
        pod_specs = [ShardSpec(s, ready[s]["host"], ready[s]["port"])
                     for s in shard_ids]
        addr_of = {s: (ready[s]["host"], ready[s]["port"])
                   for s in shard_ids}
        router = DcfRouter(
            pod_specs, n_bytes=nb,
            probe_interval_s=args.probe_interval,
            probe_timeout_s=5.0, probe_fail_n=3,
            probe_recover_m=2, reconnect_backoff_s=0.02,
            max_backoff_s=max(min(args.probe_interval, 0.5), 0.02))
        live, live_gens = _pod_live_register(
            router, dcf, rng, lam, nb, args.live_bundles)
        bundles.update(live)
        gens.update(live_gens)
        log(f"provisioned {n_bundles} durable + {len(live)} live keys "
            f"over {n_shards} shards")

        xs_gate = rng.integers(0, 256, (64, nb), dtype=np.uint8)
        for name, kb in bundles.items():
            got = router.evaluate(name, xs_gate, b=0, timeout=300) ^ \
                router.evaluate(name, xs_gate, b=1, timeout=300)
            want = eval_batch_np(prg, 0, kb.for_party(0), xs_gate) ^ \
                eval_batch_np(prg, 1, kb.for_party(1), xs_gate)
            if not np.array_equal(got, want):
                raise SystemExit(
                    f"pod_bench parity mismatch vs numpy oracle on "
                    f"{name}")
        log(f"routed parity vs numpy oracle: OK ({len(bundles)} keys)")

        owners = {n: ring.owner(n).host_id for n in bundles}
        by_owner: dict = {}
        for name, owner in owners.items():
            by_owner.setdefault(owner, []).append(name)
        max_batch = args.max_batch or (1 << 10)
        _pod_warmup(rng, nb, max_batch,
                    [(router, [keys[0] for keys in by_owner.values()])])
        log("warmup ladder done (all shards, both parties)")
        victim = max(by_owner, key=lambda s: len(by_owner[s]))
        # A key to register MID-cut: its owner stays reachable, its
        # replica is the cut victim — the heal must converge it.  The
        # name is always a FRESH one mined from the ring (placement
        # is a pure function, so the search is deterministic): the
        # soak clients snapshot their key list before it exists, so
        # no client ever oracles a key whose bundle this thread is
        # swapping mid-cut (that would race the bench's bookkeeping,
        # not the product).
        midcut_key = next(
            f"midcut-key-{i}" for i in range(100000)
            if ring.placement(f"midcut-key-{i}", replicas=1)[0]
            .host_id != victim
            and victim in {s.host_id for s in ring.placement(
                f"midcut-key-{i}", replicas=1)})
        victim_key = sorted(by_owner[victim])[0]

        router.start_health()
        deadline = time.monotonic() + 60
        while any(st != UP for st in router.health.states().values()):
            if time.monotonic() > deadline:
                raise SystemExit(
                    f"pod_bench: prober never saw the pod UP "
                    f"({router.health.states()})")
            time.sleep(0.05)

        # The soak clients (ledger accumulates across all cycles).
        import threading

        stats = {"sessions_ok": 0, "critical_ok": 0, "mismatches": 0,
                 "refused_hinted": 0, "refused_unhinted": 0,
                 "unaccounted": 0}
        lock = threading.Lock()
        stop = threading.Event()

        def client(i: int) -> None:
            from dcf_tpu.errors import DcfError

            crng = np.random.default_rng(args.seed + 311 * i)
            names = sorted(bundles)
            while not stop.is_set():
                name = names[int(crng.integers(0, len(names)))]
                pr = "critical" if crng.random() < 0.4 else "normal"
                m = int(crng.integers(1, 33))
                xs = crng.integers(0, 256, (m, nb), dtype=np.uint8)
                try:
                    f0 = router.submit(name, xs, b=0, priority=pr)
                    f1 = router.submit(name, xs, b=1, priority=pr)
                    got = f0.result(120) ^ f1.result(120)
                except DcfError as e:
                    hinted = getattr(e, "retry_after_s",
                                     None) is not None
                    with lock:
                        stats["refused_hinted" if hinted else
                              "refused_unhinted"] += 1
                    continue
                except Exception:  # fallback-ok: the gate's failure
                    # arm — anything untyped is what the soak hunts
                    with lock:
                        stats["unaccounted"] += 1
                    continue
                kb = bundles[name]
                want = eval_batch_np(prg, 0, kb.for_party(0), xs) ^ \
                    eval_batch_np(prg, 1, kb.for_party(1), xs)
                with lock:
                    if np.array_equal(got, want):
                        stats["sessions_ok"] += 1
                        if pr == "critical":
                            stats["critical_ok"] += 1
                    else:
                        stats["mismatches"] += 1

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(3)]
        t_soak0 = time.monotonic()
        for t in threads:
            t.start()
        # The cut window must fit several probe ROUNDS even on a
        # loaded 1-CPU host where a healthy shard's ping can take
        # seconds — a window shorter than fail_n rounds cannot
        # demonstrate the DOWN walk, it just measures CPU contention.
        cut_s = max(float(args.duration) / (3 * cycles),
                    6 * args.probe_interval, 10.0)
        down_seen = up_recovered = 0
        promoted_within: list = []
        digest_regressions = 0
        seen_gens: dict = {}
        try:
            for cycle in range(cycles):
                t0 = time.monotonic()
                handler = faults.partition(
                    {(router.local_tag, victim)}, clock=time.monotonic,
                    window=(t0, t0 + cut_s))
                with faults.inject("net.partition", handler=handler):
                    # Wait for the prober to mark the victim DOWN.
                    while time.monotonic() < t0 + cut_s:
                        if router.health.state(victim) == DOWN:
                            break
                        time.sleep(0.02)
                    if router.health.state(victim) == DOWN:
                        down_seen += 1
                        t_down = time.monotonic()
                        # Promotion: NORMAL traffic for a victim-owned
                        # key ROUTES to the replica (the submit
                        # returning un-refused IS the promotion — the
                        # timed claim is routing availability, not
                        # this loaded host's eval speed) and serves
                        # bit-exact.
                        xs = rng.integers(0, 256, (4, nb),
                                          dtype=np.uint8)
                        kb = bundles[victim_key]
                        try:
                            f0 = router.submit(victim_key, xs, b=0)
                            routed_s = time.monotonic() - t_down
                            f1 = router.submit(victim_key, xs, b=1)
                            got = f0.result(120) ^ f1.result(120)
                        except Exception:  # fallback-ok: a missing
                            # promotion fails the promoted_within
                            # gate below — counted, not fatal here
                            got = None
                        want = eval_batch_np(
                            prg, 0, kb.for_party(0), xs) ^ \
                            eval_batch_np(prg, 1, kb.for_party(1), xs)
                        if got is not None \
                                and np.array_equal(got, want):
                            promoted_within.append(routed_s)
                    if midcut_key is not None:
                        # Mint a NEWER generation on the reachable
                        # side mid-cut: the heal must converge it.
                        alphas = rng.integers(0, 256, (1, nb),
                                              dtype=np.uint8)
                        betas = rng.integers(0, 256, (1, lam),
                                             dtype=np.uint8)
                        bundles[midcut_key] = dcf.gen(alphas, betas,
                                                      rng=rng)
                        gens[midcut_key] = router.register_key(
                            midcut_key, bundles[midcut_key])
                    while time.monotonic() < t0 + cut_s:
                        time.sleep(0.05)
                # Healed: the prober must re-admit through the
                # anti-entropy gate.
                deadline = time.monotonic() + 60
                while router.health.state(victim) != UP:
                    if time.monotonic() > deadline:
                        break
                    time.sleep(0.05)
                if router.health.state(victim) == UP:
                    up_recovered += 1
                digest = _pod_wire_digest(addr_of[victim], nb)
                for k, g in digest.items():
                    if g < seen_gens.get(k, 0):
                        digest_regressions += 1
                    seen_gens[k] = max(g, seen_gens.get(k, 0))
        finally:
            stop.set()
            for t in threads:
                t.join(60)
        soak_wall_s = time.monotonic() - t_soak0

        # Convergence: the victim holds the owners' generations for
        # every key the ring places on it.
        digest = _pod_wire_digest(addr_of[victim], nb)
        converged = all(
            digest.get(n) == gens[n] for n in sorted(bundles)
            if victim in {s.host_id
                          for s in ring.placement(n, replicas=1)})
        # The fence: a doctored OLD-generation frame at the victim.
        alphas = rng.integers(0, 256, (1, nb), dtype=np.uint8)
        betas = rng.integers(0, 256, (1, lam), dtype=np.uint8)
        doctored = dcf.gen(alphas, betas, rng=rng)
        fence_held = False
        with EdgeClient(*addr_of[victim], n_bytes=nb) as c:
            try:
                c.register_frame(victim_key, doctored.to_bytes(),
                                 generation=gens[victim_key])
            except StaleStateError:
                fence_held = True
        xs_post = rng.integers(0, 256, (16, nb), dtype=np.uint8)
        kb = bundles[victim_key]
        got = router.evaluate(victim_key, xs_post, b=0, timeout=300) ^ \
            router.evaluate(victim_key, xs_post, b=1, timeout=300)
        want = eval_batch_np(prg, 0, kb.for_party(0), xs_post) ^ \
            eval_batch_np(prg, 1, kb.for_party(1), xs_post)
        post_parity = bool(np.array_equal(got, want))
        log(f"soak: {stats}; down_seen={down_seen}/{cycles} "
            f"up_recovered={up_recovered}/{cycles} "
            f"converged={converged} fence_held={fence_held}")

        import jax

        platform = jax.devices()[0].platform
        rsnap = router.metrics_snapshot()
        # The denominator is the MEASURED soak wall time (cut windows
        # + heal waits), not --duration: the cut floor and the gated
        # re-admission stretch the run, and sessions/s must not be
        # inflated by a denominator the soak outlived.
        rate = stats["sessions_ok"] / max(soak_wall_s, 1e-9)
        extra = {
            "mode": mode,
            "shards": n_shards,
            "bundles": n_bundles,
            "live_bundles": len(live),
            "cycles": cycles,
            "cut_s": round(cut_s, 3),
            "soak_wall_s": round(soak_wall_s, 3),
            "probe_interval_s": args.probe_interval,
            "soak_sessions_ok": stats["sessions_ok"],
            "soak_critical_ok": stats["critical_ok"],
            "soak_mismatches": stats["mismatches"],
            "soak_refused_hinted": stats["refused_hinted"],
            "soak_refused_unhinted": stats["refused_unhinted"],
            "soak_unaccounted": stats["unaccounted"],
            "down_seen": down_seen,
            "up_recovered": up_recovered,
            "promoted_serve_s": [round(s, 3)
                                 for s in promoted_within],
            "digest_converged": converged,
            "digest_regressions": digest_regressions,
            "fence_held": fence_held,
            "post_heal_parity": post_parity,
            "anti_entropy_runs": rsnap.get(
                "router_anti_entropy_runs_total", 0),
            "anti_entropy_frames": rsnap.get(
                "router_anti_entropy_frames_total", 0),
            "promoted_forwards": rsnap.get(
                "router_promoted_forwards_total", 0),
            "platform": platform,
            "repro": (f"python -m dcf_tpu.cli pod_bench --{mode} "
                      f"--shards {n_shards} "
                      f"--duration {float(args.duration):g} "
                      f"--bundles {n_bundles} "
                      f"--live-bundles {args.live_bundles} "
                      f"--seed {args.seed}"),
        }
        unit = f"sessions/s ({mode} soak, two-party, mixed priority)"
        if platform != "tpu":
            unit += (" [no TPU this session: XLA-CPU interpret mode, "
                     "disclosed]")
        _emit("pod_bench", backend, "sessions_per_sec", rate, unit,
              extra_fields=extra)

        failures = []
        if stats["mismatches"] or stats["unaccounted"] \
                or stats["refused_unhinted"]:
            failures.append(
                f"ledger not clean: {stats['mismatches']} mismatches, "
                f"{stats['unaccounted']} untyped, "
                f"{stats['refused_unhinted']} unhinted refusals")
        if stats["sessions_ok"] < 3 or stats["critical_ok"] < 1:
            failures.append(
                f"soak delivered only {stats['sessions_ok']} sessions "
                f"({stats['critical_ok']} CRITICAL)")
        if down_seen < cycles:
            failures.append(
                f"prober marked the victim DOWN in only {down_seen} "
                f"of {cycles} cut windows")
        if up_recovered < cycles:
            failures.append(
                f"victim re-admitted UP after only {up_recovered} of "
                f"{cycles} heals")
        if len(promoted_within) < down_seen:
            failures.append(
                "a victim-owned key did not serve NORMAL traffic from "
                "its promoted replica during a cut window")
        elif promoted_within and max(promoted_within) > max(
                args.probe_interval, 1.0) + 2.0:
            failures.append(
                f"promoted replica took {max(promoted_within):.2f}s "
                "after DOWN (> one probe interval + slack)")
        if not converged:
            failures.append(
                "the victim's digest did not converge to the owners' "
                "generations after the heal")
        if digest_regressions:
            failures.append(
                f"{digest_regressions} generation regressions across "
                "cycles")
        if not fence_held:
            failures.append(
                "a doctored old-generation frame was NOT fenced")
        if not post_parity:
            failures.append(
                "the fenced key stopped serving the newer bits")
        if extra["anti_entropy_runs"] < cycles:
            failures.append(
                f"anti-entropy ran only {extra['anti_entropy_runs']} "
                f"times for {cycles} heals")
        if extra["anti_entropy_frames"] < cycles:
            failures.append(
                f"anti-entropy pulled only "
                f"{extra['anti_entropy_frames']} frames — the mid-cut "
                f"registration ({midcut_key}) did not converge "
                "through the digest exchange")
        if failures:
            raise SystemExit("pod_bench: " + "; ".join(failures))
    finally:
        if router is not None:
            try:
                router.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass
        for tag, (proc, _r, _m) in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for tag, (proc, _r, _m) in procs.items():
            try:
                proc.wait(15)
            except Exception:  # fallback-ok: a shard that ignores
                # SIGTERM gets the hard kill below
                proc.kill()
        if not keep_dirs:
            shutil.rmtree(root, ignore_errors=True)


def bench_pod_churn(args) -> None:
    """``pod_bench --churn`` (ISSUE 15): the autonomous-membership
    acceptance scenario — kill -> auto-eject -> re-replication
    verified -> heal -> graceful re-join, plus a drain leg, under
    3-thread mixed load with the ledger running throughout.

    Phases:

    1. **provision + spawn** — durable keys ring-placed into owner +
       replica stores (``KeyStore.replicate_to``), live keys through
       the REGISTER fan-out, N ``serve_host`` subprocesses behind the
       self-healing router with a ``MembershipController`` owning the
       ring (``stores`` handed over for the durable migration half);
    2. **kill -> auto-eject** — one shard SIGKILLed; the prober walks
       it DOWN, the controller waits out ``--eject-grace`` and ejects:
       the ring shrinks, the epoch bumps, and every key the victim
       held is re-replicated to its new placement BEFORE the commit —
       verified over the wire DIGEST verb (live registries) and the
       stores (durable frames), generations preserved;
    3. **heal -> graceful re-join** — the victim process is respawned
       on its own store (warm restore) and re-admitted via
       ``controller.join``: warmed through the anti-entropy SYNC path
       against the prospective ring first, the epoch bumps again, and
       its digest converges before the first routed request lands;
    4. **drain** — a second shard is gracefully decommissioned:
       frames migrated, ring swapped (epoch bump), in-flight grace
       held, then the process SIGTERMed — which now DRAINS and exits
       0 with its ready file removed (the ISSUE 15 satellite);
    5. **the epoch fence** — a doctored STALE-epoch REQUEST frame
       sent straight to a shard dies typed ``E_EPOCH`` with a retry
       hint, and the key keeps serving the CURRENT ring's bits.

    Emitted-then-asserted gates: ledger clean (every request bit-exact
    vs the numpy oracle or refused typed WITH ``retry_after_s``), zero
    generation regressions across every observed digest, zero lost
    keys (every key still serves bit-exact after all three changes),
    eject/join/drain all committed with strictly-increasing epochs,
    the stale-epoch frame fenced, zero quarantines, and the drained
    shard exited 0."""
    import os
    import shutil
    import signal
    import socket as socket_mod
    import struct as struct_mod
    import tempfile
    import threading

    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.errors import DcfError
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.serve import (
        DcfRouter,
        EdgeClient,
        MembershipController,
        ShardSpec,
    )
    from dcf_tpu.serve.edge import (
        E_EPOCH,
        decode_response,
        encode_request,
    )
    from dcf_tpu.serve.health import UP

    n_shards = args.shards
    if n_shards < 3:
        raise SystemExit(
            f"--churn needs --shards >= 3 (the auto-eject must leave "
            f"a replicated ring), got {n_shards}")
    if args.probe_interval <= 0:
        raise SystemExit(
            f"--probe-interval must be > 0, got {args.probe_interval}")
    if args.eject_grace <= 0:
        raise SystemExit(
            f"--eject-grace must be > 0, got {args.eject_grace}")
    if args.live_bundles < 0:
        raise SystemExit(
            f"--live-bundles must be >= 0, got {args.live_bundles}")
    dcf, lam, nb, backend, rng = _serve_host_facade(args)
    prg = HirosePrgNp(lam, dcf.cipher_keys)
    n_bundles = args.bundles or 4

    keep_dirs = bool(args.store_dir)
    root = args.store_dir or tempfile.mkdtemp(prefix="dcf-pod-")
    os.makedirs(root, exist_ok=True)
    shard_ids = [f"shard-{i}" for i in range(n_shards)]
    ring, stores, bundles, gens = _pod_provision(
        dcf, lam, nb, rng, root, shard_ids, n_bundles)
    procs: dict = {}
    router = None
    controller = None
    try:
        for tag in shard_ids:
            procs[tag] = _pod_spawn(tag, os.path.join(root, tag),
                                    root, args)
        ready = _pod_wait_ready(procs)
        pod_specs = [ShardSpec(s, ready[s]["host"], ready[s]["port"])
                     for s in shard_ids]
        addr_of = {s: (ready[s]["host"], ready[s]["port"])
                   for s in shard_ids}
        router = DcfRouter(
            pod_specs, n_bytes=nb,
            probe_interval_s=args.probe_interval,
            probe_timeout_s=5.0, probe_fail_n=3, probe_recover_m=2,
            reconnect_backoff_s=0.02,
            max_backoff_s=max(min(args.probe_interval, 0.5), 0.02))
        controller = MembershipController(
            router, stores=stores,
            eject_grace_s=float(args.eject_grace),
            drain_grace_s=1.0, min_hosts=2,
            poll_interval_s=min(args.probe_interval, 0.25))
        live, live_gens = _pod_live_register(
            router, dcf, rng, lam, nb, args.live_bundles)
        bundles.update(live)
        gens.update(live_gens)
        log(f"provisioned {n_bundles} durable + {len(live)} live keys "
            f"over {n_shards} shards")

        # Parity gate + warmup ladder (the soak must measure churn,
        # not the XLA compile storm).
        xs_gate = rng.integers(0, 256, (64, nb), dtype=np.uint8)
        for name, kb in bundles.items():
            got = router.evaluate(name, xs_gate, b=0, timeout=300) ^ \
                router.evaluate(name, xs_gate, b=1, timeout=300)
            want = eval_batch_np(prg, 0, kb.for_party(0), xs_gate) ^ \
                eval_batch_np(prg, 1, kb.for_party(1), xs_gate)
            if not np.array_equal(got, want):
                raise SystemExit(
                    f"pod_bench parity mismatch vs numpy oracle on "
                    f"{name}")
        owners = {n: ring.owner(n).host_id for n in bundles}
        by_owner: dict = {}
        for name, owner in owners.items():
            by_owner.setdefault(owner, []).append(name)
        max_batch = args.max_batch or (1 << 10)
        _pod_warmup(rng, nb, max_batch,
                    [(router, [keys[0] for keys in by_owner.values()])])
        log("routed parity + warmup ladder done")

        router.start_health()
        deadline = time.monotonic() + 60
        while any(st != UP for st in router.health.states().values()):
            if time.monotonic() > deadline:
                raise SystemExit(
                    f"pod_bench: prober never saw the pod UP "
                    f"({router.health.states()})")
            time.sleep(0.05)
        controller.start()

        # The ledger (accumulates across every phase).
        stats = {"ok": 0, "critical_ok": 0, "mismatches": 0,
                 "refused_hinted": 0, "refused_unhinted": 0,
                 "unaccounted": 0}
        lock = threading.Lock()
        stop = threading.Event()
        names_snapshot = sorted(bundles)

        def client(i: int) -> None:
            crng = np.random.default_rng(args.seed + 401 * i)
            while not stop.is_set():
                name = names_snapshot[
                    int(crng.integers(0, len(names_snapshot)))]
                pr = "critical" if crng.random() < 0.4 else "normal"
                m = int(crng.integers(1, 33))
                xs = crng.integers(0, 256, (m, nb), dtype=np.uint8)
                try:
                    f0 = router.submit(name, xs, b=0, priority=pr)
                    f1 = router.submit(name, xs, b=1, priority=pr)
                    got = f0.result(120) ^ f1.result(120)
                except DcfError as e:
                    hinted = getattr(e, "retry_after_s",
                                     None) is not None
                    with lock:
                        stats["refused_hinted" if hinted else
                              "refused_unhinted"] += 1
                    continue
                except Exception:  # fallback-ok: the gate's failure
                    # arm — anything untyped is what the soak hunts
                    with lock:
                        stats["unaccounted"] += 1
                    continue
                kb = bundles[name]
                want = eval_batch_np(prg, 0, kb.for_party(0), xs) ^ \
                    eval_batch_np(prg, 1, kb.for_party(1), xs)
                with lock:
                    if np.array_equal(got, want):
                        stats["ok"] += 1
                        if pr == "critical":
                            stats["critical_ok"] += 1
                    else:
                        stats["mismatches"] += 1

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True) for i in range(3)]
        t_soak0 = time.monotonic()
        for t in threads:
            t.start()

        seen_gens: dict = {}
        digest_regressions = 0

        def absorb_digest(digest: dict) -> None:
            nonlocal digest_regressions
            for k, g in digest.items():
                if g < seen_gens.get(k, 0):
                    digest_regressions += 1
                seen_gens[k] = max(g, seen_gens.get(k, 0))

        failures: list = []
        # ---- Phase 2: kill -> auto-eject ----------------------------
        victim = max(by_owner, key=lambda s: (
            len([n for n in by_owner[s] if n in live]),
            len(by_owner[s])))
        victim_keys = sorted(
            n for n in bundles
            if victim in ring.placement_ids(n, replicas=1))
        log(f"SIGKILL {victim} (holds {len(victim_keys)} keys); "
            f"auto-eject after {args.eject_grace:g}s of DOWN")
        procs[victim][0].send_signal(signal.SIGKILL)
        procs[victim][0].wait(30)  # reap before the respawn reuses
        # the procs slot (a SIGKILLed child exits immediately)
        deadline = time.monotonic() + 120
        while victim in router.map:
            if time.monotonic() > deadline:
                raise SystemExit(
                    "pod_bench: the controller never auto-ejected the "
                    f"killed shard (health={router.health.states()}, "
                    f"ring={router.map.host_ids()})")
            time.sleep(0.05)
        epoch_after_eject = router.ring_epoch
        eject_ring = router.map
        if epoch_after_eject < 1:
            failures.append("eject committed without an epoch bump")
        # Re-replication verified: every key the victim held is now
        # placed wholly on survivors — live registries over the wire
        # DIGEST verb, durable frames in the stores.
        survivor_digests = {s: _pod_wire_digest(addr_of[s], nb)
                            for s in eject_ring.host_ids()}
        for d in survivor_digests.values():
            absorb_digest(d)
        for name in victim_keys:
            placed = eject_ring.placement_ids(name, replicas=1)
            for holder in placed:
                if survivor_digests[holder].get(name) != gens[name]:
                    failures.append(
                        f"post-eject holder {holder} serves "
                        f"{name!r} at generation "
                        f"{survivor_digests[holder].get(name)} "
                        f"!= provisioned {gens[name]}")
                if name not in live \
                        and stores[holder].digest().get(name) \
                        != gens[name]:
                    failures.append(
                        f"post-eject store {holder} lacks durable "
                        f"{name!r} at generation {gens[name]}")
        lost = controller.lost_keys(exclude={victim})
        if lost:
            failures.append(f"keys lost after eject: {lost}")
        log(f"auto-eject OK: ring={eject_ring.host_ids()} "
            f"epoch={epoch_after_eject}")

        # ---- Phase 3: heal -> graceful re-join ----------------------
        try:
            os.unlink(procs[victim][1])  # the SIGKILL left the stale
            # ready file behind; the respawn must publish a fresh one
        except OSError:
            pass
        procs[victim] = _pod_spawn(victim, os.path.join(root, victim),
                                   root, args)
        rejoin_ready = _pod_wait_ready({victim: procs[victim]})
        spec = ShardSpec(victim, rejoin_ready[victim]["host"],
                         rejoin_ready[victim]["port"])
        addr_of[victim] = spec.address
        controller.join(spec)
        epoch_after_join = router.ring_epoch
        if epoch_after_join <= epoch_after_eject:
            failures.append("join committed without an epoch bump")
        if victim not in router.map:
            failures.append("join did not admit the healed shard")
        # Warmed-before-admitted: the rejoined shard's digest holds
        # every key the ring places on it, generations preserved.
        victim_digest = _pod_wire_digest(addr_of[victim], nb)
        absorb_digest(victim_digest)
        for name in sorted(bundles):
            placed = router.map.placement_ids(name, replicas=1)
            if victim in placed \
                    and victim_digest.get(name) != gens[name]:
                failures.append(
                    f"rejoined shard serves {name!r} at generation "
                    f"{victim_digest.get(name)} != {gens[name]}")
        log(f"graceful re-join OK: epoch={epoch_after_join}")

        # ---- Phase 4: drain ----------------------------------------
        drain_host = next(s for s in router.map.host_ids()
                          if s != victim)
        controller.drain(drain_host)
        epoch_after_drain = router.ring_epoch
        if epoch_after_drain <= epoch_after_join:
            failures.append("drain committed without an epoch bump")
        if drain_host in router.map:
            failures.append("drain left the host in the ring")
        deadline = time.monotonic() + 60
        while drain_host in controller.draining():
            if time.monotonic() > deadline:
                failures.append(
                    "the drain grace never completed (forget pending)")
                break
            time.sleep(0.05)
        drain_digests = {s: _pod_wire_digest(addr_of[s], nb)
                         for s in router.map.host_ids()}
        for d in drain_digests.values():
            absorb_digest(d)
        for name in sorted(bundles):
            placed = router.map.placement_ids(name, replicas=1)
            for holder in placed:
                if drain_digests[holder].get(name) != gens[name]:
                    failures.append(
                        f"post-drain holder {holder} serves {name!r} "
                        f"at {drain_digests[holder].get(name)} != "
                        f"{gens[name]}")
        lost = controller.lost_keys(exclude={drain_host})
        if lost:
            failures.append(f"keys lost after drain: {lost}")
        # The drained process: SIGTERM now DRAINS and exits 0 with the
        # ready file removed (the graceful-shutdown satellite).
        procs[drain_host][0].send_signal(signal.SIGTERM)
        try:
            rc = procs[drain_host][0].wait(60)
        except Exception:  # fallback-ok: counted via the gate below
            rc = None
        if rc != 0:
            failures.append(
                f"drained shard exited rc={rc} on SIGTERM (graceful "
                "shutdown must exit 0)")
        if os.path.exists(procs[drain_host][1]):
            failures.append(
                "drained shard left its ready file behind")
        log(f"drain OK: ring={router.map.host_ids()} "
            f"epoch={epoch_after_drain} drained-exit rc={rc}")

        # ---- Phase 5: the epoch fence ------------------------------
        fence_target = router.map.host_ids()[0]
        # Make sure the target has adopted the CURRENT epoch (probes
        # disseminate it; one fenced ping is deterministic).
        with EdgeClient(*addr_of[fence_target], n_bytes=nb) as c:
            shard_epoch = c.ping_epoch(timeout=60,
                                       epoch=router.ring_epoch)
            stale = max(router.ring_epoch - 1, 1)
            fence_key = next(n for n in sorted(bundles)
                             if router.map.owner(n).host_id
                             == fence_target)
            xs_f = rng.integers(0, 256, (4, nb), dtype=np.uint8)
            doctored = encode_request(
                991, "", fence_key, 0, 255, None, xs_f.data, nb,
                4, epoch=stale)
            s = socket_mod.create_connection(addr_of[fence_target],
                                             timeout=60)
            try:
                s.sendall(doctored)
                s.shutdown(socket_mod.SHUT_WR)
                s.settimeout(60)
                data = b""
                while True:
                    try:
                        chunk = s.recv(1 << 16)
                    except OSError:
                        break
                    if not chunk:
                        break
                    data += chunk
            finally:
                s.close()
            fence_held = False
            off = 0
            while off < len(data):
                (blen,) = struct_mod.unpack_from("<I", data, off)
                decoded = decode_response(data[off + 4:off + 4 + blen])
                if decoded[0] == "error" and decoded[2] == E_EPOCH \
                        and decoded[3] is not None:
                    fence_held = True
                off += 4 + blen
        if shard_epoch != router.ring_epoch:
            failures.append(
                f"shard epoch {shard_epoch} never converged to the "
                f"ring epoch {router.ring_epoch}")
        if not fence_held:
            failures.append(
                "a doctored stale-epoch frame was NOT refused E_EPOCH "
                "with a retry hint")
        # ...and the key keeps serving the CURRENT ring's bits.
        kb = bundles[fence_key]
        got = router.evaluate(fence_key, xs_f, b=0, timeout=300) ^ \
            router.evaluate(fence_key, xs_f, b=1, timeout=300)
        want = eval_batch_np(prg, 0, kb.for_party(0), xs_f) ^ \
            eval_batch_np(prg, 1, kb.for_party(1), xs_f)
        post_parity = bool(np.array_equal(got, want))
        if not post_parity:
            failures.append(
                "the fenced key stopped serving the current ring's "
                "bits")

        stop.set()
        for t in threads:
            t.join(60)
        soak_wall_s = time.monotonic() - t_soak0

        # Zero lost keys, globally: every key still serves bit-exact
        # on the final two-host ring.
        xs_post = rng.integers(0, 256, (8, nb), dtype=np.uint8)
        for name, kb in sorted(bundles.items()):
            got = router.evaluate(name, xs_post, b=0, timeout=300) ^ \
                router.evaluate(name, xs_post, b=1, timeout=300)
            want = eval_batch_np(prg, 0, kb.for_party(0), xs_post) ^ \
                eval_batch_np(prg, 1, kb.for_party(1), xs_post)
            if not np.array_equal(got, want):
                failures.append(
                    f"{name!r} no longer serves bit-exact after the "
                    "churn (lost or rolled back)")
        metric_files = [procs[s][2] for s in shard_ids]
        time.sleep(1.2)
        roll = _pod_rollup(metric_files)
        quarantined = roll.get("serve_store_quarantined_total", 0)
        kinds = [e.kind for e in controller.events()]

        import jax

        platform = jax.devices()[0].platform
        rsnap = router.metrics_snapshot()
        rate = stats["ok"] / max(soak_wall_s, 1e-9)
        extra = {
            "mode": "churn",
            "shards": n_shards,
            "bundles": n_bundles,
            "live_bundles": len(live),
            "eject_grace_s": float(args.eject_grace),
            "probe_interval_s": args.probe_interval,
            "soak_wall_s": round(soak_wall_s, 3),
            "soak_sessions_ok": stats["ok"],
            "soak_critical_ok": stats["critical_ok"],
            "soak_mismatches": stats["mismatches"],
            "soak_refused_hinted": stats["refused_hinted"],
            "soak_refused_unhinted": stats["refused_unhinted"],
            "soak_unaccounted": stats["unaccounted"],
            "epochs": [epoch_after_eject, epoch_after_join,
                       epoch_after_drain],
            "membership_events": kinds,
            "digest_regressions": digest_regressions,
            "fence_held": fence_held,
            "post_fence_parity": post_parity,
            "drained_exit_rc": rc,
            "migrated_frames": rsnap.get(
                "membership_migrated_frames_total", 0),
            "durable_replications": rsnap.get(
                "membership_durable_replications_total", 0),
            "lost_keys": rsnap.get("membership_lost_keys_total", 0),
            "pod_quarantined": quarantined,
            "platform": platform,
            "repro": (f"python -m dcf_tpu.cli pod_bench --churn "
                      f"--shards {n_shards} "
                      f"--bundles {n_bundles} "
                      f"--live-bundles {args.live_bundles} "
                      f"--eject-grace {float(args.eject_grace):g} "
                      f"--seed {args.seed}"),
        }
        unit = "sessions/s (churn soak, two-party, mixed priority)"
        if platform != "tpu":
            unit += (" [no TPU this session: XLA-CPU interpret mode, "
                     "disclosed]")
        _emit("pod_bench", backend, "sessions_per_sec", rate, unit,
              extra_fields=extra)

        if stats["mismatches"] or stats["unaccounted"] \
                or stats["refused_unhinted"]:
            failures.append(
                f"ledger not clean: {stats['mismatches']} mismatches, "
                f"{stats['unaccounted']} untyped, "
                f"{stats['refused_unhinted']} unhinted refusals")
        if stats["ok"] < 3 or stats["critical_ok"] < 1:
            failures.append(
                f"soak delivered only {stats['ok']} sessions "
                f"({stats['critical_ok']} CRITICAL)")
        if digest_regressions:
            failures.append(
                f"{digest_regressions} generation regressions across "
                "the churn")
        for want_kind in ("eject", "join", "drain", "drain-complete"):
            if want_kind not in kinds:
                failures.append(
                    f"no {want_kind!r} membership event was committed")
        if quarantined:
            failures.append(
                f"{quarantined} frames quarantined across the pod")
        if failures:
            raise SystemExit("pod_bench: " + "; ".join(failures))
    finally:
        if controller is not None:
            try:
                controller.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass
        if router is not None:
            try:
                router.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass
        for tag, (proc, _r, _m) in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for tag, (proc, _r, _m) in procs.items():
            try:
                proc.wait(15)
            except Exception:  # fallback-ok: a shard that ignores
                # SIGTERM gets the hard kill below
                proc.kill()
        if not keep_dirs:
            shutil.rmtree(root, ignore_errors=True)


def bench_pod_surge(args) -> None:
    """``pod_bench --surge`` (ISSUE 16): the demand-driven autoscaling
    acceptance scenario — a Zipf-skewed open-loop RAMP schedule drives
    the pod into sustained pressure, the ``CapacityController`` admits
    a ``serve_host --standby`` process through the graceful join
    within the reaction bound, the post-surge idle window drains the
    least-loaded host back to standby, and a scripted oscillating-load
    leg (the ``capacity.decide`` seam) is pinned to ZERO ring churn.

    Phases:

    1. **provision + spawn** — durable keys ring-placed into the
       ``--shards`` ring stores; ``--standby-hosts`` extra
       ``serve_host --standby`` processes come up provisioned-but-idle
       (no restore: the join's warm-before-admit ships keys when — if
       — demand admits them); every shard takes a SMALL admission
       bound (``--max-queued-points``, default 4096 here) so overload
       becomes visible demand within the bench window;
    2. **surge** — a seeded open-loop ramp (``ramp up -> hold at
       ~4x the calibrated closed-loop capacity -> fall quiet``) with
       Zipf key skew and a deadline on every request, while the main
       thread pumps the capacity controller on the injectable-clock
       tick (the deterministic driving mode — the same controller the
       ``start()`` worker would tick);
    3. **scale-out** — sustained pressure (queue fraction / brownout /
       shed deltas, aggregated via the metrics-rollup path) must admit
       a standby host within ``--reaction-bound`` seconds of the ramp
       start: epoch-fenced join, warm-before-admit;
    4. **scale-in** — the post-surge idle streak must drain the
       least-loaded host back to the standby pool (durable migration,
       deferred forget) once the cooldown clears;
    5. **oscillation** — a seam handler forces
       pressure/idle/pressure/idle... verdicts inside the hysteresis
       windows: the ring epoch must not move and zero scaling events
       may commit (the flap-damping acceptance).

    Emitted-then-asserted gates: scale-out within the reaction bound,
    scale-in committed (ring back to ``--shards``, standby pool
    refilled), zero lost keys, zero generation regressions across
    every observed digest, post-shrink two-party parity vs the numpy
    oracle on EVERY key, zero CRITICAL sheds across the pod rollup,
    strictly-increasing epochs across the scaling events, and the
    oscillation leg's zero-churn pin."""
    import os
    import shutil
    import tempfile
    import threading

    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.errors import DcfError
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.serve import (
        CapacityController,
        DcfRouter,
        KeyStore,
        MembershipController,
        ShardSpec,
    )
    from dcf_tpu.serve.capacity import IDLE, PRESSURE, ForcedVerdict
    from dcf_tpu.serve.health import UP
    from dcf_tpu.serve.loadgen import closed_loop, open_loop_ramp
    from dcf_tpu.serve.metrics import labeled
    from dcf_tpu.testing import faults

    n_shards = args.shards
    if n_shards < 2:
        raise SystemExit(
            f"--surge needs --shards >= 2 (scale-in must leave a "
            f"replicated ring), got {n_shards}")
    if args.standby_hosts < 1:
        raise SystemExit(
            f"--standby-hosts must be >= 1 (a surge with nothing to "
            f"admit gates nothing), got {args.standby_hosts}")
    if args.probe_interval <= 0:
        raise SystemExit(
            f"--probe-interval must be > 0, got {args.probe_interval}")
    if args.reaction_bound <= 0:
        raise SystemExit(
            f"--reaction-bound must be > 0, got {args.reaction_bound}")
    dcf, lam, nb, backend, rng = _serve_host_facade(args)
    prg = HirosePrgNp(lam, dcf.cipher_keys)
    n_bundles = args.bundles or 4
    n_standby = args.standby_hosts
    max_batch = args.max_batch or (1 << 10)
    min_req = args.min_req_points or (max_batch * 3 // 8)
    max_req = args.max_req_points or (max_batch // 2)
    if not 1 <= min_req <= max_req:
        raise SystemExit(
            f"bad request-size range [{min_req}, {max_req}]")
    qbound = args.max_queued_points or 4096
    skew = _parse_skew(args.skew) or 1.0

    keep_dirs = bool(args.store_dir)
    root = args.store_dir or tempfile.mkdtemp(prefix="dcf-pod-")
    os.makedirs(root, exist_ok=True)
    shard_ids = [f"shard-{i}" for i in range(n_shards)]
    standby_ids = [f"standby-{i}" for i in range(n_standby)]
    ring, stores, bundles, gens = _pod_provision(
        dcf, lam, nb, rng, root, shard_ids, n_bundles)
    for tag in standby_ids:
        # Provisioned-but-empty: the graceful join's warm-before-admit
        # migration fills it IF demand ever admits the host.
        stores[tag] = KeyStore(os.path.join(root, tag))
    procs: dict = {}
    router = None
    mc = None
    cap = None
    try:
        qflags = ["--max-queued-points", str(qbound)]
        for tag in shard_ids:
            procs[tag] = _pod_spawn(tag, os.path.join(root, tag),
                                    root, args, extra=qflags)
        for tag in standby_ids:
            procs[tag] = _pod_spawn(tag, os.path.join(root, tag),
                                    root, args, standby=True,
                                    extra=qflags)
        ready = _pod_wait_ready(procs)
        for tag in standby_ids:
            if not ready[tag].get("standby") \
                    or ready[tag].get("restored"):
                raise SystemExit(
                    f"pod_bench: {tag} did not come up as an empty "
                    f"standby host ({ready[tag]})")
        pod_specs = [ShardSpec(s, ready[s]["host"], ready[s]["port"])
                     for s in shard_ids]
        addr_of = {s: (ready[s]["host"], ready[s]["port"])
                   for s in [*shard_ids, *standby_ids]}
        # Condemnation-tolerant prober: the surge INTENDS to starve
        # the shards, and a shard walked DOWN mid-overload both kills
        # the demand signal (the router refuses its traffic) and trips
        # the eject_inflight rail — the scenario under test is
        # capacity, not death detection (that's --churn).
        router = DcfRouter(
            pod_specs, n_bytes=nb,
            probe_interval_s=args.probe_interval,
            probe_timeout_s=10.0, probe_fail_n=6, probe_recover_m=1,
            reconnect_backoff_s=0.02,
            max_backoff_s=max(min(args.probe_interval, 0.5), 0.02))
        mc = MembershipController(
            router, stores=stores,
            eject_grace_s=float(args.eject_grace),
            drain_grace_s=0.5, min_hosts=2,
            poll_interval_s=min(args.probe_interval, 0.25))
        tick = max(args.probe_interval, 0.25)
        cap = CapacityController(
            router, mc,
            standby=[(ShardSpec(t, ready[t]["host"], ready[t]["port"]),
                      stores[t]) for t in standby_ids],
            interval_s=tick, scale_out_n=2, scale_in_m=3,
            cooldown_s=max(2 * tick, 1.0),
            min_hosts=n_shards, max_hosts=n_shards + n_standby,
            queue_pressure_fraction=0.5, queue_idle_fraction=0.05)
        log(f"pod up: ring={n_shards} standby={n_standby} "
            f"queue-bound={qbound} pts; capacity tick={tick:g}s "
            f"n={cap.scale_out_n} m={cap.scale_in_m} "
            f"cooldown={cap.cooldown_s:g}s")

        # Parity gate + warmup ladder (the surge must measure the
        # controller, not the XLA compile storm).
        xs_gate = rng.integers(0, 256, (64, nb), dtype=np.uint8)
        for name, kb in bundles.items():
            got = router.evaluate(name, xs_gate, b=0, timeout=300) ^ \
                router.evaluate(name, xs_gate, b=1, timeout=300)
            want = eval_batch_np(prg, 0, kb.for_party(0), xs_gate) ^ \
                eval_batch_np(prg, 1, kb.for_party(1), xs_gate)
            if not np.array_equal(got, want):
                raise SystemExit(
                    f"pod_bench parity mismatch vs numpy oracle on "
                    f"{name}")
        by_owner: dict = {}
        for name in bundles:
            by_owner.setdefault(ring.owner(name).host_id,
                                []).append(name)
        _pod_warmup(rng, nb, max_batch,
                    [(router, [keys[0] for keys in by_owner.values()])])
        router.start_health()
        deadline = time.monotonic() + 60
        while any(st != UP for st in router.health.states().values()):
            if time.monotonic() > deadline:
                raise SystemExit(
                    f"pod_bench: prober never saw the pod UP "
                    f"({router.health.states()})")
            time.sleep(0.05)
        log("routed parity + warmup ladder done; prober UP")

        # Calibrate the pod's closed-loop capacity, then shape the
        # surge: ramp to 1.5x, hold at 4x (sustained pressure against
        # the small admission bound), fall quiet.
        cal = closed_loop(router, sorted(bundles),
                          duration_s=2.0, concurrency=args.concurrency,
                          min_points=min_req, max_points=max_req,
                          seed=args.seed + 3)
        base_rps = max(cal.requests_ok / max(cal.duration_s, 1e-9),
                       2.0)
        ramp_s = max(float(args.duration) / 5, 3.0)
        hold_s = max(float(args.duration) / 2, 6.0)
        segments = [(ramp_s, 1.5 * base_rps), (hold_s, 2.5 * base_rps),
                    (max(float(args.duration) / 6, 2.0), 0.0)]
        log(f"calibrated {base_rps:,.1f} req/s closed-loop; surge "
            f"schedule {[(round(d, 1), round(r, 1)) for d, r in segments]}")

        ramp_res: dict = {}

        def offer() -> None:
            # NORMAL/BATCH carry the overload: a CRITICAL request is
            # only ever shed when the queue holds too few lower-class
            # points to evict, so the zero-CRITICAL-shed gate is
            # exercised by the dedicated heartbeat stream below, not
            # by drowning CRITICAL in its own flood.
            ramp_res["res"] = open_loop_ramp(
                router, sorted(bundles), segments=segments,
                min_points=min_req, max_points=max_req,
                seed=args.seed + 17, skew=skew, deadline_ms=2000.0,
                priority_mix={"normal": 0.65, "batch": 0.35})

        ramp_thread = threading.Thread(target=offer, daemon=True,
                                       name="surge-ramp")
        cap_events: list = []
        hb_keys = sorted(bundles)
        hb_rng = np.random.default_rng(args.seed + 29)
        hb_futs: list = []
        hb_i = 0
        hb_refused_hinted = hb_refused_unhinted = 0
        t_surge0 = time.monotonic()
        ramp_thread.start()
        t_out = t_in = None
        # The elastic cycle: pump the controller (and the membership
        # poller) on the tick until the surge scaled out, the idle
        # window scaled back in, and the drain grace completed.
        cycle_deadline = t_surge0 + sum(d for d, _r in segments) + 240
        while time.monotonic() < cycle_deadline:
            cap.pump()
            mc.pump()
            cap_events += cap.events()
            if ramp_thread.is_alive():
                # The CRITICAL heartbeat: one small two-party session
                # per tick MUST ride out the surge — eviction clears
                # lower-class room for it, never the other way around.
                name = hb_keys[hb_i % len(hb_keys)]
                hb_i += 1
                xs_hb = hb_rng.integers(0, 256, (8, nb),
                                        dtype=np.uint8)
                try:
                    f0 = router.submit(name, xs_hb, b=0,
                                       priority="critical")
                    f1 = router.submit(name, xs_hb, b=1,
                                       priority="critical")
                    hb_futs.append((name, xs_hb, f0, f1))
                except DcfError as e:
                    if getattr(e, "retry_after_s", None) is not None:
                        hb_refused_hinted += 1
                    else:
                        hb_refused_unhinted += 1
            now = time.monotonic()
            if t_out is None and any(e.kind == "scale-out"
                                     for e in cap_events):
                t_out = now
                log(f"scale-out committed {now - t_surge0:,.1f}s into "
                    f"the surge (ring={router.map.host_ids()})")
            if t_in is None and any(e.kind == "scale-in"
                                    for e in cap_events):
                t_in = now
                log(f"scale-in committed {now - t_surge0:,.1f}s in "
                    f"(ring={router.map.host_ids()})")
            if t_out is not None and t_in is not None \
                    and not ramp_thread.is_alive() \
                    and not mc.draining():
                break
            time.sleep(tick)
        ramp_thread.join()
        res = ramp_res.get("res")
        if res is None:
            raise SystemExit("pod_bench: the surge schedule never "
                             "completed")
        cap_events += cap.events()
        reaction_s = (t_out - t_surge0) if t_out is not None else None
        drained_ids = {e.host_id for e in cap_events
                       if e.kind == "scale-in"}

        failures: list = []
        # Collect the CRITICAL heartbeats: every accepted one must
        # complete bit-exact vs the oracle, or fail typed WITH a hint
        # (a membership swap mid-flight is a hinted refusal, not a
        # loss); anything untyped fails the gate.
        hb_ok = hb_unaccounted = 0
        for name, xs_hb, f0, f1 in hb_futs:
            try:
                got = f0.result(120) ^ f1.result(120)
            except DcfError as e:
                if getattr(e, "retry_after_s", None) is not None:
                    hb_refused_hinted += 1
                else:
                    hb_refused_unhinted += 1
                continue
            except Exception:  # fallback-ok: the gate's failure arm —
                # an untyped CRITICAL loss is what the stream hunts
                hb_unaccounted += 1
                continue
            kb = bundles[name]
            want = eval_batch_np(prg, 0, kb.for_party(0), xs_hb) ^ \
                eval_batch_np(prg, 1, kb.for_party(1), xs_hb)
            if np.array_equal(got, want):
                hb_ok += 1
            else:
                hb_unaccounted += 1
        # Zero generation regressions + every placed holder serves the
        # provisioned generation, over the wire DIGEST verb.
        seen_gens = dict(gens)
        digest_regressions = 0
        for host_id in router.map.host_ids():
            digest = _pod_wire_digest(addr_of[host_id], nb)
            for k, g in digest.items():
                if g < seen_gens.get(k, 0):
                    digest_regressions += 1
                seen_gens[k] = max(g, seen_gens.get(k, 0))
            for name in sorted(bundles):
                if host_id in router.map.placement_ids(
                        name, replicas=1) \
                        and digest.get(name) != gens[name]:
                    failures.append(
                        f"post-cycle holder {host_id} serves {name!r} "
                        f"at generation {digest.get(name)} != "
                        f"provisioned {gens[name]}")
        lost = mc.lost_keys(exclude=drained_ids)

        # The oscillation leg: scripted pressure/idle flapping inside
        # the hysteresis windows must produce ZERO ring churn.
        osc_epoch0 = router.ring_epoch
        osc_n = {"n": 0}

        def osc(kind, verdict) -> None:
            osc_n["n"] += 1
            raise ForcedVerdict(
                PRESSURE if osc_n["n"] % 2 else IDLE)

        osc_ticks = 4 * max(cap.scale_out_n, cap.scale_in_m)
        with faults.inject("capacity.decide", handler=osc):
            for _ in range(osc_ticks):
                cap.pump()
        osc_events = cap.events()
        osc_epoch_moved = router.ring_epoch != osc_epoch0

        # Post-shrink parity: EVERY key, both parties, vs the oracle.
        xs_post = rng.integers(0, 256, (8, nb), dtype=np.uint8)
        post_parity = True
        for name, kb in sorted(bundles.items()):
            got = router.evaluate(name, xs_post, b=0, timeout=300) ^ \
                router.evaluate(name, xs_post, b=1, timeout=300)
            want = eval_batch_np(prg, 0, kb.for_party(0), xs_post) ^ \
                eval_batch_np(prg, 1, kb.for_party(1), xs_post)
            if not np.array_equal(got, want):
                post_parity = False
                failures.append(
                    f"{name!r} no longer serves bit-exact after the "
                    "elastic cycle (lost or rolled back)")
        metric_files = [procs[t][2] for t in [*shard_ids, *standby_ids]]
        time.sleep(1.2)
        roll = _pod_rollup(metric_files)
        critical_shed = roll.get(labeled(
            "serve_shed_by_class_total", priority="critical"), 0)

        import jax

        platform = jax.devices()[0].platform
        rsnap = router.metrics_snapshot()
        epochs = [e.epoch for e in cap_events]
        rate = res.points_ok / max(res.duration_s, 1e-9)
        extra = {
            "mode": "surge",
            "shards": n_shards,
            "standby_hosts": n_standby,
            "bundles": n_bundles,
            "max_queued_points": qbound,
            "skew": skew,
            "calibrated_rps": round(base_rps, 1),
            "segments": [[round(d, 2), round(r, 1)]
                         for d, r in segments],
            "offered_rps": round(res.offered_rps, 1),
            "sent": res.sent,
            "ok": res.ok,
            "shed": res.shed,
            "expired": res.expired,
            "failed": res.failed,
            "reaction_s": (None if reaction_s is None
                           else round(reaction_s, 2)),
            "reaction_bound_s": float(args.reaction_bound),
            "capacity_events": [[e.kind, e.host_id, e.epoch]
                                for e in cap_events],
            "epochs": epochs,
            "final_ring": router.map.host_ids(),
            "standby_after": cap.standby(),
            "osc_ticks": osc_ticks,
            "osc_events": len(osc_events),
            "osc_epoch_moved": osc_epoch_moved,
            "digest_regressions": digest_regressions,
            "lost_keys": len(lost),
            "post_shrink_parity": post_parity,
            "pod_critical_shed": critical_shed,
            "critical_hb_ok": hb_ok,
            "critical_hb_refused_hinted": hb_refused_hinted,
            "critical_hb_refused_unhinted": hb_refused_unhinted,
            "critical_hb_unaccounted": hb_unaccounted,
            "capacity_skips": {
                k.split("reason=", 1)[1].rstrip("}"): v
                for k, v in rsnap.items()
                if k.startswith("capacity_skips_total{")},
            "scale_failures": rsnap.get(
                "capacity_scale_failures_total", 0),
            "probe_interval_s": args.probe_interval,
            "platform": platform,
            "repro": (f"python -m dcf_tpu.cli pod_bench --surge "
                      f"--shards {n_shards} "
                      f"--standby-hosts {n_standby} "
                      f"--bundles {n_bundles} "
                      f"--duration {float(args.duration):g} "
                      f"--seed {args.seed}"),
        }
        unit = ("evals/s (open-loop Zipf surge through the pod "
                "router, party 0)")
        if platform != "tpu":
            unit += (" [no TPU this session: XLA-CPU interpret mode, "
                     "disclosed]")
        _emit("pod_bench", backend, "evals_per_sec", rate, unit,
              extra_fields=extra)

        # Emitted-then-asserted, chaos_bench style.
        if t_out is None:
            failures.append(
                "sustained pressure never admitted a standby host "
                f"(skips={extra['capacity_skips']})")
        elif reaction_s > float(args.reaction_bound):
            failures.append(
                f"scale-out took {reaction_s:.1f}s from the ramp "
                f"start (> the {float(args.reaction_bound):g}s "
                "reaction bound)")
        if t_in is None:
            failures.append(
                "the post-surge idle window never drained a host "
                "back to standby")
        if len(router.map) != n_shards:
            failures.append(
                f"the ring ended at {len(router.map)} hosts, not the "
                f"{n_shards} it started with")
        if len(cap.standby()) != n_standby:
            failures.append(
                f"the standby pool ended at {cap.standby()}, not "
                f"{n_standby} host(s)")
        if any(b <= a for a, b in zip(epochs, epochs[1:])):
            failures.append(
                f"scaling epochs not strictly increasing: {epochs}")
        if lost:
            failures.append(f"keys lost across the cycle: {lost}")
        if digest_regressions:
            failures.append(
                f"{digest_regressions} generation regressions across "
                "the cycle")
        if critical_shed:
            failures.append(
                f"{critical_shed} CRITICAL sheds across the pod "
                "(CRITICAL must ride out a surge)")
        if hb_ok < 1 or hb_unaccounted or hb_refused_unhinted:
            failures.append(
                f"CRITICAL heartbeat stream not clean through the "
                f"surge: {hb_ok} bit-exact, {hb_unaccounted} "
                f"unaccounted, {hb_refused_unhinted} refusals without "
                "retry_after_s")
        if osc_events or osc_epoch_moved:
            failures.append(
                f"the oscillating-load leg moved the ring: "
                f"{len(osc_events)} events, epoch_moved="
                f"{osc_epoch_moved} (flap damping failed)")
        if res.sent < 10:
            failures.append(
                f"the surge offered only {res.sent} requests (the "
                "schedule never stressed the pod)")
        if failures:
            raise SystemExit("pod_bench: " + "; ".join(failures))
    finally:
        if cap is not None:
            try:
                cap.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass
        if mc is not None:
            try:
                mc.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass
        if router is not None:
            try:
                router.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass
        for tag, (proc, _r, _m) in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for tag, (proc, _r, _m) in procs.items():
            try:
                proc.wait(15)
            except Exception:  # fallback-ok: a shard that ignores
                # SIGTERM gets the hard kill below
                proc.kill()
        if not keep_dirs:
            shutil.rmtree(root, ignore_errors=True)


def bench_pod_mesh(args) -> None:
    """``pod_bench --mesh`` (ISSUE 18): route-mode vs co-evaluate on
    the SAME pod, recording the dispatch crossover.

    Route-mode sends one batch to one host (its key's owner walks all
    ``m`` points); co-evaluate scatters the same batch's 32-aligned
    point slices over EVERY mesh worker through the zero-copy DCFE
    relay and gathers the share slices back in plan order — the wall
    clock for one big batch is the slowest slice, not the whole walk.
    The crossover batch size (where co-evaluate first beats route-mode)
    is what the router's ``co_eval_min_points`` threshold should be set
    to on a given pod, so this bench measures and EMITS it.

    Legs, in order:

    1. **provision** — ``--bundles`` two-party bundles written durably
       to EVERY shard's store (mesh-wide residency: a co-evaluated key
       must be resident on all workers; the live-registration twin is
       ``DcfRouter.register_mesh_key``, exercised in the mesh suite);
    2. **spawn** — ``--shards`` serve_host subprocesses warm-restore
       ALL keys; the parent builds a route-only router
       (``co_eval="never"``) and a mesh router (``co_eval="always"``,
       group formed over the full ring) over the identical pod;
    3. **parity gate** — every key, both parties: the co-evaluated
       reconstruction is bit-exact vs route-mode AND the numpy oracle
       (scatter/gather must be invisible in the bytes);
    4. **crossover ladder** — interleaved route/co-eval segments (one
       ``--reps``-sampled leg pair per rung) over a geometric batch
       ladder; per rung the median single-batch wall time becomes
       evals/s per mode, and the crossover is the smallest rung where
       co-evaluate wins;
    5. **health check** — zero ``router_mesh_degraded_total`` (a
       degrade mid-bench means the ladder silently measured route-mode
       twice), co_evals accounted.

    The crossover gate applies only when the host offers the pod
    parallelism co-evaluation exists to exploit (>= shards + 1 CPUs);
    on a smaller host the measured ladder is EMITTED with the gate
    recorded environment-gated (the PR 3 floor-entry discipline — a
    1-core container must not "pass" or "fail" a parallel-speedup
    claim it cannot test).  Emits one ``RESULTS_mesh`` JSONL line."""
    import os
    import shutil
    import statistics
    import tempfile

    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.serve import DcfRouter, ShardSpec

    n_shards = args.shards
    if n_shards < 2:
        raise SystemExit(
            f"--mesh needs --shards >= 2 (co-evaluating over one "
            f"worker IS route-mode), got {n_shards}")
    dcf, lam, nb, backend, rng = _serve_host_facade(args)
    prg = HirosePrgNp(lam, dcf.cipher_keys)
    n_bundles = args.bundles or 4
    max_batch = args.max_batch or (1 << 10)
    base = args.min_req_points or 128
    top = args.max_req_points or (1 << 13)
    if not 1 <= base <= top:
        raise SystemExit(f"bad ladder range [{base}, {top}]")
    reps = max(args.reps, 3)

    keep_dirs = bool(args.store_dir)
    root = args.store_dir or tempfile.mkdtemp(prefix="dcf-pod-")
    os.makedirs(root, exist_ok=True)
    shard_ids = [f"shard-{i}" for i in range(n_shards)]

    # Leg 1: provision, then replicate every key to EVERY shard.
    ring, stores, bundles, gens = _pod_provision(
        dcf, lam, nb, rng, root, shard_ids, n_bundles)
    for name in bundles:
        placed = {s.host_id for s in ring.placement(name, replicas=1)}
        owner = ring.owner(name).host_id
        for tag in shard_ids:
            if tag not in placed:
                stores[owner].replicate_to(stores[tag], name)
    log(f"provisioned {n_bundles} keys mesh-wide "
        f"(every key on all {n_shards} shards)")

    procs: dict = {}
    routers: list = []
    try:
        for tag in shard_ids:
            procs[tag] = _pod_spawn(tag, os.path.join(root, tag),
                                    root, args)
        ready = _pod_wait_ready(procs)
        for tag, doc in ready.items():
            if doc["restored"] != n_bundles or doc["quarantined"]:
                raise SystemExit(
                    f"pod_bench --mesh: shard {tag} restored "
                    f"{doc['restored']}/{n_bundles} keys "
                    f"({doc['quarantined']} quarantined)")
        pod_specs = [ShardSpec(s, ready[s]["host"], ready[s]["port"])
                     for s in shard_ids]
        route_router = DcfRouter(pod_specs, n_bytes=nb,
                                 co_eval="never")
        mesh_router = DcfRouter(pod_specs, n_bytes=nb,
                                co_eval="always")
        mesh_router.set_mesh()
        routers = [route_router, mesh_router]

        # Leg 3: parity gate (both parties, both modes, numpy oracle).
        xs_gate = rng.integers(0, 256, (3 * 32 + 7, nb), dtype=np.uint8)
        for name, kb in bundles.items():
            via_mesh = mesh_router.evaluate(name, xs_gate, b=0,
                                            timeout=300) \
                ^ mesh_router.evaluate(name, xs_gate, b=1, timeout=300)
            via_route = route_router.evaluate(name, xs_gate, b=0,
                                              timeout=300) \
                ^ route_router.evaluate(name, xs_gate, b=1, timeout=300)
            want = eval_batch_np(prg, 0, kb.for_party(0), xs_gate) \
                ^ eval_batch_np(prg, 1, kb.for_party(1), xs_gate)
            if not np.array_equal(via_mesh, want):
                raise SystemExit(
                    f"pod_bench --mesh: co-evaluated parity mismatch "
                    f"vs numpy oracle on {name}")
            if not np.array_equal(via_route, want):
                raise SystemExit(
                    f"pod_bench --mesh: route-mode parity mismatch "
                    f"vs numpy oracle on {name}")
        log(f"co-evaluated parity vs route-mode + numpy oracle: OK "
            f"({n_bundles} keys x {xs_gate.shape[0]} pts, two-party)")

        # Warm every padded batch shape on every worker, both dispatch
        # modes, up to the ladder top (compile storms stay out of the
        # timed region).
        rungs = []
        m = base
        while m < top:
            rungs.append(m)
            m *= 4
        rungs.append(top)
        key0 = sorted(bundles)[0]
        _pod_warmup(rng, nb, top,
                    [(route_router,
                      [names[0] for names in _group_by_owner(
                          ring, bundles).values()]),
                     (mesh_router, [key0])])
        log(f"warmup ladder done (route + co-eval, top={top})")

        # Leg 4: the crossover ladder — interleaved route/co-eval
        # segments per rung, median single-batch wall time.
        ladder = []
        crossover = None
        for m in rungs:
            xs_m = rng.integers(0, 256, (m, nb), dtype=np.uint8)
            times: dict = {"route": [], "coeval": []}
            for rep in range(reps):
                for leg, target in (("route", route_router),
                                    ("coeval", mesh_router)):
                    name = sorted(bundles)[rep % n_bundles]
                    t0 = time.monotonic()
                    target.evaluate(name, xs_m, b=0, timeout=300)
                    times[leg].append(time.monotonic() - t0)
            route_rate = m / statistics.median(times["route"])
            coeval_rate = m / statistics.median(times["coeval"])
            ladder.append({"points": m,
                           "route_evals_per_sec": round(route_rate, 1),
                           "coeval_evals_per_sec": round(coeval_rate,
                                                         1)})
            if crossover is None and coeval_rate >= route_rate:
                crossover = m
            log(f"ladder m={m}: route {route_rate:,.1f} vs co-eval "
                f"{coeval_rate:,.1f} evals/s")

        top_rung = ladder[-1]
        coeval_vs_route = (top_rung["coeval_evals_per_sec"]
                           / max(top_rung["route_evals_per_sec"], 1e-9))
        cpus = len(os.sched_getaffinity(0))
        gate_applies = cpus >= n_shards + 1
        snap = mesh_router.metrics_snapshot()
        co_evals = snap.get("router_co_evals_total", 0)
        degraded = snap.get("router_mesh_degraded_total", 0)
        log(f"crossover: {crossover} pts "
            f"(coeval_vs_route@top={coeval_vs_route:.3f}, cpus={cpus}, "
            f"gate {'applies' if gate_applies else 'environment-gated'})")

        import jax

        platform = jax.devices()[0].platform
        extra = {
            "mode": "mesh",
            "shards": n_shards,
            "bundles": n_bundles,
            "mesh_workers": len(mesh_router.mesh_group),
            "ladder": ladder,
            "crossover_points": crossover,
            "coeval_vs_route_at_top": round(coeval_vs_route, 3),
            "co_evals": co_evals,
            "mesh_degraded": degraded,
            "reps": reps,
            "max_batch": max_batch,
            "crossover_gate": (
                "applies (co-evaluate must win by the top rung)"
                if gate_applies else
                f"environment-gated: {cpus} CPU(s) visible for "
                f"{n_shards} shard processes + router — the scattered "
                "slices serialize onto the same core, so co-evaluate "
                "pays its relay overhead with no parallel payback; "
                f"the committed repro on a >= {n_shards + 1}-core "
                "host (or a chip) is the gate"),
            "platform": platform,
            "repro": (f"python -m dcf_tpu.cli pod_bench --mesh "
                      f"--shards {n_shards} "
                      f"--bundles {n_bundles} --reps {reps} "
                      f"--max-req-points {top} --seed {args.seed}"),
        }
        extra.update(_serve_pinned_ratio(
            top_rung["coeval_evals_per_sec"], platform))
        unit = ("evals/s (one co-evaluated batch spanning every "
                "worker, top rung, party 0)")
        if platform != "tpu":
            unit += (" [no TPU this session: XLA-CPU interpret mode, "
                     "disclosed]")
        _emit("pod_bench", backend, "evals_per_sec",
              top_rung["coeval_evals_per_sec"], unit,
              extra_fields=extra)

        # Emitted-then-asserted.  Warmup co-evals ride on top of the
        # accounted ones, so the counter may only disagree upward.
        failures = []
        if co_evals < 2 * n_bundles + len(rungs) * reps:
            failures.append(
                f"router_co_evals_total={co_evals} does not cover the "
                f"{2 * n_bundles + len(rungs) * reps} accounted "
                "co-evaluated dispatches")
        if degraded:
            failures.append(
                f"{degraded} co-evaluations degraded to route-mode "
                "mid-bench (the ladder measured route twice)")
        if gate_applies and crossover is None:
            failures.append(
                f"co-evaluate never beat route-mode by the top rung "
                f"({top} pts) with {cpus} CPUs for {n_shards} workers")
        if failures:
            raise SystemExit("pod_bench --mesh: " + "; ".join(failures))
    finally:
        for target in routers:
            try:
                target.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass
        for tag, (proc, _r, _m) in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for tag, (proc, _r, _m) in procs.items():
            try:
                proc.wait(15)
            except Exception:  # fallback-ok: a shard that ignores
                # SIGTERM gets the hard kill below
                proc.kill()
        if not keep_dirs:
            shutil.rmtree(root, ignore_errors=True)


def _group_by_owner(ring, bundles) -> dict:
    """{owner_host_id: [key, ...]} over the ring's placements."""
    by_owner: dict = {}
    for name in bundles:
        by_owner.setdefault(ring.owner(name).host_id, []).append(name)
    return by_owner


def bench_pod(args) -> None:
    """The pod-scale serving acceptance bench (ISSUE 13): N localhost
    shard PROCESSES behind the zero-copy DCFE router, vs the same
    workload on one shard, at the same shape/seeds.

    Legs, in order:

    1. **provision** — ``--bundles`` two-party bundles placed by the
       rendezvous ring; each key's DCFK frame is written durably to
       its owner's store and replicated to its replica's
       (``KeyStore.replicate_to``, generations preserved), plus ALL
       keys into a solo host's store (the single-shard leg);
    2. **spawn** — ``--shards`` + 1 ``serve_host`` subprocesses warm-
       restore their stores and listen; the parent builds one pod
       router (N-ring) and one solo router (1-ring) so BOTH legs run
       the identical two-hop wire path;
    3. **routed parity gate** — every key, both parties, through the
       pod router, bit-exact vs the numpy oracle;
    4. **throughput** — interleaved closed-loop segments (3 per leg,
       shared seeds) solo vs pod; the headline is the pod leg, the
       gate is ``pod_vs_single >= 2.2`` — applied when the host
       actually offers the pod parallelism (>= shards+1 CPUs); on a
       smaller host the measured ratio is EMITTED with the gate
       recorded environment-gated and the committed repro is the
       multi-core/chip falsification (the PR 3 floor-entry
       discipline: never let a 1-core container "pass" a scaling
       claim it cannot test);
    5. **open-loop reconciliation** — a Poisson leg whose
       sent/expired/per-class-shed counts reconcile against the POD
       rollup (``loadgen.reconcile_against_rollup`` over the summed
       per-host snapshots — the ISSUE 13 small fix: one service's
       metrics no longer see a pod's traffic);
    6. **kill-a-shard failover soak** — one shard SIGKILLed mid-load;
       every request completes bit-exact or is refused typed WITH
       ``retry_after_s``; afterwards every key the victim owned still
       serves CRITICAL traffic bit-exact from its replica, the
       replica store holds the provisioned generations, and the pod
       rollup shows ZERO quarantines.

    ISSUE 14 upgrades: ``--live-bundles`` NON-durable keys are
    registered through the router's REGISTER fan-out on top of the
    durable ones, the health prober runs through every leg, and the
    kill soak additionally gates that the victim's live keys serve
    CRITICAL bit-exact from the promoted replica within about one
    probe interval of the SIGKILL — zero re-keygen, generations
    preserved on the replica's live registry (checked over the wire
    via the DIGEST verb).  ``--partition`` / ``--flap`` run the
    partition-tolerance scenario instead (``bench_pod_selfheal``).

    Emits one ``RESULTS_pod`` JSONL line (platform disclosed in-line),
    then applies the exit gates.

    ISSUE 15: ``--churn`` runs the autonomous-membership scenario
    instead (``bench_pod_churn``) — kill -> auto-eject ->
    re-replication verified -> heal -> graceful re-join, plus a drain
    leg and the stale-epoch fence.

    ISSUE 16: ``--surge`` runs the demand-driven autoscaling scenario
    instead (``bench_pod_surge``) — an open-loop Zipf ramp drives
    scale-out from a standby pool within the reaction bound, the idle
    tail drains back, and an oscillating-load leg pins zero churn.

    ISSUE 18: ``--mesh`` runs the co-evaluation crossover scenario
    instead (``bench_pod_mesh``) — route-mode vs one batch scattered
    over every worker, on the same pod, recording the dispatch
    crossover batch size."""
    if getattr(args, "mesh", ""):
        if args.surge or args.churn or args.partition or args.flap:
            raise SystemExit(
                "--mesh and --surge/--churn/--partition/--flap are "
                "separate scenarios; pick one")
        return bench_pod_mesh(args)
    if args.surge:
        if args.churn or args.partition or args.flap:
            raise SystemExit(
                "--surge and --churn/--partition/--flap are separate "
                "scenarios; pick one")
        return bench_pod_surge(args)
    if args.churn:
        if args.partition or args.flap:
            raise SystemExit(
                "--churn and --partition/--flap are separate "
                "scenarios; pick one")
        return bench_pod_churn(args)
    if args.partition or args.flap:
        return bench_pod_selfheal(args)

    import os
    import shutil
    import signal
    import tempfile

    from dcf_tpu.backends.numpy_backend import eval_batch_np
    from dcf_tpu.ops.prg import HirosePrgNp
    from dcf_tpu.serve import DcfRouter, ShardSpec
    from dcf_tpu.serve.loadgen import (
        closed_loop,
        open_loop,
        reconcile_against_rollup,
    )

    n_shards = args.shards
    if n_shards < 2:
        raise SystemExit(
            f"--shards must be >= 2 (a pod of one is the solo leg), "
            f"got {n_shards}")
    if args.probe_interval <= 0:
        raise SystemExit(
            f"--probe-interval must be > 0, got {args.probe_interval}")
    if args.live_bundles < 0:
        raise SystemExit(
            f"--live-bundles must be >= 0, got {args.live_bundles}")
    dcf, lam, nb, backend, rng = _serve_host_facade(args)
    prg = HirosePrgNp(lam, dcf.cipher_keys)
    max_batch = args.max_batch or (1 << 10)
    min_req = args.min_req_points or (max_batch * 3 // 8)
    max_req = args.max_req_points or (max_batch // 2)
    if not 1 <= min_req <= max_req:
        raise SystemExit(
            f"bad request-size range [{min_req}, {max_req}]")
    n_bundles = args.bundles or 8
    conns = args.concurrency

    keep_dirs = bool(args.store_dir)
    root = args.store_dir or tempfile.mkdtemp(prefix="dcf-pod-")
    os.makedirs(root, exist_ok=True)
    shard_ids = [f"shard-{i}" for i in range(n_shards)]

    # Leg 1: provision (the shared block — ``solo`` adds the
    # single-shard comparison store holding everything).
    ring, stores, bundles, gens = _pod_provision(
        dcf, lam, nb, rng, root, shard_ids, n_bundles, solo=True)
    owners = {n: ring.owner(n).host_id for n in bundles}
    by_owner: dict = {}
    for name, owner in owners.items():
        by_owner.setdefault(owner, []).append(name)
    log(f"provisioned {n_bundles} keys over {n_shards} shards "
        f"(+ solo): " + ", ".join(
            f"{s}:{len(by_owner.get(s, []))}" for s in shard_ids))

    # Leg 2: spawn the shard processes.
    procs: dict = {}
    routers: list = []
    try:
        for tag in [*shard_ids, "solo"]:
            procs[tag] = _pod_spawn(tag, os.path.join(root, tag),
                                    root, args)
        ready = _pod_wait_ready(procs)
        for tag, doc in ready.items():
            want = n_bundles if tag == "solo" else len(
                {k for k in bundles
                 if tag in {s.host_id
                            for s in ring.placement(k, replicas=1)}})
            if doc["restored"] != want or doc["quarantined"]:
                raise SystemExit(
                    f"pod_bench: shard {tag} restored "
                    f"{doc['restored']}/{want} keys "
                    f"({doc['quarantined']} quarantined)")
        pod_specs = [ShardSpec(s, ready[s]["host"], ready[s]["port"])
                     for s in shard_ids]
        addr_of = {s: (ready[s]["host"], ready[s]["port"])
                   for s in shard_ids}
        router = DcfRouter(pod_specs, n_bytes=nb,
                           probe_interval_s=args.probe_interval,
                           probe_timeout_s=5.0,
                           probe_fail_n=3, probe_recover_m=2,
                           max_backoff_s=max(
                               min(args.probe_interval, 0.5), 0.05))
        solo = DcfRouter(
            [ShardSpec("solo", ready["solo"]["host"],
                       ready["solo"]["port"])], n_bytes=nb)
        routers = [router, solo]

        # ISSUE 14: live (NON-durable) keys through the REGISTER
        # fan-out — owner mints, replica applies, generations
        # preserved; registered on the solo ring too so both
        # throughput legs serve the identical key set.
        live, live_gens = _pod_live_register(
            router, dcf, rng, lam, nb, args.live_bundles)
        for name, kb in live.items():
            solo.register_key(name, kb)
        bundles.update(live)
        for name in live:
            owners[name] = ring.owner(name).host_id
            by_owner.setdefault(owners[name], []).append(name)
        log(f"registered {len(live)} live (non-durable) keys through "
            "the router fan-out")

        # Leg 3: routed parity gate (both parties, numpy oracle).
        xs_gate = rng.integers(0, 256, (128, nb), dtype=np.uint8)
        for name, kb in bundles.items():
            for target in (router, solo):
                got = target.evaluate(name, xs_gate, b=0, timeout=300) \
                    ^ target.evaluate(name, xs_gate, b=1, timeout=300)
                want = eval_batch_np(prg, 0, kb.for_party(0), xs_gate) \
                    ^ eval_batch_np(prg, 1, kb.for_party(1), xs_gate)
                if not np.array_equal(got, want):
                    raise SystemExit(
                        f"pod_bench parity mismatch vs numpy oracle "
                        f"on {name} via "
                        f"{'pod' if target is router else 'solo'}")
        log(f"routed parity vs numpy oracle: OK ({n_bundles} keys x "
            "128 pts, two-party, pod + solo)")

        _pod_warmup(rng, nb, max_batch,
                    [(router, [names[0]
                               for names in by_owner.values()]),
                     (solo, ["key-0"])])
        log("warmup ladder done (all shards + solo, both parties)")
        router.start_health()  # the control plane runs from here on

        # Leg 4: interleaved solo vs pod closed-loop segments.
        segs = 3
        seg_s = max(float(args.duration) / (2 * segs), 1.0)
        runs: dict = {"solo": [], "pod": []}
        for i in range(2 * segs):
            leg = "solo" if i % 2 == 0 else "pod"
            res = closed_loop(
                solo if leg == "solo" else router, sorted(bundles),
                duration_s=seg_s, concurrency=conns,
                min_points=min_req, max_points=max_req,
                seed=args.seed + i // 2)
            runs[leg].append(res)
        res_solo = _merge_loadgen(runs["solo"])
        res_pod = _merge_loadgen(runs["pod"])
        pod_vs_single = res_pod.throughput / max(res_solo.throughput,
                                                 1e-9)
        cpus = len(os.sched_getaffinity(0))
        gate_applies = cpus >= n_shards + 1
        log(f"throughput: pod {res_pod.throughput:,.1f} vs solo "
            f"{res_solo.throughput:,.1f} evals/s "
            f"(pod_vs_single={pod_vs_single:.3f}, cpus={cpus}, "
            f"gate {'applies' if gate_applies else 'environment-gated'})")

        # Leg 5: open-loop reconciliation against the POD rollup.
        metric_files = [procs[s][2] for s in shard_ids]
        time.sleep(1.2)  # quiesce past a metrics-flush interval
        roll_before = _pod_rollup(metric_files)
        open_rate = max(
            0.6 * res_pod.requests_ok / max(res_pod.duration_s, 1e-9),
            1.0)
        res_open = open_loop(
            router, sorted(bundles), rate_rps=open_rate,
            duration_s=min(float(args.duration) / 3, 10.0),
            min_points=min_req, max_points=max_req,
            seed=args.seed + 17)
        time.sleep(1.2)
        roll_after = _pod_rollup(metric_files)
        recon = reconcile_against_rollup(res_open, roll_before,
                                         roll_after)
        log(f"open-loop @ {open_rate:,.1f} req/s: ok={res_open.ok} "
            f"shed={res_open.shed} expired={res_open.expired} "
            f"pod-reconciled={recon['reconciled']}")

        # Leg 6: kill-a-shard failover soak.  The victim owns keys —
        # preferring a shard that owns LIVE (non-durable) ones, whose
        # survival on the replica is the ISSUE 14 acceptance — and its
        # replicas must pick CRITICAL traffic up.
        victim = max(by_owner, key=lambda s: (
            len([n for n in by_owner[s] if n in live]),
            len(by_owner[s])))
        victim_keys = sorted(by_owner[victim])
        victim_live_keys = sorted(n for n in victim_keys if n in live)
        kill_stats: dict = {"critical_within_s": None}
        xs_kill = rng.integers(0, 256, (8, nb), dtype=np.uint8)

        def kill_victim() -> None:
            log(f"soak: SIGKILL {victim} (owner of "
                f"{len(victim_keys)} keys, {len(victim_live_keys)} "
                "live)")
            procs[victim][0].send_signal(signal.SIGKILL)
            if not victim_live_keys:
                return
            # ISSUE 14 acceptance: CRITICAL traffic for a victim-owned
            # NON-durable key serves bit-exact from the replica within
            # about one probe interval of the kill (per-request
            # failover does not even wait for the prober's DOWN).
            name = victim_live_keys[0]
            t0 = time.monotonic()
            while time.monotonic() - t0 < 30:
                try:
                    got = router.evaluate(name, xs_kill, b=0,
                                          timeout=60,
                                          priority="critical") ^ \
                        router.evaluate(name, xs_kill, b=1,
                                        timeout=60,
                                        priority="critical")
                except Exception:  # fallback-ok: the window between
                    # SIGKILL landing and the replica serving IS the
                    # measurement — keep trying until the deadline
                    time.sleep(0.02)
                    continue
                kb = live[name]
                want = eval_batch_np(prg, 0, kb.for_party(0),
                                     xs_kill) ^ \
                    eval_batch_np(prg, 1, kb.for_party(1), xs_kill)
                if np.array_equal(got, want):
                    kill_stats["critical_within_s"] = \
                        time.monotonic() - t0
                return

        soak_s = max(float(args.duration) / 4, 4.0)
        soak = _pod_soak(router, bundles, prg, nb,
                         duration_s=soak_s, conns=max(conns, 4),
                         seed=args.seed, kill_after_s=soak_s / 3,
                         kill_fn=kill_victim)
        log(f"soak: {soak} critical_within_s="
            f"{kill_stats['critical_within_s']}")

        # Post-soak: every victim-owned key still serves CRITICAL
        # bit-exact from its replica; durable keys' replica STORES
        # hold the provisioned generation, live keys' replica LIVE
        # registries hold the owner-minted one (checked over the wire
        # — zero re-keygen either way: the parity proves the replica
        # serves the same pre-minted bits).
        failover_parity = True
        generations_held = True
        # By now the prober has marked the victim DOWN, so NORMAL
        # traffic is served via promotion too — exercised below.
        deadline = time.monotonic() + 60
        while router.health.state(victim) != "down" \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        down_observed = router.health.state(victim) == "down"
        xs_post = rng.integers(0, 256, (16, nb), dtype=np.uint8)
        rep_digests: dict = {}
        for name in victim_keys:
            kb = bundles[name]
            pr = "critical" if name not in live else "normal"
            got = router.evaluate(name, xs_post, b=0, timeout=300,
                                  priority=pr) \
                ^ router.evaluate(name, xs_post, b=1, timeout=300,
                                  priority=pr)
            want = eval_batch_np(prg, 0, kb.for_party(0), xs_post) ^ \
                eval_batch_np(prg, 1, kb.for_party(1), xs_post)
            failover_parity &= bool(np.array_equal(got, want))
            rep = next(s.host_id
                       for s in ring.placement(name, replicas=1)[1:])
            if name in live:
                if rep not in rep_digests:
                    rep_digests[rep] = _pod_wire_digest(addr_of[rep],
                                                        nb)
                generations_held &= (
                    rep_digests[rep].get(name) == live_gens[name])
            else:
                generations_held &= (
                    stores[rep].generation_of(name) == gens[name])
        log(f"post-kill: replica parity={failover_parity}, "
            f"generations_held={generations_held}, "
            f"down_observed={down_observed}")
        time.sleep(1.2)
        roll_final = _pod_rollup(metric_files)
        quarantined = roll_final.get("serve_store_quarantined_total", 0)

        import jax

        platform = jax.devices()[0].platform
        rsnap = router.metrics_snapshot()
        extra = {
            "shards": n_shards,
            "bundles": n_bundles,
            "duration_s": round(res_pod.duration_s
                                + res_solo.duration_s, 3),
            "max_batch": max_batch,
            "req_points": [min_req, max_req],
            "concurrency": conns,
            "segments_per_leg": segs,
            "single_shard_evals_per_sec": round(res_solo.throughput, 1),
            "pod_vs_single": round(pod_vs_single, 3),
            "throughput_gate": (
                "applies (>= 2.2x required)" if gate_applies else
                f"environment-gated: {cpus} CPU(s) visible for "
                f"{n_shards} shard processes + router — aggregate "
                "CPU throughput cannot exceed 1x here; the committed "
                "repro on a >= "
                f"{n_shards + 1}-core host (or a chip) is the gate"),
            **res_pod.latency_quantiles(),
            "open_loop_rate_rps": round(open_rate, 1),
            "open_loop_ok": res_open.ok,
            "open_loop_pod_reconciled": recon["reconciled"],
            "soak_sessions_ok": soak["sessions_ok"],
            "soak_critical_ok": soak["critical_ok"],
            "soak_mismatches": soak["mismatches"],
            "soak_refused_hinted": soak["refused_hinted"],
            "soak_refused_unhinted": soak["refused_unhinted"],
            "soak_unaccounted": soak["unaccounted"],
            "failover_parity": failover_parity,
            "generations_held": generations_held,
            "live_bundles": len(live),
            "victim_live_keys": len(victim_live_keys),
            "critical_within_s": (
                None if kill_stats["critical_within_s"] is None
                else round(kill_stats["critical_within_s"], 3)),
            "probe_interval_s": args.probe_interval,
            "down_observed": down_observed,
            "promoted_forwards": rsnap.get(
                "router_promoted_forwards_total", 0),
            "pod_quarantined": quarantined,
            "router_failovers": rsnap.get("router_failovers_total", 0),
            "router_suspect_refusals": rsnap.get(
                "router_suspect_refusals_total", 0),
            "pod_requests_total": roll_final.get(
                "serve_requests_total", 0),
            "platform": platform,
            "repro": (f"python -m dcf_tpu.cli pod_bench "
                      f"--shards {n_shards} "
                      f"--duration {float(args.duration):g} "
                      f"--max-batch {max_batch} "
                      f"--concurrency {conns} --seed {args.seed}"),
        }
        extra.update(_serve_pinned_ratio(res_pod.throughput, platform))
        unit = ("evals/s (closed-loop served through the pod router, "
                "party 0)")
        if platform != "tpu":
            unit += (" [no TPU this session: XLA-CPU interpret mode, "
                     "disclosed]")
        _emit("pod_bench", backend, "evals_per_sec",
              res_pod.throughput, unit, extra_fields=extra)

        # Emitted-then-asserted, chaos_bench style.
        failures = []
        if gate_applies and pod_vs_single < 2.2:
            failures.append(
                f"pod served {pod_vs_single:.3f}x the single-shard "
                "leg at the same shape/seeds (< 2.2 with the host "
                "parallelism to do better)")
        if soak["mismatches"] or soak["unaccounted"] \
                or soak["refused_unhinted"]:
            failures.append(
                f"failover soak left requests unaccounted: "
                f"{soak['mismatches']} mismatches, "
                f"{soak['unaccounted']} untyped failures, "
                f"{soak['refused_unhinted']} refusals without "
                "retry_after_s")
        if soak["sessions_ok"] < conns or soak["critical_ok"] < 1:
            failures.append(
                f"soak delivered only {soak['sessions_ok']} sessions "
                f"({soak['critical_ok']} CRITICAL)")
        if not failover_parity:
            failures.append(
                "a victim-owned key did not serve bit-exact from its "
                "replica after the kill")
        if not generations_held:
            failures.append(
                "a replica lost its provisioned generation (store or "
                "live registry)")
        if victim_live_keys:
            within = kill_stats["critical_within_s"]
            if within is None:
                failures.append(
                    "CRITICAL traffic for a victim-owned LIVE key "
                    "never served from the replica after the kill")
            elif within > max(2 * args.probe_interval, 3.0):
                failures.append(
                    f"CRITICAL live-key failover took {within:.2f}s "
                    "(> ~one probe interval with scheduling slack)")
            if not down_observed:
                failures.append(
                    "the prober never marked the SIGKILLed victim "
                    "DOWN")
        if quarantined:
            failures.append(
                f"{quarantined} frames quarantined across the pod")
        if not recon["reconciled"]:
            failures.append(
                f"open-loop counts did not reconcile against the pod "
                f"rollup ({recon})")
        if failures:
            raise SystemExit("pod_bench: " + "; ".join(failures))
    finally:
        for target in routers:
            try:
                target.close()
            except Exception:  # fallback-ok: best-effort teardown
                pass
        for tag, (proc, _r, _m) in procs.items():
            if proc.poll() is None:
                proc.terminate()
        for tag, (proc, _r, _m) in procs.items():
            try:
                proc.wait(15)
            except Exception:  # fallback-ok: a shard that ignores
                # SIGTERM gets the hard kill below
                proc.kill()
        if not keep_dirs:
            shutil.rmtree(root, ignore_errors=True)


BENCHES = {
    "dcf": bench_dcf,
    "dcf_batch_eval": bench_batch,
    "dcf_large_lambda": bench_large_lambda,
    "secure_relu": bench_secure_relu,
    "full_domain": bench_full_domain,
    "serve_bench": bench_serve,
    "edge_bench": bench_edge,
    "mic_bench": bench_mic,
    "gate_bench": bench_gates,
    "chaos_bench": bench_chaos,
    "keygen_bench": bench_keygen,
    "pir_bench": bench_pir,
    "keyfactory_bench": bench_keyfactory,
    "serve_host": bench_serve_host,
    "pod_bench": bench_pod,
}


def _maybe_force_cpu_devices() -> None:
    """DCF_CPU_DEVICES=N runs the CLI on N virtual XLA CPU devices (the
    sharded backend's no-hardware mode; same recipe as tests/conftest.py —
    needed because this environment's sitecustomize pins JAX_PLATFORMS at
    interpreter start, so env vars alone are too late)."""
    import os

    n = os.environ.get("DCF_CPU_DEVICES")
    if not n:
        return
    from dcf_tpu.utils.provision import force_cpu_devices

    force_cpu_devices(os.environ, int(n))
    import jax

    jax.config.update("jax_platforms", "cpu")
    log(f"forced {n} virtual CPU devices")


def main(argv=None) -> None:
    _maybe_force_cpu_devices()
    # Every CLI mode recompiles the same graphs each invocation (Mosaic
    # kernels on TPU, interpret-mode Pallas graphs on CPU); share the
    # machine-local compile cache (provision.enable_compile_cache).
    from dcf_tpu.utils.provision import enable_compile_cache

    enable_compile_cache()
    p = argparse.ArgumentParser(
        prog="python -m dcf_tpu.cli",
        description="DCF benchmark CLI (reference criterion-bench analogs)",
    )
    p.add_argument("bench", choices=(*BENCHES, "all", "baseline"))
    p.add_argument("--backend", default="cpu",
                   choices=(*BACKENDS, "tree", "hybrid"),
                   help="'tree' (full_domain only): GGM tree expansion; "
                        "'hybrid' (dcf_large_lambda only): narrow walk + "
                        "GF(2)-affine wide part")
    p.add_argument("--points", type=int, default=0,
                   help="batch size (0 = bench default)")
    p.add_argument("--keys", type=int, default=0,
                   help="key count for secure_relu / dcf_large_lambda "
                        "(0 = bench default); keygen_bench: replace "
                        "the K sweep with this single K; "
                        "keyfactory_bench: the per-refill session "
                        "batch (0 = 64, the pinned keygen-baseline K)")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--seed", type=int, default=2026)
    p.add_argument("--check", action="store_true",
                   help="verify parity vs the C++ core before timing")
    p.add_argument("--mesh", default="", nargs="?", const="pod",
                   help="mesh shape KxP (e.g. 4x2) for the sharded "
                        "backends; with --backend=hybrid or "
                        "--backend=tree it switches to their mesh-sharded "
                        "variants; bare --mesh on pod_bench runs the "
                        "co-evaluation crossover scenario (ISSUE 18)")
    p.add_argument("--profile", default="",
                   help="write a jax.profiler trace of the timed region")
    p.add_argument("--n-bits", type=int, default=0,
                   help="domain bits for full_domain (0 = 24); "
                        "pir_bench: a single database domain "
                        "(0 = the {14, 16, 18} sweep)")
    p.add_argument("--lam", type=int, default=0,
                   help="range bytes for dcf_large_lambda (0 = 16384; "
                        "256 = BASELINE config 4) / keygen_bench "
                        "(0 = both 128 and 256) / keyfactory_bench "
                        "(0 = 128)")
    p.add_argument("--prefix-levels", type=int, default=0,
                   help="dcf_large_lambda --backend=hybrid: expand the "
                        "top k narrow-walk levels once per (key, party) "
                        "as a cached frontier gather table and walk only "
                        "n-k levels per point (0 = from-root walk)")
    p.add_argument("--domain-bytes", type=int, default=0,
                   help="input width for dcf_batch_eval (0 = 16)")
    p.add_argument("--device-gen", action="store_true",
                   help="secure_relu: device keygen + pallas keylanes path")
    p.add_argument("--duration", type=float, default=30.0,
                   help="serve_bench: closed-loop load duration, seconds")
    p.add_argument("--concurrency", type=int, default=4,
                   help="serve_bench: closed-loop client threads")
    p.add_argument("--max-batch", type=int, default=0,
                   help="serve_bench: service micro-batch cap in points "
                        "(power of two; 0 = 2^17)")
    p.add_argument("--bundles", type=int, default=0,
                   help="serve_bench: registered key bundles (0 = 3)")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="serve_bench: micro-batch coalescing delay")
    p.add_argument("--device-bytes-budget", type=int, default=0,
                   help="serve_bench: LRU device-residency budget "
                        "(0 = uncapped)")
    p.add_argument("--edge", action="store_true",
                   help="serve_bench: also drive the same closed-loop "
                        "shape over the DCFE wire path (serve/edge.py) "
                        "and record wire_vs_inprocess on the line "
                        "(edge_bench is the full acceptance harness)")
    p.add_argument("--connections", type=int, default=8,
                   help="edge_bench: concurrent TCP connections for "
                        "the wire legs (the soak always uses >= 8)")
    p.add_argument("--min-req-points", type=int, default=0,
                   help="serve_bench/mic_bench: request-size range lower "
                        "bound (0 = 3/8 of --max-batch)")
    p.add_argument("--max-req-points", type=int, default=0,
                   help="serve_bench/mic_bench: request-size range upper "
                        "bound (0 = half of --max-batch)")
    p.add_argument("--skew", default="0",
                   help="serve_bench/mic_bench/chaos_bench: Zipf "
                        "exponent for key choice (0 = uniform; "
                        "serve_bench --skew also runs the cold-frontier "
                        "comparison leg and reports the frontier-cache "
                        "hit rate — ISSUE 7)")
    p.add_argument("--intervals", type=int, default=0,
                   help="mic_bench/keygen_bench: MIC interval count m "
                        "(0 = 8; the bundle K-packs 2m DCF keys)")
    p.add_argument("--fault-window", type=int, default=24,
                   help="chaos_bench: serve.eval evals to fail before "
                        "the injected backend recovers (retries count)")
    p.add_argument("--priority-mix",
                   default="critical=0.2,normal=0.5,batch=0.3",
                   help="chaos_bench: per-request priority-class "
                        "weights, e.g. critical=0.2,normal=0.5,"
                        "batch=0.3")
    p.add_argument("--breaker-failures", type=int, default=3,
                   help="chaos_bench: consecutive failed attempts "
                        "(dispatches + retries) that open a (key, "
                        "backend-family) breaker")
    p.add_argument("--breaker-cooldown", type=float, default=0.25,
                   help="chaos_bench: seconds an open breaker waits "
                        "before its half-open probe")
    p.add_argument("--crash-restart", action="store_true",
                   help="chaos_bench: run the durable-store scenario "
                        "instead — durable keys, a mid-stage kill, "
                        "warm restart, bit-exact post-restart parity "
                        "vs the C++ core with zero re-keygen")
    p.add_argument("--store-dir", default="",
                   help="chaos_bench --crash-restart / "
                        "keyfactory_bench: durable key store directory "
                        "(default: a fresh temp dir, removed "
                        "afterwards; an explicit dir is kept)")
    p.add_argument("--host-refill", action="store_true",
                   help="keyfactory_bench: refill pools through the "
                        "host keygen pipeline instead of the on-device "
                        "walk (an explicit host measurement)")
    p.add_argument("--keyfactory", action="store_true",
                   help="chaos_bench --crash-restart: also run the "
                        "key-factory pool scenario — batched durable "
                        "refills, a kill between the frame writes and "
                        "the manifest flip, warm restart with the "
                        "un-claimed pool supply restored (zero torn "
                        "entries, zero re-keygen, generations held)")
    p.add_argument("--full", action="store_true",
                   help="baseline: run config 5 at the literal 10^6-key "
                        "scale (~20 min report)")
    p.add_argument("--shards", type=int, default=3,
                   help="pod_bench: localhost shard processes in the "
                        "pod ring (>= 2; the solo comparison leg is "
                        "spawned on top)")
    p.add_argument("--live-bundles", type=int, default=4,
                   help="pod_bench: LIVE (non-durable) keys registered "
                        "through the router's REGISTER fan-out on top "
                        "of the --bundles durable ones (ISSUE 14: the "
                        "kill/partition soaks prove they survive their "
                        "owner's death on the replica, generations "
                        "preserved, zero re-keygen)")
    p.add_argument("--partition", action="store_true",
                   help="pod_bench: run the partition-tolerance "
                        "scenario instead — a net.partition window "
                        "isolates one shard under load; every request "
                        "completes bit-exact or is refused typed with "
                        "retry_after_s, the prober walks the victim "
                        "UP->SUSPECT->DOWN with NORMAL traffic served "
                        "from promoted replicas, and on heal the "
                        "anti-entropy gate converges the digest with "
                        "zero generation regressions (a doctored "
                        "old-generation frame is fenced typed)")
    p.add_argument("--flap", action="store_true",
                   help="pod_bench: the partition scenario with three "
                        "cut/heal cycles — generations must be "
                        "monotone across every flap")
    p.add_argument("--churn", action="store_true",
                   help="pod_bench: the autonomous-membership "
                        "scenario (ISSUE 15) — SIGKILL one shard, the "
                        "controller auto-ejects it after the grace "
                        "with every frame re-replicated to the new "
                        "placement (verified over the DIGEST verb + "
                        "the stores), the healed shard re-joins only "
                        "after the anti-entropy warm-up, a second "
                        "shard is gracefully drained (SIGTERM exits "
                        "0), and a doctored stale-epoch frame is "
                        "refused E_EPOCH — gates: ledger clean, zero "
                        "generation regressions, zero lost keys")
    p.add_argument("--surge", action="store_true",
                   help="pod_bench: the demand-driven autoscaling "
                        "scenario (ISSUE 16) — an open-loop Zipf ramp "
                        "holds the pod at ~4x its calibrated capacity "
                        "against a small admission bound; sustained "
                        "pressure must admit a --standby host through "
                        "the graceful join within --reaction-bound "
                        "seconds, the idle tail must drain one back, "
                        "and a scripted oscillating-load leg is pinned "
                        "to ZERO ring churn — gates: zero lost keys, "
                        "zero generation regressions, post-shrink "
                        "parity vs the numpy oracle, zero CRITICAL "
                        "sheds, strictly-increasing epochs")
    p.add_argument("--standby-hosts", type=int, default=1,
                   help="pod_bench --surge: provisioned-but-idle "
                        "serve_host --standby processes declared to "
                        "the capacity controller's standby pool")
    p.add_argument("--reaction-bound", type=float, default=30.0,
                   help="pod_bench --surge: max seconds from the ramp "
                        "start to the scale-out commit (the "
                        "autoscaler's reaction-time gate)")
    p.add_argument("--eject-grace", type=float, default=3.0,
                   help="pod_bench --churn: seconds a shard must stay "
                        "DOWN before the membership controller "
                        "auto-ejects it (the flap filter; promotion "
                        "already serves its keys meanwhile)")
    p.add_argument("--probe-interval", type=float, default=0.25,
                   help="pod_bench: health-prober probe interval in "
                        "seconds (fail-3/recover-2 hysteresis rides "
                        "on it)")
    p.add_argument("--bind", default="127.0.0.1",
                   help="serve_host: address to bind the DCFE edge on")
    p.add_argument("--port", type=int, default=0,
                   help="serve_host: edge port (0 = pick a free one; "
                        "the bound port lands in --ready-file)")
    p.add_argument("--standby", action="store_true",
                   help="serve_host: come up provisioned but EMPTY — "
                        "skip the store restore and wait; the "
                        "capacity controller's graceful join ships "
                        "keys warm-before-admit if demand ever admits "
                        "this host (pod_bench --surge spawns these)")
    p.add_argument("--max-queued-points", type=int, default=0,
                   help="serve_host: admission-queue bound in points "
                        "(0 = the ServeConfig default; pod_bench "
                        "--surge pins a small bound so overload "
                        "becomes visible demand within the window)")
    p.add_argument("--ready-file", default="",
                   help="serve_host: write a JSON {host, port, pid, "
                        "restored} line here (atomic rename) once "
                        "serving — how pod_bench learns the port")
    p.add_argument("--metrics-file", default="",
                   help="serve_host: refresh this JSON metrics "
                        "snapshot every ~0.5s (atomic rename) — the "
                        "per-host half of the pod rollup")
    p.add_argument("--tls-cert", default="",
                   help="serve_host: PEM certificate arming TLS on "
                        "the edge socket (needs --tls-key)")
    p.add_argument("--tls-key", default="",
                   help="serve_host: PEM private key for --tls-cert")
    p.add_argument("--tls-client-ca", default="",
                   help="serve_host: PEM CA bundle; when set, only "
                        "clients presenting a cert signed by it may "
                        "connect (router<->shard link pinning)")
    args = p.parse_args(argv)
    if args.backend == "tree" and args.bench not in ("full_domain",
                                                     "baseline"):
        raise SystemExit(
            "--backend=tree is the full-domain tree evaluator; it only "
            "applies to the full_domain bench (and baseline)")
    if args.backend == "hybrid" and args.bench not in (
            "dcf_large_lambda", "keyfactory_bench", "baseline"):
        raise SystemExit(
            "--backend=hybrid is the large-lambda evaluator; it only "
            "applies to the dcf_large_lambda and keyfactory_bench "
            "benches (and baseline)")
    if args.prefix_levels and args.backend not in ("hybrid", "prefix"):
        raise SystemExit(
            "--prefix-levels configures the prefix-shared narrow walk; "
            "use it with --backend=hybrid (dcf_large_lambda) or "
            "--backend=prefix (serve_bench --skew frontier depth)")
    if args.bench == "baseline":
        bench_baseline(args)
        return
    for name in BENCHES if args.bench == "all" else [args.bench]:
        if args.bench == "all" and name in ("serve_bench", "edge_bench",
                                            "mic_bench", "chaos_bench",
                                            "pod_bench"):
            log(f"skipping {name} (a timed load test, not a "
                "criterion analog; run it explicitly)")
            continue
        if args.bench == "all" and name == "serve_host":
            log("skipping serve_host (a long-lived shard process, "
                "not a bench; run it explicitly)")
            continue
        if args.bench == "all" and name in ("keygen_bench",
                                            "keyfactory_bench",
                                            "pir_bench"):
            log(f"skipping {name} (device-kernel harness with its "
                "own backend routing; run it explicitly)")
            continue
        if args.bench == "all" and name == "dcf_large_lambda" and \
                args.backend in ("pallas", "sharded", "sharded-pallas"):
            log("skipping dcf_large_lambda (lam=16-only backend)")
            continue
        if args.bench == "all" and name == "dcf" and \
                args.backend in ("sharded", "sharded-pallas"):
            log("skipping dcf (single-point bench, not shardable)")
            continue
        BENCHES[name](args)


if __name__ == "__main__":
    main()
