"""Benchmark CLI mirroring the reference's three criterion benches.

Reference harness (no published numbers, SURVEY.md §6):

- ``dcf``             — single ``gen`` + single-point ``eval``, N=16, lam=16
                        (/root/reference/benches/dcf.rs:7-43)
- ``dcf_batch_eval``  — 100 000-point batch eval, N=16, lam=16
                        (/root/reference/benches/dcf_batch_eval.rs:17-39)
- ``dcf_large_lambda``— lam=16384 (2048 AES keys), 10 000 points
                        (/root/reference/benches/dcf_large_lambda.rs:8-43)

plus ``secure_relu`` — the BASELINE.json config-5 many-keys workload.

Usage::

    python -m dcf_tpu.cli dcf_batch_eval --backend=pallas --points=1048576
    python -m dcf_tpu.cli all --backend=cpu

Backends: ``cpu`` (C++ core, all threads), ``cpu1`` (C++ single thread —
the stand-in for the reference's serial feature matrix), ``numpy``,
``jax`` (XLA scan/vmap), ``bitsliced`` (XLA bit-planes), ``pallas``
(fused TPU kernel, lam=16 only).  Each bench prints one human line and one
JSON line; gen always runs on the C++ host core (keys ship to the device
once, SURVEY.md §2.2).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

from dcf_tpu.gen import random_s0s
from dcf_tpu.keys import KeyBundle
from dcf_tpu.spec import Bound

BACKENDS = ("cpu", "cpu1", "numpy", "jax", "bitsliced", "pallas")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _cipher_keys(lam: int, rng) -> list[bytes]:
    n_keys = max(2, 2 * (lam // 16))
    return [rng.bytes(32) for _ in range(n_keys)]


def _make_evaluator(backend: str, lam: int, cipher_keys, native):
    """Returns eval_fn(b, bundle_party, xs) -> uint8 [K, M, lam]."""
    if backend in ("cpu", "cpu1"):
        threads = 1 if backend == "cpu1" else None

        def run(b, bundle, xs):
            return native.eval(b, bundle, xs, num_threads=threads)

        return run
    if backend == "numpy":
        from dcf_tpu.backends.numpy_backend import eval_batch_np
        from dcf_tpu.ops.prg import HirosePrgNp

        prg = HirosePrgNp(lam, cipher_keys)
        return lambda b, bundle, xs: eval_batch_np(prg, b, bundle, xs)
    if backend == "jax":
        from dcf_tpu.backends.jax_backend import JaxBackend

        be = JaxBackend(lam, cipher_keys)
    elif backend == "bitsliced":
        from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

        be = BitslicedBackend(lam, cipher_keys)
    elif backend == "pallas":
        from dcf_tpu.backends.pallas_backend import PallasBackend

        be = PallasBackend(lam, cipher_keys)
    else:
        raise ValueError(f"unknown backend {backend!r}")
    return lambda b, bundle, xs: be.eval(b, xs, bundle=bundle)


def _timed(fn, reps: int):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def _emit(name: str, backend: str, metric: str, value: float, unit: str):
    log(f"{name}[{backend}]: {value:,.1f} {unit}")
    print(
        json.dumps(
            {"bench": name, "backend": backend, "metric": metric,
             "value": round(value, 1), "unit": unit}
        ),
        flush=True,
    )


def bench_dcf(args) -> None:
    """Single gen + single-point eval latency (benches/dcf.rs analog)."""
    from dcf_tpu.native import NativeDcf

    lam, nb = 16, 16
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    alphas = rng.integers(0, 256, (1, nb), dtype=np.uint8)
    betas = rng.integers(0, 256, (1, lam), dtype=np.uint8)
    s0s = random_s0s(1, lam, rng)

    gen_s = _timed(
        lambda: native.gen_batch(alphas, betas, s0s, Bound.LT_BETA), args.reps
    )
    _emit("dcf_gen", "cpu", "gen_latency_us", gen_s * 1e6, "us")

    bundle = native.gen_batch(alphas, betas, s0s, Bound.LT_BETA)
    run = _make_evaluator(args.backend, lam, ck, native)
    xs = rng.integers(0, 256, (1, nb), dtype=np.uint8)
    k0 = bundle.for_party(0)
    run(0, k0, xs)  # warmup / compile
    ev_s = _timed(lambda: run(0, k0, xs), args.reps)
    _emit("dcf_eval_1pt", args.backend, "eval_latency_us", ev_s * 1e6, "us")


def bench_batch(args) -> None:
    """Batch eval throughput (benches/dcf_batch_eval.rs analog)."""
    from dcf_tpu.native import NativeDcf

    lam, nb = 16, 16
    m = args.points or 100_000
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    bundle = native.gen_batch(
        rng.integers(0, 256, (1, nb), dtype=np.uint8),
        rng.integers(0, 256, (1, lam), dtype=np.uint8),
        random_s0s(1, lam, rng),
        Bound.LT_BETA,
    )
    xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
    run = _make_evaluator(args.backend, lam, ck, native)
    k0 = bundle.for_party(0)
    y = run(0, k0, xs)  # warmup / compile
    if args.check:
        want = native.eval(0, bundle, xs[:2048])
        assert np.array_equal(y[0, :2048], want[0]), "parity mismatch vs C++"
        log("parity vs C++ core: OK (first 2048 pts)")
    dt = _timed(lambda: run(0, k0, xs), args.reps)
    _emit("dcf_batch_eval", args.backend, "evals_per_sec", m / dt, "evals/s")


def bench_large_lambda(args) -> None:
    """Large-range eval, lam=16384 (benches/dcf_large_lambda.rs analog)."""
    from dcf_tpu.native import NativeDcf

    lam, nb = 16384, 16
    m = args.points or 10_000
    if args.backend == "pallas":
        raise SystemExit("pallas backend is lam=16 only; use bitsliced/jax/cpu")
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    log(f"gen (lam=16384, {2 * (lam // 16)} ciphers) ...")
    bundle = native.gen_batch(
        rng.integers(0, 256, (1, nb), dtype=np.uint8),
        rng.integers(0, 256, (1, lam), dtype=np.uint8),
        random_s0s(1, lam, rng),
        Bound.LT_BETA,
    )
    xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
    run = _make_evaluator(args.backend, lam, ck, native)
    k0 = bundle.for_party(0)
    y = run(0, k0, xs)  # warmup / compile
    if args.check:
        want = native.eval(0, bundle, xs[:64])
        assert np.array_equal(y[0, :64], want[0]), "parity mismatch vs C++"
        log("parity vs C++ core: OK (first 64 pts)")
    dt = _timed(lambda: run(0, k0, xs), args.reps)
    _emit("dcf_large_lambda", args.backend, "evals_per_sec", m / dt, "evals/s")


def bench_secure_relu(args) -> None:
    """Many-keys x few-points workload (BASELINE.json config 5, scaled)."""
    from dcf_tpu.backends.jax_bitsliced import KeyLanesBackend
    from dcf_tpu.native import NativeDcf
    from dcf_tpu.workloads import secure_relu_eval

    lam, nb = 16, 16
    k = args.keys or 65_536
    m = args.points or 1_024
    rng = np.random.default_rng(args.seed)
    ck = _cipher_keys(lam, rng)
    native = NativeDcf(lam, ck)
    log(f"gen {k} keys ...")
    bundle = native.gen_batch(
        rng.integers(0, 256, (k, nb), dtype=np.uint8),
        rng.integers(0, 256, (k, lam), dtype=np.uint8),
        random_s0s(k, lam, rng),
        Bound.LT_BETA,
    )
    xs = rng.integers(0, 256, (m, nb), dtype=np.uint8)
    be0 = KeyLanesBackend(lam, ck)
    be1 = KeyLanesBackend(lam, ck)
    secure_relu_eval(be0, be1, bundle, xs)  # warmup / compile
    t0 = time.perf_counter()
    secure_relu_eval(be0, be1, bundle, xs)
    dt = time.perf_counter() - t0
    # Two parties evaluated -> 2*K*M DCF evals.
    _emit("secure_relu", "bitsliced-keylanes", "evals_per_sec",
          2 * k * m / dt, "evals/s")


BENCHES = {
    "dcf": bench_dcf,
    "dcf_batch_eval": bench_batch,
    "dcf_large_lambda": bench_large_lambda,
    "secure_relu": bench_secure_relu,
}


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="python -m dcf_tpu.cli",
        description="DCF benchmark CLI (reference criterion-bench analogs)",
    )
    p.add_argument("bench", choices=(*BENCHES, "all"))
    p.add_argument("--backend", default="cpu", choices=BACKENDS)
    p.add_argument("--points", type=int, default=0,
                   help="batch size (0 = bench default)")
    p.add_argument("--keys", type=int, default=0,
                   help="key count for secure_relu (0 = default)")
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--seed", type=int, default=2026)
    p.add_argument("--check", action="store_true",
                   help="verify parity vs the C++ core before timing")
    args = p.parse_args(argv)
    for name in BENCHES if args.bench == "all" else [args.bench]:
        if args.bench == "all" and name == "dcf_large_lambda" and \
                args.backend == "pallas":
            log("skipping dcf_large_lambda (pallas is lam=16 only)")
            continue
        BENCHES[name](args)


if __name__ == "__main__":
    main()
