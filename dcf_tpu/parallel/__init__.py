"""Multi-chip scale-out: batch eval sharded over a TPU device mesh.

The reference's only parallelism is rayon threads across points
(src/lib.rs:194-199) — zero inter-task communication.  The TPU-native
equivalent (SURVEY.md §2.2) is a 2D ``jax.sharding.Mesh`` with axes

    ("keys", "points")

and the eval ``shard_map``'d so each chip walks its (key-shard, point-shard)
block locally; collectives ride ICI only for input/result redistribution, and
no communication happens during the walk itself (the eval is a pure map).
Keys stream host->HBM sharded over the "keys" axis, which is what makes the
10^6-keys secure-ReLU workload (BASELINE config 5) fit: each of 8 chips
holds 1/8 of the ~4.4 GB key image — in ``ShardedJaxBackend``'s byte
layout (the right sharded backend for many-keys work; the bit-plane
``ShardedBitslicedBackend`` is faster per chip but its key image is 32x
larger, so it suits few-keys x many-points shapes).
"""

from dcf_tpu.parallel.mesh import (  # noqa: F401
    ShardedBitslicedBackend,
    ShardedJaxBackend,
    make_mesh,
    make_pod_mesh,
)
from dcf_tpu.parallel.mesh_eval import (  # noqa: F401
    MeshLargeLambdaBackend,
)
from dcf_tpu.parallel.pallas_sharded import (  # noqa: F401
    ShardedDpfEvalAll,
    ShardedKeyLanesBackend,
    ShardedLargeLambdaBackend,
    ShardedPallasBackend,
    ShardedPrefixBackend,
    ShardedTreeFullDomain,
)
