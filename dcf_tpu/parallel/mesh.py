"""Mesh construction and the sharded evaluator."""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcf_tpu.backends.jax_backend import eval_core
from dcf_tpu.backends.jax_bitsliced import (
    _BitslicedBase,
    _eval_bytes,
    bundle_plane_arrays,
)
from dcf_tpu.backends._common import prepare_batch
from dcf_tpu.parallel._compat import shard_map
from dcf_tpu.errors import (
    BackendUnavailableError,
    ShapeError,
    StaleStateError,
)
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes import expand_key_np
from dcf_tpu.spec import hirose_used_cipher_indices
from dcf_tpu.testing.faults import fire

__all__ = ["make_mesh", "make_pod_mesh", "ShardedJaxBackend",
           "ShardedBitslicedBackend"]


def make_mesh(
    n_devices: int | None = None,
    axis_names: tuple[str, str] = ("keys", "points"),
    shape: tuple[int, int] | None = None,
) -> Mesh:
    """Build a 2D (keys x points) mesh over the first devices.

    ``shape=(keys_dim, points_dim)`` pins the factorization explicitly
    (8x1, 4x2, 2x4, ... — benchmarkable against each other via the CLI's
    ``--mesh``).  Without it, the keys axis gets the larger factor: key
    sharding is what divides the HBM-resident key image, while point
    sharding only divides transient state.

    Device enumeration failure (no runtime, dead TPU driver) raises a
    typed ``BackendUnavailableError`` instead of an opaque runtime
    traceback.  Fault seam: ``faults.fire("mesh.provision")``.
    """
    try:
        fire("mesh.provision")
        devs = jax.devices()
    except Exception as e:  # fallback-ok: typed re-raise, any runtime error
        raise BackendUnavailableError(
            f"mesh provisioning failed: could not enumerate devices "
            f"({type(e).__name__}: {e})") from e
    if shape is not None:
        keys_dim, points = shape
        if n_devices is not None and keys_dim * points != n_devices:
            raise ValueError(  # api-edge: documented mesh-shape contract
                f"mesh shape {shape} does not cover {n_devices} devices")
    else:
        n = len(devs) if n_devices is None else n_devices
        # Points axis is 1 or 2; the keys axis takes the rest.
        points = 2 if n % 2 == 0 else 1
        keys_dim = n // points
    if keys_dim * points > len(devs):
        raise ValueError(  # api-edge: documented mesh-provisioning contract
            f"requested {keys_dim * points} devices, have {len(devs)}")
    return Mesh(
        np.array(devs[: keys_dim * points]).reshape(keys_dim, points), axis_names
    )


def make_pod_mesh(
    axis_names: tuple[str, str] = ("keys", "points"),
    shape: tuple[int, int] | None = None,
) -> Mesh:
    """Build the POD mesh: a 2D (keys x points) mesh over EVERY device
    of every process in the distributed runtime (ISSUE 18).

    Where ``make_mesh`` factorizes one host's devices (and defaults the
    larger factor to the keys axis), the pod mesh exists for
    co-evaluation — one batch laid across all hosts — so it must cover
    ALL global devices and it defaults to ``(1, n_global)``: the ring
    already shards *keys* across hosts (``serve.shardmap``), so the
    mesh's job is to shard *points*; a keys axis wider than 1 would
    re-shard what the ring placed.  Call
    ``parallel._compat.distributed_initialize`` on every process first;
    standalone (single-process) the "pod" is just this host's devices,
    which is exactly what the parity tests exercise.

    ``shape=(keys_dim, points_dim)`` must cover the global device count
    exactly — a pod mesh with idle devices is a configuration error,
    not a fallback.  Same typed provisioning contract and
    ``faults.fire("mesh.provision")`` seam as ``make_mesh``.
    """
    try:
        fire("mesh.provision")
        devs = jax.devices()
    except Exception as e:  # fallback-ok: typed re-raise, any runtime error
        raise BackendUnavailableError(
            f"pod mesh provisioning failed: could not enumerate devices "
            f"({type(e).__name__}: {e})") from e
    n = len(devs)
    if shape is None:
        keys_dim, points = 1, n
    else:
        keys_dim, points = shape
        if keys_dim * points != n:
            raise ValueError(  # api-edge: documented pod-mesh contract —
                # the pod mesh must span every global device exactly
                f"pod mesh shape {shape} does not cover all {n} global "
                f"devices")
    return Mesh(np.array(devs).reshape(keys_dim, points), axis_names)


class ShardedJaxBackend:
    """DCF evaluator sharded over a device mesh.

    The same scan as ``JaxBackend`` runs on every chip over its local
    (key-shard, point-shard) block via ``shard_map``; there are no
    collectives inside the walk (pure map), so scaling is linear in chips
    modulo input/result movement.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh):
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        self.lam = lam
        self.mesh = mesh
        self.round_keys = tuple(
            jnp.asarray(expand_key_np(cipher_keys[i])) for i in used
        )
        self._bundle_dev = None
        kaxis, paxis = mesh.axis_names
        self._spec_keyed = P(kaxis)  # [K, ...] arrays
        self._spec_level = P(None, kaxis)  # [n, K, ...] arrays
        self._spec_xs = P(kaxis, paxis)  # per-key points [K, M, ...]
        self._spec_xs_shared = P(paxis)  # shared points [M, ...]
        self._bundle_specs = (
            P(),  # round keys replicated
            self._spec_keyed,  # s0
            self._spec_level,  # cw_s
            self._spec_level,  # cw_v
            self._spec_level,  # cw_t
            self._spec_keyed,  # cw_np1
        )
        self._group = "xor"
        self._fn: dict = {}

    def _shard_fn(self, b: int, shared: bool):
        """Cached jit(shard_map(core)) per (party, shared, group) — the
        group rides the bundle, so the cache key must carry it or a
        re-put with a different group would reuse the wrong algebra.

        No collectives inside the walk (pure map), so the
        varying-mesh-axes bookkeeping (scan carry starts key-varying,
        becomes (keys, points)-varying after level 1) buys nothing:
        check_vma=False."""
        key = (b, shared, self._group)
        fn = self._fn.get(key)
        if fn is None:
            fn = jax.jit(
                shard_map(
                    partial(eval_core, b=b, lam=self.lam,
                            group=self._group),
                    mesh=self.mesh,
                    in_specs=(
                        *self._bundle_specs,
                        self._spec_xs_shared if shared else self._spec_xs,
                    ),
                    out_specs=self._spec_xs,
                    check_vma=False,
                )
            )
            self._fn[key] = fn
        return fn

    def _put(self, arr: np.ndarray, spec: P) -> jax.Array:
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def put_bundle(self, bundle: KeyBundle) -> None:
        """Ship a party-restricted bundle to the mesh, sharded over keys."""
        if bundle.lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        ksize = self.mesh.shape[self.mesh.axis_names[0]]
        if bundle.num_keys % ksize != 0:
            raise ShapeError(
                f"num_keys={bundle.num_keys} not divisible by keys-axis size {ksize}"
            )
        lm = bundle.level_major()
        self._group = bundle.group
        self._bundle_dev = {
            k: self._put(
                v, self._spec_keyed if k in ("s0", "cw_np1") else self._spec_level
            )
            for k, v in lm.items()
        }

    def eval(
        self, b: int, xs: np.ndarray, bundle: KeyBundle | None = None
    ) -> np.ndarray:
        """Evaluate party ``b``; xs uint8 [M, n_bytes] or [K, M, n_bytes]."""
        if bundle is not None:
            self.put_bundle(bundle)
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        dev = self._bundle_dev
        shared = xs.ndim == 2
        m_axis = 0 if shared else 1
        psize = self.mesh.shape[self.mesh.axis_names[1]]
        if xs.shape[m_axis] % psize != 0:
            raise ShapeError(
                f"num_points={xs.shape[m_axis]} not divisible by points-axis size {psize}"
            )
        xs_dev = self._put(
            np.ascontiguousarray(xs),
            self._spec_xs_shared if shared else self._spec_xs,
        )
        y = self._shard_fn(int(b), shared)(
            self.round_keys,
            dev["s0"],
            dev["cw_s"],
            dev["cw_v"],
            dev["cw_t"],
            dev["cw_np1"],
            xs_dev,
        )
        return np.asarray(y)


class ShardedBitslicedBackend(_BitslicedBase):
    """The bitsliced (fast portable) eval core sharded over a device mesh.

    Same mesh contract as ``ShardedJaxBackend`` but each chip runs the
    bit-plane core (``backends.jax_bitsliced.eval_core_bitsliced``) on its
    local (key-shard, point-shard) block.  For the Pallas kernels sharded
    over the same mesh (the path a real TPU pod runs) see
    ``parallel.pallas_sharded.ShardedPallasBackend`` /
    ``ShardedKeyLanesBackend``.  No collectives inside the walk (pure
    map); keys shard the HBM-resident plane image, points shard
    transient state.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh):
        super().__init__(lam, cipher_keys)
        self.mesh = mesh
        kaxis, paxis = mesh.axis_names
        self._spec_keyed = P(None, kaxis)       # [8lam|n, K]
        self._spec_level = P(None, None, kaxis)  # [n, 8lam, K]
        self._spec_xs = P(kaxis, paxis, None)    # [K, M, nb]
        self._spec_xs_shared = P(None, paxis, None)  # [1, M, nb]
        self._spec_y = P(kaxis, paxis, None)     # [K, M, lam]
        self._bundle_specs = (
            P(),                # round keys (tuple, replicated)
            P(),                # last-bit mask
            self._spec_keyed,   # s0 planes
            self._spec_level,   # cw_s planes
            self._spec_level,   # cw_v planes
            self._spec_keyed,   # cw_tl
            self._spec_keyed,   # cw_tr
            self._spec_keyed,   # cw_np1 planes
        )
        self._fn: dict = {}

    def _shard_fn(self, b: int, shared: bool):
        """Cached jit(shard_map(core)) per (party, shared, group); the
        group rides the bundle (set at put_bundle), so it keys the
        cache.  No collectives inside the walk: check_vma=False."""
        key = (b, shared, self._group)
        fn = self._fn.get(key)
        if fn is None:
            fn = jax.jit(
                shard_map(
                    partial(_eval_bytes, b=b, lam=self.lam,
                            group=self._group),
                    mesh=self.mesh,
                    in_specs=(
                        *self._bundle_specs,
                        self._spec_xs_shared if shared else self._spec_xs,
                    ),
                    out_specs=self._spec_y,
                    check_vma=False,
                )
            )
            self._fn[key] = fn
        return fn

    def _put(self, arr, spec: P) -> jax.Array:
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def put_bundle(self, bundle: KeyBundle) -> None:
        """Ship a party-restricted bundle as plane masks, keys sharded."""
        if bundle.lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        ksize = self.mesh.shape[self.mesh.axis_names[0]]
        if bundle.num_keys % ksize != 0:
            raise ShapeError(
                f"num_keys={bundle.num_keys} not divisible by keys-axis "
                f"size {ksize}")
        self._group = bundle.group
        self._bundle_dev = {
            k: self._put(
                v, self._spec_level if v.ndim == 3 else self._spec_keyed)
            for k, v in bundle_plane_arrays(bundle).items()
        }

    def eval(self, b: int, xs: np.ndarray,
             bundle: KeyBundle | None = None) -> np.ndarray:
        """Party ``b`` eval; xs uint8 [M, nb] or [K, M, nb] -> [K, M, lam].

        The point axis is padded so each point-shard is a whole number of
        32-point lane words (pad points computed and discarded).
        """
        if bundle is not None:
            self.put_bundle(bundle)
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        dev = self._bundle_dev
        k_num = dev["s0"].shape[1]
        n = dev["cw_s"].shape[0]
        psize = self.mesh.shape[self.mesh.axis_names[1]]
        granule = 32 * psize  # whole lane words per point-shard
        shared = xs.ndim == 2
        xs_p, _, m = prepare_batch(
            (k_num, n), xs, lambda m: -(-m // granule) * granule)
        xs_dev = self._put(
            xs_p, self._spec_xs_shared if shared else self._spec_xs)
        y = self._shard_fn(int(b), shared)(
            self.rk_masks, self._last_bit_mask, dev["s0"], dev["cw_s"],
            dev["cw_v"], dev["cw_tl"], dev["cw_tr"], dev["cw_np1"], xs_dev,
        )
        return np.asarray(y)[:, :m, :]
