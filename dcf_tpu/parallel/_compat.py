"""jax version-skew shims for the sharded backends.

``shard_map`` moved from ``jax.experimental.shard_map`` to the ``jax``
namespace, and its replication-check kwarg was renamed ``check_rep`` ->
``check_vma`` along the way.  The sharded backends are written against
the current API; this shim keeps them importable and runnable on older
jax instead of dying on ``AttributeError``/``TypeError`` — the same
degrade-don't-crash rule the rest of the fault-tolerance layer follows.

ISSUE 18 widens the shim to the MULTI-PROCESS surface the pod mesh
rides: ``jax.distributed.initialize`` (whose CPU-collectives knob has
moved between a config option and an env var across versions) and the
host-local -> process-spanning-global array conversion (which has lived
in ``jax.experimental.multihost_utils`` and grown a sibling spelling in
the ``jax`` namespace).  The dcflint compat-shim pass enforces that no
other module touches these names raw — a future rename is one shim
edit, not an AttributeError scattered over the mesh tier.
"""

from __future__ import annotations

import inspect

import jax

from dcf_tpu.errors import BackendUnavailableError

_sm = getattr(jax, "shard_map", None)
if _sm is None:  # pre-move jax: the experimental location
    from jax.experimental.shard_map import shard_map as _sm

_CHECK_KW = (
    "check_vma" if "check_vma" in inspect.signature(_sm).parameters
    else "check_rep"
)

try:  # the host-local -> global conversion's long-term home
    from jax.experimental import multihost_utils as _mhu
except ImportError:  # pragma: no cover - ancient jax: single-host only
    _mhu = None

__all__ = ["shard_map", "distributed_initialize", "process_index",
           "process_count", "host_to_global"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the kwarg spelling this jax understands."""
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **{_CHECK_KW: check_vma})


def distributed_initialize(coordinator_address: str, num_processes: int,
                           process_id: int,
                           cpu_collectives: str = "gloo") -> None:
    """``jax.distributed.initialize`` with the skew handled (ISSUE 18).

    Joins this process to the pod's multi-process runtime: after it
    returns, ``jax.devices()`` enumerates EVERY process's devices and a
    mesh built over them spans hosts.  ``cpu_collectives`` selects the
    CPU cross-process collectives backend where this jax exposes the
    knob (the config option has come and gone across versions; where
    absent, jax's own default stands).  Idempotent: a repeat call on an
    already-initialized runtime is a no-op, not an error — the serving
    tier may race a test harness to it.

    Failure to reach the coordinator (or an unusable runtime) raises a
    typed ``BackendUnavailableError`` instead of an opaque runtime
    traceback — the same contract as ``make_mesh``'s provisioning seam.
    """
    try:
        jax.config.update("jax_cpu_collectives_implementation",
                          cpu_collectives)
    except Exception:  # fallback-ok: the knob was removed (newer jax
        # picks the collectives implementation itself) or never existed
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=int(num_processes),
            process_id=int(process_id))
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            return  # idempotent re-entry
        raise BackendUnavailableError(
            f"jax.distributed.initialize failed for process "
            f"{process_id}/{num_processes} at {coordinator_address!r} "
            f"({type(e).__name__}: {e})") from e
    except Exception as e:  # fallback-ok: typed re-raise, any runtime
        # or protocol error joining the pod
        raise BackendUnavailableError(
            f"jax.distributed.initialize failed for process "
            f"{process_id}/{num_processes} at {coordinator_address!r} "
            f"({type(e).__name__}: {e})") from e


def process_index() -> int:
    """This process's index in the distributed runtime (0 standalone)."""
    return int(jax.process_index())


def process_count() -> int:
    """Total processes in the distributed runtime (1 standalone)."""
    return int(jax.process_count())


def host_to_global(arr, mesh, spec) -> jax.Array:
    """Host-local array -> process-spanning global array on ``mesh``.

    Along ``spec`` dimensions whose mesh axes span processes, each
    process contributes its LOCAL slice and the global array is their
    concatenation in mesh order; along everything else the inputs must
    be identical across processes (replication).  On a single-process
    mesh (or a jax too old for multihost_utils) this degrades to a
    plain placed ``device_put`` — same result, no cross-process step.
    """
    import numpy as np

    from jax.sharding import NamedSharding

    arr = np.asarray(arr)
    if _mhu is None or jax.process_count() == 1:
        return jax.device_put(arr, NamedSharding(mesh, spec))
    return _mhu.host_local_array_to_global_array(arr, mesh, spec)
