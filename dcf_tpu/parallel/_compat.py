"""jax version-skew shims for the sharded backends.

``shard_map`` moved from ``jax.experimental.shard_map`` to the ``jax``
namespace, and its replication-check kwarg was renamed ``check_rep`` ->
``check_vma`` along the way.  The sharded backends are written against
the current API; this shim keeps them importable and runnable on older
jax instead of dying on ``AttributeError``/``TypeError`` — the same
degrade-don't-crash rule the rest of the fault-tolerance layer follows.
"""

from __future__ import annotations

import inspect

import jax

_sm = getattr(jax, "shard_map", None)
if _sm is None:  # pre-move jax: the experimental location
    from jax.experimental.shard_map import shard_map as _sm

_CHECK_KW = (
    "check_vma" if "check_vma" in inspect.signature(_sm).parameters
    else "check_rep"
)

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the kwarg spelling this jax understands."""
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **{_CHECK_KW: check_vma})
