"""Pod-mesh co-evaluation: one batch spanning every host's devices.

ISSUE 18's device-placement half.  ``ShardedLargeLambdaBackend`` lays a
batch across ONE process's devices; ``MeshLargeLambdaBackend`` extends
it across every process of a ``jax.distributed`` runtime — each host
contributes its equal-length slice of the points batch, the bundle image
is replicated (the pod mesh's keys axis is pinned to 1: the RING shards
keys across hosts via ``serve.shardmap``, so the mesh's only job is to
shard POINTS), the narrow Pallas walk + wide MXU tail run as the same
pure map per device block, and the two-party verification scalar
(``points_mismatch_count``) is the one collective at the end — a
replicated device int32 every process can read.

Contract per process (all processes must make the same calls in the
same order — jax's multi-process SPMD rule):

* ``distributed_initialize`` (``parallel._compat``), then
  ``make_pod_mesh()`` — default shape ``(1, n_global_devices)``.
* ``put_bundle(bundle)`` with the IDENTICAL bundle everywhere.
* ``stage(xs_local)`` with THIS process's slice of the batch; slices
  must be equal length (pad the tail slice — pad points are genuine
  x=0 evaluations and self-verify).
* ``eval_staged`` returns the process-spanning global [K, M, lam];
  ``staged_to_bytes`` reads back THIS process's local slice of it.

Single-process (no distributed runtime) every conversion degrades to a
plain placed ``device_put`` and the backend is bit-identical to
``ShardedLargeLambdaBackend`` over the same devices — which is exactly
the equivalence the parity suite pins.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from dcf_tpu.errors import ShapeError, StaleStateError
from dcf_tpu.parallel._compat import host_to_global, process_count
from dcf_tpu.parallel.pallas_sharded import ShardedLargeLambdaBackend

__all__ = ["MeshLargeLambdaBackend"]


class MeshLargeLambdaBackend(ShardedLargeLambdaBackend):
    """The large-lambda hybrid over a multi-process pod mesh.

    All staging, kernel dispatch, and verification logic is inherited;
    this subclass only swaps the two placement seams
    (``_place_bundle_array`` / ``_place_xs``) for the host-local ->
    global conversion and re-derives the points granule from the LOCAL
    device count (each process pads its own slice).  From-root narrow
    walk only (``prefix_levels=0``): the prefix frontier build walks an
    eager single-device pallas_call, which has no multi-process story
    yet.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh,
                 col_chunk: int = 1 << 15, interpret: bool = False):
        kaxis = mesh.axis_names[0]
        if mesh.shape[kaxis] != 1:
            raise ShapeError(
                f"pod mesh keys axis must be 1 (the ring shards keys "
                f"across hosts; the mesh shards points), got "
                f"{mesh.shape[kaxis]}")
        super().__init__(lam, cipher_keys, mesh, col_chunk=col_chunk,
                         interpret=interpret, prefix_levels=0)
        self._nproc = process_count()
        if self._psize % self._nproc:
            raise ShapeError(
                f"points-axis size {self._psize} not divisible by "
                f"process count {self._nproc}")
        # Devices this process contributes to the points axis — the
        # padding granule below is per-LOCAL-slice, not per-pod.
        self._local_psize = self._psize // self._nproc
        # The parent commits these to a local device at construction;
        # re-place as replicated globals so the jitted shard_map sees
        # consistently-addressed operands on every process.
        self.rk2 = host_to_global(np.asarray(self.rk2), mesh, P())
        self._inv_perm = host_to_global(
            np.asarray(self._inv_perm), mesh, P())

    def _place_bundle_array(self, v):
        # Keys axis is 1 => no mesh axis of the spec spans processes:
        # replication semantics, every process passes the identical
        # bundle-derived array (the put_bundle contract).
        return host_to_global(np.asarray(v), self.mesh, self._spec_keyed)

    def _place_xs(self, xs: np.ndarray):
        # Points axis spans processes: each process contributes its
        # local slice and the global batch is their concatenation in
        # process order.
        return host_to_global(
            np.ascontiguousarray(xs)[None], self.mesh, self._spec_xs)

    def stage(self, xs: np.ndarray) -> dict:
        """Stage THIS process's slice ``xs`` uint8 [M_local, nb].

        Every process must stage an equal-length slice; ``m`` in the
        returned dict is the LOCAL point count (what this process's
        ``staged_to_bytes`` clips to)."""
        if self._dev is None:
            raise StaleStateError(
                "no key bundle on device; call put_bundle first")
        if xs.ndim != 2:
            raise ShapeError(
                "MeshLargeLambdaBackend wants this process's shared-"
                "points slice [M_local, nb]")
        m = xs.shape[0]
        per_dev = -(-m // self._local_psize)
        granule = self._local_psize * (4096 if per_dev > 4096 else 32)
        m_pad = -(-m // granule) * granule
        if m_pad != m:
            xs = np.pad(xs, [(0, m_pad - m), (0, 0)])
        return {"xs": self._place_xs(xs), "m": m}

    def staged_to_bytes(self, y, m: int) -> np.ndarray:
        """This process's slice of the global output, uint8 [K, m, lam].

        The global [K, M_global, lam] is only partially addressable
        here; concatenate the local shards in points order and clip the
        local padding."""
        shards = sorted(y.addressable_shards,
                        key=lambda s: s.index[1].start or 0)
        local = np.concatenate([np.asarray(s.data) for s in shards],
                               axis=1)
        return local[:, :m, :]
