"""The Pallas fast path sharded over a device mesh.

Round 2 sharded only the XLA cores (``ShardedJaxBackend`` /
``ShardedBitslicedBackend``); the kernels the headline numbers come from
existed solely as single-chip programs.  This module runs them under
``jax.shard_map`` on the same (keys, points) mesh contract:

* ``ShardedPallasBackend`` — the fused VMEM walk kernel
  (``ops.pallas_eval.dcf_eval_pallas``, the flagship batch-eval path):
  keys shard the HBM-resident plane image, points shard the lane-word
  axis.  Each chip runs the unmodified kernel on its local
  (key-shard, word-shard) block; the walk is a pure map (reference
  parallelism: rayon over points, /root/reference/src/lib.rs:194-199), so
  there are no collectives inside it and scaling is linear modulo
  input/result movement.
* ``ShardedKeyLanesBackend`` — the many-keys kernel
  (``ops.pallas_keylanes``, the config-5 secure-ReLU path): the packed
  key-word axis shards over ``keys``, the shared-point axis over
  ``points``.
* ``ShardedLargeLambdaBackend`` — the large-lambda hybrid
  (``backends.large_lambda``, the config-4 path): keys shard the narrow
  plane image and the affine (const, W) decomposition, points shard the
  xs batch; the wide MXU matmul runs per key-shard.
* ``ShardedTreeFullDomain`` — the GGM tree expand kernel
  (``ops.pallas_tree``, the config-3 full-domain path): the level-k0
  frontier shards over ALL mesh devices (the tree is single-key, so both
  axes gang up on nodes); each device expands its disjoint sub-frontier
  to the leaves and verifies them locally with a shard-aware
  position->domain-value map, returning one counter per shard.
* ``ShardedDpfEvalAll`` — the DPF full-domain kernel
  (``ops.pallas_evalall``, the PIR engine): the K-keyed level-k0
  frontier shards its lane-word axis over all mesh devices; same
  disjoint-subtree expansion and shard-local verification as the DCF
  tree, minus the value accumulator.

Both are testable without hardware: construct with ``interpret=True`` on a
virtual CPU mesh (tests/test_sharding.py) — the Pallas interpreter lowers
to plain JAX ops, which shard_map partitions like any other program.  On a
real TPU mesh the same classes compile the Mosaic kernels per shard.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dcf_tpu.errors import ShapeError, StaleStateError
from dcf_tpu.backends._common import prepare_batch
from dcf_tpu.parallel._compat import shard_map
from dcf_tpu.backends.pallas_backend import (
    PallasBackend,
    _from_planes_jit,
    _stage_xs,
)
from dcf_tpu.backends.evalall import DpfEvalAll, leaf_pair_mismatch_count
from dcf_tpu.backends.fulldomain import TreeFullDomain, leaf_mismatch_count
from dcf_tpu.backends.large_lambda import (
    LargeLambdaBackend,
    _hybrid_eval_pallas,
    hybrid_prefix_gather_walk,
)
from dcf_tpu.backends.pallas_keylanes import KeyLanesPallasBackend
from dcf_tpu.backends.pallas_prefix import (
    MAX_PREFIX_LEVELS,
    PrefixPallasBackend,
    gather_and_walk,
)
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.pallas_eval import DEFAULT_TILE_WORDS, dcf_eval_pallas
from dcf_tpu.ops.pallas_keylanes import dcf_eval_keylanes_pallas
from dcf_tpu.ops.pallas_evalall import dpf_tree_expand_device
from dcf_tpu.ops.pallas_tree import tree_expand_device
from dcf_tpu.utils.bits import bitmajor_plane_masks

__all__ = ["ShardedPallasBackend", "ShardedKeyLanesBackend",
           "ShardedTreeFullDomain", "ShardedDpfEvalAll",
           "ShardedLargeLambdaBackend", "ShardedPrefixBackend"]


class ShardedPallasBackend(PallasBackend):
    """The flagship Pallas walk kernel under shard_map on a (keys, points)
    mesh.  Same API as ``PallasBackend`` (put_bundle / stage / eval_staged /
    eval); key count must divide the keys axis, and the point axis is padded
    so every point-shard is a whole number of kernel tiles."""

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh,
                 tile_words: int = DEFAULT_TILE_WORDS,
                 interpret: bool = False):
        super().__init__(lam, cipher_keys, tile_words=tile_words,
                         interpret=interpret)
        self.mesh = mesh
        kaxis, paxis = mesh.axis_names
        self._ksize = mesh.shape[kaxis]
        self._psize = mesh.shape[paxis]
        self._spec_keyed = P(kaxis)                     # [K, 128, 1]
        self._spec_xmask = P(kaxis, None, None, paxis)  # [K, n, 1, W]
        self._spec_xmask_shared = P(None, None, None, paxis)
        self._spec_y = P(kaxis, None, paxis)            # [K, 128, W]
        self._fns: dict = {}

    def _shard_fn(self, b: int, shared: bool, wt: int):
        """Cached jit(shard_map(kernel)) per (party, shared, tile, group)."""
        key = (b, shared, wt, self._group)
        fn = self._fns.get(key)
        if fn is None:
            fn = jax.jit(
                shard_map(
                    partial(dcf_eval_pallas, b=b, tile_words=wt,
                            interpret=self.interpret, group=self._group),
                    mesh=self.mesh,
                    in_specs=(
                        P(),                 # rk (replicated)
                        self._spec_keyed,    # s0_t
                        self._spec_keyed,    # cw_s_t
                        self._spec_keyed,    # cw_v_t
                        self._spec_keyed,    # cw_np1_t
                        self._spec_keyed,    # cw_t
                        self._spec_xmask_shared if shared
                        else self._spec_xmask,
                    ),
                    out_specs=self._spec_y,
                    check_vma=False,  # pure map, no collectives in the walk
                )
            )
            self._fns[key] = fn
        return fn

    def put_bundle(self, bundle: KeyBundle) -> None:
        if bundle.num_keys % self._ksize:
            raise ShapeError(
                f"num_keys={bundle.num_keys} not divisible by keys-axis "
                f"size {self._ksize}")
        super().put_bundle(bundle)

    def _put_plane(self, name: str, arr: np.ndarray) -> jax.Array:
        """Each device receives only its key shard of the host plane image
        (every staged array is keyed on axis 0) — no full-image transient
        on any single chip."""
        return jax.device_put(arr, NamedSharding(self.mesh, self._spec_keyed))

    def _plan_tiles(self, m: int) -> tuple[int, int]:
        """Per-SHARD tile plan: each point-shard gets the same whole number
        of kernel tiles; returns (tile words, padded total words across all
        shards)."""
        m_local = -(-m // self._psize) if m else 0
        wt, w_local = super()._plan_tiles(m_local)
        return wt, w_local * self._psize

    def _stage_sharded(self, xs: np.ndarray, shared: bool):
        key = ("stage", shared)
        stage = self._fns.get(key)
        if stage is None:
            spec = self._spec_xmask_shared if shared else self._spec_xmask
            stage = jax.jit(_stage_xs,
                            out_shardings=NamedSharding(self.mesh, spec))
            self._fns[key] = stage
        return stage(jnp.asarray(xs))

    def stage(self, xs: np.ndarray) -> dict:
        xs, m, wt = self._prepare(xs)
        if m == 0:
            raise ShapeError("cannot stage an empty batch")
        x_mask = self._stage_sharded(xs, xs.shape[0] == 1)
        return {"x_mask": x_mask, "m": m, "wt": wt}

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        dev = self._bundle_dev
        shared = staged["x_mask"].shape[0] == 1
        fn = self._shard_fn(int(b), shared, staged["wt"])
        return fn(self.rk, dev["s0"], dev["cw_s"], dev["cw_v"],
                  dev["cw_np1"], dev["cw_t"], staged["x_mask"])

    def eval(self, b: int, xs: np.ndarray,
             bundle: KeyBundle | None = None) -> np.ndarray:
        if bundle is not None:
            self.put_bundle(bundle)
        xs, m, wt = self._prepare(xs)
        if m == 0:
            return np.zeros(
                (self._bundle_dev["s0"].shape[0], 0, self.lam),
                dtype=np.uint8)
        x_mask = self._stage_sharded(xs, xs.shape[0] == 1)
        y = self.eval_staged(b, {"x_mask": x_mask, "m": m, "wt": wt})
        return self.staged_to_bytes(y, m)


class ShardedTreeFullDomain(TreeFullDomain):
    """Full-domain tree evaluation/verification sharded over a mesh.

    The GGM tree is single-key, so the frontier at level k0 (2^k0 nodes,
    bitreverse_k0 order) shards over ALL devices of the (keys, points)
    mesh: device q takes the contiguous frontier slice
    [q*2^k0/P, (q+1)*2^k0/P) and expands it to depth n independently —
    disjoint subtrees, no collectives (the exact structure the reference
    would get from rayon over subtrees).  Verification happens inside
    each shard: the local leaf at index l = e*2^c + fl (c frontier-local
    bits, e the device-level direction bits) has global walk directions
    (fl bits, then q bits, then e bits) and therefore domain value
    sum(d_i * 2^(n-1-i)); each device counts its own mismatches and the
    caller sums the P counters.

    ``host_levels`` must give every device at least one 32-node lane
    word: k0 >= 5 + log2(P) (the default raises the base class's 6 as
    needed).
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh,
                 host_levels: int | None = None, interpret: bool = False):
        p_total = 1
        for ax in mesh.axis_names:
            p_total *= mesh.shape[ax]
        if p_total & (p_total - 1):
            # api-edge: documented mesh-size contract
            raise ValueError(f"device count {p_total} must be a power of 2")
        self._log2p = p_total.bit_length() - 1
        min_k0 = 5 + self._log2p
        if host_levels is None:
            host_levels = max(6, min_k0)
        if host_levels < min_k0:
            raise ValueError(  # api-edge: constructor host_levels contract
                f"host_levels={host_levels} gives some device less than "
                f"one lane word of frontier; need >= {min_k0} for "
                f"{p_total} devices")
        super().__init__(lam, cipher_keys, host_levels=host_levels,
                         interpret=interpret)
        self.mesh = mesh
        self._ptotal = p_total
        self._axes = tuple(mesh.axis_names)
        self._spec_nodes = P(None, self._axes)  # [128|1, W] frontier/leaves
        self._fns: dict = {}

    def _put_nodes(self, arr) -> jax.Array:
        return jax.device_put(
            arr, NamedSharding(self.mesh, self._spec_nodes))

    def _check_fn(self, n_bits: int, gt: bool):
        key = (n_bits, gt)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        k0 = min(self.host_levels, n_bits)
        c = k0 - self._log2p  # frontier-local node bits per shard
        kaxis = self._axes[0]
        psize = self.mesh.shape[self._axes[1]]
        interp = self.interpret
        log2p = self._log2p

        def shard(rk, cw_s, cw_v, cw_t, cw_np1, s0, v0, t0, s1, v1, t1,
                  beta_mask, alpha):
            ys = [tree_expand_device(rk, cw_s, cw_v, cw_t, cw_np1, s, v, t,
                                     k0=k0, n=n_bits, interpret=interp)
                  for (s, v, t) in ((s0, v0, t0), (s1, v1, t1))]
            q = jax.lax.axis_index(kaxis) * psize + jax.lax.axis_index(
                self._axes[1])
            m_local = 32 * ys[0].shape[1]
            pos = jnp.arange(m_local, dtype=jnp.uint32)
            fl = pos & jnp.uint32((1 << c) - 1)
            e = pos >> c
            value = jnp.zeros(m_local, dtype=jnp.uint32)
            for i in range(c):  # frontier-local direction bits
                value = value | (((fl >> i) & 1) << (n_bits - 1 - i))
            for i in range(log2p):  # shard-index direction bits
                qbit = ((q.astype(jnp.uint32) >> i) & 1).astype(jnp.uint32)
                value = value | (qbit << (n_bits - 1 - c - i))
            for j in range(n_bits - k0):  # device-level direction bits
                value = value | (((e >> j) & 1) << (n_bits - 1 - k0 - j))
            inside = (value > alpha) if gt else (value < alpha)
            return leaf_mismatch_count(
                ys[0], ys[1], beta_mask, inside).reshape(1, 1)

        fn = jax.jit(
            shard_map(
                shard, mesh=self.mesh,
                in_specs=(P(), P(), P(), P(), P(),
                          *([self._spec_nodes] * 6), P(), P()),
                out_specs=P(*self._axes),  # [K, P] per-shard counters
                check_vma=False,  # disjoint subtrees, no collectives
            ))
        self._fns[key] = fn
        return fn

    def check_device(self, bundle: KeyBundle, alpha: int, beta: bytes,
                     n_bits: int, gt: bool = False) -> jax.Array:
        """Two-party full-domain reconstruction vs the plain comparison,
        sharded over the mesh; returns the TOTAL mismatch count as a
        device scalar (sum of the per-shard counters)."""
        if n_bits < self.host_levels:
            raise ShapeError(
                f"n_bits={n_bits} smaller than the {self.host_levels} "
                "host levels the mesh frontier needs; use the unsharded "
                "TreeFullDomain")
        if bundle.n_bits != n_bits:
            raise ShapeError("bundle depth mismatch")
        staged_cw, fronts, _parts = self._staged_for(bundle, n_bits)
        beta_mask = jnp.asarray(bitmajor_plane_masks(
            np.frombuffer(beta, dtype=np.uint8))[:, None])
        fn = self._check_fn(n_bits, gt)
        counts = fn(self.rk, *staged_cw, *fronts[0], *fronts[1],
                    beta_mask, jnp.uint32(alpha))
        return jnp.sum(counts)

    def _frontier(self, bundle: KeyBundle, b: int, k0: int):
        s, v, t = super()._frontier(bundle, b, k0)
        return self._put_nodes(s), self._put_nodes(v), self._put_nodes(t)


class ShardedDpfEvalAll(DpfEvalAll):
    """Full-domain DPF evaluation/verification sharded over a mesh.

    The DPF twin of ``ShardedTreeFullDomain`` with one extra axis: the
    node arrays are K-keyed ([K, 128, W] / [K, 1, W]), so the level-k0
    frontier shards its LANE-WORD axis over all devices of the
    (keys, points) mesh while every device holds all K keys — PIR
    serves one resident bundle of few keys against a domain of many
    leaves, so the leaf axis is the one worth cutting.  Device q takes
    the contiguous frontier slice [q*2^k0/P, (q+1)*2^k0/P) of every
    key and expands it to depth n independently — disjoint subtrees,
    no collectives.  Verification is shard-local: local leaf l =
    e*2^c + fl (c frontier-local bits, e device-level bits) has global
    walk directions (fl bits, then q bits, then e bits), hence domain
    value sum(d_i * 2^(n-1-i)); the caller sums the P counters.

    ``host_levels`` must give every device at least one 32-node lane
    word per key: k0 >= 5 + log2(P) (the default raises the base
    class's 6 as needed).
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh,
                 host_levels: int | None = None, interpret: bool = False):
        p_total = 1
        for ax in mesh.axis_names:
            p_total *= mesh.shape[ax]
        if p_total & (p_total - 1):
            # api-edge: documented mesh-size contract
            raise ValueError(f"device count {p_total} must be a power of 2")
        self._log2p = p_total.bit_length() - 1
        min_k0 = 5 + self._log2p
        if host_levels is None:
            host_levels = max(6, min_k0)
        if host_levels < min_k0:
            raise ValueError(  # api-edge: constructor host_levels contract
                f"host_levels={host_levels} gives some device less than "
                f"one lane word of frontier; need >= {min_k0} for "
                f"{p_total} devices")
        super().__init__(lam, cipher_keys, host_levels=host_levels,
                         interpret=interpret)
        self.mesh = mesh
        self._ptotal = p_total
        self._axes = tuple(mesh.axis_names)
        # [K, 128|1, W] frontier/leaf planes: shard the lane-word axis
        self._spec_nodes = P(None, None, self._axes)
        self._fns: dict = {}

    def _put_nodes(self, arr) -> jax.Array:
        return jax.device_put(
            arr, NamedSharding(self.mesh, self._spec_nodes))

    def _frontier(self, bundle, b: int, k0: int):
        s0, s1, t = super()._frontier(bundle, b, k0)
        return self._put_nodes(s0), self._put_nodes(s1), self._put_nodes(t)

    def _check_fn(self, n_bits: int):
        fn = self._fns.get(n_bits)
        if fn is not None:
            return fn
        k0 = min(self.host_levels, n_bits)
        c = k0 - self._log2p  # frontier-local node bits per shard
        kaxis = self._axes[0]
        psize = self.mesh.shape[self._axes[1]]
        interp = self.interpret
        log2p = self._log2p

        def shard(rk2, cs0_t, cs1_t, ct_pm, np10_t, np11_t,
                  s0_0, s1_0, t_0, s0_1, s1_1, t_1,
                  beta0_m, beta1_m, alphas):
            ys = [dpf_tree_expand_device(rk2, cs0_t, cs1_t, ct_pm,
                                         np10_t, np11_t, s0, s1, t,
                                         k0=k0, n=n_bits, interpret=interp)
                  for (s0, s1, t) in ((s0_0, s1_0, t_0),
                                      (s0_1, s1_1, t_1))]
            q = jax.lax.axis_index(kaxis) * psize + jax.lax.axis_index(
                self._axes[1])
            m_local = 32 * ys[0][0].shape[-1]
            pos = jnp.arange(m_local, dtype=jnp.uint32)
            fl = pos & jnp.uint32((1 << c) - 1)
            e = pos >> c
            value = jnp.zeros(m_local, dtype=jnp.uint32)
            for i in range(c):  # frontier-local direction bits
                value = value | (((fl >> i) & 1) << (n_bits - 1 - i))
            for i in range(log2p):  # shard-index direction bits
                qbit = ((q.astype(jnp.uint32) >> i) & 1).astype(jnp.uint32)
                value = value | (qbit << (n_bits - 1 - c - i))
            for j in range(n_bits - k0):  # device-level direction bits
                value = value | (((e >> j) & 1) << (n_bits - 1 - k0 - j))
            hit = (value[None, :] == alphas[:, None]).astype(jnp.uint32)
            bits = hit.reshape(hit.shape[0], -1, 32)
            inside = jax.lax.bitcast_convert_type(
                jnp.sum(bits << jnp.arange(32, dtype=jnp.uint32), axis=-1,
                        dtype=jnp.uint32), jnp.int32)[:, None, :]
            return leaf_pair_mismatch_count(
                ys[0][0], ys[0][1], ys[1][0], ys[1][1],
                beta0_m, beta1_m, inside).reshape(1, 1)

        fn = jax.jit(
            shard_map(
                shard, mesh=self.mesh,
                in_specs=(P(), P(), P(), P(), P(), P(),
                          *([self._spec_nodes] * 6), P(), P(), P()),
                out_specs=P(*self._axes),  # [K, P] per-shard counters
                check_vma=False,  # disjoint subtrees, no collectives
            ))
        self._fns[n_bits] = fn
        return fn

    def check_device(self, bundle, alphas, betas, n_bits: int) -> jax.Array:
        """Two-party full-domain reconstruction vs the point function,
        sharded over the mesh; returns the TOTAL mismatching-leaf count
        (all keys, whole domain) as a device scalar.  NOTE the sharded
        global leaf order differs from the unsharded one (the shard
        index splices into the middle of the bit-reversal) — parity is
        against the point function, not element order."""
        if n_bits < self.host_levels:
            raise ShapeError(
                f"n_bits={n_bits} smaller than the {self.host_levels} "
                "host levels the mesh frontier needs; use the unsharded "
                "DpfEvalAll")
        staged_cw, fronts, _parts = self._staged_for(bundle, n_bits)
        betas = np.asarray(betas, dtype=np.uint8)
        beta0_m = jnp.asarray(bitmajor_plane_masks(betas[:, :16])[..., None])
        beta1_m = jnp.asarray(bitmajor_plane_masks(betas[:, 16:])[..., None])
        alphas_u = jnp.asarray(np.asarray(alphas, dtype=np.uint32))
        fn = self._check_fn(n_bits)
        counts = fn(self.rk2, *staged_cw, *fronts[0], *fronts[1],
                    beta0_m, beta1_m, alphas_u)
        return jnp.sum(counts)


class ShardedLargeLambdaBackend(LargeLambdaBackend):
    """The large-lambda hybrid (narrow Pallas walk + GF(2) affine wide
    part) under shard_map: keys shard the narrow plane image AND the
    affine decomposition (const, W); points shard the shared xs batch.
    Pure map per (key-shard, point-shard) block — the narrow walk grids
    over local keys and the wide part runs its batched MXU matmul on the
    local key slice, so the reference's one large-lambda workload
    (benches/dcf_large_lambda.rs) scales out with zero collectives.

    Always uses the Pallas narrow walk (Mosaic on TPU meshes, the
    interpreter on virtual CPU meshes); the XLA-narrow layout stores keys
    on the trailing axis and is not wired for sharding.

    ``prefix_levels`` > 0 runs the prefix-shared narrow walk
    (ops.pallas_hybrid_prefix): the frontier tables are key material and
    shard over the KEYS axis with the rest of the bundle image; the
    per-point gather is a pure map against the local key shard's tables,
    so points shard with no collectives — same contract as the from-root
    path.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh,
                 col_chunk: int = 1 << 15, interpret: bool = False,
                 prefix_levels: int = 0):
        super().__init__(lam, cipher_keys, col_chunk=col_chunk,
                         narrow="pallas", interpret=interpret,
                         prefix_levels=prefix_levels)
        # A single-device planes dict has no shard placement: the serve
        # registry must stage this backend from the host bundle (the
        # put_bundle override below also rejects dev_planes typed).
        self.accepts_dev_planes = False
        self.mesh = mesh
        kaxis, paxis = mesh.axis_names
        self._ksize = mesh.shape[kaxis]
        self._psize = mesh.shape[paxis]
        self._spec_keyed = P(kaxis)              # [K, ...] bundle arrays
        self._spec_xs = P(None, paxis, None)     # [1, M, nb]
        self._spec_y = P(kaxis, paxis, None)     # [K, M, lam]
        self._spec_idx = P(paxis)                # [M] frontier positions
        self._spec_xmask_rem = P(None, None, None, paxis)
        self._fns: dict = {}

    def put_bundle(self, bundle: KeyBundle,
                   dev_planes: dict | None = None) -> None:
        if dev_planes is not None:
            # The parent (ISSUE 10) accepts a device-resident staged
            # image from the on-device keygen; this subclass re-places
            # every plane across the mesh's keys axis, and a
            # single-device planes dict has no shard placement — die
            # typed here instead of as a bare TypeError or a silently
            # unplaced image.
            raise ShapeError(
                "dev_planes is the single-device staged layout; the "
                "sharded hybrid backend stages from the host bundle "
                "and places shards itself")
        if bundle.num_keys % self._ksize:
            raise ShapeError(
                f"num_keys={bundle.num_keys} not divisible by keys-axis "
                f"size {self._ksize}")
        super().put_bundle(bundle)
        # The frontier build walks an eager pallas_call, which cannot
        # consume mesh-sharded operands — keep the single-device image
        # for it (prefix path only; the from-root path has no consumer
        # and must not pin a duplicate of the plane image).
        self._dev_host = dict(self._dev) if self.prefix_levels else None
        self._dev = {k: self._place_bundle_array(v)
                     for k, v in self._dev.items()}
        if self.prefix_levels:
            self._slice_cw_rem()  # re-slice from the PLACED image

    def _narrow_dev_for_build(self) -> dict:
        return self._dev_host

    def _place_bundle_array(self, v) -> jax.Array:
        """Place one keys-axis array (bundle plane, frontier table, wide
        factor) on the mesh.  Every bundle-image placement funnels
        through here so the pod-mesh subclass
        (``parallel.mesh_eval.MeshLargeLambdaBackend``) can swap in the
        host-local -> process-spanning-global conversion without
        re-implementing staging."""
        return jax.device_put(v, NamedSharding(self.mesh, self._spec_keyed))

    def _place_xs(self, xs: np.ndarray) -> jax.Array:
        """Place the padded points batch as [1, M, nb] sharded over the
        points axis — the other placement seam the pod subclass
        overrides (there, each process contributes its local slice)."""
        return jax.device_put(
            np.ascontiguousarray(xs)[None],
            NamedSharding(self.mesh, self._spec_xs))

    def _build_frontier_tables(self, b: int):
        """Build, then place across the mesh's keys axis — the cache
        (instance store or serve frontier cache) holds the PLACED copy,
        so a cache hit never re-broadcasts from device 0."""
        state_tbl, traj_tbl = super()._build_frontier_tables(b)
        return (self._place_bundle_array(state_tbl),
                self._place_bundle_array(traj_tbl))

    def _wide_staged(self):
        if self._wide is None:
            super()._wide_staged()
            self._wide = tuple(self._place_bundle_array(a)
                               for a in self._wide)
        return self._wide

    def stage(self, xs: np.ndarray) -> dict:
        if self._dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        if xs.ndim != 2:
            raise ShapeError("LargeLambdaBackend wants shared points [M, nb]")
        m = xs.shape[0]
        # Per-SHARD batches beyond one 4096-point tile must be whole tiles.
        local = -(-m // self._psize)
        granule = self._psize * (4096 if local > 4096 else 32)
        m_pad = -(-m // granule) * granule
        if m_pad != m:
            xs = np.pad(xs, [(0, m_pad - m), (0, 0)])
        xs_dev = self._place_xs(xs)
        staged = {"xs": xs_dev, "m": m}
        if self.prefix_levels:
            fields = self._prefix_stage_fields(
                jnp.asarray(xs)[None],
                min(128, m_pad // 32 // self._psize))
            fields["idx"] = jax.device_put(
                fields["idx"], NamedSharding(self.mesh, self._spec_idx))
            fields["x_mask_rem"] = jax.device_put(
                fields["x_mask_rem"],
                NamedSharding(self.mesh, self._spec_xmask_rem))
            staged.update(fields)
        return staged

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        const, w8 = self._wide_staged()
        dev = self._dev
        cc = self._col_chunk_for(self._bundle.num_keys // self._ksize)
        if self.prefix_levels:
            self._check_staged_fresh(staged)
            state_tbl, traj_tbl = self._frontier_tables(b)
            key = ("prefix", staged["k"], staged["wt"], cc)
            fn = self._fns.get(key)
            if fn is None:
                fn = jax.jit(
                    shard_map(
                        partial(hybrid_prefix_gather_walk,
                                col_chunk=cc, k=staged["k"],
                                frontier_size=1 << staged["k"],
                                tile_words=staged["wt"],
                                interpret=self.interpret),
                        mesh=self.mesh,
                        in_specs=(
                            P(),                   # rk2 (replicated)
                            self._spec_keyed,      # state_tbl
                            self._spec_keyed,      # traj_tbl
                            self._spec_idx,        # per-point positions
                            *([self._spec_keyed] * 4),  # remaining CWs
                            self._spec_keyed,      # np1a
                            self._spec_keyed,      # np1b
                            self._spec_keyed,      # cw_t remainder
                            self._spec_xmask_rem,
                            P(),                   # inv_perm
                            self._spec_keyed,      # wide const
                            self._spec_keyed,      # wide w8
                        ),
                        out_specs=self._spec_y,
                        check_vma=False,  # pure map, no collectives
                    ))
                self._fns[key] = fn
            cs0r, cs1r, cv0r, cv1r, cw_t_r = self._cw_rem
            return fn(self.rk2, state_tbl, traj_tbl, staged["idx"],
                      cs0r, cs1r, cv0r, cv1r, dev["np1a"], dev["np1b"],
                      cw_t_r, staged["x_mask_rem"], self._inv_perm,
                      const, w8)
        # cc is baked into the shard closure, so it must key the cache:
        # a later put_bundle with a different key count gets a fresh fn
        # (the unsharded base re-specializes via a jit static arg).
        fn = self._fns.get((int(b), cc))
        if fn is None:
            interp = self.interpret

            def shard(rk2, s0a, s0b, cs0, cs1, cv0, cv1, np1a, np1b,
                      cw_t, inv_perm, const_, w8_, xs):
                return _hybrid_eval_pallas(
                    rk2, s0a, s0b, cs0, cs1, cv0, cv1, np1a, np1b, cw_t,
                    inv_perm, const_, w8_, xs, b=int(b), col_chunk=cc,
                    interpret=interp)

            fn = jax.jit(
                shard_map(
                    shard, mesh=self.mesh,
                    in_specs=(P(), *([self._spec_keyed] * 9), P(),
                              self._spec_keyed, self._spec_keyed,
                              self._spec_xs),
                    out_specs=self._spec_y,
                    check_vma=False,  # pure map, no collectives
                ))
            self._fns[(int(b), cc)] = fn
        return fn(self.rk2, dev["s0a"], dev["s0b"], dev["cs0"], dev["cs1"],
                  dev["cv0"], dev["cv1"], dev["np1a"], dev["np1b"],
                  dev["cw_t"], self._inv_perm, const, w8, staged["xs"])


class ShardedKeyLanesBackend(KeyLanesPallasBackend):
    """The many-keys (config-5) Pallas kernel under shard_map: the packed
    key-word axis shards over ``keys``, shared points over ``points``.
    Same API as ``KeyLanesPallasBackend``; the key-word count is padded to
    a whole number of per-shard ``kw_tile`` granules."""

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh,
                 m_tile: int = 8, kw_tile: int = 128,
                 level_chunk: int = 8, interpret: bool = False):
        super().__init__(lam, cipher_keys, m_tile=m_tile, kw_tile=kw_tile,
                         level_chunk=level_chunk, interpret=interpret)
        self.mesh = mesh
        kaxis, paxis = mesh.axis_names
        self._ksize = mesh.shape[kaxis]
        self._psize = mesh.shape[paxis]
        self._spec_kw = P(None, kaxis)          # [n|128, Kw]
        self._spec_cw = P(None, None, kaxis)    # [n, 128, Kw]
        self._spec_xm = P(None, paxis, None)    # [n, M, 1]
        self._spec_y = P(None, paxis, kaxis)    # [128, M, Kw]
        self._fns: dict = {}

    def _kw_pad(self, kw: int) -> int:
        # Every shard must hold a whole number of kw_tile x 32-key granules.
        return -kw % (self._ksize * self.kw_tile)

    def _place_kw(self, arr):
        """Split each byte-major bundle array straight to the shards (the
        key-word axis is the trailing axis), so the bit-major conversion in
        the parent runs distributed and no chip holds the full image."""
        spec = self._spec_cw if arr.ndim == 3 else self._spec_kw
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def _m_granule(self) -> int:
        return self.m_tile * self._psize

    def _stage_mask(self, xs: np.ndarray) -> jax.Array:
        stage = self._fns.get("stage")
        if stage is None:
            from dcf_tpu.backends.pallas_keylanes import _stage_xs_keylanes

            stage = jax.jit(
                _stage_xs_keylanes,
                out_shardings=NamedSharding(self.mesh, self._spec_xm))
            self._fns["stage"] = stage
        return stage(jnp.asarray(xs))

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        dev = self._bundle_dev
        fn = self._fns.get(int(b))
        if fn is None:
            fn = jax.jit(
                shard_map(
                    partial(dcf_eval_keylanes_pallas, b=int(b),
                            m_tile=self.m_tile, kw_tile=self.kw_tile,
                            level_chunk=self.level_chunk,
                            interpret=self.interpret),
                    mesh=self.mesh,
                    in_specs=(
                        P(),            # rk
                        self._spec_kw,  # s0
                        self._spec_cw,  # cw_s
                        self._spec_cw,  # cw_v
                        self._spec_kw,  # cw_tl
                        self._spec_kw,  # cw_tr
                        self._spec_kw,  # cw_np1
                        self._spec_xm,  # x_mask
                    ),
                    out_specs=self._spec_y,
                    check_vma=False,
                )
            )
            self._fns[int(b)] = fn
        return fn(self.rk, dev["s0"][b], dev["cw_s"], dev["cw_v"],
                  dev["cw_tl"], dev["cw_tr"], dev["cw_np1"],
                  staged["x_mask"])


class ShardedPrefixBackend(PrefixPallasBackend):
    """The prefix-shared evaluator (backends.pallas_prefix, round 5 — the
    fastest single-key random-batch path) under shard_map.

    The workload is single-key, so the mesh's keys axis must be 1 (the
    CLI's auto factorization for the criterion benches, mesh 1xN) and all
    devices gang up on points.  The frontier gather table is key material
    and REPLICATES across point-shards — each device's points index the
    whole 2^k-node frontier, so a sharded table would turn the pure
    per-point map into an all-gather; at <= 67 MB (k = 21) replication is
    the right trade.  CW planes replicate likewise; the per-point gather
    + remaining-level walk is then a collective-free map, exactly like
    the from-root ShardedPallasBackend.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], mesh: Mesh,
                 prefix_levels: int = MAX_PREFIX_LEVELS,
                 tile_words: int = DEFAULT_TILE_WORDS,
                 interpret: bool = False, host_levels: int = 6):
        super().__init__(lam, cipher_keys, prefix_levels=prefix_levels,
                         tile_words=tile_words, interpret=interpret,
                         host_levels=host_levels)
        kaxis, paxis = mesh.axis_names
        if mesh.shape[kaxis] != 1:
            raise ShapeError(
                "ShardedPrefixBackend is single-key: use a 1xN mesh "
                f"(got keys axis {mesh.shape[kaxis]})")
        self.mesh = mesh
        self._psize = mesh.shape[paxis]
        self._spec_idx = P(paxis)
        self._spec_xmask_rem = P(None, None, None, paxis)
        self._spec_y = P(None, None, paxis)
        self._sfns: dict = {}

    def _put_plane(self, name: str, arr: np.ndarray) -> jax.Array:
        """All key material is REPLICATED here (single-key workload):
        placed across the mesh once at put_bundle, not re-broadcast from
        device 0 inside every timed dispatch (the trap the 1x1-mesh
        overhead measurement alone would never catch)."""
        return jax.device_put(arr, NamedSharding(self.mesh, P()))

    def _build_frontier_tables(self, b: int):
        """Build, then replicate across the mesh — the cache (instance
        store or serve frontier cache) holds the PLACED copy."""
        tbl = super()._build_frontier_tables(b)
        return jax.device_put(tbl, NamedSharding(self.mesh, P()))

    def _plan_tiles(self, m: int) -> tuple[int, int]:
        """Per-shard tile plan (each point-shard gets whole tiles)."""
        m_local = -(-m // self._psize) if m else 0
        wt, w_local = super()._plan_tiles(m_local)
        return wt, w_local * self._psize

    def stage(self, xs: np.ndarray) -> dict:
        staged = super().stage(xs)
        # Re-place the per-point arrays across the mesh's point axis (the
        # host staging above produced single-device arrays).
        staged["idx"] = jax.device_put(
            staged["idx"], NamedSharding(self.mesh, self._spec_idx))
        staged["x_mask_rem"] = jax.device_put(
            staged["x_mask_rem"],
            NamedSharding(self.mesh, self._spec_xmask_rem))
        return staged

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        self._check_staged_fresh(staged)  # StaleStateError on old bundles
        wt = staged["wt"]
        # Multi-key bundles ride the SAME mesh contract (keys axis 1 ->
        # every device walks all K keys on its point shard); k_num and
        # frontier_size must reach the shard body or it would silently
        # evaluate only key 0's frontier.
        k_num = self._dims()[0]
        fsize = 1 << self._k()
        negate = bool(b) and self._group != "xor"
        fn = self._sfns.get((wt, k_num, fsize, self._group, negate))
        if fn is None:
            fn = jax.jit(
                shard_map(
                    partial(gather_and_walk, tile_words=wt,
                            interpret=self.interpret,
                            k_num=k_num, frontier_size=fsize,
                            group=self._group, negate=negate),
                    mesh=self.mesh,
                    in_specs=(
                        P(),              # rk (replicated)
                        P(),              # frontier table (replicated)
                        self._spec_idx,   # per-point frontier positions
                        P(), P(), P(), P(),  # CW slices + cw_np1
                        self._spec_xmask_rem,
                    ),
                    out_specs=self._spec_y,
                    check_vma=False,  # pure map, no collectives
                )
            )
            self._sfns[(wt, k_num, fsize, self._group, negate)] = fn
        cw_s_r, cw_v_r, cw_t_r = self._cw_rem
        return fn(self.rk, self._frontier_tables(b), staged["idx"],
                  cw_s_r, cw_v_r, self._bundle_dev["cw_np1"], cw_t_r,
                  staged["x_mask_rem"])
