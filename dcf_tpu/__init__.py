"""dcf_tpu — TPU-native two-party Distributed Comparison Function framework.

A ground-up reimplementation of the capabilities of the reference Rust crate
xymeng16/dcf (GGM-tree DCF keygen, XOR-output-group batch evaluation,
AES-256 Hirose PRG, key serialization), redesigned for TPU:

- ``dcf_tpu.spec`` — pure-Python bit-exact golden model (see the package
  modules' own docstrings for the full map as they land: keys, gen, backends,
  ops, parallel).
- ``dcf_tpu.errors`` — the typed failure taxonomy (``DcfError`` family) and
  the ``BackendFallbackWarning`` degradation signal; see ``api``'s
  fault-tolerance docstring section.
- ``dcf_tpu.serve`` — the online evaluation service (micro-batching,
  device-resident key cache, admission control, metrics); entry point
  ``Dcf.serve(...)``, README "Serving" section.
- ``dcf_tpu.protocols`` — the mixed-mode protocol layer the paper
  builds DCF for: interval containment, MIC and piecewise-constant
  evaluation over K-packed batched DCF keys; entry points
  ``Dcf.interval``/``Dcf.mic``/``Dcf.piecewise``, README "Protocols"
  section.
"""

from dcf_tpu.api import Dcf, reset_backend_health  # noqa: F401
from dcf_tpu.errors import (  # noqa: F401
    BackendFallbackWarning,
    BackendUnavailableError,
    DcfError,
    DeadlineExceededError,
    KeyFormatError,
    NativeBuildError,
    QueueFullError,
    ShapeError,
    StaleStateError,
)
from dcf_tpu.spec import Bound, CmpFn, ReferenceContractWarning  # noqa: F401

__version__ = "0.1.0"
