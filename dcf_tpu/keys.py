"""Key material: structure-of-arrays bundles and serialization.

The reference keeps one ``Share`` per function (array-of-structs,
src/lib.rs:275-283) with hand-written positional serde.  On TPU the natural
layout is structure-of-arrays stacked over a key axis — the same arrays are
the HBM upload image for the eval kernels:

    s0s     uint8 [K, P, lam]   starting seeds (P = 2 from gen, 1 per party)
    cw_s    uint8 [K, n, lam]   correction-word seeds
    cw_v    uint8 [K, n, lam]   correction-word values
    cw_t    uint8 [K, n, 2]     (tl, tr) bits
    cw_np1  uint8 [K, lam]      final correction word

``cws``/``cw_np1`` are shared by both parties; only the starting seed differs
(src/lib.rs:269-272).  Two codecs are provided: ``.npz`` (convenience) and a
flat framed binary (``DCFK`` magic) that is the documented wire format the
reference's unused bincode/serde deps gesture at (SURVEY.md §3.5).

DCFK bytes on the wire (frozen; this is also the HBM upload image — the
device backends consume these exact arrays, reinterpreted, without any
re-serialization):

    offset  size            field
    0       4               magic ``b"DCFK"``
    4       2               version (uint16 LE, currently 1)
    6       2               P — parties stored (2 full bundle, 1 per-party)
    8       4               K — number of keys (uint32 LE)
    12      4               n — tree depth in bits = 8 * n_bytes (uint32 LE)
    16      2               lam — range size in bytes (uint16 LE)
    18      K*P*lam         s0s, C-order uint8
    ...     K*n*lam         cw_s
    ...     K*n*lam         cw_v
    ...     K*n*2           cw_t (tl, tr per level)
    ...     K*lam           cw_np1

No padding or alignment between sections; total size must match exactly.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from dcf_tpu import spec

__all__ = ["KeyBundle"]

_MAGIC = b"DCFK"
_VERSION = 1


@dataclass(frozen=True)
class KeyBundle:
    """K stacked DCF keys in structure-of-arrays layout."""

    s0s: np.ndarray  # uint8 [K, P, lam], P in {1, 2}
    cw_s: np.ndarray  # uint8 [K, n, lam]
    cw_v: np.ndarray  # uint8 [K, n, lam]
    cw_t: np.ndarray  # uint8 [K, n, 2]
    cw_np1: np.ndarray  # uint8 [K, lam]

    def __post_init__(self):
        k, n, lam = self.cw_s.shape
        if self.s0s.shape[0] != k or self.s0s.shape[2] != lam:
            raise ValueError("s0s shape mismatch")
        if self.s0s.shape[1] not in (1, 2):
            raise ValueError("s0s party dimension must be 1 or 2")
        if self.cw_v.shape != (k, n, lam) or self.cw_t.shape != (k, n, 2):
            raise ValueError("cw shape mismatch")
        if self.cw_np1.shape != (k, lam):
            raise ValueError("cw_np1 shape mismatch")
        if n % 8 != 0:
            raise ValueError("n must be a multiple of 8 bits")
        for a in (self.s0s, self.cw_s, self.cw_v, self.cw_t, self.cw_np1):
            if a.dtype != np.uint8:
                raise ValueError("all bundle arrays must be uint8")

    @property
    def num_keys(self) -> int:
        return self.cw_s.shape[0]

    @property
    def n_bits(self) -> int:
        return self.cw_s.shape[1]

    @property
    def n_bytes(self) -> int:
        return self.cw_s.shape[1] // 8

    @property
    def lam(self) -> int:
        return self.cw_s.shape[2]

    def for_party(self, b: int) -> "KeyBundle":
        """Restrict to party ``b``'s starting seed (s0s[:, b:b+1])."""
        if self.s0s.shape[1] != 2:
            raise ValueError("bundle already restricted to one party")
        if b not in (0, 1):
            raise ValueError(f"party must be 0 or 1, got {b}")
        return KeyBundle(
            s0s=self.s0s[:, b : b + 1].copy(),
            cw_s=self.cw_s,
            cw_v=self.cw_v,
            cw_t=self.cw_t,
            cw_np1=self.cw_np1,
        )

    def level_major(self) -> dict[str, np.ndarray]:
        """Arrays in the layout the eval scan consumes (level axis leading).

        Returns contiguous ``s0`` [K, lam] (party-restricted bundles only),
        ``cw_s``/``cw_v`` [n, K, lam], ``cw_t`` [n, K, 2], ``cw_np1`` [K, lam].
        This is the single definition of the device layout — every backend
        ships these arrays as-is.
        """
        if self.s0s.shape[1] != 1:
            raise ValueError("level_major requires a party-restricted bundle")
        return dict(
            s0=np.ascontiguousarray(self.s0s[:, 0, :]),
            cw_s=np.ascontiguousarray(self.cw_s.transpose(1, 0, 2)),
            cw_v=np.ascontiguousarray(self.cw_v.transpose(1, 0, 2)),
            cw_t=np.ascontiguousarray(self.cw_t.transpose(1, 0, 2)),
            cw_np1=np.ascontiguousarray(self.cw_np1),
        )

    # -- spec interop -------------------------------------------------------

    @classmethod
    def from_shares(cls, shares: list[spec.Share]) -> "KeyBundle":
        k = len(shares)
        n = len(shares[0].cws)
        lam = len(shares[0].cw_np1)
        p = len(shares[0].s0s)
        s0s = np.zeros((k, p, lam), dtype=np.uint8)
        cw_s = np.zeros((k, n, lam), dtype=np.uint8)
        cw_v = np.zeros((k, n, lam), dtype=np.uint8)
        cw_t = np.zeros((k, n, 2), dtype=np.uint8)
        cw_np1 = np.zeros((k, lam), dtype=np.uint8)
        for i, sh in enumerate(shares):
            for j, s0 in enumerate(sh.s0s):
                s0s[i, j] = np.frombuffer(s0, dtype=np.uint8)
            for j, cw in enumerate(sh.cws):
                cw_s[i, j] = np.frombuffer(cw.s, dtype=np.uint8)
                cw_v[i, j] = np.frombuffer(cw.v, dtype=np.uint8)
                cw_t[i, j] = (cw.tl, cw.tr)
            cw_np1[i] = np.frombuffer(sh.cw_np1, dtype=np.uint8)
        return cls(s0s, cw_s, cw_v, cw_t, cw_np1)

    def to_shares(self) -> list[spec.Share]:
        out = []
        for i in range(self.num_keys):
            cws = tuple(
                spec.Cw(
                    s=self.cw_s[i, j].tobytes(),
                    v=self.cw_v[i, j].tobytes(),
                    tl=bool(self.cw_t[i, j, 0]),
                    tr=bool(self.cw_t[i, j, 1]),
                )
                for j in range(self.n_bits)
            )
            out.append(
                spec.Share(
                    s0s=tuple(s.tobytes() for s in self.s0s[i]),
                    cws=cws,
                    cw_np1=self.cw_np1[i].tobytes(),
                )
            )
        return out

    # -- codecs -------------------------------------------------------------

    def to_bytes(self) -> bytes:
        """Flat framed binary: header + raw SoA arrays in a fixed order."""
        k, p = self.s0s.shape[0], self.s0s.shape[1]
        header = _MAGIC + struct.pack(
            "<HHIIH", _VERSION, p, k, self.n_bits, self.lam
        )
        return b"".join(
            [
                header,
                self.s0s.tobytes(),
                self.cw_s.tobytes(),
                self.cw_v.tobytes(),
                self.cw_t.tobytes(),
                self.cw_np1.tobytes(),
            ]
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "KeyBundle":
        if data[:4] != _MAGIC:
            raise ValueError("not a DCFK key bundle")
        try:
            version, p, k, n, lam = struct.unpack_from("<HHIIH", data, 4)
        except struct.error as e:
            raise ValueError(f"truncated key bundle header: {e}") from e
        if version != _VERSION:
            raise ValueError(f"unsupported key bundle version {version}")
        off = 4 + struct.calcsize("<HHIIH")

        def take(shape):
            nonlocal off
            size = int(np.prod(shape))
            arr = np.frombuffer(data, dtype=np.uint8, count=size, offset=off)
            off += size
            return arr.reshape(shape).copy()

        s0s = take((k, p, lam))
        cw_s = take((k, n, lam))
        cw_v = take((k, n, lam))
        cw_t = take((k, n, 2))
        cw_np1 = take((k, lam))
        if off != len(data):
            raise ValueError("trailing bytes in key bundle")
        return cls(s0s, cw_s, cw_v, cw_t, cw_np1)

    def save(self, path: str) -> None:
        if path.endswith(".npz"):
            np.savez(
                path,
                s0s=self.s0s,
                cw_s=self.cw_s,
                cw_v=self.cw_v,
                cw_t=self.cw_t,
                cw_np1=self.cw_np1,
            )
        else:
            with open(path, "wb") as fh:
                fh.write(self.to_bytes())

    @classmethod
    def load(cls, path: str) -> "KeyBundle":
        if path.endswith(".npz"):
            z = np.load(path)
            return cls(z["s0s"], z["cw_s"], z["cw_v"], z["cw_t"], z["cw_np1"])
        with open(path, "rb") as fh:
            return cls.from_bytes(fh.read())
