"""Key material: structure-of-arrays bundles and serialization.

The reference keeps one ``Share`` per function (array-of-structs,
src/lib.rs:275-283) with hand-written positional serde.  On TPU the natural
layout is structure-of-arrays stacked over a key axis — the same arrays are
the HBM upload image for the eval kernels:

    s0s     uint8 [K, P, lam]   starting seeds (P = 2 from gen, 1 per party)
    cw_s    uint8 [K, n, lam]   correction-word seeds
    cw_v    uint8 [K, n, lam]   correction-word values
    cw_t    uint8 [K, n, 2]     (tl, tr) bits
    cw_np1  uint8 [K, lam]      final correction word

``cws``/``cw_np1`` are shared by both parties; only the starting seed differs
(src/lib.rs:269-272).  Two codecs are provided: ``.npz`` (convenience) and a
flat framed binary (``DCFK`` magic) that is the documented wire format the
reference's unused bincode/serde deps gesture at (SURVEY.md §3.5).

DCFK bytes on the wire (the section layout is frozen; this is also the HBM
upload image — the device backends consume these exact arrays,
reinterpreted, without any re-serialization):

    offset  size            field
    0       4               magic ``b"DCFK"``
    4       2               version (uint16 LE, currently 2)
    6       2               P — parties stored (2 full bundle, 1 per-party)
    8       4               K — number of keys (uint32 LE)
    12      4               n — tree depth in bits = 8 * n_bytes (uint32 LE)
    16      2               lam — range size in bytes (uint16 LE)
    18      K*P*lam         s0s, C-order uint8
    ...     K*n*lam         cw_s
    ...     K*n*lam         cw_v
    ...     K*n*2           cw_t (tl, tr per level)
    ...     K*lam           cw_np1
    end-4   4               crc32 (uint32 LE, zlib.crc32 of all prior bytes;
                            version >= 2 only)

No padding or alignment between sections.  Version 2 (current writer)
appends the CRC32 integrity trailer; version-1 frames (no trailer) are
still read for compatibility.  Version 3 (``dcf_tpu.protocols``) adds a
uint16 ``proto`` field after ``lam``: proto=0 frames decode here
unchanged, proto!=0 frames carry a trailing protocol section (interval
combine masks) and are refused with a pointer at
``protocols.ProtocolBundle.from_bytes``.  Version 4 (PR 20) adds a
uint16 ``group`` field after ``proto`` — the output-group code
(``spec.GROUP_CODE``); only additive bundles write v4 (XOR stays v2,
byte-identical to earlier releases), so pre-v4 readers refuse additive
frames with "unsupported version" instead of silently reconstructing
with the wrong group.  Decoding is strict either way: the header
is bounds-checked field by field, every section must fit, the total size
must match exactly, and any violation raises
``errors.KeyFormatError`` naming the offending field — a two-party FSS
evaluation over silently-corrupt key material is worse than a crash.
"""

from __future__ import annotations

import math
import struct
import zlib
from dataclasses import dataclass

import numpy as np

from dcf_tpu import spec
from dcf_tpu.errors import KeyFormatError, ShapeError

__all__ = ["KeyBundle"]

_MAGIC = b"DCFK"
_VERSION = 2
_HEADER = "<HHIIH"  # version, P, K, n, lam (after the 4-byte magic)
_HEADER_SIZE = 4 + struct.calcsize(_HEADER)
_CRC_SIZE = 4
# Version 3 (dcf_tpu.protocols): the v2 header plus a uint16 ``proto``
# field.  proto=0 frames are plain bundles and decode here; proto!=0
# frames carry a protocol section (combine masks) and belong to
# ``protocols.ProtocolBundle.from_bytes`` — this reader refuses them
# rather than silently dropping the masks.
_VERSION_PROTO = 3
_HEADER3 = "<HHIIHH"  # version, P, K, n, lam, proto
_HEADER3_SIZE = 4 + struct.calcsize(_HEADER3)
# Version 4 (PR 20): the v3 header plus a uint16 ``group`` field — the
# output-group code from ``spec.GROUP_CODE`` (0 = xor, 1/2/3 = add8/16/32
# little-endian lanes over the lam payload bytes).  XOR bundles keep
# writing v2 frames (byte-identical to every earlier release); only
# additive bundles emit v4, so a v3-era reader refuses them loudly
# ("unsupported version 4") instead of silently reconstructing with the
# wrong group.
_VERSION_GROUP = 4
_HEADER4 = "<HHIIHHH"  # version, P, K, n, lam, proto, group
_HEADER4_SIZE = 4 + struct.calcsize(_HEADER4)


def _decode_sections(data: bytes, sections, header_size: int,
                     crc_size: int, claims: str) -> dict[str, np.ndarray]:
    """The strict section-decode discipline shared by every DCFK reader
    (``KeyBundle.from_bytes`` and ``protocols.ProtocolBundle.from_bytes``
    — ONE copy, so a hardening fix lands in both).

    ``sections``: ordered ``(name, shape)`` uint8 section table.
    ``claims``: the header's geometry fields rendered for error messages.
    Bounds-checks every section against the frame BEFORE touching the
    payload (so a truncated frame names the field where it ran out
    instead of surfacing a numpy buffer error — or worse, reading the
    CRC trailer as key material), requires the total size to match
    exactly, verifies the CRC32 trailer when ``crc_size`` is nonzero,
    then returns the decoded arrays by name.
    """
    payload_end = len(data) - crc_size
    off = header_size
    for name, shape in sections:
        size = math.prod(shape)  # python ints: immune to header-claimed
        if off + size > payload_end:  # sizes overflowing fixed-width math
            raise KeyFormatError(
                f"truncated frame: section {name!r} needs bytes "
                f"[{off}, {off + size}) but the payload ends at "
                f"{payload_end} (header claims {claims})")
        off += size
    if off != payload_end:
        raise KeyFormatError(
            f"oversized frame: {payload_end - off} trailing bytes after "
            f"section {sections[-1][0]!r} (corrupt header or concatenated "
            "frames)")
    if crc_size:
        (crc_stored,) = struct.unpack_from("<I", data, payload_end)
        # memoryview: hash in place — a bytes slice would transiently
        # double the footprint of a multi-GB key image.
        crc_actual = zlib.crc32(memoryview(data)[:payload_end])
        if crc_stored != crc_actual:
            raise KeyFormatError(
                f"crc32 mismatch: trailer records {crc_stored:#010x}, "
                f"frame hashes to {crc_actual:#010x} — key material is "
                "corrupt")
    off = header_size
    arrays: dict[str, np.ndarray] = {}
    for name, shape in sections:
        size = math.prod(shape)
        arr = np.frombuffer(data, dtype=np.uint8, count=size, offset=off)
        arrays[name] = arr.reshape(shape).copy()
        off += size
    return arrays


@dataclass(frozen=True)
class KeyBundle:
    """K stacked DCF keys in structure-of-arrays layout."""

    s0s: np.ndarray  # uint8 [K, P, lam], P in {1, 2}
    cw_s: np.ndarray  # uint8 [K, n, lam]
    cw_v: np.ndarray  # uint8 [K, n, lam]
    cw_t: np.ndarray  # uint8 [K, n, 2]
    cw_np1: np.ndarray  # uint8 [K, lam]
    group: str = "xor"  # output group (spec.GROUPS); wire v4 when additive

    def __post_init__(self):
        k, n, lam = self.cw_s.shape
        try:
            spec.check_group(self.group, lam)
        except ValueError as e:
            # constructor edge: group/geometry mismatch is a shape defect
            raise ShapeError(str(e)) from None
        if self.s0s.shape[0] != k or self.s0s.shape[2] != lam:
            raise ShapeError("s0s shape mismatch")
        if self.s0s.shape[1] not in (1, 2):
            raise ShapeError("s0s party dimension must be 1 or 2")
        if self.cw_v.shape != (k, n, lam) or self.cw_t.shape != (k, n, 2):
            raise ShapeError("cw shape mismatch")
        if self.cw_np1.shape != (k, lam):
            raise ShapeError("cw_np1 shape mismatch")
        if n % 8 != 0:
            raise ShapeError("n must be a multiple of 8 bits")
        for a in (self.s0s, self.cw_s, self.cw_v, self.cw_t, self.cw_np1):
            if a.dtype != np.uint8:
                raise ShapeError("all bundle arrays must be uint8")

    def __repr__(self) -> str:
        """Redacted: shapes/geometry only, never seed or CW bytes.

        The dataclass default repr prints field values — the arrays ARE
        the key material, so a stray ``f"{bundle}"`` in a log line or
        traceback would hand the other party the function.  The DCFK
        header fields (K, n, lam, parties) are exactly the non-secret
        part of the wire format; byte volume is disclosed as a size, not
        as contents.
        """
        k, n, lam = self.cw_s.shape
        secret_bytes = sum(
            a.nbytes
            for a in (self.s0s, self.cw_s, self.cw_v, self.cw_t,
                      self.cw_np1))
        return (f"KeyBundle(K={k}, n_bits={n}, lam={lam}, "
                f"parties={self.s0s.shape[1]}, group={self.group}, "
                f"<{secret_bytes} key-material bytes redacted>)")

    @property
    def num_keys(self) -> int:
        return self.cw_s.shape[0]

    @property
    def n_bits(self) -> int:
        return self.cw_s.shape[1]

    @property
    def n_bytes(self) -> int:
        return self.cw_s.shape[1] // 8

    @property
    def lam(self) -> int:
        return self.cw_s.shape[2]

    def for_party(self, b: int) -> "KeyBundle":
        """Restrict to party ``b``'s starting seed (s0s[:, b:b+1])."""
        if self.s0s.shape[1] != 2:
            raise ShapeError("bundle already restricted to one party")
        if b not in (0, 1):
            # api-edge: documented party-index contract
            raise ValueError(f"party must be 0 or 1, got {b}")
        return KeyBundle(
            s0s=self.s0s[:, b : b + 1].copy(),
            cw_s=self.cw_s,
            cw_v=self.cw_v,
            cw_t=self.cw_t,
            cw_np1=self.cw_np1,
            group=self.group,
        )

    def level_major(self) -> dict[str, np.ndarray]:
        """Arrays in the layout the eval scan consumes (level axis leading).

        Returns contiguous ``s0`` [K, lam] (party-restricted bundles only),
        ``cw_s``/``cw_v`` [n, K, lam], ``cw_t`` [n, K, 2], ``cw_np1`` [K, lam].
        This is the single definition of the device layout — every backend
        ships these arrays as-is.
        """
        if self.s0s.shape[1] != 1:
            raise ShapeError("level_major requires a party-restricted bundle")
        return dict(
            s0=np.ascontiguousarray(self.s0s[:, 0, :]),
            cw_s=np.ascontiguousarray(self.cw_s.transpose(1, 0, 2)),
            cw_v=np.ascontiguousarray(self.cw_v.transpose(1, 0, 2)),
            cw_t=np.ascontiguousarray(self.cw_t.transpose(1, 0, 2)),
            cw_np1=np.ascontiguousarray(self.cw_np1),
        )

    # -- spec interop -------------------------------------------------------

    @classmethod
    def from_shares(
        cls, shares: list[spec.Share], group: str = "xor"
    ) -> "KeyBundle":
        k = len(shares)
        n = len(shares[0].cws)
        lam = len(shares[0].cw_np1)
        p = len(shares[0].s0s)
        s0s = np.zeros((k, p, lam), dtype=np.uint8)
        cw_s = np.zeros((k, n, lam), dtype=np.uint8)
        cw_v = np.zeros((k, n, lam), dtype=np.uint8)
        cw_t = np.zeros((k, n, 2), dtype=np.uint8)
        cw_np1 = np.zeros((k, lam), dtype=np.uint8)
        for i, sh in enumerate(shares):
            for j, s0 in enumerate(sh.s0s):
                s0s[i, j] = np.frombuffer(s0, dtype=np.uint8)
            for j, cw in enumerate(sh.cws):
                cw_s[i, j] = np.frombuffer(cw.s, dtype=np.uint8)
                cw_v[i, j] = np.frombuffer(cw.v, dtype=np.uint8)
                cw_t[i, j] = (cw.tl, cw.tr)
            cw_np1[i] = np.frombuffer(sh.cw_np1, dtype=np.uint8)
        return cls(s0s, cw_s, cw_v, cw_t, cw_np1, group)

    def to_shares(self) -> list[spec.Share]:
        out = []
        for i in range(self.num_keys):
            cws = tuple(
                spec.Cw(
                    s=self.cw_s[i, j].tobytes(),
                    v=self.cw_v[i, j].tobytes(),
                    tl=bool(self.cw_t[i, j, 0]),
                    tr=bool(self.cw_t[i, j, 1]),
                )
                for j in range(self.n_bits)
            )
            out.append(
                spec.Share(
                    s0s=tuple(s.tobytes() for s in self.s0s[i]),
                    cws=cws,
                    cw_np1=self.cw_np1[i].tobytes(),
                )
            )
        return out

    # -- codecs -------------------------------------------------------------

    def to_bytes(self) -> bytes:
        """Flat framed binary: header + raw SoA arrays + CRC32 trailer.

        XOR bundles emit version-2 frames (byte-identical to earlier
        releases); additive bundles emit version-4 frames whose header
        carries the group code — old readers refuse them typed instead
        of reconstructing with the wrong group.
        """
        k, p = self.s0s.shape[0], self.s0s.shape[1]
        if self.group == "xor":
            header = _MAGIC + struct.pack(
                _HEADER, _VERSION, p, k, self.n_bits, self.lam
            )
        else:
            header = _MAGIC + struct.pack(
                _HEADER4, _VERSION_GROUP, p, k, self.n_bits, self.lam,
                0, spec.GROUP_CODE[self.group]
            )
        body = b"".join(
            [
                header,
                self.s0s.tobytes(),
                self.cw_s.tobytes(),
                self.cw_v.tobytes(),
                self.cw_t.tobytes(),
                self.cw_np1.tobytes(),
            ]
        )
        return body + struct.pack("<I", zlib.crc32(body))

    @classmethod
    def from_bytes(cls, data: bytes) -> "KeyBundle":
        """Strict bounds-checked DCFK decode.

        Accepts version 2 (CRC32 trailer, the current writer) and version 1
        (no trailer, legacy frames).  Rejects truncated, oversized or
        corrupt frames with ``KeyFormatError`` naming the offending field.
        """
        if len(data) < 4 or data[:4] != _MAGIC:
            raise KeyFormatError(
                f"bad magic: expected {_MAGIC!r}, got {bytes(data[:4])!r} "
                "(not a DCFK key bundle)")
        if len(data) < _HEADER_SIZE:
            raise KeyFormatError(
                f"truncated header: frame is {len(data)} bytes, the DCFK "
                f"header needs {_HEADER_SIZE}")
        version, p, k, n, lam = struct.unpack_from(_HEADER, data, 4)
        header_size = _HEADER_SIZE
        group = "xor"
        if version == _VERSION_GROUP:
            if len(data) < _HEADER4_SIZE:
                raise KeyFormatError(
                    f"truncated header: frame is {len(data)} bytes, the "
                    f"DCFK v4 header needs {_HEADER4_SIZE}")
            version, p, k, n, lam, proto, group_code = struct.unpack_from(
                _HEADER4, data, 4)
            header_size = _HEADER4_SIZE
            if proto != 0:
                raise KeyFormatError(
                    f"frame carries protocol section {proto}; decode with "
                    "the dcf_tpu.protocols bundle readers — reading it as "
                    "a plain bundle would misparse the sections")
            if group_code not in spec.GROUP_FROM_CODE:
                raise KeyFormatError(
                    f"unknown output-group code {group_code} (this reader "
                    f"handles {sorted(spec.GROUP_FROM_CODE)}); refusing to "
                    "guess a reconstruction group for key material")
            group = spec.GROUP_FROM_CODE[group_code]
            if group != "xor" and (8 * lam) % spec.GROUP_WIDTH[group]:
                raise KeyFormatError(
                    f"group {group!r} needs lam*8={8 * lam} divisible by "
                    f"{spec.GROUP_WIDTH[group]} — corrupt or mismatched "
                    "header fields")
        elif version == _VERSION_PROTO:
            if len(data) < _HEADER3_SIZE:
                raise KeyFormatError(
                    f"truncated header: frame is {len(data)} bytes, the "
                    f"DCFK v3 header needs {_HEADER3_SIZE}")
            version, p, k, n, lam, proto = struct.unpack_from(
                _HEADER3, data, 4)
            header_size = _HEADER3_SIZE
            if proto != 0:
                # proto ids live in dcf_tpu.protocols (keygen.PROTO_MIC=1,
                # dpf.PROTO_DPF=2); named here literally to keep keys.py
                # import-free of the protocol layer.
                if proto == 2:
                    raise KeyFormatError(
                        f"frame carries protocol section {proto} (DPF "
                        "point-function key, no cw_v); decode with "
                        "dcf_tpu.protocols.DpfBundle.from_bytes — reading "
                        "it as a plain bundle would misparse the sections")
                raise KeyFormatError(
                    f"frame carries protocol section {proto} (interval "
                    "combine masks); decode with dcf_tpu.protocols."
                    "ProtocolBundle.from_bytes — reading it as a plain "
                    "bundle would silently drop the public correction")
        elif version not in (1, _VERSION):
            raise KeyFormatError(
                f"unsupported version {version} (this reader handles "
                f"1..{_VERSION_GROUP})")
        if p not in (1, 2):
            raise KeyFormatError(f"parties field must be 1 or 2, got {p}")
        if n == 0 or n % 8:
            raise KeyFormatError(
                f"n field must be a positive multiple of 8 bits, got {n}")
        if lam == 0:
            raise KeyFormatError("lam field must be positive, got 0")
        sections = (
            ("s0s", (k, p, lam)),
            ("cw_s", (k, n, lam)),
            ("cw_v", (k, n, lam)),
            ("cw_t", (k, n, 2)),
            ("cw_np1", (k, lam)),
        )
        arrays = _decode_sections(
            data, sections, header_size,
            _CRC_SIZE if version >= 2 else 0,
            f"K={k}, P={p}, n={n}, lam={lam}")
        return cls(*(arrays[name] for name, _ in sections), group=group)

    def save(self, path: str) -> None:
        if path.endswith(".npz"):
            np.savez(
                path,
                s0s=self.s0s,
                cw_s=self.cw_s,
                cw_v=self.cw_v,
                cw_t=self.cw_t,
                cw_np1=self.cw_np1,
                group=np.uint16(spec.GROUP_CODE[self.group]),
            )
        else:
            with open(path, "wb") as fh:
                fh.write(self.to_bytes())

    @classmethod
    def load(cls, path: str) -> "KeyBundle":
        if path.endswith(".npz"):
            z = np.load(path)
            group = (spec.GROUP_FROM_CODE[int(z["group"])]
                     if "group" in z.files else "xor")
            return cls(z["s0s"], z["cw_s"], z["cw_v"], z["cw_t"],
                       z["cw_np1"], group)
        with open(path, "rb") as fh:
            return cls.from_bytes(fh.read())
