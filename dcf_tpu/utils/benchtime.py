"""Shared timing constants/helpers for the accelerator benches.

One digest-fetch sync costs a ~85ms round-trip on the tunneled dev device
(``block_until_ready`` does not block there), so timed samples dispatch
DISPATCHES_PER_SAMPLE evals and sync once; bench.py and the CLI share the
value so their methodologies cannot drift.
"""

from __future__ import annotations

import numpy as np

__all__ = ["DISPATCHES_PER_SAMPLE", "device_sync"]

# ~5ms of amortized sync against ~1.6s of kernel time at the flagship shape.
DISPATCHES_PER_SAMPLE = 16


def device_sync(y) -> None:
    """Tiny fetch depending on (the tail of) y; forces execution through
    the async tunnel.  In-order dispatch means the last output's readiness
    implies all prior dispatches completed."""
    import jax.numpy as jnp

    np.asarray(jnp.max(y.reshape(-1)[-8:].astype(jnp.int32)))
