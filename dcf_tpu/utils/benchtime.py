"""Shared timing constants/helpers for the accelerator benches.

One digest-fetch sync costs a ~85-155ms round-trip on the tunneled dev
device (``block_until_ready`` does not block there), so timed samples
dispatch DISPATCHES_PER_SAMPLE evals and sync once; bench.py and the CLI
share the value so their methodologies cannot drift.
"""

from __future__ import annotations

import numpy as np

__all__ = ["DISPATCHES_PER_SAMPLE", "DISPATCHES_PER_SAMPLE_SLOW",
           "DISPATCHES_PER_SAMPLE_TREE", "device_sync", "measure_sync_rtt",
           "monotonic"]


def monotonic() -> float:
    """The framework's one wall-clock seam: a monotonic seconds reading.

    Library code (the ``dcf_tpu.serve`` batcher's delay/deadline logic in
    particular) must NOT call ``time.*`` directly — the dcflint
    determinism pass enforces it — because a hidden clock read makes two
    runs of the "same" workload diverge un-reproducibly.  Instead,
    components take a ``clock`` callable defaulting to this function, so
    tests inject a fake clock and replay schedules deterministically
    while production gets ``time.monotonic`` (immune to wall-clock
    steps, the right base for deadlines and coalescing delays)."""
    import time

    return time.monotonic()

# ~1.2ms of amortized sync against ~100ms per dispatch at the flagship
# shape (measured 2026-07-31: 16 dispatches under-reported the chip by
# ~6% once the tunnel RTT grew to ~155ms).
DISPATCHES_PER_SAMPLE = 128

# For benches whose single dispatch is >= ~0.3s (large-lambda hybrid): the
# sync share is already < 3% at 16, and 128 would take minutes per sample.
DISPATCHES_PER_SAMPLE_SLOW = 16

# The full-domain tree dispatch is ~35 ms, fast enough that 16 dispatches
# left its median exposed to dispatch-submission jitter (round 4 quoted a
# 35% band, MAD/median ~ 0.25, the only headline that was a range instead
# of a number); 64 dispatches ~ 2.2 s/sample averages the jitter out while
# keeping a 5-sample run under 15 s.
DISPATCHES_PER_SAMPLE_TREE = 64


def device_sync(y) -> None:
    """Tiny fetch depending on (the tail of) y; forces execution through
    the async tunnel.  In-order dispatch means the last output's readiness
    implies all prior dispatches completed."""
    import jax.numpy as jnp

    np.asarray(jnp.max(y.reshape(-1)[-8:].astype(jnp.int32)))


def measure_sync_rtt(y, reps: int = 3) -> float:
    """Median bare round-trip of one ``device_sync`` on an already
    MATERIALIZED array: the tunnel-latency share a timed sample carries
    per sync, measured so benches can subtract it from the chip metric.
    The caller must have synced ``y`` already."""
    import time

    rtts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        device_sync(y)
        rtts.append(time.perf_counter() - t0)
    return float(np.median(rtts))
