"""CPU-platform provisioning for multi-device runs without real chips.

The multi-chip sharding path (dcf_tpu.parallel) is validated on N virtual
XLA CPU devices — the TPU-native analog of the reference's thread-count-
independent rayon parallelism (/root/reference/src/lib.rs:194-203).  Both
tests/conftest.py and __graft_entry__.dryrun_multichip need the same env
recipe, applied *before* the JAX backend initializes; keep it in one place.

This module must stay importable without importing jax.
"""

from __future__ import annotations

import os
from typing import MutableMapping

__all__ = ["force_cpu_devices", "enable_compile_cache", "repo_cache_dir"]

_COUNT_FLAG = "xla_force_host_platform_device_count"


def force_cpu_devices(env: MutableMapping[str, str], n_devices: int) -> None:
    """Mutate ``env`` so a JAX process started with it sees ``n_devices``
    virtual CPU devices (replacing any prior device-count flag)."""
    flags = [f for f in env.get("XLA_FLAGS", "").split() if _COUNT_FLAG not in f]
    flags.append(f"--{_COUNT_FLAG}={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_PLATFORM_NAME"] = "cpu"


def repo_cache_dir() -> str:
    """The one canonical machine-local cache location: <repo>/.jax_cache
    (this file lives at <repo>/dcf_tpu/utils/).  Every consumer resolves
    the path through here so a file move cannot silently fork a second,
    un-gitignored cache directory."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Point JAX's persistent compilation cache at ``cache_dir``
    (default: ``repo_cache_dir()``; live config mutation — safe any time
    before the first compile).

    The interpret-mode Pallas graphs (bitsliced AES unrolled per tree
    level) cost minutes of XLA CPU compile per suite run; measured on this
    host the cache turns a 104 s tree-fulldomain check into 15 s on the
    next cold process.  The cache is machine-local — XLA serializes host
    CPU features into the AOT result and warns (or worse) on a different
    machine — so ``cache_dir`` must stay out of version control; every
    consumer here points at the repo's gitignored ``.jax_cache/``.
    """
    import jax

    if cache_dir is None:
        cache_dir = repo_cache_dir()
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
