"""CPU-platform provisioning for multi-device runs without real chips.

The multi-chip sharding path (dcf_tpu.parallel) is validated on N virtual
XLA CPU devices — the TPU-native analog of the reference's thread-count-
independent rayon parallelism (/root/reference/src/lib.rs:194-203).  Both
tests/conftest.py and __graft_entry__.dryrun_multichip need the same env
recipe, applied *before* the JAX backend initializes; keep it in one place.

This module must stay importable without importing jax.
"""

from __future__ import annotations

from typing import MutableMapping

__all__ = ["force_cpu_devices"]

_COUNT_FLAG = "xla_force_host_platform_device_count"


def force_cpu_devices(env: MutableMapping[str, str], n_devices: int) -> None:
    """Mutate ``env`` so a JAX process started with it sees ``n_devices``
    virtual CPU devices (replacing any prior device-count flag)."""
    flags = [f for f in env.get("XLA_FLAGS", "").split() if _COUNT_FLAG not in f]
    flags.append(f"--{_COUNT_FLAG}={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_PLATFORM_NAME"] = "cpu"
