"""Vectorized output-group arithmetic on payload byte arrays.

The spec-level group tables and byte helpers live in ``dcf_tpu.spec``
(``GROUPS``/``GROUP_CODE``/``GROUP_WIDTH``, ``group_add`` on ``bytes``);
this module is their numpy counterpart, shared by the vectorized keygen
walk, the host backends and the protocol combine layer.  A payload axis
of ``lam`` uint8 bytes is read as ``8*lam/w`` little-endian w-bit lanes
(explicit ``<u{w/8}`` dtypes, so the view is byte-order-correct on any
host) and all arithmetic wraps mod 2^w per lane.

For XOR every helper degenerates to ``^`` / identity, so callers can be
group-generic without branching.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.spec import GROUP_WIDTH, check_group

__all__ = [
    "lane_dtype",
    "lanes_of",
    "bytes_of",
    "np_group_add",
    "np_group_sub",
    "np_group_neg",
    "np_group_reduce",
]

_LANE_DTYPE = {"add8": np.dtype("<u1"), "add16": np.dtype("<u2"),
               "add32": np.dtype("<u4")}


def lane_dtype(group: str) -> np.dtype:
    """The little-endian unsigned lane dtype of an additive group."""
    return _LANE_DTYPE[group]


def lanes_of(a: np.ndarray, group: str) -> np.ndarray:
    """uint8 [..., lam] -> lane view [..., 8*lam/w] (copy-free when
    contiguous).  The trailing axis must be the payload byte axis."""
    return np.ascontiguousarray(a).view(_LANE_DTYPE[group])


def bytes_of(lanes: np.ndarray, group: str) -> np.ndarray:
    """Inverse of :func:`lanes_of`: lane array -> uint8 byte array."""
    return np.ascontiguousarray(lanes.astype(_LANE_DTYPE[group],
                                             copy=False)).view(np.uint8)


def np_group_add(a: np.ndarray, b: np.ndarray, group: str) -> np.ndarray:
    """Group add on uint8 payload arrays (trailing axis = bytes)."""
    if group == "xor":
        return a ^ b
    return bytes_of(lanes_of(a, group) + lanes_of(b, group), group)


def np_group_sub(a: np.ndarray, b: np.ndarray, group: str) -> np.ndarray:
    """Group subtract ``a - b`` on uint8 payload arrays."""
    if group == "xor":
        return a ^ b
    return bytes_of(lanes_of(a, group) - lanes_of(b, group), group)


def np_group_neg(a: np.ndarray, group: str) -> np.ndarray:
    """Group negation on uint8 payload arrays (identity for XOR)."""
    if group == "xor":
        return a
    return bytes_of(-lanes_of(a, group), group)


def np_group_reduce(rows: np.ndarray, group: str, axis: int = 0) -> np.ndarray:
    """Group sum-reduce over ``axis`` of a uint8 payload array stack."""
    if group == "xor":
        return np.bitwise_xor.reduce(rows, axis=axis)
    w = GROUP_WIDTH[group]
    check_group(group, rows.shape[-1])
    acc = lanes_of(rows, group).astype(np.uint64).sum(axis=axis)
    return bytes_of(acc & np.uint64((1 << w) - 1), group)
