"""Bit-plane packing for the bitsliced eval path.

Layout conventions (used consistently by ops.aes_bitsliced and
backends.jax_bitsliced):

* A byte axis of size nbytes expands to 8*nbytes planes: plane index
  p = byte*8 + bit, bits LSB-first within each byte.
* A batch axis of size B is packed 32 elements per uint32 word (B must be a
  multiple of 32): word w holds elements w*32 .. w*32+31, element j in bit j.

All host-side prep is numpy; the packed arrays go to device as-is.
"""

from __future__ import annotations

from dcf_tpu.errors import ShapeError
import numpy as np

__all__ = [
    "pack_lanes",
    "unpack_lanes",
    "byte_bits_lsb",
    "byte_bits_msb",
    "planes_to_bytes",
    "bits_lsb_to_bytes",
    "expand_bits_to_masks",
    "bitmajor_perm",
    "bitmajor_plane_masks",
    "alpha_walk_bits",
]

_SHIFTS32 = np.arange(32, dtype=np.uint32)
_SHIFTS8 = np.arange(8, dtype=np.uint8)


def alpha_walk_bits(alpha: bytes) -> tuple:
    """alpha bytes -> its MSB-first walk-order bit tuple.

    Static (hashable) so the on-device parity counters can unroll the
    lexicographic compare over the staged bit-mask planes — one compile
    per key, the bench shape."""
    return tuple((byte >> (7 - k)) & 1 for byte in alpha for k in range(8))


def pack_lanes(bits: np.ndarray) -> np.ndarray:
    """Pack a trailing batch axis of {0,1} values into uint32 words.

    [..., B] (B % 32 == 0) -> uint32 [..., B//32].
    """
    b = bits.shape[-1]
    if b % 32 != 0:
        raise ShapeError(f"batch {b} not a multiple of 32")
    w = bits.astype(np.uint32).reshape(*bits.shape[:-1], b // 32, 32)
    return np.bitwise_or.reduce(w << _SHIFTS32, axis=-1)


def unpack_lanes(words: np.ndarray) -> np.ndarray:
    """Inverse of pack_lanes: uint32 [..., W] -> uint8 {0,1} [..., W*32]."""
    bits = (words[..., None] >> _SHIFTS32) & np.uint32(1)
    return bits.reshape(*words.shape[:-1], words.shape[-1] * 32).astype(np.uint8)


def byte_bits_lsb(arr: np.ndarray) -> np.ndarray:
    """uint8 [..., nbytes] -> {0,1} [..., 8*nbytes], plane order byte*8+bit."""
    bits = (arr[..., None] >> _SHIFTS8) & np.uint8(1)
    return bits.reshape(*arr.shape[:-1], arr.shape[-1] * 8)


def byte_bits_msb(arr: np.ndarray) -> np.ndarray:
    """uint8 [..., nbytes] -> {0,1} [..., 8*nbytes] in MSB-first walk order
    (bit i = the i-th bit consumed by the GGM tree walk)."""
    bits = (arr[..., None] >> _SHIFTS8[::-1]) & np.uint8(1)
    return bits.reshape(*arr.shape[:-1], arr.shape[-1] * 8)


def planes_to_bytes(planes: np.ndarray, nbytes: int) -> np.ndarray:
    """Packed planes [8*nbytes, ..., W] -> uint8 [..., W*32, nbytes]."""
    if planes.shape[0] != 8 * nbytes:
        raise ShapeError("plane count does not match nbytes")
    bits = unpack_lanes(planes)  # [8n, ..., B]
    bits = np.moveaxis(bits, 0, -1)  # [..., B, 8n]
    bits = bits.reshape(*bits.shape[:-1], nbytes, 8)
    return np.bitwise_or.reduce(bits << _SHIFTS8, axis=-1).astype(np.uint8)


def bits_lsb_to_bytes(bits: np.ndarray) -> np.ndarray:
    """Inverse of byte_bits_lsb: {0,1} [..., 8*nbytes] -> uint8 [..., nbytes]."""
    if bits.shape[-1] % 8 != 0:
        raise ShapeError("bit count not a multiple of 8")
    b8 = bits.reshape(*bits.shape[:-1], bits.shape[-1] // 8, 8)
    return np.bitwise_or.reduce(
        b8.astype(np.uint8) << _SHIFTS8, axis=-1).astype(np.uint8)


def expand_bits_to_masks(bits: np.ndarray) -> np.ndarray:
    """{0,1} array -> uint32 masks (0 or 0xFFFFFFFF), same shape."""
    return (bits.astype(np.uint32) * np.uint32(0xFFFFFFFF)).astype(np.uint32)


def bitmajor_plane_masks(a: np.ndarray) -> np.ndarray:
    """uint8 [..., 16] -> int32 bit-major plane masks [..., 128] (0 / -1).

    The staging step shared by every bit-major device backend (lam = 16):
    LSB-first bit planes, reordered to p' = bit*16 + byte, expanded to
    full/zero lane masks."""
    if a.shape[-1] != 16:
        raise ShapeError("bit-major plane masks are lam=16 only")
    bits = byte_bits_lsb(a)[..., bitmajor_perm(16)]
    return expand_bits_to_masks(bits).view(np.int32)


def bitmajor_perm(lam: int) -> np.ndarray:
    """Permutation taking byte-major planes to bit-major-within-block order.

    Byte-major plane index is p = byte*8 + bit (byte_bits_lsb).  The Pallas
    kernel wants planes grouped so that all 16 byte positions of one AES
    block sit contiguously for each bit: within 128-plane block ``blk``,
    p' = 128*blk + bit*16 + byte_in_block.  Returns ``perm`` (len 8*lam)
    such that ``planes_bm = planes[perm]``; ``np.argsort(perm)`` inverts.
    """
    perm = np.empty(8 * lam, dtype=np.int32)
    for p_new in range(8 * lam):
        blk, rem = divmod(p_new, 128)
        bit, byte_in_blk = divmod(rem, 16)
        perm[p_new] = (16 * blk + byte_in_blk) * 8 + bit
    return perm
