"""Shared utilities (bit packing for the bitsliced TPU path)."""
