"""Typed error taxonomy for the framework's fault-tolerance layer.

Every failure the facade can surface is a ``DcfError`` subclass, so callers
can catch the whole family (``except DcfError``) or a specific failure mode.
Each class also inherits the builtin exception the pre-taxonomy code raised
(``ValueError`` / ``RuntimeError``), so existing ``except ValueError``
call sites keep working — the taxonomy refines, it does not break.

One disclosed exception to that compatibility rule (PR 2's typed-error
sweep): evaluating before ``put_bundle`` ("no key bundle on device") was
a ``ValueError`` in the backends and is now ``StaleStateError`` (a
``RuntimeError``) — it is a state fault, not an argument fault, and
grouping it with geometry staleness is what lets callers write one
``except StaleStateError: re-ship and re-stage`` recovery path.

    DcfError
      +-- KeyFormatError         (ValueError)  corrupt/truncated/alien DCFK
      +-- ShapeError             (ValueError)  array shape/dtype contract
      +-- BackendUnavailableError(RuntimeError) no backend could serve
      +-- StaleStateError        (RuntimeError) staged state outlived bundle
      +-- NativeBuildError       (RuntimeError) C++ core build/load failed
      +-- QueueFullError         (RuntimeError) serve admission shed the load
      +-- DeadlineExceededError  (TimeoutError) request deadline expired
      +-- CircuitOpenError       (RuntimeError) breaker open: failing fast

The last three belong to the online serving layer (``dcf_tpu.serve``):
admission control sheds load with ``QueueFullError`` — at submit time
(queue bound hit, brownout refusal of low-priority classes, or a
draining service) or through the future when a queued request is
evicted to admit higher-priority traffic; a request whose deadline
passes before its batch is dispatched completes with
``DeadlineExceededError`` instead of a stale result; and a request
routed at a backend whose per-(key, backend-family) circuit breaker is
open fails fast with ``CircuitOpenError`` instead of burning retry
budget and deadline headroom on a backend known to be dying
(``serve.breaker``).

Recovery is signalled, not silent: whenever the framework degrades to a
slower-but-correct path (auto backend fallback, AES-NI -> portable native
core) it emits a ``BackendFallbackWarning`` carrying what failed, why, and
what now serves instead.
"""

from __future__ import annotations

__all__ = [
    "DcfError",
    "KeyFormatError",
    "ShapeError",
    "BackendUnavailableError",
    "StaleStateError",
    "NativeBuildError",
    "QueueFullError",
    "DeadlineExceededError",
    "CircuitOpenError",
    "BackendFallbackWarning",
]


class DcfError(Exception):
    """Base class of every typed framework error."""


class KeyFormatError(DcfError, ValueError):
    """A serialized key bundle failed validation (bad magic, unsupported
    version, truncated/oversized frame, CRC mismatch).  The message names
    the offending field."""


class ShapeError(DcfError, ValueError):
    """An array violated the bundle/batch shape or dtype contract."""


class BackendUnavailableError(DcfError, RuntimeError):
    """No execution backend could serve the request — the auto fallback
    chain was exhausted, or provisioning (devices/mesh) failed."""


class StaleStateError(DcfError, RuntimeError):
    """Device state is missing or out of date for the requested eval:
    staged state (a staged-points dict, a cached frontier) was built
    against a key bundle the backend no longer holds — re-stage — or no
    bundle was ever shipped (``eval`` before ``put_bundle``)."""


class NativeBuildError(DcfError, RuntimeError):
    """The C++ host core failed to build or load (after bounded retries)."""


class QueueFullError(DcfError, RuntimeError):
    """The serving layer's admission control shed a request: the
    queued-points bound was hit (overload — back off and retry), the
    service is in brownout and refused a low-priority class, or the
    service is draining/closed.  Usually raised at ``submit`` time; the
    one post-acceptance spelling is eviction — an already-queued
    lower-priority request completed with this error through its future
    because a higher-priority submit needed its room."""


class DeadlineExceededError(DcfError, TimeoutError):
    """An accepted request's deadline expired before its batch was
    dispatched; the request was dropped without evaluation (a late share
    is a useless share in an online 2PC round).  Surfaces through the
    request's result handle, not at ``submit``."""


class CircuitOpenError(DcfError, RuntimeError):
    """The per-(key_id, backend-family) circuit breaker is open: the
    backend family serving this key crossed its consecutive-failure
    threshold and the cooldown has not elapsed, so the request fails
    fast instead of re-entering a backend known to be dying (which
    would burn retry budget and deadline headroom for every queued
    request behind it).  CRITICAL-priority traffic bypasses the open
    state; after the cooldown one probe half-opens the breaker and its
    outcome decides between closing and re-opening.  Surfaces through
    the request's result handle (``serve.breaker``)."""


class BackendFallbackWarning(UserWarning):
    """The framework degraded to a slower-but-correct path.

    Structured: ``failed`` (what was tried), ``fallback`` (what now
    serves), ``cause`` (the triggering exception, possibly None).
    """

    def __init__(self, failed: str, fallback: str, cause: BaseException | None = None):
        self.failed = failed
        self.fallback = fallback
        self.cause = cause
        detail = f" ({type(cause).__name__}: {cause})" if cause is not None else ""
        super().__init__(
            f"backend {failed!r} unavailable{detail}; falling back to {fallback!r}"
        )
