"""Typed error taxonomy for the framework's fault-tolerance layer.

Every failure the facade can surface is a ``DcfError`` subclass, so callers
can catch the whole family (``except DcfError``) or a specific failure mode.
Each class also inherits the builtin exception the pre-taxonomy code raised
(``ValueError`` / ``RuntimeError``), so existing ``except ValueError``
call sites keep working — the taxonomy refines, it does not break.

One disclosed exception to that compatibility rule (PR 2's typed-error
sweep): evaluating before ``put_bundle`` ("no key bundle on device") was
a ``ValueError`` in the backends and is now ``StaleStateError`` (a
``RuntimeError``) — it is a state fault, not an argument fault, and
grouping it with geometry staleness is what lets callers write one
``except StaleStateError: re-ship and re-stage`` recovery path.

    DcfError
      +-- KeyFormatError         (ValueError)  corrupt/truncated/alien DCFK
      +-- ShapeError             (ValueError)  array shape/dtype contract
      +-- BackendUnavailableError(RuntimeError) no backend could serve
      +-- StaleStateError        (RuntimeError) staged state outlived bundle
      +-- NativeBuildError       (RuntimeError) C++ core build/load failed
      +-- QueueFullError         (RuntimeError) serve admission shed the load
      +-- DeadlineExceededError  (TimeoutError) request deadline expired
      +-- CircuitOpenError       (RuntimeError) breaker open: failing fast
      +-- KeyQuarantinedError    (RuntimeError) durable frame corrupt: set aside
      +-- BatchTimeoutError      (TimeoutError) batch overran its wall deadline
      +-- RingEpochError         (RuntimeError) frame fenced: sender's ring is stale
      +-- StandbyExhaustedError  (RuntimeError) scale-out wanted, standby pool empty
      +-- LockOrderError         (RuntimeError) lock acquired against the recorded order
      +-- MeshUnavailableError   (RuntimeError) co-evaluate mesh down: route-mode serves

The serve-layer classes belong to the online serving layer
(``dcf_tpu.serve``):
admission control sheds load with ``QueueFullError`` — at submit time
(queue bound hit, brownout refusal of low-priority classes, or a
draining service) or through the future when a queued request is
evicted to admit higher-priority traffic; a request whose deadline
passes before its batch is dispatched completes with
``DeadlineExceededError`` instead of a stale result; and a request
routed at a backend whose per-(key, backend-family) circuit breaker is
open fails fast with ``CircuitOpenError`` instead of burning retry
budget and deadline headroom on a backend known to be dying
(``serve.breaker``).  The durable key store (``serve.store``) sets a
corrupt or truncated on-disk frame aside at restore time and reports it
with ``KeyQuarantinedError`` — one damaged key must never be silently
skipped NOR take the other restored keys down with it; and the
hung-batch watchdog fails a dispatched batch that overran its
configured wall deadline with ``BatchTimeoutError``, feeding the same
breaker/retry machinery a plain failure would.  The capacity
controller (``serve.capacity``, ISSUE 16) refuses an explicit
scale-out when its declared standby pool is empty with
``StandbyExhaustedError`` — the automatic loop merely counts the skip,
but an operator-invoked ``scale_out()`` must fail typed, naming the
exhausted pool, instead of silently doing nothing.  The mesh
co-evaluation tier (``serve.meshgroup``, ISSUE 18) reports a mesh that
cannot take the scattered batch — a worker DOWN/suspect, the group's
epoch fenced behind a membership commit, or no group formed — with
``MeshUnavailableError``; in the router's default ``auto`` policy the
error never reaches the caller (co-evaluate degrades to route-mode,
counted and warned), but a caller who FORCED co-evaluation gets it
typed with the probe interval as ``retry_after_s``.

Recovery is signalled, not silent: whenever the framework degrades to a
slower-but-correct path (auto backend fallback, AES-NI -> portable native
core) it emits a ``BackendFallbackWarning`` carrying what failed, why, and
what now serves instead.
"""

from __future__ import annotations

__all__ = [
    "DcfError",
    "KeyFormatError",
    "ShapeError",
    "BackendUnavailableError",
    "StaleStateError",
    "NativeBuildError",
    "QueueFullError",
    "DeadlineExceededError",
    "CircuitOpenError",
    "KeyQuarantinedError",
    "BatchTimeoutError",
    "RingEpochError",
    "StandbyExhaustedError",
    "LockOrderError",
    "MeshUnavailableError",
    "BackendFallbackWarning",
]


class DcfError(Exception):
    """Base class of every typed framework error."""


class KeyFormatError(DcfError, ValueError):
    """A serialized key bundle failed validation (bad magic, unsupported
    version, truncated/oversized frame, CRC mismatch).  The message names
    the offending field."""


class ShapeError(DcfError, ValueError):
    """An array violated the bundle/batch shape or dtype contract."""


class BackendUnavailableError(DcfError, RuntimeError):
    """No execution backend could serve the request — the auto fallback
    chain was exhausted, or provisioning (devices/mesh) failed."""


class StaleStateError(DcfError, RuntimeError):
    """Device state is missing or out of date for the requested eval:
    staged state (a staged-points dict, a cached frontier) was built
    against a key bundle the backend no longer holds — re-stage — or no
    bundle was ever shipped (``eval`` before ``put_bundle``)."""


class NativeBuildError(DcfError, RuntimeError):
    """The C++ host core failed to build or load (after bounded retries)."""


class QueueFullError(DcfError, RuntimeError):
    """The serving layer's admission control shed a request: the
    queued-points bound was hit (overload — back off and retry), the
    service is in brownout and refused a low-priority class, a
    per-tenant token bucket at the network edge refused the points
    (``serve.edge``), or the service is draining/closed.  Usually
    raised at ``submit`` time; the one post-acceptance spelling is
    eviction — an already-queued lower-priority request completed with
    this error through its future because a higher-priority submit
    needed its room.

    ``retry_after_s`` (ISSUE 12): the caller-facing backoff hint, or
    ``None`` when no principled one exists (a draining service never
    comes back).  Populated from the refusal's own state — brownout
    hysteresis (``brownout_clear_s``: the calm the controller needs
    before it re-admits BATCH), queue pressure (about one coalescing
    drain), or the token bucket's exact time-to-refill — so the network
    edge serializes a number, not a bare "try later" string.

    ``evicted``: True for the post-acceptance spelling (the request
    WAS admitted — and counted — before a higher-priority submit took
    its room).  Load accounting needs the distinction: an evicted
    request appears in ``serve_requests_total``, a submit-time shed
    does not.  The network edge preserves it across the wire
    (``E_EVICTED``)."""

    def __init__(self, *args, retry_after_s: float | None = None,
                 evicted: bool = False):
        super().__init__(*args)
        self.retry_after_s = retry_after_s
        self.evicted = evicted


class DeadlineExceededError(DcfError, TimeoutError):
    """An accepted request's deadline expired before its batch was
    dispatched; the request was dropped without evaluation (a late share
    is a useless share in an online 2PC round).  Surfaces through the
    request's result handle, not at ``submit``."""


class CircuitOpenError(DcfError, RuntimeError):
    """The per-(key_id, backend-family) circuit breaker is open: the
    backend family serving this key crossed its consecutive-failure
    threshold and the cooldown has not elapsed, so the request fails
    fast instead of re-entering a backend known to be dying (which
    would burn retry budget and deadline headroom for every queued
    request behind it).  CRITICAL-priority traffic bypasses the open
    state; after the cooldown one probe half-opens the breaker and its
    outcome decides between closing and re-opening.  Surfaces through
    the request's result handle (``serve.breaker``).

    ``retry_after_s`` (ISSUE 12): the remaining cooldown of the open
    breaker (``BreakerBoard.retry_after`` — when it elapses the next
    request becomes the half-open probe), so the network edge can
    serialize a hint that tracks the actual recovery schedule instead
    of a guess.  ``None`` when the breaker state was not consulted."""

    def __init__(self, *args, retry_after_s: float | None = None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s


class KeyQuarantinedError(DcfError, RuntimeError):
    """A durable key-store frame failed validation when read back (bad
    magic, truncated payload, CRC mismatch — see ``KeyFormatError`` for
    the underlying rejection, carried as ``__cause__``) and was set
    aside: the file is renamed to ``<name>.quarantined-<n>`` and its
    manifest entry dropped, so the damage is preserved for forensics
    and the next restore does not trip over it again.  Raised by
    ``serve.store.KeyStore.load``; ``KeyRegistry.restore`` catches it
    PER KEY and records the quarantine in its report — one corrupt
    frame is never silently skipped and never fatal to the other keys
    (``serve.store``)."""


class BatchTimeoutError(DcfError, TimeoutError):
    """A dispatched serve batch overran the ``batch_timeout_s`` wall
    deadline on the injectable clock (a wedged backend: the eval
    neither completed nor errored in time).  The hung-batch watchdog
    fails the batch typed, records a failure outcome against the
    backend family that dispatched it (``serve.breaker``), and sends it
    down the same retry/invalidation path a plain batch failure takes —
    so a backend that hangs instead of crashing still demotes, still
    opens its breaker, and still stops stalling the worker while the
    queue sheds behind it (``serve.service``)."""


class RingEpochError(DcfError, RuntimeError):
    """A forwarded pod frame carried a ring epoch OLDER than one this
    shard has already observed: the sender routed on a stale membership
    view (ISSUE 15, ``serve.membership``).  Serving the request anyway
    could double-serve a key across two conflicting placements — the
    membership analog of the generation-fence rollback — so the shard
    refuses it structurally instead.  The sender must refresh its ring
    (``DcfRouter.set_ring`` with the current epoch) before retrying.

    ``retry_after_s``: a short constant hint — membership convergence
    is one control-plane round, not a load condition.  Crosses the wire
    as its own code (``E_EPOCH``), so a router can tell "my ring is
    stale" from every backend-health signal."""

    def __init__(self, *args, retry_after_s: float | None = None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s


class StandbyExhaustedError(DcfError, RuntimeError):
    """An explicit scale-out (``CapacityController.scale_out``) found
    the declared standby pool empty: there is no host to admit
    (ISSUE 16, ``serve.capacity``).  The AUTOMATIC scaling loop never
    raises this — it counts the skip
    (``capacity_skips_total{reason=no_standby}``) and keeps watching —
    but an operator asking for capacity that does not exist must get a
    typed refusal, not a silent no-op.  Recovery is declaring more
    standby hosts (``add_standby``), or draining elsewhere first."""


class LockOrderError(DcfError, RuntimeError):
    """A lock acquisition would close a cycle in the observed
    lock-order graph (ISSUE 17, ``dcf_tpu.testing.lockwatch``): some
    thread has taken lock B while holding lock A, and this thread is
    now taking A while holding B — the classic inversion that only
    deadlocks under the right interleave, which is exactly why it
    survives review and testing until production finds the interleave
    for you.

    Raised by the TSan-lite ``lockwatch`` harness, BEFORE the blocking
    acquire (the detector fails fast instead of reproducing the hang),
    and only when the harness is armed — chaos/soak CI legs and the
    ``lockwatch`` pytest marker; production code never constructs it.
    Carries ``cycle`` (the lock names along the inversion) and
    ``stacks`` (where each edge was first observed), so the report
    names both sides of the deadlock-to-be.  Deliberately has no wire
    code (``WIRE_INTERNAL_ONLY``): it fires in-process in test
    harnesses, never at a serving edge."""

    def __init__(self, message: str, *, cycle: tuple = (),
                 stacks: tuple = ()):
        super().__init__(message)
        self.cycle = tuple(cycle)
        self.stacks = tuple(stacks)


class MeshUnavailableError(DcfError, RuntimeError):
    """The device-mesh co-evaluation tier cannot take this batch
    (ISSUE 18, ``serve.meshgroup``): a mesh worker is DOWN or suspect,
    the mesh group's formation epoch is fenced behind a newer
    membership commit (the ring moved; the group must be re-formed),
    or the router simply has no group configured while the caller
    forced co-evaluation.  Route-mode — one host, one key — remains
    available: under the default ``co_eval="auto"`` policy the router
    absorbs this error itself (degrades the batch to route-mode,
    counted ``router_mesh_degraded_total`` + ``BackendFallbackWarning``,
    zero lost keys), so only a caller who demanded the mesh
    (``co_eval="always"``) ever sees it.

    ``retry_after_s``: one health-probe interval — the next probe
    round either recovers the worker or promotes its replacement, and
    a fenced group is one ``set_mesh`` re-formation away.  Crosses the
    wire as its own code (``E_MESH_UNAVAILABLE``) so a pod client can
    tell "the mesh is down, route-mode still serves" from
    ``E_UNAVAILABLE``'s backend-down signal."""

    def __init__(self, *args, retry_after_s: float | None = None):
        super().__init__(*args)
        self.retry_after_s = retry_after_s


class BackendFallbackWarning(UserWarning):
    """The framework degraded to a slower-but-correct path.

    Structured: ``failed`` (what was tried), ``fallback`` (what now
    serves), ``cause`` (the triggering exception, possibly None).
    """

    def __init__(self, failed: str, fallback: str, cause: BaseException | None = None):
        self.failed = failed
        self.fallback = fallback
        self.cause = cause
        detail = f" ({type(cause).__name__}: {cause})" if cause is not None else ""
        super().__init__(
            f"backend {failed!r} unavailable{detail}; falling back to {fallback!r}"
        )
