"""Top-level facade mirroring the reference's entry point.

The reference crate's surface is `DcfImpl::<N, LAMBDA>::new(prg)` with
``gen(f, s0s, bound) -> Share`` and ``eval(b, k, xs, ys)``
(/root/reference/src/lib.rs:24-35, 63-77).  ``Dcf`` is the runtime-shape
equivalent: construct once with (n_bytes, lam, cipher_keys), pick an
execution backend by name, and go.

    >>> dcf = Dcf(n_bytes=16, lam=16, cipher_keys=[k0, k1])
    >>> bundle = dcf.gen(alphas, betas)              # K keys at once
    >>> y0 = dcf.eval(0, bundle, xs)                 # uint8 [K, M, lam]

Backends (selected at construction, ``backend=``):

    auto       pallas on TPU / bitsliced elsewhere (lam=16), hybrid for
               lam >= 48, bitsliced otherwise
    cpu        the C++ native core (AES-NI, threaded)
    numpy      the host oracle
    jax        byte-level lax.scan walk
    bitsliced  XLA bit-plane walk
    pallas     fused VMEM walk kernel (lam=16)
    prefix     prefix-shared walk: per-key top-k tree frontiers cached
               + per-point gather + n-k walked levels (lam=16, shared
               points, K >= 1 — the fastest random-batch path)
    keylanes   keys-in-lanes walk kernel (many keys x few points, the
               config-5 shape; lam=16; wants the full two-party bundle —
               its CW image is shared between parties)
    hybrid     narrow walk + GF(2)-affine wide part (lam >= 48).
               ``backend_opts={"prefix_levels": k}`` switches its narrow
               walk to the prefix-shared path (round 6,
               ops.pallas_hybrid_prefix): top-k levels expanded once per
               (key, party) as a cached gather table, n-k walked levels
               per point (Pallas-only; the facade applies the same
               off-TPU interpreter rule as keylanes/prefix)

Passing ``mesh=parallel.make_mesh(...)`` makes the same facade run the
sharded variants — the reference gets its parallelism transparently from
``DcfImpl`` (rayon over points, /root/reference/src/lib.rs:194-199), and
the mesh equivalent should be just as transparent:

    >>> dcf = Dcf(16, 16, keys, mesh=make_mesh(shape=(4, 2)))
    >>> dcf.eval(0, bundle, xs)       # ShardedPallasBackend underneath

    auto       sharded pallas walk kernel (lam=16), sharded hybrid
               (lam >= 48), sharded bitsliced elsewhere
    pallas     parallel.ShardedPallasBackend (flagship walk kernel)
    prefix     parallel.ShardedPrefixBackend (prefix-shared walk;
               single key, 1xN points mesh)
    keylanes   parallel.ShardedKeyLanesBackend (many keys x few points,
               the config-5 shape; both parties share one device image)
    hybrid     parallel.ShardedLargeLambdaBackend (large lambda: narrow
               walk + affine wide part, keys+points sharded; also takes
               ``backend_opts={"prefix_levels": k}`` — frontier tables
               shard with the key image, the gather stays a pure map)
    bitsliced  parallel.ShardedBitslicedBackend
    jax        parallel.ShardedJaxBackend

Key counts must be divisible by the mesh's keys-axis size for pallas/hybrid/
bitsliced/jax (keylanes pads ragged key counts to its shard granule);
ship-once key caching works exactly as in the single-device case.
``cpu``/``numpy`` are host paths and reject a mesh.  ``backend_opts=`` forwards
constructor keywords to the selected backend (e.g. ``tile_words`` for
pallas, ``m_tile``/``kw_tile``/``level_chunk`` for keylanes).

Measured auto-routing crossover (refreshed round 6)
---------------------------------------------------

``backend="auto"``'s ``lam >= 48 -> hybrid`` threshold is the measured
winner at every recorded shape, not a guess.  Rates from
``benchmarks/RESULTS_r04.jsonl`` / ``RESULTS_r05.jsonl`` (TPU v5 lite,
criterion-grade median, full two-party device parity on every line);
vs_baseline now uses the PINNED per-shape single-core denominators
(``benchmarks/cpu_baseline.json``, CPU_BASELINE.md protocol — the
lam-shape pins are round-6 flagship-ratio transfers); asserted by
``tests/test_api.py::test_auto_routing_crossover`` (+ the slow
lam=16384 companion):

    lam (bytes)  auto picks  measured rate        vs pinned 1-core CPU
    16           pallas      10.77M evals/s       102x  (the explicit
                 (TPU; bitsliced off-TPU)          prefix backend does
                                                   12.18M = 115.6x)
    48           hybrid      runs end-to-end (extension band,
                             tests/test_extension_band.py); no recorded
                             bench line yet
    128          hybrid      3.19M evals/s        26.3x (lam128 pin)
    256          hybrid      2.87-3.21M evals/s   34.9-39.0x (lam256 pin)
    16384        hybrid      932k  evals/s        566x  (lam16384 pin)

The bitsliced path serves the 16 < lam < 48 band (hybrid's GF(2) wide
part needs lam >= 48, a multiple of 16).  The mid-lam valley (128/256,
the only measured shapes below the 100x bar) is decomposed and priced
in benchmarks/ROOFLINE.md round 6: it is the narrow walk itself
(2x the flagship's cipher work per point at the 512-lane penalty
point), and the shipped structural lever is the prefix-shared hybrid
(``backend_opts={"prefix_levels": k}``), expected +13-16% at the bench
shape with the remaining headroom priced at the cipher floor.  Auto
keeps the from-root hybrid until a chip session records the
prefix-enabled crossover; these thresholds move with the measurements.

Key generation runs on the C++ core when available, else numpy —
unless ``gen(..., device=True)``, which runs the GGM level walk ON the
accelerator through ``gen.gen_on_device`` (ISSUE 10): lam >= 48 uses
the Pallas narrow keygen kernel + affine wide tail
(``ops.pallas_keygen`` — ONE shared level-walk core with the eval
kernels), smaller lams the keys-in-lanes XLA generator
(``backends.device_gen``), with the keylanes-style off-TPU interpreter
rule and a counted, warned fallback to the host walk on any device
failure (seam ``keygen.device``).  The protocol generators
(``interval``/``mic``/``piecewise``) take the same ``device=`` flag —
an m-interval MIC's 2m bound keys are one K-packed device keygen.
Bundles are byte-identical across pipelines, so wire frames, serve
registration and the durable store cannot tell them apart.
Full-domain evaluation (``backends.fulldomain.TreeFullDomain``, domain
expansion rather than point evaluation) stays an explicit
constructor-level choice; the keylanes *eval* kernel, by contrast, IS
a facade backend (``backend="keylanes"``, with or without a mesh).

Fault tolerance (the ``dcf_tpu.errors`` taxonomy)
-------------------------------------------------

Failures surface as typed ``errors.DcfError`` subclasses instead of
opaque ``RuntimeError``/``struct.error``/XLA tracebacks:

    KeyFormatError           corrupt/truncated/alien DCFK frame (the v2
                             wire format carries a CRC32 trailer; v1
                             frames are still read)
    ShapeError               array shape/dtype contract violations
    BackendUnavailableError  the auto fallback chain exhausted, or
                             device/mesh provisioning failed
    StaleStateError          a staged-points dict outlived the bundle it
                             was staged against (prefix backend)
    NativeBuildError         the C++ core failed to build/load after
                             bounded retries
    KeyQuarantinedError      a durable key-store frame failed validation
                             at read time and was set aside (renamed,
                             counted, never fatal to the other keys)
    BatchTimeoutError        a dispatched serve batch overran the
                             hung-batch watchdog's wall deadline

``Dcf.reset_backend_health()`` (or the module-level function — one
shared invalidation path) wipes the process verdict cache AND notifies
every registered holder of backend-derived state: live facades drop
their constructed backends/shipped bundles (an ``auto`` facade
re-selects lazily on its next eval) and serving registries
(``dcf_tpu.serve``) evict their device-resident key images.

``backend="auto"`` (single-device) is self-healing: the selected backend
must first pass a tiny spec-checked canary eval (1 key x 2 points, both
parties reconstructed bit-exactly against the comparison function).  On
any canary failure — Mosaic lowering error, broken XLA install, missing
toolchain — selection degrades pallas -> bitsliced -> jax -> numpy,
emitting one ``errors.BackendFallbackWarning`` per skipped backend; only
when the whole chain fails does construction raise
``BackendUnavailableError``.  Canary verdicts are cached per
(backend, lam) for the process (``reset_backend_health()`` forgets
them).  Explicitly named backends stay strict: no canary, no silent
substitution.  The native keygen core degrades AES-NI -> portable S-box
the same way (``native.load``), warning instead of crashing.

Online serving (``Dcf.serve`` -> ``dcf_tpu.serve.DcfService``)
--------------------------------------------------------------

``dcf.serve(**knobs)`` wraps this facade in the online evaluation
service: named long-lived key bundles, micro-batched ragged requests,
LRU device residency, admission control, metrics.  The load-bearing
knobs are ``max_batch`` (throughput / compiled-shape universe),
``max_delay_ms`` (coalescing latency), ``device_bytes_budget`` (hot key
working set — shared by staged images and cached frontiers),
``frontier_cache`` (ISSUE 7, default on: prefix-family frontier
expansions live in a serve-resident LRU keyed (key_id, generation,
party, k) and survive residency churn, so a re-staged hot key skips
the 2^k-node top-k expansion; ``serve_frontier_hits_total`` /
``_misses_total`` in the snapshot; False = the pre-cache
instance-store behavior), ``max_queued_points`` (shed point),
``retries`` (fail-over persistence), ``store_dir`` (ISSUE 8: the
durable key store — ``register_key(..., durable=True)`` persists the
frame atomically before acking and ``restore_keys()`` warm-restarts
the registry with generations preserved and zero re-keygen; damaged
frames quarantine typed), ``batch_timeout_s`` (the hung-batch
watchdog: an overdue dispatch fails ``BatchTimeoutError`` into the
breaker/retry path instead of stalling the worker) and
``keyfactory_refill_interval_s`` (ISSUE 11, the key factory:
``add_pool(PoolSpec(...))`` declares ahead-of-demand keygen pools
topped up on device in K-packed batches and published to the store in
batched atomic manifest flips; ``register_key(key_id, pool=...)``
then mints a fresh session key at pool-pop latency with a counted,
warned synchronous fallback on exhaustion); full semantics in
``dcf_tpu/serve/service.py`` and the README "Serving" /
"Durability & restart" / "Key factory" sections.

Mixed-mode protocols (``dcf_tpu.protocols``)
--------------------------------------------

DCF is the building block of mixed-mode 2PC (the source paper's actual
point): ``Dcf.interval`` / ``Dcf.mic`` / ``Dcf.piecewise`` generate
interval-containment, multiple-interval-containment and
piecewise-constant keys — the 2m interval-bound DCF keys of an
m-interval MIC packed on the K axis, the batched walk kernels' best
shape — and ``Dcf.eval_interval`` / ``eval_mic`` / ``eval_piecewise``
evaluate them on any facade backend (meshes included).  Protocol
bundles register directly into ``Dcf.serve(...)`` services, which apply
the share combine server-side under the same admission/deadline/retry
semantics.  XOR-group derivation, wraparound handling and the DCFK v3
wire format: README "Protocols" section.
"""

from __future__ import annotations

import weakref

from typing import Sequence

import numpy as np

import warnings

from dcf_tpu.errors import (
    BackendFallbackWarning,
    BackendUnavailableError,
    ShapeError,
)
from dcf_tpu.gen import gen_batch, gen_on_device, random_s0s
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.spec import (
    Bound,
    ReferenceContractWarning,
    hirose_used_cipher_indices,
)

__all__ = ["Dcf", "reset_backend_health", "register_reset_listener"]


def _default_backend(lam: int) -> str:
    if lam == 16:
        try:
            import jax

            if jax.devices()[0].platform == "tpu":  # Mosaic is TPU-only
                return "pallas"
        except Exception:  # fallback-ok: no usable jax -> host bitsliced
            pass
        return "bitsliced"
    return "hybrid" if lam >= 48 else "bitsliced"


# Auto-selection fallback order (fastest first, numpy the always-works
# floor); _auto_chain starts at the selected backend and appends the
# remaining tail.  Canary verdicts cache per (backend, lam, opts) for the
# process so repeated Dcf(...) constructions don't re-run tiny compiles —
# opts are part of the key because the canary instance is built WITH them,
# so a verdict for one opts set says nothing about another.
_FALLBACK_CHAIN = ("pallas", "bitsliced", "jax", "numpy")
_HEALTHY: set = set()
_UNHEALTHY: dict = {}  # health key -> first failure; skips re-running a
# failing canary (seconds of doomed compile) on every construction

# The ONE invalidation path for cached backend state (PR 4): objects
# holding state derived from a selected backend — every live Dcf (its
# constructed eval backends + shipped bundles) and every serve-layer
# KeyRegistry (device-resident key images) — register here, weakly, and
# get ``_on_backend_health_reset()`` when verdicts are wiped.  Without
# this, a backend declared dead mid-serve would keep serving from its
# cached device state while fresh constructions fall back.
_RESET_LISTENERS: "weakref.WeakSet" = weakref.WeakSet()


def register_reset_listener(obj) -> None:
    """Subscribe ``obj`` (held weakly) to backend-health resets; it must
    define ``_on_backend_health_reset()``, which should drop any cached
    state tied to a backend selection (staged images, backend instances).
    ``Dcf`` instances and ``serve.DcfService`` register automatically."""
    _RESET_LISTENERS.add(obj)


def reset_backend_health() -> None:
    """Forget cached canary verdicts (tests; a recovered driver/toolchain)
    AND invalidate every registered holder of backend-derived cached
    state — live facades re-ship/re-select lazily on their next eval, and
    serve registries evict their device-resident key images.  One path:
    there is no way to wipe verdicts while stale device state lingers."""
    _HEALTHY.clear()
    _UNHEALTHY.clear()
    for obj in list(_RESET_LISTENERS):
        obj._on_backend_health_reset()


class _BackendMisuse(Exception):
    """Canary-internal marker: the backend constructor rejected its
    arguments (a programmer error, e.g. a typo'd backend_opts key) —
    must surface as TypeError, not count as environment ill-health."""


class Dcf:
    """Runtime-configured DCF: the `DcfImpl` equivalent.

    Shapes are runtime values (JAX specializes at trace time) instead of
    the reference's const generics.
    """

    def __init__(self, n_bytes: int, lam: int, cipher_keys: Sequence[bytes],
                 backend: str = "auto", mesh=None,
                 backend_opts: dict | None = None):
        if n_bytes < 1:
            # api-edge: constructor argument contract
            raise ValueError("n_bytes must be >= 1")
        self.n_bytes = n_bytes
        self.lam = lam
        self.cipher_keys = list(cipher_keys)
        self.mesh = mesh
        self._backend_opts = dict(backend_opts or {})
        if mesh is not None:
            if backend == "auto":
                self.backend_name = ("pallas" if lam == 16 else
                                     "hybrid" if lam >= 48 else "bitsliced")
            else:
                self.backend_name = backend
            if self.backend_name not in (
                    "pallas", "keylanes", "bitsliced", "jax", "hybrid",
                    "prefix"):
                # api-edge: documented backend-name contract at the facade edge
                raise ValueError(
                    f"backend {self.backend_name!r} has no mesh-sharded "
                    "variant (cpu/numpy are host paths); use pallas, "
                    "prefix, keylanes, hybrid, bitsliced or jax")
            if self.backend_name in ("pallas", "keylanes", "prefix") \
                    and lam != 16:
                # api-edge: documented backend/shape contract at the
                # facade edge
                raise ValueError(
                    f"the {self.backend_name} kernels support lam=16 only "
                    f"(got {lam}); use hybrid/bitsliced/jax on the mesh")
        else:
            self.backend_name = (
                _default_backend(lam) if backend == "auto" else backend)
            if self.backend_name not in (
                    "cpu", "numpy", "jax", "bitsliced", "pallas", "hybrid",
                    "keylanes", "prefix"):
                # api-edge: documented backend-name contract at the facade edge
                raise ValueError(f"unknown backend {self.backend_name!r}")
            if self.backend_name in ("keylanes", "prefix") and lam != 16:
                # api-edge: documented backend/shape contract at the
                # facade edge
                raise ValueError(
                    f"the {self.backend_name} kernel supports lam=16 only "
                    f"(got {lam}); use bitsliced or hybrid")
        # Fail fast on backend/shape incompatibility (the backends repeat
        # these checks, but construction is where the user should hear it).
        if mesh is None and self.backend_name == "pallas" and lam != 16:
            # api-edge: documented backend/shape contract at the facade edge
            raise ValueError(
                f"the pallas backend supports lam=16 only (got {lam}); "
                "use bitsliced or hybrid")
        if self.backend_name == "hybrid" and (lam < 48 or lam % 16):
            # api-edge: documented backend/shape contract at the facade edge
            raise ValueError(
                "the hybrid (large-lambda) backend wants lam >= 48, a "
                f"multiple of 16 (got {lam}); use pallas/bitsliced")
        if self._backend_opts and self.backend_name in ("cpu", "numpy"):
            # api-edge: documented backend_opts contract at the facade edge
            raise ValueError(
                f"backend_opts {sorted(self._backend_opts)} do not apply "
                f"to the {self.backend_name} backend")
        # The facade is the API edge: any ReferenceContractWarning fires
        # exactly once, here, attributed to the caller's Dcf(...) line
        # (warnings skip package-internal frames); the nested constructions
        # below (PRG, native core, backends) revalidate the same shape
        # internally and are silenced so one Dcf() does not repeat the
        # identical warning.
        hirose_used_cipher_indices(lam, len(self.cipher_keys))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", ReferenceContractWarning)
            self._prg = HirosePrgNp(lam, self.cipher_keys)
            self._gen_native = None
            try:
                from dcf_tpu.native import NativeDcf

                self._gen_native = NativeDcf(lam, self.cipher_keys)
            except Exception:  # fallback-ok: no toolchain -> numpy keygen
                pass
        # Self-healing auto selection (single-device): the chosen backend
        # must pass the canary before it may serve; otherwise degrade down
        # the chain with a structured warning.  Explicit backend names and
        # mesh variants stay strict — no silent substitution.
        if mesh is None and backend == "auto":
            self.backend_name = self._select_healthy(self.backend_name)
        if self.backend_name == "cpu" and self._gen_native is None:
            # api-edge: documented backend availability contract at
            # construction
            raise ValueError("cpu backend needs the native core")
        # One backend slot per party, created lazily on first eval(b, ...):
        # each slot retains its own shipped key image, so the documented
        # alternating two-party pattern (eval(0, bundle, xs);
        # eval(1, bundle, xs) across rounds) ships each party's image once
        # instead of re-staging on every call — and a single-party process
        # never constructs the other party's backend.
        self._eval_backends: dict = {}
        self._shipped_bundle: dict = {}
        self._dpf_evalall = None  # lazy (eval_all's device path)
        # Shared invalidation wiring: remember what was ASKED for (auto
        # may re-select after a health reset) and subscribe to resets.
        self._requested_backend = backend
        self._needs_reselect = False
        register_reset_listener(self)

    # -- backend-health invalidation (the ONE shared path) -------------------

    def _on_backend_health_reset(self) -> None:
        """Drop every backend-derived cache this facade holds.  Called via
        ``register_reset_listener`` whenever backend health is reset;
        re-construction/re-selection happens lazily on the next eval so a
        reset stays cheap for instances that never evaluate again."""
        self._eval_backends.clear()
        self._shipped_bundle.clear()
        self._dpf_evalall = None
        if self._requested_backend == "auto" and self.mesh is None:
            self._needs_reselect = True

    def _maybe_reselect(self) -> None:
        if self._needs_reselect:
            self._needs_reselect = False
            self.backend_name = self._select_healthy(
                _default_backend(self.lam))

    def reset_backend_health(self) -> None:
        """Instance spelling of :func:`reset_backend_health` — one shared
        invalidation path: wipes the process-wide canary verdicts and
        notifies every registered cache holder (this facade's backend
        slots, every serve registry's device-resident images).  An
        ``auto`` facade re-runs selection on its next eval, so a backend
        that died mid-serve is re-canaried instead of re-entered."""
        reset_backend_health()

    def _auto_chain(self, name: str) -> list[str]:
        """Fallback candidates for auto selection, starting at ``name``."""
        tail = [c for c in _FALLBACK_CHAIN[1:] if c != name]
        return [name] + tail

    def _health_key(self, name: str) -> tuple:
        return (name, self.lam, repr(sorted(self._backend_opts.items())))

    def _canary(self, name: str) -> None:
        """Prove backend ``name`` end-to-end on a tiny spec-checked eval.

        1 key x 2 points on a 2-byte canary domain: gen through the numpy
        reference PRG (deterministic seeds), both parties evaluated on a
        throwaway backend instance, XOR reconstruction compared bit-exactly
        against ``beta if x < alpha else 0``.  Raises on any failure —
        compile, lowering, or a silently-wrong result (worse than a crash
        in a two-party protocol).
        """
        lam = self.lam
        alphas = np.array([[0x80, 0x00]], dtype=np.uint8)
        betas = (np.arange(lam) % 255 + 1).astype(np.uint8)[None, :]
        s0s = random_s0s(1, lam, np.random.default_rng(0xDCF))
        bundle = gen_batch(self._prg, alphas, betas, s0s, Bound.LT_BETA)
        xs = np.array([[0x00, 0x00], [0xFF, 0x00]], dtype=np.uint8)
        if name == "numpy":
            from dcf_tpu.backends.numpy_backend import eval_batch_np

            ys = [eval_batch_np(self._prg, b, bundle.for_party(b), xs)
                  for b in (0, 1)]
        else:
            try:
                be = self._make_backend(name)
            except TypeError as e:
                # dcflint: disable=typed-error internal control-flow
                # marker, always caught inside _select_healthy — never
                # crosses the API surface
                raise _BackendMisuse(name, e) from e
            ys = [np.asarray(be.eval(b, xs, bundle.for_party(b)))
                  for b in (0, 1)]
        expect = np.stack([betas[0], np.zeros(lam, dtype=np.uint8)])
        if not np.array_equal((ys[0] ^ ys[1])[0], expect):
            raise BackendUnavailableError(
                f"canary spec check failed on backend {name!r}: 2-point "
                "two-party reconstruction does not match the comparison "
                "function")

    def _try_candidate(self, cand: str) -> Exception | None:
        """Run (or recall) the canary for one candidate; returns None on
        health, the failure otherwise.  Verdicts cache both ways — a
        failing compile is seconds of doomed work per construction."""
        key = self._health_key(cand)
        if key in _HEALTHY:
            return None
        if key in _UNHEALTHY:
            return _UNHEALTHY[key]
        try:
            self._canary(cand)
        except _BackendMisuse:
            raise  # programmer error: _select_healthy decides, not a verdict
        except Exception as e:  # fallback-ok: ANY environment failure
            # (Mosaic lowering, XLA, driver) must degrade to the next
            # correct backend, not take construction down.
            e.__traceback__ = None  # don't pin canary frames (throwaway
            # backend, jit caches) process-wide via the verdict cache
            _UNHEALTHY[key] = e
            return e
        _HEALTHY.add(key)
        return None

    def _select_healthy(self, name: str) -> str:
        """First backend in the auto chain that passes the canary."""
        failures: list[tuple[str, Exception]] = []
        chosen = None
        opts_dropped: list | None = None
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", ReferenceContractWarning)
            for cand in self._auto_chain(name):
                try:
                    err = self._try_candidate(cand)
                except _BackendMisuse as e:
                    if cand == name:
                        # The SELECTED backend rejecting its arguments is
                        # a programmer error — surface it, don't degrade.
                        # api-edge: programmer error — invalid
                        # backend_opts must surface as TypeError
                        raise TypeError(
                            f"backend_opts {sorted(self._backend_opts)} "
                            f"are invalid for backend {e.args[0]!r}: "
                            f"{e.args[1]}") from e.args[1]
                    # A FALLBACK candidate rejecting opts meant for the
                    # selected backend is expected (opts are
                    # backend-specific): degrade without them — the real
                    # eval backend is built with the same opts, so
                    # keeping them would just defer the TypeError.
                    opts_dropped = sorted(self._backend_opts)
                    self._backend_opts = {}
                    err = self._try_candidate(cand)
                if err is not None:
                    failures.append((cand, err))
                    continue
                chosen = cand
                break
        # Emitted outside the catch_warnings block so callers see them.
        if chosen is not None:
            for cand, e in failures:
                warnings.warn(BackendFallbackWarning(cand, chosen, e),
                              stacklevel=3)
            if opts_dropped:
                warnings.warn(
                    f"backend_opts {opts_dropped} were set for {name!r} "
                    f"and do not apply to fallback backend {chosen!r}; "
                    "ignored", UserWarning, stacklevel=3)
            return chosen
        raise BackendUnavailableError(
            "auto backend selection exhausted the fallback chain "
            + " -> ".join(self._auto_chain(name)) + "; causes: "
            + "; ".join(f"{c}: {type(e).__name__}: {e}"
                        for c, e in failures))

    def _make_backend(self, name: str):
        opts = self._backend_opts
        if self.mesh is not None:
            import jax

            # Mosaic kernels on TPU meshes; the Pallas interpreter (plain
            # JAX ops, shard_map-partitionable) on virtual CPU meshes.
            interp = jax.devices()[0].platform != "tpu"
            if name == "pallas":
                from dcf_tpu.parallel import ShardedPallasBackend

                return ShardedPallasBackend(
                    self.lam, self.cipher_keys, self.mesh,
                    interpret=interp, **opts)
            if name == "keylanes":
                from dcf_tpu.parallel import ShardedKeyLanesBackend

                return ShardedKeyLanesBackend(
                    self.lam, self.cipher_keys, self.mesh,
                    interpret=interp, **opts)
            if name == "hybrid":
                from dcf_tpu.parallel import ShardedLargeLambdaBackend

                return ShardedLargeLambdaBackend(
                    self.lam, self.cipher_keys, self.mesh,
                    interpret=interp, **opts)
            if name == "prefix":
                from dcf_tpu.parallel import ShardedPrefixBackend

                return ShardedPrefixBackend(
                    self.lam, self.cipher_keys, self.mesh,
                    interpret=interp, **opts)
            if name == "bitsliced":
                from dcf_tpu.parallel import ShardedBitslicedBackend

                return ShardedBitslicedBackend(
                    self.lam, self.cipher_keys, self.mesh, **opts)
            from dcf_tpu.parallel import ShardedJaxBackend

            return ShardedJaxBackend(
                self.lam, self.cipher_keys, self.mesh, **opts)
        if name in ("cpu", "numpy"):
            return None  # host paths dispatch directly in eval()
        if name == "jax":
            from dcf_tpu.backends.jax_backend import JaxBackend

            return JaxBackend(self.lam, self.cipher_keys, **opts)
        if name == "bitsliced":
            from dcf_tpu.backends.jax_bitsliced import BitslicedBackend

            return BitslicedBackend(self.lam, self.cipher_keys, **opts)
        if name == "pallas":
            from dcf_tpu.backends.pallas_backend import PallasBackend

            return PallasBackend(self.lam, self.cipher_keys, **opts)
        if name == "keylanes":
            import jax

            from dcf_tpu.backends.pallas_keylanes import KeyLanesPallasBackend

            # Mosaic is TPU-only; the interpreter keeps the facade usable
            # in CPU tests, same rule the mesh branch applies.
            return KeyLanesPallasBackend(
                self.lam, self.cipher_keys,
                interpret=jax.devices()[0].platform != "tpu", **opts)
        if name == "prefix":
            import jax

            from dcf_tpu.backends.pallas_prefix import PrefixPallasBackend

            return PrefixPallasBackend(
                self.lam, self.cipher_keys,
                interpret=jax.devices()[0].platform != "tpu", **opts)
        if name == "hybrid":
            from dcf_tpu.backends.large_lambda import LargeLambdaBackend

            if opts.get("prefix_levels") and "interpret" not in opts:
                # The prefix frontier machinery is Pallas-only; apply the
                # same interpreter rule as the keylanes/prefix paths so
                # the facade stays usable in CPU tests.
                import jax

                opts = dict(
                    opts,
                    interpret=jax.devices()[0].platform != "tpu")
            return LargeLambdaBackend(self.lam, self.cipher_keys, **opts)
        # api-edge: documented backend-name contract at the facade edge
        raise ValueError(f"unknown backend {name!r}")

    # -- keygen (reference gen, src/lib.rs:86-161) --------------------------

    def gen(self, alphas: np.ndarray, betas: np.ndarray,
            s0s: np.ndarray | None = None,
            bound: Bound = Bound.LT_BETA,
            rng: np.random.Generator | None = None,
            device: bool = False, group: str = "xor") -> KeyBundle:
        """Generate K keys: alphas uint8 [K, n_bytes], betas uint8 [K, lam].

        s0s (uint8 [K, 2, lam]) default to fresh random seeds.  Returns the
        two-party KeyBundle; ship ``bundle.for_party(b)`` to party b.

        ``device=True`` runs the level walk on the accelerator
        (``gen.gen_on_device``; the keylanes off-TPU interpreter rule
        applies) — same bytes out, throughput scaling with K instead of
        a single host core; falls back to the host walk, counted and
        warned, if the device path fails.

        ``group`` selects the OUTPUT group (``spec.GROUPS``): ``"xor"``
        (default — reconstruction is ``y0 ^ y1``) or an additive group
        ``"add8"``/``"add16"``/``"add32"`` (the payload is little-endian
        w-bit lanes; reconstruction is ``y0 + y1 mod 2^w`` per lane —
        Boyle et al. Fig. 1, the algebra the fixed-point gate suite in
        ``dcf_tpu.protocols.fixedpoint`` is built on).  The GGM tree
        walk is group-independent; additive keygen runs the vectorized
        host walk (the native core and the device keygen kernels are
        XOR-only, a documented routing), and eval backends pick the
        accumulate algebra off ``bundle.group`` at ``put_bundle``.
        """
        alphas = np.asarray(alphas, dtype=np.uint8)
        betas = np.asarray(betas, dtype=np.uint8)
        if alphas.ndim != 2 or alphas.shape[1] != self.n_bytes:
            raise ShapeError(f"alphas must be [K, {self.n_bytes}]")
        if s0s is None:
            s0s = random_s0s(
                alphas.shape[0], self.lam,
                # dcflint: disable=determinism fresh key seeds MUST be
                # unpredictable (OS entropy); pass rng= to reproduce
                rng if rng is not None else np.random.default_rng())
        if device:
            return gen_on_device(
                self.lam, self.cipher_keys, alphas, betas, s0s, bound,
                group=group)
        if self._gen_native is not None and group == "xor":
            # The C++ core implements the XOR value algebra only; the
            # additive groups take the vectorized numpy walk (a documented
            # routing, not a counted fallback — there is no native path
            # to fall back FROM).
            return self._gen_native.gen_batch(alphas, betas, s0s, bound)
        return gen_batch(self._prg, alphas, betas, s0s, bound, group=group)

    def eval_backend(self, b: int = 0):
        """The live backend instance serving party ``b`` (the shared
        two-party instance for keylanes), constructed if absent.

        The escape hatch to backend-specific staged APIs
        (``stage``/``eval_staged``/``staged_to_bytes``) once facade
        ``eval`` calls have shipped the key image — benches use it to keep
        results HBM-resident without re-staging keys.  Host backends
        (cpu/numpy) dispatch directly in ``eval`` and return ``None``.
        """
        self._maybe_reselect()
        slot = "kl" if self.backend_name == "keylanes" else int(b)
        be = self._eval_backends.get(slot)
        if be is None:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", ReferenceContractWarning)
                be = self._make_backend(self.backend_name)
            if be is not None:
                self._eval_backends[slot] = be
        return be

    def new_eval_backend(self):
        """A FRESH backend instance of the current selection, owning its
        own device key image (``None`` for the cpu/numpy host paths).

        The serve layer's hook: its registry keeps one instance per
        (key_id, party) so many long-lived keys stay device-resident at
        once — the facade's own per-party slots (``eval_backend``) hold
        exactly one shipped bundle each and would thrash.  Health-reset
        invalidation applies to instances made here exactly as to the
        facade's: the registry that owns them subscribes via
        ``register_reset_listener``."""
        self._maybe_reselect()
        if self.backend_name in ("cpu", "numpy"):
            return None
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", ReferenceContractWarning)
            return self._make_backend(self.backend_name)

    def serve(self, config=None, **knobs):
        """An online evaluation service over this facade — the serving
        entry point (``dcf_tpu.serve``):

            >>> svc = dcf.serve(max_batch=1 << 14, max_delay_ms=2.0,
            ...                 device_bytes_budget=256 << 20)
            >>> svc.register_key("model/relu-7", bundle)
            >>> with svc:                       # worker thread
            ...     fut = svc.submit("model/relu-7", xs, b=0)
            ...     y0 = fut.result()

        Pass a ``serve.ServeConfig`` or its fields as keywords.  See
        ``dcf_tpu/serve/service.py`` for the knob semantics (micro-batch
        coalescing, LRU device residency, admission control, circuit
        breakers + brownout — README "Resilience" — and metrics).
        ``submit(..., priority=)`` takes CRITICAL/NORMAL/BATCH; classes
        decide who is shed under overload, never dispatch order.
        Fresh-key-per-session traffic: declare
        ``add_pool(serve.PoolSpec(...))`` and register with
        ``register_key(key_id, pool=...)`` — the key factory
        (``serve.keyfactory``, README "Key factory") pre-mints session
        keys in K-packed device batches so registration is a pool pop,
        not a keygen walk.

        Network traffic (ISSUE 12, README "Network edge"): front the
        service with ``serve.EdgeServer(svc).start()`` — a stdlib-only
        length-prefixed binary protocol whose ingest path goes
        buffer-protocol straight into the batcher (zero per-point
        Python objects; ``submit_bytes`` is the shared entry).
        ``tenants=(serve.TenantSpec(name, priority, points_per_sec,
        burst_points), ...)`` maps edge tenants onto the SAME
        CRITICAL/NORMAL/BATCH classes (never a second policy) and
        arms a per-tenant token bucket on the injectable clock;
        refusals cross the wire as typed error frames carrying
        ``retry_after_s`` (breaker cooldown / brownout hysteresis /
        exact bucket refill).  ``tls_cert``/``tls_key`` arm stdlib-ssl
        TLS on the edge socket and ``tls_client_ca`` pins clients
        (ISSUE 13; README "Network edge").

        Pod scale (ISSUE 13, README "Pod serving"): one service +
        edge is a SHARD.  Run N of them (the ``serve_host`` CLI
        subcommand: warm-restore from the durable store, serve DCFE,
        publish address + metrics snapshots) behind a
        ``serve.DcfRouter`` over a ``serve.ShardMap`` rendezvous ring
        — keys are owned by ``owner(key_id)``, durably replicated to
        the replica (``KeyStore.replicate_to``, generations
        preserved), and the router forwards frames zero-copy,
        failing CRITICAL traffic over to the replica when a shard
        goes suspect and refusing everything else typed with
        ``retry_after_s``.

        Self-healing (ISSUE 14, README "Pod serving" / Self-healing):
        the router's ``probe_interval_s`` / ``probe_timeout_s`` /
        ``probe_fail_n`` / ``probe_recover_m`` knobs arm an active
        health prober (DCFE PING per shard; ``start_health()`` runs
        it, ``health.pump()`` drives tests) whose DOWN verdict
        PROMOTES each victim key's replica to acting owner for every
        priority class, and whose DOWN -> UP re-admission is gated
        behind an anti-entropy digest exchange.  LIVE (non-durable)
        registrations replicate through
        ``router.register_key``/``register_frame`` — the owner mints
        the generation, replicas apply it preserved, and the
        monotonic-generation fence (``StaleStateError`` /
        ``E_STALE``, ``serve_replica_fenced_total``) makes an old
        partition side structurally unable to roll a key back.  The
        shard-side surface is ``register_frame`` /
        ``apply_replica_frame`` / ``replication_digest`` /
        ``sync_frames`` on this service.

        Membership (ISSUE 15, README "Ring operations"): a
        ``serve.MembershipController`` over the router closes the
        loop from health to the ring — a shard DOWN past
        ``eject_grace_s`` is AUTO-EJECTED with every frame it held
        re-replicated to its new placement before the swap commits
        (durable via ``KeyStore.replicate_to``, live via the
        anti-entropy pull); ``join(spec)`` warms a new host through
        the SYNC path before admitting it (no cold-miss storm);
        ``drain(host_id)`` migrates, swaps, and holds the link
        through an in-flight grace before the forget (``serve_host``
        then drains on SIGTERM and exits 0).  Every commit mints a
        monotonic ring EPOCH carried on forwarded frames; this
        service tracks the observed maximum (``ring_epoch`` /
        ``check_ring_epoch``) and refuses older ones typed
        (``RingEpochError`` / ``E_EPOCH``,
        ``serve_epoch_fenced_total``) — a router on a stale ring is
        structurally unable to serve a conflicting placement.

        Autoscaling (ISSUE 16, README "Autoscaling"): the
        ``max_queued_points`` knob here is the demand signal's
        denominator — each shard reports ``queue_points`` against it
        in the ``LoadSample`` piggybacked on health PONGs
        (``load_report``; ``serve_host --max-queued-points`` is the
        CLI spelling), so size it to the shard's real appetite, not
        "large enough to never matter".  A
        ``serve.CapacityController`` over the router + membership
        pair turns those samples into ring changes: ``scale_out_n``
        consecutive pressure ticks admit a host from the declared
        standby pool (``serve_host --standby`` processes),
        ``scale_in_m`` consecutive idle ticks drain the least-loaded
        one back, with a hard ``cooldown_s`` after any observed
        membership change — oscillating load produces zero churn.

        Mesh co-evaluation (ISSUE 18, README "Mesh co-evaluation"):
        routing scales keys, the mesh scales the BATCH — the router's
        ``co_eval`` / ``co_eval_min_points`` knobs pick, per request,
        between route-mode (one host walks all points) and
        co-evaluate (``set_mesh()`` forms an epoch-fenced
        ``serve.MeshGroup`` over the ring; the batch's 32-aligned
        point slices scatter over EVERY worker and the share slices
        gather back in plan order).  ``co_eval="auto"`` (default)
        co-evaluates only at ``>= co_eval_min_points`` points — set
        the threshold to the crossover measured by ``pod_bench
        --mesh`` — and degrades typed mesh trouble
        (``MeshUnavailableError``) back to route-mode, counted and
        warned, never silent.  A co-evaluated key must be resident
        mesh-wide: ``router.register_mesh_key`` registers it on every
        worker under one generation.
        """
        from dcf_tpu.serve import DcfService, ServeConfig

        if config is not None and knobs:
            # api-edge: either a config object or keywords, not both
            raise ValueError("pass either config= or individual knobs")
        if config is None:
            config = ServeConfig(**knobs)
        return DcfService(self, config)

    # -- protocols (dcf_tpu.protocols: IC / MIC / piecewise) ----------------

    def _protocol_gen(self, rng, device: bool = False,
                      group: str = "xor"):
        from dcf_tpu.spec import Bound as _B

        def gen_fn(alphas, betas, bound: _B):
            return self.gen(alphas, betas, bound=bound, rng=rng,
                            device=device, group=group)

        return gen_fn

    def interval(self, p: int, q: int, beta: np.ndarray,
                 bound: Bound = Bound.LT_BETA,
                 rng: np.random.Generator | None = None,
                 device: bool = False, group: str = "xor"):
        """Keys for interval containment ``1_{p <= x < q} * beta``.

        ``p``/``q``: ints in ``[0, 2^n_bits]`` (``q = 2^n_bits`` makes
        ``[p, N)`` expressible); ``p > q`` is the wraparound interval
        ``[p, N) ∪ [0, q)`` and ``p == q`` is empty.  ``beta``: uint8
        [lam].  Returns a two-party ``protocols.ProtocolBundle`` packing
        the two bound keys on the K axis — ship ``pb.for_party(b)`` and
        evaluate with :meth:`eval_interval`; group-add both parties'
        outputs to reconstruct (XOR in the default group).
        Wraparound/full-domain intervals work via the public
        combine-mask correction (README "Protocols" derivation).
        ``bound`` picks which DCF bound family realizes the keys
        (LT_BETA default; GT_BETA uses the ``1_{x >= b}`` decomposition
        — same reconstruction either way).  ``group`` selects the
        output group the keys and combine run in (see :meth:`gen`);
        additive groups yield arithmetic shares of the indicator —
        the building block of the fixed-point gates.
        """
        from dcf_tpu.protocols import gen_interval_bundle

        beta = np.asarray(beta, dtype=np.uint8).reshape(1, -1)
        return gen_interval_bundle(
            self._protocol_gen(rng, device, group), [(p, q)], beta,
            self.n_bytes, bound, group)

    def mic(self, intervals, betas: np.ndarray,
            bound: Bound = Bound.LT_BETA,
            rng: np.random.Generator | None = None,
            device: bool = False, group: str = "xor"):
        """Keys for multiple interval containment over ``m`` intervals.

        ``intervals``: sequence of ``(p, q)`` int pairs (same convention
        as :meth:`interval`; the paper's MIC wants them disjoint, but
        each output row is independent so overlap is merely redundant);
        ``betas``: uint8 [m, lam].  The 2m interval-bound DCF keys pack
        into ONE K-axis bundle — exactly the K-key batched-walk shape
        the flagship kernels are fastest at — evaluated with
        :meth:`eval_mic` (facade path) or ``protocols.MicEvaluator``
        (staged, on-device combine), and servable online by registering
        the returned bundle in ``Dcf.serve(...)`` under a key id.
        Reconstruction: group-add both parties' [m, M, lam] outputs
        (XOR in the default group).  ``device=True`` runs the 2m-key
        packed keygen on the accelerator (``gen.gen_on_device`` — the
        K axis is exactly what the device walk scales with).
        ``group`` selects the output group (see :meth:`gen`).
        """
        from dcf_tpu.protocols import gen_interval_bundle

        return gen_interval_bundle(
            self._protocol_gen(rng, device, group), intervals,
            np.asarray(betas, dtype=np.uint8), self.n_bytes, bound,
            group)

    def piecewise(self, cuts, values: np.ndarray,
                  rng: np.random.Generator | None = None,
                  device: bool = False, group: str = "xor"):
        """Keys for a piecewise-constant function (spline lookup table).

        ``cuts``: strictly increasing breakpoints in ``[0, 2^n_bits)``
        (the last piece wraps around the domain top — with
        ``cuts[0] == 0`` that is the standard table over [0, N));
        ``values``: uint8 [m, lam], piece i's output.  Builds the MIC
        over the induced partition; evaluate with
        :meth:`eval_piecewise`, which group-sum-reduces the per-piece
        rows to one [M, lam] share per party (exact because the pieces
        partition the domain, so exactly one indicator fires per
        point).  In an additive ``group`` the result is an ARITHMETIC
        share of the piece value — the spline-sigmoid gate
        (``protocols.fixedpoint``) is a thin client of exactly this.
        """
        from dcf_tpu.protocols import gen_interval_bundle
        from dcf_tpu.protocols.piecewise import partition_intervals

        intervals = partition_intervals(list(cuts), 8 * self.n_bytes)
        return gen_interval_bundle(
            self._protocol_gen(rng, device, group), intervals,
            np.asarray(values, dtype=np.uint8), self.n_bytes,
            Bound.LT_BETA, group)

    def eval_interval(self, b: int, pb, xs: np.ndarray) -> np.ndarray:
        """Party ``b``'s IC share uint8 [M, lam] (see :meth:`interval`)."""
        from dcf_tpu.protocols import eval_interval

        return eval_interval(self, b, pb, np.asarray(xs, dtype=np.uint8))

    def eval_mic(self, b: int, pb, xs: np.ndarray) -> np.ndarray:
        """Party ``b``'s per-interval MIC shares uint8 [m, M, lam]
        (see :meth:`mic`).  Runs on whatever backend this facade
        selected — the 2m keys evaluate as one K-packed batch and the
        pair-combine + public-correction mask apply locally
        (``protocols.combine``, fault seam ``protocols.combine``)."""
        from dcf_tpu.protocols import eval_mic

        return eval_mic(self, b, pb, np.asarray(xs, dtype=np.uint8))

    def eval_piecewise(self, b: int, pb, xs: np.ndarray) -> np.ndarray:
        """Party ``b``'s piecewise-lookup share uint8 [M, lam]
        (see :meth:`piecewise`)."""
        from dcf_tpu.protocols import eval_piecewise

        return eval_piecewise(self, b, pb, np.asarray(xs, dtype=np.uint8))

    # -- DPF / PIR (point functions + full-domain eval; README "DPF / PIR")

    def dpf(self, alphas: np.ndarray, betas: np.ndarray | None = None,
            s0s: np.ndarray | None = None,
            rng: np.random.Generator | None = None,
            device: bool = False):
        """Generate K DPF keys for ``f(x) = beta_k * 1_{x == alpha_k}``.

        The GGM walk minus the comparison accumulation (no ``cw_v`` —
        ``protocols.dpf`` derivation): alphas uint8 [K, n_bytes], betas
        uint8 [K, lam] (default all-ones — PIR reads only the leaf
        t-bits, so the payload rarely matters), s0s uint8 [K, 2, lam]
        fresh random root seeds.  Returns the two-party
        ``protocols.DpfBundle`` (DCFK v3 ``proto=2`` on the wire; ship
        ``bundle.for_party(b)``).  Evaluate pointwise with
        ``protocols.dpf_eval_points`` or full-domain with
        :meth:`eval_all`; registering the bundle in ``Dcf.serve(...)``
        / the pod router serves it (``workloads.pir.PirServer``).
        ``device=True`` runs the K-packed keygen kernel (lam=32; falls
        back to the host walk counted + warned, like :meth:`gen`).
        """
        from dcf_tpu.protocols.dpf import dpf_gen_batch, dpf_gen_on_device

        alphas = np.asarray(alphas, dtype=np.uint8)
        if alphas.ndim != 2 or alphas.shape[1] != self.n_bytes:
            raise ShapeError(f"alphas must be [K, {self.n_bytes}]")
        if betas is None:
            betas = np.full((alphas.shape[0], self.lam), 0xFF,
                            dtype=np.uint8)
        betas = np.asarray(betas, dtype=np.uint8)
        if s0s is None:
            s0s = random_s0s(
                alphas.shape[0], self.lam,
                # dcflint: disable=determinism fresh key seeds MUST be
                # unpredictable (OS entropy); pass rng= to reproduce
                rng if rng is not None else np.random.default_rng())
        if device:
            return dpf_gen_on_device(
                self.lam, self.cipher_keys, alphas, betas, s0s)
        return dpf_gen_batch(self._prg, alphas, betas, s0s)

    def eval_all(self, b: int, bundle, device: bool = False):
        """Party ``b``'s FULL-DOMAIN DPF evaluation — every leaf at
        once, ~2^{n+1} PRG calls instead of n * 2^n per-point walks.

        Returns ``(y, t)``: leaf shares uint8 [K, 2^n_bits, lam] and
        leaf t-bits uint8 [K, 2^n_bits], in bitreverse_n leaf order
        (position p holds domain point bitreverse(p) — the level-order
        doubling's order; ``workloads.pir.PirDatabase`` packs records
        the same way, so PIR never reorders).  XOR the two parties:
        ``y0^y1`` is beta at alpha and 0 elsewhere; ``t0^t1`` is the
        one-hot selection vector.

        ``device=False``: the portable host expansion (any lam).
        ``device=True``: the Pallas EvalAll kernel (lam=32 only —
        ``backends.evalall.DpfEvalAll``, off-TPU interpreter rule),
        fetched back to host bytes; throughput-sensitive callers (PIR
        servers, benches) use ``DpfEvalAll`` directly to keep the leaf
        planes device-resident.
        """
        from dcf_tpu.backends.evalall import (
            dpf_finalize_np,
            dpf_tree_expand_np,
            leaf_planes_to_bytes,
        )

        kb = bundle.for_party(b) if bundle.s0s.shape[1] == 2 else bundle
        if device:
            ev = self._dpf_evalall
            if ev is None:
                import jax

                from dcf_tpu.backends.evalall import DpfEvalAll

                ev = DpfEvalAll(
                    self.lam, self.cipher_keys,
                    interpret=jax.devices()[0].platform != "tpu")
                self._dpf_evalall = ev
            y0, y1, t = ev.eval_party(b, kb, kb.n_bits)
            return leaf_planes_to_bytes(y0, y1, t)
        s, t = dpf_tree_expand_np(self._prg, kb, b, kb.n_bits)
        return dpf_finalize_np(kb, s, t), t

    def pir_query(self, indices, s0s: np.ndarray | None = None,
                  rng: np.random.Generator | None = None):
        """Client-side 2-server-PIR query keygen: one DPF key pair per
        record index (``workloads.pir.pir_query_bundle`` over this
        facade's PRG/domain).  Register the returned bundle with both
        servers (``PodRouter.register_key`` serves a pod), collect
        ``PirServer.answer(key_id, b)`` from each, and XOR the shares
        (``workloads.pir.pir_reconstruct``) — the record comes back
        bit-exact while neither server learns which one.
        """
        from dcf_tpu.workloads.pir import pir_query_bundle

        indices = [int(i) for i in np.asarray(indices).reshape(-1)]
        if s0s is None:
            s0s = random_s0s(
                len(indices), self.lam,
                # dcflint: disable=determinism fresh key seeds MUST be
                # unpredictable (OS entropy); pass rng= to reproduce
                rng if rng is not None else np.random.default_rng())
        return pir_query_bundle(self._prg, indices, 8 * self.n_bytes,
                                s0s)

    # -- eval (reference eval, src/lib.rs:163-204) --------------------------

    def eval(self, b: int, bundle: KeyBundle, xs: np.ndarray) -> np.ndarray:
        """Party ``b`` batch evaluation: xs uint8 [M, n_bytes] (shared) or
        [K, M, n_bytes] (per-key, backend permitting).  Returns uint8
        [K, M, lam]; XOR both parties' outputs to reconstruct f(x).

        ``bundle`` may be the full two-party bundle (restricted to party
        ``b`` internally — the recommended form, since the shipped key
        image is cached per (bundle, party) and reused across calls) or an
        already-restricted ``bundle.for_party(b)``.
        """
        xs = np.asarray(xs, dtype=np.uint8)
        self._maybe_reselect()
        if self.backend_name == "keylanes":
            # The keylanes CW image is shared between parties (reference
            # src/lib.rs:269-272): ONE backend instance and one shipped
            # two-party image serve both parties.
            if bundle.s0s.shape[1] != 2:
                raise ShapeError(
                    "the keylanes backend wants the full two-party bundle "
                    "(its CW image is shared between parties)")
            be = self.eval_backend(b)
            if self._shipped_bundle.get("kl") is not bundle:
                be.put_bundle(bundle)
                self._shipped_bundle["kl"] = bundle
            return be.eval(int(b), xs)
        kb = bundle.for_party(b) if bundle.s0s.shape[1] == 2 else bundle
        if self.backend_name == "cpu":
            if kb.group != "xor":
                # api-edge: documented group contract — the C++ core
                # implements the XOR value algebra only.
                raise ShapeError(
                    f"the cpu (native) backend is XOR-only; bundle has "
                    f"group {kb.group!r} — use numpy/bitsliced/pallas")
            return self._gen_native.eval(b, kb, xs)
        if self.backend_name == "numpy":
            from dcf_tpu.backends.numpy_backend import eval_batch_np

            return eval_batch_np(self._prg, b, kb, xs)
        # Ship the key image once per (party, bundle), not once per call
        # (put_bundle does the full host plane expansion + transfer).
        # Keyed on the CALLER's object by IDENTITY, and the object is
        # RETAINED in the cache entry — comparing raw id() of a temporary
        # like for_party(b) would false-hit when the allocator reuses the
        # address of a freed bundle.
        slot = int(b)
        be = self.eval_backend(b)
        if self._shipped_bundle.get(slot) is not bundle:
            be.put_bundle(kb)
            self._shipped_bundle[slot] = bundle
        return be.eval(b, xs)
