"""PallasBackend — device-resident DCF evaluator on the Pallas kernel.

API-compatible with BitslicedBackend (put_bundle / eval), lam = 16 only
(the kernel is specialized to one AES block per seed; other lam values use
the XLA bitsliced path).  Key material is shipped once as bit-major plane
masks; xs->bit-mask and plane->byte conversions run on device inside the
same jitted program as the kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.errors import ShapeError, StaleStateError
from dcf_tpu.backends._common import prepare_batch
from dcf_tpu.backends.jax_bitsliced import (
    _lt_lane_mask_dev,
    _planes_to_bytes_dev,
    _range_xs_dev,
    _xs_to_mask_dev,
    walk_inside_mask,
)
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes_bitsliced import round_key_masks_bitmajor
from dcf_tpu.ops.pallas_eval import DEFAULT_TILE_WORDS, dcf_eval_pallas
from dcf_tpu.spec import hirose_used_cipher_indices
from dcf_tpu.testing.faults import fire
from dcf_tpu.utils.bits import (
    alpha_walk_bits,
    bitmajor_perm,
    bitmajor_plane_masks,
)

__all__ = ["PallasBackend"]

_PERM = bitmajor_perm(16)
_INV_PERM = np.argsort(_PERM)


@jax.jit
def _stage_xs(xs):
    """uint8 [Kx, M, 16] -> int32 walk-order lane masks [Kx, n, 1, W]."""
    return jax.lax.bitcast_convert_type(
        _xs_to_mask_dev(xs).transpose(1, 0, 2), jnp.int32
    )[:, :, None, :]


@partial(jax.jit, static_argnames=("b", "tile_words", "interpret", "group"))
def _eval_staged(rk, s0_t, cw_s_t, cw_v_t, cw_np1_t, cw_t, x_mask,
                 b: int, tile_words: int, interpret: bool,
                 group: str = "xor"):
    return dcf_eval_pallas(
        rk, s0_t, cw_s_t, cw_v_t, cw_np1_t, cw_t, x_mask,
        b=b, tile_words=tile_words, interpret=interpret, group=group,
    )


@partial(jax.jit, static_argnames=("m", "nb"))
def _stage_range_jit(start, m: int, nb: int):
    return _stage_xs(_range_xs_dev(start, m, nb))


@partial(jax.jit, static_argnames=("alpha_bits", "gt"))
def _points_mismatch_bitmajor(y0, y1, beta_mask, x_mask, *,
                              alpha_bits: tuple, gt: bool):
    """Mismatch count vs the comparison function for staged RANDOM points.

    y0/y1: eval_staged outputs int32 [1, 128, W]; x_mask: the staged
    walk-order lane masks int32 [1, n, 1, W]; alpha_bits: the n bits of
    alpha MSB-first (static — one compile per key, the bench shape).  The
    lexicographic compare runs directly on the bit-mask planes
    (jax_bitsliced.walk_inside_mask, shared with the byte-major counter),
    so no extra host->device traffic is needed.  Padding points are
    genuine evaluations of x=0 and therefore self-verify.
    """
    w = y0.shape[-1]
    inside = walk_inside_mask(
        lambda i: x_mask[0, i, 0][None, :],
        lambda i: jnp.int32(-1 if alpha_bits[i] else 0),
        len(alpha_bits), jnp.zeros((1, w), jnp.int32), gt)
    expect = beta_mask[None, :, :] & inside[:, None, :]  # [1, 128, W]
    diff = jnp.bitwise_or.reduce(y0 ^ y1 ^ expect, axis=1)
    return jnp.sum(jax.lax.population_count(
        jax.lax.bitcast_convert_type(diff, jnp.uint32)).astype(jnp.int32))


@partial(jax.jit, static_argnames=("gt",))
def _points_mismatch_bitmajor_multikey(y0, y1, beta_mask_k, x_mask,
                                       alpha_pm, *, gt: bool):
    """Multi-key variant of the staged random-points counter: the
    lexicographic compare (walk_inside_mask — the one source of the
    bound semantics) runs with per-key alpha bits as DATA (int32 lane
    masks [K, n] in {0, -1}) instead of a jit-static tuple, so one
    compile covers any K and the K>1 bench lines get the same full
    on-device two-party parity as the single-key flagship.

    y0/y1: int32 [K, 128, W]; beta_mask_k: int32 [K, 128, 1];
    x_mask: int32 [1 or K, n, 1, W] (shared points broadcast over keys).
    """
    k_num, _, w = y0.shape
    inside = walk_inside_mask(
        lambda i: x_mask[:, i],                    # [1|K, 1, W]
        lambda i: alpha_pm[:, i][:, None, None],   # [K, 1, 1]
        x_mask.shape[1], jnp.zeros((k_num, 1, w), jnp.int32), gt)
    expect = beta_mask_k & inside            # [K, 128, W]
    diff = jnp.bitwise_or.reduce(y0 ^ y1 ^ expect, axis=1)  # [K, W]
    return jnp.sum(jax.lax.population_count(
        jax.lax.bitcast_convert_type(diff, jnp.uint32)).astype(jnp.int32))


@partial(jax.jit, static_argnames=("gt",))
def _fd_mismatch_bitmajor(y0, y1, beta_mask, start, alpha, *, gt: bool):
    """Mismatching-point count for bit-major planes int32 [K, 128, W], K=1."""
    w = y0.shape[-1]
    ltw = jax.lax.bitcast_convert_type(
        _lt_lane_mask_dev(start, alpha, w, gt), jnp.int32)  # [1, W]
    expect = beta_mask[None, :, :] & ltw[:, None, :]
    diff = jnp.bitwise_or.reduce(y0 ^ y1 ^ expect, axis=1)  # [K, W]
    return jnp.sum(jax.lax.population_count(
        jax.lax.bitcast_convert_type(diff, jnp.uint32)).astype(jnp.int32))


@jax.jit
def _from_planes_jit(y_planes, inv_perm):
    """int32 bit-major y planes [K, 128, W] -> uint8 [K, W*32, 16]."""
    y = jax.lax.bitcast_convert_type(y_planes, jnp.uint32)
    y = jnp.take(y, inv_perm, axis=1).transpose(1, 0, 2)  # [8lam, K, W]
    return _planes_to_bytes_dev(y, 16)


@partial(jax.jit, static_argnames=("b", "tile_words", "interpret", "group"))
def _eval_bytes(rk, s0_t, cw_s_t, cw_v_t, cw_np1_t, cw_t, xs, inv_perm,
                b: int, tile_words: int, interpret: bool,
                group: str = "xor"):
    y_bm = _eval_staged(
        rk, s0_t, cw_s_t, cw_v_t, cw_np1_t, cw_t, _stage_xs(xs),
        b=b, tile_words=tile_words, interpret=interpret, group=group,
    )
    return _from_planes_jit(y_bm, inv_perm)


class PallasBackend:
    """DCF evaluator running the fused Pallas walk kernel (lam = 16)."""

    def __init__(self, lam: int, cipher_keys: Sequence[bytes],
                 tile_words: int = DEFAULT_TILE_WORDS,
                 interpret: bool = False):
        if lam != 16:
            raise ValueError(  # api-edge: constructor lam contract
                f"PallasBackend supports lam=16 only (got {lam}); "
                "use BitslicedBackend for other lam"
            )
        if tile_words < 1:
            # api-edge: constructor tile_words contract
            raise ValueError(f"tile_words must be >= 1, got {tile_words}")
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        self.lam = lam
        self.tile_words = tile_words
        self.interpret = interpret
        self.rk = jnp.asarray(round_key_masks_bitmajor(cipher_keys[used[0]]))
        self._inv_perm = jnp.asarray(_INV_PERM)
        self._bundle_dev = None
        self._group = "xor"

    def put_bundle(self, bundle: KeyBundle) -> None:
        """Ship a party-restricted bundle as bit-major plane masks.

        The plane image is built on host and placed via ``_put_plane`` —
        the hook sharded subclasses override so each device receives only
        its key shard (no full-image transient on one chip).
        """
        if bundle.lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        if bundle.s0s.shape[1] != 1:
            raise ShapeError("put_bundle requires a party-restricted bundle")

        def keyed(a):  # [K, lam] -> [K, 128, 1]
            return bitmajor_plane_masks(a)[:, :, None]

        def leveled(a):  # [K, n, lam] -> [K, n, 128, 1]
            return bitmajor_plane_masks(a)[:, :, :, None]

        host = dict(
            s0=keyed(bundle.s0s[:, 0, :]),
            cw_s=leveled(bundle.cw_s),
            cw_v=leveled(bundle.cw_v),
            cw_np1=keyed(bundle.cw_np1),
            cw_t=np.ascontiguousarray(bundle.cw_t.astype(np.int32) * -1),
        )
        self._bundle_dev = {k: self._put_plane(k, v) for k, v in host.items()}
        self._group = bundle.group

    def _put_plane(self, name: str, arr: np.ndarray) -> jax.Array:
        """Placement hook for one staged bundle array (single device here)."""
        return jnp.asarray(arr)

    def _dims(self) -> tuple[int, int]:
        """(k_num, n_bits) of the on-device bundle; raises if absent."""
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        return self._bundle_dev["s0"].shape[0], self._bundle_dev["cw_s"].shape[1]

    def _prepare(self, xs: np.ndarray) -> tuple[np.ndarray, int, int]:
        """Shared stage/eval preamble with one tile plan: returns
        (xs padded+contiguous, m, tile words)."""
        xs, _, m = prepare_batch(
            self._dims(), xs, lambda m: 32 * self._plan_tiles(m)[1])
        return xs, m, self._plan_tiles(m)[0]

    def _plan_tiles(self, m: int) -> tuple[int, int]:
        """Pick (tile words, padded total words) for an m-point batch.

        Small batches run as one exact tile (pad <= 31 points).  Larger ones
        balance the tile count first, then round the tile up to the 128-lane
        granule Mosaic requires, so padding waste stays a tile-rounding
        sliver instead of up to a whole tile.
        """
        words = (m + 31) // 32
        tw = self.tile_words
        if words <= tw:
            return words, words
        n_tiles = -(-words // tw)
        if tw >= 128:
            wt = 128 * (-(-words // (128 * n_tiles)))
        else:  # tiny tiles (tests / interpret mode): keep the exact size
            wt = tw
        return wt, wt * n_tiles

    def stage(self, xs: np.ndarray) -> dict:
        """Ship xs to device as walk-order lane masks (criterion-setup analog).

        Returns an opaque staged dict for ``eval_staged``; the conversion and
        transfer happen here, outside any timed region, mirroring the
        reference bench's untimed xs setup
        (/root/reference/benches/dcf_batch_eval.rs:17-24).
        """
        xs, m, wt = self._prepare(xs)
        if m == 0:
            raise ShapeError("cannot stage an empty batch")
        x_mask = _stage_xs(jnp.asarray(xs))
        return {"x_mask": x_mask, "m": m, "wt": wt}

    def stage_range(self, start: int, count: int) -> dict:
        """Stage the consecutive points start..start+count-1 WITHOUT any
        host->device xs transfer: the batch is generated from an iota inside
        the jitted program (full-domain workload, BASELINE config 3)."""
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        n = self._bundle_dev["cw_s"].shape[1]
        wt, w_pad = self._plan_tiles(count)
        if 32 * w_pad != count:
            raise ShapeError(
                f"count {count} must be a whole number of {32 * wt}-point "
                "tiles for the range path")
        x_mask = _stage_range_jit(jnp.uint32(start), m=count, nb=n // 8)
        return {"x_mask": x_mask, "m": count, "wt": wt}

    def mismatch_count(self, y0, y1, alpha: int, beta: bytes, start: int,
                       gt: bool = False) -> jax.Array:
        """Device-side verification for full-domain runs: number of points in
        this staged chunk whose XOR reconstruction differs from the plain
        comparison function.  y0/y1: ``eval_staged`` outputs for the two
        parties over points start..start+32*W-1 (single key).  Returns a
        DEVICE int32 scalar so chunked callers can accumulate without a
        host round-trip per chunk."""
        beta_mask = jnp.asarray(bitmajor_plane_masks(
            np.frombuffer(beta, dtype=np.uint8))[:, None])
        return _fd_mismatch_bitmajor(
            y0, y1, beta_mask, jnp.uint32(start), jnp.uint32(alpha), gt=gt)

    # _full_device_parity's capability flag: multi-key bundles get the
    # same full on-device parity gate as single-key ones.
    points_mismatch_multikey = True

    def points_mismatch_count(self, y0, y1, alpha, beta,
                              staged: dict, gt: bool = False) -> jax.Array:
        """Full on-device two-party verification for staged RANDOM points
        (the bench parity gate): count of (key, point) pairs whose XOR
        reconstruction differs from ``beta if x < alpha else 0`` (``>``
        for gt).  y0/y1: ``eval_staged`` outputs of the two parties over
        the SAME staged batch (the x image is party-independent).

        Single-key form: ``alpha``/``beta`` as bytes.  Multi-key form:
        uint8 arrays [K, n_bytes] / [K, lam] (per-key alphas become data
        lane masks, one compile for any K).  Returns a DEVICE int32
        scalar."""
        if isinstance(alpha, (bytes, bytearray)):
            if y0.shape[0] != 1:
                raise ShapeError(
                    "bytes alpha/beta is the single-key form; pass "
                    "[K, n_bytes]/[K, lam] arrays for multi-key bundles")
            beta_mask = jnp.asarray(bitmajor_plane_masks(
                np.frombuffer(beta, dtype=np.uint8))[:, None])
            return _points_mismatch_bitmajor(
                y0, y1, beta_mask, staged["x_mask"],
                alpha_bits=alpha_walk_bits(alpha), gt=gt)
        alphas = np.asarray(alpha, dtype=np.uint8)
        betas = np.asarray(beta, dtype=np.uint8)
        if alphas.shape[0] != y0.shape[0] or betas.shape[0] != y0.shape[0]:
            raise ShapeError(
                f"{alphas.shape[0]} alphas / {betas.shape[0]} betas for "
                f"{y0.shape[0]}-key outputs")
        alpha_pm = jnp.asarray(
            np.unpackbits(alphas, axis=1).astype(np.int32) * -1)  # [K, n]
        beta_mask_k = jnp.asarray(bitmajor_plane_masks(betas)[:, :, None])
        return _points_mismatch_bitmajor_multikey(
            y0, y1, beta_mask_k, staged["x_mask"], alpha_pm, gt=gt)

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        """Party ``b`` eval on staged points; returns DEVICE-resident y planes
        (int32 [K, 128, W], bit-major).  Dispatch is async — force completion
        with a fetch.  Use ``eval`` for the bytes-in/bytes-out path."""
        fire("pallas.lowering")  # fault seam: deterministic Mosaic failure
        dev = self._bundle_dev
        return _eval_staged(
            self.rk, dev["s0"], dev["cw_s"], dev["cw_v"], dev["cw_np1"],
            dev["cw_t"], staged["x_mask"], b=int(b),
            tile_words=staged["wt"], interpret=self.interpret,
            group=self._group,
        )

    def convert_staged(self, y_planes: jax.Array) -> jax.Array:
        """Device-side plane->byte conversion of ``eval_staged`` output;
        returns a DEVICE uint8 [K, 32*W, lam] array (dispatch async).
        Pipelined consumers call ``copy_to_host_async()`` on it to overlap
        the d2h with later chunks' compute."""
        return _from_planes_jit(y_planes, self._inv_perm)

    def staged_to_bytes(self, y_planes: jax.Array, m: int) -> np.ndarray:
        """Convert ``eval_staged`` output to uint8 [K, M, lam] on host."""
        return np.asarray(self.convert_staged(y_planes))[:, :m, :]

    def eval(self, b: int, xs: np.ndarray,
             bundle: KeyBundle | None = None) -> np.ndarray:
        """Evaluate party ``b``; xs uint8 [M, n_bytes] or [K, M, n_bytes].

        Returns uint8 [K, M, lam].  Points are padded internally to whole
        lane-tiles (pad lanes computed and discarded).
        """
        fire("pallas.lowering")  # fault seam: deterministic Mosaic failure
        if bundle is not None:
            self.put_bundle(bundle)
        xs, m, wt = self._prepare(xs)
        dev = self._bundle_dev
        if m == 0:
            return np.zeros((dev["s0"].shape[0], 0, self.lam), dtype=np.uint8)
        y = _eval_bytes(
            self.rk, dev["s0"], dev["cw_s"], dev["cw_v"], dev["cw_np1"],
            dev["cw_t"], jnp.asarray(xs),
            self._inv_perm, b=int(b), tile_words=wt,
            interpret=self.interpret, group=self._group,
        )
        return np.asarray(y[:, :m, :])
