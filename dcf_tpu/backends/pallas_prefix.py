"""PrefixPallasBackend — batch eval with the shared top-of-tree expanded once.

Same contract as PallasBackend (put_bundle / stage / eval_staged /
points_mismatch_count / eval; lam = 16, single key), but the top
``prefix_levels`` (k) of the GGM walk are expanded once per (key, party)
as a tree frontier (ops.pallas_tree.tree_expand_raw) and cached on device
with the key image; each eval gathers every point's (s, v, t) carry from
the frontier and walks only the remaining n - k levels
(ops.pallas_prefix).  Work per batch drops from M*n to M*(n-k) + 2^{k+1}
PRG calls — the frontier is key material (xs-independent), so it ships
once like the CW image, while the per-point gather is xs-dependent and
stays on the eval clock.

Reference workload this accelerates: benches/dcf_batch_eval.rs:17-39
(random-point batch eval; the reference walks all n levels per point,
src/lib.rs:163-204).

Cost structure measured on v5e (benchmarks/micro_gather.py): the gather
is ~3.4-3.7 ms per 2^20 points for k <= 21 and cliffs 4x at 2^22
frontier rows (the 128 MB table), so k is clamped to <= 21; the
bit-plane repack rides inside the walk kernel (~0.5 ms/table).  At the
config-2 shape (n = 32, M = 2^20, k = 21 -> 11 walked levels) the
gather+relayout floor (~4.4 ms ~ 6 walk levels) caps the speedup at
1.86x instead of the ideal 32/11 = 2.9x.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.backends.frontier import FrontierConsumerMixin
from dcf_tpu.backends.fulldomain import tree_expand_np
from dcf_tpu.backends.pallas_backend import PallasBackend, _stage_xs
from dcf_tpu.errors import DcfError, ShapeError, StaleStateError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.pallas_prefix import dcf_eval_prefix_pallas
from dcf_tpu.ops.pallas_tree import tree_expand_raw
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.spec import ReferenceContractWarning
from dcf_tpu.testing.faults import fire
from dcf_tpu.utils.bits import bitmajor_perm, byte_bits_lsb, pack_lanes

__all__ = ["PrefixPallasBackend", "gather_and_walk"]

# Gather cliff measured at >= 2^22 frontier nodes (micro_gather.py:
# 3.4-3.7 ms for k <= 21, 13.8 ms at k = 22 — the 128 MB table is the
# break point).  The frontier is untimed key material, so k beyond
# log2(M) still wins on the eval clock as long as the gather stays fast.
MAX_PREFIX_LEVELS = 21

_PERM16 = bitmajor_perm(16)

# Row (i*32 + b) of the int32-column view <- bit-major plane index.
_PERM_I32 = np.array(
    [(b % 8) * 16 + i * 4 + b // 8 for i in range(4) for b in range(32)],
    dtype=np.int32)


@jax.jit
def _planes_to_rows(planes, perm_i32):
    """int32 bit-major planes [128, W] -> int32 rows [32*W, 4].

    Inverse of the in-kernel transpose: row m's int32 column i, bit b =
    plane (b%8)*16 + i*4 + b//8, word m//32, bit m%32.  Runs once per
    (key, party) at frontier-build time — off the eval clock.
    """
    w = planes.shape[1]
    pp = jax.lax.bitcast_convert_type(
        jnp.take(planes, perm_i32, axis=0), jnp.uint32)  # [128(i,b), W]
    bits = (pp[:, :, None] >> jnp.arange(32, dtype=jnp.uint32)) \
        & jnp.uint32(1)  # [128, W, 32(j)]
    bits = bits.reshape(4, 32, w, 32)  # [i, b, w, j]
    rows = jnp.sum(bits << jnp.arange(32, dtype=jnp.uint32)[None, :, None,
                                                            None],
                   axis=1, dtype=jnp.uint32)  # [i, w, j]
    return jax.lax.bitcast_convert_type(
        rows.transpose(1, 2, 0).reshape(32 * w, 4), jnp.int32)


@partial(jax.jit, static_argnames=("k",))
def _stage_prefix_idx(xs, k: int):
    """uint8 xs [M, nb] -> frontier positions uint32 [M].

    Frontier node order is bitreverse: position = sum_i dir_i * 2^i over
    the MSB-first walk directions dir_i = bit i of x (i < k).
    """
    nb = xs.shape[1]
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = ((xs[:, :, None] >> shifts) & jnp.uint8(1)).reshape(
        xs.shape[0], nb * 8)  # MSB-first walk bits
    return jnp.sum(bits[:, :k].astype(jnp.uint32)
                   << jnp.arange(k, dtype=jnp.uint32)[None, :], axis=1)


def gather_and_walk(rk, table, idx, cw_s_r, cw_v_r, cw_np1, cw_t_r,
                    x_mask_rem, *, tile_words: int, interpret: bool,
                    k_num: int = 1, frontier_size: int = 0,
                    group: str = "xor", negate: bool = False):
    """Gather rows, relayout, walk n-k levels — unjitted so
    ``parallel.ShardedPrefixBackend`` can wrap it in ``shard_map`` (the
    gather is a pure per-point map against the replicated frontier
    table, so points shard with no collectives).

    Multi-key: ``table`` stacks K per-key frontiers [K * 2^k, 8]
    (``frontier_size`` = 2^k) and the shared ``idx`` is offset per key —
    one flat gather of K*M rows, then the kernel grids over keys exactly
    as the from-root walk does."""
    m = idx.shape[0]
    if k_num == 1:
        flat = idx
    else:
        flat = (jnp.arange(k_num, dtype=jnp.uint32)[:, None]
                * jnp.uint32(frontier_size) + idx[None, :]).reshape(-1)
    rows = jnp.take(table, flat, axis=0).reshape(k_num, m, 8)
    # -> [K, 8, 32, W] with the j (point-within-word) axis reversed, the
    # layout the kernel's butterfly transpose expects.
    blk = (rows.transpose(0, 2, 1).reshape(k_num, 8, m // 32, 32)
           .transpose(0, 1, 3, 2)[:, :, 31::-1, :])
    srows = blk[:, :4]
    vrows = blk[:, 4:]
    return dcf_eval_prefix_pallas(
        rk, srows, vrows, cw_s_r, cw_v_r, cw_np1, cw_t_r, x_mask_rem,
        tile_words=tile_words, interpret=interpret, group=group,
        negate=negate)


_eval_prefix_staged = partial(
    jax.jit, static_argnames=("tile_words", "interpret", "k_num",
                              "frontier_size", "group", "negate"))(
    gather_and_walk)


class PrefixPallasBackend(FrontierConsumerMixin, PallasBackend):
    """Prefix-shared DCF evaluator (lam = 16, shared points).

    ``prefix_levels`` picks k (clamped to n-8 and the measured gather
    cliff at 21); the frontier for each party is built lazily on first
    ``eval_staged(b, ...)`` and cached with the key image.  Multi-key
    bundles stack per-key frontiers and offset the shared prefix
    indices per key (one flat gather); per-key POINT batches have no
    shared staging to exploit and stay on PallasBackend.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes],
                 prefix_levels: int = MAX_PREFIX_LEVELS,
                 tile_words: int = 128, interpret: bool = False,
                 host_levels: int = 6):
        super().__init__(lam, cipher_keys, tile_words=tile_words,
                         interpret=interpret)
        if prefix_levels < host_levels:
            raise ValueError(  # api-edge: constructor prefix_levels contract
                f"prefix_levels must be >= host_levels={host_levels}")
        if host_levels < 5:
            # api-edge: constructor host_levels contract
            raise ValueError("need at least 5 host levels (one lane word)")
        self.prefix_levels = min(prefix_levels, MAX_PREFIX_LEVELS)
        self.host_levels = host_levels
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore", ReferenceContractWarning)
            self._prg = HirosePrgNp(lam, cipher_keys)
        self._perm_i32 = jnp.asarray(_PERM_I32)
        self.invalidate_frontier()
        self._bundle_host = None

    def _k(self) -> int:
        """Effective prefix depth for the on-device bundle: leave at
        least 8 walked levels so the kernel's fori_loop has real work and
        the t-stash invariant (>= 1 PRG application) always holds.

        The gather cliff is on TOTAL stacked table rows (K * 2^k >= 2^22
        is the measured break), so multi-key bundles shrink k by
        ceil(log2 K); floored at 5 (one lane word of frontier — beyond
        K = 2^16 keys the stacked table crosses the cliff regardless and
        the keylanes backend is the right tool)."""
        k_num, n = self._dims()
        k_cap = MAX_PREFIX_LEVELS - (k_num - 1).bit_length()
        return max(min(self.prefix_levels, n - 8, k_cap), 5)

    def put_bundle(self, bundle: KeyBundle) -> None:
        if 8 * bundle.n_bytes < self.host_levels + 8:
            raise ShapeError(
                f"domain of {8 * bundle.n_bytes} levels is too shallow "
                "for prefix sharing; use PallasBackend")
        super().put_bundle(bundle)
        self.invalidate_frontier()  # new key image, one hook (backends.frontier)
        self._bundle_host = bundle
        # The remaining-level CW views are bundle constants: sliced once
        # here (off the eval clock) instead of per eval_staged dispatch.
        k = self._k()
        dev = self._bundle_dev
        self._cw_rem = (dev["cw_s"][:, k:], dev["cw_v"][:, k:],
                        dev["cw_t"][:, k:])

    def _one_key_table(self, b: int, key: int, k: int, k0: int):
        """One key's frontier rows int32 [2^k, 8]: columns 0-3 = s (t
        stashed in the masked bit -> plane 15), 4-7 = v."""
        kb = self._bundle_host
        per_key = KeyBundle(
            s0s=kb.s0s[key:key + 1], cw_s=kb.cw_s[key:key + 1],
            cw_v=kb.cw_v[key:key + 1], cw_t=kb.cw_t[key:key + 1],
            cw_np1=kb.cw_np1[key:key + 1], group=kb.group)
        s, v, t = tree_expand_np(self._prg, per_key, int(b), k0)

        def planes(a):  # [N, 16] -> int32 [128, N/32]
            bits = byte_bits_lsb(a)[:, _PERM16]
            return jnp.asarray(pack_lanes(
                np.ascontiguousarray(bits.T)).view(np.int32))

        t_pm = jnp.asarray(pack_lanes(t[None, :]).view(np.int32))
        dev = self._bundle_dev
        s_p, v_p, t_p = tree_expand_raw(
            self.rk, dev["cw_s"][key], dev["cw_v"][key], dev["cw_t"][key],
            planes(s), planes(v), t_pm,
            k0=k0, k1=k, interpret=self.interpret, group=self._group)
        # Stash t in plane 15 of s: structurally zero there (the Hirose
        # 8*lam-1 mask clears it in every PRG output, and cw_s XORs of
        # masked outputs preserve that; k >= 1 guarantees at least one
        # PRG application).  Guarded: a nonzero plane 15 would corrupt
        # seeds silently.
        if int(jnp.any(s_p[15] != 0)):
            # A broken stash would corrupt seeds silently — that is key
            # material, so it surfaces through the typed taxonomy.
            raise DcfError(
                "frontier s plane 15 not zero — t-stash invariant broken")
        s_p = s_p.at[15:16].set(t_p)
        return jnp.concatenate(
            [_planes_to_rows(s_p, self._perm_i32),
             _planes_to_rows(v_p, self._perm_i32)], axis=1)  # [2^k, 8]

    def _build_frontier_tables(self, b: int):
        """The party-b frontier gather table int32 [K * 2^k, 8] (per-key
        tables stacked).  Built once per (bundle, party) on device,
        cached like the CW image (instance store or the serve-resident
        frontier cache — ``backends.frontier``)."""
        k = self._k()
        k0 = min(self.host_levels, k)
        k_num = self._dims()[0]
        return jnp.concatenate(
            [self._one_key_table(b, key, k, k0) for key in range(k_num)],
            axis=0)

    def stage(self, xs: np.ndarray) -> dict:
        """Stage xs as walk-order masks (full depth, for the parity
        counter) + frontier positions; slices the remaining-level masks
        the kernel consumes.  All xs-only preprocessing — untimed, like
        the criterion setup."""
        xs, m, wt = self._prepare(xs)
        if m == 0:
            raise ShapeError("cannot stage an empty batch")
        if xs.shape[0] != 1:
            raise ShapeError(
                "PrefixPallasBackend wants shared points [M, nb] (the "
                "prefix indices are computed once and offset per key); "
                "use PallasBackend for per-key point batches")
        k = self._k()
        xj = jnp.asarray(xs)
        x_mask = _stage_xs(xj)
        return {"x_mask": x_mask, "x_mask_rem": x_mask[:, k:],
                "idx": _stage_prefix_idx(xj[0], k=k), "m": m, "wt": wt,
                "k": k, "n": 8 * xs.shape[-1]}

    def _check_staged_fresh(self, staged: dict) -> None:
        """Reject a staged dict cut for a bundle geometry this backend no
        longer holds.  The staged arrays are pure functions of (xs, k, n)
        — idx and x_mask_rem are sliced at the prefix depth k of the
        bundle shipped at stage() time — so a dict staged against one
        geometry is still VALID for any later bundle with the same
        (k, n), including on another party's backend instance (the
        documented cross-party staging pattern).  What must be rejected
        is geometry drift: put_bundle changing _k() (key count shifts the
        gather-cliff cap) or the domain depth pairs new CW slices with
        masks cut at the old k — at best an opaque Pallas shape error, at
        worst a silently-wrong share (ADVICE.md finding 3)."""
        if "idx" not in staged:
            # api-edge: documented staged-protocol contract (a non-prefix dict)
            raise ValueError("staged dict is not from a prefix backend's "
                             "stage")
        k_now, n_now = self._k(), self._dims()[1]
        if staged.get("k") != k_now or staged.get("n") != n_now:
            raise StaleStateError(
                f"staged points are stale: staged at prefix depth "
                f"k={staged.get('k')} over an n={staged.get('n')}-level "
                f"domain, but the backend now holds a bundle with "
                f"k={k_now}, n={n_now}; re-stage the points after "
                "put_bundle")

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        fire("pallas.lowering")  # fault seam: deterministic Mosaic failure
        self._check_staged_fresh(staged)
        cw_s_r, cw_v_r, cw_t_r = self._cw_rem
        tbl = self._frontier_tables(b)
        return _eval_prefix_staged(
            self.rk, tbl, staged["idx"],
            cw_s_r, cw_v_r, self._bundle_dev["cw_np1"],
            cw_t_r, staged["x_mask_rem"],
            tile_words=staged["wt"], interpret=self.interpret,
            k_num=self._dims()[0], frontier_size=1 << self._k(),
            group=self._group,
            negate=bool(b) and self._group != "xor")

    def eval(self, b: int, xs: np.ndarray,
             bundle: KeyBundle | None = None) -> np.ndarray:
        """Bytes-in/bytes-out convenience path (shared points)."""
        if bundle is not None:
            self.put_bundle(bundle)
        if xs.ndim == 3:
            if xs.shape[0] != 1:
                raise ShapeError(
                    "PrefixPallasBackend wants shared points; use "
                    "PallasBackend for per-key point batches")
            xs = xs[0]
        staged = self.stage(xs)
        return self.staged_to_bytes(self.eval_staged(b, staged),
                                    staged["m"])
