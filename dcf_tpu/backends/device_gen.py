"""Device-side batched key generation (keys-in-lanes layout).

Keygen (reference ``src/lib.rs:86-161``) is sequential across the n = 8*N
levels but embarrassingly parallel across keys, so at secure-ReLU scale
(BASELINE config 5: 10^6 keys) it belongs ON the accelerator: the host
ships only alphas + betas + starting seeds (~64 MB for 10^6 keys) and the
~4.4 GB correction-word image is born directly in HBM, in exactly the
packed keys-in-lanes form the keylanes evaluators consume — instead of
being generated on one CPU core and dragged through the host->device link.

Layout: keys packed 32-per-uint32-lane-word (Wk = K/32 words).  Seeds and
values live as byte-major planes [8*lam, Wk] (plane p = byte*8 + bit, the
``prg_planes`` convention); per-level outputs stack to [n, 8*lam, Wk].
Correctness is pinned to the numpy ``gen_batch`` bit-for-bit
(tests/test_device_gen.py, tests/test_keygen_device.py).

This generator is lam-generic (the plane count scales with lam) and
serves as the lam < 48 route of ``gen.gen_on_device`` (ISSUE 10); the
hybrid family (lam >= 48) routes to ``ops.pallas_keygen``, whose narrow
kernel shares the eval kernels' per-level AES core.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.backends.jax_bitsliced import _pack_lanes_dev, prg_planes
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes_bitsliced import round_key_masks
from dcf_tpu.spec import Bound, hirose_used_cipher_indices
from dcf_tpu.utils.bits import bits_lsb_to_bytes, unpack_lanes

__all__ = ["DeviceKeyGen"]

# numpy scalar, not jnp: a module-scope jnp constant would initialize
# the JAX backend at import, breaking jax.distributed.initialize (which
# must precede any computation); promotes identically inside jit.
_ONES = np.uint32(0xFFFFFFFF)


@partial(jax.jit, static_argnames=("n", "lam"))
def _stage_inputs_dev(alphas, betas, s0s, n: int, lam: int):
    """Raw uint8 inputs -> packed keys-in-lanes masks/planes.

    alphas uint8 [K, n/8], betas uint8 [K, lam], s0s uint8 [K, 2, lam]
    (K % 32 == 0).  Returns (alpha_mask [n, Wk], beta_pl [8lam, Wk],
    s0a_pl, s0b_pl [8lam, Wk]) — all uint32.
    """
    k = alphas.shape[0]

    def planes_lsb(a):  # uint8 [K, nbytes] -> planes [8*nbytes, Wk]
        bits = (a[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & jnp.uint8(1)
        return _pack_lanes_dev(bits.reshape(k, -1).T)

    # alpha walk bits are MSB-first (reference Msb0 view, src/lib.rs:106)
    abits = (alphas[..., None] >> jnp.arange(7, -1, -1, dtype=jnp.uint8)) \
        & jnp.uint8(1)
    alpha_mask = _pack_lanes_dev(abits.reshape(k, n).T)
    return (alpha_mask, planes_lsb(betas),
            planes_lsb(s0s[:, 0, :]), planes_lsb(s0s[:, 1, :]))


@partial(jax.jit, static_argnames=("lam", "lt_beta"))
def _gen_core(rk_masks, last_bit_mask, alpha_mask, beta_pl, s0a_pl, s0b_pl,
              lam: int, lt_beta: bool):
    """The level scan.  Mirrors gen.gen_batch line for line, with per-key
    uint8 selects replaced by lane-mask muxes.  Returns (cw_s [n, 8lam, Wk],
    cw_v [n, 8lam, Wk], cw_tl [n, Wk], cw_tr [n, Wk], cw_np1 [8lam, Wk])."""

    def mux(m, if_one, if_zero):
        return (if_one & m) | (if_zero & (m ^ _ONES))

    def body(carry, a_i):
        s_a, s_b, t_a, t_b, v_alpha = carry
        al, vl_a, tl_a, ar, vr_a, tr_a = prg_planes(
            rk_masks, last_bit_mask, lam, s_a, _ONES)
        bl, vl_b, tl_b, br, vr_b, tr_b = prg_planes(
            rk_masks, last_bit_mask, lam, s_b, _ONES)
        am = a_i[None, :]  # broadcast over planes
        # lose side: L when alpha bit is 1, R when 0 (src/lib.rs:107-111)
        s_cw = mux(am, al ^ bl, ar ^ br)
        v_cw = mux(am, vl_a ^ vl_b, vr_a ^ vr_b) ^ v_alpha
        # beta folds into v_cw when the lose side matches the bound
        # (src/lib.rs:114-125)
        beta_gate = am if lt_beta else (am ^ _ONES)
        v_cw = v_cw ^ (beta_pl & beta_gate)
        v_keep = mux(am, vr_a ^ vr_b, vl_a ^ vl_b)
        v_alpha = v_alpha ^ v_keep ^ v_cw
        tl_cw = tl_a ^ tl_b ^ a_i ^ _ONES
        tr_cw = tr_a ^ tr_b ^ a_i
        t_cw_keep = mux(a_i, tr_cw, tl_cw)
        gate_a = t_a[None, :]
        gate_b = t_b[None, :]
        new_s_a = mux(am, ar, al) ^ (s_cw & gate_a)
        new_s_b = mux(am, br, bl) ^ (s_cw & gate_b)
        new_t_a = mux(a_i, tr_a, tl_a) ^ (t_a & t_cw_keep)
        new_t_b = mux(a_i, tr_b, tl_b) ^ (t_b & t_cw_keep)
        return ((new_s_a, new_s_b, new_t_a, new_t_b, v_alpha),
                (s_cw, v_cw, tl_cw, tr_cw))

    wk = alpha_mask.shape[1]
    init = (
        s0a_pl, s0b_pl,
        jnp.zeros((wk,), jnp.uint32),   # t^(0)_0 = 0
        jnp.full((wk,), _ONES, jnp.uint32),  # t^(0)_1 = 1
        jnp.zeros((8 * lam, wk), jnp.uint32),
    )
    (s_a, s_b, _t_a, _t_b, v_alpha), (cw_s, cw_v, cw_tl, cw_tr) = \
        jax.lax.scan(body, init, alpha_mask)
    cw_np1 = s_a ^ s_b ^ v_alpha
    return cw_s, cw_v, cw_tl, cw_tr, cw_np1


class DeviceKeyGen:
    """On-device batched GGM keygen producing keys-in-lanes device bundles.

    The output dict matches ``KeyLanesBackend._bundle_dev`` (plus both
    parties' seeds), so the generated image feeds the keylanes evaluators
    without ever leaving HBM.  ``to_host_bundle`` downloads and unpacks to
    a standard KeyBundle for interop/persistence.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes]):
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        self.lam = lam
        self.rk_masks = tuple(
            jnp.asarray(round_key_masks(cipher_keys[i])) for i in used)
        lbm = np.full(8 * lam, 0xFFFFFFFF, dtype=np.uint32)
        lbm[(lam - 1) * 8] = 0
        self._last_bit_mask = jnp.asarray(lbm)

    def gen(self, alphas: np.ndarray, betas: np.ndarray, s0s: np.ndarray,
            bound: Bound) -> dict:
        """alphas uint8 [K, n_bytes], betas uint8 [K, lam], s0s uint8
        [K, 2, lam].  Returns a device bundle dict: s0 (per party
        [2][8lam, Wk]), cw_s/cw_v [n, 8lam, Wk], cw_tl/cw_tr [n, Wk],
        cw_np1 [8lam, Wk], num_keys.  K is padded to a multiple of 32
        internally (pad keys are generated and ignored)."""
        k, n_bytes = alphas.shape
        if betas.shape != (k, self.lam) or s0s.shape != (k, 2, self.lam):
            raise ShapeError("alphas/betas/s0s shape mismatch")
        k_pad = (k + 31) // 32 * 32
        if k_pad != k:
            pad = [(0, k_pad - k)]
            alphas = np.pad(alphas, pad + [(0, 0)])
            betas = np.pad(betas, pad + [(0, 0)])
            s0s = np.pad(s0s, pad + [(0, 0), (0, 0)])
        n = 8 * n_bytes
        alpha_mask, beta_pl, s0a_pl, s0b_pl = _stage_inputs_dev(
            jnp.asarray(alphas), jnp.asarray(betas), jnp.asarray(s0s),
            n=n, lam=self.lam)
        cw_s, cw_v, cw_tl, cw_tr, cw_np1 = _gen_core(
            self.rk_masks, self._last_bit_mask, alpha_mask, beta_pl,
            s0a_pl, s0b_pl, lam=self.lam,
            lt_beta=(bound is Bound.LT_BETA))
        return dict(
            s0=(s0a_pl, s0b_pl), cw_s=cw_s, cw_v=cw_v, cw_tl=cw_tl,
            cw_tr=cw_tr, cw_np1=cw_np1, num_keys=k,
        )

    def to_host_bundle(self, dev: dict) -> KeyBundle:
        """Download + unpack a device bundle to a standard KeyBundle."""
        k = dev["num_keys"]

        def unpack_planes(a):  # [..., 8lam, Wk] -> uint8 [K, ..., lam]
            bits = unpack_lanes(np.asarray(a))  # [..., 8lam, K_pad]
            return bits_lsb_to_bytes(np.moveaxis(bits, -1, 0)[:k])

        def unpack_bits(a):  # [n, Wk] -> uint8 [K, n]
            return np.moveaxis(unpack_lanes(np.asarray(a)), -1, 0)[:k]

        s0a = unpack_planes(dev["s0"][0])
        s0b = unpack_planes(dev["s0"][1])
        return KeyBundle(
            s0s=np.stack([s0a, s0b], axis=1),
            cw_s=unpack_planes(dev["cw_s"]),
            cw_v=unpack_planes(dev["cw_v"]),
            cw_t=np.stack(
                [unpack_bits(dev["cw_tl"]), unpack_bits(dev["cw_tr"])],
                axis=2),
            cw_np1=unpack_planes(dev["cw_np1"]),
        )
