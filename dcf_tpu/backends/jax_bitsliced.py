"""Fully bitsliced JAX eval backend — the TPU throughput path.

The entire GGM walk stays in bit-plane form: the scan carry is

    s  uint32 [8*lam, K, W]   seed planes (W = points/32 packed words)
    t  uint32 [K, W]          control bits, one per (key, point) lane
    v  uint32 [8*lam, K, W]   output accumulator planes

and every level is pure XOR/AND plane algebra: the Hirose PRG runs the
bitsliced AES (ops.aes_bitsliced) on the seed planes directly — seed^c is a
plane-wise NOT — correction words enter as per-key masks broadcast across
lanes, and the left/right child select is a lane-mask mux.  Nothing is ever
packed or unpacked inside the scan; bytes<->planes conversion happens once at
the edges on the host (utils.bits).

This layout keeps keys on the broadcast axis ("mode A": points packed in
lanes) — right for few-keys x many-points workloads like the flagship
100k-point bench.  The many-keys x few-points regime (secure-ReLU) packs
keys into lanes instead; see ``dcf_tpu.workloads``.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.errors import ShapeError, StaleStateError
from dcf_tpu.backends._common import prepare_batch
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes_bitsliced import aes256_encrypt_planes, round_key_masks
from dcf_tpu.ops.group_accum import (group_width, planes_add_bytemajor,
                                     planes_neg_bytemajor)
from dcf_tpu.spec import hirose_used_cipher_indices
from dcf_tpu.utils.bits import byte_bits_lsb, expand_bits_to_masks, pack_lanes

__all__ = [
    "BitslicedBackend",
    "KeyLanesBackend",
    "eval_core_bitsliced",
    "eval_core_keylanes",
    "prg_planes",
]

_ONES = np.uint32(0xFFFFFFFF)


def prg_planes(rk_masks, last_bit_mask, lam: int, seed, ones):
    """Bitsliced Hirose PRG: seed planes [8*lam, *rest] -> six outputs.

    Shape-agnostic over the trailing dims; used by both lane layouts
    (points-in-lanes and keys-in-lanes).  Returns (s_l, v_l, t_l, s_r, v_r,
    t_r) where s/v are [8*lam, *rest] planes and t are [*rest] lane masks.
    """
    n_blocks = lam // 16
    n_enc = min(2, n_blocks)
    rest = seed.shape[1:]
    lbm = last_bit_mask.reshape(8 * lam, *([1] * len(rest)))
    seed_p = seed ^ ones
    enc0: list = [None, None]
    enc1: list = [None, None]
    for k in range(n_enc):
        blk = slice(128 * k, 128 * (k + 1))
        both = aes256_encrypt_planes(
            jnp,
            rk_masks[k],
            jnp.stack([seed[blk], seed_p[blk]], axis=1),  # [128, 2, *rest]
            ones,
        )
        enc0[k] = both[:, 0]
        enc1[k] = both[:, 1]

    zeros128 = jnp.zeros((128, *rest), dtype=jnp.uint32)

    def half(enc, h):
        parts = [
            enc[h] if (j == h and h < n_enc) else zeros128 for j in range(n_blocks)
        ]
        return parts[0] if n_blocks == 1 else jnp.concatenate(parts, axis=0)

    buf0 = [half(enc0, 0) ^ seed, half(enc0, 1) ^ seed]
    buf1 = [half(enc1, 0) ^ seed_p, half(enc1, 1) ^ seed_p]
    t_l = buf0[0][0]
    t_r = buf1[0][0]
    return (
        buf0[0] & lbm,
        buf1[0] & lbm,
        t_l,
        buf0[1] & lbm,
        buf1[1] & lbm,
        t_r,
    )


def eval_core_bitsliced(
    rk_masks: tuple[jnp.ndarray, ...],  # per used cipher: uint32 [15, 128]
    last_bit_mask: jnp.ndarray,  # uint32 [8*lam] (clears plane (lam-1)*8)
    s0_pl: jnp.ndarray,  # uint32 [8*lam, K]
    cw_s_pl: jnp.ndarray,  # uint32 [n, 8*lam, K]
    cw_v_pl: jnp.ndarray,  # uint32 [n, 8*lam, K]
    cw_tl: jnp.ndarray,  # uint32 [n, K]
    cw_tr: jnp.ndarray,  # uint32 [n, K]
    cw_np1_pl: jnp.ndarray,  # uint32 [8*lam, K]
    x_mask: jnp.ndarray,  # uint32 [n, Kx, W] (Kx = K or 1 for shared points)
    b: int,
    lam: int,
    group: str = "xor",
) -> jnp.ndarray:
    """Party ``b`` eval, all planes; returns y planes uint32 [8*lam, K, W].

    ``group`` selects the value accumulation: XOR plane algebra, or the
    additive group's per-lane mod-2^w ripple add over the byte-major
    planes (ops.group_accum).  Additive output planes are SIGNED shares:
    party 1's result is negated here, inside the core, so staged planes
    already honor the signed-share contract and reconstruction is always
    a plain lane add.
    """
    ones = jnp.uint32(0xFFFFFFFF)
    gw = group_width(group)  # 0 for xor
    k_num = s0_pl.shape[1]
    w = x_mask.shape[2]
    p = 8 * lam

    s = jnp.broadcast_to(s0_pl[:, :, None], (p, k_num, w))
    t = jnp.full((k_num, w), ones if b else jnp.uint32(0), dtype=jnp.uint32)
    v = jnp.zeros((p, k_num, w), dtype=jnp.uint32)

    def body(carry, level):
        s, t, v = carry
        cs, cv, ctl, ctr, xm = level
        s_l, v_l, t_l, s_r, v_r, t_r = prg_planes(
            rk_masks, last_bit_mask, lam, s, ones
        )
        gate = t[None, :, :]
        s_l = s_l ^ (cs[:, :, None] & gate)
        s_r = s_r ^ (cs[:, :, None] & gate)
        t_l = t_l ^ (t & ctl[:, None])
        t_r = t_r ^ (t & ctr[:, None])
        xm_e = xm[None, :, :]  # broadcasts over planes and (if shared) keys
        v_hat = (v_r & xm_e) | (v_l & (xm_e ^ ones))
        cv_g = cv[:, :, None] & gate
        if gw:
            v = planes_add_bytemajor(
                v, planes_add_bytemajor(v_hat, cv_g, gw), gw)
        else:
            v = v ^ v_hat ^ cv_g
        s = (s_r & xm_e) | (s_l & (xm_e ^ ones))
        t = (t_r & xm) | (t_l & (xm ^ ones))
        return (s, t, v), None

    (s, t, v), _ = jax.lax.scan(
        body, (s, t, v), (cw_s_pl, cw_v_pl, cw_tl, cw_tr, x_mask)
    )
    tail = cw_np1_pl[:, :, None] & t[None, :, :]
    if not gw:
        return v ^ s ^ tail
    y = planes_add_bytemajor(planes_add_bytemajor(v, s, gw), tail, gw)
    return planes_neg_bytemajor(y, gw) if b else y


def eval_core_keylanes(
    rk_masks: tuple[jnp.ndarray, ...],
    last_bit_mask: jnp.ndarray,  # uint32 [8*lam]
    s0_pl: jnp.ndarray,  # uint32 [8*lam, Wk]  (keys packed in lanes)
    cw_s_pl: jnp.ndarray,  # uint32 [n, 8*lam, Wk]
    cw_v_pl: jnp.ndarray,  # uint32 [n, 8*lam, Wk]
    cw_tl: jnp.ndarray,  # uint32 [n, Wk]
    cw_tr: jnp.ndarray,  # uint32 [n, Wk]
    cw_np1_pl: jnp.ndarray,  # uint32 [8*lam, Wk]
    x_mask: jnp.ndarray,  # uint32 [n, M, 1] (0/~0 per point, shared by keys)
    b: int,
    lam: int,
    group: str = "xor",
) -> jnp.ndarray:
    """Keys-in-lanes eval (many-keys regime): y planes uint32 [8*lam, M, Wk].

    The dual of ``eval_core_bitsliced``: keys are packed 32-per-word so the
    per-key correction words are packed data (no 32x broadcast blow-up),
    while the shared evaluation points ride the explicit axis as full/zero
    masks.  This is what makes the 10^6-key secure-ReLU shape fit in HBM:
    the key image stays at its byte size (n*lam bytes per key).

    ``group`` behaves as in ``eval_core_bitsliced`` (additive shares come
    out signed; the ripple carries stay within each key's bit column, so
    the lane packing is transparent to the add).
    """
    ones = jnp.uint32(0xFFFFFFFF)
    gw = group_width(group)
    m = x_mask.shape[1]
    wk = s0_pl.shape[1]
    p = 8 * lam

    s = jnp.broadcast_to(s0_pl[:, None, :], (p, m, wk))
    t = jnp.full((m, wk), ones if b else jnp.uint32(0), dtype=jnp.uint32)
    v = jnp.zeros((p, m, wk), dtype=jnp.uint32)

    def body(carry, level):
        s, t, v = carry
        cs, cv, ctl, ctr, xm = level
        s_l, v_l, t_l, s_r, v_r, t_r = prg_planes(
            rk_masks, last_bit_mask, lam, s, ones
        )
        gate = t[None, :, :]
        s_l = s_l ^ (cs[:, None, :] & gate)
        s_r = s_r ^ (cs[:, None, :] & gate)
        t_l = t_l ^ (t & ctl[None, :])
        t_r = t_r ^ (t & ctr[None, :])
        xm_e = xm[None, :, :]
        v_hat = (v_r & xm_e) | (v_l & (xm_e ^ ones))
        cv_g = cv[:, None, :] & gate
        if gw:
            v = planes_add_bytemajor(
                v, planes_add_bytemajor(v_hat, cv_g, gw), gw)
        else:
            v = v ^ v_hat ^ cv_g
        s = (s_r & xm_e) | (s_l & (xm_e ^ ones))
        t = (t_r & xm) | (t_l & (xm ^ ones))
        return (s, t, v), None

    (s, t, v), _ = jax.lax.scan(
        body, (s, t, v), (cw_s_pl, cw_v_pl, cw_tl, cw_tr, x_mask)
    )
    tail = cw_np1_pl[:, None, :] & t[None, :, :]
    if not gw:
        return v ^ s ^ tail
    y = planes_add_bytemajor(planes_add_bytemajor(v, s, gw), tail, gw)
    return planes_neg_bytemajor(y, gw) if b else y


# ---------------------------------------------------------------------------
# Device-side bytes<->planes conversion.  The byte<->plane transposes cost
# real bandwidth at 10^6+ point batches; doing them on host (single CPU core)
# was the bottleneck, so they live inside the jitted program: the host ships
# raw bytes and receives raw bytes.
# ---------------------------------------------------------------------------


def _pack_lanes_dev(bits):
    """{0,1} [..., B] -> uint32 [..., B/32] (B % 32 == 0).  Disjoint-bit sum
    == bitwise or, and uint32 addition cannot carry across them."""
    b = bits.shape[-1]
    w = bits.astype(jnp.uint32).reshape(*bits.shape[:-1], b // 32, 32)
    return jnp.sum(w << jnp.arange(32, dtype=jnp.uint32), axis=-1, dtype=jnp.uint32)


def _xs_to_mask_dev(xs):
    """uint8 [Kx, M, n_bytes] -> walk-order lane masks uint32 [n, Kx, M/32]."""
    kx, m, nb = xs.shape
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (xs[..., None] >> shifts) & jnp.uint8(1)  # [Kx, M, nb, 8] MSB-first
    bits = jnp.moveaxis(bits.reshape(kx, m, nb * 8), 2, 0)  # [n, Kx, M]
    return _pack_lanes_dev(bits)


@partial(jax.jit, static_argnames=("m", "nb"))
def _range_xs_dev(start, m: int, nb: int):
    """Big-endian bytes of points start..start+m-1, generated ON DEVICE.

    The full-domain workload (BASELINE config 3, n=24) never ships xs from
    the host: one iota expands into the [1, m, nb] uint8 batch inside the
    jitted program (SURVEY.md section 7 step 5).  uint32 arithmetic covers
    the whole n_bits=32 domain without wraparound artifacts.
    """
    idx = start + jnp.arange(m, dtype=jnp.uint32)
    shifts = jnp.asarray([8 * (nb - 1 - k) for k in range(nb)], jnp.uint32)
    return ((idx[:, None] >> shifts) & 0xFF).astype(jnp.uint8)[None]


def _lt_lane_mask_dev(start, alpha, w: int, gt: bool):
    """uint32 [1, W] lane mask: bit set iff (start + lane index) <cmp> alpha.
    Trace-time helper (w static at trace time); unsigned 32-bit compare."""
    idx = start + jnp.arange(32 * w, dtype=jnp.uint32)
    inside = (idx > alpha) if gt else (idx < alpha)
    return _pack_lanes_dev(inside.astype(jnp.uint32)[None])


@partial(jax.jit, static_argnames=("gt",))
def _fd_mismatch_bytemajor(y0, y1, beta_mask, start, alpha, *, gt: bool):
    """Mismatching-point count for byte-major planes [8lam, K, W] (K = 1)."""
    w = y0.shape[-1]
    ltw = _lt_lane_mask_dev(start, alpha, w, gt)  # [1, W]
    expect = beta_mask[:, None, None] & ltw[None, :, :]
    diff = jnp.bitwise_or.reduce(y0 ^ y1 ^ expect, axis=0)  # [K, W]
    return jnp.sum(jax.lax.population_count(diff).astype(jnp.int32))


def walk_inside_mask(x_of, alpha_of, n: int, zero, gt: bool):
    """Lexicographic compare on walk-order lane masks, the SINGLE source
    of the bound semantics for every random-points parity counter:
    returns the ``inside`` mask (shaped like ``zero``) — all-ones in
    lanes where x < alpha (x > alpha for gt).

    ``x_of(i)`` / ``alpha_of(i)`` yield walk-bit i's masks (0 /
    all-ones), broadcast-compatible with ``zero``; alphas may be static
    python constants wrapped as masks (XLA folds the all-ones/zero ANDs
    back to the specialized form) or per-key DATA arrays (the multi-key
    counter).  Shared by the bit-major (Pallas) single- and multi-key
    counters and the byte-major (bitsliced) counter so the bound
    semantics cannot desynchronize between the bench parity gates.
    """
    inside = zero
    eq = ~zero  # all-ones
    for i in range(n):  # static unroll: a few word-ops per level
        xi = x_of(i)
        ai = alpha_of(i)
        if gt:
            inside = inside | (eq & xi & ~ai)
        else:
            inside = inside | (eq & ~xi & ai)
        eq = eq & ~(xi ^ ai)
    return inside


@partial(jax.jit, static_argnames=("alpha_bits", "gt"))
def _points_mismatch_bytemajor(y0, y1, beta_mask, x_mask, *,
                               alpha_bits: tuple, gt: bool):
    """Mismatch count vs the comparison function for staged RANDOM points.

    y0/y1: eval_staged outputs uint32 [8lam, 1, W]; x_mask: staged
    walk-order lane masks uint32 [n, 1, W]; alpha_bits: alpha's n bits
    MSB-first (static).  The lexicographic compare runs on the bit-mask
    planes directly; padding points are genuine evaluations of x=0 and
    self-verify."""
    w = y0.shape[-1]
    inside = walk_inside_mask(
        lambda i: x_mask[i],
        lambda i: jnp.uint32(0xFFFFFFFF if alpha_bits[i] else 0),
        len(alpha_bits), jnp.zeros((1, w), jnp.uint32), gt)
    expect = beta_mask[:, None, None] & inside[None, :, :]
    diff = jnp.bitwise_or.reduce(y0 ^ y1 ^ expect, axis=0)  # [1, W]
    return jnp.sum(jax.lax.population_count(diff).astype(jnp.int32))


def _planes_to_bytes_dev(planes, lam: int):
    """uint32 [8*lam, K, W] -> uint8 [K, W*32, lam]."""
    p, k, w = planes.shape
    bits = (planes[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    bits = bits.astype(jnp.uint8).reshape(p, k, w * 32)
    bits = bits.transpose(1, 2, 0).reshape(k, w * 32, lam, 8)
    return jnp.sum(bits << jnp.arange(8, dtype=jnp.uint8), axis=-1, dtype=jnp.uint8)


def _eval_bytes(
    rk_masks, last_bit_mask, s0_pl, cw_s_pl, cw_v_pl, cw_tl, cw_tr, cw_np1_pl,
    xs, b: int, lam: int, group: str = "xor",
):
    """End-to-end device program: xs bytes in, y bytes out (points-in-lanes)."""
    y_planes = eval_core_bitsliced(
        rk_masks, last_bit_mask, s0_pl, cw_s_pl, cw_v_pl, cw_tl, cw_tr,
        cw_np1_pl, _xs_to_mask_dev(xs), b, lam, group,
    )
    return _planes_to_bytes_dev(y_planes, lam)


def _eval_keylanes_bytes(
    rk_masks, last_bit_mask, s0_pl, cw_s_pl, cw_v_pl, cw_tl, cw_tr, cw_np1_pl,
    xs, b: int, lam: int, group: str = "xor",
):
    """Device program for the keys-in-lanes layout: returns uint8 [M, K_pad, lam]."""
    m, nb = xs.shape
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = ((xs[..., None] >> shifts) & jnp.uint8(1)).reshape(m, nb * 8)
    x_mask = (bits.T.astype(jnp.uint32) * jnp.uint32(0xFFFFFFFF))[:, :, None]
    y_planes = eval_core_keylanes(
        rk_masks, last_bit_mask, s0_pl, cw_s_pl, cw_v_pl, cw_tl, cw_tr,
        cw_np1_pl, x_mask, b, lam, group,
    )
    return _planes_to_bytes_dev(y_planes, lam)


@partial(jax.jit, static_argnames=("m", "nb"))
def _stage_range_mask_jit(start, m: int, nb: int):
    return _xs_to_mask_dev(_range_xs_dev(start, m, nb))


_eval_jit = partial(jax.jit, static_argnames=("b", "lam", "group"))(_eval_bytes)
_eval_keylanes_jit = partial(jax.jit, static_argnames=("b", "lam", "group"))(
    _eval_keylanes_bytes
)
_stage_xs_jit = jax.jit(_xs_to_mask_dev)
_planes_to_bytes_jit = partial(jax.jit, static_argnames=("lam",))(
    _planes_to_bytes_dev
)
_eval_core_jit = partial(jax.jit, static_argnames=("b", "lam", "group"))(
    eval_core_bitsliced
)


class _BitslicedBase:
    """Shared cipher/mask setup for the two lane layouts."""

    def __init__(self, lam: int, cipher_keys: Sequence[bytes]):
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        self.lam = lam
        self.rk_masks = tuple(
            jnp.asarray(round_key_masks(cipher_keys[i])) for i in used
        )
        lbm = np.full(8 * lam, _ONES, dtype=np.uint32)
        lbm[(lam - 1) * 8] = 0  # clears the PRG's 8*lam-1 masked bit plane
        self._last_bit_mask = jnp.asarray(lbm)
        self._bundle_dev = None
        self._group = "xor"


def bundle_plane_arrays(bundle: KeyBundle) -> dict:
    """Party-restricted bundle -> host uint32 plane-mask arrays in the
    keys-LAST layout both the local and the mesh-sharded bitsliced
    evaluators consume (s0/cw_np1 [8lam, K]; cw_s/cw_v [n, 8lam, K];
    cw_tl/cw_tr [n, K])."""
    if bundle.s0s.shape[1] != 1:
        raise ShapeError("put_bundle requires a party-restricted bundle")

    def cw_planes(a):  # [K, n, lam] -> [n, 8lam, K]
        bits = byte_bits_lsb(a)
        return expand_bits_to_masks(
            np.ascontiguousarray(bits.transpose(1, 2, 0)))

    return dict(
        s0=expand_bits_to_masks(byte_bits_lsb(bundle.s0s[:, 0, :]).T),
        cw_s=cw_planes(bundle.cw_s),
        cw_v=cw_planes(bundle.cw_v),
        cw_tl=expand_bits_to_masks(bundle.cw_t[:, :, 0].T),
        cw_tr=expand_bits_to_masks(bundle.cw_t[:, :, 1].T),
        cw_np1=expand_bits_to_masks(byte_bits_lsb(bundle.cw_np1).T),
    )


class BitslicedBackend(_BitslicedBase):
    """Device-resident bitsliced DCF evaluator (API-compatible with JaxBackend)."""

    def _dims(self) -> tuple[int, int]:
        """(k_num, n_bits) of the on-device bundle; raises if absent."""
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        return self._bundle_dev["s0"].shape[1], self._bundle_dev["cw_s"].shape[0]

    def put_bundle(self, bundle: KeyBundle) -> None:
        """Ship a party-restricted bundle to device as plane masks."""
        if bundle.lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        self._bundle_dev = {
            k: jnp.asarray(v) for k, v in bundle_plane_arrays(bundle).items()
        }
        self._group = bundle.group

    def stage(self, xs: np.ndarray) -> dict:
        """Ship xs to device as walk-order lane masks (criterion-setup analog).

        Same protocol as ``PallasBackend.stage``: conversion + transfer happen
        here, outside any timed region.
        """
        xs, _, m = prepare_batch(self._dims(), xs,
                                 lambda m: (m + 31) // 32 * 32)
        if m == 0:
            raise ShapeError("cannot stage an empty batch")
        x_mask = _stage_xs_jit(jnp.asarray(xs))
        return {"x_mask": x_mask, "m": m}

    def stage_range(self, start: int, count: int) -> dict:
        """Stage the consecutive points start..start+count-1 WITHOUT any
        host->device xs transfer: the batch is generated from an iota inside
        the jitted program (full-domain workload, BASELINE config 3)."""
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        if count % 32 != 0:
            raise ShapeError(f"count {count} must be a multiple of 32")
        n = self._bundle_dev["cw_s"].shape[0]
        x_mask = _stage_range_mask_jit(
            jnp.uint32(start), m=count, nb=n // 8)
        return {"x_mask": x_mask, "m": count}

    def mismatch_count(self, y0, y1, alpha: int, beta: bytes, start: int,
                       gt: bool = False) -> jax.Array:
        """Device-side verification for full-domain runs: number of points in
        this staged chunk whose XOR reconstruction differs from the plain
        comparison function.  y0/y1: ``eval_staged`` outputs for the two
        parties over points start..start+32*W-1 (single key).  Returns a
        DEVICE int32 scalar so chunked callers can accumulate without a
        host round-trip per chunk."""
        beta_mask = jnp.asarray(expand_bits_to_masks(
            byte_bits_lsb(np.frombuffer(beta, dtype=np.uint8))))
        return _fd_mismatch_bytemajor(
            y0, y1, beta_mask, jnp.uint32(start), jnp.uint32(alpha), gt=gt)

    def points_mismatch_count(self, y0, y1, alpha: bytes, beta: bytes,
                              staged: dict, gt: bool = False) -> jax.Array:
        """Full on-device two-party verification for staged RANDOM points
        (the bench parity gate): count of points whose XOR reconstruction
        differs from ``beta if x < alpha else 0`` (``>`` for gt).  y0/y1:
        both parties' ``eval_staged`` outputs over the SAME staged batch.
        Single key.  Returns a DEVICE int32 scalar."""
        if y0.shape[1] != 1:
            raise ShapeError("points_mismatch_count is single-key")
        from dcf_tpu.utils.bits import alpha_walk_bits

        beta_mask = jnp.asarray(expand_bits_to_masks(
            byte_bits_lsb(np.frombuffer(beta, dtype=np.uint8))))
        return _points_mismatch_bytemajor(
            y0, y1, beta_mask, staged["x_mask"],
            alpha_bits=alpha_walk_bits(alpha), gt=gt)

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        """Party ``b`` eval on staged points; returns DEVICE-resident y planes
        (uint32 [8*lam, K, W]).  Dispatch is async — force completion with a
        fetch.  Use ``eval`` for the bytes-in/bytes-out path."""
        dev = self._bundle_dev
        return _eval_core_jit(
            self.rk_masks, self._last_bit_mask, dev["s0"], dev["cw_s"],
            dev["cw_v"], dev["cw_tl"], dev["cw_tr"], dev["cw_np1"],
            staged["x_mask"], b=int(b), lam=self.lam, group=self._group,
        )

    def staged_to_bytes(self, y_planes: jax.Array, m: int) -> np.ndarray:
        """Convert ``eval_staged`` output to uint8 [K, M, lam] on host."""
        return np.asarray(
            _planes_to_bytes_jit(y_planes, lam=self.lam)
        )[:, :m, :]

    def eval(
        self, b: int, xs: np.ndarray, bundle: KeyBundle | None = None
    ) -> np.ndarray:
        """Evaluate party ``b``; xs uint8 [M, n_bytes] or [K, M, n_bytes].

        Returns uint8 [K, M, lam].  Points are padded to a multiple of 32
        internally (the pad lanes are computed and discarded).
        """
        if bundle is not None:
            self.put_bundle(bundle)
        xs, _, m = prepare_batch(self._dims(), xs,
                                 lambda m: (m + 31) // 32 * 32)
        dev = self._bundle_dev
        y = _eval_jit(
            self.rk_masks,
            self._last_bit_mask,
            dev["s0"],
            dev["cw_s"],
            dev["cw_v"],
            dev["cw_tl"],
            dev["cw_tr"],
            dev["cw_np1"],
            jnp.asarray(xs),
            b=int(b),
            lam=self.lam,
            group=self._group,
        )  # uint8 [K, m_pad, lam]
        return np.asarray(y[:, :m, :])


class KeyLanesBackend(_BitslicedBase):
    """Many-keys bitsliced evaluator (keys packed in lanes, shared points).

    Use when K >> M (e.g. the 10^6-keys x 10^3-points secure-ReLU shape):
    the device-resident key image stays at its natural byte size instead of
    the 32x mask blow-up of the points-in-lanes layout.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes]):
        super().__init__(lam, cipher_keys)
        self._num_keys = 0

    def put_bundle(self, bundle: KeyBundle) -> None:
        """Ship a party-restricted bundle, keys packed 32-per-lane-word."""
        if bundle.lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        if bundle.s0s.shape[1] != 1:
            raise ShapeError("put_bundle requires a party-restricted bundle")
        k = bundle.num_keys
        k_pad = (k + 31) // 32 * 32
        self._num_keys = k

        def pad_keys(a):
            return np.pad(a, [(0, k_pad - k)] + [(0, 0)] * (a.ndim - 1))

        def packed(bits_k_last):
            # [..., K] {0,1} -> uint32 [..., K/32]
            return jnp.asarray(pack_lanes(np.ascontiguousarray(bits_k_last)))

        cw_s_bits = byte_bits_lsb(pad_keys(bundle.cw_s))  # [K, n, 8lam]
        cw_v_bits = byte_bits_lsb(pad_keys(bundle.cw_v))
        self._bundle_dev = dict(
            s0=packed(byte_bits_lsb(pad_keys(bundle.s0s[:, 0, :])).T),
            cw_s=packed(cw_s_bits.transpose(1, 2, 0)),
            cw_v=packed(cw_v_bits.transpose(1, 2, 0)),
            cw_tl=packed(pad_keys(bundle.cw_t[:, :, 0]).T),
            cw_tr=packed(pad_keys(bundle.cw_t[:, :, 1]).T),
            cw_np1=packed(byte_bits_lsb(pad_keys(bundle.cw_np1)).T),
        )
        self._group = bundle.group

    def eval(
        self, b: int, xs: np.ndarray, bundle: KeyBundle | None = None
    ) -> np.ndarray:
        """Evaluate party ``b`` on shared points xs uint8 [M, n_bytes].

        Returns uint8 [K, M, lam].
        """
        if bundle is not None:
            self.put_bundle(bundle)
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        if xs.ndim != 2:
            raise ShapeError("KeyLanesBackend requires shared points [M, n_bytes]")
        dev = self._bundle_dev
        n = dev["cw_s"].shape[0]
        if xs.shape[1] * 8 != n:
            raise ShapeError("xs width mismatch with bundle")
        y = _eval_keylanes_jit(
            self.rk_masks,
            self._last_bit_mask,
            dev["s0"],
            dev["cw_s"],
            dev["cw_v"],
            dev["cw_tl"],
            dev["cw_tr"],
            dev["cw_np1"],
            jnp.asarray(np.ascontiguousarray(xs)),
            b=int(b),
            lam=self.lam,
            group=self._group,
        )  # uint8 [M, K_pad, lam]
        return np.asarray(y).transpose(1, 0, 2)[: self._num_keys]
