"""Shared batch-shape validation/padding for the device eval backends.

Every backend accepts xs as uint8 [M, n_bytes] (points shared by all keys)
or [K, M, n_bytes] (per-key points) and returns uint8 [K, M, lam]; the
checks and the pad-and-promote step are identical across backends and live
here so a fix lands everywhere at once.
"""

from __future__ import annotations

from dcf_tpu.errors import ShapeError
import numpy as np

__all__ = ["validate_xs", "pad_xs", "prepare_batch"]


def validate_xs(xs: np.ndarray, k_num: int, n_bits: int) -> tuple[bool, int]:
    """Check xs against the on-device bundle; returns (shared, num_points)."""
    if xs.ndim not in (2, 3):
        raise ShapeError(f"xs must be 2D or 3D, got {xs.ndim}D")
    shared = xs.ndim == 2
    m = xs.shape[0] if shared else xs.shape[1]
    if xs.shape[-1] * 8 != n_bits:
        raise ShapeError("xs width mismatch with bundle")
    if not shared and xs.shape[0] != k_num:
        raise ShapeError(
            f"xs has {xs.shape[0]} key rows but bundle has {k_num} keys"
        )
    return shared, m


def pad_xs(xs: np.ndarray, shared: bool, m: int, m_pad: int) -> np.ndarray:
    """Zero-pad the point axis to m_pad and promote shared xs to [1, M, nb]."""
    if m_pad != m:
        pad = ([(0, m_pad - m), (0, 0)] if shared
               else [(0, 0), (0, m_pad - m), (0, 0)])
        xs = np.pad(xs, pad)
    return xs[None] if shared else xs


def prepare_batch(dims: tuple[int, int], xs: np.ndarray,
                  m_pad_of) -> tuple[np.ndarray, bool, int]:
    """The stage/eval preamble the device backends share: shape validation
    against the on-device bundle dims (k_num, n_bits), point padding
    (``m_pad_of(m)`` -> padded point count), contiguity.  Returns
    (xs_padded [Kx, M_pad, nb], shared, m).  Callers apply their own
    m == 0 policy on the returned m (the helper passes it through;
    m_pad_of must tolerate 0)."""
    k_num, n_bits = dims
    shared, m = validate_xs(xs, k_num, n_bits)
    xs = pad_xs(xs, shared, m, m_pad_of(m))
    return np.ascontiguousarray(xs), shared, m
