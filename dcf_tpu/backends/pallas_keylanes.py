"""KeyLanesPallasBackend — many-keys DCF evaluator on the keylanes kernel.

The config-5 (secure-ReLU) pipeline stays device-resident end to end:
DeviceKeyGen writes the packed keys-in-lanes CW image straight into HBM,
this backend walks it with the Pallas kernel (ops.pallas_keylanes), and
``relu_mismatch_count`` verifies the two-party XOR reconstruction against
the plain comparison on device — the host ships alphas/betas/seeds/xs and
receives one mismatch counter.

Unlike the one-party bundles of the other backends, a device bundle here
carries BOTH parties' seeds (the CW image is shared between parties —
reference src/lib.rs:269-272 — and at 4 GB it should exist once, not
twice).
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.errors import ShapeError, StaleStateError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes_bitsliced import round_key_masks_bitmajor
from dcf_tpu.ops.pallas_keylanes import dcf_eval_keylanes_pallas
from dcf_tpu.spec import hirose_used_cipher_indices
from dcf_tpu.utils.bits import (
    bitmajor_perm,
    bits_lsb_to_bytes,
    byte_bits_lsb,
    pack_lanes,
    unpack_lanes,
)

__all__ = ["KeyLanesPallasBackend"]

_PERM = bitmajor_perm(16)
_INV_PERM = np.argsort(_PERM)


@jax.jit
def _to_bitmajor_planes(a, perm):
    """uint32 [..., 8lam, Wk] byte-major planes -> int32 bit-major."""
    return jax.lax.bitcast_convert_type(
        jnp.take(a, perm, axis=-2), jnp.int32)


@jax.jit
def _stage_xs_keylanes(xs):
    """uint8 [M, nb] -> walk-order masks int32 [n, M, 1] (0 / -1)."""
    m, nb = xs.shape
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = ((xs[..., None] >> shifts) & jnp.uint8(1)).reshape(m, nb * 8)
    return (bits.T.astype(jnp.int32) * jnp.int32(-1))[:, :, None]


@partial(jax.jit, static_argnames=("b", "m_tile", "kw_tile", "level_chunk",
                                   "interpret"))
def _eval_staged(rk, s0_t, cw_s_t, cw_v_t, cw_tl, cw_tr, cw_np1_t, x_mask,
                 b: int, m_tile: int, kw_tile: int, level_chunk: int,
                 interpret: bool):
    return dcf_eval_keylanes_pallas(
        rk, s0_t, cw_s_t, cw_v_t, cw_tl, cw_tr, cw_np1_t, x_mask, b=b,
        m_tile=m_tile, kw_tile=kw_tile, level_chunk=level_chunk,
        interpret=interpret)


@jax.jit
def _relu_mismatch(y0, y1, beta_t, alphas, xs, valid):
    """Mismatch count for [128, M, Kw] bit-major shares vs the plain
    comparison: expected(k, m) = beta_k iff x_m < alpha_k else 0.  ``valid``
    [1, Kw] masks out padding key lanes (which may hold garbage shares)."""
    m, nb = xs.shape
    lt = jnp.zeros((m, alphas.shape[0]), jnp.bool_)
    eq = jnp.ones((m, alphas.shape[0]), jnp.bool_)
    for j in range(nb):  # lexicographic big-endian unsigned compare
        xj = xs[:, j][:, None]
        aj = alphas[None, :, j]
        lt = lt | (eq & (xj < aj))
        eq = eq & (xj == aj)
    ltb = lt.astype(jnp.uint32).reshape(m, -1, 32)
    ltw = jax.lax.bitcast_convert_type(
        jnp.sum(ltb << jnp.arange(32, dtype=jnp.uint32), axis=-1,
                dtype=jnp.uint32), jnp.int32)  # [M, Kw]
    expect = beta_t[:, None, :] & ltw[None, :, :]
    diff = jnp.bitwise_or.reduce(y0 ^ y1 ^ expect, axis=0) & valid  # [M, Kw]
    return jnp.sum(jax.lax.population_count(
        jax.lax.bitcast_convert_type(diff, jnp.uint32)).astype(jnp.int32))


class KeyLanesPallasBackend:
    """Many-keys DCF evaluator (keys in lanes) on the Pallas walk kernel.

    lam = 16 only (one AES block per seed).  Bundles carry both parties.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes],
                 m_tile: int = 8, kw_tile: int = 128,
                 level_chunk: int = 8, interpret: bool = False):
        if lam != 16:
            raise ValueError(  # api-edge: constructor lam contract
                f"KeyLanesPallasBackend supports lam=16 only (got {lam})")
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        self.lam = lam
        self.m_tile = m_tile
        self.kw_tile = kw_tile
        self.level_chunk = level_chunk
        self.interpret = interpret
        self.rk = jnp.asarray(round_key_masks_bitmajor(cipher_keys[used[0]]))
        self._perm = jnp.asarray(_PERM)
        self._bundle_dev = None
        self._num_keys = 0

    def _kw_pad(self, kw: int) -> int:
        """Zero-padding of the key-word axis required by the kernel tiling
        (sharded subclasses pad to whole per-shard granules instead)."""
        if kw > self.kw_tile and kw % self.kw_tile:
            return -kw % self.kw_tile
        return 0

    def _place_kw(self, arr):
        """Placement hook for one padded byte-major bundle array; sharded
        subclasses device_put the key-word axis across the mesh here, so
        the bit-major conversion below runs distributed and no chip holds
        the full image."""
        return arr

    def put_bundle_device(self, dev: dict) -> None:
        """Adopt a DeviceKeyGen bundle (byte-major planes, both parties);
        planes are reordered to the kernel's bit-major layout on device and
        the key-word axis is zero-padded to the kernel's kw_tile granule
        (pad lanes hold garbage shares; every consumer truncates or masks
        by num_keys)."""
        p = self._perm
        kw = dev["cw_s"].shape[-1]
        pad = self._kw_pad(kw)

        def padded(a):
            if pad:
                widths = [(0, 0)] * (a.ndim - 1) + [(0, pad)]
                a = (np.pad(a, widths) if isinstance(a, np.ndarray)
                     else jnp.pad(a, widths))
            return self._place_kw(a)

        self._num_keys = dev["num_keys"]
        self._bundle_dev = dict(
            s0=tuple(_to_bitmajor_planes(padded(s), p) for s in dev["s0"]),
            cw_s=_to_bitmajor_planes(padded(dev["cw_s"]), p),
            cw_v=_to_bitmajor_planes(padded(dev["cw_v"]), p),
            cw_tl=jax.lax.bitcast_convert_type(
                padded(dev["cw_tl"]), jnp.int32),
            cw_tr=jax.lax.bitcast_convert_type(
                padded(dev["cw_tr"]), jnp.int32),
            cw_np1=_to_bitmajor_planes(padded(dev["cw_np1"]), p),
        )

    def put_bundle(self, bundle: KeyBundle) -> None:
        """Host-bundle path (tests / interop): pack a full two-party
        KeyBundle into the device layout."""
        if bundle.lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        if bundle.group != "xor":
            # api-edge: documented group contract — the key-lanes kernel
            # packs 32 KEYS per lane word, so a per-key additive carry
            # would ripple across the packed key axis; additive bundles
            # route to the point-lane backends instead.
            raise ShapeError(
                f"KeyLanesPallasBackend is XOR-only; bundle has group "
                f"{bundle.group!r} (use the pallas/bitsliced/prefix "
                f"point-lane backends for additive groups)")
        if bundle.s0s.shape[1] != 2:
            raise ShapeError(
                "KeyLanesPallasBackend wants the full two-party bundle")
        k = bundle.num_keys
        k_pad = (k + 31) // 32 * 32

        def pad_keys(a):
            return np.pad(a, [(0, k_pad - k)] + [(0, 0)] * (a.ndim - 1))

        # Stays numpy until put_bundle_device's placement hook, so sharded
        # subclasses can split the host image straight to the shards.
        def planes(a):  # [K, ..., lam] -> uint32 [..., 8lam, Wk]
            bits = byte_bits_lsb(pad_keys(a))  # [K, ..., 8lam]
            return pack_lanes(
                np.ascontiguousarray(np.moveaxis(bits, 0, -1)))

        def packed_bits(a):  # [K, n] -> uint32 [n, Wk]
            return pack_lanes(np.ascontiguousarray(pad_keys(a).T))

        self.put_bundle_device(dict(
            s0=(planes(bundle.s0s[:, 0]), planes(bundle.s0s[:, 1])),
            cw_s=planes(bundle.cw_s),
            cw_v=planes(bundle.cw_v),
            cw_tl=packed_bits(bundle.cw_t[:, :, 0]),
            cw_tr=packed_bits(bundle.cw_t[:, :, 1]),
            cw_np1=planes(bundle.cw_np1),
            num_keys=k,
        ))

    def _m_granule(self) -> int:
        """Point-count granule (per-shard tile granule when sharded)."""
        return self.m_tile

    def _stage_mask(self, xs: np.ndarray) -> jax.Array:
        """xs -> walk-order masks; the hook sharded subclasses override to
        place the mask across the mesh's point axis."""
        return _stage_xs_keylanes(jnp.asarray(xs))

    def stage(self, xs: np.ndarray) -> dict:
        """Shared points uint8 [M, nb] -> staged walk masks (M padded to a
        multiple of the point granule; pad points evaluated and
        discarded)."""
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        if xs.ndim != 2:
            raise ShapeError("keylanes backends need shared points [M, nb]")
        n = self._bundle_dev["cw_s"].shape[0]
        if xs.shape[1] * 8 != n:
            raise ShapeError("xs width mismatch with bundle")
        m = xs.shape[0]
        gran = self._m_granule()
        m_pad = -(-m // gran) * gran
        if m_pad != m:
            xs = np.pad(xs, [(0, m_pad - m), (0, 0)])
        return {"x_mask": self._stage_mask(np.ascontiguousarray(xs)),
                "m": m}

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        """Party ``b`` eval; returns DEVICE y planes int32 [128, M_pad, Kw]
        (bit-major).  Force completion with a fetch."""
        dev = self._bundle_dev
        return _eval_staged(
            self.rk, dev["s0"][b], dev["cw_s"], dev["cw_v"], dev["cw_tl"],
            dev["cw_tr"], dev["cw_np1"], staged["x_mask"], b=int(b),
            m_tile=self.m_tile, kw_tile=self.kw_tile,
            level_chunk=self.level_chunk, interpret=self.interpret)

    def staged_to_bytes(self, y_planes: jax.Array, m: int) -> np.ndarray:
        """int32 [128, M_pad, Kw] -> uint8 [K, M, lam] on host."""
        y = np.asarray(y_planes).view(np.uint32)[_INV_PERM]  # byte-major
        bits = unpack_lanes(y)  # [8lam, M_pad, K_pad]
        bits = np.moveaxis(bits, -1, 0).transpose(0, 2, 1)  # [K, M, 8lam]
        return bits_lsb_to_bytes(bits[: self._num_keys, :m])

    def eval(self, b: int, xs: np.ndarray,
             bundle: KeyBundle | None = None) -> np.ndarray:
        """Convenience bytes-out path: uint8 [K, M, lam]."""
        if bundle is not None:
            self.put_bundle(bundle)
        staged = self.stage(xs)
        return self.staged_to_bytes(self.eval_staged(b, staged), staged["m"])

    def relu_mismatch_count(self, y0, y1, alphas: np.ndarray,
                            betas: np.ndarray, xs: np.ndarray) -> jax.Array:
        """Config-5 device verification: count (key, point) pairs where the
        XOR reconstruction differs from `beta_k if x_m < alpha_k else 0`.
        Padding key lanes (from the 32-key word granule or the kw_tile
        granule) are masked out of the count, so both DeviceKeyGen and
        host-packed bundles verify correctly.  Pad points use real evaluated
        shares compared against their own expected value.  Returns a DEVICE
        scalar.
        """
        k = alphas.shape[0]
        if k != self._num_keys:
            raise ShapeError(
                f"got {k} alphas for a bundle of {self._num_keys} keys")
        k_pad = y0.shape[-1] * 32
        m_pad = y0.shape[1]
        alphas_p = np.pad(alphas, [(0, k_pad - k), (0, 0)])
        xs_p = np.pad(xs, [(0, m_pad - xs.shape[0]), (0, 0)])
        betas_p = np.pad(betas, [(0, k_pad - k), (0, 0)])
        beta_t = _to_bitmajor_planes(
            jnp.asarray(pack_lanes(np.ascontiguousarray(
                byte_bits_lsb(betas_p).T))), self._perm)
        valid = jnp.asarray(pack_lanes(
            (np.arange(k_pad) < k).astype(np.uint8)[None]
        ).view(np.int32))  # [1, Kw]
        return _relu_mismatch(
            y0, y1, beta_t, jnp.asarray(alphas_p), jnp.asarray(xs_p), valid)
