"""Tree (breadth-first) full-domain evaluation — the fast config-3 path.

``full_domain_check_device`` (workloads.py) walks every point's full
n-level path; this backend expands the GGM tree once instead: the host
numpy oracle expands the tiny irregular top (levels 0..k0, 2^k0 nodes),
ships the ~2^k0 * 33 B frontier to the device, and the Pallas expand
kernel (ops.pallas_tree) doubles the node arrays level by level until the
leaves.  Total PRG work drops from n * 2^n to ~2^{n+1} — at n=24 that is
~12x — and every level is one huge batched bitsliced AES call, exactly
what the VPU wants.

Leaves come out in bitreverse_n order (each level stacks
[left-children; right-children]); verification computes each position's
domain value arithmetically, so nothing is ever gathered back to natural
order.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes_bitsliced import round_key_masks_bitmajor
from dcf_tpu.ops.pallas_tree import tree_expand_device
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.spec import hirose_used_cipher_indices
from dcf_tpu.utils.bits import (
    bitmajor_perm,
    bitmajor_plane_masks,
    byte_bits_lsb,
    pack_lanes,
)

__all__ = ["TreeFullDomain", "tree_expand_np"]

_PERM = bitmajor_perm(16)


def tree_expand_np(prg: HirosePrgNp, bundle: KeyBundle, b: int,
                   levels: int):
    """Host breadth-first expansion of one party's key to ``levels`` deep.

    Returns (s [N, lam], v [N, lam], t [N]) with N = 2^levels in
    bitreverse order (position = Σ dir_i 2^i over the MSB-first walk
    directions).  Doubles as the oracle the device kernel is tested
    against.

    For additive bundle groups the pushed-down value accumulator is the
    UNSIGNED per-lane sum (the party sign factors out of the whole walk;
    consumers apply it once at their output edge).
    """
    from dcf_tpu.utils.groups import lanes_of, bytes_of

    lam = bundle.lam
    group = bundle.group
    s = bundle.s0s[:1, 0, :].copy()  # single key
    t = np.array([b], dtype=np.uint8)
    v = np.zeros((1, lam), dtype=np.uint8)
    for i in range(levels):
        p = prg.gen(s)
        cs = bundle.cw_s[0, i]
        cv = bundle.cw_v[0, i]
        ctl, ctr = bundle.cw_t[0, i]
        tc = t[:, None]
        s_l = p.s_l ^ cs * tc
        s_r = p.s_r ^ cs * tc
        if group == "xor":
            v_l = v ^ p.v_l ^ cv * tc
            v_r = v ^ p.v_r ^ cv * tc
        else:
            lv = lanes_of(v, group)
            cvg = lanes_of(np.ascontiguousarray(cv[None, :]), group) \
                * tc.astype(lanes_of(v, group).dtype)
            v_l = bytes_of(lv + lanes_of(p.v_l, group) + cvg, group)
            v_r = bytes_of(lv + lanes_of(p.v_r, group) + cvg, group)
        t_l = p.t_l ^ (t & ctl)
        t_r = p.t_r ^ (t & ctr)
        s = np.concatenate([s_l, s_r])
        v = np.concatenate([v_l, v_r])
        t = np.concatenate([t_l, t_r])
    return s, v, t


def _finalize_np(bundle: KeyBundle, s, v, t):
    """Leaf shares from a host expansion at full depth."""
    return v ^ s ^ bundle.cw_np1[0] * t[:, None]


def leaf_mismatch_count(y0, y1, beta_mask, inside):
    """Count leaves whose XOR reconstruction differs from the expected
    ``beta if inside else 0``.  y0/y1: leaf-share planes [128, W];
    beta_mask: [128, 1]; inside: bool [32*W] per-leaf expectation.
    Shared by the unsharded and mesh-sharded verifiers so the counting
    contract cannot diverge between them."""
    bits = inside.astype(jnp.uint32).reshape(-1, 32)
    ltw = jax.lax.bitcast_convert_type(
        jnp.sum(bits << jnp.arange(32, dtype=jnp.uint32), axis=-1,
                dtype=jnp.uint32), jnp.int32)[None, :]  # [1, W]
    diff = jnp.bitwise_or.reduce(y0 ^ y1 ^ (beta_mask & ltw), axis=0)
    return jnp.sum(jax.lax.population_count(
        jax.lax.bitcast_convert_type(diff, jnp.uint32)).astype(jnp.int32))


@partial(jax.jit, static_argnames=("n", "gt"))
def _tree_mismatch(y0, y1, beta_mask, alpha, n: int, *, gt: bool):
    """Mismatching-leaf count for bitrev-order y planes [128, 2^n / 32]."""
    m = 32 * y0.shape[1]
    pos = jnp.arange(m, dtype=jnp.uint32)
    value = jnp.zeros(m, dtype=jnp.uint32)
    for k in range(n):  # domain value = bitreverse_n(position)
        value = value | (((pos >> k) & 1) << (n - 1 - k))
    inside = (value > alpha) if gt else (value < alpha)
    return leaf_mismatch_count(y0, y1, beta_mask, inside)


class TreeFullDomain:
    """Full-domain evaluator/verifier on the tree expand kernel (lam=16)."""

    def __init__(self, lam: int, cipher_keys: Sequence[bytes],
                 host_levels: int = 6, interpret: bool = False):
        if lam != 16:
            # api-edge: constructor lam contract
            raise ValueError(f"TreeFullDomain supports lam=16 only, "
                             f"got {lam}")
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        self.lam = lam
        self.host_levels = host_levels
        self.interpret = interpret
        self.rk = jnp.asarray(round_key_masks_bitmajor(cipher_keys[used[0]]))
        self._prg = HirosePrgNp(lam, cipher_keys)
        # Ship-once cache for repeated checks of the SAME bundle (the
        # bench pattern): (bundle, n_bits, staged_cw, {party: frontier}).
        # Keyed on the caller's object by IDENTITY and RETAINING it, so a
        # freed bundle's address being reused cannot false-hit.
        self._cache = None

    def _stage_cw(self, bundle: KeyBundle):
        """Ship the (party-independent) correction words once per check."""
        def masks(a):  # uint8 [..., lam] -> int32 [..., 128, 1]
            return jnp.asarray(bitmajor_plane_masks(a)[..., None])

        return (masks(bundle.cw_s[0]), masks(bundle.cw_v[0]),
                jnp.asarray(bundle.cw_t[0].astype(np.int32) * -1),
                masks(bundle.cw_np1[0]))

    def _frontier(self, bundle: KeyBundle, b: int, k0: int):
        """Host-expand to level k0 and pack to device plane layout."""
        s, v, t = tree_expand_np(self._prg, bundle, b, k0)

        def planes(a):  # [N, lam] -> int32 [128, N/32]
            bits = byte_bits_lsb(a)[:, _PERM]
            return jnp.asarray(pack_lanes(
                np.ascontiguousarray(bits.T)).view(np.int32))

        t_m = jnp.asarray(pack_lanes(t[None, :]).view(np.int32))
        return planes(s), planes(v), t_m

    def eval_party(self, b: int, bundle: KeyBundle, n_bits: int,
                   staged_cw=None, frontier=None):
        """Party ``b`` full-domain leaf shares: DEVICE int32 planes
        [128, 2^n_bits / 32], bitreverse order.  ``bundle`` must be
        party-restricted (``for_party(b)``).  ``staged_cw``/``frontier``
        reuse prior ``_stage_cw``/``_frontier`` results (the CW image is
        party-independent; the frontier is per party)."""
        if bundle.n_bits != n_bits:
            raise ShapeError("bundle depth mismatch")
        if bundle.group != "xor":
            # api-edge: documented group contract — the device finalize
            # (tree_expand_device) and the mismatch verifiers reconstruct
            # by XOR; additive full-domain shares come from tree_expand_np
            # / tree_expand_raw, which DO carry the group.
            raise ShapeError(
                f"TreeFullDomain finalize is XOR-only; bundle has group "
                f"{bundle.group!r}")
        if bundle.s0s.shape[1] != 1:
            raise ShapeError("eval_party wants a party-restricted bundle")
        k0 = min(self.host_levels, n_bits)
        if k0 < 5:
            # api-edge: constructor host_levels contract
            raise ValueError("need at least 5 host levels (one lane word)")
        cw_s_t, cw_v_t, cw_t_pm, cw_np1_t = (
            staged_cw if staged_cw is not None else self._stage_cw(bundle))
        s, v, t = (frontier if frontier is not None
                   else self._frontier(bundle, b, k0))
        return tree_expand_device(
            self.rk, cw_s_t, cw_v_t, cw_t_pm, cw_np1_t, s, v, t,
            k0=k0, n=n_bits, interpret=self.interpret)

    def _staged_for(self, bundle: KeyBundle, n_bits: int):
        """Staged CW image + both parties' frontiers for ``bundle``,
        shipped to the device ONCE and reused while the caller keeps
        checking the same bundle object (repeated checks previously paid
        ~1-2 tunnel round-trips of h2d staging EACH — the dominant cost of
        the full_domain tree bench whenever the dev tunnel degrades)."""
        if bundle.group != "xor":
            # api-edge: same XOR-only finalize contract as eval_party
            # (the sharded subclass funnels through here too).
            raise ShapeError(
                f"TreeFullDomain finalize is XOR-only; bundle has group "
                f"{bundle.group!r}")
        c = self._cache
        if c is not None and c[0] is bundle and c[1] == n_bits:
            return c[2], c[3], c[4]
        k0 = min(self.host_levels, n_bits)
        staged_cw = self._stage_cw(bundle)
        parts = {b: bundle.for_party(b) for b in (0, 1)}
        fronts = {b: self._frontier(parts[b], b, k0) for b in (0, 1)}
        self._cache = (bundle, n_bits, staged_cw, fronts, parts)
        return staged_cw, fronts, parts

    def check_device(self, bundle: KeyBundle, alpha: int, beta: bytes,
                     n_bits: int, gt: bool = False) -> jax.Array:
        """Two-party full-domain reconstruction vs the plain comparison,
        entirely on device; returns the mismatching-leaf count as a DEVICE
        scalar (repeated checks can accumulate without a host round-trip
        each).  ``bundle`` is the full two-party bundle; its staged image
        ships once across repeated checks (see ``_staged_for``)."""
        staged_cw, fronts, parts = self._staged_for(bundle, n_bits)
        y0 = self.eval_party(0, parts[0], n_bits, staged_cw, fronts[0])
        y1 = self.eval_party(1, parts[1], n_bits, staged_cw, fronts[1])
        beta_mask = jnp.asarray(bitmajor_plane_masks(
            np.frombuffer(beta, dtype=np.uint8))[:, None])
        return _tree_mismatch(
            y0, y1, beta_mask, jnp.uint32(alpha), n=n_bits, gt=gt)

    def check(self, bundle: KeyBundle, alpha: int, beta: bytes,
              n_bits: int, gt: bool = False) -> int:
        return int(self.check_device(bundle, alpha, beta, n_bits, gt))
