"""Hybrid large-lambda evaluator: narrow walk + GF(2) affine wide part.

For lam >= 48 the Hirose PRG's truncated encryption loop
(reference src/prg.rs:48-56, the zip quirk) means every 16-byte block
beyond the first two is a structural COPY of the seed / its complement:
no AES ever touches it.  Consequently the walk state beyond byte 32
evolves affinely in the per-level control bits — the input x enters only
through the t-bit trajectory:

    s_{i+1}[wide] = mask(s_i[wide]) ^ t_i * cw_s_i[wide]
    v      [wide]+= mask(~s_i[wide]) ^ t_i * cw_v_i[wide]   (dir-independent!)

(v-hat's wide blocks are identical for both children because both get the
seed_p feed-forward, src/prg.rs:57-62; mask clears the global 8*lam-1 bit,
a linear map.)  So

    y[32:] = const_b ^ XOR_k t_k * W[k]          -- a GF(2) matrix product

with t_0 = b and t_n gating cw_np1.  The full evaluation becomes:

  1. a NARROW 32-byte walk — bit-identical to lam=32 (same cipher indices
     0/17, same Hirose wiring) minus the final-bit masking (the big PRG's
     masked byte is wide) — which yields y[:32] and the t trajectory;
  2. an (n+1) x 8*(lam-32) GF(2) matmul, computed on the MXU as an int8
     dot with parity extraction.

Per point this replaces n * lam bytes of plane algebra with a ~lam=32
walk plus a matmul — the regime where the plane-materializing paths lost
to the CPU (benchmarks/RESULTS_r02.jsonl, dcf_large_lambda).

The affine matrix is derived by basis probing (run the wide recursion on
unit t-vectors), so no hand-derived coefficient formula can rot.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.errors import ShapeError, StaleStateError
from dcf_tpu.backends.frontier import FrontierConsumerMixin
from dcf_tpu.backends.jax_bitsliced import (
    _pack_lanes_dev,
    _planes_to_bytes_dev,
    _xs_to_mask_dev,
    prg_planes,
)
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes_bitsliced import round_key_masks
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.spec import hirose_used_cipher_indices
from dcf_tpu.utils.bits import byte_bits_lsb

__all__ = ["LargeLambdaBackend", "wide_affine_np", "wide_affine_batch_np",
           "narrow_walk_np", "hybrid_prefix_gather_walk",
           "HYBRID_MAX_PREFIX_LEVELS"]

NARROW = 32  # bytes covered by the real (encrypted) blocks

# The hybrid frontier row is 16 int32 columns (sa|sb|va|vb) = 64 B — the
# measured XLA row gather is data-bound at 32 B and cliffs 4x at the
# 128 MB table (micro_gather.py: 2^22 x 32 B rows), so 64 B rows hit the
# same byte budget one level earlier than the lam=16 frontier's 21.
HYBRID_MAX_PREFIX_LEVELS = 20


def _clear_masked(a: np.ndarray, lam: int) -> np.ndarray:
    """Clear the global 8*lam-1 bit if it lies in this wide slice
    (byte lam-1, i.e. wide index lam-1-NARROW; it always does for
    lam > NARROW)."""
    a = a.copy()
    a[..., lam - 1 - NARROW] &= np.uint8(0xFE)
    return a


def wide_affine_batch_np(bundle: KeyBundle):
    """Affine decomposition of the wide output, batched over keys.

    bundle: party-restricted, lam > 32, K keys.  Returns
    (const [K, lam-32], w [K, n+1, lam-32]) uint8 such that per key
    y[32:] = const ^ XOR_k t_k * w[k], where t_k is the control bit
    GATING level k (t_0 = the party bit) and t_n the final bit gating
    cw_np1.  Only the matrix ``w`` is party-independent (it is built
    purely from the shared correction words); ``const`` depends on this
    party's wide seed s0, so it must be recomputed per party-restricted
    bundle — do NOT cache (const, w) across parties.  Derived by running
    the wide recursion on the zero trajectory and the n+1 unit
    trajectories at once.
    """
    lam, n, k_num = bundle.lam, bundle.n_bits, bundle.num_keys
    if lam <= NARROW:
        # api-edge: constructor lam contract
        raise ValueError("wide part needs lam > 32")
    wd = lam - NARROW
    s0w = bundle.s0s[:, 0, NARROW:]       # [K, Wd]
    cw_s_w = bundle.cw_s[:, :, NARROW:]   # [K, n, Wd]
    cw_v_w = bundle.cw_v[:, :, NARROW:]
    np1w = bundle.cw_np1[:, NARROW:]      # [K, Wd]

    nb = n + 2  # basis: [zero, e_0 .. e_n]
    t_basis = np.zeros((nb, n + 1), dtype=np.uint8)
    t_basis[1:] = np.eye(n + 1, dtype=np.uint8)
    s = np.broadcast_to(s0w[:, None, :], (k_num, nb, wd)).copy()
    v = np.zeros((k_num, nb, wd), dtype=np.uint8)
    for i in range(n):
        gate = t_basis[:, i][None, :, None]
        v ^= _clear_masked(s ^ 0xFF, lam) ^ cw_v_w[:, i][:, None, :] * gate
        s = _clear_masked(s, lam) ^ cw_s_w[:, i][:, None, :] * gate
    y = v ^ s ^ np1w[:, None, :] * t_basis[:, n][None, :, None]
    const = y[:, 0]
    return const, y[:, 1:] ^ const[:, None, :]


def wide_affine_np(bundle: KeyBundle):
    """Single-key convenience wrapper of ``wide_affine_batch_np``:
    (const [lam-32], w [n+1, lam-32])."""
    const, w = wide_affine_batch_np(bundle)
    return const[0], w[0]


def narrow_walk_np(cipher_keys: Sequence[bytes], bundle: KeyBundle, b: int,
                   xs: np.ndarray):
    """Host oracle for the narrow walk: y32 [M, 32] and the t trajectory
    [M, n+1] (t[:, 0] = b; t[:, k] gates level k; t[:, n] gates cw_np1).

    bundle: party-restricted with FULL lam (sliced to 32 bytes here).
    """
    n = bundle.n_bits
    prg = HirosePrgNp(NARROW, cipher_keys, mask=False, warn=False)
    m = xs.shape[0]
    s = np.broadcast_to(bundle.s0s[0, 0, :NARROW], (m, NARROW)).copy()
    t = np.full(m, b, dtype=np.uint8)
    v = np.zeros((m, NARROW), dtype=np.uint8)
    traj = np.empty((m, n + 1), dtype=np.uint8)
    bits = np.unpackbits(xs, axis=1)  # MSB-first walk order
    for i in range(n):
        traj[:, i] = t
        p = prg.gen(s)
        cs = bundle.cw_s[0, i, :NARROW]
        cv = bundle.cw_v[0, i, :NARROW]
        ctl, ctr = bundle.cw_t[0, i]
        tc = t[:, None]
        xm = bits[:, i].astype(bool)
        v ^= np.where(xm[:, None], p.v_r, p.v_l) ^ cv * tc
        s = np.where(xm[:, None], p.s_r, p.s_l) ^ cs * tc
        t = np.where(xm, p.t_r, p.t_l) ^ (t & np.where(xm, ctr, ctl))
    traj[:, n] = t
    y32 = v ^ s ^ bundle.cw_np1[0, :NARROW] * t[:, None]
    return y32, traj


# ---------------------------------------------------------------------------
# Device path: narrow bitsliced walk with trajectory capture + MXU matmul.
# ---------------------------------------------------------------------------


def _narrow_core(rk_masks, s0_pl, cw_s_pl, cw_v_pl, cw_tl, cw_tr, cw_np1_pl,
                 x_mask, b: int):
    """eval_core_bitsliced at lam=32 with NO masking, also returning the
    packed t trajectory [n+1, K, W].

    Multi-key: s0_pl/cw_np1_pl [p, K], cw_s_pl/cw_v_pl [n, p, K],
    cw_tl/cw_tr [n, K], x_mask [n, 1, W] (shared points).
    """
    ones = jnp.uint32(0xFFFFFFFF)
    p = 8 * NARROW
    w = x_mask.shape[2]
    k_num = s0_pl.shape[1]

    s = jnp.broadcast_to(s0_pl[:, :, None], (p, k_num, 1)) ^ jnp.zeros(
        (p, k_num, w), jnp.uint32)
    t = jnp.full((k_num, w), ones if b else jnp.uint32(0), jnp.uint32)
    v = jnp.zeros((p, k_num, w), jnp.uint32)
    no_mask = jnp.full(p, ones, jnp.uint32)

    def body(carry, level):
        s, t, v = carry
        cs, cv, ctl, ctr, xm = level  # cs/cv [p, K], ctl/ctr [K], xm [1, W]
        s_l, v_l, t_l, s_r, v_r, t_r = prg_planes(
            rk_masks, no_mask, NARROW, s, ones)
        gate = t[None, :, :]
        s_l = s_l ^ (cs[:, :, None] & gate)
        s_r = s_r ^ (cs[:, :, None] & gate)
        t_l = t_l ^ (t & ctl[:, None])
        t_r = t_r ^ (t & ctr[:, None])
        xm_e = xm[None, :, :]
        v2 = v ^ (v_r & xm_e) ^ (v_l & (xm_e ^ ones)) ^ (cv[:, :, None] & gate)
        s2 = (s_r & xm_e) | (s_l & (xm_e ^ ones))
        t2 = (t_r & xm) | (t_l & (xm ^ ones))
        return (s2, t2, v2), t  # emit the GATE t of this level

    (s, t, v), traj = jax.lax.scan(
        body, (s, t, v), (cw_s_pl, cw_v_pl, cw_tl, cw_tr, x_mask))
    y = v ^ s ^ (cw_np1_pl[:, :, None] & t[None, :, :])
    traj = jnp.concatenate([traj, t[None]], axis=0)  # + final t [n+1, K, W]
    return y, traj


def _wide_tail(t_planes, wide_const, wide_w8, m: int, col_chunk: int):
    """Shared wide part, batched over keys: packed t-trajectory planes
    [n+1, K, W] -> uint8 wide bytes [K, M, lam-32] via the int8 MXU
    batched matmul + parity extraction.  wide_const [K, lam-32],
    wide_w8 int8 [K, n+1, 8*(lam-32)]."""
    nt, k_num, _w = t_planes.shape
    tb = (t_planes[..., None] >> jnp.arange(32, dtype=jnp.uint32)) \
        & jnp.uint32(1)
    # [n+1, K, W, 32] -> [K, M, n+1]
    t_bits = tb.reshape(nt, k_num, -1).transpose(1, 2, 0).astype(jnp.int8)
    cols = wide_w8.shape[2]
    outs = []
    for c0 in range(0, cols, col_chunk):
        w_c = jax.lax.dynamic_slice_in_dim(
            wide_w8, c0, min(col_chunk, cols - c0), 2)
        acc = jax.lax.dot_general(
            t_bits, w_c,
            dimension_numbers=(((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32)  # [K, M, cc]
        bits = (acc & 1).astype(jnp.uint8)
        by = bits.reshape(k_num, m, -1, 8)
        outs.append(jnp.sum(by << jnp.arange(8, dtype=jnp.uint8), axis=-1,
                            dtype=jnp.uint8))
    return jnp.concatenate(outs, axis=2) ^ wide_const[:, None, :]


@partial(jax.jit, static_argnames=("gt",))
def _points_mismatch_bytes(y0, y1, alpha_a, beta_a, xs, *, gt: bool):
    """Mismatch count vs the comparison function for byte-level staged
    outputs (the large-lambda regime, where plane layouts would be
    wasteful): y0/y1 uint8 [K, M_pad, lam]; alpha_a [K, nb];
    beta_a [K, lam]; xs uint8 [1, M_pad, nb] (shared points).  Padding
    points are genuine evaluations of x=0 and self-verify."""
    x = xs[0]
    m, nb = x.shape
    k_num = alpha_a.shape[0]
    inside = jnp.zeros((k_num, m), jnp.bool_)
    eq = jnp.ones((k_num, m), jnp.bool_)
    for j in range(nb):  # lexicographic big-endian unsigned compare
        xj = x[None, :, j]
        aj = alpha_a[:, j][:, None]
        inside = inside | (eq & ((xj > aj) if gt else (xj < aj)))
        eq = eq & (xj == aj)
    expect = jnp.where(inside[:, :, None], beta_a[:, None, :], jnp.uint8(0))
    recon = y0 ^ y1
    return jnp.sum(jnp.any(recon != expect, axis=2).astype(jnp.int32))


@partial(jax.jit, static_argnames=("b", "col_chunk"))
def _hybrid_eval(rk_masks, s0_pl, cw_s_pl, cw_v_pl, cw_tl, cw_tr, cw_np1_pl,
                 wide_const, wide_w8, xs, b: int, col_chunk: int):
    """Full device program (XLA narrow walk): uint8 [K, M, lam]."""
    x_mask = _xs_to_mask_dev(xs)
    y32_pl, traj = _narrow_core(
        rk_masks, s0_pl, cw_s_pl, cw_v_pl, cw_tl, cw_tr, cw_np1_pl,
        x_mask, b)
    y32 = _planes_to_bytes_dev(y32_pl, NARROW)  # [K, M, 32]
    m = y32.shape[1]
    y_wide = _wide_tail(traj, wide_const, wide_w8, m, col_chunk)
    return jnp.concatenate([y32, y_wide], axis=2)


def _y_blocks_to_bytes(y0, y1, inv_perm):
    """Narrow-kernel y blocks (bit-major [K, 128, W] each) -> uint8
    [K, M, 32]: inverse bit-major permutation per block, then the shared
    plane-to-byte conversion."""
    yb = jnp.concatenate([
        jnp.take(jax.lax.bitcast_convert_type(y0, jnp.uint32),
                 inv_perm, axis=1),
        jnp.take(jax.lax.bitcast_convert_type(y1, jnp.uint32),
                 inv_perm, axis=1),
    ], axis=1).transpose(1, 0, 2)  # byte-major planes [256, K, W]
    return _planes_to_bytes_dev(yb, NARROW)


@partial(jax.jit, static_argnames=("b", "col_chunk", "interpret"))
def _hybrid_eval_pallas(rk2, s0a, s0b, cs0, cs1, cv0, cv1, np1a, np1b,
                        cw_t_pm, inv_perm, wide_const, wide_w8, xs,
                        b: int, col_chunk: int, interpret: bool):
    """Full device program (Pallas narrow walk): uint8 [K, M, lam]."""
    from dcf_tpu.backends.pallas_backend import _stage_xs
    from dcf_tpu.ops.pallas_narrow import dcf_narrow_walk_pallas

    x_mask = _stage_xs(xs)
    y0, y1, traj = dcf_narrow_walk_pallas(
        rk2, s0a, s0b, cs0, cs1, cv0, cv1, np1a, np1b, cw_t_pm, x_mask,
        b=b, interpret=interpret)
    y32 = _y_blocks_to_bytes(y0, y1, inv_perm)  # [K, M, 32]
    m = y32.shape[1]
    # trajectory [K, n+1, W] -> [n+1, K, W]
    tr = jax.lax.bitcast_convert_type(traj, jnp.uint32).transpose(1, 0, 2)
    y_wide = _wide_tail(tr, wide_const, wide_w8, m, col_chunk)
    return jnp.concatenate([y32, y_wide], axis=2)


# ---------------------------------------------------------------------------
# Prefix-shared narrow walk (ops.pallas_hybrid_prefix): frontier staging
# and the gather + remaining-level walk + wide-tail device program.
# ---------------------------------------------------------------------------


def _node_prefix_xs(k: int, n_bytes: int) -> np.ndarray:
    """uint8 [2^k, n_bytes]: node r's input has MSB-first walk bit i =
    (r >> i) & 1 for i < k, zero beyond — the frontier-position
    enumeration matching ``ops.pallas_prefix._stage_prefix_idx``, so the
    depth-k carry of "point" r IS frontier row r."""
    r = np.arange(1 << k, dtype=np.uint32)
    bits = np.zeros((1 << k, 8 * n_bytes), dtype=np.uint8)
    for i in range(k):
        bits[:, i] = (r >> np.uint32(i)) & np.uint32(1)
    return np.bitwise_or.reduce(
        bits.reshape(-1, n_bytes, 8) << np.arange(7, -1, -1,
                                                  dtype=np.uint8),
        axis=-1).astype(np.uint8)


@jax.jit
def _traj_words(traj_planes):
    """Packed gate planes int32 [K, J, W] -> per-node uint32 words
    [K, 32*W] with bit j = plane j (J = k+1 <= 32: the k prefix gates
    plus the depth-k carry at bit k).  Runs once per (bundle, party) at
    frontier-build time — off the eval clock."""
    kk, j, w = traj_planes.shape
    bits = (jax.lax.bitcast_convert_type(traj_planes, jnp.uint32)[..., None]
            >> jnp.arange(32, dtype=jnp.uint32)) & jnp.uint32(1)
    bits = bits.reshape(kk, j, w * 32)  # node = 32*word + bit
    return jnp.sum(bits << jnp.arange(j, dtype=jnp.uint32)[None, :, None],
                   axis=1, dtype=jnp.uint32)


def _words_to_planes(words, shifts):
    """Per-point uint32 words [K, M] -> packed lane planes uint32
    [K, len(shifts), W], plane j selecting bit ``shifts[j]`` of each
    word (point 32*w + m in bit m — the kernel lane convention)."""
    kk, m = words.shape
    bits = (words[:, None, :] >> shifts[None, :, None]) & jnp.uint32(1)
    bits = bits.reshape(kk, shifts.shape[0], m // 32, 32)
    return jnp.sum(bits << jnp.arange(32, dtype=jnp.uint32), axis=-1,
                   dtype=jnp.uint32)


def hybrid_prefix_gather_walk(rk2, state_tbl, traj_tbl, idx, cs0r, cs1r,
                              cv0r, cv1r, np1a, np1b, cw_t_r, x_mask_rem,
                              inv_perm, wide_const, wide_w8, *,
                              col_chunk: int, k: int, frontier_size: int,
                              tile_words: int, interpret: bool):
    """Gather frontier rows + trajectory words, walk the remaining n-k
    narrow levels, run the wide tail over the FULL reassembled
    trajectory — unjitted so ``parallel.ShardedLargeLambdaBackend`` can
    wrap it in ``shard_map`` (the gather is a pure per-point map against
    the key-sharded frontier tables, so points shard with no
    collectives).  Party is implicit in the frontier tables.

    state_tbl int32 [K, 2^k, 16] (sa|sb|va|vb rows), traj_tbl uint32
    [K, 2^k], idx uint32 [M]; returns uint8 [K, M, lam]."""
    k_num = state_tbl.shape[0]
    m = idx.shape[0]
    if k_num == 1:
        flat = idx
    else:
        flat = (jnp.arange(k_num, dtype=jnp.uint32)[:, None]
                * jnp.uint32(frontier_size) + idx[None, :]).reshape(-1)
    rows = jnp.take(state_tbl.reshape(-1, 16), flat, axis=0).reshape(
        k_num, m, 16)
    # -> [K, 16, 32, W] with the j (point-within-word) axis reversed,
    # the layout the kernel's butterfly transpose expects (same relayout
    # as backends.pallas_prefix.gather_and_walk, 16 columns wide).
    blk = (rows.transpose(0, 2, 1).reshape(k_num, 16, m // 32, 32)
           .transpose(0, 1, 3, 2)[:, :, 31::-1, :])
    tw = jnp.take(traj_tbl.reshape(-1), flat, axis=0).reshape(k_num, m)
    t0 = jax.lax.bitcast_convert_type(
        _words_to_planes(tw, jnp.arange(k, k + 1, dtype=jnp.uint32)),
        jnp.int32)  # [K, 1, W] depth-k carry
    topk = _words_to_planes(tw, jnp.arange(k, dtype=jnp.uint32))

    from dcf_tpu.ops.pallas_hybrid_prefix import dcf_hybrid_prefix_pallas

    y0, y1, tr_rem = dcf_hybrid_prefix_pallas(
        rk2, blk, t0, cs0r, cs1r, cv0r, cv1r, np1a, np1b, cw_t_r,
        x_mask_rem, tile_words=tile_words, interpret=interpret)
    y32 = _y_blocks_to_bytes(y0, y1, inv_perm)  # [K, M, 32]
    # Full gate trajectory [n+1, K, W]: gathered top-k gates, then the
    # walked levels (whose first entry is the depth-k gate == bit k of
    # the gathered word, and whose last is the final cw_np1 gate).
    tr_full = jnp.concatenate(
        [topk.transpose(1, 0, 2),
         jax.lax.bitcast_convert_type(tr_rem, jnp.uint32)
         .transpose(1, 0, 2)], axis=0)
    y_wide = _wide_tail(tr_full, wide_const, wide_w8, m, col_chunk)
    return jnp.concatenate([y32, y_wide], axis=2)


_hybrid_prefix_eval = partial(
    jax.jit, static_argnames=("col_chunk", "k", "frontier_size",
                              "tile_words", "interpret"))(
    hybrid_prefix_gather_walk)


class LargeLambdaBackend(FrontierConsumerMixin):
    """Device evaluator for lam >= 48 via the narrow-walk + affine split.

    Multi-key: the narrow Pallas walk grids over keys and the GF(2)
    affine wide part runs as one batched int8 MXU matmul (per-chunk
    memory is bounded by scaling the column chunk down with K).
    Bit-exact with the full-width oracle (tests/test_large_lambda.py).

    ``prefix_levels`` > 0 switches the narrow walk to the prefix-shared
    path (ops.pallas_hybrid_prefix): the top k levels are expanded once
    per (bundle, party) as a 2^k-row gather table cached with the key
    image, each eval gathers every point's (sa, sb, va, vb, t,
    trajectory-prefix) carry and walks only n-k levels.  Requires the
    Pallas narrow path (``narrow="auto"`` then resolves to pallas; pass
    ``interpret=True`` off-TPU).
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes],
                 col_chunk: int = 1 << 15, narrow: str = "auto",
                 interpret: bool = False, prefix_levels: int = 0,
                 host_levels: int | None = None):
        if lam < 48 or lam % 16:
            raise ValueError(  # api-edge: constructor lam contract
                "LargeLambdaBackend wants lam >= 48 (a multiple of 16); "
                "use the pallas/bitsliced backends for small lam")
        if col_chunk % 8:
            raise ValueError(  # api-edge: constructor col_chunk contract
                f"col_chunk must be a multiple of 8 (byte packing), "
                f"got {col_chunk}")
        if host_levels is not None:
            # The lam=16 prefix backend splits its tree build host/device;
            # the hybrid frontier is built entirely on device, so the knob
            # does not exist here.  Rejected by name so a caller porting
            # PrefixPallasBackend opts does not silently configure nothing.
            # api-edge: constructor host_levels contract
            raise ValueError(
                "the hybrid prefix frontier is built on device; "
                "host_levels does not apply (use prefix_levels)")
        if prefix_levels and prefix_levels < 5:
            # api-edge: constructor prefix_levels contract
            raise ValueError(
                "prefix_levels must be 0 (from-root) or >= 5 (one lane "
                f"word of frontier), got {prefix_levels}")
        if narrow == "auto":
            if prefix_levels:
                narrow = "pallas"  # the frontier machinery is plane/kernel
            else:
                try:
                    import jax as _jax

                    narrow = ("pallas" if interpret
                              or _jax.devices()[0].platform == "tpu"
                              else "xla")
                except Exception:  # fallback-ok: no usable jax -> XLA narrow
                    narrow = "xla"
        if narrow not in ("pallas", "xla"):
            # api-edge: constructor narrow-path contract
            raise ValueError(f"narrow must be pallas/xla/auto, got {narrow}")
        if prefix_levels and narrow != "pallas":
            # api-edge: constructor prefix/narrow compatibility contract
            raise ValueError(
                "prefix_levels needs the Pallas narrow walk (the XLA "
                "layout stores keys on the trailing axis and has no "
                "frontier kernel); drop narrow='xla' or prefix_levels")
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        assert tuple(used) == (0, 17)
        self.lam = lam
        self.col_chunk = col_chunk
        self.narrow = narrow
        # Capability flag the serving registry reads (ISSUE 11): only
        # the single-device Pallas narrow path can stage a
        # device-resident keygen plane dict verbatim (the sharded
        # subclass re-places shards and overrides this to False; the
        # XLA narrow path stages its own plane order).
        self.accepts_dev_planes = narrow == "pallas"
        self.interpret = interpret
        self.prefix_levels = min(prefix_levels, HYBRID_MAX_PREFIX_LEVELS)
        self.rk_masks = tuple(
            jnp.asarray(round_key_masks(cipher_keys[i])) for i in used)
        if narrow == "pallas":
            from dcf_tpu.ops.aes_bitsliced import round_key_masks_bitmajor

            self.rk2 = jnp.asarray(np.concatenate(
                [round_key_masks_bitmajor(cipher_keys[i]) for i in used],
                axis=2))  # [15, 128, 2]
            from dcf_tpu.utils.bits import bitmajor_perm

            self._inv_perm = jnp.asarray(np.argsort(bitmajor_perm(16)))
        if self.prefix_levels:
            from dcf_tpu.backends.pallas_prefix import _PERM_I32

            self._perm_i32 = jnp.asarray(_PERM_I32)
        self.invalidate_frontier()
        self._dev = None

    def _k(self) -> int:
        """Effective prefix depth for the shipped bundle: leave at least
        8 walked levels; the gather cliff is on TOTAL stacked table
        BYTES (K * 2^k 64-byte rows vs the measured 128 MB break), so
        multi-key bundles shrink k by ceil(log2 K); floored at 5 (one
        lane word of frontier)."""
        k_num, n = self._bundle.num_keys, self._bundle.n_bits
        k_cap = HYBRID_MAX_PREFIX_LEVELS - (k_num - 1).bit_length()
        return max(min(self.prefix_levels, n - 8, k_cap), 5)

    def put_bundle(self, bundle: KeyBundle,
                   dev_planes: dict | None = None) -> None:
        """Ship this party's key image.  ``dev_planes`` (ISSUE 10,
        Pallas narrow path only): a device-resident staged plane dict
        straight from the on-device keygen
        (``ops.pallas_keygen.PallasKeyGen.staged_planes``) — the narrow
        image then stages without the host bit-plane expansion or a
        host->device transfer; only the wide affine tail still reads
        the host bundle's wide halves."""
        if bundle.lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        if bundle.group != "xor":
            # api-edge: documented group contract — the wide part is a
            # GF(2) affine decomposition of the payload (XOR-linear by
            # construction); an additive payload does not factor through
            # it.  Additive groups use lam=16 and the point-lane backends.
            raise ShapeError(
                f"LargeLambdaBackend is XOR-only; bundle has group "
                f"{bundle.group!r}")
        if bundle.s0s.shape[1] != 1:
            raise ShapeError(
                "LargeLambdaBackend wants a party-restricted bundle")
        if self.prefix_levels and bundle.n_bits < 13:
            raise ShapeError(
                f"domain of {bundle.n_bits} levels is too shallow for "
                "prefix sharing (needs >= 5 frontier + 8 walked levels); "
                "use prefix_levels=0")
        # Only the affine matrix w is party-independent; const depends on
        # this party's wide seed, so (const, w) are re-derived for every
        # put_bundle (staged lazily on first eval) and never reused across
        # parties.
        self._bundle = bundle
        self.invalidate_frontier()  # new key image, one hook (backends.frontier)

        if dev_planes is not None:
            if self.narrow != "pallas":
                raise ShapeError(
                    "dev_planes is the Pallas narrow staged layout; the "
                    "XLA narrow path stages its own plane order")
            want = (bundle.num_keys, bundle.n_bits, 128, 1)
            got = tuple(dev_planes["cs0"].shape)
            if got != want:
                raise ShapeError(
                    f"dev_planes geometry {got} does not match the "
                    f"bundle's {want} (keys, levels, planes, words)")
            self._dev = dict(dev_planes)
        elif self.narrow == "pallas":
            from dcf_tpu.utils.bits import bitmajor_plane_masks

            def blk(a, lo):  # bit-major plane masks for one 16-byte block
                return jnp.asarray(
                    bitmajor_plane_masks(a[..., lo:lo + 16])[..., None])

            self._dev = dict(
                s0a=blk(bundle.s0s[:, 0, :], 0),
                s0b=blk(bundle.s0s[:, 0, :], 16),
                cs0=blk(bundle.cw_s, 0),
                cs1=blk(bundle.cw_s, 16),
                cv0=blk(bundle.cw_v, 0),
                cv1=blk(bundle.cw_v, 16),
                np1a=blk(bundle.cw_np1, 0),
                np1b=blk(bundle.cw_np1, 16),
                cw_t=jnp.asarray(bundle.cw_t.astype(np.int32) * -1),
            )
        else:
            def masks(a):  # uint8 [..., 32] -> uint32 masks [..., 256]
                return (byte_bits_lsb(a).astype(np.uint32)
                        * np.uint32(0xFFFFFFFF))

            self._dev = dict(
                # [K, n, p] -> scan-major [n, p, K]
                cw_s=jnp.asarray(np.ascontiguousarray(
                    masks(bundle.cw_s[:, :, :NARROW]).transpose(1, 2, 0))),
                cw_v=jnp.asarray(np.ascontiguousarray(
                    masks(bundle.cw_v[:, :, :NARROW]).transpose(1, 2, 0))),
                cw_tl=jnp.asarray(np.ascontiguousarray(
                    bundle.cw_t[:, :, 0].T.astype(np.uint32)
                    * np.uint32(0xFFFFFFFF))),
                cw_tr=jnp.asarray(np.ascontiguousarray(
                    bundle.cw_t[:, :, 1].T.astype(np.uint32)
                    * np.uint32(0xFFFFFFFF))),
                cw_np1=jnp.asarray(np.ascontiguousarray(
                    masks(bundle.cw_np1[:, :NARROW]).T)),
                s0_pl=jnp.asarray(np.ascontiguousarray(
                    masks(bundle.s0s[:, 0, :NARROW]).T)),
            )
        if self.prefix_levels:
            self._slice_cw_rem()
        self._wide = None

    def _slice_cw_rem(self) -> None:
        """Remaining-level CW views are bundle constants: sliced once
        off the eval clock, not per eval_staged dispatch.  The sharded
        subclass re-runs this after placing ``_dev`` across the mesh."""
        k = self._k()
        dev = self._dev
        self._cw_rem = (dev["cs0"][:, k:], dev["cs1"][:, k:],
                        dev["cv0"][:, k:], dev["cv1"][:, k:],
                        dev["cw_t"][:, k:])

    def _narrow_dev_for_build(self) -> dict:
        """The narrow plane dict the frontier build walks.  The sharded
        subclass overrides this with its unsharded host-side copy (an
        eager pallas_call cannot consume mesh-sharded operands)."""
        return self._dev

    def _build_frontier_tables(self, b: int):
        """The party-b frontier: (state rows int32 [K, 2^k, 16], per-node
        trajectory words uint32 [K, 2^k]).  Built once per (bundle,
        party) by walking all 2^k node prefixes k levels on device
        (``ops.pallas_hybrid_prefix.narrow_state_walk_pallas``) and
        cached with the key image (instance store or the serve-resident
        frontier cache — ``backends.frontier``); key material, off the
        eval clock."""
        from dcf_tpu.backends.pallas_backend import _stage_xs
        from dcf_tpu.backends.pallas_prefix import _planes_to_rows
        from dcf_tpu.ops.pallas_hybrid_prefix import narrow_state_walk_pallas

        k = self._k()
        k_num = self._bundle.num_keys
        nb = self._bundle.n_bits // 8
        dev = self._narrow_dev_for_build()
        x_nodes = jnp.asarray(_node_prefix_xs(k, nb))[None]
        x_mask_nodes = _stage_xs(x_nodes)[:, :k]
        sa, sb, va, vb, traj = narrow_state_walk_pallas(
            self.rk2, dev["s0a"], dev["s0b"],
            dev["cs0"][:, :k], dev["cs1"][:, :k],
            dev["cv0"][:, :k], dev["cv1"][:, :k], dev["cw_t"][:, :k],
            x_mask_nodes, b=int(b), interpret=self.interpret)
        state_tbl = jnp.concatenate(
            [jnp.stack([_planes_to_rows(p[key], self._perm_i32)
                        for key in range(k_num)])
             for p in (sa, sb, va, vb)], axis=2)  # [K, 2^k, 16]
        return state_tbl, _traj_words(traj)

    def _wide_staged(self):
        if self._wide is None:
            const, w = wide_affine_batch_np(self._bundle)
            self._wide = (
                jnp.asarray(const),
                jnp.asarray(byte_bits_lsb(w).astype(np.int8)),
            )
        return self._wide

    def _col_chunk_for(self, k_num: int) -> int:
        """Scale the matmul column chunk down with K so the [K, M, chunk]
        int32 accumulator stays bounded."""
        return max(8, (self.col_chunk // max(1, k_num)) // 8 * 8)

    def stage(self, xs: np.ndarray) -> dict:
        """Ship xs (uint8 [M, n_bytes], padded mod 32 internally).  With
        ``prefix_levels`` the staged dict additionally carries the
        per-point frontier positions and the remaining-level walk masks
        — all xs-only preprocessing, untimed like the criterion setup."""
        if self._dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        if xs.ndim != 2:
            raise ShapeError("LargeLambdaBackend wants shared points [M, nb]")
        m = xs.shape[0]
        # Pallas narrow walk tiles 128 lane words per grid step; batches
        # beyond one tile pad to whole tiles (<= one tile stays exact).
        granule = 4096 if self.narrow == "pallas" and m > 4096 else 32
        m_pad = -(-m // granule) * granule
        if m_pad != m:
            xs = np.pad(xs, [(0, m_pad - m), (0, 0)])
        staged = {"xs": jnp.asarray(np.ascontiguousarray(xs))[None], "m": m}
        if self.prefix_levels:
            staged.update(
                self._prefix_stage_fields(staged["xs"],
                                          min(128, m_pad // 32)))
        return staged

    def _prefix_stage_fields(self, xj, wt: int) -> dict:
        """The prefix path's xs-only staged fields (per-point frontier
        positions, remaining-level masks, freshness geometry), shared
        with the sharded subclass (which re-places the arrays across its
        mesh).  ``xj``: padded device xs [1, M_pad, nb]."""
        if xj.shape[1] == 0:
            raise ShapeError("cannot stage an empty batch")
        from dcf_tpu.backends.pallas_backend import _stage_xs
        from dcf_tpu.backends.pallas_prefix import _stage_prefix_idx

        k = self._k()
        return dict(
            idx=_stage_prefix_idx(xj[0], k=k),
            x_mask_rem=_stage_xs(xj)[:, k:],
            k=k, n=8 * int(xj.shape[-1]), wt=wt)

    def _check_staged_fresh(self, staged: dict) -> None:
        """Reject a staged dict cut for a bundle geometry this backend no
        longer holds (the PR-1 freshness contract, same rule as
        ``PrefixPallasBackend``): idx and x_mask_rem are sliced at the
        prefix depth k of the bundle shipped at stage() time, so a
        put_bundle that moves ``_k()`` (key count shifts the cliff cap)
        or the domain depth would pair new CW slices with masks cut at
        the old k — at best an opaque Pallas shape error, at worst a
        silently-wrong share.  Same-geometry re-ships stay valid,
        including on the other party's backend instance."""
        if "idx" not in staged:
            # api-edge: documented staged-protocol contract (a dict from
            # a from-root hybrid backend's stage has no prefix indices)
            raise ValueError(
                "staged dict is not from a prefix-enabled hybrid "
                "backend's stage")
        k_now, n_now = self._k(), self._bundle.n_bits
        if staged.get("k") != k_now or staged.get("n") != n_now:
            raise StaleStateError(
                f"staged points are stale: staged at prefix depth "
                f"k={staged.get('k')} over an n={staged.get('n')}-level "
                f"domain, but the backend now holds a bundle with "
                f"k={k_now}, n={n_now}; re-stage the points after "
                "put_bundle")

    def eval_staged(self, b: int, staged: dict) -> jax.Array:
        """Party ``b`` eval; returns DEVICE uint8 [K, M_pad, lam]."""
        const, w8 = self._wide_staged()
        dev = self._dev
        cc = self._col_chunk_for(self._bundle.num_keys)
        if self.prefix_levels:
            self._check_staged_fresh(staged)
            state_tbl, traj_tbl = self._frontier_tables(b)
            cs0r, cs1r, cv0r, cv1r, cw_t_r = self._cw_rem
            return _hybrid_prefix_eval(
                self.rk2, state_tbl, traj_tbl, staged["idx"],
                cs0r, cs1r, cv0r, cv1r, dev["np1a"], dev["np1b"],
                cw_t_r, staged["x_mask_rem"], self._inv_perm, const, w8,
                col_chunk=cc, k=staged["k"],
                frontier_size=1 << staged["k"],
                tile_words=staged["wt"], interpret=self.interpret)
        if self.narrow == "pallas":
            return _hybrid_eval_pallas(
                self.rk2, dev["s0a"], dev["s0b"], dev["cs0"], dev["cs1"],
                dev["cv0"], dev["cv1"], dev["np1a"], dev["np1b"],
                dev["cw_t"], self._inv_perm, const, w8, staged["xs"],
                b=int(b), col_chunk=cc,
                interpret=self.interpret)
        return _hybrid_eval(
            self.rk_masks, dev["s0_pl"], dev["cw_s"], dev["cw_v"],
            dev["cw_tl"], dev["cw_tr"], dev["cw_np1"], const, w8,
            staged["xs"], b=int(b), col_chunk=cc)

    def staged_to_bytes(self, y: jax.Array, m: int) -> np.ndarray:
        return np.asarray(y[:, :m, :])

    def points_mismatch_count(self, y0, y1, alpha, beta,
                              staged: dict, gt: bool = False) -> jax.Array:
        """Full on-device two-party verification for the staged batch:
        count of (key, point) pairs whose XOR reconstruction differs from
        ``beta_k if x < alpha_k else 0`` (``>`` for gt).  y0/y1: both
        parties' ``eval_staged`` outputs over the SAME staged dict.
        alpha/beta: bytes (single key) or uint8 arrays [K, nb] / [K, lam].
        Returns a DEVICE int32 scalar."""
        def arr(v):
            if isinstance(v, (bytes, bytearray)):
                return np.frombuffer(v, dtype=np.uint8)[None]
            a = np.asarray(v, dtype=np.uint8)
            return a[None] if a.ndim == 1 else a

        alpha_a, beta_a = arr(alpha), arr(beta)
        if alpha_a.shape[0] != y0.shape[0] or beta_a.shape[0] != y0.shape[0]:
            raise ShapeError(
                f"alpha/beta key counts ({alpha_a.shape[0]}/"
                f"{beta_a.shape[0]}) must match the evaluated bundle's "
                f"{y0.shape[0]} keys")
        return _points_mismatch_bytes(
            y0, y1, jnp.asarray(alpha_a),
            jnp.asarray(beta_a), staged["xs"], gt=gt)

    # _full_device_parity capability flag: this counter takes [K, ...] keys.
    points_mismatch_multikey = True

    def eval(self, b: int, xs: np.ndarray,
             bundle: KeyBundle | None = None) -> np.ndarray:
        """uint8 [K, M, lam]; xs uint8 [M, n_bytes] shared points (padded
        mod 32 internally)."""
        if bundle is not None:
            self.put_bundle(bundle)
        staged = self.stage(xs)
        return self.staged_to_bytes(self.eval_staged(b, staged), staged["m"])
