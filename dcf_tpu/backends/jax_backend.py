"""JAX evaluation backend — the TPU hot path (single device).

Design (SURVEY.md §3.2, §7): the reference walks each point's GGM path with a
per-point Python-equivalent loop and rayon across points (src/lib.rs:163-204).
Here the n = 8*n_bytes levels become a ``lax.scan`` whose carry is only the
live walk state (s, t, v) for every (key, point) pair — O(lam) per pair, not
the reference's O(n*lam) retained path — and the per-level correction-word
application plus Hirose PRG run vectorized over the whole (K, M) batch on the
VPU.  Keys live in HBM as the KeyBundle SoA arrays, shipped once; per-level
slices are fed to the scan pre-transposed to level-major layout.

The same jitted function is what ``dcf_tpu.parallel`` shards over a device
mesh (keys/points axes over ICI), and ``__graft_entry__`` compile-checks.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.errors import ShapeError, StaleStateError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.aes import expand_key_np
from dcf_tpu.ops.aes_jax import aes256_encrypt_jax
from dcf_tpu.ops.group_accum import (group_width, jnp_bytes_to_lanes,
                                     jnp_lanes_to_bytes)
from dcf_tpu.spec import hirose_used_cipher_indices

__all__ = ["JaxBackend", "prg_gen_jax", "eval_core", "eval_scan"]


def prg_gen_jax(
    round_keys: Sequence[jnp.ndarray], lam: int, seeds: jnp.ndarray
):
    """Batched Hirose PRG on device (bit-exact with HirosePrgNp.gen).

    round_keys: one [15, 16] uint8 array per used cipher (index 17*k).
    seeds: uint8 [..., lam].  Returns (s_l, v_l, t_l, s_r, v_r, t_r).
    """
    seed_p = seeds ^ jnp.uint8(0xFF)
    batch = seeds.shape[:-1]
    n_enc = min(2, lam // 16)
    halves0 = []
    halves1 = []
    for k in range(n_enc):
        lo = 16 * k
        # Encrypt seed and seed^c blocks in one batched call (same cipher).
        both = aes256_encrypt_jax(
            round_keys[k],
            jnp.stack([seeds[..., lo : lo + 16], seed_p[..., lo : lo + 16]]),
        )
        halves0.append(both[0])
        halves1.append(both[1])

    def assemble(half_blocks, which):
        # Place encrypted block k at byte range [16k, 16k+16) of output half
        # `which`; all other bytes are zero (the truncated-loop quirk).
        out = jnp.zeros((*batch, lam), dtype=jnp.uint8)
        if which < n_enc:
            out = out.at[..., 16 * which : 16 * which + 16].set(half_blocks[which])
        return out

    buf0 = [assemble(halves0, 0), assemble(halves0, 1)]
    buf1 = [assemble(halves1, 0), assemble(halves1, 1)]
    buf0 = [b ^ seeds for b in buf0]
    buf1 = [b ^ seed_p for b in buf1]
    t_l = buf0[0][..., 0] & jnp.uint8(1)
    t_r = buf1[0][..., 0] & jnp.uint8(1)
    mask = jnp.full((lam,), 0xFF, dtype=jnp.uint8).at[lam - 1].set(0xFE)
    buf0 = [b & mask for b in buf0]
    buf1 = [b & mask for b in buf1]
    return buf0[0], buf1[0], t_l, buf0[1], buf1[1], t_r


def eval_core(
    round_keys: tuple[jnp.ndarray, ...],
    s0: jnp.ndarray,  # uint8 [K, lam]
    cw_s: jnp.ndarray,  # uint8 [n, K, lam]  (level-major)
    cw_v: jnp.ndarray,  # uint8 [n, K, lam]
    cw_t: jnp.ndarray,  # uint8 [n, K, 2]
    cw_np1: jnp.ndarray,  # uint8 [K, lam]
    xs: jnp.ndarray,  # uint8 [K, M, n_bytes] or [M, n_bytes] (shared by keys)
    b: int,
    lam: int,
    prg_fn=prg_gen_jax,
    group: str = "xor",
) -> jnp.ndarray:
    """Evaluate party ``b`` on all (key, point) pairs -> uint8 [K, M, lam].

    ``group`` picks the value accumulation: XOR, or the additive group's
    per-lane mod-2^w add (little-endian lanes over the payload bytes).
    Additive shares come out signed — the party sign ``(-1)^b`` factors
    out of the level loop, so the walk accumulates unsigned lanes and
    party 1 negates once at the end.

    Unjitted core so ``dcf_tpu.parallel`` can wrap it in ``shard_map``; use
    ``eval_scan`` (the jitted wrapper) for single-device calls.  A 2D ``xs``
    is broadcast across keys on device (free in XLA — avoids materializing K
    copies on the host).

    ``prg_fn`` is the Prg seam (reference ``trait Prg``, src/lib.rs:52-58):
    any ``(round_keys, lam, seeds) -> (s_l, v_l, t_l, s_r, v_r, t_r)``
    satisfying the protocol in ``dcf_tpu.ops.prg`` — the walk itself is
    generic over the construction (tests wire a non-cryptographic mock
    through here to prove it).
    """
    k_num = s0.shape[0]
    if xs.ndim == 2:
        xs = jnp.broadcast_to(xs[None], (k_num, *xs.shape))
    m = xs.shape[1]
    n = cw_s.shape[0]
    # MSB-first bit planes computed on device: [K, M, n_bytes, 8] -> [n, K, M].
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    x_bits = ((xs[..., None] >> shifts) & jnp.uint8(1)).reshape(k_num, m, n)
    x_bits = jnp.moveaxis(x_bits, -1, 0)

    w = group_width(group)  # 0 for xor
    s = jnp.broadcast_to(s0[:, None, :], (k_num, m, lam)).astype(jnp.uint8)
    t = jnp.full((k_num, m), b, dtype=jnp.uint8)
    if w:
        v = jnp.zeros((k_num, m, 8 * lam // w),
                      dtype=jnp_bytes_to_lanes(s, w).dtype)
    else:
        v = jnp.zeros((k_num, m, lam), dtype=jnp.uint8)

    def body(carry, level):
        s, t, v = carry
        cw_s_i, cw_v_i, cw_t_i, xbit = level
        s_l, v_l, t_l, s_r, v_r, t_r = prg_fn(round_keys, lam, s)
        t_mask = t[..., None]
        cs = cw_s_i[:, None, :] * t_mask  # [K,1,lam] gated per (key,point)
        s_l = s_l ^ cs
        s_r = s_r ^ cs
        t_l = t_l ^ (t & cw_t_i[:, None, 0])
        t_r = t_r ^ (t & cw_t_i[:, None, 1])
        xb = xbit[..., None].astype(bool)
        v_hat = jnp.where(xb, v_r, v_l)
        if w:
            v = v + jnp_bytes_to_lanes(v_hat, w) \
                + jnp_bytes_to_lanes(cw_v_i, w)[:, None, :] \
                * t_mask.astype(v.dtype)
        else:
            v = v ^ v_hat ^ cw_v_i[:, None, :] * t_mask
        s = jnp.where(xb, s_r, s_l)
        t = jnp.where(xbit.astype(bool), t_r, t_l)
        return (s, t, v), None

    (s, t, v), _ = jax.lax.scan(body, (s, t, v), (cw_s, cw_v, cw_t, x_bits))
    if not w:
        return v ^ s ^ cw_np1[:, None, :] * t[..., None]
    v = v + jnp_bytes_to_lanes(s, w) \
        + jnp_bytes_to_lanes(cw_np1, w)[:, None, :] \
        * t[..., None].astype(v.dtype)
    if b:
        v = -v
    return jnp_lanes_to_bytes(v, w)


eval_scan = partial(
    jax.jit, static_argnames=("b", "lam", "prg_fn", "group"))(eval_core)


class JaxBackend:
    """Device-resident DCF evaluator.

    Holds the expanded cipher round keys and (optionally) a key bundle on
    device so repeated evals pay the host->HBM key transfer once.
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes], prg_fn=None):
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        self.lam = lam
        self.round_keys = tuple(
            jnp.asarray(expand_key_np(cipher_keys[i])) for i in used
        )
        # The Prg seam: default Hirose/AES-256; any callable satisfying the
        # dcf_tpu.ops.prg protocol swaps the construction without touching
        # the walk (must be a stable module-level function — it is a jit
        # static argument).
        self.prg_fn = prg_fn or prg_gen_jax
        self._bundle_dev = None
        self._group = "xor"

    def put_bundle(self, bundle: KeyBundle) -> None:
        """Ship a (party-restricted) key bundle to device, level-major."""
        if bundle.lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        self._bundle_dev = {
            k: jnp.asarray(v) for k, v in bundle.level_major().items()
        }
        self._group = bundle.group

    def eval(self, b: int, xs: np.ndarray, bundle: KeyBundle | None = None) -> np.ndarray:
        """Evaluate party ``b``; xs uint8 [M, n_bytes] or [K, M, n_bytes].

        Returns uint8 [K, M, lam].  Uses the bundle shipped via
        ``put_bundle`` unless one is passed explicitly.
        """
        if bundle is not None:
            self.put_bundle(bundle)
        if self._bundle_dev is None:
            raise StaleStateError("no key bundle on device; call put_bundle first")
        dev = self._bundle_dev
        y = eval_scan(
            self.round_keys,
            dev["s0"],
            dev["cw_s"],
            dev["cw_v"],
            dev["cw_t"],
            dev["cw_np1"],
            jnp.asarray(xs),
            b=int(b),
            lam=self.lam,
            prg_fn=self.prg_fn,
            group=self._group,
        )
        return np.asarray(y)
