"""Evaluation backends.

All backends compute the same function bit-for-bit — the per-party DCF
evaluation y_b = Eval(b, k, x) (reference src/lib.rs:163-204) — over batches:

- ``numpy_backend`` — vectorized host oracle (the layout blueprint)
- ``native`` (dcf_tpu.native) — C++ host core, serial + threaded
- ``jax_backend`` — lax.scan/vmap TPU path (single chip)
- ``dcf_tpu.parallel`` — the JAX path sharded over a device mesh
"""

from dcf_tpu.backends.numpy_backend import eval_batch_np  # noqa: F401
