"""Vectorized numpy evaluation backend (host oracle).

Evaluates K keys x M points in one level-synchronous sweep: where the
reference walks each point's GGM path independently (src/lib.rs:166-193,
rayon across points), this walks all (key, point) pairs together one level at
a time — the exact dataflow the TPU backend expresses as ``lax.scan`` over
levels with ``vmap`` over keys and points.  Bit-exact with the spec model.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.utils.groups import lanes_of, np_group_neg

__all__ = ["eval_batch_np"]


def eval_batch_np(
    prg: HirosePrgNp,
    b: int,
    bundle: KeyBundle,
    xs: np.ndarray,
) -> np.ndarray:
    """Evaluate party ``b``'s share of each key on each point.

    xs: uint8 [M, n_bytes] (shared by all keys) or [K, M, n_bytes].
    Returns uint8 [K, M, lam].

    The bundle's output group picks the value accumulation: XOR, or the
    per-lane signed add of Boyle et al. Fig. 1 — the party sign
    ``(-1)^b`` factors out of every level, so the walk accumulates
    unsigned and party 1 negates once at the output edge (the signed
    share contract: reconstruction is always ``group_add(y0, y1)``).
    """
    k_num, n, lam = bundle.cw_s.shape
    group = bundle.group
    if xs.ndim == 2:
        xs = np.broadcast_to(xs, (k_num, *xs.shape))
    if xs.shape[0] != k_num or xs.shape[2] * 8 != n:
        raise ShapeError("xs shape mismatch with bundle")
    m = xs.shape[1]
    # MSB-first bit planes: uint8 [K, M, n].
    x_bits = np.unpackbits(xs, axis=2)

    # Per-(key, point) walk state.
    s = np.broadcast_to(bundle.s0s[:, 0, None, :], (k_num, m, lam)).copy()
    t = np.full((k_num, m), np.uint8(b), dtype=np.uint8)
    v = np.zeros((k_num, m, lam), dtype=np.uint8)

    for i in range(n):
        p = prg.gen(s)
        t_mask = t[..., None]  # uint8 {0,1} [K, M, 1]
        cw_s = bundle.cw_s[:, None, i, :]  # [K, 1, lam]
        cw_v = bundle.cw_v[:, None, i, :]
        cw_tl = bundle.cw_t[:, None, i, 0]
        cw_tr = bundle.cw_t[:, None, i, 1]
        s_l = p.s_l ^ cw_s * t_mask
        s_r = p.s_r ^ cw_s * t_mask
        t_l = p.t_l ^ (t & cw_tl)
        t_r = p.t_r ^ (t & cw_tr)
        x_i = x_bits[:, :, i]  # [K, M], 1 -> right
        xb = x_i[..., None].astype(bool)
        if group == "xor":
            v ^= np.where(xb, p.v_r, p.v_l) ^ cw_v * t_mask
        else:
            v_hat = np.where(xb, p.v_r, p.v_l)
            lv = lanes_of(v, group)
            lv += lanes_of(v_hat, group)
            lv += (lanes_of(np.ascontiguousarray(cw_v), group)
                   * t_mask.astype(lv.dtype))
        s = np.where(xb, s_r, s_l)
        t = np.where(x_i.astype(bool), t_r, t_l)

    if group == "xor":
        return v ^ s ^ bundle.cw_np1[:, None, :] * t[..., None]
    lv = lanes_of(v, group)
    lv += lanes_of(np.ascontiguousarray(s), group)
    lv += (lanes_of(np.ascontiguousarray(bundle.cw_np1[:, None, :]), group)
           * t[..., None].astype(lv.dtype))
    return np_group_neg(v, group) if b else v
