"""Full-domain DPF evaluation (EvalAll) — the PIR engine backend.

``backends.fulldomain`` expands the lam=16 DCF tree; this is the DPF
twin at the device DPF width (lam=32): the host numpy walk expands the
tiny irregular top (levels 0..k0, 2^k0 nodes, K keys at once), ships
the frontier planes to the device, and ``ops.pallas_evalall`` doubles
the node arrays level by level until the leaves.  Total PRG work drops
from n * 2^n per-point walks to ~2^{n+1} level-order calls per key —
the classic EvalAll optimization, and the reason 2-server PIR is
economic: every query touches the whole database, so the per-leaf cost
IS the query cost (workloads.py rides ``eval_party``'s leaf t-bit
planes directly as the selection-vector share).

Leaves come out in bitreverse_n order (each level stacks
[left-children; right-children]); verification computes each position's
domain value arithmetically, so nothing is ever gathered back to
natural order.  Interpret-mode rule: Mosaic on TPU, the Pallas
interpreter elsewhere — callers pass ``interpret=True`` off-TPU, same
as every other Pallas backend.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.ops.aes_bitsliced import round_key_masks_bitmajor
from dcf_tpu.ops.pallas_evalall import dpf_tree_expand_device
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.protocols.dpf import DPF_DEVICE_LAM, DpfBundle
from dcf_tpu.spec import hirose_used_cipher_indices
from dcf_tpu.utils.bits import (
    bitmajor_perm,
    bitmajor_plane_masks,
    bits_lsb_to_bytes,
    byte_bits_lsb,
    pack_lanes,
    unpack_lanes,
)

__all__ = ["DpfEvalAll", "dpf_finalize_np", "dpf_tree_expand_np",
           "leaf_planes_to_bytes"]

_PERM = bitmajor_perm(16)
_INV_PERM = np.argsort(_PERM)


def dpf_tree_expand_np(prg: HirosePrgNp, bundle: DpfBundle, b: int,
                       levels: int):
    """Host breadth-first expansion of party ``b``'s K keys to
    ``levels`` deep.

    Returns (s [K, N, lam], t [K, N]) with N = 2^levels in bitreverse
    order (position = Σ dir_i 2^i over the MSB-first walk directions).
    Doubles as the oracle the device kernel is tested against, and as
    the portable EvalAll for hosts without an accelerator.
    """
    col = b if bundle.s0s.shape[1] == 2 else 0
    s = bundle.s0s[:, col, None, :].copy()  # [K, 1, lam]
    t = np.full((bundle.num_keys, 1), b, dtype=np.uint8)
    for i in range(levels):
        p = prg.gen(s)
        cs = bundle.cw_s[:, None, i, :]
        ctl = bundle.cw_t[:, None, i, 0]
        ctr = bundle.cw_t[:, None, i, 1]
        tc = t[..., None]
        s_l = p.s_l ^ cs * tc
        s_r = p.s_r ^ cs * tc
        t_l = p.t_l ^ (t & ctl)
        t_r = p.t_r ^ (t & ctr)
        s = np.concatenate([s_l, s_r], axis=1)
        t = np.concatenate([t_l, t_r], axis=1)
    return s, t


def dpf_finalize_np(bundle: DpfBundle, s: np.ndarray,
                    t: np.ndarray) -> np.ndarray:
    """Leaf shares from a host expansion at full depth:
    ``y = s ^ cw_np1 * t``, uint8 [K, N, lam]."""
    return s ^ bundle.cw_np1[:, None, :] * t[..., None]


def leaf_planes_to_bytes(y0, y1, t):
    """Device EvalAll outputs back to host bytes: the facade's fetch.

    ``(y0, y1 int32 [K, 128, N/32], t int32 [K, 1, N/32])`` from
    ``eval_party`` -> ``(y uint8 [K, N, 32], t uint8 [K, N])``, leaf
    order unchanged (bitreverse_n).  The exact inverse of the
    ``_frontier`` plane packing, block-concatenated.
    """
    def blk(a):  # int32 [K, 128, N/32] -> uint8 [K, N, 16]
        bits = unpack_lanes(np.asarray(a).view(np.uint32))  # [K, 128, N]
        return bits_lsb_to_bytes(np.swapaxes(bits, 1, 2)[..., _INV_PERM])

    y = np.concatenate([blk(y0), blk(y1)], axis=-1)
    t_bits = unpack_lanes(np.asarray(t).view(np.uint32))[:, 0, :]
    return y, t_bits.astype(np.uint8)


def leaf_pair_mismatch_count(y0b0, y0b1, y1b0, y1b1, beta0_m, beta1_m,
                             inside):
    """Count leaves whose XOR reconstruction differs from the expected
    ``beta if inside else 0`` across BOTH 16-byte blocks.

    y{party}b{block}: leaf-share planes [K, 128, W]; beta masks
    [K, 128, 1]; inside: 0/-1 lane words [K, 1, W] (or broadcastable).
    The two-block twin of ``fulldomain.leaf_mismatch_count``, shared by
    the unsharded and mesh-sharded verifiers."""
    diff = (jnp.bitwise_or.reduce(y0b0 ^ y1b0 ^ (beta0_m & inside),
                                  axis=-2)
            | jnp.bitwise_or.reduce(y0b1 ^ y1b1 ^ (beta1_m & inside),
                                    axis=-2))
    return jnp.sum(jax.lax.population_count(
        jax.lax.bitcast_convert_type(diff, jnp.uint32)).astype(jnp.int32))


@partial(jax.jit, static_argnames=("n",))
def _dpf_tree_mismatch(y0b0, y0b1, y1b0, y1b1, beta0_m, beta1_m, alphas,
                       n: int):
    """Mismatching-leaf count for bitrev-order K-keyed leaf planes
    [K, 128, 2^n / 32]; ``alphas`` uint32 [K], one point per key."""
    m = 32 * y0b0.shape[-1]
    pos = jnp.arange(m, dtype=jnp.uint32)
    value = jnp.zeros(m, dtype=jnp.uint32)
    for k in range(n):  # domain value = bitreverse_n(position)
        value = value | (((pos >> k) & 1) << (n - 1 - k))
    hit = (value[None, :] == alphas[:, None]).astype(jnp.uint32)
    bits = hit.reshape(hit.shape[0], -1, 32)
    inside = jax.lax.bitcast_convert_type(
        jnp.sum(bits << jnp.arange(32, dtype=jnp.uint32), axis=-1,
                dtype=jnp.uint32), jnp.int32)[:, None, :]  # [K, 1, W]
    return leaf_pair_mismatch_count(
        y0b0, y0b1, y1b0, y1b1, beta0_m, beta1_m, inside)


class DpfEvalAll:
    """Full-domain K-packed DPF evaluator/verifier (lam=32).

    The DPF mirror of ``fulldomain.TreeFullDomain``: host-expand the
    top ``host_levels`` of each key's GGM tree, run the Pallas EvalAll
    kernel for the rest, finalize on device.  ``eval_party`` returns
    the leaf shares as two-block planes PLUS the leaf t-bit lane words
    — the PIR selection-vector share.  Repeated calls on the same
    bundle object reuse the staged CW image and frontiers (identity
    -keyed ship-once cache, same discipline as TreeFullDomain).
    """

    def __init__(self, lam: int, cipher_keys: Sequence[bytes],
                 host_levels: int = 6, interpret: bool = False):
        if lam != DPF_DEVICE_LAM:
            # api-edge: constructor lam contract (the two-block narrow
            # width; other lams take the host dpf_tree_expand_np walk)
            raise ValueError(
                f"DpfEvalAll supports lam={DPF_DEVICE_LAM} only, "
                f"got {lam}")
        used = hirose_used_cipher_indices(lam, len(cipher_keys))
        self.lam = lam
        self.host_levels = host_levels
        self.interpret = interpret
        self.rk2 = jnp.asarray(np.concatenate(
            [round_key_masks_bitmajor(cipher_keys[i]) for i in used],
            axis=2))  # [15, 128, 2]
        self._prg = HirosePrgNp(lam, cipher_keys)
        # Ship-once cache for repeated evals of the SAME bundle (the
        # PIR serving pattern: one resident key image, many queries).
        # Keyed on the caller's object by IDENTITY and RETAINING it.
        self._cache = None

    def _stage_cw(self, bundle: DpfBundle):
        """Ship the (party-independent) correction words once."""
        def masks(a):  # uint8 [..., 16] -> int32 [..., 128, 1]
            return jnp.asarray(bitmajor_plane_masks(a)[..., None])

        return (masks(bundle.cw_s[..., :16]), masks(bundle.cw_s[..., 16:]),
                jnp.asarray(bundle.cw_t.astype(np.int32) * -1),
                masks(bundle.cw_np1[:, :16]),
                masks(bundle.cw_np1[:, 16:]))

    def _frontier(self, bundle: DpfBundle, b: int, k0: int):
        """Host-expand to level k0 and pack to device plane layout:
        (s0, s1 int32 [K, 128, N/32], t int32 [K, 1, N/32])."""
        s, t = dpf_tree_expand_np(self._prg, bundle, b, k0)

        def planes(a):  # [K, N, 16] -> int32 [K, 128, N/32]
            bits = byte_bits_lsb(a)[..., _PERM]  # [K, N, 128]
            return jnp.asarray(pack_lanes(np.ascontiguousarray(
                np.swapaxes(bits, 1, 2))).view(np.int32))

        t_m = jnp.asarray(pack_lanes(t[:, None, :]).view(np.int32))
        return planes(s[..., :16]), planes(s[..., 16:]), t_m

    def eval_party(self, b: int, bundle: DpfBundle, n_bits: int,
                   staged_cw=None, frontier=None):
        """Party ``b`` full-domain leaf shares: DEVICE int32 planes
        ``(y0, y1 [K, 128, 2^n_bits / 32], t [K, 1, 2^n_bits / 32])``
        — the two 16-byte blocks plus the leaf t-bit lane words, all
        bitreverse_n order.  ``bundle`` must be party-restricted
        (``for_party(b)``).  ``staged_cw``/``frontier`` reuse prior
        ``_stage_cw``/``_frontier`` results (the CW image is
        party-independent; the frontier is per party).

        ``n_bits < bundle.n_bits`` is a PREFIX evaluation: the walk
        stops at depth ``n_bits``, where the t lane words are the
        one-hot indicator of alpha's top-``n_bits`` bits — the PIR
        selection vector for a database domain that need not be
        byte-granular (the wire format is; see ``pir_query_bundle``).
        The y payload planes are only meaningful at FULL depth (the
        leaf correction lands on internal-node seeds otherwise);
        prefix callers must read only ``t``."""
        if bundle.n_bits < n_bits:
            raise ShapeError(
                f"bundle walks {bundle.n_bits} levels, cannot evaluate "
                f"{n_bits} deep")
        if bundle.s0s.shape[1] != 1:
            raise ShapeError("eval_party wants a party-restricted bundle")
        k0 = min(self.host_levels, n_bits)
        if k0 < 5:
            # api-edge: constructor host_levels contract
            raise ValueError("need at least 5 host levels (one lane word)")
        cs0_t, cs1_t, ct_pm, np10_t, np11_t = (
            staged_cw if staged_cw is not None else self._stage_cw(bundle))
        s0, s1, t = (frontier if frontier is not None
                     else self._frontier(bundle, b, k0))
        return dpf_tree_expand_device(
            self.rk2, cs0_t, cs1_t, ct_pm, np10_t, np11_t, s0, s1, t,
            k0=k0, n=n_bits, interpret=self.interpret)

    def invalidate(self) -> None:
        """Drop the ship-once staged image (the serve layer's
        retry-then-evict discipline: a faulted eval must not hand its
        possibly-poisoned device residency to the retry)."""
        self._cache = None

    def _staged_for(self, bundle: DpfBundle, n_bits: int):
        """Staged CW image + both parties' frontiers for ``bundle``,
        shipped to the device ONCE and reused while the caller keeps
        evaluating the same bundle object (the PIR server's resident
        key pattern)."""
        c = self._cache
        if c is not None and c[0] is bundle and c[1] == n_bits:
            return c[2], c[3], c[4]
        k0 = min(self.host_levels, n_bits)
        staged_cw = self._stage_cw(bundle)
        parts = {b: bundle.for_party(b) for b in (0, 1)}
        fronts = {b: self._frontier(parts[b], b, k0) for b in (0, 1)}
        self._cache = (bundle, n_bits, staged_cw, fronts, parts)
        return staged_cw, fronts, parts

    def check_device(self, bundle: DpfBundle, alphas: np.ndarray,
                     betas: np.ndarray, n_bits: int) -> jax.Array:
        """Two-party full-domain reconstruction vs the point function,
        entirely on device; returns the mismatching-leaf count (over
        ALL keys and the WHOLE 2^n domain) as a DEVICE scalar.
        ``bundle`` is the full two-party bundle; ``alphas`` are the K
        point values (ints < 2^n_bits, n_bits <= 32 for the device
        comparison), ``betas`` uint8 [K, 32]."""
        staged_cw, fronts, parts = self._staged_for(bundle, n_bits)
        y0 = self.eval_party(0, parts[0], n_bits, staged_cw, fronts[0])
        y1 = self.eval_party(1, parts[1], n_bits, staged_cw, fronts[1])
        betas = np.asarray(betas, dtype=np.uint8)
        beta0_m = jnp.asarray(bitmajor_plane_masks(betas[:, :16])[..., None])
        beta1_m = jnp.asarray(bitmajor_plane_masks(betas[:, 16:])[..., None])
        alphas_u = jnp.asarray(np.asarray(alphas, dtype=np.uint32))
        return _dpf_tree_mismatch(
            y0[0], y0[1], y1[0], y1[1], beta0_m, beta1_m, alphas_u,
            n=n_bits)

    def check(self, bundle: DpfBundle, alphas, betas,
              n_bits: int) -> int:
        return int(self.check_device(bundle, alphas, betas, n_bits))
