"""Frontier storage contract shared by the prefix-family backends.

Both prefix-shared evaluators (``backends.pallas_prefix`` at lam=16 and
the hybrid ``backends.large_lambda`` at lam >= 48, plus their sharded
subclasses) materialize a per-(key image, party) frontier: the top-k
walk levels expanded once as gather tables so each eval walks only the
remaining n-k levels.  The frontier is *key material* — a pure function
of (bundle, party, k), xs-independent — so where it is CACHED is a
policy question, not a correctness one:

* **instance store** (default): the frontier lives in the backend
  instance's ``_frontier`` dict and dies with it.  Right for the bare
  staged API, where one instance holds one bundle for its lifetime.
* **frontier provider** (the serving layer): ``frontier_provider`` is
  bound post-``put_bundle`` to an object with a single method
  ``get(party, k, build)``; the backend then consults the provider on
  every ``_frontier_tables`` call and never touches its local store.
  ``dcf_tpu.serve.frontier_cache.FrontierCache`` binds one provider per
  (key_id, registration generation), so the expanded frontier survives
  residency eviction and is shared across re-staged instances of the
  same key — the serve-resident amortization of the narrow-walk floor.

``invalidate_frontier`` is the ONE invalidation hook: re-staging a new
bundle onto an instance (``put_bundle``) and the serve registry evicting
the owning entry both route through it, clearing the local store AND
unbinding the provider (a provider bound to the previous key image must
never serve the next one).  Before this hook existed the two paths were
separate seams: ``put_bundle`` cleared ``_frontier`` but a registry
eviction left the dropped instance's frontier bytes device-resident (an
in-flight batch closure pins the instance) and uncounted by any budget.

Subclass contract: provide ``_k()`` (effective prefix depth for the
held bundle) and ``_build_frontier_tables(b)`` (the uncached build,
returning whatever table object the backend's eval path consumes —
sharded subclasses return mesh-placed tables so the cache holds the
placed copy).
"""

from __future__ import annotations

__all__ = ["FrontierConsumerMixin"]


class FrontierConsumerMixin:
    """Get-or-build frontier tables through the instance store or a
    bound provider (see module docstring)."""

    #: Bound by the owner of the key-id namespace (the serve registry);
    #: None = the instance-local store.  Must expose
    #: ``get(party, k, build)`` returning the (possibly cached) tables.
    frontier_provider = None

    def invalidate_frontier(self) -> None:
        """The ONE frontier-invalidation hook: drop the instance store
        and unbind the provider.  Called by ``put_bundle`` (new key
        image) and by the serve registry when it evicts the owning
        entry (hot-swap / unregister / failure eviction)."""
        self._frontier: dict = {}
        self.frontier_provider = None

    def ensure_frontier(self, b: int) -> None:
        """Build (or cache-fetch) party ``b``'s frontier now — the serve
        registry calls this at stage time so the expansion runs off the
        eval clock of later batches."""
        self._frontier_tables(int(b))

    def _frontier_tables(self, b: int):
        """Party ``b``'s frontier tables, cached in the bound provider
        (keyed (key_id, generation, party, k) there) or the instance
        store (keyed by party — a new key image resets it through
        ``invalidate_frontier``)."""
        b = int(b)
        prov = self.frontier_provider
        if prov is not None:
            return prov.get(b, self._k(),
                            lambda: self._build_frontier_tables(b))
        tbl = self._frontier.get(b)
        if tbl is None:
            tbl = self._build_frontier_tables(b)
            self._frontier[b] = tbl
        return tbl
