"""Pure-Python golden model of the DCF scheme (the "spec").

This module is the slow, obviously-correct executable specification of the
two-party Distributed Comparison Function implemented by the reference crate
(xymeng16/dcf).  Every other backend in this framework — the vectorized numpy
backend, the C++ host core, and the JAX/Pallas TPU backend — is validated
bit-for-bit against this model.

Semantics honored here (see SURVEY.md §0, §2.1, §3):

* ``f_{alpha,beta}(x) = beta if x < alpha else 0`` for the ``LT_BETA`` bound
  (strict: ``f(alpha) = 0``), ``x > alpha`` for ``GT_BETA``.  Reference:
  ``/root/reference/src/lib.rs:62`` and the test vectors at ``src/lib.rs:363-370``.
* Comparison order is unsigned big-endian lexicographic over the ``n_bytes``
  input bytes; the GGM tree is walked MSB-first (``src/lib.rs:106, 181``).
* The output group is XOR (byte-wise) by default — reconstruction is
  ``y0 ^ y1`` (``src/lib.rs:390-392``).  PR 20 adds the paper's additive
  groups (``group`` parameter, ``add8``/``add16``/``add32`` = Z_{2^w}
  lanes over the ``lam`` payload bytes, little-endian): the GGM tree
  walk is untouched; only the value-accumulation and correction-word
  algebra change, following Boyle et al. EUROCRYPT 2021 Fig. 3 — the
  correction words carry a party sign ``(-1)^{t1}`` at gen and
  ``(-1)^b`` at eval, and reconstruction is ``y0 + y1 mod 2^w`` per
  lane.
* The PRG is the Hirose double-block-length construction over AES-256 with
  its exact loop-truncation quirk (``src/prg.rs:42-73``, SURVEY.md §2.1):
  only ``min(2, lam // 16)`` block positions are ever encrypted, the t-bits
  are taken from the two *left-child* buffers before masking, and the LSB of
  the last byte of all four outputs is cleared (effective output ``8*lam - 1``
  bits).

Everything here operates on ``bytes`` and Python ints; no numpy, no JAX.
"""

from __future__ import annotations

import os
import sys
import warnings
from dataclasses import dataclass
from enum import Enum
from typing import Sequence

__all__ = [
    "AES_SBOX",
    "GROUPS",
    "GROUP_CODE",
    "GROUP_FROM_CODE",
    "GROUP_WIDTH",
    "SHIFT_ROWS",
    "ReferenceContractWarning",
    "aes256_expand_key",
    "hirose_used_cipher_indices",
    "aes256_encrypt_block",
    "HirosePrgSpec",
    "Bound",
    "CmpFn",
    "Cw",
    "Share",
    "bytes_to_lanes",
    "check_group",
    "gen",
    "eval_point",
    "eval_batch",
    "group_add",
    "group_neg",
    "group_sub",
    "lanes_to_bytes",
    "xor_bytes",
]


# ---------------------------------------------------------------------------
# Output groups.  ``xor`` is the reference crate's byte-wise XOR group;
# ``add{8,16,32}`` are the paper's additive groups Z_{2^w}: the lam
# payload bytes are read as ``8 * lam / w`` little-endian w-bit lanes and
# reconstruction is per-lane ``y0 + y1 mod 2^w``.  The name/code table is
# the single wire + API authority (keys.py v4 frames, protocols, CLI).
# ---------------------------------------------------------------------------

GROUPS = ("xor", "add8", "add16", "add32")
GROUP_CODE = {"xor": 0, "add8": 1, "add16": 2, "add32": 3}
GROUP_FROM_CODE = {code: name for name, code in GROUP_CODE.items()}
GROUP_WIDTH = {"add8": 8, "add16": 16, "add32": 32}  # lane width, bits


def check_group(group: str, lam: int) -> None:
    """Validate a group name against a payload width (API/wire edge)."""
    if group not in GROUP_CODE:
        # api-edge: documented output-group contract
        raise ValueError(
            f"unknown output group {group!r}; expected one of {GROUPS}")
    if group != "xor" and (8 * lam) % GROUP_WIDTH[group] != 0:
        # api-edge: additive lanes must tile the payload exactly
        raise ValueError(
            f"group {group!r} needs lam*8={8 * lam} divisible by "
            f"{GROUP_WIDTH[group]}")


def bytes_to_lanes(data: bytes, w: int) -> list[int]:
    """Convert: read bytes as little-endian w-bit lanes (w in 8/16/32)."""
    step = w // 8
    return [int.from_bytes(data[i:i + step], "little")
            for i in range(0, len(data), step)]


def lanes_to_bytes(lanes: Sequence[int], w: int) -> bytes:
    """Inverse of :func:`bytes_to_lanes`; values reduced mod 2^w."""
    step, mask = w // 8, (1 << w) - 1
    return b"".join((v & mask).to_bytes(step, "little") for v in lanes)


def group_add(a: bytes, b: bytes, group: str) -> bytes:
    """Group operation on payload bytes: XOR, or per-lane add mod 2^w."""
    if group == "xor":
        return xor_bytes(a, b)
    w = GROUP_WIDTH[group]
    return lanes_to_bytes(
        [x + y for x, y in zip(bytes_to_lanes(a, w), bytes_to_lanes(b, w))],
        w)


def group_sub(a: bytes, b: bytes, group: str) -> bytes:
    """Group inverse-apply: XOR, or per-lane ``a - b mod 2^w``."""
    if group == "xor":
        return xor_bytes(a, b)
    w = GROUP_WIDTH[group]
    return lanes_to_bytes(
        [x - y for x, y in zip(bytes_to_lanes(a, w), bytes_to_lanes(b, w))],
        w)


def group_neg(a: bytes, group: str) -> bytes:
    """Group negation: identity for XOR, per-lane ``-a mod 2^w`` else."""
    if group == "xor":
        return a
    w = GROUP_WIDTH[group]
    return lanes_to_bytes([-x for x in bytes_to_lanes(a, w)], w)


# ---------------------------------------------------------------------------
# AES-256 (FIPS-197), minimal encrypt-only implementation.
# ---------------------------------------------------------------------------

def _build_sbox() -> bytes:
    """Generate the AES S-box from first principles (GF(2^8) inverse + affine).

    Generated rather than transcribed so a typo is impossible; validated in
    tests against the `cryptography` package and the reference PRG vectors.
    """
    # GF(2^8) exp/log tables using generator 3.
    exp = [0] * 512
    log = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        # multiply x by 3 = x ^ (x<<1) with reduction by 0x11b
        x ^= (x << 1) ^ (0x11B if x & 0x80 else 0)
        x &= 0xFF
    for i in range(255, 512):
        exp[i] = exp[i - 255]

    def inv(a: int) -> int:
        return 0 if a == 0 else exp[255 - log[a]]

    sbox = bytearray(256)
    for a in range(256):
        b = inv(a)
        r = 0x63
        for shift in (0, 1, 2, 3, 4):
            r ^= ((b << shift) | (b >> (8 - shift))) & 0xFF
        sbox[a] = r
    return bytes(sbox)


AES_SBOX = _build_sbox()

_RCON = [0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36, 0x6C]


def aes256_expand_key(key: bytes) -> list[bytes]:
    """Expand a 32-byte AES-256 key into 15 round keys of 16 bytes each."""
    if len(key) != 32:
        # api-edge: documented AES-256 key contract (reference parity)
        raise ValueError("AES-256 key must be 32 bytes")
    nk, nr = 8, 14
    w = [key[4 * i : 4 * i + 4] for i in range(nk)]
    for i in range(nk, 4 * (nr + 1)):
        temp = w[i - 1]
        if i % nk == 0:
            rot = temp[1:] + temp[:1]
            temp = bytes(AES_SBOX[b] for b in rot)
            temp = bytes([temp[0] ^ _RCON[i // nk - 1], temp[1], temp[2], temp[3]])
        elif i % nk == 4:
            temp = bytes(AES_SBOX[b] for b in temp)
        w.append(bytes(a ^ b for a, b in zip(w[i - nk], temp)))
    return [b"".join(w[4 * r : 4 * r + 4]) for r in range(nr + 1)]


def _xtime(a: int) -> int:
    return ((a << 1) ^ (0x1B if a & 0x80 else 0)) & 0xFF


SHIFT_ROWS = [0, 5, 10, 15, 4, 9, 14, 3, 8, 13, 2, 7, 12, 1, 6, 11]


def aes256_encrypt_block(round_keys: Sequence[bytes], block: bytes) -> bytes:
    """Encrypt one 16-byte block with pre-expanded AES-256 round keys."""
    s = bytes(a ^ b for a, b in zip(block, round_keys[0]))
    for rnd in range(1, 14):
        s = bytes(AES_SBOX[b] for b in s)
        s = bytes(s[i] for i in SHIFT_ROWS)
        out = bytearray(16)
        for c in range(4):
            a0, a1, a2, a3 = s[4 * c : 4 * c + 4]
            out[4 * c + 0] = _xtime(a0) ^ _xtime(a1) ^ a1 ^ a2 ^ a3
            out[4 * c + 1] = a0 ^ _xtime(a1) ^ _xtime(a2) ^ a2 ^ a3
            out[4 * c + 2] = a0 ^ a1 ^ _xtime(a2) ^ _xtime(a3) ^ a3
            out[4 * c + 3] = _xtime(a0) ^ a0 ^ a1 ^ a2 ^ _xtime(a3)
        s = bytes(a ^ b for a, b in zip(out, round_keys[rnd]))
    s = bytes(AES_SBOX[b] for b in s)
    s = bytes(s[i] for i in SHIFT_ROWS)
    return bytes(a ^ b for a, b in zip(s, round_keys[14]))


def xor_bytes(*parts: bytes) -> bytes:
    """Byte-wise XOR of equal-length byte strings (utils::xor analog)."""
    out = bytearray(parts[0])
    for p in parts[1:]:
        for i, b in enumerate(p):
            out[i] ^= b
    return bytes(out)


# ---------------------------------------------------------------------------
# Hirose PRG (reference src/prg.rs:22-74), with its exact quirks.
# ---------------------------------------------------------------------------


class ReferenceContractWarning(UserWarning):
    """The requested shape is an extension the reference itself cannot run.

    Emitted (not raised — the framework supports these shapes, bit-exactly
    extending the reference's semantics) when either

    * ``32 <= lam < 144``: the reference's own key-count contract
      ``N_KEYS = 2*(lam/16)`` (src/prg.rs:17-18) supplies <= 17 ciphers, so
      its encryption loop would panic indexing ``ciphers[17]``
      (src/prg.rs:51) — no reference execution of this shape exists; or
    * ``num_keys < 2*(lam/16)``: fewer ciphers than the reference contract
      demands (only indices 0 and 17 are ever touched, so this framework
      accepts any count covering them).
    """


# Warning attribution skips package-internal frames so every API edge
# (facade, backend constructors, the PRG classes) points the user at THEIR
# call site, and warning dedup keys on distinct user locations.
# ``skip_file_prefixes`` is Python 3.12+; on older interpreters the warning
# still fires, just attributed to the immediate caller (stacklevel=2) —
# passing the kwarg unconditionally made every extension-band shape CRASH
# with TypeError on 3.10/3.11 instead of warning.
_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_WARN_KWARGS = (
    {"skip_file_prefixes": (_PKG_DIR,)}
    if sys.version_info >= (3, 12) else {}
)


def hirose_used_cipher_indices(
    lam: int, num_keys: int, warn: bool = True
) -> list[int]:
    """Validate a Hirose PRG shape and return the cipher indices it uses.

    The used indices are ``17*k for k < min(2, lam // 16)`` — a consequence of
    the reference's truncating encryption loop (src/prg.rs:48-51).  Shared by
    every PRG implementation in this framework so the parity-critical key-count
    contract cannot desynchronize between backends.  Shapes the reference
    could not execute itself warn with ``ReferenceContractWarning`` so the
    extension surface is explicit at every API edge; ``warn=False`` is for
    internal sub-walks (e.g. the hybrid evaluator's lam=32 narrow slice of
    a larger, contract-conforming shape), which are not API edges.
    Warnings are attributed to the first stack frame outside this package,
    i.e. the user's constructor call, whichever API edge it went through.
    """
    if lam % 16 != 0:
        # api-edge: documented Hirose lam contract (reference parity)
        raise ValueError("lam must be a multiple of 16 bytes")
    used = [17 * k for k in range(min(2, lam // 16))]
    if used and used[-1] >= num_keys:
        # api-edge: documented cipher-key-count contract (reference parity)
        raise ValueError(f"lam={lam} uses cipher indices {used}; got {num_keys} keys")
    if not warn:
        return used
    if 32 <= lam < 144:
        warnings.warn(
            f"lam={lam} is reference-inexecutable: its key-count contract "
            f"2*(lam/16)={2 * (lam // 16)} cannot cover cipher index 17 "
            "(src/prg.rs:17-18,51); this framework runs it as an extension",
            ReferenceContractWarning,
            stacklevel=2,
            **_WARN_KWARGS,
        )
    elif num_keys < 2 * (lam // 16):
        idx = "/".join(str(i) for i in used)
        warnings.warn(
            f"{num_keys} cipher keys relaxes the reference contract "
            f"N_KEYS=2*(lam/16)={2 * (lam // 16)} (src/prg.rs:17-18); only "
            f"the used cipher {'index' if len(used) == 1 else 'indices'} "
            f"({idx}) affect outputs, which are unchanged",
            ReferenceContractWarning,
            stacklevel=2,
            **_WARN_KWARGS,
        )
    return used


class HirosePrgSpec:
    """Bit-exact model of ``Aes256HirosePrg<LAMBDA, N_KEYS>``.

    ``keys`` are the caller-supplied 32-byte AES-256 keys; the reference
    requires ``lam % 16 == 0`` and ``len(keys) == 2 * (lam // 16)``, but only
    ciphers ``0`` and (when ``lam >= 32``) ``17`` are ever used, because the
    encryption loop ``(0..2).zip(0..lam/16)`` truncates to
    ``min(2, lam // 16)`` iterations with ``i == j`` (src/prg.rs:48-56).

    Reference-executable ``lam`` values are ``16`` and multiples of 16 that
    are ``>= 144``: for ``32 <= lam < 144`` the reference's own key-count
    contract gives ``2 * (lam // 16) <= 17`` ciphers, so indexing
    ``ciphers[17]`` panics (src/prg.rs:51).  This framework still supports
    those shapes (e.g. the BASELINE.json lam=128 metric) as an extension,
    provided ``keys`` covers index 17; the divergence is documented here
    because no reference behavior exists to diverge from.
    """

    def __init__(self, lam: int, keys: Sequence[bytes]):
        self.lam = lam
        # Only indices 17*k are ever used — skip expanding the rest (the
        # reference contract supplies 2*(lam/16) keys, 2046 unused at lam=16384).
        used = hirose_used_cipher_indices(lam, len(keys))
        self.round_keys = {i: aes256_expand_key(keys[i]) for i in used}

    def gen(self, seed: bytes) -> list[tuple[bytes, bytes, bool]]:
        lam = self.lam
        assert len(seed) == lam
        seed_p = bytes(b ^ 0xFF for b in seed)  # seed ^ c, c = 0xff.. (prg.rs:36-38,44)
        buf0 = [bytearray(lam), bytearray(lam)]
        buf1 = [bytearray(lam), bytearray(lam)]
        # zip truncation: iterations (k, k) for k in 0..min(2, lam/16);
        # cipher index is i*16 + j = 17*k (src/prg.rs:48-51).
        for k in range(min(2, lam // 16)):
            rk = self.round_keys[17 * k]
            lo, hi = 16 * k, 16 * (k + 1)
            buf0[k][lo:hi] = aes256_encrypt_block(rk, seed[lo:hi])
            buf1[k][lo:hi] = aes256_encrypt_block(rk, seed_p[lo:hi])
        # Miyaguchi-style feed-forward into BOTH halves (src/prg.rs:57-62);
        # never-encrypted halves become literal copies of seed / seed_p.
        for k in range(2):
            buf0[k] = bytearray(a ^ b for a, b in zip(buf0[k], seed))
            buf1[k] = bytearray(a ^ b for a, b in zip(buf1[k], seed_p))
        # t-bits from the two buffers of half 0, BEFORE masking (src/prg.rs:63-64).
        bit0 = bool(buf0[0][0] & 1)
        bit1 = bool(buf1[0][0] & 1)
        # Clear LSB of last byte of all four outputs (src/prg.rs:65-68).
        for buf in (buf0[0], buf0[1], buf1[0], buf1[1]):
            buf[lam - 1] &= 0xFE
        return [
            (bytes(buf0[0]), bytes(buf1[0]), bit0),
            (bytes(buf0[1]), bytes(buf1[1]), bit1),
        ]


# ---------------------------------------------------------------------------
# DCF gen / eval (reference src/lib.rs:86-204).
# ---------------------------------------------------------------------------


class Bound(Enum):
    """BoundState (src/lib.rs:342-349)."""

    LT_BETA = "lt"  # f(x) = beta iff x < alpha (paper's preference)
    GT_BETA = "gt"  # f(x) = beta iff x > alpha


@dataclass(frozen=True)
class CmpFn:
    """Comparison function description (src/lib.rs:41-46)."""

    alpha: bytes
    beta: bytes


@dataclass(frozen=True)
class Cw:
    """Correction word (src/lib.rs:209-214)."""

    s: bytes
    v: bytes
    tl: bool
    tr: bool

    def __repr__(self) -> str:
        """Redacted: the s/v bytes are key material (the secret-hygiene
        field regex cannot see one-letter names, so this is explicit)."""
        return (f"Cw(lam={len(self.s)}, tl={self.tl}, tr={self.tr}, "
                "<s/v bytes redacted>)")


@dataclass(frozen=True)
class Share:
    """DCF key (src/lib.rs:275-283).

    ``s0s`` has length 2 out of ``gen`` and length 1 as input to ``eval``
    (only ``s0s[0]`` is read). ``cws``/``cw_np1`` are identical for both
    parties; only the starting seed differs.
    """

    s0s: tuple[bytes, ...]
    cws: tuple[Cw, ...]
    cw_np1: bytes

    def __repr__(self) -> str:
        """Redacted: geometry only — the fields are the key material."""
        lam = len(self.cw_np1)
        return (f"Share(parties={len(self.s0s)}, n_bits={len(self.cws)}, "
                f"lam={lam}, <key-material bytes redacted>)")

    def for_party(self, b: int) -> "Share":
        return Share(s0s=(self.s0s[b],), cws=self.cws, cw_np1=self.cw_np1)


def _bit_msb(data: bytes, i: int) -> bool:
    """Bit i of ``data`` in MSB-first order (bitvec Msb0 view)."""
    return bool((data[i // 8] >> (7 - i % 8)) & 1)


def gen(
    prg: HirosePrgSpec,
    f: CmpFn,
    s0s: Sequence[bytes],
    bound: Bound,
    group: str = "xor",
) -> Share:
    """GGM-tree key generation (src/lib.rs:86-161).

    ``group`` selects the output group.  The tree walk (seeds, t-bits) is
    identical for every group; only the value correction words change.
    For the additive groups the algebra is Boyle et al. EUROCRYPT 2021
    Fig. 1: the correction words carry the party sign ``(-1)^{t1}`` of
    party 1's previous control bit (party 0 starts at t=0, party 1 at
    t=1, matching the reference), and the XOR group is the exact
    characteristic-2 degeneration of the same formulas (``-x = x``,
    signs vanish), so one code path serves both.
    """
    n_bytes, lam = len(f.alpha), len(f.beta)
    check_group(group, lam)
    n = 8 * n_bytes
    zero = bytes(lam)
    v_alpha = zero
    ss = [(bytes(s0s[0]), bytes(s0s[1]))]
    ts = [(False, True)]
    cws: list[Cw] = []
    for i in range(1, n + 1):
        (s0l, v0l, t0l), (s0r, v0r, t0r) = prg.gen(ss[i - 1][0])
        (s1l, v1l, t1l), (s1r, v1r, t1r) = prg.gen(ss[i - 1][1])
        alpha_i = _bit_msb(f.alpha, i - 1)
        keep, lose = (1, 0) if alpha_i else (0, 1)  # 0 = L, 1 = R
        sign1 = ts[i - 1][1]  # party 1's t on the alpha path: (-1)^{t1}
        s_cw = xor_bytes([s0l, s0r][lose], [s1l, s1r][lose])
        # V_CW <- (-1)^{t1} * [Convert(v1_lose) - Convert(v0_lose) - V_alpha
        #                      (+ beta on the bound-matching lose side)]
        v_cw = group_sub(
            group_sub([v1l, v1r][lose], [v0l, v0r][lose], group),
            v_alpha, group)
        if bound is Bound.LT_BETA:
            if lose == 0:
                v_cw = group_add(v_cw, f.beta, group)
        else:
            if lose == 1:
                v_cw = group_add(v_cw, f.beta, group)
        if sign1:
            v_cw = group_neg(v_cw, group)
        # V_alpha <- V_alpha - Convert(v1_keep) + Convert(v0_keep)
        #            + (-1)^{t1} * V_CW
        v_alpha = group_add(
            group_sub(v_alpha, [v1l, v1r][keep], group),
            group_add([v0l, v0r][keep],
                      group_neg(v_cw, group) if sign1 else v_cw, group),
            group)
        tl_cw = t0l ^ t1l ^ alpha_i ^ True
        tr_cw = t0r ^ t1r ^ alpha_i
        cws.append(Cw(s=s_cw, v=v_cw, tl=tl_cw, tr=tr_cw))
        ss.append(
            (
                xor_bytes([s0l, s0r][keep], s_cw if ts[i - 1][0] else zero),
                xor_bytes([s1l, s1r][keep], s_cw if ts[i - 1][1] else zero),
            )
        )
        ts.append(
            (
                [t0l, t0r][keep] ^ (ts[i - 1][0] & [tl_cw, tr_cw][keep]),
                [t1l, t1r][keep] ^ (ts[i - 1][1] & [tl_cw, tr_cw][keep]),
            )
        )
    # CW_{n+1} <- (-1)^{t1_n} * [Convert(s1_n) - Convert(s0_n) - V_alpha]
    cw_np1 = group_sub(group_sub(ss[n][1], ss[n][0], group), v_alpha, group)
    if ts[n][1]:
        cw_np1 = group_neg(cw_np1, group)
    return Share(s0s=(bytes(s0s[0]), bytes(s0s[1])), cws=tuple(cws), cw_np1=cw_np1)


def eval_point(
    prg: HirosePrgSpec, b: bool, k: Share, x: bytes, group: str = "xor"
) -> bytes:
    """Single-point evaluation (src/lib.rs:163-193).

    Returns the party's output-group share.  For the additive groups the
    share carries the party sign ``(-1)^b`` (Boyle et al. Fig. 1 eval),
    so reconstruction is always ``group_add(y0, y1, group)``; for XOR
    the sign is the identity and this is ``y0 ^ y1``.
    """
    n = len(k.cws)
    lam = len(k.cw_np1)
    assert n == 8 * len(x)
    check_group(group, lam)
    zero = bytes(lam)
    s = k.s0s[0]
    t = bool(b)
    v = zero
    for i in range(1, n + 1):
        cw = k.cws[i - 1]
        (sl, vl_hat, tl), (sr, vr_hat, tr) = prg.gen(s)
        if t:
            sl = xor_bytes(sl, cw.s)
            sr = xor_bytes(sr, cw.s)
        tl ^= t & cw.tl
        tr ^= t & cw.tr
        # V <- V + (-1)^b * [Convert(v_hat_chosen) + t * V_CW]
        if _bit_msb(x, i - 1):
            inc = group_add(vr_hat, cw.v if t else zero, group)
            s_next, t_next = sr, tr
        else:
            inc = group_add(vl_hat, cw.v if t else zero, group)
            s_next, t_next = sl, tl
        if b:
            inc = group_neg(inc, group)
        v = group_add(v, inc, group)
        s, t = s_next, t_next
    inc = group_add(s, k.cw_np1 if t else zero, group)
    if b:
        inc = group_neg(inc, group)
    return group_add(v, inc, group)


def eval_batch(
    prg: HirosePrgSpec, b: bool, k: Share, xs: Sequence[bytes],
    group: str = "xor",
) -> list[bytes]:
    """Batch evaluation: a pure map over points (src/lib.rs:194-203)."""
    return [eval_point(prg, b, k, x, group) for x in xs]
