// dcf_core.cpp — native host core: AES-256, Hirose PRG, DCF gen/eval.
//
// Role (SURVEY.md §7 step 2): the C++ equivalent of the reference Rust
// crate's host side — keygen stays on host, and the CPU eval path is both
// the parity oracle for the TPU backend and the single-core baseline that
// anchors the >=100x evals/sec/chip target (BASELINE.md).  Semantics mirror
// /root/reference/src/lib.rs:86-204 and src/prg.rs:42-73 exactly (see
// dcf_tpu/spec.py for the quirk inventory); layout is the KeyBundle SoA.
//
// Build: make -C dcf_tpu/native   (g++ -O3 -march=native; AES-NI when the
// CPU has it, portable S-box path otherwise — both bit-exact).
//
// C ABI only; loaded from Python with ctypes (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

#if defined(__AES__)
#include <wmmintrin.h>
#define DCF_HAVE_AESNI 1
#else
#define DCF_HAVE_AESNI 0
#endif

namespace {

// ---------------------------------------------------------------------------
// AES-256, encrypt-only.
// ---------------------------------------------------------------------------

struct SboxTables {
  uint8_t sbox[256];
  constexpr SboxTables() : sbox{} {
    // GF(2^8) inverse via exp/log tables (generator 3), then the affine map.
    uint8_t exp[512] = {};
    uint8_t log[256] = {};
    uint8_t x = 1;
    for (int i = 0; i < 255; i++) {
      exp[i] = x;
      log[x] = static_cast<uint8_t>(i);
      uint8_t hi = static_cast<uint8_t>(x & 0x80);
      x = static_cast<uint8_t>(x ^ ((x << 1) ^ (hi ? 0x1B : 0)));
    }
    for (int i = 255; i < 512; i++) exp[i] = exp[i - 255];
    for (int a = 0; a < 256; a++) {
      uint8_t b = a == 0 ? 0 : exp[255 - log[a]];
      uint8_t r = 0x63;
      for (int sh = 0; sh < 5; sh++)
        r = static_cast<uint8_t>(r ^ static_cast<uint8_t>((b << sh) | (b >> (8 - sh))));
      sbox[a] = r;
    }
  }
};

constexpr SboxTables kTables;

constexpr uint8_t kRcon[11] = {0x01, 0x02, 0x04, 0x08, 0x10, 0x20,
                               0x40, 0x80, 0x1B, 0x36, 0x6C};

struct RoundKeys {
  uint8_t rk[15][16];
};

void expand_key(const uint8_t key[32], RoundKeys* out) {
  uint8_t w[60][4];
  std::memcpy(w, key, 32);
  for (int i = 8; i < 60; i++) {
    uint8_t t[4] = {w[i - 1][0], w[i - 1][1], w[i - 1][2], w[i - 1][3]};
    if (i % 8 == 0) {
      uint8_t rot = t[0];
      t[0] = static_cast<uint8_t>(kTables.sbox[t[1]] ^ kRcon[i / 8 - 1]);
      t[1] = kTables.sbox[t[2]];
      t[2] = kTables.sbox[t[3]];
      t[3] = kTables.sbox[rot];
    } else if (i % 8 == 4) {
      for (auto& b : t) b = kTables.sbox[b];
    }
    for (int j = 0; j < 4; j++) w[i][j] = static_cast<uint8_t>(w[i - 8][j] ^ t[j]);
  }
  std::memcpy(out->rk, w, 240);
}

inline uint8_t xtime(uint8_t a) {
  return static_cast<uint8_t>((a << 1) ^ ((a & 0x80) ? 0x1B : 0));
}

// [[maybe_unused]]: the AES-NI build keeps the portable cipher compiled
// (it is the -Werror-checked fallback the portable .so ships) but never
// calls it.
[[maybe_unused]] void aes256_encrypt_portable(const RoundKeys& rk,
                                              const uint8_t in[16],
                                              uint8_t out[16]) {
  uint8_t s[16];
  for (int i = 0; i < 16; i++) s[i] = static_cast<uint8_t>(in[i] ^ rk.rk[0][i]);
  static constexpr int kShift[16] = {0, 5, 10, 15, 4, 9, 14, 3,
                                     8, 13, 2, 7, 12, 1, 6, 11};
  uint8_t t[16];
  for (int rnd = 1; rnd < 14; rnd++) {
    for (int i = 0; i < 16; i++) t[i] = kTables.sbox[s[kShift[i]]];
    for (int c = 0; c < 4; c++) {
      uint8_t a0 = t[4 * c], a1 = t[4 * c + 1], a2 = t[4 * c + 2], a3 = t[4 * c + 3];
      s[4 * c + 0] = static_cast<uint8_t>(xtime(a0) ^ xtime(a1) ^ a1 ^ a2 ^ a3 ^ rk.rk[rnd][4 * c + 0]);
      s[4 * c + 1] = static_cast<uint8_t>(a0 ^ xtime(a1) ^ xtime(a2) ^ a2 ^ a3 ^ rk.rk[rnd][4 * c + 1]);
      s[4 * c + 2] = static_cast<uint8_t>(a0 ^ a1 ^ xtime(a2) ^ xtime(a3) ^ a3 ^ rk.rk[rnd][4 * c + 2]);
      s[4 * c + 3] = static_cast<uint8_t>(xtime(a0) ^ a0 ^ a1 ^ a2 ^ xtime(a3) ^ rk.rk[rnd][4 * c + 3]);
    }
  }
  for (int i = 0; i < 16; i++)
    out[i] = static_cast<uint8_t>(kTables.sbox[s[kShift[i]]] ^ rk.rk[14][i]);
}

#if DCF_HAVE_AESNI
// Encrypt two independent blocks with the same key schedule, pipelined so the
// two AESENC chains overlap (the PRG always encrypts seed and seed^c pairs).
inline void aes256_encrypt2_ni(const RoundKeys& rk, const uint8_t in0[16],
                               const uint8_t in1[16], uint8_t out0[16],
                               uint8_t out1[16]) {
  const __m128i* k = reinterpret_cast<const __m128i*>(rk.rk);
  __m128i r0 = _mm_xor_si128(_mm_loadu_si128(reinterpret_cast<const __m128i*>(in0)),
                             _mm_loadu_si128(k));
  __m128i r1 = _mm_xor_si128(_mm_loadu_si128(reinterpret_cast<const __m128i*>(in1)),
                             _mm_loadu_si128(k));
  for (int rnd = 1; rnd < 14; rnd++) {
    __m128i kr = _mm_loadu_si128(k + rnd);
    r0 = _mm_aesenc_si128(r0, kr);
    r1 = _mm_aesenc_si128(r1, kr);
  }
  __m128i kr = _mm_loadu_si128(k + 14);
  r0 = _mm_aesenclast_si128(r0, kr);
  r1 = _mm_aesenclast_si128(r1, kr);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(out0), r0);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(out1), r1);
}
#endif

inline void aes256_encrypt2(const RoundKeys& rk, const uint8_t in0[16],
                            const uint8_t in1[16], uint8_t out0[16],
                            uint8_t out1[16]) {
#if DCF_HAVE_AESNI
  aes256_encrypt2_ni(rk, in0, in1, out0, out1);
#else
  aes256_encrypt_portable(rk, in0, out0);
  aes256_encrypt_portable(rk, in1, out1);
#endif
}

// ---------------------------------------------------------------------------
// Hirose PRG (reference src/prg.rs:42-73, quirks per dcf_tpu/spec.py).
// ---------------------------------------------------------------------------

struct Prg {
  uint32_t lam = 0;
  uint32_t n_enc = 0;  // min(2, lam/16)
  RoundKeys rk[2];     // ciphers 0 and 17 (the only ones ever used)
};

// One PRG call.  Outputs: s_l, v_l, s_r, v_r each `lam` bytes; t_l/t_r bits.
// seed_p_buf: caller-provided scratch of `lam` bytes (no allocation in the
// hot loop — this runs once per level per point in the CPU baseline).
void prg_gen(const Prg& prg, const uint8_t* seed, uint8_t* s_l, uint8_t* v_l,
             uint8_t* s_r, uint8_t* v_r, uint8_t* t_l, uint8_t* t_r,
             uint8_t* seed_p_buf) {
  const uint32_t lam = prg.lam;
  uint8_t* seed_p = seed_p_buf;
  for (uint32_t i = 0; i < lam; i++) seed_p[i] = static_cast<uint8_t>(seed[i] ^ 0xFF);
  uint8_t* buf0[2] = {s_l, s_r};  // result_buf0 halves
  uint8_t* buf1[2] = {v_l, v_r};  // result_buf1 halves
  std::memset(s_l, 0, lam);
  std::memset(s_r, 0, lam);
  std::memset(v_l, 0, lam);
  std::memset(v_r, 0, lam);
  for (uint32_t k = 0; k < prg.n_enc; k++) {
    aes256_encrypt2(prg.rk[k], seed + 16 * k, seed_p + 16 * k,
                    buf0[k] + 16 * k, buf1[k] + 16 * k);
  }
  for (int h = 0; h < 2; h++) {
    for (uint32_t i = 0; i < lam; i++) {
      buf0[h][i] = static_cast<uint8_t>(buf0[h][i] ^ seed[i]);
      buf1[h][i] = static_cast<uint8_t>(buf1[h][i] ^ seed_p[i]);
    }
  }
  *t_l = static_cast<uint8_t>(buf0[0][0] & 1);
  *t_r = static_cast<uint8_t>(buf1[0][0] & 1);
  buf0[0][lam - 1] &= 0xFE;
  buf0[1][lam - 1] &= 0xFE;
  buf1[0][lam - 1] &= 0xFE;
  buf1[1][lam - 1] &= 0xFE;
}

inline int bit_msb(const uint8_t* data, uint32_t i) {
  return (data[i >> 3] >> (7 - (i & 7))) & 1;
}

inline void xor_into(uint8_t* dst, const uint8_t* src, uint32_t n) {
  for (uint32_t i = 0; i < n; i++) dst[i] = static_cast<uint8_t>(dst[i] ^ src[i]);
}

// ---------------------------------------------------------------------------
// DCF gen (reference src/lib.rs:86-161) for one key.
// ---------------------------------------------------------------------------

void gen_one(const Prg& prg, uint32_t n_bytes, const uint8_t* alpha,
             const uint8_t* beta, const uint8_t* s0_pair, int bound_gt,
             uint8_t* cw_s, uint8_t* cw_v, uint8_t* cw_t, uint8_t* cw_np1) {
  const uint32_t lam = prg.lam;
  const uint32_t n = 8 * n_bytes;
  std::vector<uint8_t> s_a(s0_pair, s0_pair + lam);
  std::vector<uint8_t> s_b(s0_pair + lam, s0_pair + 2 * lam);
  uint8_t t_a = 0, t_b = 1;
  std::vector<uint8_t> v_alpha(lam, 0);
  std::vector<uint8_t> p0(4 * lam), p1(4 * lam), seed_p(lam);
  for (uint32_t i = 0; i < n; i++) {
    uint8_t* s0l = p0.data();
    uint8_t* v0l = p0.data() + lam;
    uint8_t* s0r = p0.data() + 2 * lam;
    uint8_t* v0r = p0.data() + 3 * lam;
    uint8_t* s1l = p1.data();
    uint8_t* v1l = p1.data() + lam;
    uint8_t* s1r = p1.data() + 2 * lam;
    uint8_t* v1r = p1.data() + 3 * lam;
    uint8_t t0l, t0r, t1l, t1r;
    prg_gen(prg, s_a.data(), s0l, v0l, s0r, v0r, &t0l, &t0r, seed_p.data());
    prg_gen(prg, s_b.data(), s1l, v1l, s1r, v1r, &t1l, &t1r, seed_p.data());
    int a_i = bit_msb(alpha, i);
    // keep = R iff a_i; lose is the other side.
    uint8_t* ls0 = a_i ? s0l : s0r;
    uint8_t* ls1 = a_i ? s1l : s1r;
    uint8_t* lv0 = a_i ? v0l : v0r;
    uint8_t* lv1 = a_i ? v1l : v1r;
    uint8_t* ks0 = a_i ? s0r : s0l;
    uint8_t* ks1 = a_i ? s1r : s1l;
    uint8_t* kv0 = a_i ? v0r : v0l;
    uint8_t* kv1 = a_i ? v1r : v1l;
    uint8_t* scw = cw_s + i * lam;
    uint8_t* vcw = cw_v + i * lam;
    for (uint32_t j = 0; j < lam; j++) {
      scw[j] = static_cast<uint8_t>(ls0[j] ^ ls1[j]);
      vcw[j] = static_cast<uint8_t>(lv0[j] ^ lv1[j] ^ v_alpha[j]);
    }
    // beta folds in when the lose side matches the bound (src/lib.rs:114-125):
    // LtBeta on lose==L (a_i==1), GtBeta on lose==R (a_i==0).
    if ((!bound_gt && a_i) || (bound_gt && !a_i)) xor_into(vcw, beta, lam);
    for (uint32_t j = 0; j < lam; j++)
      v_alpha[j] = static_cast<uint8_t>(v_alpha[j] ^ kv0[j] ^ kv1[j] ^ vcw[j]);
    uint8_t t0k = a_i ? t0r : t0l;
    uint8_t t1k = a_i ? t1r : t1l;
    uint8_t tl_cw = static_cast<uint8_t>(t0l ^ t1l ^ a_i ^ 1);
    uint8_t tr_cw = static_cast<uint8_t>(t0r ^ t1r ^ a_i);
    cw_t[i * 2] = tl_cw;
    cw_t[i * 2 + 1] = tr_cw;
    uint8_t t_cw_keep = a_i ? tr_cw : tl_cw;
    for (uint32_t j = 0; j < lam; j++) {
      s_a[j] = static_cast<uint8_t>(ks0[j] ^ (t_a ? scw[j] : 0));
      s_b[j] = static_cast<uint8_t>(ks1[j] ^ (t_b ? scw[j] : 0));
    }
    t_a = static_cast<uint8_t>(t0k ^ (t_a & t_cw_keep));
    t_b = static_cast<uint8_t>(t1k ^ (t_b & t_cw_keep));
  }
  for (uint32_t j = 0; j < lam; j++)
    cw_np1[j] = static_cast<uint8_t>(s_a[j] ^ s_b[j] ^ v_alpha[j]);
}

// ---------------------------------------------------------------------------
// DCF eval (reference src/lib.rs:163-204) for one (key, point) pair.
// ---------------------------------------------------------------------------

void eval_one(const Prg& prg, int b, uint32_t n_bytes, const uint8_t* s0,
              const uint8_t* cw_s, const uint8_t* cw_v, const uint8_t* cw_t,
              const uint8_t* cw_np1, const uint8_t* x, uint8_t* y,
              uint8_t* scratch /* 6*lam bytes */) {
  const uint32_t lam = prg.lam;
  const uint32_t n = 8 * n_bytes;
  uint8_t* s = scratch;
  uint8_t* s_l = scratch + lam;
  uint8_t* v_l = scratch + 2 * lam;
  uint8_t* s_r = scratch + 3 * lam;
  uint8_t* v_r = scratch + 4 * lam;
  uint8_t* seed_p = scratch + 5 * lam;
  std::memcpy(s, s0, lam);
  uint8_t t = static_cast<uint8_t>(b & 1);
  std::memset(y, 0, lam);
  for (uint32_t i = 0; i < n; i++) {
    uint8_t t_l, t_r;
    prg_gen(prg, s, s_l, v_l, s_r, v_r, &t_l, &t_r, seed_p);
    const uint8_t* scw = cw_s + i * lam;
    const uint8_t* vcw = cw_v + i * lam;
    int x_i = bit_msb(x, i);
    uint8_t* s_dir = x_i ? s_r : s_l;
    const uint8_t* v_dir = x_i ? v_r : v_l;
    uint8_t t_dir = x_i ? static_cast<uint8_t>(t_r ^ (t & cw_t[i * 2 + 1]))
                        : static_cast<uint8_t>(t_l ^ (t & cw_t[i * 2]));
    if (t) {
      for (uint32_t j = 0; j < lam; j++)
        y[j] = static_cast<uint8_t>(y[j] ^ v_dir[j] ^ vcw[j]);
      xor_into(s_dir, scw, lam);
    } else {
      xor_into(y, v_dir, lam);
    }
    std::memcpy(s, s_dir, lam);
    t = t_dir;
  }
  if (t) {
    for (uint32_t j = 0; j < lam; j++)
      y[j] = static_cast<uint8_t>(y[j] ^ s[j] ^ cw_np1[j]);
  } else {
    xor_into(y, s, lam);
  }
}

void run_threaded(uint64_t total, int num_threads,
                  const std::function<void(uint64_t, uint64_t)>& fn) {
  if (num_threads <= 1 || total < 2) {
    fn(0, total);
    return;
  }
  uint64_t nt = std::min<uint64_t>(num_threads, total);
  std::vector<std::thread> threads;
  uint64_t chunk = (total + nt - 1) / nt;
  for (uint64_t t = 0; t < nt; t++) {
    uint64_t lo = t * chunk;
    uint64_t hi = std::min(total, lo + chunk);
    if (lo >= hi) break;
    threads.emplace_back(fn, lo, hi);
  }
  for (auto& th : threads) th.join();
}
}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Returns 1 if compiled with AES-NI, else 0 (both paths are bit-exact).
int dcf_has_aesni() { return DCF_HAVE_AESNI; }

// keys: num_keys contiguous 32-byte AES-256 keys.  Uses indices 17*k for
// k < min(2, lam/16) (the reference's truncating loop).  Returns 0 on
// success, negative on contract violation.
int dcf_prg_init(void* prg_out, uint32_t lam, const uint8_t* keys,
                 uint32_t num_keys) {
  if (lam == 0 || lam % 16 != 0) return -1;
  Prg* prg = static_cast<Prg*>(prg_out);
  prg->lam = lam;
  prg->n_enc = lam / 16 < 2 ? lam / 16 : 2;
  for (uint32_t k = 0; k < prg->n_enc; k++) {
    uint32_t idx = 17 * k;
    if (idx >= num_keys) return -2;
    expand_key(keys + 32 * idx, &prg->rk[k]);
  }
  return 0;
}

uint32_t dcf_prg_sizeof() { return sizeof(Prg); }

// Batched PRG (for tests): seeds [B, lam] -> six output arrays.
void dcf_prg_gen_batch(const void* prg_in, uint64_t batch, const uint8_t* seeds,
                       uint8_t* s_l, uint8_t* v_l, uint8_t* t_l, uint8_t* s_r,
                       uint8_t* v_r, uint8_t* t_r) {
  const Prg& prg = *static_cast<const Prg*>(prg_in);
  const uint32_t lam = prg.lam;
  std::vector<uint8_t> seed_p(lam);
  for (uint64_t i = 0; i < batch; i++) {
    prg_gen(prg, seeds + i * lam, s_l + i * lam, v_l + i * lam, s_r + i * lam,
            v_r + i * lam, t_l + i, t_r + i, seed_p.data());
  }
}

// Batched keygen: K keys, outputs in KeyBundle SoA layout (key-major).
void dcf_gen_batch(const void* prg_in, uint32_t num_keys, uint32_t n_bytes,
                   const uint8_t* alphas, const uint8_t* betas,
                   const uint8_t* s0s, int bound_gt, uint8_t* cw_s,
                   uint8_t* cw_v, uint8_t* cw_t, uint8_t* cw_np1,
                   int num_threads) {
  const Prg& prg = *static_cast<const Prg*>(prg_in);
  const uint32_t lam = prg.lam;
  const uint32_t n = 8 * n_bytes;
  run_threaded(num_keys, num_threads, [&](uint64_t lo, uint64_t hi) {
    for (uint64_t k = lo; k < hi; k++) {
      gen_one(prg, n_bytes, alphas + k * n_bytes, betas + k * lam,
              s0s + k * 2 * lam, bound_gt, cw_s + k * static_cast<uint64_t>(n) * lam,
              cw_v + k * static_cast<uint64_t>(n) * lam, cw_t + k * static_cast<uint64_t>(n) * 2,
              cw_np1 + k * lam);
    }
  });
}

// Batched eval: K keys x M points -> ys [K, M, lam].
// xs is [M, n_bytes] when shared_xs != 0, else [K, M, n_bytes].
// s0 is the party-restricted seed array [K, lam].
void dcf_eval_batch(const void* prg_in, int b, uint32_t num_keys,
                    uint32_t n_bytes, uint64_t num_points, const uint8_t* s0,
                    const uint8_t* cw_s, const uint8_t* cw_v,
                    const uint8_t* cw_t, const uint8_t* cw_np1,
                    const uint8_t* xs, int shared_xs, uint8_t* ys,
                    int num_threads) {
  const Prg& prg = *static_cast<const Prg*>(prg_in);
  const uint32_t lam = prg.lam;
  const uint32_t n = 8 * n_bytes;
  const uint64_t total = static_cast<uint64_t>(num_keys) * num_points;
  run_threaded(total, num_threads, [&](uint64_t lo, uint64_t hi) {
    std::vector<uint8_t> scratch(6 * lam);
    for (uint64_t idx = lo; idx < hi; idx++) {
      uint64_t k = idx / num_points;
      uint64_t m = idx % num_points;
      const uint8_t* x = shared_xs ? xs + m * n_bytes
                                   : xs + (k * num_points + m) * n_bytes;
      eval_one(prg, b, n_bytes, s0 + k * lam,
               cw_s + k * static_cast<uint64_t>(n) * lam,
               cw_v + k * static_cast<uint64_t>(n) * lam,
               cw_t + k * static_cast<uint64_t>(n) * 2, cw_np1 + k * lam, x,
               ys + idx * lam, scratch.data());
    }
  });
}

}  // extern "C"
