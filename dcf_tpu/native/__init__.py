"""ctypes bindings for the C++ host core (libdcf.so).

The native core is the role-equivalent of the reference Rust crate itself:
host keygen, and a CPU eval path that serves as (a) the parity oracle and
(b) the single-core/multi-core baseline anchoring the TPU speedup claims.
Built on demand with ``make`` (g++; AES-NI when available, portable S-box
fallback otherwise — bit-exact either way).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings
from typing import Sequence

import numpy as np

from dcf_tpu.errors import (
    BackendFallbackWarning,
    BackendUnavailableError,
    NativeBuildError,
    ShapeError,
)
from dcf_tpu.keys import KeyBundle
from dcf_tpu.spec import Bound, hirose_used_cipher_indices
from dcf_tpu.testing.faults import InjectedFault, fire

__all__ = ["NativeDcf", "build", "load"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIBS: dict = {}  # variant key -> loaded CDLL (each variant opened once)
_FAILED: set = set()  # variant keys whose build/load failed this process:
# without this negative cache every Dcf() on a toolchain-less host would
# re-spawn up to 4 failing `make` subprocesses and re-warn.
_BUILD_ATTEMPTS = 2  # bounded retry: transient toolchain hiccups, not loops


def _sanitize_requested() -> bool:
    """DCF_NATIVE_SANITIZE=1 selects the -Wall -Wextra -Werror + UBSan
    build (``libdcf_sanitize.so``) — the CI ``sanitize`` leg's mode.
    Read at call time so a test can flip it per-process."""
    return os.environ.get("DCF_NATIVE_SANITIZE") == "1"


def build(portable: bool = False) -> str:
    """Compile the shared library if needed; returns its path.

    ``make`` is retried once (a transient failure — interrupted parallel
    build, filesystem race — should not take the native core down); a
    persistent failure raises ``NativeBuildError`` with the captured
    stderr.  Fault seam: ``faults.fire("native.build", portable)``.
    Under ``DCF_NATIVE_SANITIZE=1`` the target is the UBSan build
    regardless of ``portable`` (one instrumented variant; its cipher is
    AES-NI where the host has it, bit-exact either way).
    """
    if _sanitize_requested():
        target = "libdcf_sanitize.so"
    else:
        target = "libdcf_portable.so" if portable else "libdcf.so"
    path = os.path.join(_DIR, target)
    src = os.path.join(_DIR, "dcf_core.cpp")
    rc, err = 0, ""
    for _attempt in range(_BUILD_ATTEMPTS):
        try:
            fire("native.build", portable)
            if os.path.exists(path) \
                    and os.path.getmtime(path) >= os.path.getmtime(src):
                return path
            proc = subprocess.run(
                ["make", "-C", _DIR, target], capture_output=True, text=True
            )
            rc, err = proc.returncode, proc.stderr
        except (OSError, InjectedFault) as e:  # make/fs missing or injected
            rc, err = -1, f"{type(e).__name__}: {e}"
        if rc == 0 and os.path.exists(path):
            return path
    raise NativeBuildError(
        f"native build of {target} failed after {_BUILD_ATTEMPTS} attempts "
        f"(exit {rc}):\n{err}"
    )


def load(portable: bool = False) -> ctypes.CDLL:
    """Load (building if needed) the native core.

    The AES-NI build degrades to the portable S-box build on any
    build/load failure (bit-exact either way, slower cipher), with a
    ``BackendFallbackWarning``; a portable failure is final and raises
    ``NativeBuildError``/``BackendUnavailableError``.  Under
    ``DCF_NATIVE_SANITIZE=1`` any failure is final — silently serving an
    uninstrumented build would defeat the sanitizer leg.  Fault seam:
    ``faults.fire("native.load", portable)``.
    """
    sanitize = _sanitize_requested()
    key = (portable, sanitize)
    lib = _LIBS.get(key)
    if lib is not None:
        return lib
    if key in _FAILED:  # negative cache: warned once already
        if not portable and not sanitize:
            return load(portable=True)
        raise NativeBuildError(
            ("sanitize" if sanitize else "portable") + " native core "
            "unavailable (cached verdict from an earlier failure this "
            "process; see the prior warning)")
    try:
        path = build(portable)
        fire("native.load", portable)
        lib = ctypes.CDLL(path)
    except (NativeBuildError, OSError, InjectedFault) as e:
        _FAILED.add(key)
        if not portable and not sanitize:
            warnings.warn(
                BackendFallbackWarning("native (AES-NI)",
                                       "native (portable S-box)", e),
                stacklevel=2)
            return load(portable=True)
        if isinstance(e, NativeBuildError):
            raise
        raise BackendUnavailableError(
            f"{'sanitize' if sanitize else 'portable'} native core "
            f"failed to load: {e}") from e
    lib.dcf_prg_sizeof.restype = ctypes.c_uint32
    lib.dcf_has_aesni.restype = ctypes.c_int
    lib.dcf_prg_init.restype = ctypes.c_int
    _LIBS[key] = lib
    return lib


def _ptr(a: np.ndarray):
    """Pointer to a's buffer.  CAUTION: holds no reference — the array must
    stay alive (bound to a local) until the foreign call returns."""
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class NativeDcf:
    """DCF gen/eval backed by the C++ core.

    API mirrors the numpy layer: same SoA KeyBundle in, same [K, M, lam]
    arrays out, bit-exact with every other backend.
    """

    def __init__(
        self,
        lam: int,
        cipher_keys: Sequence[bytes],
        num_threads: int | None = None,
        portable: bool = False,
    ):
        hirose_used_cipher_indices(lam, len(cipher_keys))
        if any(len(k) != 32 for k in cipher_keys):
            # api-edge: constructor cipher-key contract
            raise ValueError("all cipher keys must be 32 bytes (AES-256)")
        self.lam = lam
        # Env overrides = the CI feature matrix (serial vs threaded eval,
        # AES-NI vs portable cipher), mirroring the reference's with/without
        # `multithread` cargo matrix.
        env_threads = os.environ.get("DCF_NATIVE_THREADS", "")
        self.num_threads = (
            num_threads
            or (int(env_threads) if env_threads.isdigit() else 0)
            or (os.cpu_count() or 1)
        )
        portable = portable or os.environ.get("DCF_NATIVE_PORTABLE") == "1"
        self._lib = load(portable)
        self._prg = ctypes.create_string_buffer(self._lib.dcf_prg_sizeof())
        keys_arr = np.frombuffer(b"".join(cipher_keys), dtype=np.uint8).copy()
        rc = self._lib.dcf_prg_init(
            self._prg, ctypes.c_uint32(lam), _ptr(keys_arr), len(cipher_keys)
        )
        if rc != 0:
            # api-edge: C-core init rejected the (lam, keys) arguments
            raise ValueError(f"dcf_prg_init failed with code {rc}")

    @property
    def has_aesni(self) -> bool:
        return bool(self._lib.dcf_has_aesni())

    def prg_gen(self, seeds: np.ndarray):
        """Batched PRG; returns the same tuple layout as HirosePrgNp.gen."""
        lam = self.lam
        assert seeds.dtype == np.uint8 and seeds.shape[-1] == lam
        batch = int(np.prod(seeds.shape[:-1]))
        flat = np.ascontiguousarray(seeds).reshape(batch, lam)
        outs = [np.empty((batch, lam), dtype=np.uint8) for _ in range(4)]
        ts = [np.empty(batch, dtype=np.uint8) for _ in range(2)]
        self._lib.dcf_prg_gen_batch(
            self._prg,
            ctypes.c_uint64(batch),
            _ptr(flat),
            _ptr(outs[0]),
            _ptr(outs[1]),
            _ptr(ts[0]),
            _ptr(outs[2]),
            _ptr(outs[3]),
            _ptr(ts[1]),
        )
        shape = seeds.shape[:-1]
        return (
            outs[0].reshape(*shape, lam),
            outs[1].reshape(*shape, lam),
            ts[0].reshape(shape),
            outs[2].reshape(*shape, lam),
            outs[3].reshape(*shape, lam),
            ts[1].reshape(shape),
        )

    def gen_batch(
        self,
        alphas: np.ndarray,
        betas: np.ndarray,
        s0s: np.ndarray,
        bound: Bound,
        num_threads: int | None = None,
    ) -> KeyBundle:
        """Batched keygen; same contract as dcf_tpu.gen.gen_batch."""
        k_num, n_bytes = alphas.shape
        lam = self.lam
        if betas.shape != (k_num, lam) or s0s.shape != (k_num, 2, lam):
            raise ShapeError("alphas/betas/s0s shape mismatch")
        if any(a.dtype != np.uint8 for a in (alphas, betas, s0s)):
            raise ShapeError("alphas/betas/s0s must be uint8")
        n = 8 * n_bytes
        cw_s = np.empty((k_num, n, lam), dtype=np.uint8)
        cw_v = np.empty((k_num, n, lam), dtype=np.uint8)
        cw_t = np.empty((k_num, n, 2), dtype=np.uint8)
        cw_np1 = np.empty((k_num, lam), dtype=np.uint8)
        # Keep contiguous copies alive across the foreign call (see _ptr).
        alphas_c = np.ascontiguousarray(alphas)
        betas_c = np.ascontiguousarray(betas)
        s0s_c = np.ascontiguousarray(s0s)
        self._lib.dcf_gen_batch(
            self._prg,
            ctypes.c_uint32(k_num),
            ctypes.c_uint32(n_bytes),
            _ptr(alphas_c),
            _ptr(betas_c),
            _ptr(s0s_c),
            ctypes.c_int(1 if bound is Bound.GT_BETA else 0),
            _ptr(cw_s),
            _ptr(cw_v),
            _ptr(cw_t),
            _ptr(cw_np1),
            ctypes.c_int(num_threads or self.num_threads),
        )
        return KeyBundle(
            s0s=s0s_c.copy(), cw_s=cw_s, cw_v=cw_v, cw_t=cw_t, cw_np1=cw_np1
        )

    def eval(
        self,
        b: int,
        bundle: KeyBundle,
        xs: np.ndarray,
        num_threads: int | None = None,
    ) -> np.ndarray:
        """Batched eval; same contract as eval_batch_np (xs 2D = shared).

        ``bundle`` may be the full two-party bundle (restricted to party
        ``b`` here — previously s0s[:, 0] was read unconditionally, which
        silently ran party 1's walk with party 0's seed) or an
        already-restricted ``bundle.for_party(b)``.
        """
        if bundle.s0s.shape[1] == 2:
            bundle = bundle.for_party(b)
        k_num, n, lam = bundle.cw_s.shape
        if lam != self.lam:
            raise ShapeError("bundle lam mismatch")
        if xs.dtype != np.uint8:
            raise ShapeError("xs must be uint8")
        shared = xs.ndim == 2
        m = xs.shape[0] if shared else xs.shape[1]
        if (shared and xs.shape[1] * 8 != n) or (
            not shared and (xs.shape[0] != k_num or xs.shape[2] * 8 != n)
        ):
            raise ShapeError("xs shape mismatch with bundle")
        ys = np.empty((k_num, m, lam), dtype=np.uint8)
        # Keep contiguous copies alive across the foreign call (see _ptr).
        s0_c = np.ascontiguousarray(bundle.s0s[:, 0, :])
        cw_s_c = np.ascontiguousarray(bundle.cw_s)
        cw_v_c = np.ascontiguousarray(bundle.cw_v)
        cw_t_c = np.ascontiguousarray(bundle.cw_t)
        cw_np1_c = np.ascontiguousarray(bundle.cw_np1)
        xs_c = np.ascontiguousarray(xs)
        self._lib.dcf_eval_batch(
            self._prg,
            ctypes.c_int(b),
            ctypes.c_uint32(k_num),
            ctypes.c_uint32(n // 8),
            ctypes.c_uint64(m),
            _ptr(s0_c),
            _ptr(cw_s_c),
            _ptr(cw_v_c),
            _ptr(cw_t_c),
            _ptr(cw_np1_c),
            _ptr(xs_c),
            ctypes.c_int(1 if shared else 0),
            _ptr(ys),
            ctypes.c_int(num_threads or self.num_threads),
        )
        return ys
