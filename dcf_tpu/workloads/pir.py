"""2-server PIR over DPF full-domain evaluation (EvalAll).

The textbook construction (Boyle-Gilboa-Ishai): a client who wants
record ``alpha`` of a database both servers hold splits a DPF for the
point function ``f(alpha) = 1`` into two keys and sends one to each
server.  Each server EvalAll's its key over the whole domain — the leaf
t-bits are an XOR sharing of the one-hot selection vector — takes the
inner product with the database over GF(2), and returns its
``record_bytes`` answer share.  XOR of the two shares is the record;
each server alone saw only a pseudorandom key, so neither learns
``alpha``.  Every query touches the whole database (information
-theoretically necessary), which is why the EvalAll kernel's
~2^{n+1}-PRG-call cost IS the query cost and the per-leaf throughput of
``backends.evalall`` is the number that matters.

Layout contract: ``PirDatabase`` packs records in bitreverse_n order —
the order EvalAll emits leaves in — as GF(2) bit-plane lane words, so
the inner product is ``popcount(t_word & db_plane) mod 2`` per database
bit plane with no gather anywhere: leaf position p of the t-planes and
packed-record position p refer to the same domain point, and the hit at
position bitreverse_n(alpha) selects exactly ``db[alpha]``.

Serving: ``PirServer`` snapshots DPF bundles from a ``KeyRegistry``
(they arrive over the ring as DCFK v3 ``proto=2`` frames —
``PodRouter.register_key`` / ``serve.replicate``), keeps the staged key
image and selection shares resident across queries (ship-once), and
answers per party with the same ``serve.eval`` fault seam + bounded
retry/evict discipline as the point-batch service: an injected eval
fault evicts the possibly-poisoned staged state and retries from the
registry snapshot.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.protocols.dpf import DpfBundle, dpf_gen_batch
from dcf_tpu.testing.faults import fire
from dcf_tpu.utils.bits import bits_lsb_to_bytes, byte_bits_lsb, pack_lanes

__all__ = [
    "PirDatabase",
    "PirServer",
    "pir_answer_share",
    "pir_query_bundle",
    "pir_reconstruct",
]


def _bitrev_values(n_bits: int) -> np.ndarray:
    """Domain value of each bitreverse-order position: value[p] =
    bitreverse_n(p) — the EvalAll leaf-order map."""
    pos = np.arange(1 << n_bits, dtype=np.uint64)
    value = np.zeros(1 << n_bits, dtype=np.uint64)
    for k in range(n_bits):
        value |= ((pos >> np.uint64(k)) & np.uint64(1)) << np.uint64(
            n_bits - 1 - k)
    return value


class PirDatabase:
    """The resident GF(2) bit-plane image of a 2^n-record database.

    ``records`` uint8 [2^n_bits, record_bytes] is permuted to
    bitreverse_n order and packed to int32 lane words
    [8 * record_bytes, 2^n_bits / 32]: plane r, word w, bit i holds bit
    r of the record at leaf position 32*w + i.  Packed once, resident
    for the server's lifetime — queries only read it.  The plaintext
    array is NOT retained (both PIR servers legitimately know the
    database; holding a second copy is just memory).
    """

    def __init__(self, records: np.ndarray, n_bits: int):
        records = np.asarray(records)
        if records.dtype != np.uint8 or records.ndim != 2:
            raise ShapeError(
                f"records must be uint8 [num_records, record_bytes], got "
                f"{records.dtype} {records.shape}")
        if n_bits < 5:
            # api-edge: leaf planes are 32-leaf lane words, so the
            # domain must fill at least one (the DPF key domain is
            # byte-granular, but the database domain is not: a depth-d
            # prefix evaluation of a deeper key serves any d >= 5 —
            # see pir_query_bundle)
            raise ValueError(f"n_bits={n_bits} must be >= 5")
        if records.shape[0] != 1 << n_bits:
            raise ShapeError(
                f"{records.shape[0]} records do not fill the 2^{n_bits} "
                "domain; pad with zero records — PIR touches every "
                "record, so the domain must be exact")
        import jax.numpy as jnp

        self.n_bits = int(n_bits)
        self.record_bytes = int(records.shape[1])
        self.num_records = int(records.shape[0])
        db_br = records[_bitrev_values(n_bits)]  # leaf order
        bits = byte_bits_lsb(db_br)  # [N, 8R]
        self.planes = jnp.asarray(pack_lanes(
            np.ascontiguousarray(bits.T)).view(np.int32))  # [8R, N/32]

    def __repr__(self) -> str:
        return (f"PirDatabase(n_bits={self.n_bits}, "
                f"record_bytes={self.record_bytes})")


_answer_fn = None


def _pir_answer_device(t_words, planes):
    global _answer_fn
    if _answer_fn is None:
        import jax
        import jax.numpy as jnp

        def f(t_words, planes):
            x = jax.lax.bitcast_convert_type(
                t_words[:, 0][:, None, :] & planes[None], jnp.uint32)
            ones = jax.lax.population_count(x)  # [K, 8R, W]
            return jnp.sum(ones.astype(jnp.uint32),
                           axis=-1) & jnp.uint32(1)  # [K, 8R] parities

        _answer_fn = jax.jit(f)
    return _answer_fn(t_words, planes)


def pir_answer_share(t_words, db: PirDatabase) -> np.ndarray:
    """One party's answer shares from its selection-vector share.

    ``t_words``: the leaf t-bit lane words int32 [K, 1, 2^n / 32] that
    ``DpfEvalAll.eval_party`` returns (bitreverse order, matching the
    database packing).  Inner product over GF(2) per database bit plane
    — ``popcount(t & plane) mod 2`` — entirely on device; only the
    K x record_bytes answer comes back.  uint8 [K, record_bytes].
    """
    if t_words.shape[-1] * 32 != db.num_records:
        raise ShapeError(
            f"selection share covers {t_words.shape[-1] * 32} leaves, "
            f"database has {db.num_records} records")
    parity = np.asarray(_pir_answer_device(t_words, db.planes))
    return bits_lsb_to_bytes(parity)


def pir_query_bundle(prg, indices, n_bits: int, s0s: np.ndarray,
                     betas: np.ndarray | None = None) -> DpfBundle:
    """Client-side query keygen: one DPF key pair per record index.

    ``indices``: the K record indices being retrieved (each in
    [0, 2^n_bits)); ``s0s`` uint8 [K, 2, lam]: fresh random root seeds
    — the client's secret randomness, caller-supplied like every keygen
    in this repo (key material is never silently minted).  ``betas``
    defaults to the all-ones payload; the PIR answer path reads only
    the leaf t-bits, so the payload never matters to retrieval — it
    exists so the same bundle can also drive payload-carrying
    ``eval_party`` uses and the reconstruction self-check.

    The DCFK wire domain is byte-granular but the database domain need
    not be: for ``n_bits`` that is not a multiple of 8 the key is
    generated over the next byte-granular domain with the index in the
    TOP ``n_bits`` (``alpha = index << pad``), and servers evaluate
    only ``n_bits`` levels deep — the depth-d t-planes are the one-hot
    indicator of alpha's d-bit prefix, i.e. exactly the selection
    vector (``DpfEvalAll.eval_party`` prefix contract).
    """
    idx = [int(i) for i in np.asarray(indices).reshape(-1)]
    n_key = 8 * ((n_bits + 7) // 8)  # wire (key) domain, byte-granular
    pad = n_key - n_bits
    for i in idx:
        if not 0 <= i < (1 << n_bits):
            # api-edge: query contract at the client edge
            raise ValueError(
                f"record index {i} outside the 2^{n_bits}-record "
                "database")
    alphas = np.array(
        [list((i << pad).to_bytes(n_key // 8, "big")) for i in idx],
        dtype=np.uint8)
    if betas is None:
        betas = np.full((len(idx), s0s.shape[-1]), 0xFF, dtype=np.uint8)
    return dpf_gen_batch(prg, alphas, betas, s0s)


def pir_reconstruct(a0: np.ndarray, a1: np.ndarray) -> np.ndarray:
    """Client-side XOR reconstruction of the two answer shares."""
    if a0.shape != a1.shape:
        raise ShapeError(
            f"answer shares disagree on shape: {a0.shape} vs {a1.shape}")
    return (np.asarray(a0) ^ np.asarray(a1)).astype(np.uint8)


class PirServer:
    """One 2-server-PIR server over the serving tier's key plumbing.

    ``registry``: anything with ``snapshot(key_id) -> (bundle,
    protocol, generation)`` — in practice a ``serve.KeyRegistry`` the
    DPF bundles reached as DCFK v3 ``proto=2`` frames through
    ``PodRouter.register_key`` / store restore.  The server serves BOTH
    parties (same contract as ``DcfService``): ``answer(key_id, b)``
    returns party ``b``'s uint8 [K, record_bytes] answer shares.

    Unlike the point-batch service, a PIR query has no input points —
    the key IS the query — so the server keeps its own full-domain
    evaluator (``backends.evalall.DpfEvalAll``) instead of a staged
    point backend, and caches each key's selection-vector shares per
    (key_id, party, generation): repeat queries under the same key
    re-run only the device inner product.  The ``serve.eval`` fault
    seam fires per answer with bounded retry; a faulted attempt evicts
    both the selection cache entry and the evaluator's staged image
    before retrying from the registry snapshot, so a poisoned
    device residency cannot serve the retry (the service's
    retry-then-evict discipline, transplanted).
    """

    def __init__(self, evaluator, db: PirDatabase, registry, *,
                 retries: int = 1):
        if retries < 0:
            # api-edge: retry contract (0 = single attempt)
            raise ValueError(f"retries must be >= 0, got {retries}")
        self.evaluator = evaluator
        self.db = db
        self.registry = registry
        self.retries = int(retries)
        self.eval_faults = 0  # attempts lost to the serve.eval seam
        self._sel: dict = {}  # (key_id, b) -> (generation, t_words)

    def _selection(self, key_id: str, b: int, bundle: DpfBundle,
                   generation: int):
        ent = self._sel.get((key_id, b))
        if ent is not None and ent[0] == generation:
            return ent[1]
        staged_cw, fronts, parts = self.evaluator._staged_for(
            bundle, self.db.n_bits)
        _y0, _y1, t = self.evaluator.eval_party(
            b, parts[b], self.db.n_bits, staged_cw, fronts[b])
        self._sel[(key_id, b)] = (generation, t)
        return t

    def answer(self, key_id: str, b: int) -> np.ndarray:
        """Party ``b``'s answer shares for the K queries registered
        under ``key_id``: uint8 [K, record_bytes]."""
        if b not in (0, 1):
            # api-edge: party selector contract at the serve edge
            raise ValueError(f"party must be 0 or 1, got {b}")
        bundle, _protocol, generation = self.registry.snapshot(key_id)
        if not isinstance(bundle, DpfBundle):
            raise ShapeError(
                f"key {key_id!r} is a {type(bundle).__name__}, not the "
                "DpfBundle a PIR query needs — register the query "
                "through the DPF keygen path")
        if bundle.n_bits < self.db.n_bits:
            raise ShapeError(
                f"key {key_id!r} walks a {bundle.n_bits}-bit domain, "
                f"too shallow for 2^{self.db.n_bits} records (deeper "
                "keys are fine: the selection vector is a depth-"
                f"{self.db.n_bits} prefix evaluation)")
        last: Exception | None = None
        for _attempt in range(self.retries + 1):
            try:
                fire("serve.eval", key_id, bundle.num_keys)
                t = self._selection(key_id, b, bundle, generation)
                return pir_answer_share(t, self.db)
            except Exception as e:  # fallback-ok: counted, bounded
                # retry below; exhaustion re-raises the last error
                last = e
                self.eval_faults += 1
                self._sel.pop((key_id, b), None)
                self.evaluator.invalidate()
        raise last  # retries exhausted — typed cause intact
