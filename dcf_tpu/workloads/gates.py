"""Served fixed-point gates: protocols.fixedpoint through DcfService.

A gate is a composition of protocol bundles plus public scalars
(``protocols.fixedpoint`` derivations), so SERVING one is pure
registration plumbing: each component ``ProtocolBundle`` registers in
a ``DcfService`` under a derived key id (``<gate_id>/wrap`` etc.,
DCFK v4 frames through the registry / durable store / replication like
any protocol key), and a gate evaluation submits the PUBLIC masked
points to every component and folds the resulting additive shares
client-side — lane adds and a group-sum reduce, no extra crypto.  The
device never learns it is running a gate; it sees K-packed interval
bundles, which is the whole point of the composition.

Fault discipline is inherited, not reimplemented: each component
submit rides the service's admission / deadline / retry-then-evict
machinery, and an injected ``protocols.combine`` fault surfaces as the
service retrying that component batch from the registry snapshot (the
gate soak test drives exactly that path).

``GateServer`` holds one service per domain: the main service for the
gate's w-bit domain and (for truncation) a second service over the
f-bit low half — two facades, two batchers, one registry discipline.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.protocols.fixedpoint import (
    SigmoidGate,
    SignGate,
    TruncGate,
    encode_lanes,
    points_of,
)
from dcf_tpu.utils.groups import np_group_add, np_group_reduce

__all__ = ["GateServer"]


class GateServer:
    """Serve registered fixed-point gates through ``DcfService``.

    ``svc``: the service whose facade matches the gates' full domain;
    ``svc_low``: the f-bit-domain service truncation gates need (the
    two facades must share ``lam``; ``svc_low`` may be omitted when no
    truncation gate is registered).  ``register`` accepts the DEALER
    (two-party) gate objects — the per-party material ships through
    the service's registry exactly like any protocol key, so both
    parties of the 2PC are served off registry snapshots, mirroring
    ``workloads.pir.PirServer``.
    """

    def __init__(self, svc, svc_low=None):
        self._svc = svc
        self._svc_low = svc_low
        self._gates: dict[str, object] = {}

    # -- registration --------------------------------------------------

    def register(self, gate_id: str, gate) -> None:
        """Register one gate's component bundles under derived ids.

        Re-registering a gate_id hot-swaps every component atomically
        enough for the gate's purposes: component ids are derived, so
        a swapped gate never mixes generations ACROSS gate ids."""
        if isinstance(gate, SignGate):
            self._svc.register_key(f"{gate_id}/sign", gate.pb)
        elif isinstance(gate, TruncGate):
            if self._svc_low is None:
                # api-edge: documented server contract
                raise ShapeError(
                    "truncation gates need the low-domain service: "
                    "construct GateServer(svc, svc_low)")
            if self._svc_low.n_bytes != gate.f // 8:
                # api-edge: documented server contract
                raise ShapeError(
                    f"svc_low serves n_bytes={self._svc_low.n_bytes} "
                    f"but gate f={gate.f} wants {gate.f // 8}")
            self._svc.register_key(f"{gate_id}/wrap", gate.pb_wrap)
            self._svc_low.register_key(f"{gate_id}/low", gate.pb_low)
        elif isinstance(gate, SigmoidGate):
            self._svc.register_key(f"{gate_id}/spline", gate.pb)
        else:
            # api-edge: documented server contract
            raise ShapeError(
                f"not a servable gate: {type(gate).__name__}")
        self._gates[gate_id] = gate

    def gate(self, gate_id: str):
        """The registered dealer gate object (oracle parameters live
        here — e.g. the sigmoid's public table)."""
        return self._gates[gate_id]

    # -- served evaluation ---------------------------------------------

    def eval_share(self, gate_id: str, b: int, x_hat,
                   deadline_ms: float | None = None) -> np.ndarray:
        """Party ``b``'s gate share uint8 [M, lam] via the SERVED path.

        ``x_hat``: public masked inputs, int array [M].  Component
        submits are issued concurrently (futures), then folded
        client-side in the gate's group."""
        try:
            gate = self._gates[gate_id]
        except KeyError:
            # api-edge: documented server contract
            raise ShapeError(f"no gate registered as {gate_id!r}") \
                from None
        x_int = np.asarray(x_hat)
        n_bytes = self._svc.n_bytes
        xs = points_of(x_int, n_bytes)
        group = gate.group
        if isinstance(gate, SignGate):
            rows = self._svc.submit(f"{gate_id}/sign", xs, b=b,
                                    deadline_ms=deadline_ms).result()
            return rows[0]
        if isinstance(gate, TruncGate):
            xs_low = np.ascontiguousarray(
                xs[:, n_bytes - gate.f // 8:])
            f_wrap = self._svc.submit(f"{gate_id}/wrap", xs, b=b,
                                      deadline_ms=deadline_ms)
            f_low = self._svc_low.submit(f"{gate_id}/low", xs_low, b=b,
                                         deadline_ms=deadline_ms)
            y = np_group_add(f_wrap.result()[0], f_low.result()[0],
                             group)
            y = np_group_add(y, gate.const_for(b)[None, :], group)
            if b == 0:
                pub = ((x_int.astype(np.uint64)
                        & np.uint64((1 << (8 * n_bytes)) - 1))
                       >> np.uint64(gate.f)).astype(np.int64)
                y = np_group_add(
                    y, encode_lanes(pub, group, y.shape[1]), group)
            return y
        # SigmoidGate
        rows = self._svc.submit(f"{gate_id}/spline", xs, b=b,
                                deadline_ms=deadline_ms).result()
        return np_group_reduce(rows, group, axis=0)

    def reconstruct(self, gate_id: str, x_hat,
                    deadline_ms: float | None = None) -> np.ndarray:
        """Both parties' served shares, group-added: uint8 [M, lam].
        (Test/bench convenience — a real deployment's parties never
        meet like this.)"""
        y0 = self.eval_share(gate_id, 0, x_hat, deadline_ms)
        y1 = self.eval_share(gate_id, 1, x_hat, deadline_ms)
        return np_group_add(y0, y1, self._gates[gate_id].group)
