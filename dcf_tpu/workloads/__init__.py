"""Reference and extension workloads as runnable functions.

``workloads.core`` holds the BASELINE.json reference configs (config 3
full-domain check, config 5 secure-ReLU); ``workloads.pir`` holds the
2-server PIR workload built on the DPF EvalAll subsystem (the served
selection-vector inner product); ``workloads.gates`` serves the
fixed-point gate suite (``protocols.fixedpoint``) through
``DcfService``.  Everything re-exports here, so
``from dcf_tpu.workloads import full_domain_check`` keeps working from
the flat-module days.
"""

from dcf_tpu.workloads.core import (  # noqa: F401
    domain_points,
    full_domain_check,
    full_domain_check_device,
    secure_relu_check_device,
    secure_relu_eval,
)
from dcf_tpu.workloads.gates import GateServer  # noqa: F401
from dcf_tpu.workloads.pir import (  # noqa: F401
    PirDatabase,
    PirServer,
    pir_answer_share,
    pir_query_bundle,
    pir_reconstruct,
)

__all__ = [
    "GateServer",
    "PirDatabase",
    "PirServer",
    "domain_points",
    "full_domain_check",
    "full_domain_check_device",
    "pir_answer_share",
    "pir_query_bundle",
    "pir_reconstruct",
    "secure_relu_check_device",
    "secure_relu_eval",
]
