"""Reference workloads (BASELINE.json configs) as runnable functions.

- ``domain_points`` / ``full_domain_check`` — config 3: full-domain
  evaluation at n bits with two-party XOR reconstruction verified against
  the plain comparison function, streamed in chunks so n=24 (16.7M points)
  runs in bounded memory.
- ``secure_relu_eval`` — config 5: the many-keys x few-points shape
  (10^6 keys x 10^3 points).  In FSS-based secure inference a ReLU/MSB
  gate consumes one DCF evaluation per wire per input; the workload is
  exactly a huge batch of independent DCF evals, which is why it scales as
  a pure map over (keys x points).  Uses the keys-in-lanes backend.
  Since the protocols PR it is a thin client of
  ``dcf_tpu.protocols.combine.xor_reconstruct_stream`` (the protocol
  layer's generic streamed two-party reconstruction) — same kernels,
  same chunk loop, one shared implementation.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.keys import KeyBundle

__all__ = [
    "domain_points",
    "full_domain_check",
    "full_domain_check_device",
    "secure_relu_check_device",
    "secure_relu_eval",
]


def domain_points(n_bytes: int, start: int, count: int) -> np.ndarray:
    """Points start..start+count-1 as big-endian uint8 [count, n_bytes]."""
    idx = np.arange(start, start + count, dtype=np.uint64)
    shifts = (8 * np.arange(n_bytes - 1, -1, -1)).astype(np.uint64)
    return ((idx[:, None] >> shifts) & np.uint64(0xFF)).astype(np.uint8)


def full_domain_check(
    eval0: Callable[[np.ndarray], np.ndarray],
    eval1: Callable[[np.ndarray], np.ndarray],
    alpha: int,
    beta: bytes,
    n_bits: int,
    gt: bool = False,
    chunk: int = 1 << 18,
) -> int:
    """Evaluate both parties over the whole 2^n_bits domain in chunks and
    verify XOR reconstruction equals the comparison function everywhere.

    eval_b(xs uint8 [M, n_bytes]) -> uint8 [1, M, lam] (or [K, M, lam]; key 0
    is checked).  Returns the number of mismatching points (0 = pass).
    """
    n_bytes = n_bits // 8
    lam = len(beta)
    beta_arr = np.frombuffer(beta, dtype=np.uint8)
    zero = np.zeros(lam, dtype=np.uint8)
    total = 1 << n_bits
    mismatches = 0
    for start in range(0, total, chunk):
        count = min(chunk, total - start)
        xs = domain_points(n_bytes, start, count)
        recon = (eval0(xs)[0] ^ eval1(xs)[0]).astype(np.uint8)  # [count, lam]
        idx = np.arange(start, start + count)
        inside = (idx > alpha) if gt else (idx < alpha)
        expect = np.where(inside[:, None], beta_arr[None, :], zero[None, :])
        mismatches += int(np.count_nonzero(np.any(recon != expect, axis=1)))
    return mismatches


def full_domain_check_device(
    backend0,
    backend1,
    alpha: int,
    beta: bytes,
    n_bits: int,
    gt: bool = False,
    chunk: int = 1 << 20,
) -> int:
    """Config 3 on the staged-backend protocol, fully device-resident.

    Unlike ``full_domain_check``, neither the 2^n_bits input points nor the
    2 x 2^n_bits x lam output shares ever touch the host: each chunk's
    points are generated from an iota inside the jitted program
    (``stage_range``), both parties evaluate on device, and the XOR
    reconstruction is compared against the plain comparison function on
    device too (``mismatch_count``) — only the per-chunk mismatch counter
    is fetched.  backend0/backend1: staged-protocol backends
    (PallasBackend / BitslicedBackend) holding the two party bundles for
    ONE key.  Returns the number of mismatching points (0 = pass).
    """
    total = 1 << n_bits
    chunk = min(chunk, total)
    if total % chunk != 0:
        raise ShapeError(f"chunk {chunk} must divide the domain {total}")
    # Per-chunk counters stay on device and are summed there; the single
    # final fetch keeps the chunk loop free of host round-trips (the dev
    # tunnel costs ~85ms each).
    import jax.numpy as jnp

    counters = []
    for start in range(0, total, chunk):
        staged = backend0.stage_range(start, chunk)
        y0 = backend0.eval_staged(0, staged)
        y1 = backend1.eval_staged(1, staged)
        counters.append(
            backend0.mismatch_count(y0, y1, alpha, beta, start, gt))
    return int(jnp.sum(jnp.stack(counters)))


def secure_relu_check_device(
    lam: int,
    cipher_keys,
    alphas: np.ndarray,
    betas: np.ndarray,
    s0s: np.ndarray,
    xs: np.ndarray,
    key_chunk: int = 1 << 16,
    interpret: bool = False,
    level_chunk: int = 8,
    kw_tile: int = 128,
) -> int:
    """Config 5 fully device-resident: keygen, two-party eval, and
    verification all on the accelerator, streaming over key chunks.

    DeviceKeyGen writes each chunk's packed CW image straight into HBM (the
    host ships only alphas/betas/seeds/xs), KeyLanesPallasBackend walks it,
    and the XOR reconstruction is compared on device against
    `beta_k if x_m < alpha_k else 0`.  Chunks are zero-padded to the
    kernel's key granule (32 * kw_tile); pad keys are real alpha=0/beta=0
    keys whose expected reconstruction is 0, so they cannot contribute
    false passes.  Returns total mismatching (key, point) pairs (0 = pass).
    """
    from dcf_tpu.backends.device_gen import DeviceKeyGen
    from dcf_tpu.backends.pallas_keylanes import KeyLanesPallasBackend
    from dcf_tpu.spec import Bound

    import jax.numpy as jnp

    k = alphas.shape[0]
    gen = DeviceKeyGen(lam, cipher_keys)
    be = KeyLanesPallasBackend(
        lam, cipher_keys, kw_tile=kw_tile, level_chunk=level_chunk,
        interpret=interpret)
    granule = 32 * kw_tile
    counters = []
    staged = None
    for lo in range(0, k, key_chunk):
        hi = min(k, lo + key_chunk)
        pad = -(hi - lo) % granule
        ap = np.pad(alphas[lo:hi], [(0, pad), (0, 0)])
        bp = np.pad(betas[lo:hi], [(0, pad), (0, 0)])
        sp = np.pad(s0s[lo:hi], [(0, pad), (0, 0), (0, 0)])
        dev = gen.gen(ap, bp, sp, Bound.LT_BETA)
        be.put_bundle_device(dev)
        if staged is None:
            staged = be.stage(xs)
        y0 = be.eval_staged(0, staged)
        y1 = be.eval_staged(1, staged)
        counters.append(be.relu_mismatch_count(y0, y1, ap, bp, xs))
    return int(jnp.sum(jnp.stack(counters)))


def secure_relu_eval(
    backend0,
    backend1,
    bundle: KeyBundle,
    xs: np.ndarray,
    key_chunk: int = 1 << 16,
) -> np.ndarray:
    """Config 5: evaluate K keys on M shared points, both parties, and
    return the XOR reconstruction uint8 [K, M, lam], streaming over keys.

    backend0/backend1: KeyLanesBackend-compatible evaluators (put_bundle +
    eval).  A thin client of the protocol layer since the protocols PR:
    the streamed two-party reconstruction lives in
    ``protocols.combine.xor_reconstruct_stream`` (the generic primitive
    IC/MIC/piecewise tests and benches share); this wrapper only keeps
    the workload's name and signature.  Keys stream through the device
    in ``key_chunk`` slices — the full 10^6-key image does not need to
    be HBM-resident at once.
    """
    from dcf_tpu.protocols.combine import xor_reconstruct_stream

    return xor_reconstruct_stream(
        backend0, backend1, bundle, xs, key_chunk=key_chunk)
