"""Fixed-point gates over the additive output group (ISSUE 20).

The three served gates — signed comparison, faithful truncation and
spline sigmoid — are all instances of ONE reduction, the masked-input
model standard in FSS-based secure computation (Boyle et al.'s gate
constructions): the dealer samples a secret mask ``r``, the parties
learn only the masked input ``x_hat = x + r mod 2^w`` (public), and
every secret predicate "x in [A, B)" becomes the PUBLIC-input predicate
"x_hat in [A + r, B + r) mod 2^w" — a wraparound interval, which the
protocol layer's IC/MIC machinery expresses natively (the combine-mask
correction absorbs the wrap).  So a gate is nothing but interval keys
with r-shifted bounds, evaluated in an additive output group so the
per-party outputs are ARITHMETIC shares that compose by lane addition:

* signed comparison (``gen_sign_gate``): x < 0 in w-bit two's
  complement iff x in [2^{w-1}, 2^w), i.e. x_hat in
  [2^{w-1} + r, r) mod 2^w — one IC bundle, nothing else.

* faithful truncation (``gen_trunc_gate``): with x = x_hat - r + 2^w c,
  c = [x_hat < r], and splitting low/high f-bit halves
  (x_hat = 2^f h + l, r = 2^f h_r + l_r):

      (x >> f)  =  h - h_r - [l < l_r] + 2^{w-f} c      (mod 2^w)

  ``h`` is public (party 0 contributes it), ``-h_r`` is dealt as
  additive scalar shares, and the two bracket terms are ICs over
  PREFIX intervals [0, l_r) (f-bit domain, payload -1) and [0, r)
  (full domain, payload +2^{w-f}) — prefix intervals because the
  mask r shifted them to start at 0.  f must be a multiple of 8:
  the DCF domain is byte-granular, so the low half must be a whole
  byte suffix of the point encoding.

* spline sigmoid (``gen_sigmoid_gate``): a piecewise-constant sigma
  table (``sigmoid_table``) is a MIC over a partition; shifting every
  cut by r keeps it a partition, and the group-sum reduce of the MIC
  rows telescopes to additive shares of the containing piece's value
  (``protocols.piecewise`` derivation).  The table itself is public.

Everything here is integer math on uint8 payload arrays — the dealer's
sigma table is computed with scalar ``math.exp`` and rounded to fixed
point before any ndarray exists, so no float dtype ever touches the
share paths (dcflint crypto-dtype enforces this module).  Golden
oracles (``sign_oracle``/``trunc_oracle``/``sigmoid_fixed_oracle``)
compute the same functions on the CLEAR input; every gate test and the
``gate_bench`` parity gate compares reconstructions against them
bit-exactly.

Served form: each gate's component bundles register in
``Dcf.serve``/``KeyRegistry`` like any protocol key —
``workloads.gates`` wires that path.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Sequence

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.protocols.keygen import ProtocolBundle
from dcf_tpu.protocols.piecewise import partition_intervals
from dcf_tpu.spec import GROUP_WIDTH, check_group
from dcf_tpu.utils.groups import (
    bytes_of,
    lane_dtype,
    lanes_of,
    np_group_add,
    np_group_reduce,
)

__all__ = [
    "SignGate",
    "TruncGate",
    "SigmoidGate",
    "gen_sign_gate",
    "gen_trunc_gate",
    "gen_sigmoid_gate",
    "eval_sign_share",
    "eval_trunc_share",
    "eval_sigmoid_share",
    "encode_lanes",
    "decode_lanes",
    "points_of",
    "gate_reconstruct",
    "sigmoid_table",
    "sign_oracle",
    "trunc_oracle",
    "sigmoid_fixed_oracle",
]


# -- lane/point codecs -------------------------------------------------

def _additive(group: str, lam: int) -> int:
    """Validate an ADDITIVE group for a gate and return its width."""
    check_group(group, lam)
    if group == "xor":
        # api-edge: documented gate contract — gates need arithmetic
        raise ShapeError(
            "fixed-point gates need an additive output group "
            "(add8/add16/add32); XOR shares have no carry to fold the "
            "gate algebra into")
    return GROUP_WIDTH[group]


def encode_lanes(vals, group: str, lam: int) -> np.ndarray:
    """Integers -> payload bytes, each value broadcast to EVERY w-bit
    lane of the lam-byte payload (so any single lane reconstructs the
    gate output; ``decode_lanes`` reads lane 0).

    ``vals``: int scalar or integer array [...]; values are reduced
    mod 2^w.  Returns uint8 [..., lam].  Rejects inexact dtypes — a
    rounded share is a silently-wrong share, so fixed-point encoding
    must happen BEFORE values enter this layer.
    """
    w = _additive(group, lam)
    vals = np.asarray(vals)
    if not np.issubdtype(vals.dtype, np.integer):
        # api-edge: crypto-dtype contract at the gate boundary
        raise ShapeError(
            f"encode_lanes wants integer values, got dtype {vals.dtype}; "
            "quantize to fixed point before encoding")
    n_lanes = 8 * lam // w
    lanes = (vals.astype(object) % (1 << w))  # exact for any int width
    lanes = np.asarray(lanes, dtype=np.uint64).astype(lane_dtype(group))
    lanes = np.broadcast_to(lanes[..., None],
                            vals.shape + (n_lanes,))
    return bytes_of(np.ascontiguousarray(lanes), group)


def decode_lanes(payload: np.ndarray, group: str) -> np.ndarray:
    """Payload bytes uint8 [..., lam] -> int64 lane-0 values [...].

    Gate outputs broadcast one value to every lane (``encode_lanes``),
    so lane 0 is the canonical read of a reconstruction."""
    return lanes_of(np.asarray(payload, dtype=np.uint8),
                    group)[..., 0].astype(np.int64)


def points_of(vals, n_bytes: int) -> np.ndarray:
    """Integers [M] -> big-endian evaluation points uint8 [M, n_bytes]
    (the DCF point encoding — MSB first, matching the spec walk)."""
    vals = np.asarray(vals)
    if not np.issubdtype(vals.dtype, np.integer):
        # api-edge: crypto-dtype contract at the gate boundary
        raise ShapeError(
            f"points_of wants integer inputs, got dtype {vals.dtype}")
    v = vals.astype(np.uint64) & np.uint64((1 << (8 * n_bytes)) - 1)
    shifts = np.arange(8 * (n_bytes - 1), -1, -8, dtype=np.uint64)
    return ((v[..., None] >> shifts) & np.uint64(0xFF)).astype(np.uint8)


def gate_reconstruct(y0: np.ndarray, y1: np.ndarray,
                     group: str) -> np.ndarray:
    """Group-add the two parties' gate shares and decode: int64 [...]."""
    return decode_lanes(np_group_add(y0, y1, group), group)


# -- signed comparison -------------------------------------------------

@dataclass(frozen=True)
class SignGate:
    """One signed-comparison gate: additive shares of
    ``beta * [x < 0]`` from the public masked input x_hat.

    Wraps the single r-shifted IC bundle; ``for_party`` restricts it
    for shipping (DCFK v4 on the wire via ``pb.to_bytes``)."""

    pb: ProtocolBundle

    @property
    def group(self) -> str:
        return self.pb.group

    def for_party(self, b: int) -> "SignGate":
        return SignGate(self.pb.for_party(b))


def gen_sign_gate(dcf, r: int, rng: np.random.Generator,
                  group: str, beta: int = 1) -> SignGate:
    """Dealer keygen for ``beta * [x < 0]`` under input mask ``r``.

    ``x < 0`` (two's complement, w = 8 * dcf.n_bytes) iff
    ``x_hat in [2^{w-1} + r, r) mod 2^w`` — one wraparound IC."""
    _additive(group, dcf.lam)
    n_total = 1 << (8 * dcf.n_bytes)
    p = ((n_total >> 1) + r) % n_total
    q = r % n_total
    beta_bytes = encode_lanes(beta, group, dcf.lam)
    return SignGate(dcf.interval(p, q, beta_bytes, rng=rng, group=group))


def eval_sign_share(dcf, b: int, gate: SignGate, x_hat) -> np.ndarray:
    """Party ``b``'s share uint8 [M, lam] of ``beta * [x < 0]``.

    ``x_hat``: PUBLIC masked inputs — int array [M] or pre-encoded
    points uint8 [M, n_bytes]."""
    xs = _as_points(x_hat, dcf.n_bytes)
    return dcf.eval_interval(b, gate.pb, xs)


def sign_oracle(x, n_bits: int) -> np.ndarray:
    """Clear-input oracle: int64 [M], 1 iff ``x`` is negative in
    n_bits-bit two's complement."""
    x = np.asarray(x, dtype=np.uint64) & np.uint64((1 << n_bits) - 1)
    return ((x >> np.uint64(n_bits - 1)) & np.uint64(1)).astype(np.int64)


# -- faithful truncation ----------------------------------------------

@dataclass(frozen=True)
class TruncGate:
    """One faithful-truncation gate: additive shares of
    ``((x_hat - r) mod 2^w) >> f``.

    ``pb_low`` lives on the f-bit domain (its facade has
    ``n_bytes = f // 8``), ``pb_wrap`` on the full domain;
    ``const_share`` holds BOTH parties' additive scalar shares of
    ``-(r >> f)`` until ``for_party`` restricts to one row (it is key
    material: one share reveals nothing, the pair reveals ``r >> f``,
    so the repr redacts it)."""

    pb_low: ProtocolBundle
    pb_wrap: ProtocolBundle
    const_share: np.ndarray     # uint8 [2, lam] dealer / [1, lam] party
    f: int
    party: int | None = None

    @property
    def group(self) -> str:
        return self.pb_wrap.group

    def __repr__(self) -> str:  # redacts const_share (key material)
        return (f"TruncGate(f={self.f}, group={self.group!r}, "
                f"party={self.party})")

    def for_party(self, b: int) -> "TruncGate":
        return TruncGate(self.pb_low.for_party(b),
                         self.pb_wrap.for_party(b),
                         self.const_share[b:b + 1].copy(), self.f, b)

    def const_for(self, b: int) -> np.ndarray:
        if self.party is not None:
            if b != self.party:
                # api-edge: party-restricted key contract
                raise ShapeError(
                    f"gate restricted to party {self.party}, asked "
                    f"for {b}")
            return self.const_share[0]
        return self.const_share[b]


def gen_trunc_gate(dcf, dcf_low, r: int, f: int,
                   rng: np.random.Generator, group: str) -> TruncGate:
    """Dealer keygen for faithful truncation by ``f`` bits.

    ``dcf``: full-domain facade (w = 8 * n_bytes must equal the group
    width — the 2^{w-f} wrap term is arithmetic mod 2^w);
    ``dcf_low``: facade over the low half, ``n_bytes = f // 8``,
    same lam.  ``f`` must be a whole number of bytes in (0, w)."""
    w = _additive(group, dcf.lam)
    if w != 8 * dcf.n_bytes:
        # api-edge: documented gate contract
        raise ShapeError(
            f"trunc gate needs group width == domain bits: group "
            f"{group} is {w}-bit but the domain is {8 * dcf.n_bytes}")
    if f % 8 != 0 or not 0 < f < w:
        # api-edge: documented gate contract
        raise ShapeError(
            f"f must be a positive multiple of 8 below {w} (the DCF "
            f"domain is byte-granular), got {f}")
    if dcf_low.n_bytes != f // 8 or dcf_low.lam != dcf.lam:
        # api-edge: documented gate contract
        raise ShapeError(
            f"dcf_low must have n_bytes == f//8 == {f // 8} and lam "
            f"== {dcf.lam}, got n_bytes={dcf_low.n_bytes} "
            f"lam={dcf_low.lam}")
    n_total = 1 << w
    r %= n_total
    l_r = r & ((1 << f) - 1)
    h_r = r >> f
    pb_low = dcf_low.interval(0, l_r, encode_lanes(-1, group, dcf.lam),
                              rng=rng, group=group)
    pb_wrap = dcf.interval(0, r, encode_lanes(1 << (w - f), group,
                                              dcf.lam),
                           rng=rng, group=group)
    c0 = int(rng.integers(0, n_total, dtype=np.uint64))
    const_share = np.stack([encode_lanes(c0, group, dcf.lam),
                            encode_lanes(-h_r - c0, group, dcf.lam)])
    return TruncGate(pb_low, pb_wrap, const_share, f)


def eval_trunc_share(dcf, dcf_low, b: int, gate: TruncGate,
                     x_hat) -> np.ndarray:
    """Party ``b``'s share uint8 [M, lam] of the faithful truncation.

    ``x_hat``: PUBLIC masked inputs, int array [M].  The low-half
    points are the trailing ``f // 8`` bytes of the big-endian
    encoding; the public ``x_hat >> f`` term is party 0's to add
    (adding it once, not half each, keeps everything integral)."""
    group = gate.group
    x_int = np.asarray(x_hat)
    xs = _as_points(x_int, dcf.n_bytes)
    xs_low = np.ascontiguousarray(xs[:, dcf.n_bytes - gate.f // 8:])
    y = dcf.eval_interval(b, gate.pb_wrap, xs)
    y = np_group_add(y, dcf_low.eval_interval(b, gate.pb_low, xs_low),
                     group)
    y = np_group_add(y, gate.const_for(b)[None, :], group)
    if b == 0:
        pub = _ints_of(xs, dcf.n_bytes) >> np.uint64(gate.f)
        y = np_group_add(
            y, encode_lanes(pub.astype(np.int64), group, dcf.lam),
            group)
    return y


def trunc_oracle(x_hat, r: int, f: int, n_bits: int) -> np.ndarray:
    """Clear oracle: int64 [M], ``((x_hat - r) mod 2^n_bits) >> f`` —
    the faithful (floor) truncation of the unmasked representative."""
    mask = np.uint64((1 << n_bits) - 1)
    x = (np.asarray(x_hat, dtype=np.uint64) -
         np.uint64(r % (1 << n_bits))) & mask
    return (x >> np.uint64(f)).astype(np.int64)


# -- spline sigmoid ----------------------------------------------------

@dataclass(frozen=True)
class SigmoidGate:
    """One spline-sigmoid gate: additive shares of the fixed-point
    sigma table value at the unmasked input.

    ``cuts``/``values`` are the PUBLIC table (kept for the oracle and
    for bench disclosure); the MIC bundle's intervals are the
    r-shifted partition, its payloads the table values."""

    pb: ProtocolBundle
    cuts: tuple
    values: np.ndarray          # int64 [m], public fixed-point table
    f: int

    @property
    def group(self) -> str:
        return self.pb.group

    def for_party(self, b: int) -> "SigmoidGate":
        return SigmoidGate(self.pb.for_party(b), self.cuts,
                           self.values, self.f)


def sigmoid_table(n_bits: int, f: int, m: int,
                  saturation: int = 8) -> tuple:
    """Public piecewise-constant sigma table in n_bits-bit two's
    complement fixed point with ``f`` fractional bits.

    ``m`` pieces (even, >= 4): one saturation piece per sign beyond
    ``+-saturation`` (real units) and ``(m - 2) / 2`` uniform interior
    pieces per sign on the active region, where sigma actually bends.
    Returns ``(cuts, values)``: strictly increasing unsigned
    breakpoints starting at 0 (``partition_intervals`` convention)
    and int64 [m] piece values ``round(sigma(mid) * 2^f)``, computed
    with SCALAR math and rounded before any array exists — no float
    ndarray on this path."""
    if m < 4 or m % 2:
        # api-edge: documented table contract
        raise ShapeError(f"sigmoid_table wants even m >= 4, got {m}")
    if not 0 < f < n_bits:
        # api-edge: documented table contract
        raise ShapeError(f"f must lie in (0, {n_bits}), got {f}")
    n_total = 1 << n_bits
    half = n_total >> 1
    c_fx = min(saturation << f, half - 1)  # active region edge
    k = (m - 2) // 2
    cuts = sorted({0, half}
                  | {(j * c_fx) // k for j in range(1, k + 1)}
                  | {n_total - c_fx + (j * c_fx) // k
                     for j in range(k)})
    if len(cuts) != m:
        # api-edge: documented table contract
        raise ShapeError(
            f"m={m} pieces collapse on the {n_bits}-bit domain "
            f"(got {len(cuts)} distinct cuts); use fewer pieces or "
            "more bits")
    values = []
    for i, lo in enumerate(cuts):
        hi = cuts[i + 1] if i + 1 < len(cuts) else n_total
        mid = (lo + hi) // 2
        signed = mid - n_total if mid >= half else mid
        real = signed / (1 << f)           # scalar float, dealer-side
        sig = 1.0 / (1.0 + math.exp(-real))
        values.append(int(round(sig * (1 << f))))
    return cuts, np.asarray(values, dtype=np.int64)


def gen_sigmoid_gate(dcf, r: int, rng: np.random.Generator,
                     group: str, f: int, m: int = 16) -> SigmoidGate:
    """Dealer keygen for the spline sigmoid under input mask ``r``:
    MIC over the table partition with every cut shifted by ``r``
    (a shifted partition is still a partition; wraparound pieces are
    native to the interval convention)."""
    _additive(group, dcf.lam)
    n_bits = 8 * dcf.n_bytes
    n_total = 1 << n_bits
    cuts, values = sigmoid_table(n_bits, f, m)
    shifted = []
    for p, q in partition_intervals(list(cuts), n_bits):
        if (q - p) % n_total == 0 and p != q:   # full domain stays put
            shifted.append((0, n_total))
        else:
            shifted.append(((p + r) % n_total, (q + r) % n_total))
    betas = encode_lanes(values, group, dcf.lam)
    pb = dcf.mic(shifted, betas, rng=rng, group=group)
    return SigmoidGate(pb, tuple(cuts), values, f)


def eval_sigmoid_share(dcf, b: int, gate: SigmoidGate,
                       x_hat) -> np.ndarray:
    """Party ``b``'s share uint8 [M, lam] of ``table(x)``: group-sum
    reduce of the MIC rows (exactly one shifted piece fires per
    point, so the reduce telescopes — ``protocols.piecewise``)."""
    xs = _as_points(x_hat, dcf.n_bytes)
    rows = dcf.eval_mic(b, gate.pb, xs)
    return np_group_reduce(rows, gate.group, axis=0)


def sigmoid_fixed_oracle(x, cuts: Sequence[int],
                         values: np.ndarray) -> np.ndarray:
    """Clear oracle: int64 [M], the table value at UNMASKED ``x`` —
    piece i covers [cuts[i], cuts[i+1]) with the last wrapping to the
    domain top (cuts[0] == 0 makes that the plain suffix)."""
    idx = np.searchsorted(np.asarray(cuts, dtype=np.uint64),
                          np.asarray(x, dtype=np.uint64),
                          side="right") - 1
    return np.asarray(values, dtype=np.int64)[idx]


# -- internals ---------------------------------------------------------

def _as_points(x_hat, n_bytes: int) -> np.ndarray:
    """Accept int array [M] or pre-encoded points uint8 [M, n_bytes]."""
    x = np.asarray(x_hat)
    if x.ndim == 2 and x.dtype == np.uint8 and x.shape[1] == n_bytes:
        return x
    if x.ndim != 1:
        # api-edge: documented gate input contract
        raise ShapeError(
            f"x_hat must be int [M] or uint8 [M, {n_bytes}], got "
            f"{x.dtype} {x.shape}")
    return points_of(x, n_bytes)


def _ints_of(xs: np.ndarray, n_bytes: int) -> np.ndarray:
    """Big-endian points uint8 [M, n_bytes] -> uint64 [M]."""
    shifts = np.arange(8 * (n_bytes - 1), -1, -8, dtype=np.uint64)
    return (xs.astype(np.uint64) << shifts).sum(axis=1,
                                                dtype=np.uint64)
