"""dcf_tpu.protocols — mixed-mode secure-computation protocols over DCF.

The source paper (Boyle et al., EUROCRYPT 2021) presents DCF as the
building block for mixed-mode 2PC: interval containment (IC), multiple
interval containment (MIC) and piecewise/spline function evaluation.
This package is that layer for this framework, in the repo's XOR output
group:

- ``protocols.oracle``    numpy golden models (IC / MIC / piecewise),
  the bit-exact reference every evaluator is tested against;
- ``protocols.keygen``    protocol-level key generation: the 2m
  interval-bound DCF keys of an m-interval MIC packed into ONE
  ``KeyBundle`` on the K axis (the exact shape the batched walk kernels
  are fastest at), wrapped in a ``ProtocolBundle`` with the per-interval
  combine masks; DCFK v3 wire format (version-gated, v1/v2 still read);
- ``protocols.combine``   the share-combine algebra: pairwise XOR of
  per-bound shares (on device for the staged plane layouts), the
  ``protocols.combine`` fault seam, and the streamed two-party
  reconstruction helper the workloads layer rides on;
- ``protocols.ic``        single-interval containment evaluation;
- ``protocols.mic``       batched MIC evaluation: the facade path (any
  backend the facade can select, meshes included) and the staged
  ``MicEvaluator`` (put_bundle/stage/eval_staged once, combine on
  device);
- ``protocols.piecewise`` piecewise-constant lookup as a MIC over a
  domain partition, XOR-reduced to one value per point;
- ``protocols.fixedpoint`` fixed-point gates over the ADDITIVE output
  groups (``group="add8"/"add16"/"add32"``): signed comparison,
  faithful truncation and spline sigmoid, each a masked-input
  composition of r-shifted IC/MIC bundles with a numpy golden oracle
  (served form in ``workloads.gates``; bench: ``gate_bench``);
- ``protocols.dpf``       distributed point functions: the GGM walk
  minus the comparison accumulation (no ``cw_v``), K-packed host and
  device keygen, the per-point reference evaluator, and the DCFK v3
  ``proto=PROTO_DPF`` wire frame — the engine of the 2-server PIR
  workload (``workloads.py``) via the full-domain EvalAll backends
  (``backends.evalall``).

Entry points: ``Dcf.interval`` / ``Dcf.mic`` / ``Dcf.piecewise`` (key
generation) and ``Dcf.eval_interval`` / ``Dcf.eval_mic`` /
``Dcf.eval_piecewise`` (per-party evaluation); protocol bundles register
directly into the serving layer (``DcfService.register_key``), which
applies the combine server-side with the same retry semantics as plain
DCF batches.  Derivation and wire format: README "Protocols" section.
"""

from dcf_tpu.protocols.combine import (  # noqa: F401
    combine_pair_shares,
    xor_reconstruct_stream,
)
from dcf_tpu.protocols.dpf import (  # noqa: F401
    DPF_DEVICE_LAM,
    DpfBundle,
    PROTO_DPF,
    decode_proto_frame,
    dpf_device_fallback_count,
    dpf_eval_points,
    dpf_gen_batch,
    dpf_gen_on_device,
)
from dcf_tpu.protocols.fixedpoint import (  # noqa: F401
    SigmoidGate,
    SignGate,
    TruncGate,
    eval_sigmoid_share,
    eval_sign_share,
    eval_trunc_share,
    gate_reconstruct,
    gen_sigmoid_gate,
    gen_sign_gate,
    gen_trunc_gate,
    sigmoid_fixed_oracle,
    sigmoid_table,
    sign_oracle,
    trunc_oracle,
)
from dcf_tpu.protocols.ic import eval_interval  # noqa: F401
from dcf_tpu.protocols.keygen import (  # noqa: F401
    ProtocolBundle,
    gen_interval_bundle,
    interval_bound_alphas,
)
from dcf_tpu.protocols.mic import MicEvaluator, eval_mic  # noqa: F401
from dcf_tpu.protocols.oracle import (  # noqa: F401
    dpf_oracle,
    ic_oracle,
    mic_oracle,
    piecewise_oracle,
)
from dcf_tpu.protocols.piecewise import (  # noqa: F401
    eval_piecewise,
    partition_intervals,
)

__all__ = [
    "DPF_DEVICE_LAM",
    "DpfBundle",
    "MicEvaluator",
    "PROTO_DPF",
    "ProtocolBundle",
    "SigmoidGate",
    "SignGate",
    "TruncGate",
    "combine_pair_shares",
    "decode_proto_frame",
    "dpf_device_fallback_count",
    "dpf_eval_points",
    "dpf_gen_batch",
    "dpf_gen_on_device",
    "dpf_oracle",
    "eval_interval",
    "eval_mic",
    "eval_piecewise",
    "eval_sigmoid_share",
    "eval_sign_share",
    "eval_trunc_share",
    "gate_reconstruct",
    "gen_interval_bundle",
    "gen_sigmoid_gate",
    "gen_sign_gate",
    "gen_trunc_gate",
    "ic_oracle",
    "interval_bound_alphas",
    "mic_oracle",
    "partition_intervals",
    "piecewise_oracle",
    "sigmoid_fixed_oracle",
    "sigmoid_table",
    "sign_oracle",
    "trunc_oracle",
    "xor_reconstruct_stream",
]
