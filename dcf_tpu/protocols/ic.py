"""Single-interval containment (IC): the m=1 slice of MIC.

IC is where the XOR-group derivation is easiest to see (README
"Protocols"): two DCF keys — one per bound — K-packed into a K=2
bundle, pair-combined to ``1_{p <= x < q} * beta`` shares.  Everything
here delegates to ``protocols.mic``; the module exists so the facade's
``Dcf.interval``/``Dcf.eval_interval`` surface has a first-class
single-interval form with [M, lam]-shaped outputs.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.protocols.keygen import ProtocolBundle
from dcf_tpu.protocols.mic import eval_mic

__all__ = ["eval_interval"]


def eval_interval(dcf, b: int, pb: ProtocolBundle,
                  xs: np.ndarray) -> np.ndarray:
    """Party ``b``'s IC share: uint8 [M, lam].  Group-add both parties'
    outputs (XOR in the default group) to reconstruct
    ``beta if x in [p, q) else 0`` (wraparound intervals included — the
    combine mask carries the correction)."""
    if pb.num_intervals != 1:
        raise ShapeError(
            f"eval_interval wants a single-interval bundle, got m="
            f"{pb.num_intervals}; use eval_mic for the batched form")
    return eval_mic(dcf, b, pb, xs)[0]
