"""Numpy golden models for the protocol layer (the protocols "spec").

Slow, obviously-correct references for interval containment, MIC and
piecewise-constant evaluation.  Every protocol evaluator (facade path,
staged device path, the serving layer) is validated bit-for-bit against
these, exactly as the DCF backends are validated against
``dcf_tpu.spec``.

The oracles are OUTPUT-GROUP INDEPENDENT: each models the plaintext
function (``beta`` where the indicator fires, ``0`` elsewhere; the
firing piece's value for piecewise), and that plaintext is the same
whether the shares being checked against it reconstruct by XOR or by
mod-2^w lane addition — the group only changes HOW the two parties'
outputs are folded (``utils.groups.np_group_add``), not what they fold
to.  The fixed-point gate oracles (sign, truncation, sigmoid), which DO
have group-specific plaintext semantics, live with their gates in
``protocols.fixedpoint``.

Interval convention (shared with ``protocols.keygen`` — the single
source of the semantics):

* the domain is ``[0, N)`` with ``N = 2^(8*n_bytes)``; interval bounds
  are Python ints ``0 <= p, q <= N`` (``N`` itself is a legal bound so
  ``[p, N)`` suffixes are expressible);
* ``(p, q)`` denotes the half-open interval ``[p, q)`` when ``p <= q``
  and the WRAPAROUND interval ``[p, N) ∪ [0, q)`` when ``p > q``;
* ``p == q`` is the EMPTY interval (never full-domain: the full domain
  is ``(0, N)``).  This disambiguation is load-bearing — in the XOR
  group the two cases differ only by the public correction bit, see
  ``keygen.interval_bound_alphas``.

Outputs mirror the DCF evaluators: uint8 ``[m, M, lam]`` (MIC),
``[M, lam]`` (IC / piecewise), with ``beta`` where the indicator is 1
and ``0`` elsewhere.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from dcf_tpu.errors import ShapeError

__all__ = [
    "dpf_oracle",
    "ic_oracle",
    "interval_indicator",
    "mic_oracle",
    "piecewise_oracle",
    "points_to_ints",
]


def points_to_ints(xs: np.ndarray) -> list[int]:
    """uint8 [M, n_bytes] big-endian points -> Python ints (arbitrary
    width: the flagship 16-byte domain overflows uint64)."""
    xs = np.asarray(xs, dtype=np.uint8)
    if xs.ndim != 2:
        raise ShapeError(f"xs must be [M, n_bytes], got {xs.shape}")
    return [int.from_bytes(row.tobytes(), "big") for row in xs]


def _check_bounds(p: int, q: int, n: int) -> None:
    if not (0 <= p <= n and 0 <= q <= n):
        # api-edge: documented interval-bound contract (bounds are ints
        # in [0, 2^n_bits], N itself included so [p, N) is expressible)
        raise ValueError(
            f"interval bounds must lie in [0, {n}], got ({p}, {q})")


def interval_indicator(xs: np.ndarray, p: int, q: int) -> np.ndarray:
    """bool [M]: x in [p, q), wraparound when p > q, empty when p == q."""
    n_total = 1 << (8 * xs.shape[1])
    _check_bounds(p, q, n_total)
    vals = points_to_ints(xs)
    if p <= q:
        inside = [p <= x < q for x in vals]
    else:
        inside = [x >= p or x < q for x in vals]
    return np.asarray(inside, dtype=bool)


def dpf_oracle(xs: np.ndarray, alpha: int, beta: np.ndarray) -> np.ndarray:
    """Distributed point function 1_{x == alpha} * beta: uint8 [M, lam].

    The DPF golden model: ``beta`` at the single point ``alpha``, zero
    everywhere else — the degenerate interval ``[alpha, alpha+1)`` of
    the IC family, kept separate because the DPF key (protocols.dpf)
    carries no comparison accumulation and its evaluators are validated
    against this directly.
    """
    n_total = 1 << (8 * xs.shape[1])
    if not 0 <= alpha < n_total:
        # api-edge: documented point contract (alpha is a domain VALUE,
        # so N itself is out of range — unlike interval bounds)
        raise ValueError(f"alpha must lie in [0, {n_total}), got {alpha}")
    beta = np.asarray(beta, dtype=np.uint8)
    hit = np.asarray([x == alpha for x in points_to_ints(xs)], dtype=bool)
    return np.where(hit[:, None], beta[None, :],
                    np.zeros_like(beta)[None, :])


def ic_oracle(xs: np.ndarray, p: int, q: int, beta: np.ndarray) -> np.ndarray:
    """Interval containment 1_{x in [p, q)} * beta: uint8 [M, lam]."""
    beta = np.asarray(beta, dtype=np.uint8)
    inside = interval_indicator(xs, p, q)
    return np.where(inside[:, None], beta[None, :],
                    np.zeros_like(beta)[None, :])


def mic_oracle(xs: np.ndarray, intervals: Sequence[tuple[int, int]],
               betas: np.ndarray) -> np.ndarray:
    """Multiple interval containment: uint8 [m, M, lam], row i is
    ``ic_oracle(xs, *intervals[i], betas[i])``.  Disjointness is the
    caller's protocol-level concern — each row is independent."""
    betas = np.asarray(betas, dtype=np.uint8)
    if betas.ndim != 2 or betas.shape[0] != len(intervals):
        raise ShapeError(
            f"betas must be [{len(intervals)}, lam], got {betas.shape}")
    return np.stack([ic_oracle(xs, p, q, betas[i])
                     for i, (p, q) in enumerate(intervals)])


def piecewise_oracle(xs: np.ndarray, cuts: Sequence[int],
                     values: np.ndarray) -> np.ndarray:
    """Piecewise-constant lookup: uint8 [M, lam].

    ``cuts`` (strictly increasing ints in [0, N)) partition the domain
    into m = len(cuts) intervals ``[cuts[i], cuts[i+1])`` with the LAST
    one wrapping: ``[cuts[m-1], N) ∪ [0, cuts[0])``.  With
    ``cuts[0] == 0`` this is the standard spline table over [0, N);
    a nonzero ``cuts[0]`` rotates the table.  ``values``: uint8
    [m, lam].  Exactly one interval contains each x, so the XOR-reduce
    over the MIC rows IS the lookup — the identity the evaluator relies
    on (``protocols.piecewise``).
    """
    from dcf_tpu.protocols.piecewise import partition_intervals

    intervals = partition_intervals(cuts, 8 * xs.shape[1])
    rows = mic_oracle(xs, intervals, values)
    return np.bitwise_xor.reduce(rows, axis=0)
