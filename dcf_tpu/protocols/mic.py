"""Batched multiple-interval-containment (MIC) evaluation.

Two paths, both bit-exact against ``protocols.oracle.mic_oracle``:

* ``eval_mic(dcf, b, pb, xs)`` — the facade path: the 2m bound keys
  evaluate through ``Dcf.eval`` (ANY backend the facade can select,
  mesh-sharded variants included; the key image ships once per
  (bundle, party) exactly as for plain DCF) and the pair-combine runs
  on the host bytes.  The zero-setup path, and the only one for host
  backends.
* ``MicEvaluator`` — the staged discipline for long-lived keys: a
  dedicated backend instance per (bundle, party) stages the key image
  once (``put_bundle``), points stage per batch (``stage``), and the
  pair-combine runs ON DEVICE in the staged plane layout before the
  planes->bytes conversion (half the conversion volume; see
  ``protocols.combine``).  The serving layer reaches the same effect
  through its residency registry + the service-side combine.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.protocols.combine import (
    combine_pair_shares,
    staged_pair_combine,
)
from dcf_tpu.protocols.keygen import ProtocolBundle
from dcf_tpu.utils.groups import np_group_add

__all__ = ["MicEvaluator", "eval_mic"]


def eval_mic(dcf, b: int, pb: ProtocolBundle, xs: np.ndarray) -> np.ndarray:
    """Party ``b``'s per-interval MIC shares: uint8 [m, M, lam].

    Group-add both parties' outputs (XOR for the default group) to
    reconstruct ``betas[i] if x in intervals[i] else 0`` per interval
    row.  ``dcf``: the facade the keys were generated for; any backend.
    """
    y = dcf.eval(b, pb.keys, xs)  # [2m, M, lam]
    return combine_pair_shares(np.asarray(y), pb.masks_for(b), pb.group)


class MicEvaluator:
    """Staged MIC evaluation for one (bundle, party): stage once, eval
    many, combine on device.

    >>> ev = MicEvaluator(dcf, pb, b=0)
    >>> y0 = ev.eval(xs)            # uint8 [m, M, lam]

    Owns a fresh backend instance (``Dcf.new_eval_backend``) holding
    this bundle's device image, so many protocol bundles can stay
    resident at once without thrashing the facade's per-party slot —
    the same reason the serve registry uses ``new_eval_backend``.
    Host-path facades (cpu/numpy) degrade to the facade path
    internally.
    """

    def __init__(self, dcf, pb: ProtocolBundle, b: int):
        if b not in (0, 1):
            # api-edge: documented party-index contract
            raise ValueError(f"party must be 0 or 1, got {b}")
        self._dcf = dcf
        self._pb = pb
        self._b = int(b)
        self._group = pb.group
        self._masks = pb.masks_for(b)
        self._be = dcf.new_eval_backend()
        if self._be is not None:
            kb = (pb.keys if dcf.backend_name == "keylanes"
                  else pb.keys.for_party(b) if pb.keys.s0s.shape[1] == 2
                  else pb.keys)
            self._be.put_bundle(kb)

    @property
    def backend(self):
        """The owned backend instance (None for host paths) — the
        escape hatch to its staged API once ``eval`` calls have
        shipped the image."""
        return self._be

    def eval(self, xs: np.ndarray) -> np.ndarray:
        """Per-interval shares uint8 [m, M, lam] for this party."""
        xs = np.asarray(xs, dtype=np.uint8)
        if xs.ndim != 2:
            raise ShapeError(f"xs must be [M, n_bytes], got {xs.shape}")
        m_points = xs.shape[0]
        be = self._be
        if be is None:  # host path: the facade dispatches directly
            return eval_mic(self._dcf, self._b, self._pb, xs)
        if hasattr(be, "stage") and hasattr(be, "staged_to_bytes"):
            staged = be.stage(xs)
            y_dev = be.eval_staged(self._b, staged)
            y_comb = staged_pair_combine(be, y_dev, self._group)  # seam
            if y_comb is not None:
                y = be.staged_to_bytes(y_comb, m_points)  # [m, M, lam]
                return np_group_add(y, self._masks[:, None, :],
                                    self._group)
            y = be.staged_to_bytes(y_dev, m_points)  # [2m, M, lam]
            return combine_pair_shares(y, self._masks, self._group)
        y = np.asarray(be.eval(self._b, xs))
        return combine_pair_shares(y, self._masks, self._group)

    def reconstruct_with(self, other: "MicEvaluator",
                         xs: np.ndarray) -> np.ndarray:
        """Two-party reconstruction convenience (tests/benches): the
        group add of this evaluator's shares with ``other``'s (the
        opposite party) — XOR in the default group."""
        if other._b == self._b:
            # api-edge: documented two-party contract
            raise ValueError("reconstruct_with wants the OPPOSITE party")
        return np_group_add(self.eval(xs), other.eval(xs), self._group)
