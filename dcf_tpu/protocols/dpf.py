"""Distributed point functions: the GGM walk minus the comparison.

A DPF key for ``f_{alpha,beta}(x) = beta * 1_{x == alpha}`` is a strict
subset of the DCF key material (Boyle et al., EUROCRYPT 2021, Fig. 1 vs
Fig. 3): the SAME per-level seed/t-bit correction words steer the two
parties' GGM walks apart exactly on the path to ``alpha``, and because a
point function needs no per-level value accumulation, the whole ``v``
column (``cw_v``, the v-half of every PRG call) drops out.  What remains
per level is ``(s_cw, tl_cw, tr_cw)`` plus one final leaf correction
``cw_np1 = s_a ^ s_b ^ beta``: off the special path the parties' states
are equal (XOR share of 0), on it they differ by exactly ``beta`` after
the leaf correction.  Reconstruction is the repo's XOR group:
``y = y0 ^ y1``.

Keygen reuses the DCF pipelines directly: the host walk below mirrors
``gen.gen_batch`` line for line (minus ``v_alpha``/``cw_v``), and the
device path drives the K-packed keys-in-lanes Pallas kernel
(``ops.pallas_keygen.PallasDpfKeyGen`` — the ISSUE 10 keygen kernel
with the v lanes deleted).  The device width is pinned to lam=32: two
AES blocks, exactly the ``narrow_prg_expand`` core every narrow kernel
shares.  Host paths stay generic over lam.

Wire format: DCFK version 3 with ``proto=PROTO_DPF`` — the v2 sections
minus ``cw_v``, version-gated BOTH ways: ``KeyBundle.from_bytes`` on a
DPF frame refuses typed with a pointer here (a plain reader would
fabricate a ``cw_v`` of zeros and evaluate garbage), and
``DpfBundle.from_bytes`` refuses plain and MIC frames with pointers the
other way.  ``decode_proto_frame`` dispatches any typed v3 frame to the
right decoder off the header's proto field — the serving store and the
replication plane route through it so DPF bundles ride the existing
DCFK/registry/pod machinery unchanged.
"""

from __future__ import annotations

import struct
import warnings
import zlib
from dataclasses import dataclass

import numpy as np

from dcf_tpu.errors import BackendFallbackWarning, KeyFormatError, ShapeError
from dcf_tpu.gen import _check_gen_inputs, _sel
from dcf_tpu.keys import (
    _CRC_SIZE,
    _HEADER3,
    _HEADER3_SIZE,
    _MAGIC,
    _VERSION_PROTO,
    _decode_sections,
)
from dcf_tpu.ops.prg import HirosePrgNp

__all__ = [
    "DPF_DEVICE_LAM",
    "DpfBundle",
    "PROTO_DPF",
    "decode_proto_frame",
    "dpf_device_fallback_count",
    "dpf_eval_points",
    "dpf_gen_batch",
    "dpf_gen_on_device",
]

#: proto header value for DPF frames.  0 = plain DCF (KeyBundle), 1 =
#: the interval-containment family (protocols.keygen.PROTO_MIC).
PROTO_DPF = 2

#: the device keygen/EvalAll width: two 16-byte AES blocks, the exact
#: shape ``narrow_prg_expand`` expands in one fused bitsliced call.
DPF_DEVICE_LAM = 32


@dataclass(frozen=True)
class DpfBundle:
    """K packed DPF keys: the DCF bundle minus the ``cw_v`` column.

    ``s0s``: uint8 [K, P, lam] starting seeds (P=2 out of gen, P=1 after
    ``for_party``); ``cw_s``: uint8 [K, n, lam] per-level seed
    corrections; ``cw_t``: uint8 [K, n, 2] per-level (left, right) t-bit
    corrections; ``cw_np1``: uint8 [K, lam] leaf correction
    ``s_a ^ s_b ^ beta``.
    """

    s0s: np.ndarray
    cw_s: np.ndarray
    cw_t: np.ndarray
    cw_np1: np.ndarray

    # Wire-typing marker: non-zero means "this bundle serializes as a
    # typed v3 frame" — the serving store/replication plane key their
    # proto manifest bit off this (KeyBundle has no attribute -> 0).
    WIRE_PROTO = PROTO_DPF

    def __post_init__(self):
        for name in ("s0s", "cw_s", "cw_t", "cw_np1"):
            if getattr(self, name).dtype != np.uint8:
                raise ShapeError(f"{name} must be uint8")
        k, p, lam = (self.s0s.shape if self.s0s.ndim == 3 else (0, 0, 0))
        if self.s0s.ndim != 3 or p not in (1, 2):
            raise ShapeError(
                f"s0s must be [K, parties(1|2), lam], got {self.s0s.shape}")
        if self.cw_s.ndim != 3 or self.cw_s.shape[::2] != (k, lam):
            raise ShapeError(
                f"cw_s must be [K={k}, n, lam={lam}], got {self.cw_s.shape}")
        n = self.cw_s.shape[1]
        if n == 0 or n % 8:
            raise ShapeError(
                f"depth must be a positive multiple of 8 bits, got {n}")
        if self.cw_t.shape != (k, n, 2):
            raise ShapeError(
                f"cw_t must be {(k, n, 2)}, got {self.cw_t.shape}")
        if self.cw_np1.shape != (k, lam):
            raise ShapeError(
                f"cw_np1 must be {(k, lam)}, got {self.cw_np1.shape}")

    def __repr__(self) -> str:
        """Redacted: geometry only (every section is key material)."""
        return (f"DpfBundle(K={self.num_keys}, n_bits={self.n_bits}, "
                f"lam={self.lam}, parties={self.s0s.shape[1]}, "
                "<key material redacted>)")

    @property
    def num_keys(self) -> int:
        return self.s0s.shape[0]

    @property
    def n_bits(self) -> int:
        return self.cw_s.shape[1]

    @property
    def n_bytes(self) -> int:
        return self.cw_s.shape[1] // 8

    @property
    def lam(self) -> int:
        return self.s0s.shape[2]

    def for_party(self, b: int) -> "DpfBundle":
        """Restrict to party ``b``'s seed column (correction words are
        public-to-both-parties key material and stay whole)."""
        if b not in (0, 1):
            # api-edge: documented party-index contract
            raise ValueError(f"party must be 0 or 1, got {b}")
        if self.s0s.shape[1] != 2:
            raise ShapeError("bundle is already party-restricted")
        return DpfBundle(
            s0s=self.s0s[:, b : b + 1].copy(), cw_s=self.cw_s,
            cw_t=self.cw_t, cw_np1=self.cw_np1)

    # -- codec (DCFK v3, proto=PROTO_DPF) -----------------------------------

    def to_bytes(self) -> bytes:
        """DCFK v3 frame: the v2 sections minus ``cw_v``, typed
        ``proto=PROTO_DPF`` + CRC32 trailer."""
        k, p = self.s0s.shape[0], self.s0s.shape[1]
        header = _MAGIC + struct.pack(
            _HEADER3, _VERSION_PROTO, p, k, self.n_bits, self.lam,
            PROTO_DPF)
        body = b"".join([
            header,
            self.s0s.tobytes(),
            self.cw_s.tobytes(),
            self.cw_t.tobytes(),
            self.cw_np1.tobytes(),
        ])
        return body + struct.pack("<I", zlib.crc32(body))

    @classmethod
    def from_bytes(cls, data: bytes) -> "DpfBundle":
        """Strict bounds-checked decode of a v3 DPF frame; the same
        field-naming rejection discipline as ``KeyBundle.from_bytes``.
        Plain frames and MIC frames are refused with pointers at the
        right decoder — a DPF evaluator fed DCF material would treat
        ``cw_v`` bytes as seed corrections and walk garbage."""
        if len(data) < 4 or data[:4] != _MAGIC:
            raise KeyFormatError(
                f"bad magic: expected {_MAGIC!r}, got {bytes(data[:4])!r} "
                "(not a DCFK frame)")
        if len(data) < _HEADER3_SIZE:
            raise KeyFormatError(
                f"truncated header: frame is {len(data)} bytes, the DCFK "
                f"v3 header needs {_HEADER3_SIZE}")
        version, p, k, n, lam, proto = struct.unpack_from(_HEADER3, data, 4)
        if version != _VERSION_PROTO:
            raise KeyFormatError(
                f"version {version} frames carry no proto field; "
                "decode with KeyBundle.from_bytes")
        if proto != PROTO_DPF:
            pointer = ("dcf_tpu.protocols.ProtocolBundle.from_bytes"
                       if proto != 0 else "KeyBundle.from_bytes")
            raise KeyFormatError(
                f"proto field {proto} is not the point-function family "
                f"({PROTO_DPF}); decode with {pointer}")
        if p not in (1, 2):
            raise KeyFormatError(f"parties field must be 1 or 2, got {p}")
        if n == 0 or n % 8:
            raise KeyFormatError(
                f"n field must be a positive multiple of 8 bits, got {n}")
        if lam == 0:
            raise KeyFormatError("lam field must be positive, got 0")
        if k == 0:
            raise KeyFormatError(
                f"K field must be a positive key count, got {k}")
        sections = (
            ("s0s", (k, p, lam)),
            ("cw_s", (k, n, lam)),
            ("cw_t", (k, n, 2)),
            ("cw_np1", (k, lam)),
        )
        arrays = _decode_sections(
            data, sections, _HEADER3_SIZE, _CRC_SIZE,
            f"K={k}, P={p}, n={n}, lam={lam}")
        return cls(
            s0s=arrays["s0s"], cw_s=arrays["cw_s"], cw_t=arrays["cw_t"],
            cw_np1=arrays["cw_np1"])


def decode_proto_frame(data: bytes):
    """Dispatch a typed DCFK v3 frame to its decoder off the header's
    proto field: ``PROTO_MIC`` -> ``ProtocolBundle``, ``PROTO_DPF`` ->
    ``DpfBundle``.  The single place the serving store and replication
    plane decode typed frames, so a new proto id extends exactly one
    dispatch table.  Plain frames (v1/v2, or v3 proto=0) are refused
    with a pointer at ``KeyBundle.from_bytes``."""
    from dcf_tpu.protocols.keygen import PROTO_MIC, ProtocolBundle

    if len(data) < 4 or data[:4] != _MAGIC:
        raise KeyFormatError(
            f"bad magic: expected {_MAGIC!r}, got {bytes(data[:4])!r} "
            "(not a DCFK frame)")
    if len(data) < _HEADER3_SIZE:
        raise KeyFormatError(
            f"truncated header: frame is {len(data)} bytes, the DCFK "
            f"v3 header needs {_HEADER3_SIZE}")
    version = struct.unpack_from("<H", data, 4)[0]
    if version != _VERSION_PROTO:
        raise KeyFormatError(
            f"version {version} frames carry no proto field; "
            "decode with KeyBundle.from_bytes")
    proto = struct.unpack_from(_HEADER3, data, 4)[5]
    if proto == PROTO_MIC:
        return ProtocolBundle.from_bytes(data)
    if proto == PROTO_DPF:
        return DpfBundle.from_bytes(data)
    if proto == 0:
        raise KeyFormatError(
            "proto field 0 is a plain frame; decode with "
            "KeyBundle.from_bytes")
    raise KeyFormatError(
        f"unknown proto field {proto} (known: {PROTO_MIC}=MIC, "
        f"{PROTO_DPF}=DPF)")


# -- host keygen / eval -------------------------------------------------------


def dpf_gen_batch(
    prg: HirosePrgNp,
    alphas: np.ndarray,
    betas: np.ndarray,
    s0s: np.ndarray,
) -> DpfBundle:
    """Generate K DPF keys at once (host numpy walk).

    alphas: uint8 [K, n_bytes]; betas: uint8 [K, lam]; s0s: uint8
    [K, 2, lam].  Returns a two-party ``DpfBundle``.  Mirrors
    ``gen.gen_batch`` with the ``v`` accumulation deleted: the lose-side
    seed correction and the keep-side t-bit algebra are IDENTICAL (same
    walk, same corrections), and beta enters only through the leaf
    correction ``cw_np1 = s_a ^ s_b ^ betas``.
    """
    lam = prg.lam
    _check_gen_inputs(alphas, betas, s0s, lam)
    k_num, n_bytes = alphas.shape
    n = 8 * n_bytes
    alpha_bits = np.unpackbits(alphas, axis=1)  # MSB-first [K, n]

    s_a = s0s[:, 0, :].copy()
    s_b = s0s[:, 1, :].copy()
    t_a = np.zeros(k_num, dtype=np.uint8)  # t^(0)_0 = 0
    t_b = np.ones(k_num, dtype=np.uint8)  # t^(0)_1 = 1

    cw_s = np.zeros((k_num, n, lam), dtype=np.uint8)
    cw_t = np.zeros((k_num, n, 2), dtype=np.uint8)

    for i in range(n):
        p0 = prg.gen(s_a)
        p1 = prg.gen(s_b)
        a_i = alpha_bits[:, i]  # 1 -> keep R / lose L
        lose_is_r = (a_i ^ 1).astype(np.uint8)
        s_cw = _sel(p0.s_l, p0.s_r, lose_is_r) ^ _sel(
            p1.s_l, p1.s_r, lose_is_r)
        tl_cw = p0.t_l ^ p1.t_l ^ a_i ^ 1
        tr_cw = p0.t_r ^ p1.t_r ^ a_i
        cw_s[:, i] = s_cw
        cw_t[:, i, 0] = tl_cw
        cw_t[:, i, 1] = tr_cw
        t_cw_keep = _sel(tl_cw, tr_cw, a_i)
        new_s_a = _sel(p0.s_l, p0.s_r, a_i) ^ s_cw * t_a[:, None]
        new_s_b = _sel(p1.s_l, p1.s_r, a_i) ^ s_cw * t_b[:, None]
        new_t_a = _sel(p0.t_l, p0.t_r, a_i) ^ (t_a & t_cw_keep)
        new_t_b = _sel(p1.t_l, p1.t_r, a_i) ^ (t_b & t_cw_keep)
        s_a, s_b, t_a, t_b = new_s_a, new_s_b, new_t_a, new_t_b

    cw_np1 = s_a ^ s_b ^ betas
    return DpfBundle(s0s=s0s.copy(), cw_s=cw_s, cw_t=cw_t, cw_np1=cw_np1)


def dpf_eval_points(
    prg: HirosePrgNp,
    bundle: DpfBundle,
    b: int,
    xs: np.ndarray,
) -> np.ndarray:
    """Party ``b``'s DPF shares at arbitrary points: uint8 [K, M, lam].

    The slow per-point reference walk (n PRG levels per point) — the
    golden model the full-domain EvalAll backends are checked bit-exact
    against, exactly as the DCF per-point evaluators anchor the frontier
    builds.  ``bundle`` may be two-party or party-restricted; ``b``
    picks the seed column and the initial t-bit either way.
    """
    if b not in (0, 1):
        # api-edge: documented party-index contract
        raise ValueError(f"party must be 0 or 1, got {b}")
    xs = np.asarray(xs, dtype=np.uint8)
    if xs.ndim != 2 or 8 * xs.shape[1] != bundle.n_bits:
        raise ShapeError(
            f"xs must be [M, {bundle.n_bytes}] to match the bundle "
            f"depth, got {xs.shape}")
    k_num, m = bundle.num_keys, xs.shape[0]
    col = b if bundle.s0s.shape[1] == 2 else 0
    s = np.broadcast_to(
        bundle.s0s[:, col, None, :], (k_num, m, bundle.lam)).copy()
    t = np.full((k_num, m), b, dtype=np.uint8)
    xbits = np.unpackbits(xs, axis=1)  # MSB-first [M, n]
    for i in range(bundle.n_bits):
        p = prg.gen(s)
        x_i = np.broadcast_to(xbits[None, :, i], (k_num, m))
        cond = x_i.astype(bool)[..., None]
        cs = bundle.cw_s[:, None, i, :]
        s = np.where(cond, p.s_r, p.s_l) ^ cs * t[..., None]
        ct = np.where(x_i.astype(bool), bundle.cw_t[:, None, i, 1],
                      bundle.cw_t[:, None, i, 0])
        t = np.where(x_i.astype(bool), p.t_r, p.t_l) ^ (t & ct)
    return s ^ bundle.cw_np1[:, None, :] * t[..., None]


# -- the on-device keygen router ----------------------------------------------

_DPF_DEVICE_GENS: dict = {}
_DPF_DEVICE_GENS_CAP = 8
_DPF_DEVICE_FALLBACKS = 0


def dpf_device_fallback_count() -> int:
    """How many ``dpf_gen_on_device`` calls fell back to the host walk
    this process (the same counted-and-warned contract as
    ``gen.device_fallback_count``)."""
    return _DPF_DEVICE_FALLBACKS


def dpf_gen_on_device(
    lam: int,
    cipher_keys,
    alphas: np.ndarray,
    betas: np.ndarray,
    s0s: np.ndarray,
    *,
    interpret: bool | None = None,
    tile_words: int = 128,
) -> DpfBundle:
    """Generate K DPF keys with the level walk ON the accelerator.

    Drives the K-packed keys-in-lanes DPF kernel
    (``ops.pallas_keygen.PallasDpfKeyGen``); ``lam`` must be
    ``DPF_DEVICE_LAM`` (=32, the two-block narrow shape).
    ``interpret=None`` applies the keylanes rule: Mosaic on TPU, the
    Pallas interpreter elsewhere.  Returns the host two-party
    ``DpfBundle``, byte-identical to ``dpf_gen_batch`` on the same
    ``(alphas, betas, s0s)``.  Any device failure (injectable at the
    ``keygen.device`` seam) falls back to the host walk: silent-correct,
    counted (``dpf_device_fallback_count``), warned via
    ``BackendFallbackWarning``.
    """
    if lam != DPF_DEVICE_LAM:
        # api-edge: documented device-width contract (two AES blocks —
        # the narrow_prg_expand shape; host dpf_gen_batch is generic)
        raise ValueError(
            f"device DPF keygen is pinned to lam={DPF_DEVICE_LAM} "
            f"(two narrow AES blocks), got {lam}")
    _check_gen_inputs(alphas, betas, s0s, lam)
    global _DPF_DEVICE_FALLBACKS
    try:
        from dcf_tpu.testing.faults import fire

        fire("keygen.device", alphas.shape[0], lam)
        if interpret is None:
            import jax

            interpret = jax.devices()[0].platform != "tpu"
        key = (lam, tuple(cipher_keys), bool(interpret), tile_words)
        kg = _DPF_DEVICE_GENS.get(key)
        if kg is None:
            if len(_DPF_DEVICE_GENS) >= _DPF_DEVICE_GENS_CAP:
                _DPF_DEVICE_GENS.pop(next(iter(_DPF_DEVICE_GENS)))
            from dcf_tpu.ops.pallas_keygen import PallasDpfKeyGen

            kg = PallasDpfKeyGen(lam, cipher_keys,
                                 interpret=bool(interpret),
                                 tile_words=tile_words)
            _DPF_DEVICE_GENS[key] = kg
        return kg.gen(alphas, betas, s0s)
    except Exception as e:  # fallback-ok: keygen must never fail for a
        # device-side reason — the host walk is always correct, and the
        # caller asked for keys, not for a particular pipeline.
        _DPF_DEVICE_FALLBACKS += 1
        warnings.warn(
            BackendFallbackWarning("dpf-device-keygen", "dpf_gen_batch", e),
            stacklevel=2)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # the facade edge already
            # validated the Hirose shape; don't re-warn from the fallback
            prg = HirosePrgNp(lam, cipher_keys)
        return dpf_gen_batch(prg, alphas, betas, s0s)
