"""Protocol-level key generation: interval bounds -> K-packed DCF keys.

An m-interval MIC needs one DCF key per interval BOUND — 2m keys.  The
structural observation this module is built on: those 2m keys are just
a K=2m batched keygen (one ``gen_batch`` call, the same host/native/
device pipelines as plain DCF), and the resulting ``KeyBundle`` is
exactly the K-axis-packed image the batched walk kernels are fastest
at.  Key ``2i`` carries interval i's LOWER bound, key ``2i+1`` its
UPPER bound; both use ``betas[i]``.

XOR-group derivation (differs from the paper's additive-group IC, which
subtracts shares; here subtraction IS addition):

    x < p  implies  x < q   (for p <= q), so
    1_{p <= x < q} = 1_{x < q} XOR 1_{x < p}

and each one-sided bound b in [0, N] decomposes over an LT-bound DCF as

    1_{x < b} = DCF_{< b mod N}(x) XOR [b == N]

(the b == N case keys alpha=0, whose DCF is identically 0, and the
public bit supplies the constant 1).  A wraparound interval p > q
(``[p, N) ∪ [0, q)``) is the COMPLEMENT of ``[q, p)``, adding one more
public XOR of beta.  Folding the three public bits together:

    1_{(p,q)}(x) = DCF_{<q%N} XOR DCF_{<p%N} XOR pub * 1,
    pub = [p > q] ^ [p == N] ^ [q == N]

For GT-bound keys the same algebra runs on 1_{x >= b} = GT_{(b-1) mod N}
XOR [b == 0], giving pub = [p == 0] ^ [q == 0] ^ [p > q].

The public correction ``pub * beta`` is applied at share-combine time as
a per-interval mask carried by the bundle: party 0's mask is
``pub * beta`` and party 1's is zero (the party-0 public-correction
scheme; the wire format stores a mask PER PARTY, so a dealer who wants
beta hidden from party 0 outside the interval can XOR-share the
correction across both masks instead — the combine is symmetric).

Additive output groups (``group`` in ``spec.GROUPS``, mod-2^w lanes)
run the SAME decomposition with signs instead of parities:

    1_{(p,q)}(x) = DCF_{<q%N} - DCF_{<p%N} + pub * 1,
    pub = [q == N] - [p == N] + [p > q]  in {-1, 0, +1}

(GT: 1_{x>=p} - 1_{x>=q} with pub = [p == 0] - [q == 0] + [p > q]).
Rather than teach the combine a per-bound sign pattern, the MINUS is
folded into the key betas at keygen time: the subtracted bound's key
(LT: the lower key 2i; GT: the upper key 2i+1) is generated with
``-beta`` so the combine stays the uniform ``y[2i] + y[2i+1] + mask``
— the exact characteristic-2 degeneration of the XOR path, where
``-beta == beta`` and ``+`` is ``^``.  The mask is the group-encoded
``pub * beta`` (``-beta`` bytes when pub = -1), carried by party 0.

Wire format: DCFK version 3 — the v2 frame plus a ``proto`` header
field and a trailing protocol section (bound byte + combine masks),
version-gated: v1/v2 frames (and v3 frames with proto=0) still decode
as plain ``KeyBundle``; ``KeyBundle.from_bytes`` on a proto!=0 frame
refuses with a pointer here instead of silently dropping the masks.
Additive protocol bundles write version 4 (the v3 header plus the
``group`` code, mirroring the plain-bundle v4 gate): a v3-era reader
refuses them loudly instead of reconstructing in the wrong group.
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np

from dcf_tpu.errors import KeyFormatError, ShapeError
from dcf_tpu.keys import (
    _CRC_SIZE,
    _HEADER3,
    _HEADER3_SIZE,
    _HEADER4,
    _HEADER4_SIZE,
    _MAGIC,
    _VERSION_GROUP,
    _VERSION_PROTO,
    KeyBundle,
    _decode_sections,
)
from dcf_tpu.spec import (
    GROUP_CODE,
    GROUP_FROM_CODE,
    GROUP_WIDTH,
    Bound,
    check_group,
)
from dcf_tpu.utils.groups import np_group_neg

__all__ = [
    "PROTO_MIC",
    "ProtocolBundle",
    "gen_interval_bundle",
    "interval_bound_alphas",
    "interval_session_material",
]

#: proto header values.  0 is reserved for "plain DCF" (decoded by
#: ``KeyBundle.from_bytes``); 1 is the interval-containment family (IC,
#: MIC, piecewise — all the same key structure, m intervals, 2m keys).
PROTO_MIC = 1

_BOUND_CODE = {Bound.LT_BETA: 0, Bound.GT_BETA: 1}
_BOUND_FROM = {v: k for k, v in _BOUND_CODE.items()}


def interval_bound_alphas(
    intervals: Sequence[tuple[int, int]], n_bytes: int,
    bound: Bound = Bound.LT_BETA, group: str = "xor",
) -> tuple[np.ndarray, np.ndarray]:
    """Intervals -> (alphas uint8 [2m, n_bytes], pub [m]).

    ``alphas[2i]``/``alphas[2i+1]`` are the DCF comparison points for
    interval i's lower/upper bound under ``bound``'s decomposition (see
    the module docstring); ``pub[i]`` is the public correction — a
    uint8 bit for the XOR group, a SIGNED int8 in {-1, 0, +1} for
    additive groups (same three indicator terms, summed instead of
    XORed; they never collide, so the sum stays in range and its parity
    IS the XOR bit).  The alphas are group-independent.  Shared by the
    host keygen below and any device-keygen caller
    (``backends.device_gen.DeviceKeyGen`` consumes these alphas as-is).
    """
    n_total = 1 << (8 * n_bytes)
    m = len(intervals)
    alphas = np.zeros((2 * m, n_bytes), dtype=np.uint8)
    signed = group != "xor"
    pub = np.zeros(m, dtype=np.int8 if signed else np.uint8)
    for i, (p, q) in enumerate(intervals):
        if not (0 <= p <= n_total and 0 <= q <= n_total):
            # api-edge: documented interval-bound contract (ints in
            # [0, 2^n_bits]; N itself legal so [p, N) is expressible)
            raise ValueError(
                f"interval {i} bounds must lie in [0, {n_total}], "
                f"got ({p}, {q})")
        if bound is Bound.LT_BETA:
            lo, hi = p % n_total, q % n_total
            pub[i] = ((q == n_total) - (p == n_total) + (p > q) if signed
                      else (p > q) ^ (p == n_total) ^ (q == n_total))
        else:
            lo, hi = (p - 1) % n_total, (q - 1) % n_total
            pub[i] = ((p == 0) - (q == 0) + (p > q) if signed
                      else (p == 0) ^ (q == 0) ^ (p > q))
        alphas[2 * i] = np.frombuffer(
            lo.to_bytes(n_bytes, "big"), dtype=np.uint8)
        alphas[2 * i + 1] = np.frombuffer(
            hi.to_bytes(n_bytes, "big"), dtype=np.uint8)
    return alphas, pub


@dataclass(frozen=True)
class ProtocolBundle:
    """An m-interval protocol key: 2m K-packed DCF keys + combine masks.

    ``keys``: the inner ``KeyBundle`` (K = 2m; two-party out of gen,
    party-restricted after ``for_party``).  ``combine_masks``: uint8
    [P, m, lam] — party b XORs ``combine_masks[b]`` onto its combined
    per-interval shares (``protocols.combine``); the default keygen puts
    the whole public correction in party 0's mask.  ``bound``: which
    DCF bound family the keys were generated under (the evaluators do
    not need it — the decomposition already absorbed it into the alphas
    and pub bits — but the wire format records it so a bundle is
    self-describing).
    """

    keys: KeyBundle
    combine_masks: np.ndarray  # uint8 [P, m, lam]
    bound: Bound = Bound.LT_BETA

    def __post_init__(self):
        k = self.keys.num_keys
        if k == 0 or k % 2:
            raise ShapeError(
                f"protocol bundles pack 2 DCF keys per interval; got "
                f"K={k}")
        p = self.keys.s0s.shape[1]
        want = (p, k // 2, self.keys.lam)
        if self.combine_masks.shape != want:
            raise ShapeError(
                f"combine_masks must be {want} (parties, intervals, "
                f"lam), got {self.combine_masks.shape}")
        if self.combine_masks.dtype != np.uint8:
            raise ShapeError("combine_masks must be uint8")
        if self.bound not in _BOUND_CODE:
            raise ShapeError(f"unknown bound {self.bound!r}")

    def __repr__(self) -> str:
        """Redacted: geometry only — the inner keys AND the masks are
        key material (a mask is ``pub*beta``: beta in the clear)."""
        return (f"ProtocolBundle(m={self.num_intervals}, "
                f"n_bits={self.keys.n_bits}, lam={self.lam}, "
                f"parties={self.combine_masks.shape[0]}, "
                f"bound={self.bound.value}, group={self.group}, "
                f"<key material redacted>)")

    @property
    def group(self) -> str:
        """The output group — carried by the inner keys (one source)."""
        return self.keys.group

    @property
    def num_intervals(self) -> int:
        return self.keys.num_keys // 2

    @property
    def lam(self) -> int:
        return self.keys.lam

    @property
    def n_bytes(self) -> int:
        return self.keys.n_bytes

    def masks_for(self, b: int) -> np.ndarray:
        """Party ``b``'s combine mask, uint8 [m, lam].  On a
        party-restricted bundle the single stored mask is returned
        (the restriction already chose the party)."""
        if self.combine_masks.shape[0] == 1:
            return self.combine_masks[0]
        if b not in (0, 1):
            # api-edge: documented party-index contract
            raise ValueError(f"party must be 0 or 1, got {b}")
        return self.combine_masks[b]

    def for_party(self, b: int) -> "ProtocolBundle":
        """Restrict to party ``b``: the inner keys AND the mask."""
        return ProtocolBundle(
            keys=self.keys.for_party(b),
            combine_masks=self.combine_masks[b : b + 1].copy(),
            bound=self.bound,
        )

    # -- codec (DCFK v3 / v4) -----------------------------------------------

    def to_bytes(self) -> bytes:
        """DCFK v3 frame: v2's sections + proto field + protocol section
        (bound byte, combine masks) + CRC32 trailer.  Additive bundles
        write v4 (v3's header + the group code) — XOR frames stay
        byte-identical to earlier releases, and a pre-v4 reader refuses
        an additive frame typed instead of combining with XOR algebra."""
        k, p = self.keys.s0s.shape[0], self.keys.s0s.shape[1]
        if self.group == "xor":
            header = _MAGIC + struct.pack(
                _HEADER3, _VERSION_PROTO, p, k, self.keys.n_bits,
                self.keys.lam, PROTO_MIC)
        else:
            header = _MAGIC + struct.pack(
                _HEADER4, _VERSION_GROUP, p, k, self.keys.n_bits,
                self.keys.lam, PROTO_MIC, GROUP_CODE[self.group])
        body = b"".join([
            header,
            self.keys.s0s.tobytes(),
            self.keys.cw_s.tobytes(),
            self.keys.cw_v.tobytes(),
            self.keys.cw_t.tobytes(),
            self.keys.cw_np1.tobytes(),
            bytes([_BOUND_CODE[self.bound]]),
            self.combine_masks.tobytes(),
        ])
        return body + struct.pack("<I", zlib.crc32(body))

    @classmethod
    def from_bytes(cls, data: bytes) -> "ProtocolBundle":
        """Strict bounds-checked decode of a v3/v4 proto frame; the same
        field-naming rejection discipline as ``KeyBundle.from_bytes``.
        Plain frames (v1/v2, or v3 with proto=0) are refused with a
        pointer at ``KeyBundle.from_bytes`` — a protocol evaluator fed
        a maskless bundle would silently skip the public correction.
        v4 frames carry the output-group code; an unknown code is
        refused rather than guessed."""
        if len(data) < 4 or data[:4] != _MAGIC:
            raise KeyFormatError(
                f"bad magic: expected {_MAGIC!r}, got {bytes(data[:4])!r} "
                "(not a DCFK frame)")
        if len(data) < _HEADER3_SIZE:
            raise KeyFormatError(
                f"truncated header: frame is {len(data)} bytes, the DCFK "
                f"v3 header needs {_HEADER3_SIZE}")
        version, p, k, n, lam, proto = struct.unpack_from(_HEADER3, data, 4)
        header_size = _HEADER3_SIZE
        group = "xor"
        if version == _VERSION_GROUP:
            if len(data) < _HEADER4_SIZE:
                raise KeyFormatError(
                    f"truncated header: frame is {len(data)} bytes, the "
                    f"DCFK v4 header needs {_HEADER4_SIZE}")
            version, p, k, n, lam, proto, group_code = struct.unpack_from(
                _HEADER4, data, 4)
            header_size = _HEADER4_SIZE
            if group_code not in GROUP_FROM_CODE:
                raise KeyFormatError(
                    f"unknown output-group code {group_code} (this reader "
                    f"handles {sorted(GROUP_FROM_CODE)}); refusing to "
                    "guess a combine group for key material")
            group = GROUP_FROM_CODE[group_code]
            if group != "xor" and (8 * lam) % GROUP_WIDTH[group]:
                raise KeyFormatError(
                    f"group {group!r} needs lam*8={8 * lam} divisible by "
                    f"{GROUP_WIDTH[group]} — corrupt or mismatched "
                    "header fields")
        elif version != _VERSION_PROTO:
            raise KeyFormatError(
                f"version {version} frames carry no protocol section; "
                "decode with KeyBundle.from_bytes")
        if proto != PROTO_MIC:
            if proto == 2:  # protocols.dpf.PROTO_DPF (no import cycle)
                raise KeyFormatError(
                    f"proto field {proto} is a DPF point-function frame; "
                    "decode with dcf_tpu.protocols.DpfBundle.from_bytes")
            raise KeyFormatError(
                f"proto field {proto} is not the interval-containment "
                f"family ({PROTO_MIC}); plain v3 frames (proto=0) decode "
                "with KeyBundle.from_bytes")
        if p not in (1, 2):
            raise KeyFormatError(f"parties field must be 1 or 2, got {p}")
        if n == 0 or n % 8:
            raise KeyFormatError(
                f"n field must be a positive multiple of 8 bits, got {n}")
        if lam == 0:
            raise KeyFormatError("lam field must be positive, got 0")
        if k == 0 or k % 2:
            raise KeyFormatError(
                f"K field must be a positive even key count (2 per "
                f"interval), got {k}")
        m = k // 2
        sections = (
            ("s0s", (k, p, lam)),
            ("cw_s", (k, n, lam)),
            ("cw_v", (k, n, lam)),
            ("cw_t", (k, n, 2)),
            ("cw_np1", (k, lam)),
            ("bound", (1,)),
            ("combine_masks", (p, m, lam)),
        )
        arrays = _decode_sections(
            data, sections, header_size, _CRC_SIZE,
            f"K={k}, P={p}, n={n}, lam={lam}")
        bound_code = int(arrays["bound"][0])
        if bound_code not in _BOUND_FROM:
            raise KeyFormatError(
                f"bound field must be 0 (LT) or 1 (GT), got {bound_code}")
        return cls(
            keys=KeyBundle(
                s0s=arrays["s0s"], cw_s=arrays["cw_s"],
                cw_v=arrays["cw_v"], cw_t=arrays["cw_t"],
                cw_np1=arrays["cw_np1"], group=group),
            combine_masks=arrays["combine_masks"],
            bound=_BOUND_FROM[bound_code],
        )


def gen_interval_bundle(
    gen_fn: Callable[[np.ndarray, np.ndarray, Bound], KeyBundle],
    intervals: Sequence[tuple[int, int]],
    betas: np.ndarray,
    n_bytes: int,
    bound: Bound = Bound.LT_BETA,
    group: str = "xor",
) -> ProtocolBundle:
    """Generate an m-interval protocol bundle through ``gen_fn``.

    ``gen_fn(alphas, betas, bound) -> KeyBundle`` is any K-batched DCF
    keygen — the facade's host path (native core when available, else
    ``gen.gen_batch``) or the on-device walk (``gen.gen_on_device``,
    what ``Dcf.mic(..., device=True)`` passes: the m-interval MIC's 2m
    bound keys are exactly the K-packed shape the device keygen kernel
    scales with — ISSUE 10).  The 2m bound keys land in ONE K-packed
    bundle: interval i's shares are keys 2i (lower) and 2i+1 (upper),
    both carrying ``betas[i]`` (up to the additive sign fold — see
    ``interval_session_material``).  The pipelines are byte-identical,
    so the ``ProtocolBundle`` wire frame does not record which one ran.

    ``group``: the output group the KEYS must be generated in — the
    caller's ``gen_fn`` closure carries it to the keygen (the facade's
    ``_protocol_gen`` does); the mismatch check below catches a closure
    that dropped it, because an XOR-keyed bundle combined with additive
    algebra reconstructs noise.
    """
    betas = np.asarray(betas, dtype=np.uint8)
    m = len(intervals)
    if m == 0:
        raise ShapeError("need at least one interval")
    if betas.ndim != 2 or betas.shape[0] != m:
        raise ShapeError(f"betas must be [{m}, lam], got {betas.shape}")
    check_group(group, betas.shape[1])
    alphas, key_betas, masks = interval_session_material(
        intervals, betas, n_bytes, bound, group)
    keys = gen_fn(alphas, key_betas, bound)
    if keys.group != group:
        raise ShapeError(
            f"gen_fn produced a {keys.group!r}-group bundle for a "
            f"{group!r} protocol — the keygen closure must thread the "
            "group through (Dcf._protocol_gen does)")
    return ProtocolBundle(keys=keys, combine_masks=masks, bound=bound)


def interval_session_material(
    intervals: Sequence[tuple[int, int]],
    betas: np.ndarray,
    n_bytes: int,
    bound: Bound = Bound.LT_BETA,
    group: str = "xor",
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """The ONE per-session MIC key-material derivation: intervals ->
    ``(alphas uint8 [2m, n_bytes], key_betas uint8 [2m, lam],
    combine_masks uint8 [2, m, lam])``.  Shared by
    ``gen_interval_bundle`` (host/device single-session gen) and the
    key factory's batched refill (ISSUE 11, which tiles the triple
    across a refill batch) — the combine convention must not be able
    to fork between a pooled MIC key and the sync-mint fallback.

    For additive groups the subtracted bound's key betas are NEGATED
    (LT: lower keys ``2i``; GT: upper keys ``2i+1``) so the pairwise
    combine stays the uniform ``y[2i] + y[2i+1] + mask`` — see the
    module docstring.  The party-0 mask is the group-encoded
    ``pub * beta`` with pub in {-1, 0, +1}."""
    alphas, pub = interval_bound_alphas(intervals, n_bytes, bound, group)
    masks = np.zeros((2,) + betas.shape, dtype=np.uint8)
    if group == "xor":
        masks[0] = betas * pub[:, None]  # party-0 public correction
        return alphas, np.repeat(betas, 2, axis=0), masks
    masks[0][pub > 0] = betas[pub > 0]
    masks[0][pub < 0] = np_group_neg(betas[pub < 0], group)
    key_betas = np.repeat(betas, 2, axis=0).copy()
    neg_slot = 0 if bound is Bound.LT_BETA else 1
    key_betas[neg_slot::2] = np_group_neg(betas, group)
    return alphas, key_betas, masks
