"""Share-combine algebra for the protocol layer.

Every protocol in this package reduces to the same local step: party b
evaluates the 2m K-packed bound keys, combines adjacent key pairs
(interval i = keys 2i ∘ 2i+1) and folds in its per-interval combine
mask.  In the XOR output group ``∘`` is XOR; in an additive group it is
the per-lane mod-2^w add — the keygen already folded the decomposition's
minus sign into the key betas (``keygen.interval_session_material``), so
the combine is the SAME uniform pairwise sum for every group and every
bound.  The step is local and linear, so it runs unchanged on host
uint8 bytes OR on device arrays — and for the staged plane layouts it
runs BEFORE the planes->bytes conversion, halving the conversion volume
(2m keys in, m intervals out); additive staged combines ride the
``ops.group_accum`` ripple adders in the same plane domain the eval
kernels accumulate in.

``fire("protocols.combine", m, points)`` is the fault seam: it sits at
the exact spot where a combine-time failure (a bad mask shape, a dead
device mid-XOR) would surface, so the serving layer's retry path and
the evaluators' error contracts are deterministically testable
(``dcf_tpu.testing.faults``).

``xor_reconstruct_stream`` is the two-party reconstruction loop
streaming over the key axis — the protocol layer's generic "both
parties, chunked K" primitive that ``workloads.secure_relu_eval`` is a
thin client of.  The name records its XOR origin; it reconstructs in
the bundle's group (``group_add(y0, y1)``).
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.spec import GROUP_WIDTH
from dcf_tpu.testing.faults import fire
from dcf_tpu.utils.groups import np_group_add

__all__ = [
    "combine_pair_shares",
    "staged_pair_combine",
    "xor_reconstruct_stream",
]


def combine_pair_shares(y, masks_b: np.ndarray | None, group: str = "xor"):
    """Pairwise share combine: y [2m, M, lam] -> [m, M, lam].

    ``y`` may be host uint8 (numpy) or a device array (jax) — for XOR
    the combine stays wherever the shares already live; additive groups
    need the little-endian lane view, so ``y`` is materialized to host
    bytes first (device-resident additive combines go through
    ``staged_pair_combine`` instead, in the plane domain).  ``masks_b``:
    this party's uint8 [m, lam] combine mask
    (``ProtocolBundle.masks_for``), or None to skip the public
    correction (an already-masked device path).
    """
    if y.ndim != 3 or y.shape[0] % 2:
        raise ShapeError(
            f"expected [2m, M, lam] bound-key shares, got {y.shape}")
    fire("protocols.combine", y.shape[0] // 2, y.shape[1])
    if group == "xor":
        yc = y[0::2] ^ y[1::2]
        if masks_b is not None:
            _check_mask(masks_b, yc)
            yc = yc ^ masks_b[:, None, :]
        return yc
    y = np.asarray(y)
    yc = np_group_add(y[0::2], y[1::2], group)
    if masks_b is not None:
        _check_mask(masks_b, yc)
        yc = np_group_add(yc, masks_b[:, None, :], group)
    return yc


def _check_mask(masks_b: np.ndarray, yc) -> None:
    if masks_b.shape != (yc.shape[0], yc.shape[2]):
        raise ShapeError(
            f"combine mask must be [{yc.shape[0]}, {yc.shape[2]}], "
            f"got {masks_b.shape}")


# Staged-plane key-axis table: which axis of ``eval_staged``'s output
# carries K for each staged backend family.  Bit-major Pallas layouts
# are [K, 128, W]; the byte-major bitsliced layout is [8*lam, K, W].
# Matched over the backend's MRO (by class NAME, so this module never
# imports the jax-heavy backend classes), so subclasses of a listed
# family inherit its axis.  Backends matching nothing
# (keys-packed-in-lanes, the hybrid's dict-valued staging, host paths)
# fall back to the bytes-domain combine — correct everywhere, just
# without the pre-conversion halving.
_KEY_AXIS = {
    "PallasBackend": 0,
    "PrefixPallasBackend": 0,
    "ShardedPallasBackend": 0,
    "ShardedPrefixBackend": 0,
    "BitslicedBackend": 1,
}


def staged_pair_combine(be, y_dev, group: str = "xor"):
    """Device-side pairwise combine of ``be.eval_staged`` output, or
    ``None`` when ``be``'s staged layout is not in the key-axis table
    (caller then combines after ``staged_to_bytes``).  Additive groups
    combine with the plane-domain ripple adders (``ops.group_accum``) —
    bit-major [K, 128, W] blocks for the Pallas families, byte-major
    [8*lam, K, W] slabs for the bitsliced family — and fall back to
    ``None`` for a layout whose plane geometry doesn't match.  The mask
    is NOT applied here — layouts differ; apply it via
    ``combine_pair_shares(..., masks_b)`` on the converted bytes or
    fold it on host."""
    axis = next((_KEY_AXIS[c.__name__] for c in type(be).__mro__
                 if c.__name__ in _KEY_AXIS), None)
    if axis is None:
        return None
    if group == "xor":
        fire("protocols.combine", y_dev.shape[axis] // 2, -1)
        if axis == 0:
            return y_dev[0::2] ^ y_dev[1::2]
        return y_dev[:, 0::2] ^ y_dev[:, 1::2]
    w = GROUP_WIDTH[group]
    if axis == 0:
        if y_dev.ndim != 3 or y_dev.shape[1] != 128:
            return None  # not the bit-major [K, 128, W] block layout
        import jax

        from dcf_tpu.ops.group_accum import planes_add_bitmajor16

        fire("protocols.combine", y_dev.shape[0] // 2, -1)
        return jax.vmap(
            lambda a, c: planes_add_bitmajor16(a, c, w)
        )(y_dev[0::2], y_dev[1::2])
    if y_dev.ndim != 3 or y_dev.shape[0] % 8:
        return None  # not the byte-major [8*lam, K, W] slab layout
    from dcf_tpu.ops.group_accum import planes_add_bytemajor

    fire("protocols.combine", y_dev.shape[1] // 2, -1)
    return planes_add_bytemajor(y_dev[:, 0::2], y_dev[:, 1::2], w)


def xor_reconstruct_stream(
    backend0, backend1, bundle: KeyBundle, xs: np.ndarray,
    key_chunk: int = 1 << 16,
) -> np.ndarray:
    """Two-party reconstruction of K keys on M shared points in the
    bundle's output group, streamed over the key axis: uint8 [K, M, lam].

    ``backend0``/``backend1``: evaluators holding the two party roles
    (``put_bundle`` via the ``bundle=`` kwarg + ``eval``).  Keys stream
    through the device in ``key_chunk`` slices so the full key image
    (10^6 keys in the secure-ReLU shape) never needs to be HBM-resident
    at once.  This is the generic primitive under
    ``workloads.secure_relu_eval`` and the protocol test harnesses.
    """
    k = bundle.num_keys
    m, lam = xs.shape[0], bundle.lam
    out = np.empty((k, m, lam), dtype=np.uint8)
    for lo in range(0, k, key_chunk):
        hi = min(k, lo + key_chunk)
        sub = KeyBundle(
            s0s=bundle.s0s[lo:hi],
            cw_s=bundle.cw_s[lo:hi],
            cw_v=bundle.cw_v[lo:hi],
            cw_t=bundle.cw_t[lo:hi],
            cw_np1=bundle.cw_np1[lo:hi],
            group=bundle.group,
        )
        y0 = backend0.eval(0, xs, bundle=sub.for_party(0))
        y1 = backend1.eval(1, xs, bundle=sub.for_party(1))
        out[lo:hi] = np_group_add(np.asarray(y0), np.asarray(y1),
                                  bundle.group)
    return out
