"""Share-combine algebra for the protocol layer.

Every protocol in this package reduces to the same local step: party b
evaluates the 2m K-packed bound keys, XORs adjacent key pairs
(interval i = keys 2i ^ 2i+1) and XORs its per-interval combine mask.
That step is pure XOR, so it runs unchanged on host uint8 bytes OR on
device arrays — and for the staged plane layouts it runs BEFORE the
planes->bytes conversion, halving the conversion volume (2m keys in, m
intervals out).

``fire("protocols.combine", m, points)`` is the fault seam: it sits at
the exact spot where a combine-time failure (a bad mask shape, a dead
device mid-XOR) would surface, so the serving layer's retry path and
the evaluators' error contracts are deterministically testable
(``dcf_tpu.testing.faults``).

``xor_reconstruct_stream`` is the two-party XOR reconstruction loop
streaming over the key axis — the protocol layer's generic "both
parties, chunked K" primitive that ``workloads.secure_relu_eval`` is a
thin client of.
"""

from __future__ import annotations

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.testing.faults import fire

__all__ = [
    "combine_pair_shares",
    "staged_pair_combine",
    "xor_reconstruct_stream",
]


def combine_pair_shares(y, masks_b: np.ndarray | None):
    """Pairwise share combine: y [2m, M, lam] -> [m, M, lam].

    ``y`` may be host uint8 (numpy) or a device array (jax) — XOR and
    strided slicing mean the combine stays wherever the shares already
    live.  ``masks_b``: this party's uint8 [m, lam] combine mask
    (``ProtocolBundle.masks_for``), or None to skip the public
    correction (an already-masked device path).
    """
    if y.ndim != 3 or y.shape[0] % 2:
        raise ShapeError(
            f"expected [2m, M, lam] bound-key shares, got {y.shape}")
    fire("protocols.combine", y.shape[0] // 2, y.shape[1])
    yc = y[0::2] ^ y[1::2]
    if masks_b is not None:
        if masks_b.shape != (yc.shape[0], yc.shape[2]):
            raise ShapeError(
                f"combine mask must be [{yc.shape[0]}, {yc.shape[2]}], "
                f"got {masks_b.shape}")
        yc = yc ^ masks_b[:, None, :]
    return yc


# Staged-plane key-axis table: which axis of ``eval_staged``'s output
# carries K for each staged backend family.  Bit-major Pallas layouts
# are [K, 128, W]; the byte-major bitsliced layout is [8*lam, K, W].
# Matched over the backend's MRO (by class NAME, so this module never
# imports the jax-heavy backend classes), so subclasses of a listed
# family inherit its axis.  Backends matching nothing
# (keys-packed-in-lanes, the hybrid's dict-valued staging, host paths)
# fall back to the bytes-domain combine — correct everywhere, just
# without the pre-conversion halving.
_KEY_AXIS = {
    "PallasBackend": 0,
    "PrefixPallasBackend": 0,
    "ShardedPallasBackend": 0,
    "ShardedPrefixBackend": 0,
    "BitslicedBackend": 1,
}


def staged_pair_combine(be, y_dev):
    """Device-side pairwise combine of ``be.eval_staged`` output, or
    ``None`` when ``be``'s staged layout is not in the key-axis table
    (caller then combines after ``staged_to_bytes``).  The mask XOR is
    NOT applied here — layouts differ; apply it via
    ``combine_pair_shares(..., masks_b)`` on the converted bytes or
    fold it on host."""
    axis = next((_KEY_AXIS[c.__name__] for c in type(be).__mro__
                 if c.__name__ in _KEY_AXIS), None)
    if axis is None:
        return None
    fire("protocols.combine", y_dev.shape[axis] // 2, -1)
    if axis == 0:
        return y_dev[0::2] ^ y_dev[1::2]
    return y_dev[:, 0::2] ^ y_dev[:, 1::2]


def xor_reconstruct_stream(
    backend0, backend1, bundle: KeyBundle, xs: np.ndarray,
    key_chunk: int = 1 << 16,
) -> np.ndarray:
    """Two-party XOR reconstruction of K keys on M shared points,
    streamed over the key axis: uint8 [K, M, lam].

    ``backend0``/``backend1``: evaluators holding the two party roles
    (``put_bundle`` via the ``bundle=`` kwarg + ``eval``).  Keys stream
    through the device in ``key_chunk`` slices so the full key image
    (10^6 keys in the secure-ReLU shape) never needs to be HBM-resident
    at once.  This is the generic primitive under
    ``workloads.secure_relu_eval`` and the protocol test harnesses.
    """
    k = bundle.num_keys
    m, lam = xs.shape[0], bundle.lam
    out = np.empty((k, m, lam), dtype=np.uint8)
    for lo in range(0, k, key_chunk):
        hi = min(k, lo + key_chunk)
        sub = KeyBundle(
            s0s=bundle.s0s[lo:hi],
            cw_s=bundle.cw_s[lo:hi],
            cw_v=bundle.cw_v[lo:hi],
            cw_t=bundle.cw_t[lo:hi],
            cw_np1=bundle.cw_np1[lo:hi],
        )
        y0 = backend0.eval(0, xs, bundle=sub.for_party(0))
        y1 = backend1.eval(1, xs, bundle=sub.for_party(1))
        out[lo:hi] = y0 ^ y1
    return out
