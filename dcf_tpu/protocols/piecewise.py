"""Piecewise-constant function evaluation as a MIC over a partition.

A piecewise-constant f with m pieces is an m-interval MIC whose
intervals PARTITION the domain: exactly one indicator fires per point,
so the group sum over the per-interval rows collapses to the containing
piece's value — "sum of selected values" and "the selected value"
coincide in ANY output group when exactly one indicator fires, which is
what makes the spline lookup a pure reduce over the MIC output.  In the
XOR group that needs no arithmetic shares at all; in an additive group
the same reduce yields ADDITIVE shares of the piece value — the form
the fixed-point gates (``protocols.fixedpoint``) compose further.  The last interval wraps (``[cuts[-1], N) ∪ [0, cuts[0])``),
so with ``cuts[0] == 0`` the table covers [0, N) in the standard way
and the wraparound machinery costs nothing extra.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from dcf_tpu.protocols.keygen import ProtocolBundle
from dcf_tpu.protocols.mic import eval_mic
from dcf_tpu.utils.groups import np_group_reduce

__all__ = ["eval_piecewise", "partition_intervals"]


def partition_intervals(cuts: Sequence[int],
                        n_bits: int) -> list[tuple[int, int]]:
    """Breakpoints -> the m partition intervals (last one wrapping).

    ``cuts``: strictly increasing ints in [0, 2^n_bits).  Returns
    ``[(cuts[0], cuts[1]), ..., (cuts[-1], cuts[0])]`` — the final
    pair wraps around the domain top (with ``cuts[0] == 0`` it
    degenerates to the plain suffix ``[cuts[-1], N)``).  A single cut
    would yield ``(c, c)``, which the interval convention reads as
    EMPTY, so m == 1 maps to the explicit full-domain interval
    ``(0, N)`` instead: a one-piece table is the constant function.
    """
    n_total = 1 << n_bits
    m = len(cuts)
    if m == 0:
        # api-edge: documented breakpoint contract
        raise ValueError("need at least one breakpoint")
    for i, c in enumerate(cuts):
        if not 0 <= c < n_total:
            # api-edge: documented breakpoint contract
            raise ValueError(
                f"cut {i} must lie in [0, {n_total}), got {c}")
        if i and c <= cuts[i - 1]:
            # api-edge: documented breakpoint contract
            raise ValueError(
                f"cuts must be strictly increasing, got {cuts[i - 1]} "
                f"then {c}")
    if m == 1:
        return [(0, n_total)]  # one piece == the constant function
    out = [(cuts[i], cuts[i + 1]) for i in range(m - 1)]
    out.append((cuts[-1], cuts[0]))  # wraparound back to the first cut
    return out


def eval_piecewise(dcf, b: int, pb: ProtocolBundle,
                   xs: np.ndarray) -> np.ndarray:
    """Party ``b``'s piecewise-lookup share: uint8 [M, lam] — the
    group-sum reduce of the MIC rows (XOR in the default group; mod-2^w
    lane sums for additive bundles, where the share rows are uniform
    and only the reduce in the RIGHT group telescopes to the containing
    piece).  Valid because the bundle's intervals partition the domain;
    ``Dcf.piecewise`` builds exactly that."""
    rows = eval_mic(dcf, b, pb, xs)  # [m, M, lam]
    return np_group_reduce(rows, pb.group, axis=0)
