"""Host-side batched key generation.

Vectorized numpy port of the GGM keygen (reference src/lib.rs:86-161) over a
key axis: K comparison functions are processed level-by-level with one batched
PRG call per party per level (2K AES-256 block pairs), instead of the
reference's one-key-at-a-time loop.  Keygen is inherently sequential across
the n = 8*n_bytes levels (level i consumes level i-1's seeds), so it stays on
the host; keys are generated once and shipped to HBM for evaluation.

A C++ fast path with the same output lives in ``dcf_tpu.native``.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from dcf_tpu.errors import ShapeError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.spec import Bound

__all__ = ["gen_batch", "random_s0s"]


def random_s0s(num_keys: int, lam: int, rng: np.random.Generator) -> np.ndarray:
    """Sample the two random starting seeds per key: uint8 [K, 2, lam]."""
    return rng.integers(0, 256, size=(num_keys, 2, lam), dtype=np.uint8)


def _sel(left: np.ndarray, right: np.ndarray, take_right: np.ndarray) -> np.ndarray:
    """Per-key child selection; take_right is uint8 [K] broadcast over trailing dims."""
    cond = take_right.astype(bool).reshape(-1, *([1] * (left.ndim - 1)))
    return np.where(cond, right, left)


def gen_batch(
    prg: HirosePrgNp,
    alphas: np.ndarray,
    betas: np.ndarray,
    s0s: np.ndarray,
    bound: Bound,
) -> KeyBundle:
    """Generate K DCF keys at once.

    alphas: uint8 [K, n_bytes]; betas: uint8 [K, lam]; s0s: uint8 [K, 2, lam].
    Returns a two-party KeyBundle (s0s retained with P=2).
    """
    k_num, n_bytes = alphas.shape
    lam = prg.lam
    if betas.shape != (k_num, lam) or s0s.shape != (k_num, 2, lam):
        raise ShapeError("alphas/betas/s0s shape mismatch")
    n = 8 * n_bytes
    # MSB-first bit planes of alpha: uint8 [K, n] (np.unpackbits is MSB-first,
    # matching the reference's Msb0 bit view at src/lib.rs:106).
    alpha_bits = np.unpackbits(alphas, axis=1)

    s_a = s0s[:, 0, :].copy()  # party 0 seeds [K, lam]
    s_b = s0s[:, 1, :].copy()  # party 1 seeds
    t_a = np.zeros(k_num, dtype=np.uint8)  # t^(0)_0 = 0
    t_b = np.ones(k_num, dtype=np.uint8)  # t^(0)_1 = 1
    v_alpha = np.zeros((k_num, lam), dtype=np.uint8)

    cw_s = np.zeros((k_num, n, lam), dtype=np.uint8)
    cw_v = np.zeros((k_num, n, lam), dtype=np.uint8)
    cw_t = np.zeros((k_num, n, 2), dtype=np.uint8)

    for i in range(n):
        p0 = prg.gen(s_a)
        p1 = prg.gen(s_b)
        a_i = alpha_bits[:, i]  # 1 -> keep R / lose L
        # lose side: R when a_i == 0, L when a_i == 1.
        lose_is_r = (a_i ^ 1).astype(np.uint8)
        s_cw = _sel(p0.s_l, p0.s_r, lose_is_r) ^ _sel(p1.s_l, p1.s_r, lose_is_r)
        v_cw = (
            _sel(p0.v_l, p0.v_r, lose_is_r)
            ^ _sel(p1.v_l, p1.v_r, lose_is_r)
            ^ v_alpha
        )
        # beta folds into v_cw when the lose side matches the bound
        # (src/lib.rs:114-125): LT_BETA on lose==L (a_i==1), GT_BETA on
        # lose==R (a_i==0).
        beta_gate = a_i if bound is Bound.LT_BETA else (a_i ^ 1)
        v_cw ^= betas * beta_gate[:, None]
        v_alpha ^= _sel(p0.v_l, p0.v_r, a_i) ^ _sel(p1.v_l, p1.v_r, a_i) ^ v_cw
        tl_cw = p0.t_l ^ p1.t_l ^ a_i ^ 1
        tr_cw = p0.t_r ^ p1.t_r ^ a_i
        cw_s[:, i] = s_cw
        cw_v[:, i] = v_cw
        cw_t[:, i, 0] = tl_cw
        cw_t[:, i, 1] = tr_cw
        t_cw_keep = _sel(tl_cw, tr_cw, a_i)
        new_s_a = _sel(p0.s_l, p0.s_r, a_i) ^ s_cw * t_a[:, None]
        new_s_b = _sel(p1.s_l, p1.s_r, a_i) ^ s_cw * t_b[:, None]
        new_t_a = _sel(p0.t_l, p0.t_r, a_i) ^ (t_a & t_cw_keep)
        new_t_b = _sel(p1.t_l, p1.t_r, a_i) ^ (t_b & t_cw_keep)
        s_a, s_b, t_a, t_b = new_s_a, new_s_b, new_t_a, new_t_b

    cw_np1 = s_a ^ s_b ^ v_alpha
    return KeyBundle(
        s0s=s0s.copy(), cw_s=cw_s, cw_v=cw_v, cw_t=cw_t, cw_np1=cw_np1
    )
