"""Batched key generation: the host walk and the on-device router.

Keygen (reference src/lib.rs:86-161) is sequential across the
n = 8*n_bytes levels (level i consumes level i-1's seeds) but
embarrassingly parallel across keys, and at production scale — fresh
keys per session, the protocol layer packing 2m bound keys per MIC
query class — it is a first-class hot path, not a setup step.  Three
pipelines produce byte-identical ``KeyBundle``s:

* ``gen_batch`` (this module): the vectorized numpy walk — K comparison
  functions processed level-by-level with one batched PRG call per
  party per level (2K AES-256 block pairs), instead of the reference's
  one-key-at-a-time loop.  The portable floor and the parity oracle.
* the C++ native core (``dcf_tpu.native``, AES-NI): the fast HOST path,
  what the facade uses by default when the toolchain is present.
* ``gen_on_device`` (this module's router): the GGM level walk run ON
  the accelerator.  For lam >= 48 it is ``ops.pallas_keygen`` — the
  narrow keygen walk as one K-packed Pallas kernel sharing the per-level
  AES core (``make_narrow_aes`` + ``narrow_prg_expand``) with the eval
  kernels, plus the GF(2)-affine wide correction words; for smaller lam
  it is the keys-in-lanes XLA generator (``backends.device_gen``).
  The facade spelling is ``Dcf.gen(..., device=True)``.

When does the device path win?  The walk is sequential across levels,
so a SINGLE key gains nothing; the win is the key axis.  K keys cost
the same n-level latency as one (the kernel lanes and the AES cores
are K-wide), so throughput scales with K until the lane budget — the
MIC shape (K = 2m) and session-keygen bursts are exactly that regime,
and the correction-word image is born in HBM next to the evaluators
that will consume it instead of crossing the host link.  Interop,
wire-format and durable-store consumers see identical DCFK bytes
either way.  ``python -m dcf_tpu.cli keygen_bench`` measures keys/s
against the pinned single-core host baseline (CPU_BASELINE.md).

Knobs (``gen_on_device``): ``interpret`` (None = auto: Mosaic on TPU,
the Pallas interpreter elsewhere — the keylanes rule), ``tile_words``
(kernel lane tile).  Failures of the device path fall back to
``gen_batch`` — silent-correct, counted by ``device_fallback_count()``,
warned via ``errors.BackendFallbackWarning``, and injectable at the
``keygen.device`` fault seam (``testing.faults``).
"""

from __future__ import annotations

import warnings
from functools import partial

import numpy as np

from dcf_tpu.errors import BackendFallbackWarning, ShapeError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.ops.prg import HirosePrgNp
from dcf_tpu.spec import Bound, check_group
from dcf_tpu.utils.groups import bytes_of, lanes_of

__all__ = ["gen_batch", "gen_on_device", "gen_on_device_with_planes",
           "random_s0s", "device_fallback_count"]


def random_s0s(num_keys: int, lam: int, rng: np.random.Generator) -> np.ndarray:
    """Sample the two random starting seeds per key: uint8 [K, 2, lam]."""
    return rng.integers(0, 256, size=(num_keys, 2, lam), dtype=np.uint8)


def _sel(left: np.ndarray, right: np.ndarray, take_right: np.ndarray) -> np.ndarray:
    """Per-key child selection; take_right is uint8 [K] broadcast over trailing dims."""
    cond = take_right.astype(bool).reshape(-1, *([1] * (left.ndim - 1)))
    return np.where(cond, right, left)


def _check_gen_inputs(alphas, betas, s0s, lam: int) -> None:
    """Typed api-edge validation shared by every keygen pipeline: a
    non-uint8 array must die ``ShapeError`` naming the argument, not as
    ``np.unpackbits``'s bare TypeError deep in the walk."""
    for name, arr in (("alphas", alphas), ("betas", betas), ("s0s", s0s)):
        if not isinstance(arr, np.ndarray) or arr.dtype != np.uint8:
            got = (arr.dtype if isinstance(arr, np.ndarray)
                   else type(arr).__name__)
            raise ShapeError(
                f"{name} must be a uint8 numpy array (got {got}); key "
                "material is byte-exact — cast explicitly, never "
                "implicitly")
    k_num = alphas.shape[0] if alphas.ndim == 2 else -1
    if alphas.ndim != 2 or alphas.shape[1] < 1:
        raise ShapeError(
            f"alphas must be [K, n_bytes], got {alphas.shape}")
    if betas.shape != (k_num, lam) or s0s.shape != (k_num, 2, lam):
        raise ShapeError("alphas/betas/s0s shape mismatch")


def gen_batch(
    prg: HirosePrgNp,
    alphas: np.ndarray,
    betas: np.ndarray,
    s0s: np.ndarray,
    bound: Bound,
    group: str = "xor",
) -> KeyBundle:
    """Generate K DCF keys at once (host numpy walk).

    alphas: uint8 [K, n_bytes]; betas: uint8 [K, lam]; s0s: uint8 [K, 2, lam].
    Returns a two-party KeyBundle (s0s retained with P=2).

    ``group`` selects the output group (spec.GROUPS).  The tree walk
    (seeds, t-bits) is group-independent; the additive groups change only
    the value correction-word algebra (Boyle et al. Fig. 1 — see
    ``spec.gen``), vectorized here in the little-endian lane domain.
    """
    lam = prg.lam
    check_group(group, lam)
    _check_gen_inputs(alphas, betas, s0s, lam)
    k_num, n_bytes = alphas.shape
    n = 8 * n_bytes
    additive = group != "xor"
    # MSB-first bit planes of alpha: uint8 [K, n] (np.unpackbits is MSB-first,
    # matching the reference's Msb0 bit view at src/lib.rs:106).
    alpha_bits = np.unpackbits(alphas, axis=1)

    s_a = s0s[:, 0, :].copy()  # party 0 seeds [K, lam]
    s_b = s0s[:, 1, :].copy()  # party 1 seeds
    t_a = np.zeros(k_num, dtype=np.uint8)  # t^(0)_0 = 0
    t_b = np.ones(k_num, dtype=np.uint8)  # t^(0)_1 = 1
    v_alpha = np.zeros((k_num, lam), dtype=np.uint8)
    if additive:
        lanes = partial(lanes_of, group=group)
        va = lanes(v_alpha)  # lane-domain V_alpha accumulator
        betas_l = lanes(betas)

    cw_s = np.zeros((k_num, n, lam), dtype=np.uint8)
    cw_v = np.zeros((k_num, n, lam), dtype=np.uint8)
    cw_t = np.zeros((k_num, n, 2), dtype=np.uint8)

    for i in range(n):
        p0 = prg.gen(s_a)
        p1 = prg.gen(s_b)
        a_i = alpha_bits[:, i]  # 1 -> keep R / lose L
        # lose side: R when a_i == 0, L when a_i == 1.
        lose_is_r = (a_i ^ 1).astype(np.uint8)
        s_cw = _sel(p0.s_l, p0.s_r, lose_is_r) ^ _sel(p1.s_l, p1.s_r, lose_is_r)
        # beta folds into v_cw when the lose side matches the bound
        # (src/lib.rs:114-125): LT_BETA on lose==L (a_i==1), GT_BETA on
        # lose==R (a_i==0).
        beta_gate = a_i if bound is Bound.LT_BETA else (a_i ^ 1)
        if not additive:
            v_cw = (
                _sel(p0.v_l, p0.v_r, lose_is_r)
                ^ _sel(p1.v_l, p1.v_r, lose_is_r)
                ^ v_alpha
            )
            v_cw ^= betas * beta_gate[:, None]
            v_alpha ^= (_sel(p0.v_l, p0.v_r, a_i)
                        ^ _sel(p1.v_l, p1.v_r, a_i) ^ v_cw)
        else:
            # V_CW <- (-1)^{t1} * [Convert(v1_lose) - Convert(v0_lose)
            #                      - V_alpha + beta_gate * beta]
            sign = t_b.astype(bool)[:, None]  # party 1's t on the alpha path
            vcw_l = (lanes(_sel(p1.v_l, p1.v_r, lose_is_r))
                     - lanes(_sel(p0.v_l, p0.v_r, lose_is_r)) - va
                     + betas_l * beta_gate[:, None].astype(betas_l.dtype))
            vcw_l = np.where(sign, -vcw_l, vcw_l)
            # V_alpha <- V_alpha - Convert(v1_keep) + Convert(v0_keep)
            #            + (-1)^{t1} * V_CW
            va = (va - lanes(_sel(p1.v_l, p1.v_r, a_i))
                  + lanes(_sel(p0.v_l, p0.v_r, a_i))
                  + np.where(sign, -vcw_l, vcw_l))
            v_cw = bytes_of(vcw_l, group)
        tl_cw = p0.t_l ^ p1.t_l ^ a_i ^ 1
        tr_cw = p0.t_r ^ p1.t_r ^ a_i
        cw_s[:, i] = s_cw
        cw_v[:, i] = v_cw
        cw_t[:, i, 0] = tl_cw
        cw_t[:, i, 1] = tr_cw
        t_cw_keep = _sel(tl_cw, tr_cw, a_i)
        new_s_a = _sel(p0.s_l, p0.s_r, a_i) ^ s_cw * t_a[:, None]
        new_s_b = _sel(p1.s_l, p1.s_r, a_i) ^ s_cw * t_b[:, None]
        new_t_a = _sel(p0.t_l, p0.t_r, a_i) ^ (t_a & t_cw_keep)
        new_t_b = _sel(p1.t_l, p1.t_r, a_i) ^ (t_b & t_cw_keep)
        s_a, s_b, t_a, t_b = new_s_a, new_s_b, new_t_a, new_t_b

    if not additive:
        cw_np1 = s_a ^ s_b ^ v_alpha
    else:
        # CW_{n+1} <- (-1)^{t1_n} * [Convert(s1_n) - Convert(s0_n) - V_alpha]
        last = lanes(s_b) - lanes(s_a) - va
        cw_np1 = bytes_of(
            np.where(t_b.astype(bool)[:, None], -last, last), group)
    return KeyBundle(
        s0s=s0s.copy(), cw_s=cw_s, cw_v=cw_v, cw_t=cw_t, cw_np1=cw_np1,
        group=group,
    )


# -- the on-device router -----------------------------------------------------

# Device generators hold only derived cipher state (bit-major round-key
# masks); cached per (lam, cipher_keys, interpret, tile_words) so repeated
# facade/bench calls don't re-expand round keys.  Small and bounded.
_DEVICE_GENS: dict = {}
_DEVICE_GENS_CAP = 16
_DEVICE_FALLBACKS = 0


def device_fallback_count() -> int:
    """How many ``gen_on_device`` calls fell back to the host walk this
    process (chaos tests assert the fallback is silent-correct AND
    counted)."""
    return _DEVICE_FALLBACKS


def _device_gen_for(lam: int, cipher_keys, interpret: bool,
                    tile_words: int):
    key = (lam, tuple(cipher_keys), interpret, tile_words)
    kg = _DEVICE_GENS.get(key)
    if kg is None:
        if len(_DEVICE_GENS) >= _DEVICE_GENS_CAP:
            _DEVICE_GENS.pop(next(iter(_DEVICE_GENS)))
        if lam >= 48 and lam % 16 == 0:
            from dcf_tpu.ops.pallas_keygen import PallasKeyGen

            kg = PallasKeyGen(lam, cipher_keys, interpret=interpret,
                              tile_words=tile_words)
        else:
            from dcf_tpu.backends.device_gen import DeviceKeyGen

            kg = DeviceKeyGen(lam, cipher_keys)
        _DEVICE_GENS[key] = kg
    return kg


def gen_on_device(
    lam: int,
    cipher_keys,
    alphas: np.ndarray,
    betas: np.ndarray,
    s0s: np.ndarray,
    bound: Bound,
    *,
    group: str = "xor",
    interpret: bool | None = None,
    tile_words: int = 128,
) -> KeyBundle:
    """Generate K keys with the GGM level walk ON the accelerator.

    ``group`` other than ``"xor"`` routes to the host ``gen_batch`` walk
    directly (NOT a counted fallback): the device keygen kernels and the
    C++ native core implement the characteristic-2 correction-word
    algebra only, while the additive groups need the signed lane algebra
    — a documented routing decision, not a failure.

    Routes lam >= 48 to the Pallas narrow keygen kernel + affine wide
    tail (``ops.pallas_keygen`` — one shared level-walk core with the
    eval kernels) and smaller lams to the keys-in-lanes XLA generator
    (``backends.device_gen``).  ``interpret=None`` applies the keylanes
    rule: Mosaic on TPU, the Pallas interpreter elsewhere.  Returns the
    host two-party ``KeyBundle``, byte-identical to ``gen_batch`` on
    the same ``(alphas, betas, s0s, bound)`` — wire frames, serve
    registration and the durable store cannot tell the pipelines apart.

    Any device failure (lowering, OOM, a broken install — injectable at
    the ``keygen.device`` seam) falls back to the host ``gen_batch``:
    silent-correct, counted (``device_fallback_count``), warned once per
    call via ``BackendFallbackWarning``.
    """
    if group != "xor":
        check_group(group, lam)
        _check_gen_inputs(alphas, betas, s0s, lam)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            prg = HirosePrgNp(lam, cipher_keys)
        return gen_batch(prg, alphas, betas, s0s, bound, group)
    return _gen_on_device(lam, cipher_keys, alphas, betas, s0s, bound,
                          interpret=interpret, tile_words=tile_words,
                          want_planes=False)[0]


def gen_on_device_with_planes(
    lam: int,
    cipher_keys,
    alphas: np.ndarray,
    betas: np.ndarray,
    s0s: np.ndarray,
    bound: Bound,
    *,
    interpret: bool | None = None,
    tile_words: int = 128,
) -> tuple[KeyBundle, dict | None]:
    """``gen_on_device`` plus the staged narrow image: returns
    ``(bundle, planes)`` where ``planes`` is ``{party: staged plane
    dict}`` for BOTH parties from the SAME kernel walk
    (``ops.pallas_keygen.PallasKeyGen.gen_with_planes_pair``) — the
    key factory hands the pair to the serving registry so a claimed
    key's image stages with zero host round-trip (ISSUE 11).

    ``planes`` is ``None`` whenever the staged layout does not apply:
    the keys-in-lanes route (lam < 48 has no hybrid staged layout) and
    ANY fallback to the host walk (which is counted and warned exactly
    like ``gen_on_device``'s).  Callers must treat a ``None`` as "stage
    from the host bundle" — the bundle itself is byte-identical either
    way."""
    return _gen_on_device(lam, cipher_keys, alphas, betas, s0s, bound,
                          interpret=interpret, tile_words=tile_words,
                          want_planes=True)


def _gen_on_device(lam, cipher_keys, alphas, betas, s0s, bound, *,
                   interpret, tile_words, want_planes
                   ) -> tuple[KeyBundle, dict | None]:
    _check_gen_inputs(alphas, betas, s0s, lam)
    global _DEVICE_FALLBACKS
    try:
        from dcf_tpu.testing.faults import fire

        fire("keygen.device", alphas.shape[0], lam)
        if interpret is None:
            import jax

            interpret = jax.devices()[0].platform != "tpu"
        kg = _device_gen_for(lam, cipher_keys, bool(interpret), tile_words)
        if hasattr(kg, "to_host_bundle"):  # keys-in-lanes generator
            return kg.to_host_bundle(
                kg.gen(alphas, betas, s0s, bound)), None
        if want_planes:
            return kg.gen_with_planes_pair(alphas, betas, s0s, bound)
        return kg.gen(alphas, betas, s0s, bound), None
    except Exception as e:  # fallback-ok: keygen must never fail for a
        # device-side reason — the host walk is always correct, and the
        # caller asked for keys, not for a particular pipeline.  The
        # fallback is counted and warned so it cannot pass unnoticed,
        # and it prefers the SAME host path the non-device facade would
        # take (C++ AES-NI core when the toolchain is present, numpy
        # floor otherwise) so a fallback storm degrades to the default
        # host rate, not silently to the portable floor.
        _DEVICE_FALLBACKS += 1
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # the facade edge already
            # validated the Hirose shape; don't re-warn from the fallback
            native = None
            try:
                from dcf_tpu.native import NativeDcf

                native = NativeDcf(lam, cipher_keys)
            except Exception:  # fallback-ok: no toolchain -> numpy walk
                pass
        warnings.warn(
            BackendFallbackWarning(
                "device-keygen",
                "native gen_batch" if native is not None else "gen_batch",
                e),
            stacklevel=3)  # through the gen_on_device[_with_planes]
        # wrapper: the warning must attribute to the CALLER's line, or
        # per-location dedup collapses distinct call sites
        if native is not None:
            return native.gen_batch(alphas, betas, s0s, bound), None
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            prg = HirosePrgNp(lam, cipher_keys)
        return gen_batch(prg, alphas, betas, s0s, bound), None
