"""Network edge: a zero-copy DCFK wire path for the serving tier
(ISSUE 12).

Every request so far entered ``DcfService`` as an in-process Python
call; real traffic arrives over a socket.  This module is the
dependency-light TCP front: a length-prefixed binary protocol (stdlib
``socket`` + ``threading`` only — the container bakes nothing else)
that carries DCFE-framed evaluation requests end to end, with the
ingest path going buffer-protocol straight into the batcher's staged
layout:

    socket ──recv_into──► per-frame bytearray ──memoryview──►
    batcher.ingest_points ──► Request.xs (a VIEW, no copy) ──►
    gather_batch ──► the padded pow-2 device batch

Zero per-point Python objects exist anywhere on that path: the one
host copy is the socket read into the frame buffer, the next is the
span gather into the padded batch (which the in-process path pays
too).  Responses are serialized straight from the fetched result
planes — the uint8 [K, M, lam] array's own buffer is handed to
``sendmsg`` behind an incremental CRC, never an intermediate
list-of-ints.

Wire format (all integers little-endian; every frame is a ``u32``
body-length envelope followed by the body)::

    0   4   magic  b"DCFE"
    4   2   version (u16, currently 2 — v2 added the ring-epoch
            field to REQUEST/PING/REGISTER, ISSUE 15; both ends of
            every link in this repo speak v2, v1 is refused typed)
    6   1   type    (u8: 1=REQUEST  2=SHARE  3=ERROR)

    REQUEST body (type 1):
    7   8   req_id      u64  client-chosen; responses echo it
    15  1   party       u8   0 or 1
    16  1   priority    u8   0/1/2 = CRITICAL/NORMAL/BATCH,
                             255 = the tenant's class (the default)
    17  8   deadline_ms f64  <= 0 = none (relative, like ``submit``)
    25  4   m           u32  points in this request
    29  2   n_bytes     u16  bytes per point (must match the service)
    31  1   tenant_len  u8
    32  1   key_len     u8
    33  4   epoch       u32  ring epoch the sender routed on (0 = un-
                             fenced: direct clients, solo services)
    37      tenant      utf-8 [tenant_len]
    ..      key_id      utf-8 [key_len]
    ..      xs payload  raw packed points, m * n_bytes
    end-4   crc32       u32 of ALL prior body bytes (zlib.crc32)

    SHARE body (type 2):
    7   8   req_id  u64
    15  2   k       u16  output rows (K keys, or m intervals)
    17  4   m       u32
    21  2   lam     u16
    23      share bytes  k * m * lam (C order)
    end-4   crc32   u32 of all prior body bytes

    ERROR body (type 3):
    7   8   req_id        u64  0 = connection-level (not a request)
    15  2   code          u16  see WIRE_CODES
    17  8   retry_after_s f64  < 0 = no hint
    25  2   msg_len       u16
    27      message       utf-8
    end-4   crc32         u32 of all prior body bytes

Decoding is strict, DCFK-style: bounds-checked field by field, exact
total size, CRC verified — any violation is ``KeyFormatError`` naming
the field.  A FRAMING violation (bad magic/length/CRC) additionally
closes the connection: after it the byte stream cannot be trusted to
re-synchronize.  Request-level refusals (unknown key, shed load, rate
limit) keep the connection — framing was intact.

Tenancy (the tenant table lives in ``ServeConfig.tenants``, a tuple of
``serve.TenantSpec`` — it maps tenants onto the EXISTING CRITICAL/
NORMAL/BATCH classes, never a second policy): a request's effective
class is its tenant's class, demotable per request but never
promotable above it.  Per-tenant admission is a points-per-second
token bucket on the injectable clock, applied BEFORE the shared queue:
a refusal costs the shared service nothing and carries the exact
time-to-refill as its ``retry_after_s``.  An empty table (the
default) admits every tenant unlimited, defaulting to NORMAL but
honoring a frame's EXPLICIT class verbatim — the open edge is "no
policy", which is what lets a pod router forward its already-admitted
effective class to a shard without the shard re-clamping it (ISSUE
13); a configured table refuses unknown tenants typed and enforces
the never-promote cap.

Refusals and failures cross the wire as typed ERROR frames: the code
maps back to the ``dcf_tpu.errors`` class on the client
(``EdgeClient`` re-raises the real ``QueueFullError`` /
``CircuitOpenError`` / ``DeadlineExceededError`` ... with
``retry_after_s`` attached), so a remote caller sees exactly the typed
taxonomy an in-process caller sees.

Failure injection: the ``edge.accept`` / ``edge.read`` seams
(``dcf_tpu.testing.faults``) fire before each accept and each
connection recv — a raising read handler kills ONE connection typed
(the accept loop and every other tenant's connection survive), and
``faults.latency`` armed at ``edge.read`` is the slow-client seam:
each blocking read advances the injectable clock, so a stalled sender
trips the existing deadline/watchdog path instead of wedging the
worker.

TLS (ISSUE 13 satellite): ``ServeConfig.tls_cert``/``tls_key`` (or
the same ``EdgeServer`` kwargs) wrap every accepted connection in
stdlib ``ssl``; ``tls_client_ca`` PINS clients — only peers
presenting a cert signed by that CA complete the handshake (the
router<->shard link hardening).  The handshake is deferred to the
reader thread, so a plaintext or unpinned peer is a counted
per-connection failure, never a wedged accept loop.  ``EdgeClient``
takes ``tls=/tls_ca=/tls_cert=/tls_key=``; ``EdgeClientPool`` (the
reusable reconnect-with-backoff transport the pod router forwards
through — ISSUE 13) passes them along.

Control frames (ISSUE 14, the pod self-healing tier): beside REQUEST/
SHARE/ERROR the protocol carries four lightweight control verbs —

* **PING** (type 4) / **PONG** (type 5): the health prober's liveness
  round trip.  Answered straight off the reader thread, admission-free
  by design: a shard in brownout is ALIVE (it is shedding load on
  purpose), and refusing pings there would make the router mark it
  DOWN and promote replicas against a host that is serving CRITICAL
  traffic fine.  PONG doubles as the generic ack (its ``value`` field
  carries the registration generation for REGISTER).

  Load piggyback (ISSUE 16, ``serve.capacity``): a PING may append a
  one-byte flags field with bit 0 set (``want_load``), asking the
  responder to append a fixed ``LoadSample`` block to its PONG —
  queue points vs bound, the brownout latch, and the cumulative
  shed / tenant-refusal / key-factory-pool-miss counters, the demand
  signals the capacity controller aggregates.  Both extensions are
  version-gated by SIZE: a load-free v2 PING/PONG keeps its exact
  legacy length and parses unchanged (old shards keep probing clean),
  and a responder without a load surface simply answers with the base
  PONG — the sampler reads "no sample", never an error.
* **REGISTER** (type 6): a DCFK frame forwarded by reference —
  ``(key_id, generation, proto flag, frame bytes)``.  ``generation=0``
  asks the receiver to MINT one (the owner-side registration);
  ``generation>0`` is the replica/anti-entropy spelling: apply with
  the owner's generation preserved, fenced by the monotonic-generation
  guard (a frame at or below the local generation dies typed
  ``StaleStateError`` / ``E_STALE`` — an old partition side is
  structurally unable to roll a key back).  Not tenant-gated:
  registration is an operator/router action authenticated by the TLS
  client-pinning story, not the evaluation admission table.
* **DIGEST** (type 7) / **SYNC** (type 8): the anti-entropy exchange.
  Mode 1 asks for the peer's ``{key_id: generation}`` digest (SYNC
  entries with zero-length frames); mode 0 carries the caller's digest
  and the SYNC response returns only frames whose generation is
  STRICTLY newer — the pull half of partition healing
  (``serve.replicate``).

Epoch fencing (ISSUE 15, ``serve.membership``): every ring-membership
change is committed under a monotonic **ring epoch** minted by the
membership controller.  Forwarded REQUEST and REGISTER frames (and the
health prober's PINGs) carry the sender's epoch; a shard tracks the
highest epoch it has seen (``DcfService.check_ring_epoch`` — adoption
is monotonic-max, the same first-writer discipline the generation
fence uses) and REFUSES any fenced frame carrying an older one, typed
``RingEpochError`` / ``E_EPOCH`` with a retry hint.  A router still
routing on a pre-change ring is therefore *structurally* unable to
double-serve a key against a conflicting placement — the PR 14
generation-fence discipline lifted from keys to membership.  Epoch 0
means unfenced (direct clients, solo deployments): the check is
skipped, exactly as generation 0 means "mint here" on REGISTER.
Epoch adoption, like REGISTER itself, is an operator/router action
authenticated by the TLS client-pinning story, not the tenant table.

Partition seam (ISSUE 14): a client constructed with ``tags=(local,
peer)`` fires ``net.partition`` before each dial and each frame send
(``testing.faults.partition`` is the canonical handler — it raises
``OSError`` for cut pairs, which the client contains as transport
death), so the pod soaks can cut and heal router<->shard links
deterministically.  Untagged clients never fire it.

Clocking: admission math (buckets, deadlines) uses the service's
injectable clock, never ``time.*`` (dcflint determinism).  Server-side
socket reads BLOCK by default — the right behavior for trusted/idle
keep-alive peers; against hostile ones, ``EdgeServer(read_timeout_s=N)``
bounds every recv (wall-clock by nature, like any socket timeout), so
a slow-loris peer holding a half-sent frame costs at most N seconds of
one reader thread before its connection dies typed and counted.  The
per-connection response backlog is bounded either way
(``_Conn.MAX_PENDING_RESPONSES``), and a frame buffer is at most
``max_frame_bytes``.
"""

from __future__ import annotations

import queue
import socket
import ssl
import struct
import threading
import zlib
from collections import namedtuple

import numpy as np

from dcf_tpu.errors import (
    BackendUnavailableError,
    BatchTimeoutError,
    CircuitOpenError,
    DcfError,
    DeadlineExceededError,
    KeyFormatError,
    KeyQuarantinedError,
    LockOrderError,
    MeshUnavailableError,
    NativeBuildError,
    QueueFullError,
    RingEpochError,
    ShapeError,
    StaleStateError,
    StandbyExhaustedError,
)
from dcf_tpu.serve.admission import (
    Priority,
    ServeFuture,
    TenantSpec,
    parse_priority,
)
from dcf_tpu.serve.metrics import Metrics, labeled
from dcf_tpu.testing.faults import fire
from dcf_tpu.utils.benchtime import monotonic

__all__ = ["EdgeServer", "EdgeClient", "EdgeClientPool", "TokenBucket",
           "LoadSample", "WIRE_CODES", "MAGIC", "VERSION", "T_REQUEST",
           "T_SHARE", "T_ERROR", "T_PING", "T_PONG", "T_REGISTER",
           "T_DIGEST", "T_SYNC", "encode_request", "encode_error",
           "encode_ping", "encode_pong", "encode_register",
           "encode_digest", "encode_sync"]

MAGIC = b"DCFE"
VERSION = 2  # v2 (ISSUE 15): REQUEST/PING/REGISTER carry a ring epoch

T_REQUEST = 1
T_SHARE = 2
T_ERROR = 3
T_PING = 4      # liveness probe (ISSUE 14: the health prober's frame)
T_PONG = 5      # ping/register ack; ``value`` carries the generation
T_REGISTER = 6  # DCFK frame forwarding (mint / fenced replica apply)
T_DIGEST = 7    # anti-entropy digest exchange request
T_SYNC = 8      # anti-entropy response: strictly-newer frames

_PREFIX = struct.Struct("<I")        # the length envelope
_FRAME_HEAD = struct.Struct("<HB")   # version, type (after the magic)
_BODY_MIN = 4 + _FRAME_HEAD.size     # magic + version + type
_REQ_HEAD = struct.Struct("<QBBdIHBBI")  # ..., tenant_len, key_len, epoch
_RES_HEAD = struct.Struct("<QHIH")
_ERR_HEAD = struct.Struct("<QHdH")
_PING_HEAD = struct.Struct("<QI")    # req_id, ring epoch (0 = unfenced)
_PING_FLAGS = struct.Struct("<B")    # optional: bit 0 = want_load
_PONG_HEAD = struct.Struct("<QQ")    # req_id, value
_PONG_LOAD = struct.Struct("<QQBQQQ")  # optional LoadSample block:
#   queue_points, queue_limit, brownout (u8 bool), shed_total,
#   refusals_total, pool_misses — appended only when the PING asked
#   (want_load) AND the responder has a load surface; version-gated
#   by size, so a load-free v2 PONG parses unchanged

# One shard's demand signals, sampled off a PING/PONG round trip
# (ISSUE 16): the capacity controller's per-shard input.  Counters
# are CUMULATIVE (the controller differences consecutive samples);
# ``queue_limit`` is the shard's configured queue-points bound, so
# ``queue_points / queue_limit`` is its queue fraction.
LoadSample = namedtuple("LoadSample", [
    "queue_points", "queue_limit", "brownout", "shed_total",
    "refusals_total", "pool_misses"])
_REG_HEAD = struct.Struct("<QQIBB")  # req_id, generation, epoch, proto,
#                                      key_len
_DIG_HEAD = struct.Struct("<QBI")    # req_id, mode, entry count
_DIG_ENTRY = struct.Struct("<QB")    # generation, key_len
_SYNC_HEAD = struct.Struct("<QI")    # req_id, entry count
_SYNC_ENTRY = struct.Struct("<QBBI")  # generation, proto, key_len, frame_len
_CRC = struct.Struct("<I")
_PRI_DEFAULT = 255  # "the tenant's class" priority byte

# Typed wire error codes <-> the dcf_tpu.errors taxonomy.  The server
# serializes the code for the exception it caught; the client
# re-raises the mapped class (retry_after_s re-attached where the
# class carries one).  E_RATE_LIMITED is a QueueFullError flavor —
# the refusal happened at the tenant bucket, before the shared queue.
E_INTERNAL = 1
E_WIRE = 2
E_SHAPE = 3
E_BAD_REQUEST = 4
E_QUEUE_FULL = 5
E_RATE_LIMITED = 6
E_DEADLINE = 7
E_CIRCUIT_OPEN = 8
E_UNAVAILABLE = 9
E_UNKNOWN_TENANT = 10
E_TIMEOUT = 11
E_EVICTED = 12  # QueueFullError's post-ACCEPTANCE spelling: the
#                 request was admitted (and counted) before a
#                 higher-priority submit took its room — load
#                 accounting must not retract a "sent" for it
E_STALE = 13  # StaleStateError's own code (ISSUE 13): a hot-swap
#               racing a forwarded eval is a KEY-level race the caller
#               resolves by retrying the same target — the router must
#               be able to tell it from E_UNAVAILABLE, which is a
#               backend-down signal it treats as failover pressure
E_EPOCH = 14  # RingEpochError (ISSUE 15): the SENDER's ring is stale —
#               a membership change committed a newer epoch than the
#               one this frame carries.  Neither a shard-health signal
#               (the shard is fine) nor a key-level outcome: the
#               sender must refresh its ring before retrying
E_MESH_UNAVAILABLE = 15  # MeshUnavailableError (ISSUE 18): the pod's
#               device-mesh co-evaluation tier cannot take the batch
#               (worker down, group epoch fenced, no group) while the
#               caller FORCED co-evaluation.  Distinct from
#               E_UNAVAILABLE: route-mode still serves — the caller's
#               recovery is "retry without forcing the mesh", not
#               "back off from a dead backend"

#: code -> exception class the client raises (see ``_raise_wire``).
WIRE_CODES = {
    E_INTERNAL: DcfError,
    E_WIRE: KeyFormatError,
    E_SHAPE: ShapeError,
    E_BAD_REQUEST: ValueError,
    E_QUEUE_FULL: QueueFullError,
    E_RATE_LIMITED: QueueFullError,
    E_DEADLINE: DeadlineExceededError,
    E_CIRCUIT_OPEN: CircuitOpenError,
    E_UNAVAILABLE: BackendUnavailableError,
    E_UNKNOWN_TENANT: ValueError,
    E_TIMEOUT: BatchTimeoutError,
    E_EVICTED: QueueFullError,
    E_STALE: StaleStateError,
    E_EPOCH: RingEpochError,
    E_MESH_UNAVAILABLE: MeshUnavailableError,
}

#: Taxonomy classes that DELIBERATELY cross the wire as ``E_INTERNAL``
#: (via the ``DcfError`` entry in ``_EXC_CODES``): build/disk/test-
#: harness faults that no remote caller can act on distinctly, so a
#: dedicated code would be dead protocol surface.  The wire-taxonomy-
#: sync dcflint pass enforces that every ``dcf_tpu.errors`` class is
#: either wire-coded or declared here — a NEW typed error cannot ship
#: with its wire behavior undecided.
WIRE_INTERNAL_ONLY = frozenset({
    NativeBuildError,       # build/load fault: host-local, operator-fixed
    KeyQuarantinedError,    # disk-frame fault: surfaces via store reports
    StandbyExhaustedError,  # operator scale-out misuse: never request-path
    LockOrderError,         # test-harness detector: never constructed live
})

_EXC_CODES = (
    # Order matters: first match wins, subclasses before bases.
    (QueueFullError, E_QUEUE_FULL),
    (DeadlineExceededError, E_DEADLINE),
    (CircuitOpenError, E_CIRCUIT_OPEN),
    (BatchTimeoutError, E_TIMEOUT),
    (KeyFormatError, E_WIRE),
    (ShapeError, E_SHAPE),
    (RingEpochError, E_EPOCH),
    (StaleStateError, E_STALE),
    (MeshUnavailableError, E_MESH_UNAVAILABLE),
    (BackendUnavailableError, E_UNAVAILABLE),
    (DcfError, E_INTERNAL),
    (ValueError, E_BAD_REQUEST),
)


def _code_for(exc: BaseException) -> int:
    if isinstance(exc, QueueFullError) and getattr(exc, "evicted",
                                                   False):
        return E_EVICTED
    for cls, code in _EXC_CODES:
        if isinstance(exc, cls):
            return code
    return E_INTERNAL


class _Disconnect(DcfError, ConnectionError):
    """A peer vanished mid-frame (EOF inside an envelope or body) —
    a per-connection event, typed so the containment handlers can
    tell it from a framing violation."""


def _sendmsg_all(sock: socket.socket, parts: list) -> None:
    """``sendmsg`` with the ``sendall`` guarantee: a blocking TCP
    socket may still accept only part of a large gather write, so loop
    over the remainder without flattening the parts (the share payload
    is referenced by buffer the whole way — no intermediate copy
    unless the kernel short-writes)."""
    views = [memoryview(p).cast("B") if not isinstance(p, memoryview)
             else p.cast("B") for p in parts]
    if isinstance(sock, ssl.SSLSocket):
        # SSLSocket has no scatter-gather send (sendmsg raises
        # NotImplementedError): join once and sendall.  The copy is
        # inherent to TLS anyway — every byte is re-encrypted into the
        # record layer — so the zero-copy claim is scoped to the
        # plaintext transport, and the TLS knob trades that copy for
        # the wire staying confidential.
        sock.sendall(b"".join(views))
        return
    total = sum(v.nbytes for v in views)
    sent = sock.sendmsg(views)
    while sent < total:
        total -= sent
        while sent:
            if sent >= views[0].nbytes:
                sent -= views[0].nbytes
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0
        sent = sock.sendmsg(views)


# ------------------------------------------------------------ codecs


def _frame(body_parts) -> bytes:
    """Envelope + body + CRC from byte pieces (one join, no
    re-serialization of the pieces themselves).  Pieces are flattened
    to byte views first — ``len()`` of a 2D memoryview counts rows,
    not bytes."""
    views = [memoryview(p).cast("B") for p in body_parts]
    crc = 0
    for v in views:
        crc = zlib.crc32(v, crc)
    body_len = sum(v.nbytes for v in views) + _CRC.size
    return b"".join([_PREFIX.pack(body_len), *views,
                     _CRC.pack(crc)])


def _request_parts(req_id: int, tenant: str, key_id: str, party: int,
                   priority: int, deadline_ms: float | None,
                   payload, n_bytes: int, m: int,
                   epoch: int = 0) -> list:
    """The ONE REQUEST-body encoding (validation included), as byte
    pieces with the payload referenced by buffer: ``encode_request``
    joins them into a frame; ``EdgeClient.submit_bytes`` hands them to
    the scatter-gather send.  Two encoders would drift.  ``epoch``
    (ISSUE 15): the ring epoch the sender routed on; 0 = unfenced."""
    tb = tenant.encode("utf-8")
    kb_name = key_id.encode("utf-8")
    if len(tb) > 255 or len(kb_name) > 255:
        raise ShapeError("tenant/key_id must encode to <= 255 bytes")
    if not 0 <= int(party) <= 255:
        # Validated here, not by struct.pack: submit_bytes relies on
        # encoding failures being raised BEFORE a future registers
        raise ShapeError(f"party byte must fit u8, got {party}")
    if epoch < 0:
        raise ShapeError(f"ring epoch must be >= 0, got {epoch}")
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_REQUEST) + _REQ_HEAD.pack(
        req_id, int(party), priority,
        -1.0 if deadline_ms is None else float(deadline_ms),
        m, n_bytes, len(tb), len(kb_name), int(epoch))
    return [head, tb, kb_name, memoryview(payload)]


def encode_request(req_id: int, tenant: str, key_id: str, party: int,
                   priority: int, deadline_ms: float | None,
                   payload, n_bytes: int, m: int,
                   epoch: int = 0) -> bytes:
    """One REQUEST frame (envelope included).  ``payload`` is any
    buffer-protocol object of ``m * n_bytes`` packed point bytes."""
    return _frame(_request_parts(req_id, tenant, key_id, party,
                                 priority, deadline_ms, payload,
                                 n_bytes, m, epoch))


def encode_share(req_id: int, y: np.ndarray) -> list[bytes]:
    """SHARE frame pieces for ``sendmsg``: the fetched uint8
    [k, m, lam] planes are referenced by buffer — no intermediate
    list-of-ints, no payload copy (the kernel gathers the pieces)."""
    k, m, lam = y.shape
    if y.dtype != np.uint8:
        raise ShapeError(f"share planes must be uint8, got {y.dtype}")
    view = memoryview(np.ascontiguousarray(y)).cast("B")
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_SHARE) + _RES_HEAD.pack(
        req_id, k, m, lam)
    crc = zlib.crc32(view, zlib.crc32(head))
    body_len = len(head) + view.nbytes + _CRC.size
    return [_PREFIX.pack(body_len), head, view, _CRC.pack(crc)]


def encode_error(req_id: int, code: int, message: str,
                 retry_after_s: float | None = None) -> bytes:
    mb = message.encode("utf-8")[:4096]
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_ERROR) + _ERR_HEAD.pack(
        req_id, code,
        -1.0 if retry_after_s is None else float(retry_after_s),
        len(mb))
    return _frame([head, mb])


def encode_ping(req_id: int, epoch: int = 0,
                want_load: bool = False) -> bytes:
    """One PING frame (ISSUE 14: the health prober's liveness probe).
    ``epoch`` (ISSUE 15): the prober's ring epoch — probes DISSEMINATE
    membership epochs, so shards converge on a committed epoch within
    about one probe interval; 0 = unfenced liveness only.
    ``want_load`` (ISSUE 16): ask the responder to append its
    ``LoadSample`` to the PONG — encoded as a trailing flags byte, so
    a load-free ping keeps the exact legacy frame size."""
    if epoch < 0:
        raise ShapeError(f"ring epoch must be >= 0, got {epoch}")
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_PING) + _PING_HEAD.pack(
        req_id, int(epoch))
    parts = [head]
    if want_load:
        parts.append(_PING_FLAGS.pack(1))
    return _frame(parts)


def encode_pong(req_id: int, value: int = 0, load=None) -> bytes:
    """PING/REGISTER ack; ``value`` echoes the registration generation
    (for REGISTER) or the receiver's current ring epoch (for PING —
    how the membership benches verify epoch convergence over the
    wire).  ``load`` (ISSUE 16): a ``LoadSample`` (or 6-tuple) to
    append — only a PING that asked (``want_load``) gets one; None
    keeps the exact legacy frame size."""
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_PONG) + _PONG_HEAD.pack(
        req_id, value)
    parts = [head]
    if load is not None:
        qp, ql, bo, shed, refused, misses = load
        parts.append(_PONG_LOAD.pack(
            int(qp), int(ql), 1 if bo else 0, int(shed), int(refused),
            int(misses)))
    return _frame(parts)


def encode_register(req_id: int, key_id: str, frame, generation: int = 0,
                    proto: bool = False, epoch: int = 0) -> bytes:
    """One REGISTER frame: a DCFK v2/v3 frame forwarded by reference
    (``frame`` is any buffer-protocol object — the bundle bytes are
    never re-materialized here).  ``generation=0`` = mint at the
    receiver (the owner-side registration); ``generation>0`` = the
    fenced replica/anti-entropy apply, owner's generation preserved.
    ``epoch`` (ISSUE 15): the sender's ring epoch; 0 = unfenced."""
    kb_name = key_id.encode("utf-8")
    if len(kb_name) > 255:
        raise ShapeError("key_id must encode to <= 255 bytes")
    if generation < 0:
        raise ShapeError(f"generation must be >= 0, got {generation}")
    if epoch < 0:
        raise ShapeError(f"ring epoch must be >= 0, got {epoch}")
    head = MAGIC + _FRAME_HEAD.pack(VERSION, T_REGISTER) + _REG_HEAD.pack(
        req_id, int(generation), int(epoch), int(bool(proto)),
        len(kb_name))
    return _frame([head, kb_name, memoryview(frame)])


def encode_digest(req_id: int, digest: dict, mode: int = 0) -> bytes:
    """One DIGEST frame carrying ``{key_id: generation}``.  ``mode=0``:
    "here is my digest, send me strictly-newer frames"; ``mode=1``:
    "report your digest" (the response's SYNC entries then carry
    zero-length frames).  Entries are emitted in sorted key order —
    deterministic bytes for a given digest."""
    if mode not in (0, 1):
        raise ShapeError(f"digest mode must be 0 or 1, got {mode}")
    parts = [MAGIC + _FRAME_HEAD.pack(VERSION, T_DIGEST)
             + _DIG_HEAD.pack(req_id, mode, len(digest))]
    for key_id in sorted(digest):
        kb_name = key_id.encode("utf-8")
        if len(kb_name) > 255:
            raise ShapeError("key_id must encode to <= 255 bytes")
        parts.append(_DIG_ENTRY.pack(int(digest[key_id]), len(kb_name)))
        parts.append(kb_name)
    return _frame(parts)


def encode_sync(req_id: int, entries) -> bytes:
    """One SYNC frame: ``entries`` is a list of ``(key_id, generation,
    proto, frame)`` tuples (``frame`` = DCFK bytes, or ``b""`` for a
    digest-only reply)."""
    entries = list(entries)
    parts = [MAGIC + _FRAME_HEAD.pack(VERSION, T_SYNC)
             + _SYNC_HEAD.pack(req_id, len(entries))]
    for key_id, generation, proto, frame in entries:
        kb_name = key_id.encode("utf-8")
        view = memoryview(frame).cast("B") if frame else memoryview(b"")
        if len(kb_name) > 255:
            raise ShapeError("key_id must encode to <= 255 bytes")
        parts.append(_SYNC_ENTRY.pack(int(generation), int(bool(proto)),
                                      len(kb_name), view.nbytes))
        parts.append(kb_name)
        if view.nbytes:
            parts.append(view)
    return _frame(parts)


def _check_body(body, claims: str) -> memoryview:
    """Shared strict-decode head: magic, version, CRC over the whole
    body — ``KeyFormatError`` naming the field, DCFK discipline."""
    view = memoryview(body)
    if view.nbytes < _BODY_MIN + _CRC.size:
        raise KeyFormatError(
            f"truncated frame: {view.nbytes} bytes cannot hold the "
            f"DCFE header and CRC ({claims})")
    if bytes(view[:4]) != MAGIC:
        raise KeyFormatError(
            f"bad magic: expected {MAGIC!r}, got {bytes(view[:4])!r} "
            "(not a DCFE frame)")
    version, _ = _FRAME_HEAD.unpack_from(view, 4)
    if version != VERSION:
        raise KeyFormatError(
            f"unsupported DCFE version {version} (this reader handles "
            f"{VERSION})")
    (crc_stored,) = _CRC.unpack_from(view, view.nbytes - _CRC.size)
    crc_actual = zlib.crc32(view[:view.nbytes - _CRC.size])
    if crc_stored != crc_actual:
        raise KeyFormatError(
            f"crc32 mismatch: trailer records {crc_stored:#010x}, frame "
            f"hashes to {crc_actual:#010x} — the wire bytes are corrupt")
    return view


def decode_request(body) -> dict:
    """Strict REQUEST decode.  Returns the header fields plus
    ``payload``: a zero-copy ``memoryview`` of the packed xs bytes
    inside ``body`` (the caller owns the buffer's lifetime)."""
    view = _check_body(body, "a request")
    _, ftype = _FRAME_HEAD.unpack_from(view, 4)
    if ftype != T_REQUEST:
        raise KeyFormatError(
            f"frame type {ftype} is not a request (server side only "
            "accepts type 1)")
    if view.nbytes < _BODY_MIN + _REQ_HEAD.size + _CRC.size:
        raise KeyFormatError(
            f"truncated frame: {view.nbytes} bytes cannot hold a "
            "request header")
    (req_id, party, priority, deadline_ms, m, n_bytes, tenant_len,
     key_len, epoch) = _REQ_HEAD.unpack_from(view, _BODY_MIN)
    off = _BODY_MIN + _REQ_HEAD.size
    end = view.nbytes - _CRC.size
    claims = f"m={m}, n_bytes={n_bytes}"
    for name, size in (("tenant", tenant_len), ("key_id", key_len),
                       ("xs payload", m * n_bytes)):
        if off + size > end:
            raise KeyFormatError(
                f"truncated frame: section {name!r} needs bytes "
                f"[{off}, {off + size}) but the payload ends at {end} "
                f"(header claims {claims})")
        off += size
    if off != end:
        raise KeyFormatError(
            f"oversized frame: {end - off} trailing bytes after the xs "
            "payload (corrupt header or concatenated frames)")
    off = _BODY_MIN + _REQ_HEAD.size
    tenant = bytes(view[off:off + tenant_len]).decode("utf-8",
                                                      "replace")
    off += tenant_len
    key_id = bytes(view[off:off + key_len]).decode("utf-8", "replace")
    off += key_len
    return {
        "req_id": req_id, "tenant": tenant, "key_id": key_id,
        "party": party, "priority": priority,
        "deadline_ms": deadline_ms if deadline_ms > 0 else None,
        "m": m, "n_bytes": n_bytes, "epoch": epoch,
        "payload": view[off:end],
    }


def decode_ping(body) -> tuple:
    """Strict PING decode -> ``(req_id, epoch, want_load)`` (epoch 0 =
    unfenced liveness only).  Exactly TWO sizes are legal: the legacy
    load-free frame and the one-flags-byte extension (ISSUE 16) —
    anything else dies typed like every other mangled frame."""
    view = _check_body(body, "a ping")
    _, ftype = _FRAME_HEAD.unpack_from(view, 4)
    if ftype != T_PING:
        raise KeyFormatError(f"frame type {ftype} is not a ping")
    base = _BODY_MIN + _PING_HEAD.size + _CRC.size
    if view.nbytes not in (base, base + _PING_FLAGS.size):
        raise KeyFormatError(
            f"ping frame must be exactly {base} bytes (or "
            f"{base + _PING_FLAGS.size} with the load-request flags), "
            f"got {view.nbytes}")
    req_id, epoch = _PING_HEAD.unpack_from(view, _BODY_MIN)
    want_load = False
    if view.nbytes == base + _PING_FLAGS.size:
        (flags,) = _PING_FLAGS.unpack_from(
            view, _BODY_MIN + _PING_HEAD.size)
        if flags & ~1:
            raise KeyFormatError(
                f"ping flags {flags:#x} set reserved bits")
        want_load = bool(flags & 1)
    return req_id, epoch, want_load


def decode_register(body) -> dict:
    """Strict REGISTER decode.  ``frame`` is a zero-copy ``memoryview``
    of the DCFK bytes inside ``body`` (the caller owns the buffer)."""
    view = _check_body(body, "a register")
    _, ftype = _FRAME_HEAD.unpack_from(view, 4)
    if ftype != T_REGISTER:
        raise KeyFormatError(f"frame type {ftype} is not a register")
    if view.nbytes < _BODY_MIN + _REG_HEAD.size + _CRC.size:
        raise KeyFormatError(
            f"truncated frame: {view.nbytes} bytes cannot hold a "
            "register header")
    req_id, generation, epoch, proto, key_len = _REG_HEAD.unpack_from(
        view, _BODY_MIN)
    if proto not in (0, 1):
        raise KeyFormatError(
            f"register proto flag must be 0 or 1, got {proto}")
    off = _BODY_MIN + _REG_HEAD.size
    end = view.nbytes - _CRC.size
    if off + key_len > end:
        raise KeyFormatError(
            f"truncated frame: section 'key_id' needs bytes "
            f"[{off}, {off + key_len}) but the payload ends at {end}")
    key_id = bytes(view[off:off + key_len]).decode("utf-8", "replace")
    off += key_len
    if off >= end:
        raise KeyFormatError(
            "register frame carries no DCFK payload (a zero-byte "
            "frame cannot be a key)")
    return {"req_id": req_id, "key_id": key_id,
            "generation": generation, "proto": bool(proto),
            "epoch": epoch, "frame": view[off:end]}


def decode_digest(body) -> tuple:
    """Strict DIGEST decode -> ``(req_id, mode, {key_id: generation})``."""
    view = _check_body(body, "a digest")
    _, ftype = _FRAME_HEAD.unpack_from(view, 4)
    if ftype != T_DIGEST:
        raise KeyFormatError(f"frame type {ftype} is not a digest")
    if view.nbytes < _BODY_MIN + _DIG_HEAD.size + _CRC.size:
        raise KeyFormatError(
            f"truncated frame: {view.nbytes} bytes cannot hold a "
            "digest header")
    req_id, mode, count = _DIG_HEAD.unpack_from(view, _BODY_MIN)
    if mode not in (0, 1):
        raise KeyFormatError(
            f"digest mode must be 0 or 1, got {mode}")
    off = _BODY_MIN + _DIG_HEAD.size
    end = view.nbytes - _CRC.size
    digest: dict = {}
    for i in range(count):
        if off + _DIG_ENTRY.size > end:
            raise KeyFormatError(
                f"truncated frame: digest entry {i} needs bytes "
                f"[{off}, {off + _DIG_ENTRY.size}) but the payload "
                f"ends at {end} (header claims {count} entries)")
        generation, key_len = _DIG_ENTRY.unpack_from(view, off)
        off += _DIG_ENTRY.size
        if off + key_len > end:
            raise KeyFormatError(
                f"truncated frame: digest entry {i}'s key_id "
                f"overruns the payload (header claims {count} entries)")
        key_id = bytes(view[off:off + key_len]).decode("utf-8",
                                                       "replace")
        off += key_len
        digest[key_id] = generation
    if off != end:
        raise KeyFormatError(
            f"oversized frame: {end - off} trailing bytes after "
            f"{count} digest entries")
    return req_id, mode, digest


def _decode_sync_entries(view: memoryview, off: int, end: int,
                         count: int) -> list:
    entries = []
    for i in range(count):
        if off + _SYNC_ENTRY.size > end:
            raise KeyFormatError(
                f"truncated frame: sync entry {i} needs bytes "
                f"[{off}, {off + _SYNC_ENTRY.size}) but the payload "
                f"ends at {end} (header claims {count} entries)")
        generation, proto, key_len, frame_len = _SYNC_ENTRY.unpack_from(
            view, off)
        if proto not in (0, 1):
            raise KeyFormatError(
                f"sync entry {i} proto flag must be 0 or 1, got {proto}")
        off += _SYNC_ENTRY.size
        if off + key_len + frame_len > end:
            raise KeyFormatError(
                f"truncated frame: sync entry {i}'s sections overrun "
                f"the payload (header claims {count} entries)")
        key_id = bytes(view[off:off + key_len]).decode("utf-8",
                                                       "replace")
        off += key_len
        frame = bytes(view[off:off + frame_len])
        off += frame_len
        entries.append((key_id, generation, bool(proto), frame))
    if off != end:
        raise KeyFormatError(
            f"oversized frame: {end - off} trailing bytes after "
            f"{count} sync entries")
    return entries


def decode_response(body) -> tuple:
    """Client-side strict decode: ``("share", req_id, y)``,
    ``("error", req_id, code, retry_after_s, message)``,
    ``("pong", req_id, value)`` or ``("sync", req_id, entries)``."""
    view = _check_body(body, "a response")
    _, ftype = _FRAME_HEAD.unpack_from(view, 4)
    end = view.nbytes - _CRC.size
    if ftype == T_SHARE:
        if view.nbytes < _BODY_MIN + _RES_HEAD.size + _CRC.size:
            raise KeyFormatError("truncated frame: no share header")
        req_id, k, m, lam = _RES_HEAD.unpack_from(view, _BODY_MIN)
        off = _BODY_MIN + _RES_HEAD.size
        if off + k * m * lam != end:
            raise KeyFormatError(
                f"share payload size mismatch: header claims "
                f"k={k}, m={m}, lam={lam} but {end - off} bytes follow")
        y = np.frombuffer(view[off:end], dtype=np.uint8)
        return ("share", req_id, y.reshape(k, m, lam))
    if ftype == T_ERROR:
        if view.nbytes < _BODY_MIN + _ERR_HEAD.size + _CRC.size:
            raise KeyFormatError("truncated frame: no error header")
        req_id, code, retry, msg_len = _ERR_HEAD.unpack_from(
            view, _BODY_MIN)
        off = _BODY_MIN + _ERR_HEAD.size
        if off + msg_len != end:
            raise KeyFormatError(
                f"error message size mismatch: header claims "
                f"{msg_len} bytes but {end - off} follow")
        msg = bytes(view[off:end]).decode("utf-8", "replace")
        return ("error", req_id, code,
                retry if retry >= 0 else None, msg)
    if ftype == T_PONG:
        base = _BODY_MIN + _PONG_HEAD.size + _CRC.size
        if view.nbytes not in (base, base + _PONG_LOAD.size):
            raise KeyFormatError(
                f"pong frame must be exactly {base} bytes (or "
                f"{base + _PONG_LOAD.size} with the load block), "
                f"got {view.nbytes}")
        req_id, value = _PONG_HEAD.unpack_from(view, _BODY_MIN)
        if view.nbytes == base:
            return ("pong", req_id, value)
        qp, ql, bo, shed, refused, misses = _PONG_LOAD.unpack_from(
            view, _BODY_MIN + _PONG_HEAD.size)
        if bo > 1:
            raise KeyFormatError(
                f"pong brownout byte must be 0 or 1, got {bo}")
        return ("pong", req_id,
                (value, LoadSample(qp, ql, bool(bo), shed, refused,
                                   misses)))
    if ftype == T_SYNC:
        if view.nbytes < _BODY_MIN + _SYNC_HEAD.size + _CRC.size:
            raise KeyFormatError("truncated frame: no sync header")
        req_id, count = _SYNC_HEAD.unpack_from(view, _BODY_MIN)
        entries = _decode_sync_entries(
            view, _BODY_MIN + _SYNC_HEAD.size, end, count)
        return ("sync", req_id, entries)
    raise KeyFormatError(
        f"frame type {ftype} is not a response (client side accepts "
        "types 2, 3, 5 and 8)")


# ------------------------------------------------------ admission


class TokenBucket:
    """Per-tenant points-per-second admission on the injectable clock.

    ``admit(points, now)`` returns 0.0 when admitted (tokens consumed)
    or the retry-after hint in seconds — the EXACT time until the
    bucket would hold ``points`` tokens, so a refused client's backoff
    is a schedule, not a guess.  A request larger than the bucket
    capacity is refused UNCONDITIONALLY — ``points > burst`` can never
    be admitted, full bucket or not, or an oversized request would
    bypass the rate limit entirely; its hint is the (unreachable)
    time-to-``points``, always positive: split the request or raise
    the burst.  Thread-safe: several connections may serve one tenant.
    """

    __slots__ = ("rate", "burst", "_tokens", "_last", "_lock")

    def __init__(self, points_per_sec: float, burst_points: float,
                 now: float):
        if points_per_sec < 0:
            # api-edge: bucket contract (0 disables rate limiting)
            raise ValueError(
                f"points_per_sec must be >= 0, got {points_per_sec}")
        self.rate = float(points_per_sec)
        self.burst = float(burst_points) if burst_points > 0 \
            else max(self.rate, 1.0)
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._tokens = self.burst
        # guarded-by: _lock
        self._last = float(now)

    def admit(self, points: int, now: float) -> float:
        if self.rate <= 0:
            return 0.0
        with self._lock:
            elapsed = max(now - self._last, 0.0)
            self._tokens = min(self.burst,
                               self._tokens + elapsed * self.rate)
            self._last = now
            if points <= self._tokens:
                self._tokens -= points
                return 0.0
            # points > tokens: refused.  For points > burst this is
            # ALWAYS positive even against a full bucket — clamping
            # the hint at capacity would return 0.0 there, which the
            # caller reads as "admitted": a zero-token rate-limit
            # bypass for any request sized above the burst.
            return (points - self._tokens) / self.rate


class _Tenant:
    """One resolved tenant: its class, its bucket, its metric series."""

    __slots__ = ("spec", "bucket", "c_requests", "c_points",
                 "c_refusals")

    def __init__(self, spec: TenantSpec, metrics: Metrics, now: float):
        self.spec = spec
        self.bucket = TokenBucket(spec.points_per_sec,
                                  spec.burst_points, now)
        name = spec.name
        self.c_requests = metrics.counter(labeled(
            "edge_tenant_requests_total", tenant=name))
        self.c_points = metrics.counter(labeled(
            "edge_tenant_points_total", tenant=name))
        self.c_refusals = metrics.counter(labeled(
            "edge_tenant_refusals_total", tenant=name))


# ------------------------------------------------------ the server


class _Conn:
    """One accepted connection: a reader thread decoding frames and
    submitting, a writer thread streaming completions back.  All
    failures are PER-CONNECTION: they end these two threads, never the
    accept loop or another connection."""

    #: Response-backlog bound per connection: a peer that pipelines
    #: requests but never reads responses would otherwise grow the
    #: out-queue (completed futures + their frame buffers) without
    #: limit while the writer sits in ``sendall`` on the full socket.
    #: At the bound the READER blocks instead — TCP backpressure
    #: propagates to the slow peer, and memory per connection stays
    #: bounded.  (The admission queue bounds only UNSERVED points, so
    #: it cannot provide this.)
    MAX_PENDING_RESPONSES = 256

    def __init__(self, server: "EdgeServer", sock: socket.socket,
                 peer: str):
        self._srv = server
        self._sock = sock
        self._peer = peer
        # Deliberately lock-free (hence no guarded-by annotations):
        # the cross-thread state is ``_out`` — a queue.Queue, which
        # owns its synchronization — and ``_closing``, a monotonic
        # False->True sentinel both loops only poll (a stale read
        # costs one extra 0.1 s put slice, never correctness).
        self._out: queue.Queue = queue.Queue(self.MAX_PENDING_RESPONSES)
        self._closing = False
        self._reader = threading.Thread(
            target=self._read_loop, name=f"edge-read-{peer}",
            daemon=True)
        self._writer = threading.Thread(
            target=self._write_loop, name=f"edge-write-{peer}",
            daemon=True)

    def start(self) -> None:
        self._reader.start()
        self._writer.start()

    def close(self) -> None:
        """Server-initiated shutdown: unblock both threads."""
        self._closing = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # already gone
        self._sock.close()
        try:
            self._out.put_nowait(None)
        except queue.Full:
            pass  # the writer is mid-backlog; the closed socket ends it

    def nudge(self) -> None:
        """Graceful-shutdown half (ISSUE 15): queue the writer's
        end-sentinel so it delivers the queued responses — their
        futures are already complete because ``serve_host`` drains the
        service first — then exits.  ``EdgeServer.close(drain_s=)``
        nudges EVERY connection before joining any writer, so the
        flush wall time is one shared deadline, not per-connection."""
        try:
            self._out.put_nowait(None)
        except queue.Full:
            pass  # a full backlog still drains; the join bounds it

    def join_writer(self, timeout: float) -> None:
        self._writer.join(timeout)

    def _enqueue(self, item) -> None:
        """Reader-side put honouring the backlog bound: blocks in
        slices so a server/connection close can always free the reader
        (the closed socket ends the writer, which may never drain)."""
        while not self._closing:
            try:
                self._out.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def join(self, timeout: float | None = None) -> None:
        self._reader.join(timeout)
        self._writer.join(timeout)

    # -- receive path ------------------------------------------------

    def _recv_into(self, view: memoryview) -> None:
        got = 0
        while got < len(view):
            fire("edge.read", self._peer, len(view) - got)
            n = self._sock.recv_into(view[got:], len(view) - got)
            if n == 0:
                # dcflint: disable=typed-error _Disconnect IS a
                # DcfError subclass (module-local, so the containment
                # handler can tell a vanished peer from a framing
                # violation without string matching)
                raise _Disconnect(
                    f"peer {self._peer} disconnected mid-frame "
                    f"({got}/{len(view)} bytes of the section)")
            got += n

    def _read_frame(self) -> bytearray | None:
        """One envelope + body; ``None`` on a clean EOF between
        frames.  The body buffer is FRESH per frame: the decoded xs
        payload stays aliased by the queued request until its batch
        is gathered, so the buffer must never be reused."""
        prefix = bytearray(_PREFIX.size)
        fire("edge.read", self._peer, _PREFIX.size)
        n = self._sock.recv_into(prefix, _PREFIX.size)
        if n == 0:
            return None  # clean close between frames
        if n < _PREFIX.size:
            self._recv_into(memoryview(prefix)[n:])
        (body_len,) = _PREFIX.unpack(prefix)
        if not _BODY_MIN + _CRC.size <= body_len \
                <= self._srv.max_frame_bytes:
            raise KeyFormatError(
                f"length prefix {body_len} outside "
                f"[{_BODY_MIN + _CRC.size}, "
                f"{self._srv.max_frame_bytes}] (oversized or mangled "
                "envelope)")
        body = bytearray(body_len)
        self._recv_into(memoryview(body))
        return body

    def _read_loop(self) -> None:
        srv = self._srv
        try:
            if isinstance(self._sock, ssl.SSLSocket):
                # Deferred TLS handshake (see the accept loop): a
                # peer speaking plaintext, or one without the pinned
                # client cert, fails HERE — an SSLError is an OSError,
                # so the containment below counts it and ends only
                # this connection.  read_timeout_s bounds the
                # handshake like any other read.
                self._sock.do_handshake()
            while not self._closing:
                body = self._read_frame()
                if body is None:
                    break
                srv._c_frames.inc()
                self._handle_frame(body)
        except KeyFormatError as e:
            # Framing violation (bad magic/length/CRC, from the
            # envelope read or the frame decode): answer typed, then
            # hang up — after a mangled frame the stream cannot be
            # trusted to re-synchronize on the next envelope.
            srv._c_wire_errors.inc()
            self._enqueue(encode_error(0, E_WIRE, str(e)))
        except _Disconnect:
            srv._c_conn_errors.inc()
        except OSError:
            # fallback-ok: socket teardown (server close or peer
            # reset) ends the connection; the accept loop and the
            # other connections are untouched.
            if not self._closing:
                srv._c_conn_errors.inc()
        except Exception as e:  # fallback-ok: ANY per-connection
            # failure (e.g. an armed edge.read fault) must end THIS
            # connection typed, never the accept loop or another
            # tenant's connection.
            srv._c_conn_errors.inc()
            self._enqueue(encode_error(0, E_INTERNAL,
                                       f"{type(e).__name__}: {e}"))
        finally:
            self._enqueue(None)  # writer drains what is queued, then
            srv._forget(self)   # the connection is gone

    def _handle_frame(self, body: bytearray) -> None:
        """Dispatch one decoded-length frame by type.  ``_read_frame``
        already bounds ``body`` at >= the header size, so the type
        peek cannot overrun; a corrupt type byte lands in a decoder
        whose CRC/type check dies ``KeyFormatError`` — the framing
        kill, exactly like any other mangled frame."""
        ftype = body[6]  # after magic (4) + version (2)
        if ftype == T_REQUEST:
            self._handle_request(body)
        elif ftype == T_PING:
            req_id, epoch, want_load = decode_ping(body)
            srv = self._srv
            srv._c_control.inc()
            # Admission-free by design: liveness, not serving capacity
            # (a shard in brownout is alive and must answer probes —
            # see the module docstring's control-frame section).  A
            # fenced ping (epoch > 0) adopts-or-refuses like any other
            # fenced frame: probes are how epochs disseminate, and a
            # STALE prober must learn its ring is old, not keep
            # confirming a membership view the pod has moved past.
            try:
                current = self._check_epoch(epoch)
            except Exception as e:  # fallback-ok: the typed E_EPOCH
                # refusal is a request-level outcome; the connection
                # survives (framing was intact)
                srv._c_refused.inc()
                self._enqueue(encode_error(
                    req_id, _code_for(e), str(e),
                    getattr(e, "retry_after_s", None)))
                return
            load = None
            report = getattr(srv._service, "load_report", None)
            if want_load and callable(report):
                try:
                    load = report()
                except Exception:  # fallback-ok: the probe is
                    # LIVENESS first — a load surface failing must
                    # degrade to "no sample", never an unanswered ping
                    load = None
            self._enqueue(("ctl", encode_pong(req_id, current,
                                              load=load)))
        elif ftype == T_REGISTER:
            self._handle_register(body)
        elif ftype == T_DIGEST:
            self._handle_digest(body)
        else:
            raise KeyFormatError(
                f"frame type {ftype} is not a server-side frame "
                "(server side accepts types 1, 4, 6 and 7)")

    def _check_epoch(self, epoch: int, adopt: bool = True) -> int:
        """The ring-epoch fence (ISSUE 15): adopt-or-refuse ``epoch``
        against the service's observed maximum.  Returns the service's
        current epoch (0 when the target has no epoch surface — a
        router door, or a pre-membership service); raises the typed
        ``RingEpochError`` for a stale sender.  Epoch 0 frames are
        unfenced and skip the check entirely.  ``adopt=False`` =
        refuse-only (the REQUEST path's pre-admission check — see
        ``DcfService.check_ring_epoch``)."""
        check = getattr(self._srv._service, "check_ring_epoch", None)
        if check is None:
            return 0
        if not epoch:
            return int(getattr(self._srv._service, "ring_epoch", 0))
        return int(check(epoch, adopt=adopt))

    def _handle_register(self, body: bytearray) -> None:
        req = decode_register(body)
        srv = self._srv
        req_id = req["req_id"]
        srv._c_control.inc()
        try:
            # The membership fence runs FIRST: a registration routed on
            # a stale ring must not mint/apply against a placement the
            # pod has moved past (it would be healed by anti-entropy,
            # but structurally refusing it is what makes a stale
            # router's writes impossible rather than merely repaired).
            self._check_epoch(req["epoch"])
            if req["generation"]:
                apply_fn = getattr(srv._service, "apply_replica_frame",
                                   None)
                if apply_fn is None:
                    # api-edge: surface contract — this endpoint (e.g.
                    # a pod router's own door when the frame carries a
                    # forced generation it should never see) does not
                    # accept replica applies
                    raise ValueError(
                        "this endpoint does not accept replica "
                        "REGISTER frames (no apply_replica_frame "
                        "surface)")
                gen = apply_fn(req["key_id"], req["frame"],
                               req["generation"], proto=req["proto"])
            else:
                mint_fn = getattr(srv._service, "register_frame", None)
                if mint_fn is None:
                    # api-edge: surface contract
                    raise ValueError(
                        "this endpoint does not accept REGISTER "
                        "frames (no register_frame surface)")
                gen = mint_fn(req["key_id"], req["frame"],
                              proto=req["proto"])
        except Exception as e:  # fallback-ok: a refused registration
            # (fenced generation -> E_STALE, geometry mismatch, corrupt
            # DCFK payload) is a REQUEST-level outcome — answer typed,
            # keep the connection (framing was intact).
            srv._c_refused.inc()
            self._enqueue(encode_error(
                req_id, _code_for(e), str(e),
                getattr(e, "retry_after_s", None)))
            return
        self._enqueue(("ctl", encode_pong(req_id, int(gen))))

    def _handle_digest(self, body: bytearray) -> None:
        req_id, mode, digest = decode_digest(body)
        srv = self._srv
        srv._c_control.inc()
        try:
            if mode == 1:
                dig_fn = getattr(srv._service, "replication_digest",
                                 None)
                if dig_fn is None:
                    # api-edge: surface contract (a router holds no
                    # registrations to digest)
                    raise ValueError(
                        "this endpoint holds no registrations to "
                        "digest (no replication_digest surface)")
                entries = [(k, g, False, b"")
                           for k, g in sorted(dig_fn().items())]
            else:
                sync_fn = getattr(srv._service, "sync_frames", None)
                if sync_fn is None:
                    # api-edge: surface contract
                    raise ValueError(
                        "this endpoint cannot serve an anti-entropy "
                        "pull (no sync_frames surface)")
                entries = sync_fn(digest)
        except Exception as e:  # fallback-ok: request-level outcome,
            # answered typed; the connection survives
            srv._c_refused.inc()
            self._enqueue(encode_error(
                req_id, _code_for(e), str(e),
                getattr(e, "retry_after_s", None)))
            return
        self._enqueue(("ctl", encode_sync(req_id, entries)))

    def _handle_request(self, body: bytearray) -> None:
        req = decode_request(body)
        srv = self._srv
        req_id = req["req_id"]

        def refuse(code: int, msg: str,
                   retry_after_s: float | None = None) -> None:
            srv._c_refused.inc()
            self._enqueue(encode_error(req_id, code, msg,
                                       retry_after_s))

        if req["n_bytes"] != srv.n_bytes:
            refuse(E_SHAPE,
                   f"point width {req['n_bytes']} != service domain "
                   f"{srv.n_bytes} bytes")
            return
        if req["party"] not in (0, 1):
            refuse(E_BAD_REQUEST,
                   f"party must be 0 or 1, got {req['party']}")
            return
        try:
            # Epoch fence BEFORE tenant admission, refuse-only: a
            # stale router's forward must not consume a tenant's token
            # budget on a request this shard will structurally refuse
            # — but an UNADMITTED sender must not be able to ADOPT
            # either (one forged frame with a huge epoch would fence
            # out the real router); adoption runs post-admission.
            self._check_epoch(req["epoch"], adopt=False)
        except Exception as e:  # fallback-ok: typed E_EPOCH refusal —
            # request-level, the connection survives
            refuse(_code_for(e), str(e),
                   getattr(e, "retry_after_s", None))
            return
        tenant = srv._resolve_tenant(req["tenant"])
        if tenant is None:
            refuse(E_UNKNOWN_TENANT,
                   f"unknown tenant {req['tenant']!r}: the service's "
                   "tenant table does not name it")
            return
        pri = req["priority"]
        if pri == _PRI_DEFAULT:
            eff = tenant.spec.priority
        elif pri in (0, 1, 2):
            if tenant is srv._default_tenant:
                # The OPEN edge (no tenant table) honors the frame's
                # class verbatim: an empty table is "no policy", and
                # the router->shard link depends on the forwarded
                # class surviving the hop — the tenant cap is a
                # CONFIGURED-table rule, not a default clamp (ISSUE
                # 13: a clamp here silently demoted every routed
                # CRITICAL request to NORMAL on its shard).
                eff = Priority(pri)
            else:
                # A request may demote below its tenant class, never
                # promote above it (larger enum value = lower class).
                eff = Priority(max(pri, tenant.spec.priority.value))
        else:
            refuse(E_BAD_REQUEST,
                   f"priority byte must be 0/1/2 or 255, got {pri}")
            return
        tenant.c_requests.inc()
        now = srv._clock()
        retry = tenant.bucket.admit(req["m"], now)
        if retry > 0:
            tenant.c_refusals.inc()
            refuse(E_RATE_LIMITED,
                   f"tenant {tenant.spec.name!r} over its "
                   f"{tenant.bucket.rate:g} points/s admission rate",
                   retry_after_s=retry)
            return
        try:
            # Admitted: NOW a newer epoch is adopted (the refuse-only
            # half already ran pre-admission).  A membership commit
            # landing BETWEEN the two checks can make the sender stale
            # here — still a typed request-level refusal.
            self._check_epoch(req["epoch"])
        except Exception as e:  # fallback-ok: typed E_EPOCH refusal
            refuse(_code_for(e), str(e),
                   getattr(e, "retry_after_s", None))
            return
        try:
            fut = srv._service.submit_bytes(
                req["key_id"], req["payload"], b=req["party"],
                deadline_ms=req["deadline_ms"], priority=eff)
        except Exception as e:  # fallback-ok: a refused submit
            # (QueueFullError, unknown key, shape violation) is a
            # REQUEST-level outcome — answer typed, keep the
            # connection (framing was intact).
            srv._c_refused.inc()
            self._enqueue(encode_error(
                req_id, _code_for(e), str(e),
                getattr(e, "retry_after_s", None)))
            return
        tenant.c_points.inc(req["m"])
        # The frame buffer rides with the future: the payload view
        # aliases it until the batch gather copies the spans out.
        self._enqueue((req_id, fut, body))

    # -- response path -----------------------------------------------

    def _write_loop(self) -> None:
        srv = self._srv
        try:
            while True:
                item = self._out.get()
                if item is None:
                    break
                if isinstance(item, (bytes, bytearray)):
                    self._sock.sendall(item)
                    srv._c_errors_sent.inc()
                    continue
                if item[0] == "ctl":  # PONG/SYNC control responses
                    self._sock.sendall(item[1])
                    srv._c_responses.inc()
                    continue
                req_id, fut, _body = item
                try:
                    y = fut.result()
                except Exception as e:  # fallback-ok: a failed
                    # request (deadline, breaker, retries exhausted)
                    # crosses the wire as a typed ERROR frame; the
                    # connection survives.
                    self._sock.sendall(encode_error(
                        req_id, _code_for(e), str(e),
                        getattr(e, "retry_after_s", None)))
                    srv._c_errors_sent.inc()
                    continue
                _sendmsg_all(self._sock, encode_share(req_id, y))
                srv._c_responses.inc()
        except OSError:
            # fallback-ok: the peer stopped reading (reset/close) —
            # per-connection, contained; queued futures complete in
            # the service regardless (results are simply undeliverable)
            if not self._closing:
                srv._c_conn_errors.inc()
        finally:
            # The writer IS the out-queue's only consumer: mark the
            # connection closing so a reader blocked in _enqueue on a
            # full backlog (slow peer that then died) exits its slice
            # loop instead of spinning forever against a queue nobody
            # will ever drain.
            self._closing = True
            self._sock.close()


class EdgeServer:
    """The serving tier's TCP front (see the module docstring).

    ``EdgeServer(service).start()`` binds and spawns the accept loop;
    ``address`` is the bound ``(host, port)`` (port 0 picks a free
    one).  Tenancy and rate limits come from the service's
    ``ServeConfig.tenants``; all admission math runs on the service's
    injectable clock.  ``close()`` stops accepting, hangs up every
    connection, and joins the threads.
    """

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0,
                 *, max_frame_bytes: int = 64 << 20, backlog: int = 64,
                 read_timeout_s: float = 0.0,
                 tls_cert: str | None = None, tls_key: str | None = None,
                 tls_client_ca: str | None = None):
        if max_frame_bytes < _BODY_MIN + _CRC.size:
            # api-edge: config contract — a bound below one empty
            # frame refuses everything
            raise ValueError(
                f"max_frame_bytes must be >= {_BODY_MIN + _CRC.size}, "
                f"got {max_frame_bytes}")
        if read_timeout_s < 0:
            # api-edge: config contract (0 = block forever, the
            # trusted-peer default; a positive bound is the
            # slow-loris guard — note it also hangs up idle
            # keep-alive connections at the same horizon)
            raise ValueError(
                f"read_timeout_s must be >= 0, got {read_timeout_s}")
        self.read_timeout_s = float(read_timeout_s)
        self._service = service
        self._host = host
        self._port = port
        self.max_frame_bytes = int(max_frame_bytes)
        self._backlog = int(backlog)
        # The point width comes from the service-like target: a
        # DcfService exposes it as a property; the pod router
        # (serve.router) carries its own — anything with n_bytes,
        # _clock, metrics, config.tenants and submit_bytes can sit
        # behind this server (ISSUE 13: the router speaks DCFE on both
        # sides by fronting itself with this exact class).
        self.n_bytes = int(service.n_bytes)
        self._clock = service._clock
        self.metrics = service.metrics
        # TLS (ISSUE 13 satellite): explicit kwargs override the
        # service config's tls_* knobs (None = inherit).  cert+key arm
        # the server context; tls_client_ca additionally PINS clients —
        # only peers presenting a cert signed by that CA complete the
        # handshake (the router<->shard link hardening).
        cfg = getattr(service, "config", None)
        cert = tls_cert if tls_cert is not None \
            else getattr(cfg, "tls_cert", "")
        key = tls_key if tls_key is not None \
            else getattr(cfg, "tls_key", "")
        client_ca = tls_client_ca if tls_client_ca is not None \
            else getattr(cfg, "tls_client_ca", "")
        if bool(cert) != bool(key):
            # api-edge: TLS config contract — half a keypair serves
            # nothing; failing loudly beats a plaintext surprise
            raise ValueError(
                "TLS needs BOTH tls_cert and tls_key (got only one)")
        if client_ca and not cert:
            # api-edge: TLS config contract — client pinning without a
            # server identity is not a mode ssl offers
            raise ValueError(
                "tls_client_ca requires tls_cert/tls_key")
        self._tls_ctx: ssl.SSLContext | None = None
        if cert:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(cert, key)
            if client_ca:
                ctx.load_verify_locations(client_ca)
                ctx.verify_mode = ssl.CERT_REQUIRED
            self._tls_ctx = ctx
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._conns: set[_Conn] = set()
        self._listener: socket.socket | None = None
        self._acceptor: threading.Thread | None = None
        self._closing = False
        self._draining = False  # stop_accepting() ran (listener down,
        #                         live connections still serving)
        now = self._clock()
        self._tenants = {
            spec.name: _Tenant(spec, self.metrics, now)
            for spec in service.config.tenants}
        # The open-edge default: no table -> every tenant serves as
        # NORMAL, unlimited, under one shared metric identity.
        self._default_tenant = (None if self._tenants else _Tenant(
            TenantSpec(name="default"), self.metrics, now))
        m = self.metrics
        self._c_connections = m.counter("edge_connections_total")
        self._g_open = m.gauge("edge_connections_open")
        self._c_accept_errors = m.counter("edge_accept_errors_total")
        self._c_conn_errors = m.counter("edge_connection_errors_total")
        self._c_wire_errors = m.counter("edge_wire_errors_total")
        self._c_frames = m.counter("edge_frames_total")
        self._c_refused = m.counter("edge_refused_total")
        self._c_responses = m.counter("edge_responses_total")
        self._c_errors_sent = m.counter("edge_errors_sent_total")
        self._c_control = m.counter("edge_control_frames_total")

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "EdgeServer":
        if self._listener is not None:
            return self
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self._host, self._port))
        sock.listen(self._backlog)
        self._listener = sock
        self._acceptor = threading.Thread(
            target=self._accept_loop, name="edge-accept", daemon=True)
        self._acceptor.start()
        return self

    @property
    def address(self) -> tuple[str, int]:
        if self._listener is None:
            raise StaleStateError("edge server not started")
        return self._listener.getsockname()[:2]

    def stop_accepting(self) -> None:
        """Shut the listener down but leave live connections OPEN —
        the first half of a graceful shutdown (ISSUE 15): ``serve_host``
        stops new connections, drains the service so queued requests
        complete, and the writer threads deliver those responses over
        the still-open links before ``close()`` tears them down.
        Idempotent; ``close()`` calls it."""
        self._draining = True
        listener = self._listener
        if listener is not None:
            try:
                listener.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # never connected / already down
            listener.close()
        if self._acceptor is not None:
            self._acceptor.join(5.0)

    def close(self, drain_s: float = 0.0) -> None:
        """Tear the edge down.  ``drain_s`` > 0 is the graceful
        spelling: after the listener stops, each connection's writer
        gets up to that long to flush queued responses (the futures
        behind them must already be complete — ``serve_host`` drains
        the service first) before the hard close."""
        self._closing = True
        self.stop_accepting()
        with self._lock:
            conns = list(self._conns)
        if drain_s > 0:
            # Sentinel every writer FIRST, then join against ONE
            # shared deadline: K peers that stopped reading cost at
            # most drain_s total, not K * drain_s (a supervisor's
            # TERM-to-KILL window must bound the whole flush).
            for c in conns:
                c.nudge()
            deadline = monotonic() + drain_s
            for c in conns:
                c.join_writer(max(0.0, deadline - monotonic()))
        for c in conns:
            c.close()
        for c in conns:
            c.join(5.0)

    def __enter__(self) -> "EdgeServer":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- internals ----------------------------------------------------

    def _resolve_tenant(self, name: str) -> _Tenant | None:
        if self._default_tenant is not None:
            return self._default_tenant
        return self._tenants.get(name)

    def _forget(self, conn: _Conn) -> None:
        with self._lock:
            self._conns.discard(conn)
            self._g_open.set(len(self._conns))

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                fire("edge.accept")
                sock, addr = self._listener.accept()
            except OSError:
                # fallback-ok: close()/stop_accepting() shut the
                # listener down, or a transient accept failure — the
                # loop survives the latter and exits on the former.
                if self._closing or self._draining:
                    return
                self._c_accept_errors.inc()
                continue
            except Exception:  # fallback-ok: an armed edge.accept
                # fault models EMFILE-style accept errors; count and
                # keep accepting — live connections are untouched.
                self._c_accept_errors.inc()
                continue
            conn = None
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                1)
                if self._tls_ctx is not None:
                    # Wrap WITHOUT handshaking: the handshake blocks on
                    # peer bytes, and it must cost a reader thread, not
                    # the accept loop (the reader performs it as its
                    # first read — a plaintext or unpinned peer dies
                    # there as a counted per-connection failure).
                    sock = self._tls_ctx.wrap_socket(
                        sock, server_side=True,
                        do_handshake_on_connect=False)
                if self.read_timeout_s:
                    # The slow-loris bound: a recv blocking past this
                    # dies as a per-connection OSError (counted,
                    # contained) — a half-sent frame cannot pin a
                    # reader thread and its frame buffer forever.
                    sock.settimeout(self.read_timeout_s)
                conn = _Conn(self, sock, f"{addr[0]}:{addr[1]}")
                with self._lock:
                    if self._closing:
                        sock.close()
                        return
                    self._conns.add(conn)
                    n_open = len(self._conns)
                self._c_connections.inc()
                self._g_open.set(n_open)
                conn.start()
            except Exception:  # fallback-ok: a peer that reset before
                # setup, or thread/fd pressure at conn.start() — one
                # bad accepted socket is a per-connection failure, and
                # the accept loop must outlive it ('never a dead
                # accept loop', same contract the edge.accept seam
                # pins).
                self._c_accept_errors.inc()
                if conn is not None:
                    with self._lock:
                        self._conns.discard(conn)
                        self._g_open.set(len(self._conns))
                try:
                    sock.close()
                except OSError:
                    pass  # already gone


# ------------------------------------------------------ the client


def _raise_wire(code: int, retry_after_s: float | None, msg: str):
    cls = WIRE_CODES.get(code, DcfError)
    if cls is QueueFullError:
        err = cls(msg, retry_after_s=retry_after_s,
                  evicted=code == E_EVICTED)
    elif cls in (CircuitOpenError, RingEpochError, MeshUnavailableError):
        err = cls(msg, retry_after_s=retry_after_s)
    elif cls is ValueError:
        # api-edge: the server flagged a request-contract violation
        # (unknown key/tenant, bad party) — builtin semantics, exactly
        # what the in-process call site would have raised.
        err = ValueError(msg)
    else:
        err = cls(msg)
    # The raw wire code rides along (ISSUE 13): two codes can map to
    # one class (E_QUEUE_FULL vs E_RATE_LIMITED, E_UNAVAILABLE vs a
    # local transport death, which carries NO wire_code), and the
    # router's suspicion policy is keyed on the code, not the class.
    err.wire_code = code
    return err


class EdgeClient:
    """A pipelining DCFE client: ``submit`` returns a ``ServeFuture``
    completed by a reader thread matching ``req_id``s, so one
    connection can carry many requests in flight (the open-loop
    loadgen's shape) or be driven closed-loop (submit -> result).
    Typed failures arrive as the real ``dcf_tpu.errors`` classes, with
    ``retry_after_s`` re-attached where the taxonomy carries one.

    Not a pool: one instance = one TCP connection.  ``n_bytes`` is the
    service's point width (the client cannot discover it over the
    wire; passing the wrong one is refused typed by the server).
    """

    def __init__(self, host: str, port: int, *, n_bytes: int,
                 tenant: str = "", connect_timeout: float = 30.0,
                 max_frame_bytes: int = 256 << 20, tls: bool = False,
                 tls_ca: str = "", tls_cert: str = "",
                 tls_key: str = "", tags: tuple | None = None):
        self.n_bytes = int(n_bytes)
        self.tenant = tenant
        # Partition seam identity (ISSUE 14): ``(local, peer)`` tags —
        # when set, every dial and every frame send fires
        # ``net.partition`` so the chaos harness can cut this link
        # (the handler raises OSError, contained as transport death).
        self._tags = tuple(tags) if tags is not None else None
        if self._tags is not None:
            fire("net.partition", *self._tags)
        # Response-frame sanity bound (mirrors the server's request
        # knob): a SHARE payload is k*m*lam — raise this when a
        # large-lambda service legitimately returns more than 256 MiB
        # per response, or an oversized VALID share would tear the
        # connection down as a framing error.
        self.max_frame_bytes = int(max_frame_bytes)
        ctx: ssl.SSLContext | None = None
        if tls or tls_ca or tls_cert:
            # TLS (ISSUE 13 satellite): ``tls_ca`` pins the server —
            # the handshake verifies its cert chains to that CA (the
            # cert's SAN must cover ``host``, IP or name).  Without a
            # CA the link is encrypted but UNAUTHENTICATED — lab-only,
            # stated here so nobody mistakes it for pinning.
            # ``tls_cert``/``tls_key`` present a client cert for
            # servers that pin clients (``tls_client_ca``).  Context
            # construction precedes the dial: a bad TLS config must
            # fail loudly, not after a connect timeout.
            if bool(tls_cert) != bool(tls_key):
                # api-edge: TLS config contract (half a keypair)
                raise ValueError(
                    "client TLS needs BOTH tls_cert and tls_key")
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            if tls_ca:
                ctx.load_verify_locations(tls_ca)
                ctx.check_hostname = True
            else:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if tls_cert:
                ctx.load_cert_chain(tls_cert, tls_key)
        self._sock = socket.create_connection(
            (host, port), timeout=connect_timeout)
        if ctx is not None:
            self._sock = ctx.wrap_socket(self._sock,
                                         server_hostname=host)
        # Blocking from here on: the reader parks in recv between
        # responses (close() unblocks it); waiting bounds belong to
        # ``ServeFuture.result(timeout)``, not the transport.
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()       # id/pending/closed state
        self._send_lock = threading.Lock()  # frame writes stay whole
        # guarded-by: _lock
        self._pending: dict[int, ServeFuture] = {}
        # guarded-by: _lock
        self._next_id = 1
        # guarded-by: _lock
        self._closed = False
        self._reader = threading.Thread(
            target=self._read_loop, name="edge-client-read",
            daemon=True)
        self._reader.start()

    # -- submission ---------------------------------------------------

    def submit(self, key_id: str, xs, b: int = 0,
               deadline_ms: float | None = None,
               priority=None) -> ServeFuture:
        """Wire twin of ``DcfService.submit`` (``priority=None`` =
        the tenant's class).  Thread-safe."""
        xs = np.ascontiguousarray(np.asarray(xs, dtype=np.uint8))
        if xs.ndim != 2 or xs.shape[1] != self.n_bytes:
            raise ShapeError(
                f"xs must be [M, {self.n_bytes}], got {xs.shape}")
        if xs.shape[0] < 1:
            raise ShapeError("cannot submit an empty request")
        return self.submit_bytes(key_id, xs.data, m=xs.shape[0], b=b,
                                 deadline_ms=deadline_ms,
                                 priority=priority)

    def submit_bytes(self, key_id: str, data, m: int | None = None,
                     b: int = 0, deadline_ms: float | None = None,
                     priority=None, epoch: int = 0) -> ServeFuture:
        """Wire twin of ``DcfService.submit_bytes`` — and the pod
        router's relay path (ISSUE 13): ``data`` (any buffer-protocol
        object of ``m`` packed ``n_bytes``-wide points; ``m`` derived
        when omitted) is sent BY REFERENCE via the scatter-gather
        write, so a forwarded request's payload crosses this hop as a
        ``memoryview`` of the upstream frame buffer — no join, no
        re-materialization.  The caller must keep ``data`` alive until
        this call returns (the send completes synchronously).
        ``epoch`` (ISSUE 15): the ring epoch the sender routed on —
        the router passes its current one; direct callers leave 0
        (unfenced)."""
        view = memoryview(data).cast("B")
        if m is None:
            if view.nbytes == 0 or view.nbytes % self.n_bytes:
                raise ShapeError(
                    f"payload of {view.nbytes} bytes is not a positive "
                    f"multiple of n_bytes={self.n_bytes}")
            m = view.nbytes // self.n_bytes
        if m < 1 or m * self.n_bytes != view.nbytes:
            raise ShapeError(
                f"payload holds {view.nbytes} bytes, not m={m} points "
                f"of {self.n_bytes}")
        pri = _PRI_DEFAULT if priority is None \
            else parse_priority(priority).value
        with self._lock:
            if self._closed:
                raise BackendUnavailableError(
                    "edge connection is closed")
            req_id = self._next_id
            self._next_id += 1
        # Encode BEFORE registering: an encoding failure (a key_id
        # over the 255-byte field, a bad party byte) must not leave an
        # orphaned never-completed future in _pending for the
        # connection's lifetime.  The burned req_id is harmless.
        views = [memoryview(p).cast("B") for p in _request_parts(
            req_id, self.tenant, key_id, b, pri, deadline_ms, view,
            self.n_bytes, m, epoch)]
        crc = 0
        for v in views:
            crc = zlib.crc32(v, crc)
        body_len = sum(v.nbytes for v in views) + _CRC.size
        fut = ServeFuture()
        with self._lock:
            if self._closed:
                raise BackendUnavailableError(
                    "edge connection is closed")
            self._pending[req_id] = fut
        try:
            with self._send_lock:
                if self._tags is not None:
                    fire("net.partition", *self._tags)
                _sendmsg_all(self._sock,
                             [_PREFIX.pack(body_len), *views,
                              _CRC.pack(crc)])
        except OSError as e:
            # A failed send means the TRANSPORT is gone, not just this
            # request: mark the connection closed and fail every
            # pending future typed, or a pooled caller would keep
            # retrying a dead connection forever (``closed`` stays the
            # reliable reconnect signal).
            err = BackendUnavailableError(
                f"edge connection lost on send: {e}")
            self._fail_pending(err)
            raise err from e
        return fut

    # -- control frames (ISSUE 14) ------------------------------------

    def _roundtrip(self, encode, timeout: float | None):
        """Register a future, send one control frame (``encode(req_id)
        -> frame bytes``), wait for its response.  Send failures take
        the submit path's transport-death containment; a TIMEOUT prunes
        the pending entry (a prober timing out every interval must not
        grow ``_pending`` without bound — a late response to a pruned
        id is dropped by the reader, harmless)."""
        with self._lock:
            if self._closed:
                raise BackendUnavailableError(
                    "edge connection is closed")
            req_id = self._next_id
            self._next_id += 1
        # Encode BEFORE registering: same orphaned-future rule as
        # submit_bytes.
        wire = encode(req_id)
        fut = ServeFuture()
        with self._lock:
            if self._closed:
                raise BackendUnavailableError(
                    "edge connection is closed")
            self._pending[req_id] = fut
        try:
            with self._send_lock:
                if self._tags is not None:
                    fire("net.partition", *self._tags)
                # dcflint: disable=blocking-under-lock _send_lock exists
                # precisely to serialize whole-frame socket writes —
                # interleaved partial frames from two submitting threads
                # would corrupt the stream for every request in flight.
                # It guards no other state and is never nested inside
                # another lock, so contenders wait on peer I/O by design.
                self._sock.sendall(wire)
        except OSError as e:
            err = BackendUnavailableError(
                f"edge connection lost on send: {e}")
            self._fail_pending(err)
            raise err from e
        try:
            return fut.result(timeout)
        except TimeoutError:
            with self._lock:
                self._pending.pop(req_id, None)
            raise

    def ping(self, timeout: float | None = None,
             epoch: int = 0) -> bool:
        """One PING round trip (ISSUE 14: the health prober's liveness
        probe).  Returns True, or raises — transport death typed
        ``BackendUnavailableError``, an unanswered probe the builtin
        ``TimeoutError``, a stale fenced probe the typed
        ``RingEpochError`` (ISSUE 15 — ``epoch`` is the prober's ring
        epoch; 0 = unfenced liveness only)."""
        self._roundtrip(lambda rid: encode_ping(rid, epoch), timeout)
        return True

    def ping_epoch(self, timeout: float | None = None,
                   epoch: int = 0) -> int:
        """PING returning the PEER's current ring epoch (the PONG
        value — ISSUE 15: how the membership benches verify epoch
        convergence over the wire).  Same failure modes as ``ping``."""
        return int(self._roundtrip(
            lambda rid: encode_ping(rid, epoch), timeout))

    def ping_load(self, timeout: float | None = None,
                  epoch: int = 0) -> tuple:
        """PING asking for the peer's demand signals (ISSUE 16:
        ``want_load``).  Returns ``(peer_epoch, LoadSample | None)`` —
        None when the peer has no load surface (an older shard, or a
        router front): the probe itself still succeeded.  Failure
        modes are ``ping``'s."""
        out = self._roundtrip(
            lambda rid: encode_ping(rid, epoch, want_load=True),
            timeout)
        if isinstance(out, tuple):
            value, load = out
            return int(value), load
        return int(out), None

    def register_frame(self, key_id: str, frame, generation: int = 0,
                       proto: bool = False,
                       timeout: float | None = None,
                       epoch: int = 0) -> int:
        """Forward one DCFK frame for registration (ISSUE 14).
        ``generation=0`` mints at the receiver (owner registration);
        ``generation>0`` is the fenced replica apply — a receiver
        already at or past that generation raises the real
        ``StaleStateError`` here (``E_STALE``).  ``epoch`` fences the
        registration against membership staleness (``E_EPOCH``,
        ISSUE 15; 0 = unfenced).  Returns the generation the key is
        registered under."""
        return int(self._roundtrip(
            lambda rid: encode_register(rid, key_id, frame,
                                        generation, proto, epoch),
            timeout))

    def pull_digest(self, timeout: float | None = None) -> dict:
        """The peer's live ``{key_id: generation}`` registration
        digest (anti-entropy, mode 1 — no frame bytes move)."""
        entries = self._roundtrip(
            lambda rid: encode_digest(rid, {}, mode=1), timeout)
        return {k: g for k, g, _p, _f in entries}

    def sync_newer(self, digest: dict,
                   timeout: float | None = None) -> list:
        """Anti-entropy pull (mode 0): send ``digest`` and receive
        ``(key_id, generation, proto, frame)`` entries for every key
        the peer holds at a STRICTLY newer generation."""
        return self._roundtrip(
            lambda rid: encode_digest(rid, dict(digest), mode=0),
            timeout)

    def evaluate(self, key_id: str, xs, b: int = 0,
                 deadline_ms: float | None = None,
                 timeout: float | None = None,
                 priority=None) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(key_id, xs, b, deadline_ms,
                           priority).result(timeout)

    # -- the reader ---------------------------------------------------

    def _recv_into(self, view: memoryview) -> int:
        got = 0
        while got < len(view):
            n = self._sock.recv_into(view[got:], len(view) - got)
            if n == 0:
                return got
            got += n
        return got

    def _read_loop(self) -> None:
        try:
            while True:
                prefix = bytearray(_PREFIX.size)
                if self._recv_into(memoryview(prefix)) < _PREFIX.size:
                    break  # server hung up
                (body_len,) = _PREFIX.unpack(prefix)
                if not _BODY_MIN + _CRC.size <= body_len \
                        <= self.max_frame_bytes:
                    raise KeyFormatError(
                        f"length prefix {body_len} is not a frame "
                        f"(bound {self.max_frame_bytes})")
                body = bytearray(body_len)
                if self._recv_into(memoryview(body)) < body_len:
                    break  # mid-frame EOF: fail pending below
                kind, req_id, *rest = decode_response(body)
                # Claim the future under the lock (ISSUE 17 guarded-by
                # sweep): an unlocked pop could race _fail_pending's
                # swap-and-fail — both sides claiming the same future,
                # one completing it with a result while the other
                # fails it.  Holding _lock makes exactly one claimant
                # win per future.
                with self._lock:
                    fut = self._pending.pop(req_id, None)
                if kind in ("share", "pong", "sync"):
                    if fut is not None:
                        fut.set_result(rest[0])
                elif fut is not None:
                    code, retry, msg = rest
                    fut.set_exception(_raise_wire(code, retry, msg))
                elif req_id == 0:
                    # A connection-level error frame: the server is
                    # about to hang up; every pending request dies
                    # with the typed cause.
                    code, retry, msg = rest
                    self._fail_pending(_raise_wire(code, retry, msg))
        except Exception as e:  # fallback-ok: the reader must fail
            # every pending future on ANY teardown (socket error,
            # mangled frame) instead of leaving waiters hanging.
            self._fail_pending(BackendUnavailableError(
                f"edge connection lost: {type(e).__name__}: {e}"))
            return
        finally:
            self._fail_pending(BackendUnavailableError(
                "edge connection closed"))

    @property
    def closed(self) -> bool:
        """True once the connection is dead (peer/server hung up, a
        wire error, or ``close()``): pending futures have been failed
        typed and further ``submit`` calls raise.  The reconnect
        signal for pooled clients — a request-level typed failure
        (deadline, shed, breaker) leaves the connection OPEN and this
        False."""
        # dcflint: disable=guarded-by monitoring snapshot: one atomic
        # bool read; submit/roundtrip re-check under _lock before
        # registering a future
        return self._closed

    def _fail_pending(self, error: BaseException) -> None:
        with self._lock:
            self._closed = True
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(error)

    def close(self) -> None:
        with self._lock:
            self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # already gone
        self._sock.close()
        self._reader.join(5.0)

    def __enter__(self) -> "EdgeClient":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class EdgeClientPool:
    """A bounded, reconnecting pool of ``EdgeClient`` connections to
    ONE target (ISSUE 13): PR 12's benches hand-rolled ``closed``-check
    + reconnect loops; this is that logic promoted into the reusable
    transport the pod router forwards through (and ``edge_bench``/
    ``loadgen`` drive).

    Semantics:

    * at most ``size`` live connections, leased round-robin — a lease
      prefers a live slot and only DIALS when the slot it lands on is
      empty or its client reports ``closed`` (the PR 12 reconnect
      signal: transport death fails every pending future typed and
      latches ``closed``; request-level typed failures leave the
      connection open and the pool alone);
    * dial failures back off exponentially on the INJECTABLE clock
      (``reconnect_backoff_s`` doubling up to ``max_backoff_s``) —
      while the target stays dark every lease fails typed
      ``BackendUnavailableError`` immediately, without burning a
      connect timeout per request; the first successful dial resets
      the backoff;
    * no internal request retry: a submit that fails is the CALLER's
      typed outcome (the router's failover policy decides what happens
      next — the transport must not make that call for it).

    ``reconnects``/``dials`` are plain counters the benches read
    (``reconnects`` counts dials that REPLACED a dead client, i.e. the
    PR 12 soak's reconnect stat).  Thread-safe.
    """

    def __init__(self, host: str, port: int, *, n_bytes: int,
                 tenant: str = "", size: int = 2, clock=monotonic,
                 connect_timeout: float = 5.0,
                 reconnect_backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0,
                 max_frame_bytes: int = 256 << 20, tls: bool = False,
                 tls_ca: str = "", tls_cert: str = "",
                 tls_key: str = "", tags: tuple | None = None):
        if size < 1:
            # api-edge: pool config contract
            raise ValueError(f"pool size must be >= 1, got {size}")
        if reconnect_backoff_s <= 0 or max_backoff_s < reconnect_backoff_s:
            # api-edge: pool config contract — a zero base would make
            # "dark" unrepresentable and hammer a dead target
            raise ValueError(
                f"need 0 < reconnect_backoff_s <= max_backoff_s, got "
                f"{reconnect_backoff_s}/{max_backoff_s}")
        self.host, self.port = host, int(port)
        self.n_bytes = int(n_bytes)
        self.tenant = tenant
        self.size = int(size)
        self._clock = clock
        self._connect_timeout = float(connect_timeout)
        self._base_backoff = float(reconnect_backoff_s)
        self._max_backoff = float(max_backoff_s)
        self._client_kwargs = dict(
            n_bytes=self.n_bytes, tenant=tenant,
            connect_timeout=self._connect_timeout,
            max_frame_bytes=max_frame_bytes, tls=tls, tls_ca=tls_ca,
            tls_cert=tls_cert, tls_key=tls_key, tags=tags)
        self._lock = threading.Lock()
        # guarded-by: _lock
        self._slots: list[EdgeClient | None] = [None] * self.size
        # guarded-by: _lock
        self._rr = 0
        # guarded-by: _lock
        self._backoff = 0.0
        # guarded-by: _lock
        self._dark_until: float | None = None
        # guarded-by: _lock
        self._closed = False
        self.reconnects = 0  # dials that replaced a dead client
        self.dials = 0       # every successful connect

    def _lease(self) -> EdgeClient:
        with self._lock:
            if self._closed:
                raise BackendUnavailableError(
                    f"pool to {self.host}:{self.port} is closed")
            # One full round-robin scan for a LIVE slot first: a dead
            # slot must not force a dial while healthy connections sit
            # idle beside it.
            for _ in range(self.size):
                i = self._rr
                self._rr = (self._rr + 1) % self.size
                c = self._slots[i]
                if c is not None and not c.closed:
                    return c
            # Every slot is empty or dead: dial into the current slot,
            # honoring the dark-target backoff on the injectable clock.
            now = self._clock()
            if self._dark_until is not None and now < self._dark_until:
                raise BackendUnavailableError(
                    f"target {self.host}:{self.port} is dark; next "
                    f"dial in {self._dark_until - now:.3f}s "
                    "(reconnect backoff)")
            i = self._rr
            self._rr = (self._rr + 1) % self.size
            replacing = self._slots[i] is not None
            try:
                fresh = EdgeClient(self.host, self.port,
                                   **self._client_kwargs)
            except OSError as e:
                self._backoff = min(
                    max(2 * self._backoff, self._base_backoff),
                    self._max_backoff)
                self._dark_until = now + self._backoff
                raise BackendUnavailableError(
                    f"cannot connect to {self.host}:{self.port} "
                    f"(backing off {self._backoff:.3f}s): {e}") from e
            self._backoff = 0.0
            self._dark_until = None
            self._slots[i] = fresh
            self.dials += 1
            if replacing:
                self.reconnects += 1
            return fresh

    def submit(self, key_id: str, xs, b: int = 0,
               deadline_ms: float | None = None,
               priority=None) -> ServeFuture:
        return self._lease().submit(key_id, xs, b=b,
                                    deadline_ms=deadline_ms,
                                    priority=priority)

    def submit_bytes(self, key_id: str, data, m: int | None = None,
                     b: int = 0, deadline_ms: float | None = None,
                     priority=None, epoch: int = 0) -> ServeFuture:
        return self._lease().submit_bytes(key_id, data, m=m, b=b,
                                          deadline_ms=deadline_ms,
                                          priority=priority,
                                          epoch=epoch)

    def evaluate(self, key_id: str, xs, b: int = 0,
                 deadline_ms: float | None = None,
                 timeout: float | None = None,
                 priority=None) -> np.ndarray:
        return self.submit(key_id, xs, b, deadline_ms,
                           priority).result(timeout)

    # -- control frames (ISSUE 14: the health/replication surface) ----

    def ping(self, timeout: float | None = None,
             epoch: int = 0) -> bool:
        """One PING round trip through a leased connection — the
        health prober's probe.  While the target is dark the lease
        fails typed inside the backoff without dialing, so probe
        frequency against a dead host is bounded by ``max_backoff_s``
        (recovery detection is therefore at most one backoff late —
        and the UP transition clamps the backoff so REQUESTS never
        wait it out; see ``reset_backoff``)."""
        return self._lease().ping(timeout, epoch=epoch)

    def ping_epoch(self, timeout: float | None = None,
                   epoch: int = 0) -> int:
        return self._lease().ping_epoch(timeout, epoch=epoch)

    def ping_load(self, timeout: float | None = None,
                  epoch: int = 0) -> tuple:
        """``EdgeClient.ping_load`` through a leased connection — the
        health prober's load-sampling probe (ISSUE 16)."""
        return self._lease().ping_load(timeout, epoch=epoch)

    def register_frame(self, key_id: str, frame, generation: int = 0,
                       proto: bool = False,
                       timeout: float | None = None,
                       epoch: int = 0) -> int:
        return self._lease().register_frame(key_id, frame, generation,
                                            proto, timeout,
                                            epoch=epoch)

    def pull_digest(self, timeout: float | None = None) -> dict:
        return self._lease().pull_digest(timeout)

    def sync_newer(self, digest: dict,
                   timeout: float | None = None) -> list:
        return self._lease().sync_newer(digest, timeout)

    def reset_backoff(self) -> None:
        """Clamp the dial backoff to zero (ISSUE 14 satellite): the
        health prober just CONFIRMED the target is up, so a pool that
        accumulated the full exponential backoff during a long outage
        must not keep failing leases fast until it drains — the next
        lease dials immediately."""
        with self._lock:
            self._backoff = 0.0
            self._dark_until = None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            slots, self._slots = self._slots, [None] * self.size
        for c in slots:
            if c is not None:
                c.close()

    def __enter__(self) -> "EdgeClientPool":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
