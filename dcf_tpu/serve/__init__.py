"""dcf_tpu.serve — the online DCF evaluation service layer.

Everything between "a staged backend that can evaluate one big batch"
and "a service answering bursty small queries against many long-lived
keys":

- ``serve.batcher``   pure micro-batch planning: coalesce ragged
  requests into padded power-of-two device batches, scatter results
  back per request (property-tested in isolation);
- ``serve.registry``  named key bundles + LRU device-residency cache
  under a device-bytes budget, one invalidation path shared with
  ``Dcf.reset_backend_health``;
- ``serve.admission`` bounded queue (``QueueFullError`` shedding),
  priority classes (CRITICAL/NORMAL/BATCH: lowest-class-first eviction
  and brownout refusal), deadline propagation
  (``DeadlineExceededError``), result futures;
- ``serve.breaker``   per-(key_id, backend-family) circuit breakers
  (closed/open/half-open on the injectable clock; open pairings fail
  fast with ``CircuitOpenError``, CRITICAL bypasses);
- ``serve.frontier_cache`` serve-resident LRU over prefix-family
  frontier expansions, keyed (key_id, generation, party, k), sharing
  the registry's byte budget and deterministic LRU stamps (ISSUE 7:
  amortize the narrow-walk floor under skewed traffic);
- ``serve.store``     durable key store (ISSUE 8): DCFK frames
  published atomic write-fsync-rename under a CRC'd manifest, 0o600,
  typed quarantine of damaged frames (``KeyQuarantinedError``) and the
  warm-restart path ``KeyRegistry.restore`` /
  ``DcfService.restore_keys`` preserving generations;
- ``serve.keyfactory`` ahead-of-demand keygen pools (ISSUE 11):
  per-(function, priority) pools of pre-minted two-party session
  bundles topped up on device in K-packed batches, published to the
  store in batched atomic manifest flips, claimed by
  ``register_key(key_id, pool=...)`` at pool-pop latency with a
  counted, warned synchronous-mint fallback on exhaustion;
- ``serve.edge``      the network edge (ISSUE 12): a stdlib-only
  length-prefixed binary protocol over TCP carrying DCFE-framed
  requests with a zero-copy ingest path (received point bytes go
  buffer-protocol straight into the batcher's staged layout via the
  ONE ``batcher.ingest_points`` feed), tenant->priority-class mapping
  with per-tenant token buckets (``TenantSpec`` in
  ``ServeConfig.tenants``), and typed wire error frames carrying
  retry-after hints; ``EdgeClient`` is the pipelining counterpart;
- ``serve.meshgroup`` the co-evaluation group (ISSUE 18): device
  placement for one batch spanning every host — 32-aligned contiguous
  point slices per mesh worker, epoch-fenced formation; the router's
  "co-evaluate" dispatch mode scatters over it and gathers shares
  back in plan order, degrading typed to route-mode when the mesh
  cannot take the batch;
- ``serve.shardmap``  the pod shard ring (ISSUE 13): rendezvous
  placement of keys onto host shards — deterministic keyed-digest
  scores, minimal disruption under membership change, the replica
  ranking failover and frame replication both read;
- ``serve.membership`` autonomous ring membership (ISSUE 15):
  health-driven auto-eject with pre-commit re-replication, graceful
  warm-before-admit join, three-phase drain for planned decommission,
  and the monotonic ring-epoch fence (``RingEpochError``/``E_EPOCH``)
  that structurally refuses routers on a stale membership view;
- ``serve.capacity``  demand-driven autoscaling (ISSUE 16): a
  capacity controller aggregating per-shard load samples (piggybacked
  on the health probes) through the metrics-rollup path into typed
  pressure verdicts, with fail-N/recover-M hysteresis and a hard
  cooldown lifted to scaling decisions — sustained pressure admits a
  standby host through the graceful join, sustained idleness drains
  the least-loaded host back to standby, oscillation produces zero
  ring churn;
- ``serve.router``    the pod routing tier (ISSUE 13): a DCFE-on-
  both-sides router forwarding frames header-decode-only (payload
  relayed as a memoryview through pooled ``EdgeClient``s) with
  typed-taxonomy failover — suspect shards fail CRITICAL traffic
  over to the key's replica, everything else refused typed with
  ``retry_after_s``;
- ``serve.metrics``   dependency-free counters/gauges/histograms with a
  deterministic snapshot (embedded in RESULTS_serve JSONL lines);
  ``rollup_snapshots`` sums per-host snapshots into the pod view;
- ``serve.service``   ``DcfService``: the worker loop tying it together,
  with a stage-ahead double-buffered dispatch pipeline and the
  ``serve.stage``/``serve.eval`` fault seams;
- ``serve.loadgen``   the closed-loop load generator behind the
  ``serve_bench`` CLI subcommand, plus the open-loop (Poisson) mode
  the edge latency quantiles need (ISSUE 12: no coordinated
  omission).

Entry point: ``Dcf.serve(...)`` (see ``dcf_tpu.api``).
"""

from dcf_tpu.serve.admission import (  # noqa: F401
    Priority,
    ServeFuture,
    TenantSpec,
)
from dcf_tpu.serve.breaker import BreakerBoard  # noqa: F401
from dcf_tpu.serve.edge import (  # noqa: F401
    EdgeClient,
    EdgeClientPool,
    EdgeServer,
)
from dcf_tpu.serve.capacity import (  # noqa: F401
    CapacityController,
    CapacityEvent,
    CapacityVerdict,
)
from dcf_tpu.serve.frontier_cache import FrontierCache  # noqa: F401
from dcf_tpu.serve.health import (  # noqa: F401
    HealthEvent,
    HealthProber,
)
from dcf_tpu.serve.keyfactory import KeyFactory, PoolSpec  # noqa: F401
from dcf_tpu.serve.membership import (  # noqa: F401
    MembershipController,
    MembershipEvent,
)
from dcf_tpu.serve.meshgroup import MeshGroup, MeshSlice  # noqa: F401
from dcf_tpu.serve.metrics import Metrics, rollup_snapshots  # noqa: F401
from dcf_tpu.serve.registry import KeyRegistry  # noqa: F401
from dcf_tpu.serve.replicate import Replicator  # noqa: F401
from dcf_tpu.serve.router import DcfRouter  # noqa: F401
from dcf_tpu.serve.service import DcfService, ServeConfig  # noqa: F401
from dcf_tpu.serve.shardmap import ShardMap, ShardSpec  # noqa: F401
from dcf_tpu.serve.store import KeyStore, RestoreReport  # noqa: F401

__all__ = ["DcfService", "ServeConfig", "ServeFuture", "Priority",
           "TenantSpec", "EdgeServer", "EdgeClient", "EdgeClientPool",
           "BreakerBoard", "CapacityController", "CapacityEvent",
           "CapacityVerdict", "DcfRouter", "FrontierCache",
           "HealthEvent", "HealthProber", "KeyFactory", "Metrics",
           "KeyRegistry", "KeyStore", "MembershipController",
           "MembershipEvent", "MeshGroup", "MeshSlice", "PoolSpec",
           "Replicator",
           "RestoreReport", "ShardMap", "ShardSpec",
           "rollup_snapshots"]
