"""Shard ring for the pod-scale serving tier (ISSUE 13): rendezvous
placement of serve keys onto host shards.

A pod is N independent shard processes — each the existing crash-safe,
breaker-guarded, pool-fed single-host unit (``DcfService`` +
``EdgeServer``) — fronted by a router (``serve.router``) that forwards
DCFE frames by hashing ``key_id`` onto this ring.  The ring is PURE
placement: no sockets, no health state (the router owns suspicion and
failover), no clocks — a deterministic function from (membership,
key_id) to a host ranking, so two processes holding the same member
list always agree on who owns a key.

Rendezvous (highest-random-weight) hashing, not consistent-hash
tokens: every host scores ``blake2b(host_id || key_id)`` per key and
the ranking is the descending score order.  The properties the serving
tier leans on:

* **deterministic** — the score is a keyed digest of two strings;
  PYTHONHASHSEED, process identity and dict order are irrelevant, so a
  router restart (or a second router) computes the same placement;
* **minimally disruptive** — removing a host moves EXACTLY the keys it
  owned (every other pair's relative score is untouched), and adding
  one steals on average 1/N of the keys from the incumbents
  (seeded-fuzz-pinned in ``tests/test_pod.py``);
* **replica-consistent** — the ranking's second entry is the key's
  replica: the host that BECOMES the owner if the owner is removed, so
  failover routing and durable-frame replication (``KeyStore``
  discipline, generations preserved) name the same host by
  construction.

Membership change returns a NEW ``ShardMap`` (``with_host`` /
``without_host``): the router swaps the reference atomically, and an
in-flight request keeps the ranking it started with.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

__all__ = ["ShardSpec", "ShardMap"]


@dataclass(frozen=True)
class ShardSpec:
    """One shard host: a stable identity plus its DCFE edge address.

    ``host_id`` is the PLACEMENT identity — it, not the address, feeds
    the rendezvous score, so a shard that restarts on a new port (or
    migrates hosts) keeps its keys as long as it keeps its id."""

    host_id: str
    host: str = "127.0.0.1"
    port: int = 0

    def __post_init__(self):
        if not self.host_id:
            # api-edge: ring membership contract — the empty id would
            # silently collide every anonymous shard onto one score
            raise ValueError("shard host_id must be non-empty")

    @property
    def address(self) -> tuple[str, int]:
        return (self.host, self.port)


def _score(host_id: str, key_id: str) -> int:
    """The rendezvous weight of ``host_id`` for ``key_id``: a 64-bit
    keyed digest (blake2b — stdlib, stable across processes and
    platforms; NEVER builtin ``hash``, which is salted per process)."""
    h = hashlib.blake2b(key_id.encode("utf-8"), digest_size=8,
                        key=host_id.encode("utf-8")[:64])
    return int.from_bytes(h.digest(), "little")


class ShardMap:
    """Immutable rendezvous ring over a set of ``ShardSpec`` hosts."""

    def __init__(self, shards):
        shards = tuple(shards)
        if not shards:
            # api-edge: ring membership contract — an empty ring has
            # no owner for any key; the router refuses to build one
            raise ValueError("a shard ring needs at least one host")
        ids = [s.host_id for s in shards]
        if len(set(ids)) != len(ids):
            # api-edge: ring membership contract — duplicate ids would
            # make the ranking order depend on list position
            raise ValueError(f"duplicate shard host_ids in {ids}")
        # Stored sorted by host_id: the ring is a SET — two routers
        # configured with the same members in different order must be
        # the same ring (ties in the ranking also break by this order).
        self._shards = tuple(sorted(shards, key=lambda s: s.host_id))
        self._by_id = {s.host_id: s for s in self._shards}

    # -- membership ---------------------------------------------------

    def hosts(self) -> tuple[ShardSpec, ...]:
        return self._shards

    def host_ids(self) -> list[str]:
        return [s.host_id for s in self._shards]

    def __len__(self) -> int:
        return len(self._shards)

    def __contains__(self, host_id: str) -> bool:
        return host_id in self._by_id

    def get(self, host_id: str) -> ShardSpec | None:
        return self._by_id.get(host_id)

    def with_host(self, shard: ShardSpec) -> "ShardMap":
        """A new ring with ``shard`` added (or its address updated —
        same ``host_id`` replaces the member, which moves no keys)."""
        kept = [s for s in self._shards if s.host_id != shard.host_id]
        return ShardMap([*kept, shard])

    def without_host(self, host_id: str) -> "ShardMap":
        """A new ring with ``host_id`` removed — exactly that host's
        keys move (to each key's next-ranked host)."""
        if host_id not in self._by_id:
            # api-edge: ring membership contract (removing an unknown
            # id is a caller bookkeeping bug, not a no-op)
            raise ValueError(f"host {host_id!r} is not in the ring "
                             f"({self.host_ids()})")
        kept = [s for s in self._shards if s.host_id != host_id]
        return ShardMap(kept)

    # -- placement ----------------------------------------------------

    def peers(self, host_id: str) -> tuple[ShardSpec, ...]:
        """Every OTHER member of the ring (ISSUE 14: the anti-entropy
        exchange set for ``host_id`` — a healed shard converges with
        its peers, never with itself).  Raises for an unknown id, same
        contract as ``without_host``."""
        if host_id not in self._by_id:
            # api-edge: ring membership contract
            raise ValueError(f"host {host_id!r} is not in the ring "
                             f"({self.host_ids()})")
        return tuple(s for s in self._shards if s.host_id != host_id)

    def ranked(self, key_id: str) -> list[ShardSpec]:
        """Every host, descending rendezvous score for ``key_id``:
        ``[owner, replica, ...]``.  Ties (astronomically unlikely with
        64-bit scores, but the ranking must still be total) break by
        ``host_id`` order."""
        return sorted(
            self._shards,
            key=lambda s: (-_score(s.host_id, key_id), s.host_id))

    def owner(self, key_id: str) -> ShardSpec:
        """The host that serves ``key_id``."""
        best = self._shards[0]
        best_score = _score(best.host_id, key_id)
        for s in self._shards[1:]:
            sc = _score(s.host_id, key_id)
            if sc > best_score:
                best, best_score = s, sc
        return best

    def replica(self, key_id: str) -> ShardSpec | None:
        """The failover host for ``key_id`` (the ranking's second
        entry — the owner-if-the-owner-leaves), or ``None`` on a
        single-host ring."""
        if len(self._shards) < 2:
            return None
        return self.ranked(key_id)[1]

    def placement(self, key_id: str, replicas: int = 1) -> list[ShardSpec]:
        """The hosts that should HOLD ``key_id``'s durable frame: the
        owner plus ``replicas`` successors (clamped to the ring size).
        The provisioning twin of the router's failover walk — both read
        the same ranking, so the host failover lands on is a host the
        frame was replicated to."""
        if replicas < 0:
            # api-edge: placement contract
            raise ValueError(f"replicas must be >= 0, got {replicas}")
        return self.ranked(key_id)[:1 + replicas]

    def placement_ids(self, key_id: str, replicas: int = 1) -> set:
        """``placement`` as a host-id SET (ISSUE 15): the membership
        controller, the router's promotion walk and the pod benches
        all ask "does host X hold this key?" — one spelling, not four
        copies of the comprehension."""
        return {s.host_id for s in self.placement(key_id, replicas)}

    def __repr__(self) -> str:
        return f"ShardMap({self.host_ids()})"
