"""Key registry + device-residency cache for the serving layer.

Long-lived DCF keys are the defining asset of an online FSS service:
a bundle is generated once, then answers queries for hours.  The
registry separates the two lifetimes involved:

* **registration** (host): ``register(key_id, bundle)`` records the
  host-side ``KeyBundle`` under a caller-chosen name.  Cheap, unbounded
  by device memory.  Re-registration under a live name is guarded the
  same way PR 1's staged-geometry freshness check guards re-staging: it
  is allowed, bumps the key's generation, and atomically evicts every
  device residency built against the old bundle — serving a share from
  a superseded key would be the silent-corruption analog of ADVICE.md
  finding 3.
* **residency** (device): ``resident(key_id, party)`` lazily constructs
  a dedicated backend instance for that (key, party) slot and ships the
  key image via the backend's existing ``put_bundle``; the instance (and
  with it the staged plane image, frontier tables, etc.) is cached and
  reused across batches.  Residencies are evicted LRU when the summed
  device-image bytes exceed ``device_bytes_budget`` — dropping the
  backend instance releases its device arrays to the allocator.

The keylanes backend's CW image is shared between parties (reference
src/lib.rs:269-272), so its residency slot is per-key, not per
(key, party) — same rule the ``Dcf`` facade applies.

LRU order is tracked with a deterministic access counter, not a clock:
eviction order must be a pure function of the request sequence so tests
can pin it (and the dcflint determinism pass holds serve code to that).

ISSUE 8: ``restore(store)`` is the warm-restart path — a
``serve.store.KeyStore`` re-registers every durable key at startup
with its persisted generation intact (and the registry's generation
counter advanced past all of them, so nothing a later hot-swap mints
can alias a pre-crash snapshot).  Quarantined frames are reported and
skipped per key, never fatal to the rest.

ISSUE 7: a ``serve.frontier_cache.FrontierCache`` can live beside the
registry — prefix-family backends then keep their expanded top-k
frontiers in it (keyed (key_id, generation, party, k)) instead of the
instance store, so the expansion survives residency eviction under
skewed traffic.  The cache shares this registry's deterministic stamp
sequence and ``device_bytes_budget``: one merged LRU over staged images
and cached frontiers, one entry-invalidation hook (``_evict_entry``)
for hot-swap/unregister/failure eviction.
"""

from __future__ import annotations

import threading

from dcf_tpu.errors import ShapeError, StaleStateError
from dcf_tpu.keys import KeyBundle
from dcf_tpu.serve.frontier_cache import (
    FrontierCache,
    TickSource,
    tables_nbytes,
)
from dcf_tpu.serve.metrics import Metrics

__all__ = ["KeyRegistry", "device_image_bytes"]

# Device-image dict attributes across the backend zoo: ``_bundle_dev``
# (pallas / bitsliced / keylanes), ``_dev`` (large-lambda hybrid),
# ``_frontier`` (prefix family's instance-store gather tables, filled
# lazily when no serve frontier cache is bound).
_IMAGE_ATTRS = ("_bundle_dev", "_dev", "_frontier")


def device_image_bytes(be) -> int:
    """Best-effort byte count of a backend instance's device-resident
    key image (the LRU accounting unit).  Sums ``nbytes`` over the known
    image dicts through the ONE byte rule (``tables_nbytes`` — the
    hybrid's frontier store holds (state, trajectory) TUPLES per party);
    a backend that stages nothing (host paths) counts 0."""
    total = 0
    for attr in _IMAGE_ATTRS:
        d = getattr(be, attr, None)
        if isinstance(d, dict):
            for v in d.values():
                total += tables_nbytes(v)
    return total


class _Entry:
    """One registered key: host bundle + its live device residencies.

    ``protocol`` (PR 5): the ``protocols.ProtocolBundle`` this key was
    registered as, or None for a plain DCF key.  The DEVICE image is
    always the inner ``KeyBundle`` (the residency machinery is
    protocol-agnostic); the protocol record tells the service to apply
    the per-interval share combine when it fetches a batch.

    ``planes`` (ISSUE 11): ``{party: staged plane dict}`` from the
    on-device keygen (``gen.gen_on_device_with_planes``), or None.
    When present and the backend advertises ``accepts_dev_planes``,
    ``resident`` stages through ``put_bundle(kb, dev_planes=...)`` —
    the narrow image never round-trips through the host bit-plane
    expansion, which is the key factory's zero-copy registration flow.
    Budget (LRU) evictions keep the planes (a re-stage reuses them —
    that is the amortization); the entry-invalidation hook drops them
    (hot-swap/unregister supersede the key, and a failure eviction
    must not re-feed state from the path that just died)."""

    __slots__ = ("bundle", "generation", "residents", "protocol",
                 "planes")

    def __init__(self, bundle: KeyBundle, generation: int, protocol=None,
                 planes: dict | None = None):
        self.bundle = bundle
        self.generation = generation
        self.protocol = protocol
        self.planes = planes
        self.residents: dict = {}  # slot (party int | "kl") -> _Resident

    def __repr__(self) -> str:  # never the bundle's bytes — shapes only
        return (f"_Entry(gen={self.generation}, "
                f"proto={self.protocol is not None}, "
                f"planes={self.planes is not None}, "
                f"resident_slots={sorted(map(str, self.residents))})")


class _Resident:
    """One (key, slot) device residency: the backend instance owning the
    shipped image, its byte cost, and its LRU stamp."""

    __slots__ = ("be", "bytes", "stamp", "generation")

    def __init__(self, be, nbytes: int, stamp: int, generation: int):
        self.be = be
        self.bytes = nbytes
        self.stamp = stamp
        self.generation = generation

    def __repr__(self) -> str:
        return (f"_Resident(bytes={self.bytes}, stamp={self.stamp}, "
                f"gen={self.generation})")


class KeyRegistry:
    """Named bundles + LRU device-residency cache (see module docstring).

    ``make_backend``: zero-arg factory returning a fresh eval backend
    instance (the ``Dcf`` facade's ``new_eval_backend``), or ``None``
    for host paths — then ``resident`` returns ``None`` backends and the
    service evaluates through the facade directly.
    """

    def __init__(self, make_backend, *, shared_image: bool = False,
                 device_bytes_budget: int = 0,
                 metrics: Metrics | None = None, breakers=None,
                 frontier_cache: FrontierCache | None = None):
        self._make_backend = make_backend
        self._shared_image = shared_image  # keylanes: one slot, both parties
        self.device_bytes_budget = int(device_bytes_budget)
        self._metrics = metrics if metrics is not None else Metrics()
        # The serve-resident frontier cache (serve.frontier_cache), or
        # None to leave prefix-family frontiers in their instance stores
        # (then they die with each LRU residency eviction — the pre-
        # cache behavior, kept as the ``frontier_cache=False`` knob and
        # the cold leg of ``serve_bench --skew``).  The cache shares
        # this registry's LRU stamp sequence and byte budget: eviction
        # order across staged images AND cached frontiers is one merged
        # least-recently-used order.
        self._frontier_cache = frontier_cache
        self._ticks = (frontier_cache.ticks if frontier_cache is not None
                       else TickSource())
        # guarded-by: _lock
        self._staging_keep = None  # the residency mid-staging (RLock-
        # guarded): a frontier warm's budget sweep must not evict it
        if frontier_cache is not None:
            frontier_cache.set_growth_hook(self._apply_budget)
        # The serving layer's ``serve.breaker.BreakerBoard`` (or None).
        # Breaker state is (key_id, backend-family) failure HISTORY, so
        # its lifetime is tied to the registration NAME, not to entry
        # generations or device residencies: ``register`` hot-swaps and
        # LRU/budget evictions leave it alone (a re-registered bundle
        # re-staged onto the same dying backend is still on a dying
        # backend), and only ``unregister`` — the name ceasing to exist
        # — forgets it.
        self._breakers = breakers
        self._lock = threading.RLock()
        # guarded-by: _lock
        self._entries: dict[str, _Entry] = {}
        # guarded-by: _lock
        self._generation = 0
        g = self._metrics.gauge
        self._g_resident_bytes = g("serve_resident_device_bytes")
        self._g_resident_count = g("serve_resident_images")
        self._g_registered = g("serve_registered_keys")
        self._c_evictions = self._metrics.counter("serve_evictions_total")
        self._c_stagings = self._metrics.counter("serve_key_stagings_total")

    # -- registration -------------------------------------------------------

    def register(self, key_id: str, bundle: KeyBundle,
                 protocol=None, dev_planes: dict | None = None) -> int:
        """Register (or replace) the bundle served under ``key_id``;
        returns the entry's generation (the durable write-through path
        publishes the frame under it).

        The bundle must be the full two-party bundle: the service serves
        both parties, and the keylanes image is two-party by design.
        Replacing a live key evicts its residencies atomically (the
        staleness guard), so no later batch can pair old device state
        with the new key.  ``protocol``: the ``ProtocolBundle`` wrapper
        when ``bundle`` is a protocol key's inner bundle — recorded so
        the service applies the share combine at fetch time
        (``DcfService.register_key`` unwraps and passes both).
        ``dev_planes`` (ISSUE 11): both parties' staged plane dicts
        from the on-device keygen — see ``_Entry.planes``.
        """
        if bundle.s0s.shape[1] != 2:
            raise ShapeError(
                f"register({key_id!r}) wants the full two-party bundle "
                "(shape [K, 2, lam] s0s); restrict per party at eval, "
                "not at registration")
        with self._lock:
            prev = self._entries.get(key_id)
            if prev is not None and prev.bundle is bundle \
                    and prev.protocol is protocol:
                # idempotent re-registration: keep the residencies
                return prev.generation
            self._generation += 1
            if prev is not None:
                self._evict_entry(key_id, prev)
            self._entries[key_id] = _Entry(bundle, self._generation,
                                           protocol, dev_planes)
            self._g_registered.set(len(self._entries))
            return self._generation

    def register_at(self, key_id: str, bundle: KeyBundle,
                    generation: int, protocol=None) -> int:
        """Register ``key_id`` under a FORCED generation (ISSUE 14:
        the replica-apply / anti-entropy path — the generation was
        minted by the key's OWNER and must be preserved so the ring
        agrees on one total order per key).  The monotonic-generation
        fence: an entry already at or past ``generation`` raises
        ``StaleStateError`` — an old partition side is structurally
        unable to roll a key back, because the only way to supersede a
        registration is a strictly newer generation.  The registry's
        own counter advances past the applied generation, so a later
        LOCAL hot-swap of any key mints strictly above everything this
        registry has ever seen (the restart-ordering guarantee: a
        recovered owner anti-entropies FIRST, flooring its counter on
        the replica's generations, and only then re-admits traffic —
        its next mint can never alias a pre-crash generation)."""
        if generation < 1:
            # api-edge: replication contract — generation 0 is the
            # wire's "mint here" sentinel, never a forced apply
            raise ValueError(
                f"register_at({key_id!r}) needs a generation >= 1, "
                f"got {generation}")
        if bundle.s0s.shape[1] != 2:
            raise ShapeError(
                f"register_at({key_id!r}) wants the full two-party "
                "bundle (shape [K, 2, lam] s0s)")
        with self._lock:
            prev = self._entries.get(key_id)
            if prev is not None and prev.generation >= generation:
                raise StaleStateError(
                    f"replica frame for {key_id!r} carries generation "
                    f"{generation} but this registry already holds "
                    f"generation {prev.generation}; the monotonic "
                    "fence refuses the rollback")
            if prev is not None:
                self._evict_entry(key_id, prev)
            self._entries[key_id] = _Entry(bundle, int(generation),
                                           protocol)
            self._generation = max(self._generation, int(generation))
            self._g_registered.set(len(self._entries))
            return int(generation)

    def digest(self) -> dict:
        """The live ``{key_id: generation}`` map (ISSUE 14: the
        anti-entropy exchange unit — generations only, no key
        material)."""
        with self._lock:
            return {key_id: entry.generation
                    for key_id, entry in self._entries.items()}

    def mint_generations(self, count: int) -> range:
        """Reserve ``count`` fresh generations from the shared counter
        (ISSUE 11: the key factory publishes pool frames under real
        registry generations, so pool entries live in the same total
        order as registrations — ``sync_generation_floor`` at the next
        restart then floors past them like any other, and no later
        hot-swap can mint a generation a pooled durable frame already
        carries)."""
        if count < 1:
            # api-edge: reservation contract (programmer error)
            raise ValueError(f"count must be >= 1, got {count}")
        with self._lock:
            lo = self._generation + 1
            self._generation += count
            return range(lo, self._generation + 1)

    def unregister(self, key_id: str) -> None:
        with self._lock:
            entry = self._entries.pop(key_id, None)
            if entry is not None:
                self._evict_entry(key_id, entry)
            self._g_registered.set(len(self._entries))
        if self._breakers is not None:
            self._breakers.forget(key_id)

    def restore(self, store) -> "RestoreReport":
        """Warm restart (ISSUE 8): re-register every key a
        ``serve.store.KeyStore`` holds, PRESERVING each key's persisted
        generation — and advance this registry's generation counter
        past the highest restored one, so a post-restore hot-swap
        mints a generation no pre-crash snapshot (or pre-crash durable
        frame) ever carried.  That is the PR 5 aliasing guard extended
        across process death: a restored key must never share a
        generation with different key content.

        A frame the store quarantines (corrupt, truncated, vanished)
        is recorded in the report and SKIPPED — typed, counted, never
        fatal to the other keys.  A corrupt MANIFEST, by contrast,
        raises ``KeyFormatError``: without a trustworthy index there is
        nothing safe to restore.  Returns the ``RestoreReport``
        (``restored``: key_id -> generation; ``quarantined``: key_id ->
        failure message)."""
        from dcf_tpu.serve.store import RestoreReport

        report = RestoreReport()
        store.sweep_orphans()  # crash debris: unreferenced frames/tmps
        loaded, report.quarantined = store.load_all()  # ONE manifest
        # read for the whole restore — per-key load() would make this
        # O(n^2) manifest parses over n stored keys
        for key_id in sorted(loaded):
            bundle, protocol, generation = loaded[key_id]
            if bundle.s0s.shape[1] != 2:
                # the store's put() refuses one-party frames, so this
                # is defense in depth against a hand-edited store —
                # and it must REALLY quarantine (rename aside, drop the
                # manifest entry, bump the counter), or every later
                # restore re-reads the bad frame and re-reports it
                # forever while its manifest entry lingers.
                store.quarantine(key_id)
                report.quarantined[key_id] = (
                    "restored frame is party-restricted; the service "
                    "serves both parties")
                continue
            with self._lock:
                prev = self._entries.get(key_id)
                if prev is not None:
                    self._evict_entry(key_id, prev)
                self._entries[key_id] = _Entry(bundle, generation,
                                               protocol)
                self._generation = max(self._generation, generation)
                self._g_registered.set(len(self._entries))
            report.restored[key_id] = generation
        self._metrics.counter("serve_store_restored_total").inc(
            len(report.restored))
        return report

    def sync_generation_floor(self, floor: int) -> None:
        """Advance the generation counter to at least ``floor`` (a
        store-backed service passes its store's ``max_generation()`` at
        construction).  Without this, a FRESH process on an existing
        store that registers durably BEFORE restoring would mint
        generations the manifest already records — the store's
        monotonic guard would then silently drop the write-through,
        un-acking an acked durable registration."""
        with self._lock:
            self._generation = max(self._generation, int(floor))

    def bundle(self, key_id: str) -> KeyBundle:
        with self._lock:
            entry = self._entries.get(key_id)
            if entry is None:
                # api-edge: unknown-name lookup contract at the serve edge
                raise ValueError(f"no bundle registered under {key_id!r}")
            return entry.bundle

    def snapshot(self, key_id: str):
        """``(bundle, protocol, generation)`` read under ONE lock
        acquisition — the serving layer snapshots this once per request
        group so a concurrent ``register`` hot-swap cannot pair the old
        key's geometry (or combine masks) with the new key's state
        mid-group.  The generation is handed back to ``resident`` so a
        residency lazily re-staged from a SWAPPED entry is refused (the
        group then fails with ``StaleStateError``, same as unregistering
        mid-flight — never silent corruption)."""
        with self._lock:
            entry = self._entries.get(key_id)
            if entry is None:
                # api-edge: unknown-name lookup contract at the serve edge
                raise ValueError(f"no bundle registered under {key_id!r}")
            return entry.bundle, entry.protocol, entry.generation

    def key_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    # -- residency ----------------------------------------------------------

    def resident(self, key_id: str, b: int, generation: int | None = None):
        """The backend instance holding ``key_id``'s party-``b`` image on
        device, staging it (and possibly evicting colder images) if
        absent.  Returns ``None`` for host-path services.

        ``generation``: when given (the serving layer passes its group
        snapshot's), a mismatch with the live entry raises
        ``StaleStateError`` — a hot-swapped key must not lazily re-stage
        under an in-flight group whose combine masks belong to the old
        key (the batch would resolve successfully with silently wrong
        shares)."""
        with self._lock:
            entry = self._entries.get(key_id)
            if entry is None:
                # api-edge: unknown-name lookup contract at the serve edge
                raise ValueError(f"no bundle registered under {key_id!r}")
            if generation is not None and entry.generation != generation:
                raise StaleStateError(
                    f"key {key_id!r} was re-registered (generation "
                    f"{entry.generation} != snapshot {generation}); the "
                    "in-flight group must fail, not serve mixed key state")
            slot = "kl" if self._shared_image else int(b)
            res = entry.residents.get(slot)
            if res is not None:
                res.stamp = self._ticks.next()
                return res.be
            be = self._make_backend()
            if be is None:
                return None
            kb = (entry.bundle if self._shared_image
                  else entry.bundle.for_party(b))
            planes = (entry.planes.get(int(b))
                      if entry.planes is not None
                      and not self._shared_image else None)
            if planes is not None \
                    and getattr(be, "accepts_dev_planes", False):
                # ISSUE 11: the on-device keygen already staged this
                # party's narrow image — hand it over instead of
                # re-expanding host bit planes.  Guarded by the
                # backend's capability flag: after an auto-facade
                # demotion the fresh instance may be a different
                # family, which stages from the host bundle as usual.
                be.put_bundle(kb, dev_planes=planes)
            else:
                be.put_bundle(kb)
            self._c_stagings.inc()
            res = _Resident(be, device_image_bytes(be), self._ticks.next(),
                            entry.generation)
            entry.residents[slot] = res
            # Prefix-family backends: bind the serve-resident frontier
            # provider (scoped to this key_id + generation — put_bundle
            # just unbound any previous one) and warm the frontier at
            # STAGE time, so later batches' evals gather from cache
            # instead of expanding 2^k nodes on their clock.  The warm
            # runs BEFORE the image budget sweep below: a re-staged
            # key's consult re-stamps its surviving frontier FIRST, so
            # the sweep sees it as the hot entry it is (sweep-first
            # would eat the returning key's own cold-stamped frontier
            # moments before the warm hits it — every re-stage then
            # misses and the cache amortizes nothing).  The warm's own
            # budget sweep (frontier-cache growth hook) must not evict
            # the residency being staged: _staging_keep extends the
            # ``keep`` guarantee across the re-entrant sweep.
            if self._frontier_cache is not None \
                    and hasattr(be, "frontier_provider") \
                    and getattr(be, "prefix_levels", 0):
                be.frontier_provider = self._frontier_cache.bind(
                    key_id, entry.generation)
                self._staging_keep = res
                try:
                    be.ensure_frontier(int(b))
                finally:
                    self._staging_keep = None
            self._enforce_budget(keep=res)
            self._update_gauges()
            return res.be

    def note_image_growth(self, key_id: str, b: int) -> None:
        """Re-measure a residency whose image grew after staging (the
        prefix backends build frontier tables lazily on first eval) and
        re-apply the budget."""
        with self._lock:
            entry = self._entries.get(key_id)
            if entry is None:
                return
            res = entry.residents.get("kl" if self._shared_image else int(b))
            if res is None:
                return
            res.bytes = device_image_bytes(res.be)
            self._enforce_budget(keep=res)
            self._update_gauges()

    # -- eviction -----------------------------------------------------------

    # holds-lock: _lock
    def _iter_residents(self):
        for entry in self._entries.values():
            for slot, res in list(entry.residents.items()):
                yield entry, slot, res

    def _apply_budget(self) -> None:
        """The frontier cache's growth hook: re-run the merged budget
        sweep after an insert.  Takes the registry lock (an RLock — a
        stage-time warm re-enters from ``resident``, where
        ``_staging_keep`` extends the keep guarantee)."""
        with self._lock:
            self._enforce_budget(keep=self._staging_keep)
            self._update_gauges()

    # holds-lock: _lock
    def _enforce_budget(self, keep) -> None:
        """Evict least-recently-used holdings until the summed device
        bytes fit the budget.  Staged key images AND serve-cached
        frontiers share the budget and the stamp sequence, so the sweep
        picks the coldest across BOTH populations — a frontier whose
        key keeps getting evals outlives the churn of colder keys'
        images, which is the whole amortization.  ``keep`` (the
        residency being served/staged) is never evicted, so one
        over-budget key still serves — a budget too small for a single
        image degrades to stage-per-use, not to an unservable key.
        Budget 0 disables the cap."""
        if not self.device_bytes_budget:
            return
        fc = self._frontier_cache
        total = sum(r.bytes for _, _, r in self._iter_residents())
        if fc is not None:
            total += fc.total_bytes()
        if total <= self.device_bytes_budget:
            return
        # One snapshot of both populations, coldest-first, then a
        # decrementing walk: the sweep runs on the serving path under
        # the registry lock, so it must be O(entries log entries), not
        # O(victims * entries) of repeated rescans.  (Cache entries can
        # be re-stamped concurrently by eval-path hits — the staleness
        # window is one sweep, and ``evict`` returning 0 for an entry
        # a racing miss already replaced keeps the total honest.)
        victims = [(res.stamp, "res", (entry, slot, res))
                   for entry, slot, res in self._iter_residents()
                   if res is not keep]
        if fc is not None:
            victims += [(stamp, "frontier", key)
                        for stamp, key, _nb in fc.lru_entries()]
        victims.sort(key=lambda v: v[0])
        for _, kind, victim in victims:
            if total <= self.device_bytes_budget:
                return
            if kind == "res":
                entry, slot, res = victim
                if hasattr(res.be, "invalidate_frontier"):
                    # Budget eviction keeps the key's CACHED frontiers
                    # (their stamps decide their own fate) but clears
                    # the dropped instance's local state: an in-flight
                    # batch closure can pin the instance, and pinned
                    # instance-store frontier bytes would be resident
                    # and uncounted.
                    res.be.invalidate_frontier()
                del entry.residents[slot]
                self._c_evictions.inc()
                total -= res.bytes
            else:
                total -= fc.evict(victim)

    # holds-lock: _lock
    def _evict_entry(self, key_id: str, entry: _Entry) -> None:
        """The ONE entry-invalidation hook: hot-swap, unregister and
        failure eviction all route here, which (a) drops the entry's
        residencies, (b) clears each dropped instance's frontier state
        through ``invalidate_frontier`` (an in-flight batch closure can
        pin the instance — its frontier bytes must not linger unbound
        and uncounted), and (c) drops the serve frontier cache's
        entries for the key (the key image they were expanded from is
        gone or superseded)."""
        n = len(entry.residents)
        for res in entry.residents.values():
            if hasattr(res.be, "invalidate_frontier"):
                res.be.invalidate_frontier()
        entry.residents.clear()
        # Keygen-staged planes die with the entry: a hot-swap/unregister
        # superseded the key they image, and a failure eviction must not
        # re-stage from the device state that just failed (the re-stage
        # then runs the host path — slower, known-good).  Budget (LRU)
        # evictions do NOT route here and deliberately keep them.
        entry.planes = None
        if n:
            self._c_evictions.inc(n)
        if self._frontier_cache is not None:
            self._frontier_cache.invalidate_key(key_id)
        self._update_gauges()

    def evict_key(self, key_id: str) -> None:
        """Drop one key's device residencies (registration stays).  The
        serving layer's cheap first-line invalidation after a batch
        failure — transient faults must not cost every other hot key its
        staged image.  Routes through the shared entry-invalidation
        hook, so the key's cached frontiers go too: they were built by
        the device state that just failed."""
        with self._lock:
            entry = self._entries.get(key_id)
            if entry is not None:
                self._evict_entry(key_id, entry)

    def evict_all(self) -> None:
        """Drop every device residency (the shared invalidation path:
        ``reset_backend_health`` routes here so a backend declared dead
        mid-serve never serves again from cached state — frontiers
        included)."""
        with self._lock:
            for key_id, entry in self._entries.items():
                self._evict_entry(key_id, entry)

    # holds-lock: _lock
    def _update_gauges(self) -> None:
        total = n = 0
        for _, _, res in self._iter_residents():
            total += res.bytes
            n += 1
        self._g_resident_bytes.set(total)
        self._g_resident_count.set(n)
