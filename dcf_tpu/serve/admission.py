"""Admission control: bounded request queue, deadlines, result handles.

The service's overload policy is decided HERE, at submit time, not
discovered later as memory pressure: the queue is bounded in queued
POINTS (requests are ragged — a bound in requests would let one giant
request soak the device for seconds while claiming a queue depth of 1),
and a submit that would exceed the bound is shed immediately with
``QueueFullError``.  A shed request costs the caller one exception and
zero device work — the cheapest possible failure in a loaded system.

Deadlines propagate as absolute clock values (the injectable serve clock,
``utils.benchtime.monotonic`` by default).  They are enforced at batch
formation: an expired request is completed with ``DeadlineExceededError``
and never reaches the device.  In-flight batches are never aborted — a
dispatched batch is at most one ``max_delay + eval`` old, and tearing
down a device dispatch mid-flight costs more than finishing it.

``ServeFuture`` is the result handle: ``result(timeout)`` blocks on a
``threading.Event`` (the service's worker thread completes it) and either
returns the uint8 [K, M, lam] share or raises the typed failure.
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np

from dcf_tpu.errors import DeadlineExceededError, QueueFullError, ShapeError
from dcf_tpu.serve.metrics import Metrics

__all__ = ["ServeFuture", "Request", "AdmissionQueue", "expire"]


class ServeFuture:
    """Completion handle for one submitted request."""

    __slots__ = ("_event", "_value", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def set_result(self, value: np.ndarray) -> None:
        self._value = value
        self._event.set()

    def set_exception(self, error: BaseException) -> None:
        self._error = error
        self._event.set()

    def result(self, timeout: float | None = None) -> np.ndarray:
        """The request's uint8 [K, M, lam] share, or its typed failure.
        Raises ``TimeoutError`` if the service has not completed the
        request within ``timeout`` seconds (the request stays live)."""
        if not self._event.wait(timeout):
            # dcflint: disable=typed-error a result-wait timeout means
            # "not done yet", not a framework failure: the builtin
            # TimeoutError is the documented contract (and deliberately
            # NOT DeadlineExceededError, which means "dropped undone")
            raise TimeoutError("request not completed yet")
        error = self._error  # re-raise of the stored completion failure
        if error is not None:
            raise error
        return self._value


class Request:
    """One accepted request: points for one (key_id, party) pair."""

    __slots__ = ("key_id", "b", "xs", "m", "deadline", "enq_t", "future")

    def __init__(self, key_id: str, b: int, xs: np.ndarray,
                 deadline: float | None, enq_t: float):
        self.key_id = key_id
        self.b = int(b)
        self.xs = xs
        self.m = int(xs.shape[0])
        self.deadline = deadline
        self.enq_t = enq_t
        self.future = ServeFuture()

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def __repr__(self) -> str:  # points are caller data: shapes only
        return (f"Request(key_id={self.key_id!r}, b={self.b}, m={self.m}, "
                f"deadline={self.deadline})")


class AdmissionQueue:
    """FIFO bounded-points queue with group extraction for the batcher.

    Thread-safe; ``cond`` is the wakeup signal the worker waits on
    (notified on every accepted submit and on ``close``).
    """

    def __init__(self, max_queued_points: int,
                 metrics: Metrics | None = None):
        if max_queued_points < 1:
            # api-edge: constructor bound contract
            raise ValueError(
                f"max_queued_points must be >= 1, got {max_queued_points}")
        self.max_queued_points = int(max_queued_points)
        self._metrics = metrics if metrics is not None else Metrics()
        self.cond = threading.Condition()
        self._reqs: list[Request] = []
        self._points = 0
        self._closed = False
        self._g_depth = self._metrics.gauge("serve_queue_depth")
        self._g_points = self._metrics.gauge("serve_queue_points")
        self._c_shed = self._metrics.counter("serve_shed_total")
        self._c_accepted = self._metrics.counter("serve_requests_total")
        self._c_accepted_points = self._metrics.counter("serve_points_total")

    def put(self, req: Request) -> None:
        """Admit or shed ``req`` (QueueFullError on overload/shutdown)."""
        if req.m > self.max_queued_points:
            # Not an overload: this request can NEVER be admitted, so a
            # "back off and retry" QueueFullError would send the caller
            # into a futile loop — it is a size-contract violation.
            raise ShapeError(
                f"request of {req.m} points exceeds the admission bound "
                f"max_queued_points={self.max_queued_points} outright; "
                "split the request (or raise the bound)")
        with self.cond:
            if self._closed:
                # Shutdown rejections count as shed too: loadgen counts
                # them off the same QueueFullError, and the two numbers
                # land in the same RESULTS_serve line — they must agree.
                self._c_shed.inc()
                raise QueueFullError(
                    "service is draining/closed; no new requests")
            if self._points + req.m > self.max_queued_points:
                self._c_shed.inc()
                raise QueueFullError(
                    f"admission queue full: {self._points} points queued "
                    f"+ {req.m} requested > bound "
                    f"{self.max_queued_points}; back off and retry")
            self._reqs.append(req)
            self._points += req.m
            self._c_accepted.inc()
            self._c_accepted_points.inc(req.m)
            self._sync_gauges()
            self.cond.notify_all()

    def close(self) -> None:
        """Stop admitting; queued requests remain for draining."""
        with self.cond:
            self._closed = True
            self.cond.notify_all()

    @property
    def closed(self) -> bool:
        return self._closed

    def __len__(self) -> int:
        return len(self._reqs)

    @property
    def points(self) -> int:
        return self._points

    def oldest_enq_t(self) -> float | None:
        with self.cond:
            return self._reqs[0].enq_t if self._reqs else None

    def take_expired(self, now: float) -> list[Request]:
        """Remove and return every queued request whose deadline passed
        (the caller completes them with ``DeadlineExceededError``)."""
        with self.cond:
            expired = [r for r in self._reqs if r.expired(now)]
            if expired:
                self._reqs = [r for r in self._reqs if not r.expired(now)]
                self._points = sum(r.m for r in self._reqs)
                self._sync_gauges()
            return expired

    def take_group(self, max_batch_points: int) -> list[Request]:
        """Remove and return the head request's (key_id, party) group:
        same-group requests in FIFO order until one does not fit in
        ``max_batch_points`` — at which point the group CLOSES, so a
        later-submitted smaller request can never jump an earlier one
        (per-request latency stays FIFO within a group).  The head
        request is always taken, however large — the batcher splits it.
        Other groups keep their order."""
        with self.cond:
            if not self._reqs:
                return []
            head = self._reqs[0]
            group, rest, total = [head], [], head.m
            closed_group = False
            for r in self._reqs[1:]:
                if (r.key_id, r.b) == (head.key_id, head.b) \
                        and not closed_group:
                    if total + r.m <= max_batch_points:
                        group.append(r)
                        total += r.m
                        continue
                    closed_group = True  # preserve FIFO within the group
                rest.append(r)
            self._reqs = rest
            self._points = sum(r.m for r in rest)
            self._sync_gauges()
            return group

    def fail_all(self, make_error: Callable[[], BaseException]) -> int:
        """Drop every queued request, completing each with a fresh error
        (non-drain shutdown).  Returns the count."""
        with self.cond:
            reqs, self._reqs, self._points = self._reqs, [], 0
            self._sync_gauges()
        for r in reqs:
            r.future.set_exception(make_error())
        return len(reqs)

    def _sync_gauges(self) -> None:
        self._g_depth.set(len(self._reqs))
        self._g_points.set(self._points)


def expire(reqs: list[Request], metrics: Metrics) -> None:
    """Complete ``reqs`` with DeadlineExceededError (and count them)."""
    if reqs:
        metrics.counter("serve_deadline_expired_total").inc(len(reqs))
    for r in reqs:
        r.future.set_exception(DeadlineExceededError(
            f"deadline passed before dispatch ({r!r})"))
